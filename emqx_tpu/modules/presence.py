"""Presence events: publishes connected/disconnected notifications to
``$SYS/brokers/<node>/clients/<clientid>/...``
(reference: src/emqx_mod_presence.erl)."""

from __future__ import annotations

import json
import time

from emqx_tpu.modules import Module
from emqx_tpu.types import Message


class PresenceModule(Module):
    name = "presence"

    def __init__(self, node) -> None:
        super().__init__(node)
        self.qos = 0

    def load(self, env: dict) -> None:
        self.qos = env.get("qos", 0)
        self.node.hooks.add("client.connected", self.on_connected)
        self.node.hooks.add("client.disconnected", self.on_disconnected)

    def unload(self) -> None:
        self.node.hooks.delete("client.connected", self.on_connected)
        self.node.hooks.delete("client.disconnected", self.on_disconnected)

    def _pub(self, clientid: str, event: str, payload: dict) -> None:
        topic = (f"$SYS/brokers/{self.node.name}/clients/"
                 f"{clientid}/{event}")
        self.node.broker.publish(Message(
            topic=topic, qos=self.qos,
            payload=json.dumps(payload).encode(), flags={"sys": True}))

    def on_connected(self, clientinfo: dict, conninfo: dict):
        cid = clientinfo.get("clientid", "")
        self._pub(cid, "connected", {
            "clientid": cid,
            "username": clientinfo.get("username"),
            "ipaddress": clientinfo.get("peerhost"),
            "proto_ver": clientinfo.get("proto_ver"),
            "keepalive": clientinfo.get("keepalive"),
            "clean_start": clientinfo.get("clean_start"),
            "connected_at": conninfo.get("connected_at", time.time()),
            "ts": int(time.time() * 1000),
        })

    def on_disconnected(self, clientinfo: dict, reason):
        cid = clientinfo.get("clientid", "")
        self._pub(cid, "disconnected", {
            "clientid": cid,
            "username": clientinfo.get("username"),
            "reason": str(reason),
            "ts": int(time.time() * 1000),
        })
