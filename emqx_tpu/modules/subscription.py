"""Auto-subscribe on connect with %c/%u templated topics
(reference: src/emqx_mod_subscription.erl)."""

from __future__ import annotations

from emqx_tpu.modules import Module
from emqx_tpu.mountpoint import replvar
from emqx_tpu.types import SubOpts


class SubscriptionModule(Module):
    name = "subscription"

    def __init__(self, node) -> None:
        super().__init__(node)
        self._topics = []  # [(template, qos)]

    def load(self, env: dict) -> None:
        self._topics = list(env.get("topics", []))
        self.node.hooks.add("client.connected", self.on_connected)

    def unload(self) -> None:
        self.node.hooks.delete("client.connected", self.on_connected)

    def on_connected(self, clientinfo: dict, conninfo: dict):
        cid = clientinfo.get("clientid", "")
        chan = self.node.cm.lookup_channel(cid)
        if chan is None or chan.session is None:
            return
        for template, qos in self._topics:
            flt = replvar(template, cid, clientinfo.get("username"))
            try:
                chan.session.subscribe(flt, SubOpts(qos=qos))
            except Exception:
                pass
