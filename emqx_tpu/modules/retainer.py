"""Retained-message store and delivery.

The reference core delegates retained messages to the separate
``emqx_retainer`` plugin application (the core only carries the
``retain`` flag and the v5 Retain-Handling/Retain-As-Published
subscription options); a broker users can actually switch to needs
the behavior in the box, so it ships here as a built-in module wired
through the same two hookpoints the reference plugin uses:

  - ``'message.publish'``: a retained PUBLISH stores its message
    under the topic (an empty retained payload deletes — MQTT
    3.3.1-6/-7); the message still routes normally.
  - ``'session.subscribed'``: a new subscription receives every
    stored message matching its filter, with the retain flag SET
    (MQTT 3.3.1-8) regardless of RAP, honoring Retain-Handling
    (0 = always send, 1 = only if the subscription did not exist,
    2 = never — MQTT 3.8.3.1) and skipping shared subscriptions
    (retained messages are never sent to ``$share`` groups) and
    expired messages (Message-Expiry-Interval).

Bounded: ``max_retained`` topics (new stores beyond it are dropped
with a counter, like the plugin's ``max_retained_messages``) and
``max_payload`` bytes per message.
"""

from __future__ import annotations

import logging
import threading
from typing import Dict, List, Optional, Sequence

import numpy as np

from emqx_tpu import topic as T
from emqx_tpu.modules import Module
from emqx_tpu.types import Message

log = logging.getLogger(__name__)


#: '+' sentinel in an encoded FILTER row — never collides with real
#: word ids (≥0) or the topic-side UNKNOWN (-1) / PAD (-2)
_PLUS_ID = -3


class RetainIndex:
    """Device-side reverse index over retained topic NAMES.

    The reference plugin indexes retained topics in its own Mnesia
    trie so a wildcard subscribe doesn't scan the store. The
    TPU-first equivalent inverts the publish problem: retained names
    live as a persistent encoded ``[cap, L]`` word-id matrix, and a
    wildcard subscribe matches its ONE filter against every stored
    name in a single data-parallel device pass instead of N Python
    ``T.match`` calls.

    A filter needs no automaton walk at all: per level the filter
    word either equals the topic word or is ``+``, with a ``#``
    suffix relaxing the depth check and the ``$``-root rule masking
    system topics — a pure elementwise program over ``[cap, L]``
    (zero gathers, HBM-bandwidth bound; an earlier automaton-based
    variant spent its time in per-level gather chains). Since PR 19
    the kernel is batched on the filter side too
    (ops/retained_match.py): :meth:`match_many` encodes a whole
    subscribe burst as ``[F, L]`` and matches every filter against
    every stored name in ONE dispatch; :meth:`match` is the F=1
    special case of the same path.

    Rows are slot-allocated (free list); a deleted row gets
    ``n_words = 0``, which matches nothing. Names deeper than ``L``
    levels live in a host-matched side set, the same overflow
    contract as the publish path. Below ``device_threshold`` live
    rows (or on any device failure) matching falls back to the host
    scan. With a router attached (:meth:`attach_router`) the index
    rides device-loss recovery: a suspended device plane forces the
    host scan and drops the cached matrix (its HBM references may be
    dead), and suspension lifting (``rebuild_complete``) forgives the
    failure breaker — a fresh backend deserves a clean slate.
    """

    L = 16
    GROW = 1024

    def __init__(self) -> None:
        from emqx_tpu.ops.tokenize import PAD, WordTable

        self._pad = PAD
        self._table = WordTable()
        self._word_refs: Dict[str, int] = {}
        self._cap = self.GROW
        self._ids = np.full((self._cap, self.L), PAD, dtype=np.int32)
        self._n = np.zeros(self._cap, dtype=np.int32)
        self._sys = np.zeros(self._cap, dtype=bool)
        self._row_topic: List[Optional[str]] = [None] * self._cap
        self._row_of: Dict[str, int] = {}
        self._free = list(range(self._cap - 1, -1, -1))
        self._deep: set = set()
        self._epoch = 0
        self._dev = None  # (epoch, cap, ids, n, sys) device cache
        self._dirty: set = set()  # rows mutated since _dev was built
        self._device_broken = 0  # consecutive failures; >=3 disables
        self._router = None  # devloss riding (attach_router)
        self._suspended_seen = False
        self._last_batch = 0  # filters in the last device dispatch
        # store mutations run on the broker's home loop but subscribe
        # bursts match from every front-door loop; the lock covers
        # the matrix + device-cache critical sections (uncontended on
        # a single-loop node)
        self._lock = threading.RLock()

    def __len__(self) -> int:
        return len(self._row_of) + len(self._deep)

    def attach_router(self, router) -> None:
        """Arm device-loss riding (docs/ROBUSTNESS.md): the index
        holds its own device references outside
        ``Router.rebuild_device_state()``, so instead of being
        rebuilt it watches the router's suspension flag — see the
        class docstring."""
        self._router = router

    def add(self, topic: str) -> None:
        with self._lock:
            self._add_locked(topic)

    def _add_locked(self, topic: str) -> None:
        if topic in self._row_of or topic in self._deep:
            return  # overwrite of the same name: index unchanged
        ws = topic.split("/")
        if len(ws) > self.L:
            self._deep.add(topic)
            return
        if not self._free:
            self._grow()
        row = self._free.pop()
        for j, w in enumerate(ws):
            self._ids[row, j] = self._table.intern(w)
            self._word_refs[w] = self._word_refs.get(w, 0) + 1
        self._ids[row, len(ws):] = self._pad
        self._n[row] = len(ws)
        self._sys[row] = ws[0].startswith("$")
        self._row_topic[row] = topic
        self._row_of[topic] = row
        self._touch(row)

    def remove(self, topic: str) -> None:
        with self._lock:
            self._remove_locked(topic)

    def _remove_locked(self, topic: str) -> None:
        if topic in self._deep:
            self._deep.discard(topic)
            return
        row = self._row_of.pop(topic, None)
        if row is None:
            return
        for w in topic.split("/"):
            left = self._word_refs.get(w, 0) - 1
            if left <= 0:
                self._word_refs.pop(w, None)
            else:
                self._word_refs[w] = left
        self._ids[row, :] = self._pad
        self._n[row] = 0
        self._sys[row] = False
        self._row_topic[row] = None
        self._free.append(row)
        self._touch(row)
        # backstop only (loop-less library usage): the periodic sweep
        # task owns compaction; this inline trigger fires far later
        # so the publish hook never pays a big rebuild in the common
        # case
        self._maybe_compact(backstop=True)

    def clear(self) -> None:
        router = self._router
        self.__init__()
        self._router = router

    def _touch(self, row: int) -> None:
        self._epoch += 1
        if self._dev is not None:
            self._dirty.add(row)

    def _compact_due(self, backstop: bool = False) -> bool:
        dead = len(self._table) - len(self._word_refs)
        live = len(self._word_refs)
        if backstop:
            return dead >= max(65536, 4 * max(live, 1))
        return dead >= max(4096, live)

    def _maybe_compact(self, backstop: bool = False) -> None:
        """Re-intern into a fresh WordTable when most interned words
        are dead — name churn must not grow the table forever (the
        same leak class the stability soak exists to catch).
        Synchronous; the periodic sweep prefers :meth:`compact_async`
        which chunks the rebuild so the event loop never stalls."""
        if not self._compact_due(backstop):
            return
        from emqx_tpu.ops.tokenize import WordTable

        table = WordTable()
        for row, topic in enumerate(self._row_topic):
            if topic is None:
                continue
            for j, w in enumerate(topic.split("/")):
                self._ids[row, j] = table.intern(w)
        self._table = table
        self._dev = None
        self._dirty.clear()
        self._epoch += 1

    async def compact_async(self, chunk: int = 4096) -> bool:
        """Cooperative compaction: rebuild the id matrix + table in
        row chunks, yielding between chunks; a store mutation during
        the rebuild aborts it (epoch guard) and the next sweep cycle
        retries. Returns True when a swap happened."""
        import asyncio

        if not self._compact_due():
            return False
        from emqx_tpu.ops.tokenize import WordTable

        start_epoch = self._epoch
        table = WordTable()
        new_ids = np.full_like(self._ids, self._pad)
        for base in range(0, self._cap, chunk):
            for row in range(base, min(base + chunk, self._cap)):
                topic = self._row_topic[row]
                if topic is None:
                    continue
                for j, w in enumerate(topic.split("/")):
                    new_ids[row, j] = table.intern(w)
            await asyncio.sleep(0)
            if self._epoch != start_epoch:
                return False
        with self._lock:
            if self._epoch != start_epoch:
                return False
            self._ids = new_ids
            self._table = table
            self._dev = None
            self._dirty.clear()
            self._epoch += 1
        return True

    def _grow(self) -> None:
        old = self._cap
        self._cap = old * 2
        for name, fill in (("_ids", self._pad), ("_n", 0), ("_sys", False)):
            arr = getattr(self, name)
            shape = (self._cap,) + arr.shape[1:]
            new = np.full(shape, fill, dtype=arr.dtype)
            new[:old] = arr
            setattr(self, name, new)
        self._row_topic.extend([None] * old)
        self._free.extend(range(self._cap - 1, old - 1, -1))

    def match(self, flt: str, device_threshold: int = 4096) -> List[str]:
        """All stored names matching ``flt`` (exact oracle parity)."""
        return self.match_many([flt], device_threshold)[0]

    def match_many(self, filters: Sequence[str],
                   device_threshold: int = 4096) -> List[List[str]]:
        """Batched match: every filter of a subscribe burst against
        every stored name in ONE device dispatch (``[F, L] ×
        [cap, L]`` elementwise kernel, ops/retained_match.py).
        Returns per-filter hit lists aligned with ``filters``, exact
        host-oracle (``T.match``) parity — including the ``$``-root
        mask, ``#`` depth relax and the deep (> L levels) host side
        set, which is scanned per filter either way."""
        if not filters:
            return []
        deep = self._deep
        deep_hits = ([[t for t in deep if T.match(t, f)]
                      for f in filters] if deep
                     else [[] for _ in filters])
        with self._lock:
            if (len(self._row_of) < device_threshold
                    or not self._device_ok()):
                return [self._host_scan(f, dh)
                        for f, dh in zip(filters, deep_hits)]
            try:
                hits = self._match_device_many(filters)
                self._device_broken = 0
                return [h + dh for h, dh in zip(hits, deep_hits)]
            except Exception:
                # circuit breaker: a host with a permanently failing
                # backend must not pay a failed dispatch + a stack
                # trace on EVERY wildcard subscribe
                self._device_broken += 1
                if self._device_broken >= 3:
                    log.exception(
                        "retain index device match failed %d times; "
                        "host scan from now on", self._device_broken)
                else:
                    log.warning(
                        "retain index device match failed; "
                        "host fallback (%d/3)", self._device_broken)
                return [self._host_scan(f, dh)
                        for f, dh in zip(filters, deep_hits)]

    def _host_scan(self, flt: str, deep_hits: List[str]) -> List[str]:
        return [t for t in self._row_of if T.match(t, flt)] + deep_hits

    def _device_ok(self) -> bool:
        """Device-path gate: the failure breaker, plus devloss riding
        when a router is attached — suspended means the device plane
        is mid-recovery (the cached matrix may reference a LOST
        backend: drop it, host-scan, and don't let the doomed
        dispatch burn breaker strikes); the suspension lifting means
        ``rebuild_complete`` ran, so the breaker resets."""
        r = self._router
        if r is not None:
            try:
                suspended = bool(r.device_suspended())
            except Exception:
                suspended = False
            if suspended:
                self._dev = None
                self._dirty.clear()
                self._suspended_seen = True
                return False
            if self._suspended_seen:
                self._suspended_seen = False
                self._device_broken = 0
        return self._device_broken < 3

    def _match_device_many(self, filters: Sequence[str]
                           ) -> List[List[str]]:
        import jax.numpy as jnp

        from emqx_tpu.ops.retained_match import match_names_auto

        F = len(filters)
        # pad the burst to a power of two so compile count stays
        # logarithmic in burst size (capacity is already pow-2);
        # padding rows (fn=0, no '#') match nothing
        Fp = max(1, 1 << (F - 1).bit_length()) if F > 1 else 1
        fw = np.full((Fp, self.L), self._pad, dtype=np.int32)
        fn = np.zeros(Fp, dtype=np.int32)
        hh = np.zeros(Fp, dtype=bool)
        for i, flt in enumerate(filters):
            ws = flt.split("/")
            if ws[-1] == "#":
                hh[i] = True
                ws = ws[:-1]
            if len(ws) > self.L:
                # deeper than any indexed name can be: leave the row
                # a no-match (the deep side set covers such names)
                hh[i] = False
                continue
            fn[i] = len(ws)
            for j, w in enumerate(ws):
                # lookup, NOT intern: an unseen filter word
                # (UNKNOWN=-1) matches no stored id >= 0 — identical
                # result, and subscribe traffic can't grow the table
                fw[i, j] = _PLUS_ID if w == "+" else self._table.lookup(w)
        dev = self._device_arrays()
        ok = np.asarray(match_names_auto(
            jnp.asarray(fw), jnp.asarray(fn), jnp.asarray(hh),
            dev[2], dev[3], dev[4]))
        self._last_batch = F
        rt = self._row_topic
        return [[rt[row] for row in np.nonzero(ok[i])[0]
                 if rt[row] is not None] for i in range(F)]

    def _device_arrays(self):
        import jax.numpy as jnp

        dev = self._dev
        if dev is None or dev[0] != self._epoch or dev[1] != self._cap:
            if (dev is not None and dev[1] == self._cap
                    and len(self._dirty) <= 256):
                # interleaved store/subscribe traffic: patch the few
                # mutated rows instead of re-uploading the matrix
                rows = np.fromiter(self._dirty, dtype=np.int32)
                dev = (self._epoch, self._cap,
                       dev[2].at[rows].set(self._ids[rows]),
                       dev[3].at[rows].set(self._n[rows]),
                       dev[4].at[rows].set(self._sys[rows]))
            else:
                dev = (self._epoch, self._cap, jnp.asarray(self._ids),
                       jnp.asarray(self._n), jnp.asarray(self._sys))
            self._dev = dev
            self._dirty.clear()
        return dev

    def device_info(self) -> dict:
        """Diagnostic snapshot for ``ctl retained``
        (docs/OPERATIONS.md): live/deep row counts, device-cache
        state, breaker/suspension state and the last batch size."""
        from emqx_tpu.ops.walk_pallas import walk_variant

        r = self._router
        suspended = False
        if r is not None:
            try:
                suspended = bool(r.device_suspended())
            except Exception:
                pass
        return {
            "rows": len(self._row_of),
            "deep": len(self._deep),
            "cap": self._cap,
            "epoch": self._epoch,
            "cached": self._dev is not None,
            "dirty_rows": len(self._dirty),
            "device_broken": self._device_broken,
            "suspended": suspended,
            "last_batch": self._last_batch,
            "walk": walk_variant(),
        }


class RetainerModule(Module):
    name = "retainer"

    def __init__(self, node) -> None:
        super().__init__(node)
        self._store: Dict[str, Message] = {}
        self._index = RetainIndex()
        self.index_device_threshold = 4096
        # delete tombstones (topic -> delete time): a stale
        # rejoiner's sync must not resurrect a deleted message
        self._tombstones: Dict[str, float] = {}
        # durability (docs/DURABILITY.md): store/delete journal
        # through node.durability; True while crash recovery is
        # refilling the store (those mutations must not re-journal)
        self._restoring = False
        self.max_retained = 0
        self.max_payload = 0
        # replay accumulator (PR 19): per-event-loop pending
        # (session, filter, subopts) triples; the first append on a
        # loop schedules a same-tick drain, so every session.subscribed
        # firing queued behind one SUBACK burst lands in ONE batched
        # index match + ONE delivery plan — the subscribe-side mirror
        # of IngressBatcher's zero-linger coalescing
        self._pending: Dict[object, list] = {}
        self._replay_last_batch = 0
        self._gc_tick = 0
        # cluster seam: Cluster sets node.retain_replicate so stores/
        # deletes broadcast (the reference plugin replicates via
        # Mnesia); applied remotely through apply_remote (no re-fan)

    #: stats ticks between expired-entry sweeps — the stats tick runs
    #: on every $SYS heartbeat, far more often than eviction needs
    _GC_EVERY = 6

    def load(self, env: dict) -> None:
        self.max_retained = int(env.get("max_retained", 1_000_000))
        self.max_payload = int(env.get("max_payload", 1 << 20))
        self.index_device_threshold = int(
            env.get("index_device_threshold", 4096))
        self.sweep_interval = float(env.get("sweep_interval", 60.0))
        self._sweep_task = None
        self._kick_on_loop()
        self.node.metrics.new("retained.count")
        self.node.metrics.new("retained.dropped")
        self.node.metrics.new("retained.expired")
        self.node.metrics.new("retained.replay.batches")
        self.node.metrics.new("retained.replay.messages")
        router = getattr(self.node, "router", None)
        if router is None:
            router = getattr(getattr(self.node, "broker", None),
                             "router", None)
        if router is not None:
            # devloss riding: a suspended device plane host-scans and
            # the breaker resets on rebuild_complete
            self._index.attach_router(router)
        stats = getattr(self.node, "stats", None)
        if stats is not None:
            # expired-retained GC on the stats tick (low frequency):
            # entries past Message-Expiry must leave the store/index
            # even when nothing ever subscribes to them again
            stats.register_update(self._on_stats_tick)
        self.node.hooks.add("message.publish", self.on_publish,
                            priority=50)
        self.node.hooks.add("session.subscribed", self.on_subscribed,
                            priority=50)

    def _on_stats_tick(self, stats) -> None:
        self._gc_tick += 1
        if self._gc_tick >= self._GC_EVERY:
            self._gc_tick = 0
            self.sweep_expired()

    def on_loop_start(self) -> None:
        import asyncio

        if getattr(self, "_sweep_task", None) is None \
                or self._sweep_task.done():
            self._sweep_task = asyncio.get_running_loop().create_task(
                self._sweep_loop())

    def on_loop_stop(self) -> None:
        task = getattr(self, "_sweep_task", None)
        if task is not None:
            task.cancel()
            self._sweep_task = None

    async def _sweep_loop(self) -> None:
        """Periodic expiry sweep (the reference plugin expires on a
        timer too, not only lazily) + cooperative index compaction —
        both off the publish hot path."""
        import asyncio

        while True:
            await asyncio.sleep(self.sweep_interval)
            try:
                self.sweep_expired()
                await self._index.compact_async()
            except Exception:
                log.exception("retainer sweep failed")

    def unload(self) -> None:
        self.on_loop_stop()
        self.node.hooks.delete("message.publish", self.on_publish)
        self.node.hooks.delete("session.subscribed", self.on_subscribed)
        self._pending.clear()
        self._store.clear()
        self._index.clear()

    # every store mutation goes through these so the reverse index
    # (device matrix) stays in lockstep with the dict — and, with
    # durability on, the journal sees exactly the store's mutations
    def _put(self, topic: str, msg: Message) -> None:
        self._store[topic] = msg
        self._index.add(topic)
        if not self._restoring:
            dur = getattr(self.node, "durability", None)
            if dur is not None:
                dur.journal_retain(topic, msg, msg.timestamp)

    def _pop(self, topic: str):
        msg = self._store.pop(topic, None)
        if msg is not None:
            self._index.remove(topic)
            if not self._restoring:
                dur = getattr(self.node, "durability", None)
                if dur is not None:
                    dur.journal_retain(topic, None)
        return msg

    def restore_entries(self, items, tombstones=()) -> None:
        """Crash-recovery refill (durability.py): install recovered
        (topic, Message) pairs + delete tombstones without
        re-journaling, honoring expiry and the store bounds."""
        self._restoring = True
        try:
            for topic, msg in items:
                if msg is None or msg.is_expired():
                    continue
                if self.max_retained \
                        and len(self._store) >= self.max_retained:
                    self.node.metrics.inc("retained.dropped")
                    continue
                if topic not in self._store:
                    self.node.metrics.inc("retained.count")
                self._put(topic, msg)
            for topic, ts in tombstones:
                self._tombstones[topic] = max(
                    self._tombstones.get(topic, 0.0), float(ts))
        finally:
            self._restoring = False

    # -- store maintenance -------------------------------------------------

    def on_publish(self, msg: Message):
        if not msg.flags.get("retain") or msg.topic.startswith("$SYS/"):
            return None
        if not msg.payload:
            if self._pop(msg.topic) is not None:
                self.node.metrics.dec("retained.count")
                # monotone like apply_remote/apply_tombstone: a local
                # delete must not move an (ahead-clock) peer's
                # tombstone backwards
                self._tombstones[msg.topic] = max(
                    self._tombstones.get(msg.topic, 0.0), msg.timestamp)
                self._replicate(msg.topic, None, msg.timestamp)
            return None
        if len(msg.payload) > self.max_payload or (
                msg.topic not in self._store
                and len(self._store) >= self.max_retained):
            self.node.metrics.inc("retained.dropped")
            return None
        if msg.topic not in self._store:
            self.node.metrics.inc("retained.count")
        stored = msg.copy()
        # the broadcast wire cache is per-live-delivery state, not
        # part of the retained record
        stored.headers.pop("_wire", None)
        self._put(msg.topic, stored)
        self._replicate(msg.topic, stored)
        return None  # the message still routes normally

    def _replicate(self, topic: str, msg, ts: float = None) -> None:
        fn = getattr(self.node, "retain_replicate", None)
        if fn is not None:
            fn(topic, msg, ts)

    def apply_remote(self, topic: str, msg, sync: bool = False,
                     ts: float = None) -> None:
        """A peer's store/delete (idempotent, never re-broadcast).

        LIVE replication (``sync=False``) applies in arrival order —
        concurrent publishes race exactly as the reference's Mnesia
        writes do, and a node with a lagging clock must not have its
        updates silently dropped cluster-wide. JOIN sync
        (``sync=True``) is the anti-entropy path: it applies
        last-WRITER-wins by message timestamp and respects delete
        tombstones, so a rejoiner's stale snapshot can neither
        clobber newer values nor resurrect deletions."""
        if msg is None:
            if self._pop(topic) is not None:
                self.node.metrics.dec("retained.count")
            # tombstone carries the DELETING message's origin
            # timestamp (not local wall-clock) so join-sync LWW stays
            # consistent under clock skew; monotone like apply_tombstone
            if ts is None:
                import time as _time

                ts = _time.time()
            self._tombstones[topic] = max(
                self._tombstones.get(topic, 0.0), ts)
            return
        if msg.is_expired():
            return
        if len(msg.payload) > self.max_payload:
            # same bound on_publish enforces — a peer with a larger
            # limit must not replicate oversize payloads into ours
            self.node.metrics.inc("retained.dropped")
            return
        if sync:
            tomb = self._tombstones.get(topic)
            if tomb is not None and tomb >= msg.timestamp:
                return
        cur = self._store.get(topic)
        if cur is not None:
            if not sync or msg.timestamp > cur.timestamp:
                self._put(topic, msg)
            return
        if len(self._store) >= self.max_retained:
            self.node.metrics.inc("retained.dropped")
            return
        self.node.metrics.inc("retained.count")
        self._put(topic, msg)

    def sweep_expired(self) -> int:
        """Drop expired entries (lazy pruning otherwise happens only
        on a matching subscribe — the stats-tick GC and the periodic
        sweep both land here)."""
        dead = [t for t, m in self._store.items() if m.is_expired()]
        for t in dead:
            self._pop(t)
            self.node.metrics.dec("retained.count")
            self.node.metrics.inc("retained.expired")
        self._sweep_tombstones()
        return len(dead)

    def entries(self):
        """Live snapshot for cluster join sync (expired swept
        first — a join must not resurrect dead entries)."""
        self.sweep_expired()
        return list(self._store.items())

    def tombstones(self):
        return list(self._tombstones.items())

    def apply_tombstone(self, topic: str, ts: float) -> None:
        """A peer's delete record (join sync): drop any locally
        stored message older than the deletion."""
        cur = self._store.get(topic)
        if cur is not None and cur.timestamp <= ts:
            self._pop(topic)
            self.node.metrics.dec("retained.count")
        prev = self._tombstones.get(topic, 0.0)
        self._tombstones[topic] = max(prev, ts)

    _TOMBSTONE_TTL = 3600.0

    def _sweep_tombstones(self) -> None:
        import time as _time

        cutoff = _time.time() - self._TOMBSTONE_TTL
        for t in [t for t, ts in self._tombstones.items()
                  if ts < cutoff]:
            self._tombstones.pop(t, None)

    # -- delivery on subscribe ---------------------------------------------

    def on_subscribed(self, clientinfo: dict, flt: str,
                      subopts: dict) -> None:
        """Hook entry: Retain-Handling/shared-sub gating happens here
        at submit time (both are per-subscription properties, fully
        known now); the matched set, expiry eviction and the delivery
        plan are deferred one event-loop tick so a SUBSCRIBE burst
        coalesces into one batched replay (:meth:`_replay_flush`)."""
        if flt.startswith(("$share/", "$queue/")):
            return  # never to shared subscriptions
        rh = subopts.get("rh", 0)
        if rh == 2 or (rh == 1 and subopts.get("resub")):
            return
        chan = self.node.cm.lookup_channel(
            clientinfo.get("clientid", ""))
        session = getattr(chan, "session", None)
        if session is None or not self._store:
            return
        import asyncio

        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            loop = None
        if loop is None:
            # loop-less (library/sync) callers keep the synchronous
            # semantics: a one-item burst, flushed inline
            self._replay_flush([(session, flt, subopts)])
            return
        # the hook fires on the subscribing channel's owner loop and
        # delivery targets that same loop's session, so pending lists
        # are per-loop: append + drain never cross threads
        pend = self._pending.get(loop)
        if pend is None:
            self._pending[loop] = pend = []
        pend.append((session, flt, subopts))
        if len(pend) == 1:
            # first item this tick: drain at the end of the current
            # loop iteration — every hook firing queued behind the
            # same SUBSCRIBE burst lands in THIS batch (zero-linger
            # coalescing, like IngressBatcher.submit)
            loop.call_soon(self._replay_kick, loop)

    def _replay_kick(self, loop) -> None:
        items = self._pending.pop(loop, None)
        if items:
            try:
                self._replay_flush(items)
            except Exception:
                log.exception("retained replay flush failed")

    def _replay_flush(self, items: list) -> None:
        """One subscribe burst → one batched index match → one
        subscriber-grouped delivery plan.

        The publish path's full PR 3/5 treatment applied to replay
        (docs/DISPATCH.md "Retained replay"): unique wildcard filters
        match in ONE device dispatch (RetainIndex.match_many), every
        stored topic materializes ONE shared out-copy per burst
        (retain flag kept per MQTT-3.3.1-8, expiry filtered here in
        the plan stage with lazy eviction), the (session, filter,
        row) triples group by subscriber through
        ops/dispatch_plan.DispatchPlan, wire frames pre-build through
        preserialize_plan (retain-set and RAP variants are serialize
        classes there), and each session takes its whole group in one
        ``deliver_many`` = one notify wakeup per connection per
        burst. ``dispatch.planner=false`` restores the legacy
        per-delivery walk byte-for-byte."""
        store = self._store
        if not store:
            return
        metrics = self.node.metrics
        # unique filters across the burst; wildcards batch through
        # the index, exact filters stay a dict probe
        flt_list: List[str] = []
        fidx: Dict[str, int] = {}
        for _sess, flt, _opts in items:
            if flt not in fidx:
                fidx[flt] = len(flt_list)
                flt_list.append(flt)
        wild = [f for f in flt_list if T.wildcard(f)]
        hits: Dict[str, List[str]] = {}
        if wild:
            hits.update(zip(wild, self._index.match_many(
                wild, device_threshold=self.index_device_threshold)))
        for f in flt_list:
            if f not in hits:
                hits[f] = [f] if f in store else []
        # burst-local message rows: ONE copy per stored topic however
        # many sessions/filters matched it, so wire caches and the
        # pre-serialized frames are shared across the whole burst
        row_of: Dict[str, int] = {}
        rows: List[Message] = []

        def row_for(topic: str) -> int:
            r = row_of.get(topic)
            if r is not None:
                return r
            msg = store.get(topic)
            if msg is None or msg.is_expired():
                if msg is not None:
                    self._pop(topic)
                    metrics.dec("retained.count")
                    metrics.inc("retained.expired")
                row_of[topic] = -1
                return -1
            out = msg.copy()
            # retained-delivery keeps retain=1 (MQTT-3.3.1-8); the
            # 'retained' header tells the session's RAP logic this
            # flag is not subject to clearing
            out.set_header("retained", True)
            row_of[topic] = r = len(rows)
            rows.append(out)
            return r

        sess_of: Dict[int, int] = {}
        sessions: List[object] = []
        sids: List[int] = []
        fids: List[int] = []
        rids: List[int] = []
        opts_of: Dict[tuple, object] = {}
        for sess, flt, _opts in items:
            topics = hits.get(flt, ())
            if not topics:
                continue
            key = id(sess)
            sid = sess_of.get(key)
            if sid is None:
                sid = sess_of[key] = len(sessions)
                sessions.append(sess)
            fid = fidx[flt]
            subs = getattr(sess, "subscriptions", None)
            # the REAL SubOpts object (the hook hands a plain dict):
            # deliver_many and preserialize_plan key serialize
            # classes off its qos/rap/share/subid fields
            opts_of[(sid, fid)] = subs.get(flt) if subs else None
            for t in topics:
                r = row_for(t)
                if r >= 0:
                    sids.append(sid)
                    fids.append(fid)
                    rids.append(r)
        if not sids:
            return
        metrics.inc("retained.replay.batches")
        metrics.inc("retained.replay.messages", len(sids))
        self._replay_last_batch = len(sids)
        cfg = getattr(getattr(self.node, "broker", None),
                      "dispatch_config", None)
        if cfg is None or not cfg.planner:
            # legacy per-delivery path (dispatch.planner=false),
            # byte-for-byte the pre-batching replay loop
            for k in range(len(sids)):
                sessions[sids[k]].deliver(
                    flt_list[fids[k]], rows[rids[k]])
            return
        from emqx_tpu.ops.dispatch_plan import (DispatchPlan,
                                                preserialize_plan)

        plan = DispatchPlan(np.asarray(sids, np.int64),
                            np.asarray(fids, np.int64),
                            np.asarray(rids, np.int64))
        if cfg.preserialize:
            subscribers: Dict[str, dict] = {}
            for (sid, fid), opts in opts_of.items():
                if opts is not None:
                    subscribers.setdefault(
                        flt_list[fid], {})[sessions[sid]] = opts
            preserialize_plan(plan, list(enumerate(rows)), flt_list,
                              subscribers, lambda sid: sessions[sid])
        g_ptr = plan.g_ptr
        for g in range(plan.n_groups):
            sid = plan.g_sids[g]
            sess = sessions[sid]
            group = []
            for k in range(g_ptr[g], g_ptr[g + 1]):
                fid = plan.fids[k]
                group.append((flt_list[fid], rows[plan.rows[k]],
                              opts_of.get((sid, fid)), False))
            dm = getattr(sess, "deliver_many", None)
            if dm is not None:
                dm(group)
            else:
                # plain subscriber objects (tests, adapters) without
                # the batched protocol
                for gflt, gmsg, _o, _f in group:
                    sess.deliver(gflt, gmsg)

    def replay_info(self) -> dict:
        """``ctl retained`` snapshot: store/replay-side counters to
        pair with ``RetainIndex.device_info``."""
        m = self.node.metrics
        return {
            "store": len(self._store),
            "tombstones": len(self._tombstones),
            "dropped": m.val("retained.dropped"),
            "expired": m.val("retained.expired"),
            "replay_batches": m.val("retained.replay.batches"),
            "replay_messages": m.val("retained.replay.messages"),
            "replay_last_batch": self._replay_last_batch,
        }

    def info(self) -> dict:
        return {"retained": len(self._store)}
