"""Retained-message store and delivery.

The reference core delegates retained messages to the separate
``emqx_retainer`` plugin application (the core only carries the
``retain`` flag and the v5 Retain-Handling/Retain-As-Published
subscription options); a broker users can actually switch to needs
the behavior in the box, so it ships here as a built-in module wired
through the same two hookpoints the reference plugin uses:

  - ``'message.publish'``: a retained PUBLISH stores its message
    under the topic (an empty retained payload deletes — MQTT
    3.3.1-6/-7); the message still routes normally.
  - ``'session.subscribed'``: a new subscription receives every
    stored message matching its filter, with the retain flag SET
    (MQTT 3.3.1-8) regardless of RAP, honoring Retain-Handling
    (0 = always send, 1 = only if the subscription did not exist,
    2 = never — MQTT 3.8.3.1) and skipping shared subscriptions
    (retained messages are never sent to ``$share`` groups) and
    expired messages (Message-Expiry-Interval).

Bounded: ``max_retained`` topics (new stores beyond it are dropped
with a counter, like the plugin's ``max_retained_messages``) and
``max_payload`` bytes per message.
"""

from __future__ import annotations

from typing import Dict

from emqx_tpu import topic as T
from emqx_tpu.modules import Module
from emqx_tpu.types import Message


class RetainerModule(Module):
    name = "retainer"

    def __init__(self, node) -> None:
        super().__init__(node)
        self._store: Dict[str, Message] = {}
        # delete tombstones (topic -> delete time): a stale
        # rejoiner's sync must not resurrect a deleted message
        self._tombstones: Dict[str, float] = {}
        self.max_retained = 0
        self.max_payload = 0
        # cluster seam: Cluster sets node.retain_replicate so stores/
        # deletes broadcast (the reference plugin replicates via
        # Mnesia); applied remotely through apply_remote (no re-fan)

    def load(self, env: dict) -> None:
        self.max_retained = int(env.get("max_retained", 1_000_000))
        self.max_payload = int(env.get("max_payload", 1 << 20))
        self.node.metrics.new("retained.count")
        self.node.metrics.new("retained.dropped")
        self.node.hooks.add("message.publish", self.on_publish,
                            priority=50)
        self.node.hooks.add("session.subscribed", self.on_subscribed,
                            priority=50)

    def unload(self) -> None:
        self.node.hooks.delete("message.publish", self.on_publish)
        self.node.hooks.delete("session.subscribed", self.on_subscribed)
        self._store.clear()

    # -- store maintenance -------------------------------------------------

    def on_publish(self, msg: Message):
        if not msg.flags.get("retain") or msg.topic.startswith("$SYS/"):
            return None
        if not msg.payload:
            if self._store.pop(msg.topic, None) is not None:
                self.node.metrics.dec("retained.count")
                # monotone like apply_remote/apply_tombstone: a local
                # delete must not move an (ahead-clock) peer's
                # tombstone backwards
                self._tombstones[msg.topic] = max(
                    self._tombstones.get(msg.topic, 0.0), msg.timestamp)
                self._replicate(msg.topic, None, msg.timestamp)
            return None
        if len(msg.payload) > self.max_payload or (
                msg.topic not in self._store
                and len(self._store) >= self.max_retained):
            self.node.metrics.inc("retained.dropped")
            return None
        if msg.topic not in self._store:
            self.node.metrics.inc("retained.count")
        stored = msg.copy()
        # the broadcast wire cache is per-live-delivery state, not
        # part of the retained record
        stored.headers.pop("_wire", None)
        self._store[msg.topic] = stored
        self._replicate(msg.topic, stored)
        return None  # the message still routes normally

    def _replicate(self, topic: str, msg, ts: float = None) -> None:
        fn = getattr(self.node, "retain_replicate", None)
        if fn is not None:
            fn(topic, msg, ts)

    def apply_remote(self, topic: str, msg, sync: bool = False,
                     ts: float = None) -> None:
        """A peer's store/delete (idempotent, never re-broadcast).

        LIVE replication (``sync=False``) applies in arrival order —
        concurrent publishes race exactly as the reference's Mnesia
        writes do, and a node with a lagging clock must not have its
        updates silently dropped cluster-wide. JOIN sync
        (``sync=True``) is the anti-entropy path: it applies
        last-WRITER-wins by message timestamp and respects delete
        tombstones, so a rejoiner's stale snapshot can neither
        clobber newer values nor resurrect deletions."""
        if msg is None:
            if self._store.pop(topic, None) is not None:
                self.node.metrics.dec("retained.count")
            # tombstone carries the DELETING message's origin
            # timestamp (not local wall-clock) so join-sync LWW stays
            # consistent under clock skew; monotone like apply_tombstone
            if ts is None:
                import time as _time

                ts = _time.time()
            self._tombstones[topic] = max(
                self._tombstones.get(topic, 0.0), ts)
            return
        if msg.is_expired():
            return
        if len(msg.payload) > self.max_payload:
            # same bound on_publish enforces — a peer with a larger
            # limit must not replicate oversize payloads into ours
            self.node.metrics.inc("retained.dropped")
            return
        if sync:
            tomb = self._tombstones.get(topic)
            if tomb is not None and tomb >= msg.timestamp:
                return
        cur = self._store.get(topic)
        if cur is not None:
            if not sync or msg.timestamp > cur.timestamp:
                self._store[topic] = msg
            return
        if len(self._store) >= self.max_retained:
            self.node.metrics.inc("retained.dropped")
            return
        self.node.metrics.inc("retained.count")
        self._store[topic] = msg

    def sweep_expired(self) -> int:
        """Drop expired entries (lazy pruning otherwise happens only
        on a matching subscribe)."""
        dead = [t for t, m in self._store.items() if m.is_expired()]
        for t in dead:
            self._store.pop(t, None)
            self.node.metrics.dec("retained.count")
        self._sweep_tombstones()
        return len(dead)

    def entries(self):
        """Live snapshot for cluster join sync (expired swept
        first — a join must not resurrect dead entries)."""
        self.sweep_expired()
        return list(self._store.items())

    def tombstones(self):
        return list(self._tombstones.items())

    def apply_tombstone(self, topic: str, ts: float) -> None:
        """A peer's delete record (join sync): drop any locally
        stored message older than the deletion."""
        cur = self._store.get(topic)
        if cur is not None and cur.timestamp <= ts:
            self._store.pop(topic, None)
            self.node.metrics.dec("retained.count")
        prev = self._tombstones.get(topic, 0.0)
        self._tombstones[topic] = max(prev, ts)

    _TOMBSTONE_TTL = 3600.0

    def _sweep_tombstones(self) -> None:
        import time as _time

        cutoff = _time.time() - self._TOMBSTONE_TTL
        for t in [t for t, ts in self._tombstones.items()
                  if ts < cutoff]:
            self._tombstones.pop(t, None)

    # -- delivery on subscribe ---------------------------------------------

    def on_subscribed(self, clientinfo: dict, flt: str,
                      subopts: dict) -> None:
        if flt.startswith(("$share/", "$queue/")):
            return  # never to shared subscriptions
        rh = subopts.get("rh", 0)
        if rh == 2 or (rh == 1 and subopts.get("resub")):
            return
        chan = self.node.cm.lookup_channel(
            clientinfo.get("clientid", ""))
        session = getattr(chan, "session", None)
        if session is None or not self._store:
            return
        if not T.wildcard(flt):
            # exact filter: one dict probe, not a store scan
            matches = [flt] if flt in self._store else []
        else:
            matches = [t for t in self._store if T.match(t, flt)]
        for topic in matches:
            msg = self._store[topic]
            if msg.is_expired():
                self._store.pop(topic, None)
                self.node.metrics.dec("retained.count")
                continue
            out = msg.copy()
            # retained-delivery keeps retain=1 (MQTT-3.3.1-8); the
            # 'retained' header tells the session's RAP logic this
            # flag is not subject to clearing
            out.set_header("retained", True)
            session.deliver(flt, out)

    def info(self) -> dict:
        return {"retained": len(self._store)}
