"""Built-in modules — lightweight plugins with load/unload
(reference: src/emqx_modules.erl + emqx_gen_mod.erl behaviour)."""

from __future__ import annotations

from typing import Dict, Type


class Module:
    """Behaviour: subclasses implement load/unload
    (emqx_gen_mod callbacks)."""

    name = "module"

    def __init__(self, node) -> None:
        self.node = node

    def load(self, env: dict) -> None:
        raise NotImplementedError

    def unload(self) -> None:
        raise NotImplementedError

    def on_loop_start(self) -> None:
        """Called by node.start() inside the running event loop.

        Config-file modules load in boot_from_file BEFORE any loop
        exists, so a module that needs background tasks (timers,
        sockets) starts them here, idempotently — load() may already
        have started them when it ran in an async context."""

    def on_loop_stop(self) -> None:
        """Called by node.stop(): quiesce background tasks WITHOUT
        unloading (hooks stay registered; a later start() re-kicks
        on_loop_start — the reference keeps modules loaded across a
        broker restart)."""

    def _kick_on_loop(self) -> bool:
        """load() helper: start loop-bound work now if a loop is
        already running, else leave it for node.start()."""
        import asyncio

        try:
            asyncio.get_running_loop()
        except RuntimeError:
            return False
        self.on_loop_start()
        return True


class ModuleRegistry:
    def __init__(self, node) -> None:
        self.node = node
        self._loaded: Dict[str, Module] = {}

    def load(self, cls: Type[Module], env: dict | None = None) -> Module:
        if cls.name in self._loaded:
            return self._loaded[cls.name]
        mod = cls(self.node)
        mod.load(env or {})
        self._loaded[cls.name] = mod
        return mod

    def unload(self, name: str) -> bool:
        mod = self._loaded.pop(name, None)
        if mod is None:
            return False
        mod.unload()
        return True

    def loaded(self):
        return list(self._loaded)

    def on_loop_start(self) -> None:
        """Kick every loaded module's loop-start hook, crash-isolated
        like hook callbacks (one broken module must not block the
        node boot)."""
        self._each("on_loop_start")

    def on_loop_stop(self) -> None:
        self._each("on_loop_stop")

    def _each(self, hook: str) -> None:
        import logging

        for mod in list(self._loaded.values()):
            try:
                getattr(mod, hook)()
            except Exception:
                logging.getLogger(__name__).exception(
                    "module %s %s failed", mod.name, hook)
