"""Regex topic rewrite on publish/subscribe
(reference: src/emqx_mod_rewrite.erl — rules of
{pub|sub, TopicFilter, Regex, Dest} where $N backrefs feed the
destination template)."""

from __future__ import annotations

import re
from typing import List, Tuple

from emqx_tpu import topic as T
from emqx_tpu.modules import Module
from emqx_tpu.types import Message

# rule: (pubsub, topic_filter, regex, dest_template)
Rule = Tuple[str, str, str, str]


class RewriteModule(Module):
    name = "rewrite"

    def __init__(self, node) -> None:
        super().__init__(node)
        self._pub_rules: List[Tuple[str, re.Pattern, str]] = []
        self._sub_rules: List[Tuple[str, re.Pattern, str]] = []

    def load(self, env: dict) -> None:
        for pubsub, flt, regex, dest in env.get("rules", []):
            compiled = (flt, re.compile(regex), dest)
            if pubsub in ("pub", "all"):
                self._pub_rules.append(compiled)
            if pubsub in ("sub", "all"):
                self._sub_rules.append(compiled)
        self.node.hooks.add("message.publish", self.on_publish,
                            priority=90)
        self.node.hooks.add("client.subscribe", self.on_subscribe,
                            priority=90)
        self.node.hooks.add("client.unsubscribe", self.on_unsubscribe,
                            priority=90)

    def unload(self) -> None:
        self.node.hooks.delete("message.publish", self.on_publish)
        self.node.hooks.delete("client.subscribe", self.on_subscribe)
        self.node.hooks.delete("client.unsubscribe", self.on_unsubscribe)

    @staticmethod
    def _rewrite(rules, topic: str) -> str:
        for flt, regex, dest in rules:
            if T.match(topic, flt):
                m = regex.match(topic)
                if m:
                    out = dest
                    for i, g in enumerate(m.groups(), 1):
                        out = out.replace(f"${i}", g or "")
                    topic = out
        return topic

    def on_publish(self, msg: Message):
        if msg.topic.startswith("$SYS/"):
            return None
        new = self._rewrite(self._pub_rules, msg.topic)
        if new != msg.topic:
            msg.topic = new
        return msg

    def on_subscribe(self, clientinfo, props, topic_filters):
        return [(self._rewrite(self._sub_rules, f), opts)
                for f, opts in topic_filters]

    def on_unsubscribe(self, clientinfo, props, topic_filters):
        return [self._rewrite(self._sub_rules, f) for f in topic_filters]
