"""Per-topic in/out/dropped counters with rate EMA
(reference: src/emqx_mod_topic_metrics.erl)."""

from __future__ import annotations

import time
from typing import Dict, Optional

from emqx_tpu import topic as T
from emqx_tpu.modules import Module
from emqx_tpu.types import Message

METRICS = ["messages.in", "messages.out", "messages.qos0.in",
           "messages.qos1.in", "messages.qos2.in", "messages.dropped"]
MAX_TOPICS = 512


class _Counters(dict):
    def __init__(self):
        super().__init__({m: 0 for m in METRICS})
        self.created = time.time()
        self._rate: Dict[str, float] = {}
        self._last: Dict[str, tuple] = {}

    def rate(self, metric: str) -> float:
        now = time.time()
        last_v, last_t = self._last.get(metric, (0, self.created))
        dt = max(now - last_t, 1e-9)
        inst = (self[metric] - last_v) / dt
        # exponential moving average (reference's speed calc)
        ema = self._rate.get(metric, 0.0) * 0.7 + inst * 0.3
        self._rate[metric] = ema
        self._last[metric] = (self[metric], now)
        return ema


class TopicMetricsModule(Module):
    name = "topic_metrics"

    def __init__(self, node) -> None:
        super().__init__(node)
        self._topics: Dict[str, _Counters] = {}

    def load(self, env: dict) -> None:
        for t in env.get("topics", []):
            self.register(t)
        self.node.hooks.add("message.publish", self.on_publish,
                            priority=-100)  # after rewrites
        self.node.hooks.add("message.dropped", self.on_dropped)
        self.node.hooks.add("message.delivered", self.on_delivered)

    def unload(self) -> None:
        self.node.hooks.delete("message.publish", self.on_publish)
        self.node.hooks.delete("message.dropped", self.on_dropped)
        self.node.hooks.delete("message.delivered", self.on_delivered)
        self._topics.clear()

    def register(self, topic: str) -> bool:
        if T.wildcard(topic):
            raise ValueError("wildcard topic not allowed")
        if len(self._topics) >= MAX_TOPICS:
            return False
        self._topics.setdefault(topic, _Counters())
        return True

    def unregister(self, topic: str) -> None:
        self._topics.pop(topic, None)

    def on_publish(self, msg: Message):
        c = self._topics.get(msg.topic)
        if c is not None:
            c["messages.in"] += 1
            c[f"messages.qos{min(msg.qos, 2)}.in"] += 1
        return None

    def on_dropped(self, msg: Message, reason: str):
        c = self._topics.get(msg.topic)
        if c is not None:
            c["messages.dropped"] += 1

    def on_delivered(self, msg: Message, n: int):
        self.inc_out(msg.topic, n)

    def inc_out(self, topic: str, n: int = 1) -> None:
        c = self._topics.get(topic)
        if c is not None:
            c["messages.out"] += n

    def metrics(self, topic: str) -> Optional[dict]:
        c = self._topics.get(topic)
        return dict(c) if c is not None else None

    def rates(self, topic: str) -> Optional[dict]:
        c = self._topics.get(topic)
        if c is None:
            return None
        return {m: c.rate(m) for m in METRICS}

    def all_topics(self):
        return list(self._topics)
