"""``$delayed/<secs>/<topic>`` delayed publish
(reference: src/emqx_mod_delayed.erl — intercepts 'message.publish',
stores, republishes after the delay)."""

from __future__ import annotations

import asyncio
import heapq
import time
from typing import List, Optional, Tuple

from emqx_tpu.hooks import STOP
from emqx_tpu.modules import Module
from emqx_tpu.types import Message

PREFIX = "$delayed/"
MAX_DELAY = 4294967  # seconds (reference caps at 0xFFFFFFFF ms)


class DelayedModule(Module):
    name = "delayed"

    def __init__(self, node) -> None:
        super().__init__(node)
        self._heap: List[Tuple[float, int, Message]] = []
        self._seq = 0
        self._task: Optional[asyncio.Task] = None

    def load(self, env: dict) -> None:
        self.node.broker.delayed = self  # the channel consults this
        self.node.hooks.add("message.publish", self.on_publish,
                            priority=100)
        # no loop yet -> node.start() kicks on_loop_start;
        # bare-sync tests tick() manually
        self._kick_on_loop()

    def on_loop_start(self) -> None:
        if self._task is None or self._task.done():
            loop = asyncio.get_running_loop()
            self._task = loop.create_task(self._timer_loop())

    def on_loop_stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None

    def unload(self) -> None:
        if getattr(self.node.broker, 'delayed', None) is self:
            self.node.broker.delayed = None
        self.node.hooks.delete("message.publish", self.on_publish)
        self.on_loop_stop()

    # -- hook -------------------------------------------------------------

    def on_publish(self, msg: Message):
        if not msg.topic.startswith(PREFIX):
            return None
        rest = msg.topic[len(PREFIX):]
        if "/" not in rest:
            return None
        secs_s, real_topic = rest.split("/", 1)
        try:
            secs = min(int(secs_s), MAX_DELAY)
        except ValueError:
            return None
        self.delay(msg, secs, real_topic)
        # veto the immediate publish
        msg.set_header("allow_publish", False)
        if self.node.broker is not None:
            self.node.broker.metrics.inc("messages.delayed")
        return (STOP, msg)

    def delay(self, msg: Message, secs: float,
              real_topic: Optional[str] = None) -> None:
        m = msg.copy()
        if real_topic is not None:
            m.topic = real_topic
        m.headers.pop("allow_publish", None)
        self._seq += 1
        heapq.heappush(self._heap, (time.time() + secs, self._seq, m))

    # -- delivery ---------------------------------------------------------

    def tick(self, now: Optional[float] = None) -> int:
        now = time.time() if now is None else now
        n = 0
        while self._heap and self._heap[0][0] <= now:
            _, _, msg = heapq.heappop(self._heap)
            self.node.broker.publish(msg)
            n += 1
        return n

    async def _timer_loop(self) -> None:
        while True:
            await asyncio.sleep(0.5)
            self.tick()

    def __len__(self) -> int:
        return len(self._heap)
