"""File/rule-based ACL — the internal ACL backend.

Mirrors ``src/emqx_mod_acl_internal.erl`` + ``src/emqx_access_rule.erl``
(etc/acl.conf): ordered rules of

    (allow|deny, who, access, topics)

who:    "all" | ("user", Name) | ("client", Id) | ("ipaddr", CIDR)
access: "subscribe" | "publish" | "pubsub"
topics: list of topic filters; ("eq", topic) pins a literal match
        (no wildcard expansion); %c/%u placeholders substitute the
        client's id/username.

First matching rule wins; no match falls through to the zone's
acl_nomatch default (handled by AccessControl).
"""

from __future__ import annotations

import ipaddress
from typing import List, Tuple, Union

from emqx_tpu import topic as T
from emqx_tpu.access_control import ALLOW, DENY
from emqx_tpu.hooks import STOP
from emqx_tpu.modules import Module

Who = Union[str, Tuple[str, str]]
TopicSpec = Union[str, Tuple[str, str]]


DEFAULT_RULES: List[tuple] = [
    # mirror etc/acl.conf defaults: dashboard user, localhost full
    # access, deny $SYS+eq(#) sub for others, allow rest
    ("allow", ("user", "dashboard"), "subscribe", ["$SYS/#"]),
    ("allow", ("ipaddr", "127.0.0.1"), "pubsub", ["$SYS/#", "#"]),
    ("deny", "all", "subscribe", ["$SYS/#", ("eq", "#")]),
    ("allow", "all", "pubsub", ["#"]),
]


def parse_acl_file(text: str) -> List[tuple]:
    """Parse the reference's ``etc/acl.conf`` format (a subset of
    Erlang terms — ``src/emqx_mod_acl_internal.erl`` consults the
    file the same way):

        {allow, {user, "dashboard"}, subscribe, ["$SYS/#"]}.
        {deny, all, subscribe, ["$SYS/#", {eq, "#"}]}.
        {allow, all}.

    ``%%`` comments out the rest of a line. Returns rule tuples in
    this module's native shape; a 2-tuple ``{allow|deny, all}``
    becomes a catch-all over every access and topic.
    """
    import re

    # strip %-comments (the reference's files use %%), keep strings
    lines = []
    for line in text.splitlines():
        out, i, in_str = [], 0, False
        while i < len(line):
            ch = line[i]
            if in_str and ch == "\\" and i + 1 < len(line):
                # escaped char inside a string (e.g. \") must not
                # toggle string tracking or start a comment
                out.append(ch)
                out.append(line[i + 1])
                i += 2
                continue
            if ch == '"':
                in_str = not in_str
            if ch == "%" and not in_str:
                break
            out.append(ch)
            i += 1
        lines.append("".join(out))
    src = "\n".join(lines)
    toks = re.findall(r'"(?:[^"\\]|\\.)*"|[{}\[\],.]|[A-Za-z0-9_/$#+%.-]+',
                      src)
    pos = 0

    def peek():
        return toks[pos] if pos < len(toks) else None

    def take(expect=None):
        nonlocal pos
        t = toks[pos]
        if expect is not None and t != expect:
            raise ValueError(f"acl.conf: expected {expect!r}, got {t!r}")
        pos += 1
        return t

    def term():
        t = peek()
        if t == "{":
            take()
            items = []
            while peek() != "}":
                items.append(term())
                if peek() == ",":
                    take()
            take("}")
            return tuple(items)
        if t == "[":
            take()
            items = []
            while peek() != "]":
                items.append(term())
                if peek() == ",":
                    take()
            take("]")
            return items
        t = take()
        if t.startswith('"'):
            return t[1:-1].replace('\\"', '"')
        return t

    rules: List[tuple] = []
    while pos < len(toks):
        r = term()
        take(".")
        if not isinstance(r, tuple) or r[0] not in ("allow", "deny"):
            raise ValueError(f"acl.conf: bad rule {r!r}")
        if len(r) == 2:
            # {allow|deny, all} catch-all: matches EVERY topic,
            # including $-prefixed ones '#' would exclude
            rules.append((r[0], r[1], "pubsub", None))
        elif len(r) == 4:
            rules.append((r[0], r[1], r[2], list(r[3])))
        else:
            raise ValueError(f"acl.conf: bad rule arity {r!r}")
    return rules


class AclFileModule(Module):
    name = "acl_internal"

    def __init__(self, node) -> None:
        super().__init__(node)
        self.rules: List[tuple] = []

    def load(self, env: dict) -> None:
        if "file" in env:
            with open(env["file"], "r", encoding="utf-8") as f:
                self.rules = parse_acl_file(f.read())
        else:
            self.rules = list(env.get("rules", DEFAULT_RULES))
        self.node.hooks.add("client.check_acl", self.check_acl,
                            priority=-10)

    def unload(self) -> None:
        self.node.hooks.delete("client.check_acl", self.check_acl)

    # -- rule evaluation (emqx_access_rule:match/3) -----------------------

    def check_acl(self, clientinfo: dict, pubsub: str, topic: str, acc):
        for rule in self.rules:
            verdict, who, access, topics = rule
            if not self._match_access(access, pubsub):
                continue
            if not self._match_who(who, clientinfo):
                continue
            if not self._match_topics(topics, topic, clientinfo):
                continue
            return (STOP, ALLOW if verdict == "allow" else DENY)
        return None  # fall through to default

    @staticmethod
    def _match_access(access: str, pubsub: str) -> bool:
        return access == "pubsub" or access == pubsub

    @staticmethod
    def _match_who(who: Who, clientinfo: dict) -> bool:
        if who == "all":
            return True
        kind, value = who
        if kind == "user":
            return clientinfo.get("username") == value
        if kind == "client":
            return clientinfo.get("clientid") == value
        if kind == "ipaddr":
            try:
                host = clientinfo.get("peerhost", "")
                return ipaddress.ip_address(host) in ipaddress.ip_network(
                    value, strict=False)
            except ValueError:
                return False
        return False

    @staticmethod
    def _match_topics(topics, topic: str,
                      clientinfo: dict) -> bool:
        from emqx_tpu.mountpoint import replvar

        if topics is None:
            # {allow|deny, all} catch-all: every topic, including
            # $-prefixed names that '#' would exclude
            return True

        for spec in topics:
            if isinstance(spec, tuple):  # ("eq", literal)
                if spec[1] == topic:
                    return True
                continue
            flt = replvar(spec, clientinfo.get("clientid", ""),
                          clientinfo.get("username"))
            if T.match(topic, flt):
                return True
        return False
