"""File/rule-based ACL — the internal ACL backend.

Mirrors ``src/emqx_mod_acl_internal.erl`` + ``src/emqx_access_rule.erl``
(etc/acl.conf): ordered rules of

    (allow|deny, who, access, topics)

who:    "all" | ("user", Name) | ("client", Id) | ("ipaddr", CIDR)
access: "subscribe" | "publish" | "pubsub"
topics: list of topic filters; ("eq", topic) pins a literal match
        (no wildcard expansion); %c/%u placeholders substitute the
        client's id/username.

First matching rule wins; no match falls through to the zone's
acl_nomatch default (handled by AccessControl).
"""

from __future__ import annotations

import ipaddress
from typing import List, Optional, Tuple, Union

from emqx_tpu import topic as T
from emqx_tpu.access_control import ALLOW, DENY
from emqx_tpu.hooks import STOP
from emqx_tpu.modules import Module

Who = Union[str, Tuple[str, str]]
TopicSpec = Union[str, Tuple[str, str]]


DEFAULT_RULES: List[tuple] = [
    # mirror etc/acl.conf defaults: dashboard user, localhost full
    # access, deny $SYS+eq(#) sub for others, allow rest
    ("allow", ("user", "dashboard"), "subscribe", ["$SYS/#"]),
    ("allow", ("ipaddr", "127.0.0.1"), "pubsub", ["$SYS/#", "#"]),
    ("deny", "all", "subscribe", ["$SYS/#", ("eq", "#")]),
    ("allow", "all", "pubsub", ["#"]),
]


class AclFileModule(Module):
    name = "acl_internal"

    def __init__(self, node) -> None:
        super().__init__(node)
        self.rules: List[tuple] = []

    def load(self, env: dict) -> None:
        self.rules = list(env.get("rules", DEFAULT_RULES))
        self.node.hooks.add("client.check_acl", self.check_acl,
                            priority=-10)

    def unload(self) -> None:
        self.node.hooks.delete("client.check_acl", self.check_acl)

    # -- rule evaluation (emqx_access_rule:match/3) -----------------------

    def check_acl(self, clientinfo: dict, pubsub: str, topic: str, acc):
        for rule in self.rules:
            verdict, who, access, topics = rule
            if not self._match_access(access, pubsub):
                continue
            if not self._match_who(who, clientinfo):
                continue
            if not self._match_topics(topics, topic, clientinfo):
                continue
            return (STOP, ALLOW if verdict == "allow" else DENY)
        return None  # fall through to default

    @staticmethod
    def _match_access(access: str, pubsub: str) -> bool:
        return access == "pubsub" or access == pubsub

    @staticmethod
    def _match_who(who: Who, clientinfo: dict) -> bool:
        if who == "all":
            return True
        kind, value = who
        if kind == "user":
            return clientinfo.get("username") == value
        if kind == "client":
            return clientinfo.get("clientid") == value
        if kind == "ipaddr":
            try:
                host = clientinfo.get("peerhost", "")
                return ipaddress.ip_address(host) in ipaddress.ip_network(
                    value, strict=False)
            except ValueError:
                return False
        return False

    @staticmethod
    def _match_topics(topics: List[TopicSpec], topic: str,
                      clientinfo: dict) -> bool:
        from emqx_tpu.mountpoint import replvar

        for spec in topics:
            if isinstance(spec, tuple):  # ("eq", literal)
                if spec[1] == topic:
                    return True
                continue
            flt = replvar(spec, clientinfo.get("clientid", ""),
                          clientinfo.get("username"))
            if T.match(topic, flt):
                return True
        return False
