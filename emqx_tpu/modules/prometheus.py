"""Prometheus exposition endpoint for the node's counters and gauges.

The reference ecosystem ships this as the `emqx_prometheus` plugin
(outside the core app); here it is a built-in module because the
metric registries it reads (`emqx_tpu/metrics.py` ↔
src/emqx_metrics.erl, `emqx_tpu/stats.py` ↔ src/emqx_stats.erl) are
core surfaces and an ops stack without a scrape endpoint is
incomplete. Stdlib-only: a minimal asyncio HTTP listener serving
`GET /metrics` in the Prometheus text exposition format (0.0.4).

Naming: metric/stat keys are dotted (`messages.received`,
`subscriptions.count`); Prometheus names must match
``[a-zA-Z_:][a-zA-Z0-9_:]*``, so dots and slashes become underscores
under an ``emqx_`` prefix: ``emqx_messages_received``. Counters from
the metrics registry are TYPE counter — EXCEPT the audited
non-monotonic names (`metrics.GAUGE_METRICS`, e.g. the retainer's
live-entry count, which `Metrics.dec` moves down): those are TYPE
gauge, because a scraper computes `rate()` over counters and reads
any decrease as a process restart. Stats are point-in-time TYPE
gauge (their ``.max`` companions included). Publish-path latency
histograms (`emqx_tpu/telemetry.py`) render as proper histogram
families: cumulative ``_bucket{le=...}`` lines (buckets in
milliseconds, matching the ``_ms`` family suffix), ``_sum``,
``_count``.

Env keys (``[modules.prometheus]``): ``host`` (default 127.0.0.1),
``port`` (default 9505; 0 = ephemeral, the bound port is in
``self.port`` after load).
"""

from __future__ import annotations

import asyncio
import logging
import re
from typing import Optional

from emqx_tpu.modules import Module

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def prom_name(key: str) -> str:
    return "emqx_" + _NAME_RE.sub("_", key)


def render(metrics: dict, stats: dict,
           histograms: Optional[dict] = None) -> str:
    """The registries as one exposition document. Counters and
    gauges carry no labels (single-node registry; per-topic metrics
    stay in the topic_metrics module, deliberately unexported — an
    unbounded topic set is a label-cardinality trap); histogram
    buckets carry only the standard ``le`` label.

    ``histograms`` maps a ready-made family name to a
    ``Histogram.snapshot()`` dict (cumulative ``(le, count)`` bucket
    pairs + sum/count) — the shape ``Telemetry.histograms()``
    produces."""
    from emqx_tpu.metrics import GAUGE_METRICS

    out = []
    for key in sorted(metrics):
        name = prom_name(key)
        kind = "gauge" if key in GAUGE_METRICS else "counter"
        out.append(f"# TYPE {name} {kind}")
        out.append(f"{name} {int(metrics[key])}")
    for key in sorted(stats):
        name = prom_name(key)
        out.append(f"# TYPE {name} gauge")
        val = stats[key]
        if isinstance(val, float) and not val.is_integer():
            # sub-unit gauges (cluster.hb.rtt_ms) must not floor to 0
            out.append(f"{name} {val}")
        else:
            out.append(f"{name} {int(val)}")
    for name in sorted(histograms or ()):
        snap = histograms[name]
        out.append(f"# TYPE {name} histogram")
        for le, cum in snap["buckets"]:
            out.append(f'{name}_bucket{{le="{format(le, "g")}"}} {cum}')
        out.append(f'{name}_bucket{{le="+Inf"}} {snap["count"]}')
        out.append(f"{name}_sum {snap['sum']:.6f}")
        out.append(f"{name}_count {snap['count']}")
    return "\n".join(out) + "\n"


class PrometheusModule(Module):
    name = "prometheus"

    def __init__(self, node) -> None:
        super().__init__(node)
        self._server: Optional[asyncio.base_events.Server] = None
        self._task: Optional[asyncio.Task] = None
        self._closing = False
        self.port: Optional[int] = None

    def load(self, env: dict) -> None:
        self._host = env.get("host", "127.0.0.1")
        self._port = int(env.get("port", 9505))
        self._kick_on_loop()

    def on_loop_start(self) -> None:
        self._closing = False
        if self._task is None or (self._task.done()
                                  and self._server is None):
            loop = asyncio.get_running_loop()
            self._task = loop.create_task(self._serve())

    def on_loop_stop(self) -> None:
        # flag-based shutdown, NOT a mid-bind cancel: cancelling the
        # serve task exactly as start_server completes internally
        # would drop an already-bound Server with no reference left
        # to close — the flag lets _serve finish and self-close
        self._closing = True
        if self._server is not None:
            self._server.close()
            self._server = None
            self.port = None

    def unload(self) -> None:
        self.on_loop_stop()
        self._task = None

    async def _serve(self) -> None:
        try:
            server = await asyncio.start_server(
                self._handle, self._host, self._port)
        except OSError as e:
            # a silent scrape endpoint is an ops trap: say WHY at
            # boot (EADDRINUSE etc), don't leave an unretrieved task
            # exception for loop teardown
            logging.getLogger(__name__).error(
                "prometheus endpoint failed to bind %s:%s: %s",
                self._host, self._port, e)
            return
        if self._closing:  # unload/stop raced the bind
            server.close()
            return
        self._server = server
        self.port = server.sockets[0].getsockname()[1]

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        try:
            req = await asyncio.wait_for(reader.readline(), timeout=5.0)
            # drain headers to be a polite HTTP/1.1 peer
            while True:
                line = await asyncio.wait_for(reader.readline(),
                                              timeout=5.0)
                if line in (b"\r\n", b"\n", b""):
                    break
            parts = req.decode("latin-1").split()
            if len(parts) >= 2 and parts[0] == "GET" \
                    and parts[1].split("?")[0] == "/metrics":
                # refresh registered gauge update-funs before reading,
                # like the $SYS heartbeat does
                self.node.stats.tick()
                tel = getattr(self.node, "telemetry", None)
                hists = (tel.histograms()
                         if tel is not None and tel.enabled else None)
                body = render(self.node.metrics.all(),
                              self.node.stats.all(), hists).encode()
                head = (b"HTTP/1.1 200 OK\r\n"
                        b"Content-Type: text/plain; version=0.0.4; "
                        b"charset=utf-8\r\n"
                        b"Content-Length: %d\r\n"
                        b"Connection: close\r\n\r\n" % len(body))
                writer.write(head + body)
            else:
                writer.write(b"HTTP/1.1 404 Not Found\r\n"
                             b"Content-Length: 0\r\n"
                             b"Connection: close\r\n\r\n")
            await writer.drain()
        except (asyncio.TimeoutError, ConnectionError, ValueError):
            # ValueError = StreamReader's LimitOverrunError on a
            # >64KiB line (scanner garbage) — drop, don't crash the
            # connection task
            pass
        finally:
            try:
                writer.close()
            except Exception:
                pass
