"""Session message queue with per-topic priorities and bounded length.

Mirrors ``src/emqx_mqueue.erl`` (record at :94-102, ``in/2`` at
:148-168): QoS0 messages are dropped unless ``store_qos0``; when a
priority class reaches ``max_len`` the *oldest message of that class*
is dropped (drop-oldest, not drop-new); ``max_len == 0`` means
unbounded. No disk persistence by design (the reference documents the
same, emqx_mqueue.erl:20-25).
"""

from __future__ import annotations

from typing import Dict, Optional

from emqx_tpu.pqueue import PQueue
from emqx_tpu.types import Message, QOS_0

MAX_LEN_INFINITY = 0


class MQueue:
    def __init__(
        self,
        max_len: int = MAX_LEN_INFINITY,
        store_qos0: bool = False,
        priorities: Optional[Dict[str, int]] = None,
        default_priority: float = 0,
    ) -> None:
        self.max_len = max_len if isinstance(max_len, int) and max_len > 0 else 0
        self.store_qos0 = store_qos0
        self.p_table = priorities
        self.default_p = default_priority
        self.dropped = 0
        self._len = 0
        self._q = PQueue()

    def __len__(self) -> int:
        return self._len

    def is_empty(self) -> bool:
        return self._len == 0

    def _priority(self, topic: str) -> float:
        # no priority table -> always lowest (the reference's
        # micro-optimization, emqx_mqueue.erl:196-200)
        if not self.p_table:
            return 0
        return self.p_table.get(topic, self.default_p)

    def push(self, msg: Message) -> Optional[Message]:
        """Enqueue; returns the dropped message if any (the new one
        for unstored QoS0, the class-oldest when full)."""
        if msg.qos == QOS_0 and not self.store_qos0:
            return msg
        prio = self._priority(msg.topic)
        if self.max_len != 0 and self._q.plen(prio) >= self.max_len:
            _, dropped = self._q.pop(prio)
            self._q.push(msg, prio)
            self.dropped += 1
            return dropped
        self._q.push(msg, prio)
        self._len += 1
        return None

    def pop(self) -> Optional[Message]:
        if self._len == 0:
            return None
        found, msg = self._q.pop()
        if found:
            self._len -= 1
            return msg
        return None

    def info(self) -> dict:
        return {"store_qos0": self.store_qos0, "max_len": self.max_len,
                "len": self._len, "dropped": self.dropped}

    # -- serialization (session to_wire / durability checkpoints) ---------

    def snapshot(self):
        """Per-priority FIFO contents, order-preserving:
        ``[(priority, [Message, ...]), ...]`` — pure data, encodable
        by the cluster wire codec."""
        return [(p, list(q)) for p, q in self._q._qs.items()]

    def restore(self, items) -> None:
        """Refill from :meth:`snapshot` output (onto an empty queue;
        bypasses the QoS0/length policies — the messages already
        passed them when first enqueued)."""
        for prio, msgs in items:
            for msg in msgs:
                self._q.push(msg, prio)
                self._len += 1
