"""Per-client session state machine: subscriptions, QoS flows,
delivery window, message queue.

Mirrors ``src/emqx_session.erl`` (#session record :96-124): the
session is the per-client, inherently-sequential half of the broker
(SURVEY §7 step 4 — kept host-side by design; the batched device path
ends at the broker's dispatch into sessions). Covers:

  - subscribe/unsubscribe with max_subscriptions quota (:238-276)
  - inbound publish with QoS2 awaiting_rel two-phase flow (:281-301)
  - outbound delivery: subopts enrichment (qos min/upgrade, nl, rap,
    subid :505-530), packet-id assignment, inflight window with
    mqueue overflow (:419-457)
  - puback/pubrec/pubrel/pubcomp (:314-376) with dequeue-on-ack
  - retry with dup flag + delivery expiry (:543-577)
  - awaiting_rel expiry (:582-599)
  - takeover/resume/replay (:606-629)

A Session is also a broker subscriber: ``deliver(filter, msg)``
enriches + windows the message and appends ready-to-send publishes to
``outbox`` for the channel/connection to drain.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Iterable, List, Optional, Tuple

from emqx_tpu import topic as T
from emqx_tpu.concurrency import owner_loop
from emqx_tpu.inflight import Inflight
from emqx_tpu.mqueue import MQueue
from emqx_tpu.types import Message, QOS_0, QOS_2, SubOpts

# reason codes used at the session boundary (mqtt/reason_codes has
# the full table)
RC_SUCCESS = 0x00
RC_NO_SUBSCRIPTION_EXISTED = 0x11
RC_PACKET_IDENTIFIER_IN_USE = 0x91
RC_PACKET_IDENTIFIER_NOT_FOUND = 0x92
RC_RECEIVE_MAXIMUM_EXCEEDED = 0x93
RC_QUOTA_EXCEEDED = 0x97

PUBREL_MARKER = "pubrel"


class SessionError(Exception):
    def __init__(self, rc: int):
        super().__init__(hex(rc))
        self.rc = rc


class Session:
    def __init__(
        self,
        client_id: str,
        broker=None,
        clean_start: bool = True,
        max_subscriptions: int = 0,
        max_inflight: int = 32,
        max_mqueue_len: int = 1000,
        mqueue_store_qos0: bool = False,
        mqueue_priorities: Optional[Dict[str, int]] = None,
        mqueue_default_priority: float = 0,
        upgrade_qos: bool = False,
        retry_interval: float = 30.0,
        max_awaiting_rel: int = 100,
        await_rel_timeout: float = 300.0,
        expiry_interval: float = 0.0,
    ) -> None:
        self.client_id = client_id
        self.broker = broker
        self.clean_start = clean_start
        self.created_at = time.time()
        self.subscriptions: Dict[str, SubOpts] = {}
        # reverse share-suffix map: bare filter -> the full
        # "$share/<g>/…" / "$queue/…" subscription key, so shared
        # deliveries resolve their subopts in one dict fetch instead
        # of a linear scan over every subscription (_enrich). First
        # subscription wins on a bare-filter collision, matching the
        # old scan's insertion-order pick.
        self._share_keys: Dict[str, str] = {}
        self.max_subscriptions = max_subscriptions
        self.upgrade_qos = upgrade_qos
        self.inflight = Inflight(max_inflight)
        self.mqueue = MQueue(max_mqueue_len, mqueue_store_qos0,
                             mqueue_priorities, mqueue_default_priority)
        self.next_pkt_id = 1
        self.retry_interval = retry_interval
        self.awaiting_rel: Dict[int, float] = {}
        self.max_awaiting_rel = max_awaiting_rel
        self.await_rel_timeout = await_rel_timeout
        self.expiry_interval = expiry_interval
        # (packet_id | None, Message) or (PUBREL_MARKER, packet_id)
        self.outbox: List[Tuple[Any, Any]] = []
        # wakeup hook: the owning connection sets this so broker-driven
        # deliveries flush to the socket (the BEAM's message-send wakeup
        # has no implicit analogue in asyncio)
        self.notify = None
        # False while the owner is disconnected (persistent session):
        # deliveries then enqueue instead of entering the send window
        # (the reference channel's `disconnected` state)
        self.connected = True
        # egress pre-serialization hints, stamped by the owning
        # channel at CONNECT (ops/dispatch_plan.preserialize_plan
        # reads them off-loop): the negotiated protocol version, and
        # whether the transport can take shared wire bytes at all
        # (wire_fast, no mountpoint, no outbound topic aliasing).
        # None/False = never pre-build for this subscriber.
        self.proto_ver: Optional[int] = None
        self.wire_fast_hint = False
        # multi-loop front door (loops.LoopGroup): the event loop that
        # owns this session's connection — stamped by the channel at
        # CONNECT, cleared on detach. The dispatch planner's cross-loop
        # delivery ring routes this session's subscriber group to that
        # loop, so inflight/mqueue/outbox are only touched from it.
        # None = deliver from the main loop (single-loop build,
        # detached sessions, loop-less sync callers).
        self.owner_loop = None
        # durability (docs/DURABILITY.md): True once the channel
        # opened this session with a session-expiry > 0 — its
        # lifecycle, subscriptions and QoS1/2 window then journal
        # through `_dur` (the node's DurabilityManager). Both stay
        # None/False on a non-durable build: every `_mark_dirty`
        # below is one attribute test
        self.durable = False
        self._dur = None

    # -- info --------------------------------------------------------------

    def info(self) -> dict:
        return {
            "clientid": self.client_id,
            "clean_start": self.clean_start,
            "subscriptions_cnt": len(self.subscriptions),
            "inflight_cnt": len(self.inflight),
            "mqueue_len": len(self.mqueue),
            "mqueue_dropped": self.mqueue.dropped,
            "awaiting_rel_cnt": len(self.awaiting_rel),
            "next_pkt_id": self.next_pkt_id,
            "created_at": self.created_at,
        }

    stats = info

    # -- wire transfer (cross-node takeover) ------------------------------

    def to_wire(self) -> dict:
        """Pure-data snapshot for the cluster wire (emqx_tpu.wire) —
        every value is a scalar, container, Message or SubOpts; no
        live references (broker/notify are connection-local and the
        takeover path severs them anyway)."""
        return {
            "client_id": self.client_id,
            "clean_start": self.clean_start,
            "created_at": self.created_at,
            "subscriptions": dict(self.subscriptions),
            "max_subscriptions": self.max_subscriptions,
            "upgrade_qos": self.upgrade_qos,
            "max_inflight": self.inflight.max_size,
            "inflight": self.inflight.to_list(),
            "next_pkt_id": self.next_pkt_id,
            "retry_interval": self.retry_interval,
            "awaiting_rel": dict(self.awaiting_rel),
            "max_awaiting_rel": self.max_awaiting_rel,
            "await_rel_timeout": self.await_rel_timeout,
            "expiry_interval": self.expiry_interval,
            "outbox": list(self.outbox),
            "mq_max_len": self.mqueue.max_len,
            "mq_store_qos0": self.mqueue.store_qos0,
            "mq_priorities": self.mqueue.p_table,
            "mq_default_p": self.mqueue.default_p,
            "mq_dropped": self.mqueue.dropped,
            # per-priority FIFO order preserved
            "mq_items": self.mqueue.snapshot(),
        }

    @classmethod
    def from_wire(cls, d: dict) -> "Session":
        """Rebuild a session from :meth:`to_wire` data. The result is
        detached (no broker, not connected) — ``resume()`` attaches
        it on the taking-over node."""
        s = cls(
            client_id=d["client_id"],
            clean_start=bool(d["clean_start"]),
            max_subscriptions=int(d["max_subscriptions"]),
            max_inflight=int(d["max_inflight"]),
            max_mqueue_len=int(d["mq_max_len"]),
            mqueue_store_qos0=bool(d["mq_store_qos0"]),
            mqueue_priorities=d["mq_priorities"],
            mqueue_default_priority=d["mq_default_p"],
            upgrade_qos=bool(d["upgrade_qos"]),
            retry_interval=d["retry_interval"],
            max_awaiting_rel=int(d["max_awaiting_rel"]),
            await_rel_timeout=d["await_rel_timeout"],
            expiry_interval=d["expiry_interval"],
        )
        s.created_at = d["created_at"]
        s.subscriptions = dict(d["subscriptions"])
        s._rebuild_share_keys()
        s.inflight.restore(d["inflight"])
        s.next_pkt_id = int(d["next_pkt_id"])
        s.awaiting_rel = dict(d["awaiting_rel"])
        s.outbox = list(d["outbox"])
        s.mqueue.dropped = int(d["mq_dropped"])
        s.mqueue.restore(d["mq_items"])
        s.connected = False
        return s

    # -- SUBSCRIBE / UNSUBSCRIBE ------------------------------------------

    def subscribe(self, topic_filter: str,
                  opts: Optional[SubOpts] = None) -> None:
        is_new = topic_filter not in self.subscriptions
        if (is_new and self.max_subscriptions
                and len(self.subscriptions) >= self.max_subscriptions):
            raise SessionError(RC_QUOTA_EXCEEDED)
        opts = opts or SubOpts()
        if self.broker is not None:
            self.broker.subscribe(self, topic_filter, opts)
        self.subscriptions[topic_filter] = opts
        if opts.share is not None or topic_filter.startswith(
                ("$share/", "$queue/")):
            bare, _ = T.parse(topic_filter)
            self._share_keys.setdefault(bare, topic_filter)

    def unsubscribe(self, topic_filter: str) -> SubOpts:
        if topic_filter not in self.subscriptions:
            raise SessionError(RC_NO_SUBSCRIPTION_EXISTED)
        if self.broker is not None:
            self.broker.unsubscribe(self, topic_filter)
        opts = self.subscriptions.pop(topic_filter)
        if self._share_keys:
            bare, _ = T.parse(topic_filter)
            if self._share_keys.get(bare) == topic_filter:
                # another group may still cover the bare filter
                self._rebuild_share_keys()
        return opts

    def _rebuild_share_keys(self) -> None:
        keys: Dict[str, str] = {}
        for key, o in self.subscriptions.items():
            if o.share is not None or key.startswith(
                    ("$share/", "$queue/")):
                bare, _ = T.parse(key)
                keys.setdefault(bare, key)
        self._share_keys = keys

    # -- inbound PUBLISH (client -> broker) -------------------------------

    @owner_loop
    def publish(self, packet_id: Optional[int], msg: Message) -> int:
        """Returns the delivery count from the broker."""
        if msg.qos == QOS_2:
            self.check_awaiting_rel(packet_id)
            n = self.broker.publish(msg) if self.broker else 0
            self.record_awaiting_rel(packet_id)
            return n
        return self.broker.publish(msg) if self.broker else 0

    def check_awaiting_rel(self, packet_id: Optional[int]) -> None:
        """QoS2 receive-window checks, split from :meth:`publish` so
        the batched ingress path can validate synchronously while the
        broker call itself is deferred to the batch flush."""
        if (self.max_awaiting_rel
                and len(self.awaiting_rel) >= self.max_awaiting_rel):
            raise SessionError(RC_RECEIVE_MAXIMUM_EXCEEDED)
        if packet_id in self.awaiting_rel:
            raise SessionError(RC_PACKET_IDENTIFIER_IN_USE)

    def record_awaiting_rel(self, packet_id: Optional[int]) -> None:
        self.awaiting_rel[packet_id] = time.time()
        self._mark_dirty()

    @owner_loop
    def pubrel(self, packet_id: int) -> None:
        if packet_id not in self.awaiting_rel:
            raise SessionError(RC_PACKET_IDENTIFIER_NOT_FOUND)
        del self.awaiting_rel[packet_id]
        self._mark_dirty()

    # -- outbound acks (client acks our deliveries) -----------------------

    @owner_loop
    def puback(self, packet_id: int) -> Message:
        val = self.inflight.lookup(packet_id)
        if val is None:
            raise SessionError(RC_PACKET_IDENTIFIER_NOT_FOUND)
        msg, _ts = val
        if msg == PUBREL_MARKER:
            raise SessionError(RC_PACKET_IDENTIFIER_IN_USE)
        self.inflight.delete(packet_id)
        self.dequeue()
        self._mark_dirty()
        return msg

    def discard_delivery(self, packet_id: int) -> None:
        """Release an inflight slot for a PUBLISH the transport could
        not legally send (client Maximum-Packet-Size, MQTT-3.1.2-24:
        the message is 'discarded but treated as acknowledged') —
        without this the slot leaks and the retry timer re-drops the
        same message forever."""
        if self.inflight.lookup(packet_id) is not None:
            self.inflight.delete(packet_id)
            self.dequeue()
            self._mark_dirty()

    @owner_loop
    def pubrec(self, packet_id: int) -> Message:
        val = self.inflight.lookup(packet_id)
        if val is None:
            raise SessionError(RC_PACKET_IDENTIFIER_NOT_FOUND)
        msg, _ts = val
        if msg == PUBREL_MARKER:
            raise SessionError(RC_PACKET_IDENTIFIER_IN_USE)
        self.inflight.update(packet_id, (PUBREL_MARKER, time.time()))
        self._mark_dirty()
        return msg

    @owner_loop
    def pubcomp(self, packet_id: int) -> None:
        val = self.inflight.lookup(packet_id)
        if val is None:
            raise SessionError(RC_PACKET_IDENTIFIER_NOT_FOUND)
        if val[0] != PUBREL_MARKER:
            raise SessionError(RC_PACKET_IDENTIFIER_IN_USE)
        self.inflight.delete(packet_id)
        self.dequeue()
        self._mark_dirty()

    # -- outbound delivery (broker -> client) -----------------------------

    def _mark_dirty(self) -> None:
        """QoS1/2 window / mqueue / awaiting-rel state changed: tell
        the durability layer this session needs a journal snapshot at
        the next batched flush (docs/DURABILITY.md — ONE state record
        per flush however many transitions happened, so the hot path
        pays an attribute test here and serialization off-loop)."""
        d = self._dur
        if d is not None:
            d.mark_dirty(self)

    @owner_loop
    def deliver(self, topic_filter: str, msg: Message) -> None:
        """Broker subscriber protocol: enrich, window, queue."""
        m = self._enrich(topic_filter, msg)
        if not self.connected:
            self.enqueue(m)
            self._mark_dirty()
            return
        self._deliver_msg(m)
        if m.qos != QOS_0:
            # QoS0 to a live connection is transient by contract
            # (recovery may lose it) — only window/queue state
            # journals
            self._mark_dirty()
        if self.outbox and self.notify is not None:
            self.notify()

    @owner_loop
    def deliver_many(self, items: Iterable[tuple]) -> None:
        """Batched broker→client delivery — the dispatch planner's
        grouped enqueue (docs/DISPATCH.md). Each item is
        ``(topic_filter, msg, opts, fast)``: the broker already
        resolved this session's subopts from its own table (the same
        SubOpts object ``subscriptions`` holds, so the per-delivery
        dict fetch is hoisted out), and ``fast`` pre-classifies the
        QoS0/plain-subopts broadcast fast path per (row, filter)
        group. Everything enqueues, then ONE notify fires for the
        whole group — the batch-wide wakeup coalescing that turns
        N-deliveries-per-batch into one flush per connection."""
        now = None  # one inflight timestamp per delivery group
        dirty = False
        for flt, msg, opts, fast in items:
            if fast and self.connected:
                # the _enrich fast path, pre-decided: nothing to
                # rewrite, every session shares the same object
                self.outbox.append((None, msg))
                continue
            m = msg if fast else self._enrich(flt, msg, opts)
            if not self.connected:
                self.enqueue(m)
                dirty = True
            else:
                if now is None:
                    now = time.time()
                self._deliver_msg(m, now)
                dirty = dirty or m.qos != QOS_0
        if dirty:
            # one mark per delivery group, not per message — the
            # durability flush then writes ONE state record per batch
            self._mark_dirty()
        if self.outbox and self.notify is not None:
            self.notify()

    def _enrich(self, topic_filter: str, msg: Message,
                opts: Optional[SubOpts] = None) -> Message:
        if opts is None:
            opts = self.subscriptions.get(topic_filter)
        if (opts is not None and msg.qos == 0
                and not msg.flags.get("retain")
                and opts.share is None and not opts.nl
                and opts.subid is None
                and (opts.qos == 0 or not self.upgrade_qos)):
            # broadcast fast path: a QoS0, non-retained delivery with
            # plain subopts has NOTHING to rewrite — every session
            # shares the SAME message object (and its cached wire
            # image, see Broker._deliver_one); downstream treats it
            # as immutable
            return msg
        # look up the shared form too: the session keys by full
        # filter string; the reverse share-suffix map (maintained on
        # subscribe/unsubscribe) replaces the old linear scan over
        # every subscription
        if opts is None:
            key = self._share_keys.get(topic_filter)
            if key is not None:
                opts = self.subscriptions.get(key)
        m = Message(
            topic=msg.topic, payload=msg.payload, qos=msg.qos,
            from_=msg.from_, flags=dict(msg.flags),
            headers=dict(msg.headers), id=msg.id, timestamp=msg.timestamp)
        if opts is None:
            return m
        if self.upgrade_qos:
            m.qos = max(opts.qos, m.qos)
        else:
            m.qos = min(opts.qos, m.qos)
        if opts.nl:
            m.set_flag("nl")
        if not opts.rap and not m.get_header("retained", False):
            m.set_flag("retain", False)
        if opts.subid is not None:
            props = dict(m.get_header("properties") or {})
            props["Subscription-Identifier"] = opts.subid
            m.set_header("properties", props)
        if opts.share:
            # mark for group redispatch if this session dies before
            # acking (emqx_shared_sub redispatch protocol). The
            # *pre-enrichment* message rides along: redispatch must
            # hand the survivor the original, not this copy with our
            # subid/downgraded qos baked in
            m.set_header("shared", (opts.share, topic_filter, msg))
            if m.get_header("redispatch") and m.qos > 0:
                # retransmission of a possibly-seen message — DUP only
                # at QoS>0 after OUR downgrade (MQTT-3.3.1-2)
                m.set_flag("dup", True)
        return m

    def _deliver_msg(self, msg: Message,
                     now: Optional[float] = None) -> None:
        if msg.qos == QOS_0:
            self.outbox.append((None, msg))
            return
        if self.inflight.is_full():
            self.enqueue(msg)
            return
        pid = self._next_pkt_id()
        self.inflight.insert(
            pid, (msg, time.time() if now is None else now))
        self.outbox.append((pid, msg))

    @owner_loop
    def enqueue(self, msg: Message) -> None:
        if msg.qos == QOS_0 and self.broker is not None:
            ov = getattr(self.broker, "overload", None)
            if ov is not None and ov.shed_qos0(len(self.mqueue),
                                               self.mqueue.max_len):
                # overload shedding (warn+): QoS0 has no redelivery
                # contract — drop it at mqueue pressure so the
                # remaining queue capacity serves QoS>0
                self.broker.metrics.inc("delivery.dropped")
                self.broker.metrics.inc("overload.shed.qos0")
                return
        dropped = self.mqueue.push(msg)
        if dropped is not None and self.broker is not None:
            self.broker.metrics.inc("delivery.dropped")
            if msg.qos == QOS_0 and not self.mqueue.store_qos0:
                self.broker.metrics.inc("delivery.dropped.qos0_msg")
            else:
                self.broker.metrics.inc("delivery.dropped.queue_full")

    @owner_loop
    def dequeue(self) -> None:
        """Move queued messages into the freed inflight window
        (emqx_session:dequeue/1 :389-409)."""
        while not self.mqueue.is_empty() and not self.inflight.is_full():
            msg = self.mqueue.pop()
            if msg is None:
                break
            if msg.is_expired():
                if self.broker is not None:
                    self.broker.metrics.inc("delivery.dropped")
                    self.broker.metrics.inc("delivery.dropped.expired")
                continue
            self._deliver_msg(msg)

    def _next_pkt_id(self) -> int:
        # skip ids still awaited (wrap-around safety; reference wraps
        # at 0xFFFF and relies on window < 65535)
        for _ in range(0x10000):
            pid = self.next_pkt_id
            self.next_pkt_id = 1 if pid == 0xFFFF else pid + 1
            if pid not in self.inflight:
                return pid
        raise SessionError(RC_QUOTA_EXCEEDED)

    # -- timers -----------------------------------------------------------

    @owner_loop
    def retry(self, now: Optional[float] = None) -> float:
        """Re-send timed-out inflight entries (dup=true) / pubrels.
        Returns the next retry delay in seconds."""
        now = time.time() if now is None else now
        if self.inflight.is_empty():
            return self.retry_interval
        items = self.inflight.to_list(sort_key=lambda kv: kv[1][1])
        next_delay = self.retry_interval
        for pid, (msg, ts) in items:
            age = now - ts
            if age < self.retry_interval:
                next_delay = self.retry_interval - age
                break
            if msg == PUBREL_MARKER:
                self.inflight.update(pid, (PUBREL_MARKER, now))
                self.outbox.append((PUBREL_MARKER, pid))
            elif msg.is_expired():
                self.inflight.delete(pid)
                if self.broker is not None:
                    self.broker.metrics.inc("delivery.dropped")
                    self.broker.metrics.inc("delivery.dropped.expired")
            else:
                msg.set_flag("dup", True)
                self.inflight.update(pid, (msg, now))
                self.outbox.append((pid, msg))
        self._mark_dirty()  # retry stamped new timestamps/dup flags
        return next_delay

    def expire_awaiting_rel(self, now: Optional[float] = None) -> None:
        now = time.time() if now is None else now
        expired = [pid for pid, ts in self.awaiting_rel.items()
                   if now - ts >= self.await_rel_timeout]
        for pid in expired:
            del self.awaiting_rel[pid]
        if expired and self.broker is not None:
            self.broker.metrics.inc("messages.dropped", len(expired))
            self.broker.metrics.inc("messages.dropped.expired", len(expired))

    # -- takeover / resume / replay (emqx_session:606-629) ----------------

    @owner_loop
    def take_shared_pending(self) -> List[Tuple[str, str, Message, bool]]:
        """Drain unacked/queued shared-group messages for redispatch
        when this session terminates: [(group, topic, original_msg,
        was_transmitted)]. QoS2 messages already PUBREC'd
        (PUBREL_MARKER) are past the point of redispatch, matching the
        reference's ack protocol."""
        out: List[Tuple[str, str, Message, bool]] = []
        for _pid, val in self.inflight.to_list():
            msg = val[0]
            if msg == PUBREL_MARKER or not isinstance(msg, Message):
                continue
            sh = msg.get_header("shared")
            if sh and not msg.is_expired():
                out.append((sh[0], sh[1], sh[2], True))
        kept: List[Message] = []
        while not self.mqueue.is_empty():
            msg = self.mqueue.pop()
            if msg is None:
                break
            sh = msg.get_header("shared")
            if sh:
                if not msg.is_expired():
                    out.append((sh[0], sh[1], sh[2], False))
                # expired shared messages drop here — they must not
                # re-occupy queue capacity in a handed-over session
            else:
                kept.append(msg)  # non-shared queued messages stay:
                # the session may be handed over, not destroyed
        for m in kept:
            self.mqueue.push(m)
        return out

    def takeover(self) -> None:
        """Old owner: detach from the broker, keep state for handoff."""
        if self.broker is not None:
            for topic_filter in self.subscriptions:
                self.broker.unsubscribe(self, topic_filter)

    def resume(self, broker) -> None:
        """New owner: reattach subscriptions to the (possibly new)
        broker."""
        self.broker = broker
        self.connected = True
        for topic_filter, opts in self.subscriptions.items():
            broker.subscribe(self, topic_filter, opts)
        if broker is not None:
            broker.metrics.inc("session.resumed")
            broker.hooks.run("session.resumed", (self.client_id, self.info()))

    @owner_loop
    def replay(self) -> None:
        """Re-emit all inflight entries (dup) then drain the queue."""
        for pid, (msg, _ts) in self.inflight.to_list(
                sort_key=lambda kv: kv[0]):
            if msg == PUBREL_MARKER:
                self.outbox.append((PUBREL_MARKER, pid))
            else:
                msg.set_flag("dup", True)
                self.outbox.append((pid, msg))
        self.dequeue()

    @owner_loop
    def drain_outbox(self) -> List[Tuple[Any, Any]]:
        out, self.outbox = self.outbox, []
        return out
