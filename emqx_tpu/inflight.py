"""QoS1/2 in-flight window, insertion-keyed by packet id.

Mirrors ``src/emqx_inflight.erl`` (gb_trees + max-size bound):
insert/update/delete/lookup plus the size/full tests the session's
delivery window logic depends on. ``max_size == 0`` means unbounded.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple


class KeyExists(KeyError):
    pass


class Inflight:
    def __init__(self, max_size: int = 32) -> None:
        self.max_size = max_size
        self._d: Dict[int, Any] = {}

    def __len__(self) -> int:
        return len(self._d)

    def __contains__(self, key: int) -> bool:
        return key in self._d

    def is_empty(self) -> bool:
        return not self._d

    def is_full(self) -> bool:
        return self.max_size != 0 and len(self._d) >= self.max_size

    def insert(self, key: int, value: Any) -> None:
        if key in self._d:
            raise KeyExists(key)
        self._d[key] = value

    def update(self, key: int, value: Any) -> None:
        if key not in self._d:
            raise KeyError(key)
        self._d[key] = value

    def delete(self, key: int) -> None:
        del self._d[key]

    def lookup(self, key: int) -> Optional[Any]:
        return self._d.get(key)

    def to_list(self, sort_key=None) -> List[Tuple[int, Any]]:
        items = list(self._d.items())
        if sort_key is not None:
            items.sort(key=sort_key)
        return items

    def keys(self) -> List[int]:
        return list(self._d)

    def window(self) -> List[int]:
        return self.keys()

    # -- serialization (session to_wire / durability checkpoints) ---------

    def restore(self, items: List[Tuple[int, Any]]) -> None:
        """Refill from :meth:`to_list` output (onto an empty window;
        insertion order preserved so retry/replay scan order
        survives a restart)."""
        for key, value in items:
            self.insert(key, value)
