"""Keepalive by byte-counter delta (reference: src/emqx_keepalive.erl).

The check passes if any bytes arrived since the last check; a
connection idle for a full interval is dead."""

from __future__ import annotations


class Keepalive:
    def __init__(self, interval: float, backoff: float = 0.75) -> None:
        # MQTT spec: server closes after 1.5x the keepalive interval;
        # the reference checks at interval with a byte-delta (backoff
        # applied by the caller when scheduling)
        self.interval = interval
        self.backoff = backoff
        self.last_bytes = 0

    def check_interval(self) -> float:
        return self.interval * 1.5

    def check(self, recv_bytes: int) -> bool:
        """True = alive (progress since last check)."""
        ok = recv_bytes != self.last_bytes
        self.last_bytes = recv_bytes
        return ok
