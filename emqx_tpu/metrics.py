"""Broker metrics: fixed-index counter array + named registry.

Mirrors ``src/emqx_metrics.erl``: a lock-free counters array indexed
by a name registry (emqx_metrics.erl:230-271) with the standard
BYTES/PACKETS/MESSAGES/DELIVERY metric names pre-registered
(emqx_metrics.erl:82-183). Host counters are a flat int list
(single-writer per-process); the device publish step additionally
accumulates per-batch counts on-TPU and folds them in with one
transfer per flush (the reference's pdict-batched counter idea,
src/emqx_pd.erl).
"""

from __future__ import annotations

from typing import Dict, List

from emqx_tpu.concurrency import any_thread, owner_loop, shared_state

MAX_METRICS = 1024

# Pre-registered names (counter kind), reference emqx_metrics.erl:82-183
BYTES_METRICS = ["bytes.received", "bytes.sent"]
PACKET_METRICS = [
    "packets.received", "packets.sent",
    "packets.connect.received", "packets.connack.sent",
    "packets.connack.error", "packets.connack.auth_error",
    "packets.publish.received", "packets.publish.sent",
    "packets.publish.error", "packets.publish.auth_error",
    "packets.publish.dropped",
    "packets.puback.received", "packets.puback.sent",
    "packets.puback.inuse", "packets.puback.missed",
    "packets.pubrec.received", "packets.pubrec.sent",
    "packets.pubrec.inuse", "packets.pubrec.missed",
    "packets.pubrel.received", "packets.pubrel.sent",
    "packets.pubrel.missed",
    "packets.pubcomp.received", "packets.pubcomp.sent",
    "packets.pubcomp.inuse", "packets.pubcomp.missed",
    "packets.subscribe.received", "packets.suback.sent",
    "packets.subscribe.error", "packets.subscribe.auth_error",
    "packets.unsubscribe.received", "packets.unsuback.sent",
    "packets.unsubscribe.error",
    "packets.pingreq.received", "packets.pingresp.sent",
    "packets.disconnect.received", "packets.disconnect.sent",
    "packets.auth.received", "packets.auth.sent",
]
MESSAGE_METRICS = [
    "messages.received", "messages.sent",
    "messages.qos0.received", "messages.qos0.sent",
    "messages.qos1.received", "messages.qos1.sent",
    "messages.qos2.received", "messages.qos2.sent",
    "messages.publish", "messages.dropped",
    "messages.dropped.expired", "messages.dropped.no_subscribers",
    "messages.forward", "messages.retained", "messages.redispatched",
    "messages.delayed", "messages.delivered", "messages.acked",
]
# will dispatch (Broker.publish_will, docs/DISPATCH.md "Will
# batching"): wills funneled through the ingress accumulator — a
# mass-disconnect wave coalesces into device batches — vs published
# directly (no accumulator running: sync drivers, shutdown tail)
WILL_METRICS = [
    "wills.batched", "wills.direct",
]
DELIVERY_METRICS = [
    "delivery.dropped", "delivery.dropped.no_local",
    "delivery.dropped.too_large", "delivery.dropped.qos0_msg",
    "delivery.dropped.queue_full", "delivery.dropped.expired",
    # connection flush wakeups actually scheduled (after
    # Connection._schedule_flush coalescing): with the dispatch
    # planner this is ≤1 per connection per batch — the bench's
    # wakeups/batch column divides it by ingress flushes
    "delivery.wakeups",
    # PUBLISH frames serialized ON the event loop (the per-delivery
    # slow path, plus template/image cache misses that build there).
    # With egress pre-serialization on (docs/DISPATCH.md) eligible
    # traffic patches pre-built frames instead, so this stays ~0 —
    # the bench's LIVE_PRESER A/B reads it per delivery
    "delivery.serialize.onloop",
    # cross-loop delivery ring (docs/DISPATCH.md "Multi-loop front
    # door"): handoffs posted to a session's owning event loop — at
    # most one per loop per batch — and the deliveries they carried.
    # Both stay 0 with [node] loops = 1
    "delivery.xloop.handoffs",
    "delivery.xloop.deliveries",
    # cross-loop deliveries/results LOST to a gone or wedged loop
    # (shutdown race, dead loop thread, join timeout): every
    # formerly-silent `home loop gone` path counts here, with one
    # warning log per batch (docs/ROBUSTNESS.md)
    "delivery.xloop.orphaned",
]
CLIENT_METRICS = [
    "client.connect", "client.connack", "client.connected",
    "client.authenticate", "client.check_acl", "client.subscribe",
    "client.unsubscribe", "client.disconnected",
]
SESSION_METRICS = [
    "session.created", "session.resumed", "session.takeovered",
    "session.discarded", "session.terminated",
]
AUTH_ACL_METRICS = [
    "client.auth.anonymous", "client.acl.cache_hit", "client.acl.deny",
]
# on-device accumulators (psum'd in the sharded publish step), folded
# into the host array by Metrics.fold_device_stats — the pdict-batched
# counter idea (src/emqx_pd.erl) applied across the PCIe boundary
DEVICE_METRICS = [
    "device.matches", "device.deliveries", "device.overflows",
]

# publish match cache (ops/match_cache.py): per-unique-topic hit/miss
# split counters, drained from the router by the stats flush (and
# thence into $SYS heartbeats + the Prometheus exposition). `stale`
# counts entries found but epoch-invalidated (route churn / rebuild).
# The `bump.*` pair splits epoch-bump traffic by invalidation scope
# (docs/MATCH_CACHE.md "Partitioned epochs"): `bump.partition` =
# literal-rooted filter mutations that invalidated one partition,
# `bump.global` = root-wildcard mutations / rebuilds / reclaims that
# invalidated everything — a churn-driven hit-rate collapse is
# diagnosable from this split alone (global racing ⇒ root-wildcard
# churn; partition racing with `stale` ⇒ literal churn colliding
# into hot partitions)
CACHE_METRICS = [
    "cache.match.hit", "cache.match.miss",
    "cache.match.insert", "cache.match.stale",
    "cache.match.bump.global", "cache.match.bump.partition",
]

TRANSPORT_METRICS = [
    # slow-consumer guard closes (zone send_timeout)
    "connections.closed.slow_consumer",
]

# online delta automaton + off-lock compaction (ops/delta.py,
# docs/DELTA.md), drained from the router by the stats flush:
# `delta.probes` = match batches that ran the two-probe walk,
# `delta.filters` = route adds absorbed by the side-automaton,
# `delta.merges` = compactions that folded a delta into the main
# tables, `rebuild.stall_ms` = cumulative milliseconds the router
# lock was held across compaction freeze/swap sections (the number
# the off-lock design keeps near zero — a multi-second value here
# means rebuilds are stalling route ops again)
AUTOMATON_METRICS = [
    "automaton.delta.probes", "automaton.delta.filters",
    "automaton.delta.merges", "automaton.rebuild.stall_ms",
    # level-compressed walk tables (ops/csr.py compress_automaton):
    # `compaction.chains` = compressed edges carrying a fused
    # single-child run, `compaction.fused_edges` = interior states
    # those runs absorbed — table-state snapshots carried as drain
    # deltas (GAUGE_METRICS: a rebuild may shrink them); 0/0 means
    # the live tables walk narrow (no deep chains worth fusing)
    "automaton.compaction.fused_edges", "automaton.compaction.chains",
]

# overload protection + self-healing (overload.py,
# docs/ROBUSTNESS.md): `shed.*` counts work refused under pressure
# (QoS0 at mqueue pressure, ServerBusy CONNACKs at critical, ingress
# publishers shed after the bounded submit wait), `force_shutdown`
# the per-connection OOM-policy kills, `transitions` the ok/warn/
# critical level changes, `heal.*` the supervision actions (fetch
# executor respawned, crashed flatten put on backoff-retry, dead
# front-door loop routed around), `takeover.timeout` the bounded
# cross-loop takeover waits that expired (the client got a fresh
# session instead of a hung CONNECT)
OVERLOAD_METRICS = [
    "overload.shed.qos0", "overload.shed.connect",
    "overload.shed.ingress_timeout", "overload.force_shutdown",
    "overload.transitions", "overload.heal.executor",
    "overload.heal.flatten", "overload.heal.loop",
    "overload.takeover.timeout",
]

# device-path circuit breaker (overload.DeviceBreaker): `failures` =
# device steps that failed (or exceeded breaker_slow_ms), `trips` =
# closed/half-open → open transitions, `probes` = half-open probe
# batches admitted, `fallback.batches` = publish batches matched on
# the exact host oracle because the breaker was open or rebuilding.
# Device-loss recovery (devloss.py): `rebuilds` = successful
# device-state reconstructions after a lost-backend classification
# (trie re-flattened straight to HBM, caches cold-started, kernels
# re-warmed), `rebuild.failures` = rebuild attempts that failed
# (backend still gone — retried with backoff)
BREAKER_METRICS = [
    "breaker.failures", "breaker.trips", "breaker.probes",
    "breaker.fallback.batches",
    "breaker.rebuilds", "breaker.rebuild.failures",
]

# fault injection (faults.py): total armed injection points that
# actually fired — 0 in any production configuration
FAULT_METRICS = [
    "faults.injected",
]

# zero-downtime operations (drain.py + reload.py,
# docs/OPERATIONS.md): `drain.rejected.connects` = CONNECTs refused
# with 0x9C Use-Another-Server while DRAINING, `drain.redirects` =
# live clients redirected by the paced waves, `drain.waves` /
# `drain.waves.deferred` = waves executed / held because the target
# reported critical overload, `drain.handoff.sessions` = persistent
# sessions whose custody moved to the drain target,
# `drain.handoff.errors` = hand-offs that failed or whose digest
# never settled inside the bound, `config.reload.applied` /
# `config.reload.rejected` = knobs applied by / boot-only knobs that
# rejected a `ctl reload`
OPS_METRICS = [
    "drain.rejected.connects", "drain.redirects", "drain.waves",
    "drain.waves.deferred", "drain.handoff.sessions",
    "drain.handoff.errors",
    "config.reload.applied", "config.reload.rejected",
]

# durability layer (wal.py + durability.py + replication.py,
# docs/DURABILITY.md): `wal.appends` = journal records framed,
# `wal.fsyncs` = batched write+sync cycles (one per shard per group
# commit with dirty state, NOT one per record — the fsync-batching
# contract), `wal.fsync_errors` = flushes that failed and degraded a
# shard to memory-only, `wal.degraded.dropped` = records shed by the
# memory-only degrade path's bounded drop-oldest buffers (per-shard
# AND the pre-recovery pending buffer — they used to vanish
# silently), `wal.group.commits`/`wal.group.coalesced` = leader
# group-commit passes / follower flushes that rode one,
# `checkpoint.saves`/`checkpoint.errors` = atomic generation commits
# and failed attempts, `checkpoint.delta.saves` = the subset that
# were incremental (differential) generations, `recovery.replayed` =
# journal records applied at boot, `recovery.torn` = journals
# truncated at a torn tail (a crash mid-append — expected, alarmed,
# never fatal), `recovery.sessions` = persistent sessions
# resurrected, `recovery.routes.pruned` = crash-dead clean-session
# route refs removed after restore. Replication (journal-shipped
# warm standby): `durability.repl.shipped`/`.acked` = records
# shipped to / acknowledged by the standby, `.ship_errors` = ship
# calls that failed (shipper drops to local-only), `.resyncs` = full
# snapshot re-syncs (first contact, gap repair, queue overflow),
# `.dropped` = queued-but-unshipped records discarded by the bounded
# ship queue (triggers a resync), `.promotions` = standby
# promotions executed after a primary death. Replication groups
# (multi-standby fan-out + quorum): `.quorum.waits` = group commits
# that blocked (bounded) for the ack quorum, `.quorum.timeouts` =
# waits that hit quorum_timeout_ms and degraded, `.failbacks` =
# completed FAILBACK hand-offs (either side), `.failback_errors` =
# hand-off attempts aborted by a transfer failure (the standby stays
# promoted and retries)
DURABILITY_METRICS = [
    "wal.appends", "wal.fsyncs", "wal.fsync_errors",
    "wal.degraded.dropped", "wal.group.commits",
    "wal.group.coalesced",
    "checkpoint.saves", "checkpoint.errors", "checkpoint.delta.saves",
    "recovery.replayed", "recovery.torn", "recovery.sessions",
    "recovery.routes.pruned",
    "durability.repl.shipped", "durability.repl.acked",
    "durability.repl.ship_errors", "durability.repl.resyncs",
    "durability.repl.dropped", "durability.repl.promotions",
    "durability.repl.quorum.waits", "durability.repl.quorum.timeouts",
    "durability.repl.failbacks", "durability.repl.failback_errors",
]

# cluster plane (cluster.py + cluster_net.py, docs/CLUSTER.md),
# folded from the per-node Cluster/transport event counters on the
# stats tick: `cluster.hb.*` = failure-detector transitions
# (ok→suspect, suspect→down, down→reappeared), `cluster.rpc.fastfail`
# = calls refused WITHOUT touching the wire because the detector held
# the peer suspect/down, `cluster.forward.dropped` = at-most-once
# data-plane casts shed (cast buffer full, or net.drop chaos) — the
# loss anti-entropy exists to repair, `cluster.heal.rejoins` =
# auto-heal handshakes completed, `cluster.ae.sweeps`/
# `cluster.ae.repairs` = anti-entropy rounds run / entries re-pushed,
# `cluster.locker.degraded` = lock quorums that proceeded without a
# suspect member's vote
CLUSTER_METRICS = [
    "cluster.hb.suspects", "cluster.hb.downs",
    "cluster.hb.reappears", "cluster.rpc.fastfail",
    "cluster.rpc.errors",
    "cluster.forward.dropped", "cluster.heal.rejoins",
    "cluster.ae.sweeps", "cluster.ae.repairs",
    "cluster.locker.degraded",
]

# sampled end-to-end tracing + slow-subscriber attribution
# (emqx_tpu/tracing.py, docs/OBSERVABILITY.md "Tracing"), folded on
# the stats tick: `tracing.spans` = span records drained from the
# per-loop rings, `tracing.dropped` = spans shed because a ring was
# full when its owner loop tried to record (the ring never blocks the
# hot path), `slow_subs.flushes` = flush spans folded into the
# slow-subscriber ranking, `slow_subs.breaches` = flushes whose
# delivery latency crossed slow_subs_threshold_ms
TRACING_METRICS = [
    "tracing.spans", "tracing.dropped",
    "slow_subs.flushes", "slow_subs.breaches",
]

# MQTT frame-parser engine (emqx_tpu/mqtt/frame.py NativeParser,
# docs/PERF_NOTES.md "Round 7"): `frame.native.frames` = MQTT frames
# decoded through the C++ incremental parser, `frame.fallback` =
# connections that asked for frame="native" but got the Python parser
# (shared library missing or built without the parser symbols),
# `frame.oversize` = frames rejected at header-decode time for
# exceeding the zone's max_packet_size (both engines; counted before
# the body is ever buffered)
FRAME_METRICS = [
    "frame.native.frames", "frame.fallback", "frame.oversize",
]

ALL_METRICS = (BYTES_METRICS + PACKET_METRICS + MESSAGE_METRICS
               + WILL_METRICS
               + DELIVERY_METRICS + CLIENT_METRICS + SESSION_METRICS
               + AUTH_ACL_METRICS + DEVICE_METRICS + CACHE_METRICS
               + AUTOMATON_METRICS + TRANSPORT_METRICS
               + OVERLOAD_METRICS + BREAKER_METRICS + FAULT_METRICS
               + OPS_METRICS + DURABILITY_METRICS + CLUSTER_METRICS
               + TRACING_METRICS + FRAME_METRICS)

#: registry names that are NOT monotonic — ``Metrics.dec`` runs on
#: them in steady state (today: the retainer's live-entry count,
#: modules/retainer.py). Prometheus semantics split on this: a
#: ``counter`` may only go up (scrapers compute rate() over it and
#: treat any decrease as a process restart), so the exposition
#: (modules/prometheus.render) must emit these as ``gauge``. Add any
#: new dec'd name here or its scraped rates turn to garbage.
GAUGE_METRICS = frozenset({
    "retained.count",
    "automaton.compaction.fused_edges",
    "automaton.compaction.chains",
})


@shared_state(lock="_lock", attrs=("_counters",))
class Metrics:
    def __init__(self) -> None:
        # a plain list, not numpy: scalar element updates are the
        # hottest metric op and a list add is ~3x cheaper than
        # numpy item assignment (single-writer per process, like
        # the reference's counters array)
        self._counters: List[int] = [0] * MAX_METRICS
        self._index: Dict[str, int] = {}
        # multi-loop front door ([node] loops > 1): counters are then
        # incremented from several event-loop threads, and the bare
        # read-modify-write below would lose updates under the GIL's
        # opcode-level interleaving. Node.start() arms the lock; the
        # single-loop build keeps the lock-free single-writer path
        self._lock = None
        for name in ALL_METRICS:
            self.new(name)

    def enable_threadsafe(self) -> None:
        """Arm the increment lock (multi-loop nodes). One-way: a
        started multi-loop node never goes back to single-writer."""
        if self._lock is None:
            import threading
            self._lock = threading.Lock()

    def new(self, name: str) -> int:
        idx = self._index.get(name)
        if idx is None:
            idx = len(self._index)
            if idx >= MAX_METRICS:
                raise RuntimeError("metric index overflow")
            self._index[name] = idx
        return idx

    @any_thread
    def inc(self, name: str, n: int = 1) -> None:
        lock = self._lock
        if lock is None:
            # lint: ok-CD102 single-writer fast path: the lock stays
            # None until Node.start arms multi-loop mode, and until
            # then every increment runs on the one event loop
            self._counters[self._index[name]] += n
        else:
            with lock:
                self._counters[self._index[name]] += n

    @any_thread
    def dec(self, name: str, n: int = 1) -> None:
        lock = self._lock
        if lock is None:
            # lint: ok-CD102 single-writer fast path, as in inc()
            self._counters[self._index[name]] -= n
        else:
            with lock:
                self._counters[self._index[name]] -= n

    def val(self, name: str) -> int:
        return int(self._counters[self._index[name]])

    def all(self) -> Dict[str, int]:
        return {n: int(self._counters[i]) for n, i in self._index.items()}

    def names(self) -> List[str]:
        return list(self._index)

    def inc_msg(self, msg) -> None:
        """Count an inbound message by QoS (emqx_metrics.erl qos_received)."""
        self.inc("messages.received")
        self.inc(_QOS_RECV[min(msg.qos, 2)])

    def inc_sent(self, msg) -> None:
        self.inc("messages.sent")
        self.inc(_QOS_SENT[min(msg.qos, 2)])

    @owner_loop
    def fold_device_stats(self, stats: Dict[str, int]) -> None:
        """Fold a drained device accumulator (matches/deliveries/
        overflows) into the host counters — one transfer per flush."""
        for key, val in stats.items():
            self.inc(f"device.{key}", int(val))

    def fold_cache_stats(self, stats: Dict[str, int]) -> None:
        """Fold drained match-cache counter deltas (hit/miss/insert/
        stale) into the host counters (Router.drain_cache_stats)."""
        for key, val in stats.items():
            self.inc(f"cache.match.{key}", int(val))

    def fold_automaton_stats(self, stats: Dict[str, int]) -> None:
        """Fold drained delta-automaton / rebuild counter deltas
        (Router.drain_automaton_stats)."""
        for key, val in stats.items():
            self.inc(f"automaton.{key}", int(val))

    @owner_loop
    def fold_cluster_stats(self, stats: Dict[str, int]) -> None:
        """Fold drained cluster-plane event counters
        (Cluster.drain_counters). Keys outside CLUSTER_METRICS are
        registered on first sight — the cluster/transport layers may
        grow event names without a registry edit here."""
        for key, val in stats.items():
            name = f"cluster.{key}"
            if name not in self._index:
                self.new(name)
            self.inc(name, int(val))


_QOS_RECV = ("messages.qos0.received", "messages.qos1.received",
             "messages.qos2.received")
_QOS_SENT = ("messages.qos0.sent", "messages.qos1.sent",
             "messages.qos2.sent")

_global = Metrics()


def global_metrics() -> Metrics:
    return _global
