"""emqx_tpu — a TPU-native publish/subscribe message-routing framework.

A ground-up re-design of the EMQ X 4.0 broker core (reference:
/root/reference, Erlang/OTP) for TPU hardware: the hot publish path —
wildcard topic matching and subscriber fan-out — runs as a compiled
JAX/XLA program over publish batches, with the subscription trie
flattened into a CSR state automaton in HBM and multi-chip operation
via jax.sharding meshes and XLA collectives.

Public API mirrors the reference's `emqx` facade (src/emqx.erl:26-64):
subscribe/unsubscribe/publish plus hook management.
"""

__version__ = "0.1.0"

from emqx_tpu import topic  # noqa: F401

__all__ = ["topic", "__version__"]
