"""emqx_tpu — a TPU-native publish/subscribe message-routing framework.

A ground-up re-design of the EMQ X 4.0 broker core (reference:
/root/reference, Erlang/OTP) for TPU hardware: the hot publish path —
wildcard topic matching and subscriber fan-out — runs as a compiled
JAX/XLA program over publish batches, with the subscription trie
flattened into a CSR state automaton in HBM and multi-chip operation
via jax.sharding meshes and XLA collectives.

Public API mirrors the reference's `emqx` facade (src/emqx.erl:26-64):
subscribe/unsubscribe/publish plus hook management.
"""

__version__ = "0.1.0"

from emqx_tpu import topic  # noqa: F401

import threading as _threading

_default_broker = None
_default_broker_lock = _threading.Lock()


def default_broker():
    """The process-default Broker, created on first use (the role of
    the running `emqx` application). Heavy imports (jax) happen here,
    not at package import. (Named default_broker, not broker: the
    ``emqx_tpu.broker`` SUBMODULE import rebinds a package attribute
    of that name.)"""
    global _default_broker
    if _default_broker is None:
        with _default_broker_lock:
            if _default_broker is None:  # double-checked: two racing
                # first calls must not each build a Broker and strand
                # one thread's subscriptions on the losing instance
                from emqx_tpu.broker import Broker
                _default_broker = Broker()
    return _default_broker


def subscribe(sub, topic_filter: str, opts=None):
    """emqx:subscribe (src/emqx.erl:26-64): ``sub`` needs a
    ``deliver(topic_filter, msg)`` method."""
    return default_broker().subscribe(sub, topic_filter, opts)


def unsubscribe(sub, topic_filter: str) -> bool:
    return default_broker().unsubscribe(sub, topic_filter)


def publish(msg) -> int:
    """emqx:publish — ``msg`` is an :class:`emqx_tpu.types.Message`;
    returns the local delivery count."""
    return default_broker().publish(msg)


def hook(name: str, fn, priority: int = 0):
    """emqx:hook — register on a hookpoint chain."""
    return default_broker().hooks.add(name, fn, priority=priority)


def unhook(name: str, fn) -> None:
    default_broker().hooks.delete(name, fn)


__all__ = ["topic", "default_broker", "subscribe",
           "unsubscribe", "publish",
           "hook", "unhook", "__version__"]
