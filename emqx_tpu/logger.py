"""Logging facade: per-connection metadata + the broker line format.

Mirrors ``src/emqx_logger.erl`` (set_metadata_clientid/peername —
stamped once per connection at src/emqx_connection.erl:232 and
src/emqx_channel.erl:1161-1162 so every later log line carries the
client context) and ``src/emqx_logger_formatter.erl`` (the
``date time level clientid@peername msg`` line format). asyncio tasks
share one process-wide logging module, so the metadata lives in a
:class:`contextvars.ContextVar` — each connection task sees its own
values, the way each BEAM process owns its logger metadata.
"""

from __future__ import annotations

import contextvars
import logging
from typing import Optional, Tuple

_metadata: contextvars.ContextVar[dict] = contextvars.ContextVar(
    "emqx_log_metadata", default={})


def set_metadata_clientid(clientid: str) -> None:
    md = dict(_metadata.get())
    md["clientid"] = clientid
    _metadata.set(md)


def set_metadata_peername(peername: Tuple[str, int]) -> None:
    md = dict(_metadata.get())
    md["peername"] = f"{peername[0]}:{peername[1]}"
    _metadata.set(md)


def get_metadata() -> dict:
    return _metadata.get()


def clear_metadata() -> None:
    _metadata.set({})


class MetadataFilter(logging.Filter):
    """Injects the context metadata onto every record passing through
    a handler (the role of OTP logger process metadata)."""

    def filter(self, record: logging.LogRecord) -> bool:
        md = _metadata.get()
        if "clientid" in md and not hasattr(record, "clientid"):
            record.clientid = md["clientid"]
        if "peername" in md and not hasattr(record, "peername"):
            record.peername = md["peername"]
        return True


class BrokerFormatter(logging.Formatter):
    """``date time [level] clientid@peername msg`` — the reference
    formatter's single-line template (emqx_logger_formatter default
    template, src/emqx_logger_formatter.erl)."""

    default_fmt = "%(asctime)s [%(levelname)s] %(client_tag)s%(message)s"

    def __init__(self) -> None:
        super().__init__(self.default_fmt)

    def format(self, record: logging.LogRecord) -> str:
        clientid = getattr(record, "clientid", None)
        peername = getattr(record, "peername", None)
        if clientid and peername:
            record.client_tag = f"{clientid}@{peername} "
        elif clientid:
            record.client_tag = f"{clientid} "
        else:
            record.client_tag = ""
        return super().format(record)


def set_level(level: int) -> None:
    """Runtime level change for the broker's logging (the ctl 'log
    set-level' backend): adjusts the package logger plus only the
    broker-OWNED handlers (BrokerFormatter — the same ownership test
    setup() uses for idempotence). Handlers an embedding app attached
    with a deliberately pinned level are never touched."""
    root = logging.getLogger("emqx_tpu")
    root.setLevel(level)
    for h in root.handlers:
        if isinstance(h.formatter, BrokerFormatter):
            h.setLevel(level)


def setup(level: int = logging.INFO,
          handler: Optional[logging.Handler] = None) -> logging.Handler:
    """Attach the broker formatter + metadata filter to the package
    logger (primary_log_level in the reference's logger config)."""
    root = logging.getLogger("emqx_tpu")
    root.setLevel(level)
    if handler is None:
        # idempotent: a second setup() reuses the existing default
        # handler instead of stacking one (duplicate log lines)
        for h in root.handlers:
            if isinstance(h.formatter, BrokerFormatter):
                h.setLevel(level)
                return h
        handler = logging.StreamHandler()
    else:
        # an explicit handler REPLACES prior broker handlers — a
        # second setup(handler=...) must not double every log line
        for h in list(root.handlers):
            if isinstance(h.formatter, BrokerFormatter):
                root.removeHandler(h)
    handler.addFilter(MetadataFilter())
    handler.setFormatter(BrokerFormatter())
    root.addHandler(handler)
    return handler
