"""Core record types shared across layers.

Mirrors the reference records in ``include/emqx.hrl``: ``#message{}``
(lines 57-76), ``#delivery{}`` (78-81), ``#route{}`` (87-90) and the
subscription options map of ``emqx_types`` (src/emqx_types.erl).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Dict, Optional

from emqx_tpu.utils.guid import new_guid

QOS_0 = 0
QOS_1 = 1
QOS_2 = 2


@dataclass
class Message:
    """A routable message (include/emqx.hrl:57-76)."""

    topic: str
    payload: bytes = b""
    qos: int = QOS_0
    from_: str = "undefined"          # publisher clientid
    flags: Dict[str, bool] = field(default_factory=dict)   # sys/dup/retain
    headers: Dict[str, Any] = field(default_factory=dict)  # proto_ver, props, ...
    id: int = field(default_factory=new_guid)
    timestamp: float = field(default_factory=time.time)

    def copy(self) -> "Message":
        """Shallow copy with independent flags/headers dicts — for per-wire
        mutation (unmount, expiry rewrite) without corrupting the
        inflight/mqueue-retained original."""
        return Message(
            topic=self.topic, payload=self.payload, qos=self.qos,
            from_=self.from_, flags=dict(self.flags),
            headers={k: (dict(v) if isinstance(v, dict) else v)
                     for k, v in self.headers.items()},
            id=self.id, timestamp=self.timestamp)

    def get_flag(self, name: str, default: bool = False) -> bool:
        return self.flags.get(name, default)

    def set_flag(self, name: str, val: bool = True) -> "Message":
        self.flags[name] = val
        return self

    def get_header(self, name: str, default=None):
        return self.headers.get(name, default)

    def set_header(self, name: str, val) -> "Message":
        self.headers[name] = val
        return self

    def is_sys(self) -> bool:
        return self.get_flag("sys") or self.topic.startswith("$SYS/")

    def is_expired(self) -> bool:
        interval = (self.headers.get("properties") or {}).get(
            "Message-Expiry-Interval")
        if interval is None:
            return False
        return time.time() - self.timestamp > interval

    def update_expiry(self) -> "Message":
        """Shrink Message-Expiry-Interval by elapsed time on delivery
        (reference emqx_message:update_expiry/1)."""
        props = self.headers.get("properties") or {}
        interval = props.get("Message-Expiry-Interval")
        if interval is not None:
            elapsed = max(0, int(time.time() - self.timestamp))
            props = dict(props)
            props["Message-Expiry-Interval"] = max(1, interval - elapsed)
            self.headers["properties"] = props
        return self


@dataclass
class Delivery:
    """A message en-route from a publisher (include/emqx.hrl:78-81)."""

    sender: str
    message: Message


@dataclass(frozen=True)
class Route:
    """topic filter → destination node or (group, node)
    (include/emqx.hrl:87-90)."""

    topic: str
    dest: Any = "local"


@dataclass
class SubOpts:
    """Subscription options (MQTT v5 + EMQX extensions)."""

    qos: int = QOS_0
    nl: int = 0            # no-local
    rap: int = 0           # retain-as-published
    rh: int = 0            # retain-handling
    share: Optional[str] = None
    subid: Optional[str] = None

    def to_dict(self) -> Dict[str, Any]:
        d = {"qos": self.qos, "nl": self.nl, "rap": self.rap, "rh": self.rh}
        if self.share is not None:
            d["share"] = self.share
        if self.subid is not None:
            d["subid"] = self.subid
        return d
