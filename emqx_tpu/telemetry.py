"""Publish-path pipeline telemetry: per-stage latency histograms,
per-batch span records, and the slow-publish log.

The reference broker attributes production latency with BEAM VM
introspection and system monitors (SURVEY §5 "Tracing/profiling",
``emqx_vm.erl``, long_gc/long_schedule); the TPU reproduction's
publish path is a *pipeline* — host pre-work → device walk /
match-cache gather → fan-out/pack dispatch → ONE coalesced transfer →
host delivery tail — so the equivalent question is "which STAGE did
this batch spend its time in". This module answers it with:

  - :class:`Histogram` — fixed log-spaced latency buckets (Prometheus
    ``_bucket``/``_sum``/``_count`` exposition) plus a ring buffer of
    raw samples for exact p50/p95/p99 over the recent window
    (single-writer, like :class:`~emqx_tpu.metrics.Metrics`);
  - :class:`PublishSpan` — one per :class:`~emqx_tpu.broker
    .PendingBatch`, stamped through ``publish_begin`` →
    ``publish_fetch`` → ``publish_finish`` (and the host / mesh /
    chunked-ingress variants), tagged with batch size, unique-topic
    count, cache hit/miss split, host-fallback count and the padding
    bucket;
  - :class:`Telemetry` — the per-node registry: folds finished spans
    into the stage histograms, keeps the last-N slow batches, emits
    the slow-publish log line (plus a tee through the
    :class:`~emqx_tpu.tracer.Tracer`) and drives the sustained-breach
    :class:`~emqx_tpu.alarm.AlarmManager` alarm.

Stage semantics (all host wall-clock, milliseconds):

  ``match``          async dispatch of the NFA walk (device regime:
                     encode + enqueue, NOT device execution — that
                     surfaces in ``fetch``); host regime: the actual
                     trie walk.
  ``cache_gather``   match-cache probe + HBM-row merge dispatch
                     (cache-split batches only).
  ``pack``           fan-out + sparse-compaction kernel dispatch.
  ``fetch``          the ONE coalesced device→host transfer — the
                     only synchronizing stage, so queued device
                     execution time surfaces here. No NEW
                     ``block_until_ready`` is introduced anywhere:
                     spans only read the clock at boundaries the
                     pipeline already crosses.
  ``dispatch_plan``  the batch dispatch planner's numpy grouping pass
                     (ops/dispatch_plan.py): CSR/bitmap expansion +
                     subscriber argsort over the fetched packed
                     arrays. Runs right after the transfer, on the
                     same (possibly executor) thread — recorded
                     separately so planner cost is attributable
                     against the dispatch time it saves. Zero when
                     the planner is off or the batch fell back.
  ``serialize``      the egress pre-serialization pass
                     (ops/dispatch_plan.preserialize_plan): QoS0
                     shared wire images + QoS1/2 pid-placeholder
                     templates built per (message, variant) right
                     after the plan, on the same (possibly executor)
                     thread — the serialize work the delivery tail no
                     longer pays on-loop. Zero when ``[dispatch]
                     preserialize = false`` or the batch didn't plan.
  ``host_fallback``  overflow topics re-matched on the host oracle
                     during the delivery tail (a subset of
                     ``dispatch`` time, recorded separately so
                     fallback cost is attributable).
  ``dispatch``       the host delivery tail (packed-row expansion +
                     session ``deliver`` calls), summed over chunks.
  ``xloop``          the cross-loop delivery ring (docs/DISPATCH.md
                     "Multi-loop front door"): handoff post → last
                     owning loop's group enqueue complete. Overlaps
                     ``dispatch`` (the main loop delivers its own
                     groups while peer loops run theirs); zero with
                     ``[node] loops = 1``.
  ``end_to_end``     ``publish_begin`` entry → last delivery chunk.

Cost model: disabled (``[telemetry] enabled = false``) the broker
takes one predicate branch per batch and records nothing — the
dispatch byte-stream is identical to the un-instrumented path (pinned
by tests/test_telemetry.py). Enabled, the cost is a handful of
``perf_counter`` reads per batch (not per message).
"""

from __future__ import annotations

import dataclasses
import json
import logging
import threading
import time
from collections import deque
from typing import Dict, List, Optional

log = logging.getLogger("emqx_tpu.telemetry")

#: guards direct (cross-thread) stage observes — see
#: :meth:`Telemetry.observe_stage`; span folds stay lock-free
#: (single-writer on the event loop)
_observe_lock = threading.Lock()

#: the publish pipeline's stage names, in pipeline order (ctl and the
#: $SYS heartbeat render in this order; Prometheus sorts its own).
#: ``rebuild`` is the one non-span stage: automaton compaction /
#: re-flatten durations (inline and background), observed directly
#: via :meth:`Telemetry.observe_stage` — it shares the histogram
#: surfaces so a churn-driven rebuild storm shows up next to the
#: publish latencies it would otherwise silently explain
STAGES = ("match", "cache_gather", "pack", "fetch", "dispatch_plan",
          "serialize", "host_fallback", "dispatch", "xloop",
          "rebuild", "end_to_end")

#: fixed log-spaced bucket upper bounds, milliseconds (1-2.5-5 per
#: decade, 10µs..5s). Fixed — not adaptive — so scrapes from
#: different nodes/epochs aggregate; the raw-sample ring carries the
#: exact percentiles the coarse buckets can't.
BUCKETS_MS = (0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
              10.0, 25.0, 50.0, 100.0, 250.0, 500.0, 1000.0,
              2500.0, 5000.0)

_now = time.perf_counter


@dataclasses.dataclass
class TelemetryConfig:
    """``[telemetry]`` TOML section (emqx_tpu/config.py). Unknown
    keys are startup errors — same closed-schema rule as zones."""

    enabled: bool = True
    #: end-to-end batch latency past this emits one slow-publish log
    #: line (and counts toward the sustained-breach alarm)
    slow_threshold_ms: float = 100.0
    #: per-stage raw-sample ring size (exact p50/p99 window)
    ring_size: int = 2048
    #: how many slow-batch records ``ctl telemetry slow`` keeps
    slow_log_size: int = 64
    #: consecutive slow batches before the AlarmManager alarm fires
    #: (one slow batch is a blip; a streak is a regime)
    slow_alarm_after: int = 10

    #: live-reloadable knobs (emqx_tpu/reload.py): read per span;
    #: ``enabled``/``ring_size``/``slow_log_size`` shape the
    #: histograms and the slow-record ring at build (not a dataclass
    #: field: unannotated)
    RELOADABLE = frozenset({"slow_threshold_ms", "slow_alarm_after"})


class Histogram:
    """One latency family: fixed log-bucket counts + sum/count for
    the Prometheus exposition, and a bounded ring of raw samples for
    exact recent percentiles. Single-writer (the event loop folds
    finished spans); plain ints/floats, no locks — same discipline as
    the Metrics counter array."""

    __slots__ = ("bounds", "counts", "sum", "count", "ring")

    def __init__(self, ring_size: int = 2048,
                 bounds=BUCKETS_MS) -> None:
        self.bounds = bounds
        self.counts = [0] * len(bounds)  # per-bucket (non-cumulative)
        self.sum = 0.0
        self.count = 0
        self.ring: deque = deque(maxlen=max(1, ring_size))

    def observe(self, ms: float) -> None:
        # linear scan beats bisect at 18 buckets, and the common case
        # (sub-ms stages) exits in the first few probes
        for i, b in enumerate(self.bounds):
            if ms <= b:
                self.counts[i] += 1
                break
        self.sum += ms
        self.count += 1
        self.ring.append(ms)

    def percentile(self, q: float) -> float:
        """Exact percentile over the raw-sample ring (0 when empty)."""
        if not self.ring:
            return 0.0
        xs = sorted(self.ring)
        # nearest-rank on the sorted window — matches numpy's
        # 'lower' interpolation within one sample
        idx = min(len(xs) - 1, int(q / 100.0 * len(xs)))
        return xs[idx]

    def snapshot(self) -> dict:
        """Prometheus-shaped view: CUMULATIVE ``(le, count)`` pairs
        (``+Inf`` is implicit — it equals ``count``), plus sum/count."""
        cum = []
        acc = 0
        for b, c in zip(self.bounds, self.counts):
            acc += c
            cum.append((b, acc))
        return {"buckets": cum, "sum": self.sum, "count": self.count}

    def stats(self) -> dict:
        return {
            "count": self.count,
            "p50_ms": self.percentile(50),
            "p95_ms": self.percentile(95),
            "p99_ms": self.percentile(99),
            "sum_ms": self.sum,
        }

    def reset(self) -> None:
        self.counts = [0] * len(self.bounds)
        self.sum = 0.0
        self.count = 0
        self.ring.clear()


class PublishSpan:
    """Per-batch stage stopwatch + tags. Created by
    :meth:`Telemetry.begin`, carried on ``PendingBatch.span``, closed
    by :meth:`Telemetry.finish` when the last delivery chunk lands.

    Writers hand off in pipeline order (begin on the event loop,
    fetch possibly on an executor thread, finish back on the loop) —
    the ingress pipeline sequences those with happens-before edges,
    so no stage field is ever written concurrently."""

    __slots__ = ("t0", "stages", "batch", "n_uniq", "bucket", "path",
                 "cache_hit", "cache_miss", "fallbacks", "topic",
                 "closed")

    def __init__(self, batch: int) -> None:
        self.t0 = _now()
        self.stages: Dict[str, float] = {}
        self.batch = batch
        self.n_uniq = 0
        self.bucket = 0          # device padding bucket (0 = host)
        self.path = "device"     # device | host | mesh
        self.cache_hit = -1      # -1 = batch wasn't cache-split
        self.cache_miss = -1
        self.fallbacks = 0
        self.topic: Optional[str] = None  # sample (tracer tee)
        self.closed = False

    @staticmethod
    def clock() -> float:
        return _now()

    def add(self, stage: str, t_start: float) -> None:
        """Accumulate ``now - t_start`` into a stage (chunked stages
        call this once per chunk)."""
        self.add_ms(stage, (_now() - t_start) * 1000.0)

    def add_ms(self, stage: str, ms: float) -> None:
        self.stages[stage] = self.stages.get(stage, 0.0) + ms

    def stamp_match(self, router, t_start: float) -> None:
        """Close the match-dispatch stage, splitting out the
        cache-gather share when the router's cache-split path left
        its per-dispatch info (set only while telemetry is enabled —
        see Router._match_dispatch_cached)."""
        total = (_now() - t_start) * 1000.0
        info = router._last_dispatch
        if info is not None:
            router._last_dispatch = None
            self.cache_hit = info["hit"]
            self.cache_miss = info["miss"]
            gather = min(total, info["cache_gather_ms"])
            self.add_ms("cache_gather", gather)
            self.add_ms("match", total - gather)
        else:
            self.add_ms("match", total)

    def record(self) -> dict:
        """The structured form (slow log / ctl telemetry slow)."""
        rec = {
            "batch": self.batch,
            "n_uniq": self.n_uniq,
            "path": self.path,
            "bucket": self.bucket,
            "fallbacks": self.fallbacks,
            "stages_ms": {k: round(v, 3)
                          for k, v in self.stages.items()},
        }
        if self.cache_hit >= 0:
            rec["cache_hit"] = self.cache_hit
            rec["cache_miss"] = self.cache_miss
        if self.topic is not None:
            rec["topic"] = self.topic
        return rec


class Telemetry:
    """Per-node telemetry registry (wired by Node onto broker +
    router + sys/ctl). Histogram folds and the slow ring are
    single-writer — finished spans land on the event loop, the same
    place the Metrics counters mutate."""

    def __init__(self, config: Optional[TelemetryConfig] = None,
                 tracer=None, alarms=None,
                 node: str = "local") -> None:
        self.config = config or TelemetryConfig()
        self.tracer = tracer
        self.alarms = alarms
        self.node = node
        self.hists: Dict[str, Histogram] = {
            s: Histogram(self.config.ring_size) for s in STAGES}
        self.spans_total = 0
        self.slow_total = 0
        self._slow_streak = 0
        self._slow_ring: deque = deque(
            maxlen=max(1, self.config.slow_log_size))

    @property
    def enabled(self) -> bool:
        return self.config.enabled

    # -- span lifecycle ---------------------------------------------------

    def begin(self, batch: int) -> Optional[PublishSpan]:
        """A new span, or None when disabled (the broker stores the
        None and every instrumented section reduces to one ``is not
        None`` branch — the near-zero disabled cost)."""
        if not self.config.enabled:
            return None
        return PublishSpan(batch)

    def finish(self, span: PublishSpan) -> None:
        """Fold a finished span into the stage histograms; slow-log /
        alarm on threshold breach. Idempotent (the chunked delivery
        tail and the one-shot finish can both reach the end)."""
        if span.closed:
            return
        span.closed = True
        e2e = (_now() - span.t0) * 1000.0
        span.stages["end_to_end"] = e2e
        for stage, ms in span.stages.items():
            h = self.hists.get(stage)
            if h is not None:
                h.observe(ms)
        self.spans_total += 1
        if e2e >= self.config.slow_threshold_ms:
            self._slow(span, e2e)
        else:
            self._slow_streak = 0
            if self.alarms is not None:
                self.alarms.deactivate("slow_publish")

    def _slow(self, span: PublishSpan, e2e: float) -> None:
        self.slow_total += 1
        self._slow_streak += 1
        rec = span.record()
        rec["end_to_end_ms"] = round(e2e, 3)
        rec["ts"] = time.time()
        self._slow_ring.append(rec)
        # ONE structured line per slow batch — a saturated broker must
        # not drown its own logs, and the ring keeps the rest
        log.warning("slow publish batch: %s", json.dumps(rec))
        if self.tracer is not None:
            self.tracer.trace_slow_publish(rec)
        if (self.alarms is not None
                and self._slow_streak >= self.config.slow_alarm_after):
            self.alarms.activate(
                "slow_publish",
                details={"streak": self._slow_streak,
                         "threshold_ms": self.config.slow_threshold_ms,
                         "last": rec},
                message=(f"publish end-to-end latency over "
                         f"{self.config.slow_threshold_ms}ms for "
                         f"{self._slow_streak} consecutive batches"))

    def observe_stage(self, stage: str, ms: float) -> None:
        """Record one direct (non-span) stage sample — the rebuild
        histogram's entry point. Unlike span folds this may be called
        from the background compaction thread, so it takes a small
        lock (rebuilds are rare and ms-scale; the cost is noise)."""
        if not self.config.enabled:
            return
        h = self.hists.get(stage)
        if h is None:
            return
        with _observe_lock:
            h.observe(ms)

    # -- read surfaces ----------------------------------------------------

    def stage_stats(self) -> Dict[str, dict]:
        """Per-stage count/p50/p95/p99 from the sample rings — the
        ctl table and the $SYS heartbeat both read this."""
        return {s: self.hists[s].stats() for s in STAGES}

    def histograms(self) -> Dict[str, dict]:
        """Prometheus families: ``emqx_tpu_publish_stage_<stage>_ms``
        → cumulative-bucket snapshots (modules/prometheus.render)."""
        return {f"emqx_tpu_publish_stage_{s}_ms": self.hists[s].snapshot()
                for s in STAGES}

    def slow_records(self) -> List[dict]:
        """The last-N slow batches, oldest first."""
        return list(self._slow_ring)

    def reset(self) -> None:
        for h in self.hists.values():
            h.reset()
        self.spans_total = 0
        self.slow_total = 0
        self._slow_streak = 0
        self._slow_ring.clear()
