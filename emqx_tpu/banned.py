"""Ban list: clientid / username / peerhost with expiry
(reference: src/emqx_banned.erl — Mnesia table + expiry timer)."""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Dict, Optional, Tuple


@dataclass
class BanRule:
    who: Tuple[str, str]           # ("clientid"|"username"|"peerhost", value)
    by: str = "admin"
    reason: str = ""
    at: float = field(default_factory=time.time)
    until: Optional[float] = None  # None = forever


class Banned:
    """Writers arrive from the serving loop (CLI/flapping), the
    housekeeping task, AND — with a socket cluster — the transport IO
    thread (replicated ban applies), so the table takes a lock; the
    check() hot path holds it only for dict probes."""

    def __init__(self) -> None:
        self._rules: Dict[Tuple[str, str], BanRule] = {}
        self._lock = threading.Lock()

    def create(self, kind: str, value: str, by: str = "admin",
               reason: str = "", duration: Optional[float] = None) -> BanRule:
        if kind not in ("clientid", "username", "peerhost"):
            raise ValueError(f"bad ban kind: {kind}")
        until = time.time() + duration if duration is not None else None
        rule = BanRule(who=(kind, value), by=by, reason=reason, until=until)
        with self._lock:
            self._rules[rule.who] = rule
        return rule

    @staticmethod
    def _outlasts(a: Optional[float], b: Optional[float]) -> bool:
        """Does expiry ``a`` last at least as long as ``b``?
        (None = forever.)"""
        return a is None or (b is not None and a >= b)

    def apply(self, kind: str, value: str, by: str, reason: str,
              until: Optional[float], overwrite: bool = False) -> None:
        """Install a replicated rule with an absolute expiry.

        ``overwrite=True`` is a LIVE create relayed from a peer: it
        replaces whatever is here, exactly as the originating node's
        own create() did — otherwise tables diverge (an operator
        shortening a ban must win everywhere). ``overwrite=False`` is
        a join-time table sync: longest-ban-wins merge, so a stale
        short ban from one member never clobbers another member's
        permanent rule."""
        if until is not None and time.time() > until:
            # expired in transit (broadcast delay / clock skew). An
            # overwrite must still take effect as a DELETE — the
            # originator's table expires the rule too; a no-op here
            # would leave this node holding the replaced rule forever.
            # Direct pop, NOT self.delete: on a clustered node that
            # attribute is the replicating wrapper, and a receive
            # path must never re-broadcast (ping-pong / concurrent-
            # create deletion)
            if overwrite:
                with self._lock:
                    self._rules.pop((kind, value), None)
            return
        with self._lock:
            cur = self._rules.get((kind, value))
            if not overwrite and cur is not None \
                    and self._outlasts(cur.until, until):
                return
            self._rules[(kind, value)] = BanRule(
                who=(kind, value), by=by, reason=reason, until=until)

    def create_unless_outlasted(self, kind: str, value: str,
                                by: str = "auto", reason: str = "",
                                duration: Optional[float] = None
                                ) -> Optional[BanRule]:
        """Atomic check-and-create for AUTO bans (flapping): installs
        only if no existing rule outlasts the new one — the compare
        must live under the table lock, or a permanent operator ban
        applied between a caller's look_up and create would still be
        overwritten (and the downgrade would replicate)."""
        until = time.time() + duration if duration is not None else None
        with self._lock:
            cur = self._rules.get((kind, value))
            if cur is not None and self._outlasts(cur.until, until):
                return None
            rule = BanRule(who=(kind, value), by=by, reason=reason,
                           until=until)
            self._rules[rule.who] = rule
        return rule

    def delete(self, kind: str, value: str) -> None:
        with self._lock:
            self._rules.pop((kind, value), None)

    def look_up(self, kind: str, value: str) -> Optional[BanRule]:
        with self._lock:
            return self._rules.get((kind, value))

    def check(self, clientid: str = "", username: Optional[str] = None,
              peerhost: str = "") -> bool:
        """True if any identity facet is banned (emqx_banned:check/1)."""
        now = time.time()
        for who in (("clientid", clientid), ("username", username or ""),
                    ("peerhost", peerhost)):
            with self._lock:
                rule = self._rules.get(who)
                if rule is not None and rule.until is not None \
                        and now > rule.until:
                    # lazy expiry — re-checked under the lock so a
                    # concurrent refreshed ban is never deleted
                    del self._rules[who]
                    rule = None
            if rule is not None:
                return True
        return False

    def expire(self, now: Optional[float] = None) -> int:
        now = time.time() if now is None else now
        with self._lock:
            dead = [w for w, r in self._rules.items()
                    if r.until is not None and now > r.until]
            for w in dead:
                del self._rules[w]
        return len(dead)

    def info(self) -> list:
        with self._lock:
            return list(self._rules.values())
