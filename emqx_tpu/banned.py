"""Ban list: clientid / username / peerhost with expiry
(reference: src/emqx_banned.erl — Mnesia table + expiry timer)."""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, Optional, Tuple


@dataclass
class BanRule:
    who: Tuple[str, str]           # ("clientid"|"username"|"peerhost", value)
    by: str = "admin"
    reason: str = ""
    at: float = field(default_factory=time.time)
    until: Optional[float] = None  # None = forever


class Banned:
    def __init__(self) -> None:
        self._rules: Dict[Tuple[str, str], BanRule] = {}

    def create(self, kind: str, value: str, by: str = "admin",
               reason: str = "", duration: Optional[float] = None) -> BanRule:
        if kind not in ("clientid", "username", "peerhost"):
            raise ValueError(f"bad ban kind: {kind}")
        until = time.time() + duration if duration is not None else None
        rule = BanRule(who=(kind, value), by=by, reason=reason, until=until)
        self._rules[rule.who] = rule
        return rule

    def delete(self, kind: str, value: str) -> None:
        self._rules.pop((kind, value), None)

    def look_up(self, kind: str, value: str) -> Optional[BanRule]:
        return self._rules.get((kind, value))

    def check(self, clientid: str = "", username: Optional[str] = None,
              peerhost: str = "") -> bool:
        """True if any identity facet is banned (emqx_banned:check/1)."""
        now = time.time()
        for who in (("clientid", clientid), ("username", username or ""),
                    ("peerhost", peerhost)):
            rule = self._rules.get(who)
            if rule is not None:
                if rule.until is not None and now > rule.until:
                    del self._rules[who]  # lazy expiry
                    continue
                return True
        return False

    def expire(self, now: Optional[float] = None) -> int:
        now = time.time() if now is None else now
        dead = [w for w, r in self._rules.items()
                if r.until is not None and now > r.until]
        for w in dead:
            del self._rules[w]
        return len(dead)

    def info(self) -> list:
        return list(self._rules.values())
