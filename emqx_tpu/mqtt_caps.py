"""Per-zone MQTT capability checks.

Mirrors ``src/emqx_mqtt_caps.erl`` (check_pub/2, check_sub/3,
get_caps/1): a publish or subscribe is vetted against the listener
zone's advertised limits before it touches the session/broker. The
checks return an MQTT v5 reason code on violation, ``None`` when the
operation is within caps.
"""

from __future__ import annotations

from typing import Dict, Optional

from emqx_tpu import topic as T
from emqx_tpu.mqtt import reason_codes as RC
from emqx_tpu.zone import Zone

# check_pub codes that count as a dropped publish (vs a malformed one)
PUB_DROP_CODES = frozenset({RC.QOS_NOT_SUPPORTED, RC.RETAIN_NOT_SUPPORTED})

DEFAULT_CAPS_KEYS = (
    "max_packet_size", "max_clientid_len", "max_topic_alias",
    "max_topic_levels", "max_qos_allowed", "retain_available",
    "wildcard_subscription", "shared_subscription",
)


def check_pub(zone: Zone, qos: int, retain: bool,
              topic: str) -> Optional[int]:
    """Vet a PUBLISH against zone caps (emqx_mqtt_caps:check_pub/2)."""
    if qos > zone.max_qos_allowed:
        return RC.QOS_NOT_SUPPORTED
    if retain and not zone.retain_available:
        return RC.RETAIN_NOT_SUPPORTED
    if zone.max_topic_levels and T.levels(topic) > zone.max_topic_levels:
        return RC.TOPIC_NAME_INVALID
    return None


def check_sub(zone: Zone, bare: str,
              popts: Dict[str, str]) -> Optional[int]:
    """Vet one SUBSCRIBE filter against zone caps
    (emqx_mqtt_caps:check_sub/3). ``bare`` is the filter with any
    ``$share/<group>/`` prefix stripped; ``popts`` carries the parsed
    share group if present."""
    if "share" in popts and not zone.shared_subscription:
        return RC.SHARED_SUBSCRIPTIONS_NOT_SUPPORTED
    if T.wildcard(bare) and not zone.wildcard_subscription:
        return RC.WILDCARD_SUBSCRIPTIONS_NOT_SUPPORTED
    if zone.max_topic_levels and T.levels(bare) > zone.max_topic_levels:
        return RC.TOPIC_FILTER_INVALID
    return None


def get_caps(zone: Zone) -> Dict[str, object]:
    """Snapshot of the zone's advertised capabilities
    (emqx_mqtt_caps:get_caps/1) — what a CONNACK advertises."""
    return {k: getattr(zone, k) for k in DEFAULT_CAPS_KEYS}
