"""Write-ahead journal for the durability layer (docs/DURABILITY.md).

The reference broker's durability is Mnesia ram-replication plus
session takeover — a single node that dies takes its routes, retained
messages and persistent sessions with it unless a peer holds a
replica. This build runs the "millions of users" workload on ONE
device-backed node, so it needs what the reference never shipped
in-core: a crash-consistent local journal.

Design (the classic WAL contract, scoped to broker state):

  - **CRC-framed records.** Every record is
    ``magic(2B) | length(4B LE) | crc32(4B LE) | payload`` with the
    payload encoded by the cluster wire codec (:mod:`emqx_tpu.wire`
    — data-only, no pickle: a corrupt journal can produce garbage
    values but never code execution). Replay verifies magic, bounds
    and CRC per record and STOPS at the first torn/corrupt frame —
    a crash mid-append loses at most the unsynced tail, never the
    prefix, and never crashes the recovering node.
  - **Batched appends, batched fsync.** ``append`` only frames into
    an in-memory buffer; ``flush`` writes the whole buffer and pays
    ONE ``fsync`` for it. The broker calls ``flush`` from the
    ingress executor thread at batch granularity (plus a periodic
    timer for quiet periods), so the socket loops never wait on disk
    and the hot path pays one append per batch, not per op.
  - **Degrades, never wedges.** An fsync/write failure (disk full,
    dying volume) flips the journal into memory-only mode: appends
    keep buffering (bounded, drop-oldest with a counter), the
    ``wal_write_failed`` alarm raises, and a bounded exponential
    backoff retries the flush. Publishes never block on a broken
    disk — durability degrades to the pre-journal contract instead.

Record vocabulary (applied idempotently on replay — a doubly-replayed
record is a no-op; see DurabilityManager._apply):

  ``("route", filter, dest, refs)``      absolute refcount after the op
  ``("retain", topic, Message|None, ts)`` set / clear (None payload)
  ``("sess.state", cid, detached_ts|None, to_wire)``  full snapshot
  ``("sess.sub", cid, filter_key, SubOpts)``
  ``("sess.unsub", cid, filter_key)``
  ``("sess.close", cid)``

Fault points (docs/ROBUSTNESS.md): ``wal.append`` short-writes one
frame (torn tail) and degrades the writer; ``wal.fsync`` fails the
sync (the disk-full path). Both fire inside :meth:`Wal.flush`, so in
sharded mode they are naturally PER SHARD — one shard degrades or
tears while its siblings keep committing.

Sharding (:class:`WalGroup`, docs/DURABILITY.md "Sharded WAL"):
``[durability] wal_shards`` splits the journal into per-loop shards
(``journal-<shard>-<seq>.wal``). Every record is routed by a stable
KEY (the route filter, the retained topic, the session client-id), so
all records for one key live in one shard in true order — which is
what makes recovery's per-shard-ordered merge converge regardless of
how the shards interleave (absolute refcounts, full-state session
records, LWW retained). ``wal_shards = 1`` keeps the single
``journal-<seq>.wal`` byte-for-byte. Concurrent flushes (N front-door
loops + the timer + shutdown) coalesce through a leader-based GROUP
COMMIT: the first flusher becomes the leader, optionally sleeps the
``group_commit_window_ms`` window to pick up stragglers, and pays one
write+fsync pass per shard for everything buffered; followers wait on
the leader's commit instead of issuing their own.
"""

from __future__ import annotations

import binascii
import logging
import os
import struct
import threading
import time
from typing import Any, List, Optional, Tuple

from emqx_tpu import faults, wire
from emqx_tpu.concurrency import any_thread, shared_state

log = logging.getLogger("emqx_tpu.wal")

#: frame header: magic, payload length, payload crc32
MAGIC = 0xE17A
_HDR = struct.Struct("<HII")
#: refuse absurd lengths during replay — a corrupt length field must
#: not allocate gigabytes before the CRC check can reject it
MAX_RECORD = 64 << 20


class WalError(Exception):
    """Unrecoverable journal I/O error surfaced to the manager."""


def frame(payload: bytes) -> bytes:
    """One CRC-framed journal record."""
    return _HDR.pack(MAGIC, len(payload),
                     binascii.crc32(payload) & 0xFFFFFFFF) + payload


def encode_record(op: Tuple[Any, ...]) -> bytes:
    return frame(wire.dumps(op))


def iter_records(path: str):
    """Yield ``(offset, record_tuple)`` for every intact record, then
    a final ``(offset, None)`` sentinel carrying the clean-end offset.
    Stops (without raising) at the first torn or corrupt frame — the
    caller learns truncation happened when the sentinel offset is
    short of the file size."""
    try:
        size = os.path.getsize(path)
    except OSError:
        yield (0, None)
        return
    with open(path, "rb") as f:
        off = 0
        while True:
            hdr = f.read(_HDR.size)
            if len(hdr) < _HDR.size:
                break  # clean EOF or torn header
            magic, length, crc = _HDR.unpack(hdr)
            if magic != MAGIC or length > MAX_RECORD:
                break
            payload = f.read(length)
            if len(payload) < length:
                break  # torn payload
            if binascii.crc32(payload) & 0xFFFFFFFF != crc:
                break  # bit rot / interleaved short write
            try:
                rec = wire.loads(payload)
            except wire.WireError:
                break  # framed but undecodable — treat as torn
            off = f.tell()
            yield (off, rec)
        yield (off, None)
    # size consulted only for the caller's torn-tail report
    del size


def replay(path: str) -> Tuple[List[Tuple[Any, ...]], bool]:
    """Read every intact record; returns ``(records, torn)`` where
    ``torn`` is True when the file holds bytes past the last intact
    frame (a crash mid-append — expected, not an error)."""
    records: List[Tuple[Any, ...]] = []
    clean_end = 0
    for off, rec in iter_records(path):
        if rec is None:
            clean_end = off
        else:
            records.append(rec)
    try:
        torn = clean_end < os.path.getsize(path)
    except OSError:
        torn = False
    return records, torn


@shared_state(lock="_lock", attrs=("_buf",))
class Wal:
    """Appender half of the journal: one open segment file, an
    in-memory frame buffer, batched write+fsync, rotation, and the
    degrade-don't-wedge error path. Thread-safe (appends arrive from
    event-loop threads, flushes from the ingress executor)."""

    def __init__(self, path: str, fsync: bool = True,
                 max_buffer: int = 100_000,
                 retry_backoff_s: float = 1.0,
                 retry_backoff_max_s: float = 30.0,
                 on_error=None) -> None:
        self._lock = threading.Lock()
        self.path = path
        self.fsync = fsync
        self.max_buffer = max_buffer
        self._buf: List[bytes] = []
        self._f = open(path, "ab")
        #: intact records written to the CURRENT segment
        self.records = 0
        self.bytes = int(self._f.tell())
        self.appends_total = 0
        self.fsyncs = 0
        self.fsync_errors = 0
        self.dropped = 0
        self.flushes = 0
        self.last_fsync_ms = 0.0
        #: memory-only mode after a write/fsync failure; flush retries
        #: after the backoff deadline
        self.degraded = False
        self._retry_at = 0.0
        self._backoff = retry_backoff_s
        self._backoff0 = retry_backoff_s
        self._backoff_max = retry_backoff_max_s
        #: manager callback: on_error(exc | None) — exc on degrade,
        #: None when a later flush recovers (alarm raise/clear)
        self.on_error = on_error

    # -- append side ------------------------------------------------------

    @any_thread
    def append(self, op: Tuple[Any, ...]) -> None:
        """Frame + buffer one record (no I/O here — the hot path pays
        serialization only; disk happens in :meth:`flush`)."""
        rec = encode_record(op)
        with self._lock:
            self._buf.append(rec)
            self.appends_total += 1
            if len(self._buf) > self.max_buffer:
                # bounded memory in degraded mode: drop-oldest, count
                del self._buf[0]
                self.dropped += 1

    def pending(self) -> int:
        with self._lock:
            return len(self._buf)

    # -- flush side -------------------------------------------------------

    @any_thread
    def flush(self) -> bool:
        """Write + fsync everything buffered (ONE sync for the whole
        batch). Returns True when the buffer reached disk; False when
        nothing was pending or the journal is degraded and inside its
        retry backoff. Never raises — failures degrade."""
        with self._lock:
            if not self._buf:
                return False
            now = time.monotonic()
            if self.degraded and now < self._retry_at:
                return False
            batch, self._buf = self._buf, []
            try:
                wrote_bytes = 0
                for rec in batch:
                    if faults.enabled and faults.fire("wal.append"):
                        # injected short write: half a frame lands —
                        # the torn tail replay must truncate at — and
                        # the writer degrades like a real ENOSPC
                        self._f.write(rec[:max(1, len(rec) // 2)])
                        self._f.flush()
                        raise WalError("short write (injected)")
                    self._f.write(rec)
                    wrote_bytes += len(rec)
                self._f.flush()
                if faults.enabled:
                    faults.fire("wal.fsync")
                if self.fsync:
                    t0 = time.perf_counter()
                    os.fsync(self._f.fileno())
                    self.last_fsync_ms = (time.perf_counter() - t0) \
                        * 1000.0
                # counters commit only with the sync: a failed batch
                # re-buffers IN FULL and the retry rewrites it from
                # the pre-batch boundary — exactly-once on disk
                self.records += len(batch)
                self.bytes += wrote_bytes
                self.fsyncs += 1
                self.flushes += 1
                if self.degraded:
                    self.degraded = False
                    self._backoff = self._backoff0
                    if self.on_error is not None:
                        self.on_error(None)
                    log.warning("journal recovered: %s", self.path)
                return True
            except Exception as e:
                # the WHOLE batch goes back to the front (order
                # kept): nothing in it counts as durable until the
                # fsync lands
                self._buf[:0] = batch
                if not isinstance(e, WalError):
                    # a real partial write / failed sync leaves an
                    # unsynced (possibly torn) tail; truncate back to
                    # the last durable boundary so the retry rewrites
                    # cleanly and replay never loses post-recovery
                    # records behind a torn frame. The INJECTED short
                    # write skips this — it models a crash, and the
                    # torn tail is exactly what the recovery tests
                    # must see on disk.
                    try:
                        self._f.seek(self.bytes)
                        self._f.truncate(self.bytes)
                    except OSError:
                        pass
                self.fsync_errors += 1
                self.degraded = True
                self._retry_at = time.monotonic() + self._backoff
                self._backoff = min(self._backoff * 2,
                                    self._backoff_max)
                if self.on_error is not None:
                    self.on_error(e)
                log.error("journal write failed (%s): memory-only, "
                          "retry in %.1fs", e, self._backoff)
                return False

    def rotate(self, new_path: str) -> str:
        """Flush, then switch appends to a fresh segment (checkpoint
        commit protocol: the old segment stays on disk until the new
        manifest lands). Returns the OLD path."""
        self.flush()
        with self._lock:
            old = self.path
            try:
                self._f.close()
            except OSError:
                pass
            self.path = new_path
            self._f = open(new_path, "ab")
            self.records = 0
            self.bytes = int(self._f.tell())
            return old

    def close(self) -> None:
        self.flush()
        with self._lock:
            try:
                self._f.close()
            except OSError:
                pass

    def info(self) -> dict:
        with self._lock:
            return {
                "path": self.path,
                "records": self.records,
                "bytes": self.bytes,
                "pending": len(self._buf),
                "appends_total": self.appends_total,
                "fsyncs": self.fsyncs,
                "fsync_errors": self.fsync_errors,
                "dropped": self.dropped,
                "degraded": self.degraded,
                "last_fsync_ms": round(self.last_fsync_ms, 3),
            }


def shard_path(dirpath: str, shard: Optional[int], seq: int) -> str:
    """Segment file name: ``journal-<seq>.wal`` for the single-journal
    build (shard None), ``journal-<shard>-<seq>.wal`` for sharded
    mode — the legacy layout stays byte-for-byte when shards == 1."""
    if shard is None:
        return os.path.join(dirpath, f"journal-{seq}.wal")
    return os.path.join(dirpath, f"journal-{shard}-{seq}.wal")


def shard_of(key: str, n: int) -> int:
    """Stable key → shard assignment (the merge-rule anchor: every
    record for one key lands in one shard, in true order)."""
    if n <= 1:
        return 0
    return binascii.crc32(key.encode("utf-8", "surrogatepass")) % n


@shared_state(lock="_cv", attrs=("_req", "_done", "_leader",
                                 "_last_ok"))
class WalGroup:
    """``n`` per-loop WAL shards behind one appender/flush surface,
    with leader-based batched group commit.

    Appends route by key (:func:`shard_of`); flush runs the group-
    commit protocol: concurrent flushers elect the first as leader,
    the leader optionally sleeps ``group_window_ms`` to coalesce
    stragglers, then pays ONE write+fsync pass over the shards with
    pending records; followers block on the leader's commit covering
    their appends instead of issuing their own fsyncs. With
    ``shards == 1`` the on-disk layout (name, framing, rotation) is
    byte-for-byte the single-journal :class:`Wal` build.
    """

    def __init__(self, dirpath: str, seq: int, shards: int = 1,
                 fsync: bool = True, max_buffer: int = 100_000,
                 retry_backoff_s: float = 1.0,
                 retry_backoff_max_s: float = 30.0,
                 on_error=None,
                 group_window_ms: float = 0.0) -> None:
        if shards < 1:
            raise ValueError(f"wal shards must be >= 1, got {shards}")
        self.dir = dirpath
        self.n = shards
        self.seq = seq
        self.group_window_ms = group_window_ms
        #: manager alarm callback — the group arbitrates shard
        #: callbacks so a recovering shard can't clear the alarm
        #: while a sibling is still degraded
        self.on_error = on_error
        self.shards: List[Wal] = [
            Wal(shard_path(dirpath, i if shards > 1 else None, seq),
                fsync=fsync, max_buffer=max_buffer,
                retry_backoff_s=retry_backoff_s,
                retry_backoff_max_s=retry_backoff_max_s,
                on_error=self._shard_error)
            for i in range(shards)]
        # group-commit coordinator state (guarded by the condition)
        self._cv = threading.Condition()
        self._req = 0          # flush requests issued
        self._done = 0         # highest request covered by a commit
        self._leader = False
        self._last_ok = False
        #: leader commit passes / follower flushes satisfied by one
        self.commits = 0
        self.coalesced = 0
        #: duration of the last leader commit pass (window sleep +
        #: write + fsync across shards) — the group_commit_window_ms
        #: tuning signal (BENCH_MODE=recovery sweep)
        self.last_commit_ms = 0.0

    # -- shard routing -----------------------------------------------------

    @any_thread
    def append(self, op: Tuple[Any, ...],
               key: Optional[str] = None) -> None:
        """Frame + buffer one record into its key's shard (no I/O).
        ``key=None`` routes to shard 0 (single-journal semantics)."""
        idx = shard_of(key, self.n) if key is not None else 0
        self.shards[idx].append(op)

    def _shard_error(self, exc) -> None:
        cb = self.on_error
        if cb is None:
            return
        if exc is not None:
            cb(exc)
        elif not any(w.degraded for w in self.shards):
            # clear only once EVERY shard recovered
            cb(None)

    # -- group-commit flush ------------------------------------------------

    @any_thread
    def flush(self) -> bool:
        """Group commit: everything buffered across all shards at the
        time of the call reaches disk before this returns (or the
        write degrades — never raises). Concurrent callers coalesce
        into one leader pass per round."""
        with self._cv:
            self._req += 1
            my_req = self._req
            if self._leader:
                # a leader is committing: wait for a round that
                # covers appends made before this call
                self.coalesced += 1
                while self._done < my_req and self._leader:
                    self._cv.wait(timeout=0.05)
                if self._done >= my_req:
                    return self._last_ok
                # leader exited without covering us — take over
            self._leader = True
        try:
            while True:
                t0 = time.perf_counter()
                if self.group_window_ms > 0:
                    # the coalescing window: stragglers' appends land
                    # in the buffers this pass is about to commit
                    time.sleep(self.group_window_ms / 1000.0)
                with self._cv:
                    upto = self._req
                ok = False
                any_pending = False
                for w in self.shards:
                    if w.pending():
                        any_pending = True
                        ok = w.flush() or ok
                if any_pending:
                    self.commits += 1
                    self.last_commit_ms = \
                        (time.perf_counter() - t0) * 1000.0
                with self._cv:
                    self._done = upto
                    self._last_ok = ok
                    self._cv.notify_all()
                    if self._req == upto:
                        return ok
                # more flush requests arrived mid-commit: go again
        finally:
            with self._cv:
                self._leader = False
                self._cv.notify_all()

    def pending(self) -> int:
        return sum(w.pending() for w in self.shards)

    # -- rotation / lifecycle ---------------------------------------------

    def rotate_to(self, seq: int) -> List[str]:
        """Flush, then switch every shard to its ``seq`` segment
        (checkpoint commit protocol). Returns the OLD paths."""
        self.flush()
        old = []
        for i, w in enumerate(self.shards):
            old.append(w.rotate(shard_path(
                self.dir, i if self.n > 1 else None, seq)))
        self.seq = seq
        return old

    def close(self) -> None:
        self.flush()
        for w in self.shards:
            w.close()

    # -- aggregate surface (the manager/tests' single-Wal view) -----------

    @property
    def records(self) -> int:
        return sum(w.records for w in self.shards)

    @property
    def bytes(self) -> int:
        return sum(w.bytes for w in self.shards)

    @property
    def dropped(self) -> int:
        return sum(w.dropped for w in self.shards)

    @property
    def degraded(self) -> bool:
        return any(w.degraded for w in self.shards)

    @property
    def _retry_at(self) -> float:
        return max(w._retry_at for w in self.shards)

    @_retry_at.setter
    def _retry_at(self, v: float) -> None:
        for w in self.shards:
            w._retry_at = v

    def info(self) -> dict:
        per = [w.info() for w in self.shards]
        out = {
            "shards": self.n,
            "path": per[0]["path"] if self.n == 1 else self.dir,
            "records": sum(p["records"] for p in per),
            "bytes": sum(p["bytes"] for p in per),
            "pending": sum(p["pending"] for p in per),
            "appends_total": sum(p["appends_total"] for p in per),
            "fsyncs": sum(p["fsyncs"] for p in per),
            "fsync_errors": sum(p["fsync_errors"] for p in per),
            "dropped": sum(p["dropped"] for p in per),
            "degraded": any(p["degraded"] for p in per),
            "last_fsync_ms": max(p["last_fsync_ms"] for p in per),
            "group_commits": self.commits,
            "group_coalesced": self.coalesced,
            "last_commit_ms": round(self.last_commit_ms, 3),
        }
        if self.n > 1:
            out["per_shard"] = per
        return out
