"""Write-ahead journal for the durability layer (docs/DURABILITY.md).

The reference broker's durability is Mnesia ram-replication plus
session takeover — a single node that dies takes its routes, retained
messages and persistent sessions with it unless a peer holds a
replica. This build runs the "millions of users" workload on ONE
device-backed node, so it needs what the reference never shipped
in-core: a crash-consistent local journal.

Design (the classic WAL contract, scoped to broker state):

  - **CRC-framed records.** Every record is
    ``magic(2B) | length(4B LE) | crc32(4B LE) | payload`` with the
    payload encoded by the cluster wire codec (:mod:`emqx_tpu.wire`
    — data-only, no pickle: a corrupt journal can produce garbage
    values but never code execution). Replay verifies magic, bounds
    and CRC per record and STOPS at the first torn/corrupt frame —
    a crash mid-append loses at most the unsynced tail, never the
    prefix, and never crashes the recovering node.
  - **Batched appends, batched fsync.** ``append`` only frames into
    an in-memory buffer; ``flush`` writes the whole buffer and pays
    ONE ``fsync`` for it. The broker calls ``flush`` from the
    ingress executor thread at batch granularity (plus a periodic
    timer for quiet periods), so the socket loops never wait on disk
    and the hot path pays one append per batch, not per op.
  - **Degrades, never wedges.** An fsync/write failure (disk full,
    dying volume) flips the journal into memory-only mode: appends
    keep buffering (bounded, drop-oldest with a counter), the
    ``wal_write_failed`` alarm raises, and a bounded exponential
    backoff retries the flush. Publishes never block on a broken
    disk — durability degrades to the pre-journal contract instead.

Record vocabulary (applied idempotently on replay — a doubly-replayed
record is a no-op; see DurabilityManager._apply):

  ``("route", filter, dest, refs)``      absolute refcount after the op
  ``("retain", topic, Message|None, ts)`` set / clear (None payload)
  ``("sess.state", cid, detached_ts|None, to_wire)``  full snapshot
  ``("sess.sub", cid, filter_key, SubOpts)``
  ``("sess.unsub", cid, filter_key)``
  ``("sess.close", cid)``

Fault points (docs/ROBUSTNESS.md): ``wal.append`` short-writes one
frame (torn tail) and degrades the writer; ``wal.fsync`` fails the
sync (the disk-full path).
"""

from __future__ import annotations

import binascii
import logging
import os
import struct
import threading
import time
from typing import Any, List, Tuple

from emqx_tpu import faults, wire

log = logging.getLogger("emqx_tpu.wal")

#: frame header: magic, payload length, payload crc32
MAGIC = 0xE17A
_HDR = struct.Struct("<HII")
#: refuse absurd lengths during replay — a corrupt length field must
#: not allocate gigabytes before the CRC check can reject it
MAX_RECORD = 64 << 20


class WalError(Exception):
    """Unrecoverable journal I/O error surfaced to the manager."""


def frame(payload: bytes) -> bytes:
    """One CRC-framed journal record."""
    return _HDR.pack(MAGIC, len(payload),
                     binascii.crc32(payload) & 0xFFFFFFFF) + payload


def encode_record(op: Tuple[Any, ...]) -> bytes:
    return frame(wire.dumps(op))


def iter_records(path: str):
    """Yield ``(offset, record_tuple)`` for every intact record, then
    a final ``(offset, None)`` sentinel carrying the clean-end offset.
    Stops (without raising) at the first torn or corrupt frame — the
    caller learns truncation happened when the sentinel offset is
    short of the file size."""
    try:
        size = os.path.getsize(path)
    except OSError:
        yield (0, None)
        return
    with open(path, "rb") as f:
        off = 0
        while True:
            hdr = f.read(_HDR.size)
            if len(hdr) < _HDR.size:
                break  # clean EOF or torn header
            magic, length, crc = _HDR.unpack(hdr)
            if magic != MAGIC or length > MAX_RECORD:
                break
            payload = f.read(length)
            if len(payload) < length:
                break  # torn payload
            if binascii.crc32(payload) & 0xFFFFFFFF != crc:
                break  # bit rot / interleaved short write
            try:
                rec = wire.loads(payload)
            except wire.WireError:
                break  # framed but undecodable — treat as torn
            off = f.tell()
            yield (off, rec)
        yield (off, None)
    # size consulted only for the caller's torn-tail report
    del size


def replay(path: str) -> Tuple[List[Tuple[Any, ...]], bool]:
    """Read every intact record; returns ``(records, torn)`` where
    ``torn`` is True when the file holds bytes past the last intact
    frame (a crash mid-append — expected, not an error)."""
    records: List[Tuple[Any, ...]] = []
    clean_end = 0
    for off, rec in iter_records(path):
        if rec is None:
            clean_end = off
        else:
            records.append(rec)
    try:
        torn = clean_end < os.path.getsize(path)
    except OSError:
        torn = False
    return records, torn


class Wal:
    """Appender half of the journal: one open segment file, an
    in-memory frame buffer, batched write+fsync, rotation, and the
    degrade-don't-wedge error path. Thread-safe (appends arrive from
    event-loop threads, flushes from the ingress executor)."""

    def __init__(self, path: str, fsync: bool = True,
                 max_buffer: int = 100_000,
                 retry_backoff_s: float = 1.0,
                 retry_backoff_max_s: float = 30.0,
                 on_error=None) -> None:
        self._lock = threading.Lock()
        self.path = path
        self.fsync = fsync
        self.max_buffer = max_buffer
        self._buf: List[bytes] = []
        self._f = open(path, "ab")
        #: intact records written to the CURRENT segment
        self.records = 0
        self.bytes = int(self._f.tell())
        self.appends_total = 0
        self.fsyncs = 0
        self.fsync_errors = 0
        self.dropped = 0
        self.flushes = 0
        self.last_fsync_ms = 0.0
        #: memory-only mode after a write/fsync failure; flush retries
        #: after the backoff deadline
        self.degraded = False
        self._retry_at = 0.0
        self._backoff = retry_backoff_s
        self._backoff0 = retry_backoff_s
        self._backoff_max = retry_backoff_max_s
        #: manager callback: on_error(exc | None) — exc on degrade,
        #: None when a later flush recovers (alarm raise/clear)
        self.on_error = on_error

    # -- append side ------------------------------------------------------

    def append(self, op: Tuple[Any, ...]) -> None:
        """Frame + buffer one record (no I/O here — the hot path pays
        serialization only; disk happens in :meth:`flush`)."""
        rec = encode_record(op)
        with self._lock:
            self._buf.append(rec)
            self.appends_total += 1
            if len(self._buf) > self.max_buffer:
                # bounded memory in degraded mode: drop-oldest, count
                del self._buf[0]
                self.dropped += 1

    def pending(self) -> int:
        with self._lock:
            return len(self._buf)

    # -- flush side -------------------------------------------------------

    def flush(self) -> bool:
        """Write + fsync everything buffered (ONE sync for the whole
        batch). Returns True when the buffer reached disk; False when
        nothing was pending or the journal is degraded and inside its
        retry backoff. Never raises — failures degrade."""
        with self._lock:
            if not self._buf:
                return False
            now = time.monotonic()
            if self.degraded and now < self._retry_at:
                return False
            batch, self._buf = self._buf, []
            try:
                wrote_bytes = 0
                for rec in batch:
                    if faults.enabled and faults.fire("wal.append"):
                        # injected short write: half a frame lands —
                        # the torn tail replay must truncate at — and
                        # the writer degrades like a real ENOSPC
                        self._f.write(rec[:max(1, len(rec) // 2)])
                        self._f.flush()
                        raise WalError("short write (injected)")
                    self._f.write(rec)
                    wrote_bytes += len(rec)
                self._f.flush()
                if faults.enabled:
                    faults.fire("wal.fsync")
                if self.fsync:
                    t0 = time.perf_counter()
                    os.fsync(self._f.fileno())
                    self.last_fsync_ms = (time.perf_counter() - t0) \
                        * 1000.0
                # counters commit only with the sync: a failed batch
                # re-buffers IN FULL and the retry rewrites it from
                # the pre-batch boundary — exactly-once on disk
                self.records += len(batch)
                self.bytes += wrote_bytes
                self.fsyncs += 1
                self.flushes += 1
                if self.degraded:
                    self.degraded = False
                    self._backoff = self._backoff0
                    if self.on_error is not None:
                        self.on_error(None)
                    log.warning("journal recovered: %s", self.path)
                return True
            except Exception as e:
                # the WHOLE batch goes back to the front (order
                # kept): nothing in it counts as durable until the
                # fsync lands
                self._buf[:0] = batch
                if not isinstance(e, WalError):
                    # a real partial write / failed sync leaves an
                    # unsynced (possibly torn) tail; truncate back to
                    # the last durable boundary so the retry rewrites
                    # cleanly and replay never loses post-recovery
                    # records behind a torn frame. The INJECTED short
                    # write skips this — it models a crash, and the
                    # torn tail is exactly what the recovery tests
                    # must see on disk.
                    try:
                        self._f.seek(self.bytes)
                        self._f.truncate(self.bytes)
                    except OSError:
                        pass
                self.fsync_errors += 1
                self.degraded = True
                self._retry_at = time.monotonic() + self._backoff
                self._backoff = min(self._backoff * 2,
                                    self._backoff_max)
                if self.on_error is not None:
                    self.on_error(e)
                log.error("journal write failed (%s): memory-only, "
                          "retry in %.1fs", e, self._backoff)
                return False

    def rotate(self, new_path: str) -> str:
        """Flush, then switch appends to a fresh segment (checkpoint
        commit protocol: the old segment stays on disk until the new
        manifest lands). Returns the OLD path."""
        self.flush()
        with self._lock:
            old = self.path
            try:
                self._f.close()
            except OSError:
                pass
            self.path = new_path
            self._f = open(new_path, "ab")
            self.records = 0
            self.bytes = int(self._f.tell())
            return old

    def close(self) -> None:
        self.flush()
        with self._lock:
            try:
                self._f.close()
            except OSError:
                pass

    def info(self) -> dict:
        with self._lock:
            return {
                "path": self.path,
                "records": self.records,
                "bytes": self.bytes,
                "pending": len(self._buf),
                "appends_total": self.appends_total,
                "fsyncs": self.fsyncs,
                "fsync_errors": self.fsync_errors,
                "dropped": self.dropped,
                "degraded": self.degraded,
                "last_fsync_ms": round(self.last_fsync_ms, 3),
            }
