"""Batched retained-name matching — the subscribe-path analogue of
the publish walk.

The retained index (:class:`emqx_tpu.modules.retainer.RetainIndex`)
keeps stored topic NAMES as a persistent ``[cap, L]`` word-id matrix;
a wildcard subscribe matches a filter against every stored name with
a pure elementwise program (per level: equality or ``+``, a ``#``
suffix relaxing the depth check, the ``$``-root rule masking system
topics — no automaton walk, no gathers). Until PR 19 that kernel took
ONE filter per dispatch, so a subscribe burst — session resume,
reconnect storm, shared-group rebalance — paid one device round-trip
per resumed subscription. This module batches the filter side too:

  ``[F, L]`` encoded filters × ``[cap, L]`` stored names → ``[F, cap]``
  hit matrix, one dispatch per burst.

Two implementations, byte-parity pinned (tests/test_retained_replay):

  - :func:`match_names_many` — the jitted lax baseline. The level
    loop is unrolled (``L`` static), carrying one ``[F, cap]`` bool
    accumulator, so peak memory never materializes ``[F, cap, L]``.
  - :func:`match_names_many_pallas` — the Pallas variant: grid over
    (filter-block × name-block) tiles, each program ANDing its
    ``[BF, BN]`` tile entirely in VMEM. Elementwise and HBM-bandwidth
    bound, like the publish fan-out kernels.

Dispatch (:func:`match_names_auto`) follows the walk seam
(:func:`~emqx_tpu.ops.walk_pallas.walk_variant`): Pallas on TPU-class
backends, lax elsewhere, ``EMQX_TPU_WALK`` overriding for A/B runs —
a forced override on a non-TPU backend runs the kernel in interpret
mode (slow, byte-exact; how CI drives the Pallas path on CPU).

Unlike the publish side there is no ``has_hash`` static argument:
the batch mixes ``#``- and non-``#`` filters, so the flag rides as an
array input and compile count depends only on the (padded) shapes.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from emqx_tpu.ops.walk_pallas import walk_variant

#: '+' sentinel in an encoded FILTER row — never collides with real
#: word ids (≥0) or the topic-side UNKNOWN (-1) / PAD (-2); mirrors
#: retainer's encoding (the index owns the tokenization)
PLUS_ID = -3

#: Pallas tile: filters per program × names per program. Elementwise
#: work, so the tile only has to amortize grid overhead; BN spans
#: whole VPU lanes, BF keeps a burst's worth of filters per program.
_BF = 8
_BN = 512


def _match_many_body(fw, fn, has_hash, topic_ids, n_words, sys_mask):
    """``[F, L]`` filters vs ``[cap, L]`` names → ``[F, cap]`` bool.

    ``fw`` filter word ids (``PLUS_ID`` for ``+``, PAD beyond ``fn``);
    ``fn`` per-filter word count excluding a trailing ``#``;
    ``has_hash`` the trailing-``#`` flag per filter. Semantics =
    emqx_topic:match/2 exactly as the old one-filter kernel: per-level
    equality with ``+`` wildcards; a ``#`` suffix matches the parent
    itself and anything deeper (src/emqx_topic.erl:64-87); root
    wildcards never match ``$``-topics (src/emqx_trie.erl:162-163).
    Dead rows have ``n_words == 0`` — excluded by the ``n > 0`` live
    gate. A padding filter row (``fn == 0``, no ``#``) matches
    nothing for the same reason."""
    L = topic_ids.shape[1]
    fnc = fn[:, None]                                    # [F, 1]
    ok = jnp.ones((fw.shape[0], topic_ids.shape[0]), dtype=jnp.bool_)
    for lvl in range(L):                                 # L static
        w = fw[:, lvl][:, None]                          # [F, 1]
        ok &= ((topic_ids[:, lvl][None, :] == w) | (w == PLUS_ID)
               | (lvl >= fnc))
    nw = n_words[None, :]                                # [1, cap]
    exact = ok & (nw == fnc)
    deeper = has_hash[:, None] & ok & (nw >= fnc)
    hit = (exact | deeper) & (nw > 0)
    root_wild = (fw[:, 0] == PLUS_ID) | (has_hash & (fn == 0))
    return hit & ~(sys_mask[None, :] & root_wild[:, None])


# jit once; shapes vary only with the padded burst size and the index
# capacity (both power-of-two) so compile count stays log² bounded
match_names_many = jax.jit(_match_many_body)


def _retained_kernel(fw_ref, fn_ref, hh_ref, ids_ref, n_ref, sys_ref,
                     out_ref, *, L):
    """One program = one ``[BF, BN]`` tile of the hit matrix; the
    same elementwise math as :func:`_match_many_body`, all operands
    block-copied into VMEM by the BlockSpecs."""
    fw = fw_ref[...]                                     # [BF, L]
    fn = fn_ref[...][:, 0]                               # [BF]
    hh = hh_ref[...][:, 0] > 0
    ids = ids_ref[...]                                   # [BN, L]
    nw = n_ref[...][:, 0][None, :]                       # [1, BN]
    sysm = sys_ref[...][:, 0] > 0
    fnc = fn[:, None]
    ok = jnp.ones((fw.shape[0], ids.shape[0]), dtype=jnp.bool_)
    for lvl in range(L):
        w = fw[:, lvl][:, None]
        ok &= ((ids[:, lvl][None, :] == w) | (w == PLUS_ID)
               | (lvl >= fnc))
    exact = ok & (nw == fnc)
    deeper = hh[:, None] & ok & (nw >= fnc)
    hit = (exact | deeper) & (nw > 0)
    root_wild = (fw[:, 0] == PLUS_ID) | (hh & (fn == 0))
    out_ref[...] = (hit & ~(sysm[None, :] & root_wild[:, None])
                    ).astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("interpret",))
def match_names_many_pallas(fw, fn, has_hash, topic_ids, n_words,
                            sys_mask, *, interpret: bool = False):
    """Pallas twin of :func:`match_names_many` — same arguments, same
    ``[F, cap]`` bool result, byte-identical output."""
    import jax.experimental.pallas as pl

    F, L = fw.shape
    N = topic_ids.shape[0]
    Fp = -(-F // _BF) * _BF
    Np = -(-N // _BN) * _BN
    if Fp != F:
        # padding filter rows: fn=0 without '#' matches nothing
        pad = Fp - F
        fw = jnp.concatenate([fw, jnp.full((pad, L), -2, fw.dtype)])
        fn = jnp.concatenate([fn, jnp.zeros((pad,), fn.dtype)])
        has_hash = jnp.concatenate(
            [has_hash, jnp.zeros((pad,), has_hash.dtype)])
    if Np != N:
        # padding name rows: n_words=0 fails the live gate
        pad = Np - N
        topic_ids = jnp.concatenate(
            [topic_ids, jnp.full((pad, L), -2, topic_ids.dtype)])
        n_words = jnp.concatenate(
            [n_words, jnp.zeros((pad,), n_words.dtype)])
        sys_mask = jnp.concatenate(
            [sys_mask, jnp.zeros((pad,), sys_mask.dtype)])
    out = pl.pallas_call(
        functools.partial(_retained_kernel, L=L),
        grid=(Fp // _BF, Np // _BN),
        in_specs=[
            pl.BlockSpec((_BF, L), lambda f, t: (f, 0)),
            pl.BlockSpec((_BF, 1), lambda f, t: (f, 0)),
            pl.BlockSpec((_BF, 1), lambda f, t: (f, 0)),
            pl.BlockSpec((_BN, L), lambda f, t: (t, 0)),
            pl.BlockSpec((_BN, 1), lambda f, t: (t, 0)),
            pl.BlockSpec((_BN, 1), lambda f, t: (t, 0)),
        ],
        out_specs=pl.BlockSpec((_BF, _BN), lambda f, t: (f, t)),
        out_shape=jax.ShapeDtypeStruct((Fp, Np), jnp.int32),
        interpret=interpret,
    )(fw, fn[:, None].astype(jnp.int32),
      has_hash[:, None].astype(jnp.int32),
      topic_ids, n_words[:, None].astype(jnp.int32),
      sys_mask[:, None].astype(jnp.int32))
    return out[:F, :N] > 0


def match_names_auto(fw, fn, has_hash, topic_ids, n_words, sys_mask):
    """Dispatch seam the retained index calls: the Pallas tiles on
    TPU-class backends, the lax baseline everywhere else. Byte parity
    between the two is pinned, so the choice is purely a performance
    knob — the ``EMQX_TPU_WALK`` env var overrides for A/B runs."""
    if walk_variant() == "pallas":
        interp = jax.default_backend() not in ("tpu", "axon")
        return match_names_many_pallas(
            fw, fn, has_hash, topic_ids, n_words, sys_mask,
            interpret=interp)
    return match_names_many(fw, fn, has_hash, topic_ids, n_words,
                            sys_mask)
