"""Host-side topic tokenization: words → dense int32 word ids.

The reference walks the trie with binary words as ETS keys
(src/emqx_trie.erl:166-178); a TPU automaton needs fixed-dtype integer
word ids. We *intern* words into a dense vocabulary (exact, no hash
collisions — parity-safe): every word that appears in any subscription
filter gets an id; publish-topic words never seen in a filter map to
``UNKNOWN`` and can only be matched by ``+``/``#`` edges, which is
exactly the reference's "no literal edge exists" case.

Special ids (negative, never collide with vocab ids):
  - ``UNKNOWN`` (-1): word not in any filter
  - ``PAD``     (-2): padding beyond the topic's word count

Wildcard words ``+``/``#`` are interned like ordinary vocab words when
they appear in *filters* (they index edge tables, not publish words).
A publish *name* containing "+"/"#" is not valid MQTT but would simply
intern/list as literal words here, matching emqx_topic:match/2 which
treats them as literals on the name side.
"""

from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

import numpy as np

UNKNOWN = -1
PAD = -2


class WordTable:
    """Interning table: word str ↔ dense int id. Append-only."""

    def __init__(self) -> None:
        self._ids: Dict[str, int] = {}
        self._words: List[str] = []

    def __len__(self) -> int:
        return len(self._words)

    def words(self):
        """All interned words in id order (checkpoint export)."""
        return list(self._words)

    def intern(self, word: str) -> int:
        wid = self._ids.get(word)
        if wid is None:
            wid = len(self._words)
            self._ids[word] = wid
            self._words.append(word)
        return wid

    def lookup(self, word: str) -> int:
        """Id for a publish-topic word; UNKNOWN if never interned."""
        return self._ids.get(word, UNKNOWN)

    def word(self, wid: int) -> str:
        return self._words[wid]

    def encode_filter(self, ws: Sequence[str]) -> List[int]:
        return [self.intern(w) for w in ws]

    def encode_topic(self, ws: Sequence[str]) -> List[int]:
        return [self.lookup(w) for w in ws]


def encode_batch(
    table: WordTable, topics: Sequence[str], max_levels: int
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Encode publish topics into fixed-shape arrays.

    Returns ``(word_ids[B, L], n_words[B], sys_mask[B])`` where
    ``sys_mask`` marks topics whose first word starts with ``$`` (these
    skip root wildcards, emqx_trie.erl:162-163). Topics with more than
    ``max_levels`` levels are marked with ``n_words = -1`` — the caller
    must route them to the host oracle (static-shape overflow fallback).
    """
    B = len(topics)
    ids = np.full((B, max_levels), PAD, dtype=np.int32)
    n_words = np.zeros((B,), dtype=np.int32)
    sys_mask = np.zeros((B,), dtype=bool)
    for i, t in enumerate(topics):
        ws = t.split("/")
        if len(ws) > max_levels:
            n_words[i] = -1
            continue
        n_words[i] = len(ws)
        sys_mask[i] = ws[0].startswith("$")
        for j, w in enumerate(ws):
            ids[i, j] = table.lookup(w)
    return ids, n_words, sys_mask
