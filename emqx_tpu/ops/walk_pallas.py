"""VMEM-resident NFA walk — the Pallas variant of :mod:`ops.match`.

The lax.scan walk (:func:`emqx_tpu.ops.match.match_batch`) carries the
active-state frontier through the scan carry: every hop ends in a
fresh XLA op whose operands round-trip HBM, so a deep topic pays one
HBM latency per hop *on top of* the probe gathers (docs/PERF_NOTES.md
"gather-op count governs throughput"). This kernel runs the whole
walk for one topic inside a single Pallas program:

  - the frontier (≤ K packed lanes) lives in **VMEM scratch** across
    hops — between-hop state never leaves the chip;
  - the walk tables stay in HBM (``pl.ANY``) sized for 10M-sub scale;
    each hop DMAs exactly the probed rows (2 buckets + 1 ``node2``
    row per live lane) into VMEM scratch — the same rows the lax
    walk gathers, minus the per-hop dispatch/HBM-carry overhead;
  - the hop loop is **unrolled** (``steps`` is static, ≤ L+1), so
    emit stores use static indices and Mosaic sees straight-line
    vector code.

Byte-exact parity with ``match_batch`` is the contract (pinned by
tests/test_walk_pallas.py on CPU interpret mode): same probe math
(:func:`~emqx_tpu.ops.csr.hash_mix`), same exact inline chain-word
verify, same compaction order, same overflow semantics. The lax walk
stays the dispatch fallback for the host regime, interpret-heavy
paths and non-TPU backends (:func:`match_batch_auto`).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np

from emqx_tpu.ops.csr import (NARROW_SLOT, WIDE_SLOT, Automaton,
                              hash_mix)
from emqx_tpu.ops.match import (_LVL_BITS, _LVL_MASK, MatchResult,
                                match_batch)

#: probe-row gathers per live lane per hop: two 2-choice buckets +
#: one node2 terminal row (the bench's ``gathers_per_topic`` model)
GATHERS_PER_HOP = 3

#: env override for dispatch: "auto" (backend-gated), "lax", "pallas"
_WALK_ENV = "EMQX_TPU_WALK"


def walk_variant() -> str:
    """The walk implementation dispatch would select right now:
    ``"pallas"`` on TPU-class backends, ``"lax"`` elsewhere, with the
    ``EMQX_TPU_WALK`` env var as the operator override (surfaces in
    ``ctl cache`` as the ``walk`` tag)."""
    mode = os.environ.get(_WALK_ENV, "auto")
    if mode in ("lax", "pallas"):
        return mode
    return ("pallas" if jax.default_backend() in ("tpu", "axon")
            else "lax")


def _compact_lanes(cands: jax.Array, k: int):
    """Kernel-side mirror of ``match._compact``: candidates ``[n]``
    (-1 invalid) → packed ``[k]`` + overflow scalar.

    ``match._compact`` sorts small sets (n ≤ 32) descending on a
    Batcher network and order-preserving-packs larger ones. Trie
    children are unique, so both reduce to a rank-select: descending
    value rank for the sorted branch, valid-prefix rank for the
    scatter branch — each implemented as a one-hot max (pure VPU
    compares, no dynamic scatter for Mosaic to choke on)."""
    n = cands.shape[0]
    valid = cands >= 0
    count = jnp.sum(valid)
    if n <= 32:
        # rank = number of strictly-larger candidates; valid values
        # are unique so this is exactly the descending sort position
        rank = jnp.sum(cands[:, None] > cands[None, :], axis=0)
    else:
        rank = jnp.cumsum(valid) - 1
    lane = jax.lax.broadcasted_iota(jnp.int32, (k, n), 0)
    sel = valid[None, :] & (rank[None, :] == lane)
    packed = jnp.max(jnp.where(sel, cands[None, :], -1), axis=1)
    return packed, count > k


def _walk_kernel(words_ref, win_ref, n_ref, sys_ref, seed_ref,
                 wt_ref, node2_ref, emits_ref, ovf_ref,
                 active_ref, sidx_ref, bb_ref, lvl_ref,
                 node_buf, row_buf, sem,
                 *, k, steps, slots, take, L, nb):
    """One program = one topic's full walk. See module doc."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    wide = take > 1
    sw = WIDE_SLOT if wide else NARROW_SLOT
    seed = seed_ref[0]
    n = n_ref[0]
    is_sys = sys_ref[0] > 0

    # frontier init: lane 0 at the root, packed lvl 0
    lane_iota = jax.lax.broadcasted_iota(jnp.int32, (1, k), 1)
    active_ref[...] = jnp.where(lane_iota == 0, 0, -1)
    ovf = jnp.zeros((), jnp.bool_)

    for s in range(steps):
        active = active_ref[0, :]
        if wide:
            state = jnp.where(active >= 0, active >> _LVL_BITS, -1)
            lvl = active & _LVL_MASK
            lvl_ref[...] = jnp.minimum(lvl, L - 1)[None, :]
        else:
            state = active
            w_s = words_ref[0, s] if s < L else jnp.int32(-2)
        alive = state >= 0
        s_idx = jnp.maximum(state, 0)
        sidx_ref[...] = s_idx[None, :]
        if wide:
            w0_probe = None  # per-lane window word, loaded below
        else:
            w0 = jnp.broadcast_to(w_s, state.shape)
        # bucket pair per lane — the same mix the builder placed with
        h1, h2 = hash_mix(
            state, w0 if not wide else jnp.zeros_like(state), seed)
        if not wide:
            bb_ref[0, :] = (h1 & jnp.uint32(nb - 1)).astype(jnp.int32)
            bb_ref[1, :] = (h2 & jnp.uint32(nb - 1)).astype(jnp.int32)

        win = None
        if wide:
            # per-lane word window [k, take] (dynamic level start)
            rows = []
            for i in range(k):
                li = lvl_ref[0, i]
                rows.append(pl.load(
                    win_ref,
                    (pl.ds(0, 1), pl.ds(li, 1), slice(None)))[0])
            win = jnp.concatenate(rows, axis=0)  # [k, take]
            w0 = win[:, 0]
            h1, h2 = hash_mix(state, w0, seed)
            bb_ref[0, :] = (h1 & jnp.uint32(nb - 1)).astype(jnp.int32)
            bb_ref[1, :] = (h2 & jnp.uint32(nb - 1)).astype(jnp.int32)

        # stream exactly the probed rows HBM→VMEM: 2 bucket rows + 1
        # node2 row per lane, all copies in flight before one wait
        copies = []
        for i in range(k):
            copies.append(pltpu.make_async_copy(
                node2_ref.at[sidx_ref[0, i]], node_buf.at[i],
                sem.at[i]))
            copies.append(pltpu.make_async_copy(
                wt_ref.at[bb_ref[0, i]], row_buf.at[2 * i],
                sem.at[k + 2 * i]))
            copies.append(pltpu.make_async_copy(
                wt_ref.at[bb_ref[1, i]], row_buf.at[2 * i + 1],
                sem.at[k + 2 * i + 1]))
        for c in copies:
            c.start()
        for c in copies:
            c.wait()

        node = node_buf[...]                       # [k, 4]
        plus_col, hashf_col, endf_col = (
            node[:, 0], node[:, 1], node[:, 2])
        if wide:
            at_root_sys = (active == 0) & is_sys
            walking = alive & (lvl < n)
            ending = alive & (lvl == n)
        else:
            at_root_sys = ((jnp.int32(s) == 0) & is_sys) & alive
            walking = alive & (s < n)
            ending = alive & (s == n)
        emit_h = jnp.where(
            (walking | ending) & ~at_root_sys, hashf_col, -1)
        emit_e = jnp.where(ending, endf_col, -1)

        # probe both buckets' rows as one [k, 2*slots] candidate set
        # (max over the union ≡ match_batch's max of per-bucket maxes)
        row = row_buf[...].reshape((k, 2 * slots, sw))
        if wide:
            stake = row[..., 2]
            hit = (row[..., 0] == state[:, None]) & (
                row[..., 1] == win[:, None, 0])
            for i in range(take - 1):
                hit &= (stake <= i + 1) | (
                    row[..., 4 + i] == win[:, None, 1 + i])
            hit &= lvl[:, None] + stake <= n
            child = jnp.max(jnp.where(hit, row[..., 3], -1), axis=1)
            adv = jnp.max(jnp.where(hit, stake, 0), axis=1)
            lit_ok = walking & (w0 >= 0) & (child >= 0)
            lit = jnp.where(
                lit_ok, (child << _LVL_BITS) | (lvl + adv), -1)
            plus_ok = walking & ~at_root_sys & (plus_col >= 0)
            plus = jnp.where(
                plus_ok,
                (jnp.maximum(plus_col, 0) << _LVL_BITS) | (lvl + 1),
                -1)
        else:
            hit = (row[..., 0] == state[:, None]) & (
                row[..., 1] == w0[:, None])
            lit = jnp.max(jnp.where(hit, row[..., 2], -1), axis=1)
            lit = jnp.where(walking & (w0 >= 0), lit, -1)
            plus = jnp.where(walking & ~at_root_sys, plus_col, -1)

        nxt, over = _compact_lanes(jnp.concatenate([lit, plus]), k)
        ovf = ovf | over
        active_ref[...] = nxt[None, :]
        emits_ref[0, s, :] = jnp.concatenate([emit_h, emit_e])

    # residue: lanes alive after the last hop were never processed —
    # flag for the exact host fallback (match_batch's check, verbatim)
    residue = active_ref[0, :]
    if wide:
        r_lvl = residue & _LVL_MASK
        ovf = ovf | jnp.any((residue >= 0) & (r_lvl <= n))
    else:
        ovf = ovf | jnp.any((residue >= 0) & (steps <= n))
    ovf_ref[0, 0] = ovf.astype(jnp.int32)


@functools.partial(jax.jit,
                   static_argnames=("k", "m", "steps", "slots", "take",
                                    "pack_ids", "interpret"))
def match_batch_pallas(
    auto: Automaton,
    word_ids: jax.Array,   # int32[B, L]
    n_words: jax.Array,    # int32[B]
    sys_mask: jax.Array,   # bool[B]
    *,
    k: int = 16,
    m: int = 64,
    steps: int | None = None,
    slots: int = 2,
    take: int = 1,
    pack_ids: bool = True,
    interpret: bool = False,
) -> MatchResult:
    """Drop-in replacement for :func:`ops.match.match_batch` — same
    signature, same ``MatchResult``, byte-identical output."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, L = word_ids.shape
    if steps is None:
        steps = L + 1
    wide = take > 1
    if wide and L > _LVL_MASK:
        raise ValueError(
            f"wide walk supports at most {_LVL_MASK} levels, got {L}")
    sw = WIDE_SLOT if wide else NARROW_SLOT
    nb = auto.wt.shape[0]

    # word windows [B, L, take]: win[b, l] = words[l : l+take] padded
    # with -2 beyond the topic (the same construction match_batch's
    # wide path builds per topic)
    wp = jnp.concatenate(
        [word_ids, jnp.full((B, take), -2, jnp.int32)], axis=1)
    win_mat = jnp.stack([wp[:, l:l + take] for l in range(L)], axis=1)

    kern = functools.partial(
        _walk_kernel, k=k, steps=steps, slots=slots, take=take,
        L=L, nb=nb)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=0,
        grid=(B,),
        in_specs=[
            pl.BlockSpec((1, L), lambda b: (b, 0)),
            pl.BlockSpec((1, L, take), lambda b: (b, 0, 0)),
            pl.BlockSpec((1,), lambda b: (b,)),
            pl.BlockSpec((1,), lambda b: (b,)),
            pl.BlockSpec((1,), lambda b: (0,)),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=[
            pl.BlockSpec((1, steps, 2 * k), lambda b: (b, 0, 0)),
            pl.BlockSpec((1, 1), lambda b: (b, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((1, k), jnp.int32),       # frontier
            pltpu.VMEM((1, k), jnp.int32),       # node2 row indices
            pltpu.VMEM((2, k), jnp.int32),       # bucket pair
            pltpu.VMEM((1, k), jnp.int32),       # clamped levels
            pltpu.VMEM((k, 4), jnp.int32),       # node2 rows
            pltpu.VMEM((2 * k, slots * sw), jnp.int32),  # probe rows
            pltpu.SemaphoreType.DMA((3 * k,)),
        ],
    )
    emits, ovf_i = pl.pallas_call(
        kern,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((B, steps, 2 * k), jnp.int32),
            jax.ShapeDtypeStruct((B, 1), jnp.int32),
        ],
        interpret=interpret,
    )(word_ids, win_mat, n_words,
      sys_mask.astype(jnp.int32), auto.wt_seed, auto.wt, auto.node2)

    # tail identical to match_batch (packing / overflow composition)
    ovf = ovf_i[:, 0] > 0
    flat = emits.reshape(B, -1)
    valid = flat >= 0
    cnt = jnp.sum(valid, axis=1)
    too_long = n_words < 0
    if pack_ids:
        pos = jnp.cumsum(valid, axis=1) - 1
        ids = jnp.full((B, m), -1, dtype=flat.dtype).at[
            jnp.arange(B)[:, None],
            jnp.where(valid, pos, m)].set(flat, mode="drop")
        return MatchResult(
            ids=jnp.where(too_long[:, None], -1, ids),
            count=jnp.where(too_long, 0,
                            jnp.minimum(cnt, m)).astype(jnp.int32),
            overflow=ovf | (cnt > m) | too_long,
        )
    return MatchResult(
        ids=jnp.where(too_long[:, None], -1, flat),
        count=jnp.where(too_long, 0, cnt).astype(jnp.int32),
        overflow=ovf | too_long,
    )


def match_batch_auto(auto, word_ids, n_words, sys_mask, *, k=16, m=64,
                     steps=None, slots=2, take=1,
                     pack_ids=True) -> MatchResult:
    """Dispatch seam the router and delta probes call: the Pallas
    walk on TPU-class backends, the lax.scan walk everywhere else
    (CPU tests, interpret-heavy hosts). Byte parity between the two
    is pinned, so the choice is purely a performance knob — the
    ``EMQX_TPU_WALK`` env var overrides for A/B runs."""
    if walk_variant() == "pallas":
        # a forced override on a non-TPU backend runs the kernel in
        # interpret mode: slow, but byte-exact — how the CI parity
        # gate drives this exact dispatch path on CPU
        interp = jax.default_backend() not in ("tpu", "axon")
        return match_batch_pallas(
            auto, word_ids, n_words, sys_mask, k=k, m=m, steps=steps,
            slots=slots, take=take, pack_ids=pack_ids,
            interpret=interp)
    return match_batch(
        auto, word_ids, n_words, sys_mask, k=k, m=m, steps=steps,
        slots=slots, take=take, pack_ids=pack_ids)


def fetch_walk_result(res: MatchResult):
    """The walk's coalesced device→host transfer seam (parity suites,
    deep_smoke): ONE fetch materializing all three result arrays —
    the only sanctioned sync on the walk path (DP301 whitelist)."""
    ids, cnt, ovf = jax.device_get((res.ids, res.count, res.overflow))
    return np.asarray(ids), np.asarray(cnt), np.asarray(ovf)
