"""Device-side compiled ops: tokenization, CSR automaton build, the
vmapped NFA-walk matcher, and subscriber fan-out."""
