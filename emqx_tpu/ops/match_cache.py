"""Epoch-guarded device-resident publish match cache.

The publish hot loop (``emqx_broker:publish/1`` →
``emqx_trie:match/1``, SURVEY §3.1 "HOT LOOP 1") re-walks every
unique topic per batch, yet real traffic is massively repetitive:
the Zipf bench rows see the same hot topics re-walked from scratch
every tick (and EMQX itself ships a host-side route cache in front of
``emqx_router:match_routes/1`` for exactly this reason). This module
memoizes per-topic match rows in a fixed-shape HBM table so a repeat
topic costs one gather instead of an NFA walk + per-topic compaction.

Layout and contract:

  - the device table is ``int32[slots, 1 + width]``: column 0 is a
    validity/overflow flag, the rest the packed matched-filter-id row
    (-1 padded). ``slots`` is a power of two; rows never move — the
    host side owns a ``topic → slot`` index plus a per-slot epoch
    *key*, so the device never hashes strings;
  - entries are **epoch-guarded**: the key stored at insert time must
    equal the probing key exactly or the entry is a (counted) stale
    miss. The router bumps its cache revision on any filter-set
    change, rebuild, or capacity boost — wildcard filters make exact
    per-key invalidation intractable (an added ``a/+`` changes the
    match set of unboundedly many cached topics), so invalidation is
    epoch-scoped and entries self-heal by re-insert. No flush kernel
    exists or is needed. The cache itself is key-agnostic: the caller
    may hand :meth:`MatchCache.probe` one batch-wide key (whole-epoch
    invalidation, the ``cache_partitions = 1`` legacy behavior) or a
    per-topic key list (the router's partitioned epochs — each key
    carries the revision of the partition owning the topic's first
    level, so disjoint-prefix route churn leaves other partitions'
    entries valid; see docs/MATCH_CACHE.md "Partitioned epochs");
  - **overflow topics are never served from the cache**: a miss row
    whose walk overflowed is stored as an invalid marker (flag 0,
    ids all -1). A later hit on such a slot surfaces ``overflow=True``
    and the caller's exact host-oracle fallback runs, same as a fresh
    walk would have — parity by fallback, never truncation. The
    marker pins the topic to the host path only until the next epoch
    bump (route churn, compaction rebuild, k/d boost);
  - probe/insert host bookkeeping is mutex-guarded and the device
    table is updated functionally (``.at[].set`` returns a new
    array), so a concurrent reader holding the probed table snapshot
    can never observe a torn or reallocated row.

All device work is async-dispatched: probe is pure host bookkeeping,
``merge`` is one jit'd gather+scatter producing the combined
``[B_pad, width]`` id array (hits from the table, misses from the
fresh walk), ``insert`` one jit'd scatter. Nothing here ever forces a
device→host sync — the publish path's coalesced fetch stays the only
transfer.
"""

from __future__ import annotations

import functools
import threading
from typing import Any, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["MatchCache"]

#: flag column values: _VALID = cached ids are the exact match set;
#: _OVF = the walk overflowed (host fallback, match-only bound);
#: _FOVF = overflow where the match side itself was fine (the mesh
#: fan-out d bound) — merged back into (ovf, movf) so the router's
#: boost_k/boost_d signals keep their meaning across cached batches
_OVF, _VALID, _FOVF = 0, 1, 2

_MIN_PAD = 8


def _pow2(n: int, floor: int = 1) -> int:
    out = floor
    while out < n:
        out *= 2
    return out


@functools.partial(jax.jit, static_argnames=("b_pad",))
def _merge_jit(table, hit_slots, hit_pos, miss_rows, miss_ovf,
               miss_movf, miss_pos, *, b_pad: int):
    """Combined id rows + overflow flags for one batch: gather hit
    rows from the table snapshot, scatter them and the fresh miss
    rows into the ``[b_pad, width]`` output (OOB positions drop —
    that is how both pad rows and absent hits/misses vanish)."""
    S = table.shape[0]
    width = table.shape[1] - 1
    out = jnp.full((b_pad, width), -1, jnp.int32)
    ovf = jnp.zeros((b_pad,), bool)
    movf = jnp.zeros((b_pad,), bool)
    hv = table[jnp.clip(hit_slots, 0, S - 1)]
    flag = hv[:, 0]
    out = out.at[hit_pos].set(hv[:, 1:], mode="drop")
    ovf = ovf.at[hit_pos].set(flag != _VALID, mode="drop")
    movf = movf.at[hit_pos].set(flag == _OVF, mode="drop")
    out = out.at[miss_pos].set(miss_rows, mode="drop")
    ovf = ovf.at[miss_pos].set(miss_ovf | miss_movf, mode="drop")
    movf = movf.at[miss_pos].set(miss_movf, mode="drop")
    return out, ovf, movf


@jax.jit
def _insert_jit(table, idx, rows, ovf, movf):
    """Scatter fresh miss rows into their slots. Overflowed rows are
    stored as invalid markers (never as truncated results); padding
    entries carry an out-of-range index and drop."""
    flag = jnp.where(movf, _OVF, jnp.where(ovf, _FOVF, _VALID))
    rows = jnp.where((ovf | movf)[:, None], -1, rows.astype(jnp.int32))
    vals = jnp.concatenate(
        [flag.astype(jnp.int32)[:, None], rows], axis=1)
    return table.at[idx].set(vals, mode="drop")


class _Probe:
    """One batch's host-side split (returned by :meth:`MatchCache.
    probe`): hit/miss positions, assigned slots, the epoch key(s), and
    the device-table *snapshot* the hits must gather from (later
    inserts produce new arrays, so the snapshot can't be clobbered).
    ``miss_keys`` is the per-miss insert key: identical to ``key``
    under whole-epoch probing, the topic's own partitioned key when
    the caller passed per-topic keys."""

    __slots__ = ("table", "key", "hit_pos", "hit_slots", "miss_pos",
                 "miss_topics", "miss_slots", "miss_keys")

    def __init__(self, table, key) -> None:
        self.table = table
        self.key = key
        self.hit_pos: List[int] = []
        self.hit_slots: List[int] = []
        self.miss_pos: List[int] = []
        self.miss_topics: List[str] = []
        self.miss_slots: List[int] = []
        self.miss_keys: List[Any] = []


class MatchCache:
    """Fixed-shape device match-row cache with host topic index.

    ``width`` is the packed row width (``max_matches`` on one chip;
    the mesh cache concatenates ids+subs+src into one wider row).
    Eviction is a clock sweep over the slot ring: allocation cost is
    O(1) per miss and a hot entry is only displaced once the ring
    wraps — adequate for a cache whose entries are cheap to refill.
    """

    def __init__(self, slots: int, width: int) -> None:
        self.slots = _pow2(max(2, int(slots)))
        self.width = int(width)
        self._lock = threading.Lock()
        self._table = None  # lazy: int32[slots, 1 + width]
        self._index: dict = {}                     # topic -> slot
        self._slot_topic: List[Optional[str]] = [None] * self.slots
        self._slot_key: List[Any] = [None] * self.slots
        self._clock = 0
        # cumulative counters (drain_stats hands out deltas)
        self.hits = 0
        self.misses = 0
        self.inserts = 0
        self.stale = 0
        self._drained = {"hit": 0, "miss": 0, "insert": 0, "stale": 0}

    # -- host bookkeeping --------------------------------------------------

    def _table_now(self):
        if self._table is None:
            self._table = jnp.full(
                (self.slots, 1 + self.width), -1, jnp.int32)
        return self._table

    def _alloc(self, topic: str) -> int:
        s = self._clock
        self._clock = (s + 1) % self.slots
        old = self._slot_topic[s]
        if old is not None:
            self._index.pop(old, None)
        self._slot_topic[s] = topic
        self._slot_key[s] = None  # pending until insert() lands
        self._index[topic] = s
        return s

    def probe(self, topics: Sequence[str], key,
              keys: Optional[Sequence[Any]] = None) -> _Probe:
        """Split a unique-topic batch into hits (slot per topic, key
        matches) and misses (slot assigned now, marked pending — a
        crash before :meth:`insert` just leaves a permanent miss).

        ``keys`` (optional, parallel to ``topics``) overrides ``key``
        per topic: the router's partitioned-epoch probe passes one key
        per topic carrying that topic's partition revision. Omitted,
        every topic probes (and later inserts) under the single
        batch-wide ``key`` — byte-identical to the pre-partition
        behavior."""
        with self._lock:
            p = _Probe(self._table_now(), key)
            for i, t in enumerate(topics):
                k = key if keys is None else keys[i]
                s = self._index.get(t)
                if s is not None and self._slot_key[s] == k:
                    p.hit_pos.append(i)
                    p.hit_slots.append(s)
                    continue
                if s is not None:
                    if self._slot_key[s] is not None:
                        self.stale += 1  # pending slots aren't stale
                    self._slot_key[s] = None
                else:
                    s = self._alloc(t)
                p.miss_pos.append(i)
                p.miss_topics.append(t)
                p.miss_slots.append(s)
                p.miss_keys.append(k)
            self.hits += len(p.hit_pos)
            self.misses += len(p.miss_pos)
            return p

    # -- device ops --------------------------------------------------------

    def insert(self, probe: _Probe, rows, ovf, movf=None) -> None:
        """Store the fresh walk results for ``probe``'s misses.

        ``rows`` is the (possibly batch-padded) ``[Mb, width]`` device
        result; rows past the real miss count drop via OOB indices.
        ``ovf`` rows store invalid markers, never truncated ids."""
        n = len(probe.miss_slots)
        if n == 0:
            return
        mb = int(rows.shape[0])
        idx = np.full((mb,), self.slots, np.int32)  # OOB pad -> drop
        idx[:n] = probe.miss_slots
        if movf is None:
            movf = ovf
        with self._lock:
            self._table = _insert_jit(self._table_now(), idx, rows,
                                      ovf, movf)
            for s, t, k in zip(probe.miss_slots, probe.miss_topics,
                               probe.miss_keys):
                # skip slots another batch's clock sweep reassigned
                if self._slot_topic[s] == t:
                    self._slot_key[s] = k
            self.inserts += n

    def merge(self, b_pad: int, probe: _Probe, miss_rows=None,
              miss_ovf=None, miss_movf=None):
        """One jit'd gather+scatter producing the batch's combined
        ``(ids[b_pad, width], ovf[b_pad], movf[b_pad])`` device
        arrays. Pass the miss walk outputs (or nothing when the batch
        fully hit)."""
        hb = _pow2(max(len(probe.hit_pos), 1), _MIN_PAD)
        hit_slots = np.zeros((hb,), np.int32)
        hit_pos = np.full((hb,), b_pad, np.int32)  # OOB pad -> drop
        if probe.hit_pos:
            hit_slots[:len(probe.hit_slots)] = probe.hit_slots
            hit_pos[:len(probe.hit_pos)] = probe.hit_pos
        if miss_rows is None:
            miss_rows = jnp.full((1, self.width), -1, jnp.int32)
            miss_ovf = jnp.zeros((1,), bool)
            miss_movf = jnp.zeros((1,), bool)
        elif miss_movf is None:
            miss_movf = miss_ovf
        mb = int(miss_rows.shape[0])
        miss_pos = np.full((mb,), b_pad, np.int32)
        miss_pos[:len(probe.miss_pos)] = probe.miss_pos
        return _merge_jit(probe.table, hit_slots, hit_pos, miss_rows,
                          miss_ovf, miss_movf, miss_pos, b_pad=b_pad)

    # -- introspection -----------------------------------------------------

    def entries(self) -> int:
        return len(self._index)

    def stats(self) -> dict:
        """Cumulative counters (+ hit rate) — bench/introspection."""
        total = self.hits + self.misses
        return {
            "hit": self.hits, "miss": self.misses,
            "insert": self.inserts, "stale": self.stale,
            "entries": self.entries(),
            "hit_rate": (self.hits / total) if total else 0.0,
        }

    def drain_stats(self) -> dict:
        """Counter deltas since the previous drain (the metrics-fold
        contract, mirroring ``Router.drain_device_stats``)."""
        with self._lock:
            cur = {"hit": self.hits, "miss": self.misses,
                   "insert": self.inserts, "stale": self.stale}
            out = {k: cur[k] - self._drained[k] for k in cur}
            self._drained = cur
            return out
