"""O(delta) automaton maintenance: patch instead of re-flatten.

The reference's trie insert/delete touches O(topic depth) Mnesia rows
(src/emqx_trie.erl:82-116). Round 1 re-flattened the whole trie on
any route change — O(all filters) under the router lock (the round-1
verdict's churn-stall finding). This module restores O(depth):

  - a **host mirror** of the device automaton (the dense columns +
    the bucketed 2-choice edge hash) is the patching authority;
  - ``insert``/``delete`` walk the filter's words through the mirror,
    appending states into the padded capacity and placing new edges
    into free hash slots (bounded cuckoo evictions), exactly the
    structure a fresh flatten would produce — only the state-id
    *order* differs, which the kernel never observes;
  - every host mutation queues a device update; :func:`apply_updates`
    replays the queue as functional ``.at[].set`` ops — the result is
    a **new** device automaton swapped in atomically while matchers
    holding the old one keep running (true double buffering);
  - ``delete`` is a tombstone (terminal id cleared, path kept). A
    full re-flatten happens only on capacity overflow or when
    tombstones dominate — amortized O(1) per mutation.

Update queues drain in fixed-size chunks padded with out-of-range
indices (``mode="drop"``), so XLA compiles the scatter exactly once.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Tuple

import jax
import numpy as np

from emqx_tpu import topic as T
from emqx_tpu.ops.csr import _BUCKET, Automaton, hash_mix

_OOB = np.int32(2**30)  # out-of-range pad index -> .set(mode="drop")
_MAX_EVICT = 64


class PatchOverflow(Exception):
    """Capacity exhausted or eviction bound hit: caller must
    re-flatten (with doubled capacity). ``kind`` is the structure
    that overflowed: "state" or "edge"."""

    def __init__(self, kind: str, msg: Optional[str] = None) -> None:
        super().__init__(msg or f"{kind} capacity")
        self.kind = kind


class AutoPatcher:
    """Host mirror + device-update queue for one automaton buffer
    generation. Recreated from each full flatten."""

    def __init__(self, auto: Automaton,
                 intern: Callable[[str], int]) -> None:
        # numpy copies = the patching authority (device arrays are
        # immutable snapshots of this state + queued updates)
        self.plus_child = np.array(auto.plus_child)
        self.hash_filter = np.array(auto.hash_filter)
        self.end_filter = np.array(auto.end_filter)
        self.ht_state = np.array(auto.ht_state)
        self.ht_word = np.array(auto.ht_word)
        self.ht_child = np.array(auto.ht_child)
        self.seed = np.uint32(np.asarray(auto.ht_seed)[0])
        self.n_states = int(auto.n_states)
        self.n_edges = int(auto.n_edges)
        self.s_cap = int(auto.plus_child.shape[0])
        self.e_cap = int(auto.edge_word.shape[0])
        self.nb = int(auto.ht_state.shape[0])
        self.intern = intern
        self.tombstones = 0
        # a PatchOverflow mid-insert leaves the mirror with a dangling
        # prefix (states/edges allocated for the words already walked).
        # That partial state must never reach the device: the patcher
        # marks itself broken and the owner re-flattens (discarding
        # mirror + queue) before any further patch or apply.
        self.broken = False
        # pending device updates
        self._col: List[Tuple[int, int, int]] = []  # (col, idx, val)
        self._ht: List[Tuple[int, int, int, int, int]] = []  # b,s,st,w,ch

    # -- host-mirror edge hash ops ----------------------------------------

    def _buckets(self, state: int, word: int) -> Tuple[int, int]:
        with np.errstate(over="ignore"):
            h1, h2 = hash_mix(np.array(state, np.int32),
                              np.array(word, np.int32), self.seed)
        mask = np.uint32(self.nb - 1)
        return int(h1 & mask), int(h2 & mask)

    def _ht_lookup(self, state: int, word: int) -> int:
        b1, b2 = self._buckets(state, word)
        for b in (b1, b2):
            row = np.nonzero((self.ht_state[b] == state)
                             & (self.ht_word[b] == word))[0]
            if len(row):
                return int(self.ht_child[b, row[0]])
        return -1

    def _ht_insert(self, state: int, word: int, child: int) -> None:
        """Place one edge; cuckoo-evict on full buckets. Transactional:
        on failure every displaced edge is restored (losing a victim
        would silently break an existing filter) and PatchOverflow
        tells the caller to re-flatten."""
        if self.n_edges >= self.e_cap:
            raise PatchOverflow("edge")
        undo: List[Tuple[int, int, int, int, int]] = []  # b,slot,s,w,c
        moves: List[Tuple[int, int, int, int, int]] = []

        def place(b: int, slot: int, s: int, w: int, c: int) -> None:
            undo.append((b, slot, int(self.ht_state[b, slot]),
                         int(self.ht_word[b, slot]),
                         int(self.ht_child[b, slot])))
            self.ht_state[b, slot] = s
            self.ht_word[b, slot] = w
            self.ht_child[b, slot] = c
            moves.append((b, slot, s, w, c))

        cs, cw, cc = state, word, child
        cb, _ = self._buckets(cs, cw)
        for step in range(_MAX_EVICT):
            free = np.nonzero(self.ht_state[cb] < 0)[0]
            if len(free):
                place(cb, int(free[0]), cs, cw, cc)
                self._ht.extend(moves)
                self.n_edges += 1
                return
            alt1, alt2 = self._buckets(cs, cw)
            other = alt2 if cb == alt1 else alt1
            if len(np.nonzero(self.ht_state[other] < 0)[0]):
                cb = other
                continue
            # both buckets full: evict a rotating victim from cb
            victim = step % _BUCKET
            vs, vw, vc = (int(self.ht_state[cb, victim]),
                          int(self.ht_word[cb, victim]),
                          int(self.ht_child[cb, victim]))
            place(cb, victim, cs, cw, cc)
            cs, cw, cc = vs, vw, vc
            a1, a2 = self._buckets(cs, cw)
            cb = a2 if cb == a1 else a1
        for b, slot, s, w, c in reversed(undo):
            self.ht_state[b, slot] = s
            self.ht_word[b, slot] = w
            self.ht_child[b, slot] = c
        raise PatchOverflow("edge", "eviction bound")

    # -- column ops --------------------------------------------------------

    _PLUS, _HASHF, _ENDF = 0, 1, 2

    def _set_col(self, col: int, idx: int, val: int) -> None:
        [self.plus_child, self.hash_filter, self.end_filter][col][idx] = val
        self._col.append((col, idx, val))

    def _new_state(self) -> int:
        if self.n_states >= self.s_cap:
            raise PatchOverflow("state")
        sid = self.n_states
        self.n_states += 1
        return sid

    # -- public API --------------------------------------------------------

    def insert(self, filter_: str, fid: int) -> None:
        """Add ``filter_`` terminating with filter id ``fid``.

        Raises :class:`PatchOverflow` when a re-flatten is needed. A
        mid-walk overflow (a deeper word hitting state/edge capacity
        after earlier words already allocated) leaves a dangling
        prefix in the mirror; the patcher then flips :attr:`broken`
        and refuses all further work until the owner re-flattens —
        the partial mutations can never reach the device."""
        if self.broken:
            raise PatchOverflow("state", "patcher broken")
        state = 0
        try:
            for w in T.words(filter_):
                if w == T.HASH:  # '#' is a leaf collapsed into parent
                    self._set_col(self._HASHF, state, fid)
                    return
                if w == T.PLUS:
                    child = int(self.plus_child[state])
                    if child < 0:
                        child = self._new_state()
                        self._set_col(self._PLUS, state, child)
                    state = child
                else:
                    wid = self.intern(w)
                    child = self._ht_lookup(state, wid)
                    if child < 0:
                        child = self._new_state()
                        self._ht_insert(state, wid, child)
                    state = child
            self._set_col(self._ENDF, state, fid)
        except PatchOverflow:
            self.broken = True
            raise

    def delete(self, filter_: str) -> bool:
        """Tombstone ``filter_``'s terminal marker; the path stays
        (compacted by the next full flatten). False = not found."""
        if self.broken:
            raise PatchOverflow("state", "patcher broken")
        state = 0
        ws = T.words(filter_)
        for i, w in enumerate(ws):
            if w == T.HASH:
                if int(self.hash_filter[state]) < 0:
                    return False
                self._set_col(self._HASHF, state, -1)
                self.tombstones += 1
                return True
            if w == T.PLUS:
                state = int(self.plus_child[state])
            else:
                state = self._ht_lookup(state, self.intern(w))
            if state < 0:
                return False
        if int(self.end_filter[state]) < 0:
            return False
        self._set_col(self._ENDF, state, -1)
        self.tombstones += 1
        return True

    def needs_compaction(self, live_filters: int) -> bool:
        return self.tombstones > max(1024, live_filters)

    # -- device replay -----------------------------------------------------

    @property
    def dirty(self) -> bool:
        return bool(self._col or self._ht)

    def apply_updates(self, auto: Automaton) -> Automaton:
        """Replay queued host mutations onto the device automaton,
        returning a NEW automaton (old buffers untouched — matchers
        holding them are safe; the caller swaps atomically).

        Updates go in FIXED-size chunks (padded with out-of-range
        indices, ``mode="drop"``): the scatter jits exactly once and
        is reused for every drain — variable pow2 padding would pay a
        fresh XLA compile per new queue size (measured as a 40x p99
        spike in the churn bench)."""
        assert not self.broken, \
            "partial mutations must not reach the device (re-flatten)"
        if not self.dirty:
            return auto
        for chunk in self._drain_chunks():
            auto = _apply_jit(auto, *chunk)
        return auto._replace(n_states=self.n_states,
                             n_edges=self.n_edges)

    def _drain_deduped(self):
        """Consume + dedup the raw queues by index, last write wins:
        repeated indices inside one ``.at[].set`` chunk apply in
        implementation-defined order (a delete+re-add of the same
        filter, or a cuckoo slot written twice, could otherwise
        resurrect the stale value on device)."""
        col, self._col = self._col, []
        ht, self._ht = self._ht, []
        col_d = {(c, idx): val for c, idx, val in col}
        ht_d = {(b, s): (st, w, ch) for b, s, st, w, ch in ht}
        return ([(c, i, v) for (c, i), v in col_d.items()],
                [(b, s, st, w, ch) for (b, s), (st, w, ch)
                 in ht_d.items()])

    def _drain_chunks(self):
        """Consume the update queues as fixed-size padded chunks."""
        col, ht = self._drain_deduped()
        while col or ht:
            # largest ladder rung the remaining backlog fills: a big
            # idle-accumulated queue drains in few passes instead of
            # ceil(K/128) full-capacity copies
            rem = max(len(col), len(ht))
            n = _CHUNKS[-1]  # smallest rung is the floor
            for size in _CHUNKS:
                if rem >= size:
                    n = size
                    break
            c_part, col = col[:n], col[n:]
            h_part, ht = ht[:n], ht[n:]
            ci = np.full((3, n), _OOB, dtype=np.int32)
            cv = np.zeros((3, n), dtype=np.int32)
            counts = [0, 0, 0]
            for c, idx, val in c_part:
                ci[c, counts[c]] = idx
                cv[c, counts[c]] = val
                counts[c] += 1
            hb = np.full((n,), _OOB, dtype=np.int32)
            hs = np.zeros((n,), dtype=np.int32)
            hsv = np.zeros((n,), dtype=np.int32)
            hwv = np.zeros((n,), dtype=np.int32)
            hcv = np.zeros((n,), dtype=np.int32)
            for i, (b, s, st, w, ch) in enumerate(h_part):
                hb[i], hs[i], hsv[i], hwv[i], hcv[i] = b, s, st, w, ch
            yield ci, cv, hb, hs, hsv, hwv, hcv


# drain chunk ladder, largest first: bounded compile count (one
# specialization per rung), small steady-state pad, few passes for
# a large idle-accumulated backlog
_CHUNKS = (32768, 4096, 128)


@jax.jit
def _apply_jit(auto: Automaton, ci, cv, hb, hs, hsv, hwv, hcv):
    upd = dict(
        plus_child=auto.plus_child.at[ci[0]].set(cv[0], mode="drop"),
        hash_filter=auto.hash_filter.at[ci[1]].set(cv[1], mode="drop"),
        end_filter=auto.end_filter.at[ci[2]].set(cv[2], mode="drop"),
        ht_state=auto.ht_state.at[hb, hs].set(hsv, mode="drop"),
        ht_word=auto.ht_word.at[hb, hs].set(hwv, mode="drop"),
        ht_child=auto.ht_child.at[hb, hs].set(hcv, mode="drop"),
    )
    # the packed mirrors the match kernel actually gathers from must
    # see the same mutations (layout: see csr.pack_tables)
    if auto.ht_packed is not None:
        upd["ht_packed"] = (
            auto.ht_packed
            .at[hb, hs].set(hsv, mode="drop")
            .at[hb, hs + 4].set(hwv, mode="drop")
            .at[hb, hs + 8].set(hcv, mode="drop"))
    if auto.node_packed is not None:
        npk = auto.node_packed
        for c in range(3):
            npk = npk.at[ci[c], c].set(cv[c], mode="drop")
        upd["node_packed"] = npk
    return auto._replace(**upd)


def apply_stacked_multi(patchers, stacked):
    """Drain EVERY listed ``(shard_row, patcher)``'s queue into the
    stacked sharded automaton with SHARED chunks — one scatter pass
    per chunk regardless of how many shards are dirty (each
    ``.at[].set`` copy-on-writes the whole stacked buffer, so a
    per-shard loop would pay T full copies for a T-shard storm).
    Entries carry their shard row as an extra index column."""
    col = []  # (t, col, idx, val)
    ht = []   # (t, b, slot, state, word, child)
    for t, p in patchers:
        assert not p.broken, \
            "partial mutations must not reach the device (re-flatten)"
        c_, h_ = p._drain_deduped()
        col.extend((t, c, i, v) for c, i, v in c_)
        ht.extend((t, b, s, st, w, ch) for b, s, st, w, ch in h_)
    while col or ht:
        rem = max(len(col), len(ht))
        n = _CHUNKS[-1]
        for size in _CHUNKS:
            if rem >= size:
                n = size
                break
        c_part, col = col[:n], col[n:]
        h_part, ht = ht[:n], ht[n:]
        ti = np.zeros((3, n), dtype=np.int32)
        ci = np.full((3, n), _OOB, dtype=np.int32)
        cv = np.zeros((3, n), dtype=np.int32)
        counts = [0, 0, 0]
        for t, c, idx, val in c_part:
            ti[c, counts[c]] = t
            ci[c, counts[c]] = idx
            cv[c, counts[c]] = val
            counts[c] += 1
        th = np.zeros((n,), dtype=np.int32)
        hb = np.full((n,), _OOB, dtype=np.int32)
        hs = np.zeros((n,), dtype=np.int32)
        hsv = np.zeros((n,), dtype=np.int32)
        hwv = np.zeros((n,), dtype=np.int32)
        hcv = np.zeros((n,), dtype=np.int32)
        for i, (t, b, s, st, w, ch) in enumerate(h_part):
            th[i], hb[i], hs[i] = t, b, s
            hsv[i], hwv[i], hcv[i] = st, w, ch
        stacked = _apply_jit_stacked(stacked, ti, ci, cv, th, hb, hs,
                                     hsv, hwv, hcv)
    return stacked


@jax.jit
def _apply_jit_stacked(stacked, ti, ci, cv, th, hb, hs, hsv, hwv, hcv):
    """The stacked-shard form of :func:`_apply_jit`: scatter one
    chunk into ``[T, ...]`` arrays with a per-entry shard row (only
    the columns the match kernel reads — the CSR edge arrays are
    rebuild inputs, never patched). Pad entries keep the OOB index
    convention (any out-of-bounds index drops the write)."""
    upd = dict(
        plus_child=stacked.plus_child.at[ti[0], ci[0]].set(
            cv[0], mode="drop"),
        hash_filter=stacked.hash_filter.at[ti[1], ci[1]].set(
            cv[1], mode="drop"),
        end_filter=stacked.end_filter.at[ti[2], ci[2]].set(
            cv[2], mode="drop"),
        ht_state=stacked.ht_state.at[th, hb, hs].set(hsv, mode="drop"),
        ht_word=stacked.ht_word.at[th, hb, hs].set(hwv, mode="drop"),
        ht_child=stacked.ht_child.at[th, hb, hs].set(hcv, mode="drop"),
        ht_packed=(stacked.ht_packed
                   .at[th, hb, hs].set(hsv, mode="drop")
                   .at[th, hb, hs + 4].set(hwv, mode="drop")
                   .at[th, hb, hs + 8].set(hcv, mode="drop")),
    )
    npk = stacked.node_packed
    for c in range(3):
        npk = npk.at[ti[c], ci[c], c].set(cv[c], mode="drop")
    upd["node_packed"] = npk
    return stacked._replace(**upd)
