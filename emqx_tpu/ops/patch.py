"""O(delta) automaton maintenance: patch instead of re-flatten.

The reference's trie insert/delete touches O(topic depth) Mnesia rows
(src/emqx_trie.erl:82-116). Round 1 re-flattened the whole trie on
any route change — O(all filters) under the router lock (the round-1
verdict's churn-stall finding). This module restores O(depth) against
the *compressed* walk tables (:mod:`emqx_tpu.ops.csr`):

  - a **host mirror** of the device tables (``wt`` edge-hash rows +
    ``node2`` state columns) is the patching authority;
  - ``insert``/``delete`` walk the filter's words through the mirror,
    following multi-word edges with exact chain comparison. A filter
    that diverges mid-chain **splits** the edge: the existing slot is
    rewritten to end at a new interior state and the chain remainder
    is re-inserted as its own edge — O(1) slot writes, no subtree
    touch (new states/edges land in the padded capacity, exactly the
    structure a fresh compress would produce up to state order, which
    the kernel never observes);
  - every host mutation queues a device update; :func:`apply_updates`
    replays the queue as functional ``.at[].set`` ops — the result is
    a **new** device automaton swapped in atomically while matchers
    holding the old one keep running (true double buffering);
  - ``delete`` is a tombstone (terminal id cleared, path kept);
  - hop accounting: a split lengthens one walk path, so the mirror
    bumps ``hops_for_level`` (clamped at the uncompressed bound
    ``d+1``) — the router picks the new step count up on its next
    call (one cached recompile, exact fallback meanwhile via the
    kernel's residual-overflow check). A full re-flatten happens only
    on capacity overflow or when tombstones/splits dominate —
    amortized O(1) per mutation.

Update queues drain in fixed-size chunks padded with out-of-range
indices (``mode="drop"``), so XLA compiles the scatter exactly once.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Tuple

import jax
import numpy as np

from emqx_tpu import topic as T
from emqx_tpu.ops.csr import (CW_PAD, NARROW_SLOT, WIDE_SLOT, Automaton,
                              hash_mix)

_OOB = np.int32(2**30)  # out-of-range pad index -> .set(mode="drop")
_MAX_EVICT = 64


class PatchOverflow(Exception):
    """Capacity exhausted or eviction bound hit: caller must
    re-flatten (with doubled capacity). ``kind`` is the structure
    that overflowed: "state" or "edge"."""

    def __init__(self, kind: str, msg: Optional[str] = None) -> None:
        super().__init__(msg or f"{kind} capacity")
        self.kind = kind


class AutoPatcher:
    """Host mirror + device-update queue for one automaton buffer
    generation. Recreated from each full flatten."""

    def __init__(self, auto: Automaton,
                 intern: Callable[[str], int]) -> None:
        # numpy copies = the patching authority (device arrays are
        # immutable snapshots of this state + queued updates)
        self.wt = np.array(auto.wt)
        self.node2 = np.array(auto.node2)
        self.hop = np.array(auto.v2_hop)
        self.depth = np.array(auto.v2_depth)
        self.hops_for_level = np.array(auto.hops_for_level)
        self.seed = np.uint32(np.asarray(auto.wt_seed)[0])
        self.slots = int(auto.wt_slots)
        self.take = int(auto.wt_take)
        self.sw = WIDE_SLOT if self.take > 1 else NARROW_SLOT
        self.n_states = int(auto.v2_states)
        self.n_edges = int(auto.v2_edges)
        self.s_cap = int(auto.node2.shape[0])
        self.nb = int(auto.wt.shape[0])
        # fill bound: same ≤50% discipline the builder sizes for
        self.e_cap = self.nb * self.slots // 2
        self.intern = intern
        self.tombstones = 0
        self.splits = 0
        self.hops_grown = False  # steps bound changed since flatten
        # host-fallback matches observed while the hop bound is stale
        # (a split bumps only the direct child's hop, so descendants'
        # values run one low and hops_for_level can under-grow —
        # correctness holds via the kernel's residual-overflow
        # fallback, but hot deep topics then pin to the host oracle;
        # counting those fallbacks as a compaction trigger rebuilds
        # the automaton long before 1024 splits accumulate)
        self.hop_fallbacks = 0
        # a PatchOverflow mid-insert leaves the mirror with a dangling
        # prefix (states/edges allocated for the words already walked).
        # That partial state must never reach the device: the patcher
        # marks itself broken and the owner re-flattens (discarding
        # mirror + queue) before any further patch or apply.
        self.broken = False
        # pending device updates
        self._col: List[Tuple[int, int, int]] = []  # (col, idx, val)
        self._slot: List[Tuple[int, int]] = []      # (bucket, slot)

    # -- host-mirror edge hash ops ----------------------------------------

    def _buckets(self, state: int, word: int) -> Tuple[int, int]:
        with np.errstate(over="ignore"):
            h1, h2 = hash_mix(np.array(state, np.int32),
                              np.array(word, np.int32), self.seed)
        mask = np.uint32(self.nb - 1)
        return int(h1 & mask), int(h2 & mask)

    def _slot_view(self, b: int, s: int) -> np.ndarray:
        return self.wt[b, s * self.sw:(s + 1) * self.sw]

    def _ht_find(self, state: int, word: int):
        """(bucket, slot) of the edge keyed (state, word); None if
        absent."""
        b1, b2 = self._buckets(state, word)
        for b in (b1, b2):
            for s in range(self.slots):
                v = self._slot_view(b, s)
                if v[0] == state and v[1] == word:
                    return b, s
        return None

    def _edge_fields(self, b: int, s: int):
        """(take, child, chain_words) of the slot. The chain words
        are COPIED — a split rewrites the slot and then reads the
        original tail, so a live view would alias the clobber."""
        v = self._slot_view(b, s)
        if self.take > 1:
            return int(v[2]), int(v[3]), v[4:4 + self.take - 1].copy()
        return 1, int(v[2]), v[:0]

    def _make_row(self, state: int, word: int, take: int, child: int,
                  cw) -> np.ndarray:
        row = np.full(self.sw, -1, np.int32)
        if self.take > 1:
            row[0], row[1], row[2], row[3] = state, word, take, child
            row[4:4 + self.take - 1] = CW_PAD
            if take > 1:
                row[4:4 + take - 1] = cw[:take - 1]
        else:
            row[0], row[1], row[2] = state, word, child
        return row

    def _write_slot(self, b: int, s: int, row: np.ndarray) -> None:
        self.wt[b, s * self.sw:(s + 1) * self.sw] = row
        self._slot.append((b, s))

    def _ht_insert(self, row: np.ndarray) -> None:
        """Place one edge row; cuckoo-evict on full buckets.
        Transactional: on failure every displaced edge is restored
        (losing a victim would silently break an existing filter) and
        PatchOverflow tells the caller to re-flatten."""
        if self.n_edges >= self.e_cap:
            raise PatchOverflow("edge")
        undo: List[Tuple[int, int, np.ndarray]] = []

        def place(b: int, s: int, r: np.ndarray) -> None:
            undo.append((b, s, self._slot_view(b, s).copy()))
            self._write_slot(b, s, r)

        cur = row
        cb, _ = self._buckets(int(cur[0]), int(cur[1]))
        for step in range(_MAX_EVICT):
            free = [s for s in range(self.slots)
                    if self._slot_view(cb, s)[0] < 0]
            if free:
                place(cb, free[0], cur)
                self.n_edges += 1
                return
            alt1, alt2 = self._buckets(int(cur[0]), int(cur[1]))
            other = alt2 if cb == alt1 else alt1
            if any(self._slot_view(other, s)[0] < 0
                   for s in range(self.slots)):
                cb = other
                continue
            victim = step % self.slots
            vrow = self._slot_view(cb, victim).copy()
            place(cb, victim, cur)
            cur = vrow
            a1, a2 = self._buckets(int(cur[0]), int(cur[1]))
            cb = a2 if cb == a1 else a1
        for b, s, r in reversed(undo):
            self.wt[b, s * self.sw:(s + 1) * self.sw] = r
            self._slot.append((b, s))
        raise PatchOverflow("edge", "eviction bound")

    # -- column / state ops ------------------------------------------------

    _PLUS, _HASHF, _ENDF = 0, 1, 2

    def _set_col(self, col: int, idx: int, val: int) -> None:
        self.node2[idx, col] = val
        self._col.append((col, idx, val))

    def _new_state(self, depth: int, hop: int) -> int:
        if self.n_states >= self.s_cap:
            raise PatchOverflow("state")
        sid = self.n_states
        self.n_states += 1
        self.hop[sid] = hop
        self.depth[sid] = depth
        self._note_hops(depth, hop)
        return sid

    def _note_hops(self, depth: int, hop: int) -> None:
        """Keep the step bound ≥ hop+1 for every batch depth ≥ depth
        (monotone array; clamped at the uncompressed bound d+1)."""
        hl = self.hops_for_level
        if depth >= len(hl):
            # extension: past the old max depth the walk can always
            # fall back to one hop per extra level
            d_ext = np.arange(len(hl), depth + 1, dtype=np.int64)
            ext = np.minimum(int(hl[-1]) + (d_ext - (len(hl) - 1)),
                             d_ext + 1)
            hl = np.concatenate([hl, ext.astype(hl.dtype)])
            self.hops_for_level = hl
            self.hops_grown = True
        idx = np.arange(len(hl))
        want = np.where(idx >= depth, hop + 1, 0)
        grown = np.maximum(hl, np.minimum(want, idx + 1)).astype(hl.dtype)
        if not np.array_equal(grown, hl):
            self.hops_for_level = grown
            self.hops_grown = True

    def _bump_hops_from(self, depth: int) -> None:
        """A split made every path through depth ≥ ``depth`` one hop
        longer; bump the whole tail (clamped at d+1) — cheaper and
        safer than renumbering the subtree's hop values."""
        hl = self.hops_for_level
        idx = np.arange(len(hl))
        grown = np.where(idx >= depth,
                         np.minimum(hl + 1, idx + 1), hl).astype(hl.dtype)
        if not np.array_equal(grown, hl):
            self.hops_for_level = grown
            self.hops_grown = True

    # -- public API --------------------------------------------------------

    def insert(self, filter_: str, fid: int) -> None:
        """Add ``filter_`` terminating with filter id ``fid``.

        Raises :class:`PatchOverflow` when a re-flatten is needed. A
        mid-walk overflow leaves a dangling prefix in the mirror; the
        patcher then flips :attr:`broken` and refuses all further
        work until the owner re-flattens — the partial mutations can
        never reach the device."""
        if self.broken:
            raise PatchOverflow("state", "patcher broken")
        words = T.words(filter_)
        state = 0
        i = 0
        try:
            while i < len(words):
                w = words[i]
                if w == T.HASH:  # '#' is a leaf collapsed into parent
                    self._set_col(self._HASHF, state, fid)
                    return
                if w == T.PLUS:
                    child = int(self.node2[state, self._PLUS])
                    if child < 0:
                        child = self._new_state(
                            i + 1, int(self.hop[state]) + 1)
                        self._set_col(self._PLUS, state, child)
                    state = child
                    i += 1
                    continue
                wid = self.intern(w)
                found = self._ht_find(state, wid)
                if found is None:
                    # fresh chain: consume the maximal literal run in
                    # compressed hops (exactly what a flatten builds)
                    run = 1
                    while (i + run < len(words)
                           and words[i + run] not in (T.PLUS, T.HASH)
                           and run < self.take):
                        run += 1
                    cw = np.array([self.intern(x)
                                   for x in words[i + 1:i + run]],
                                  np.int32)
                    child = self._new_state(
                        i + run, int(self.hop[state]) + 1)
                    self._ht_insert(self._make_row(
                        state, wid, run, child, cw))
                    state = child
                    i += run
                    continue
                b, s = found
                take_e, child_e, cw_e = self._edge_fields(b, s)
                # longest shared prefix of the edge's words vs ours
                match = 1
                while match < take_e:
                    j = i + match
                    if (j >= len(words)
                            or words[j] in (T.PLUS, T.HASH)
                            or self.intern(words[j]) != int(
                                cw_e[match - 1])):
                        break
                    match += 1
                if match == take_e:
                    state = child_e
                    i += take_e
                    continue
                # split: interior state at the divergence point
                mid = self._new_state(i + match,
                                      int(self.hop[state]) + 1)
                self._write_slot(b, s, self._make_row(
                    state, wid, match, mid, cw_e))
                self._ht_insert(self._make_row(
                    mid, int(cw_e[match - 1]), take_e - match,
                    child_e, cw_e[match:]))
                self.splits += 1
                # the old child (and its whole subtree) is now one hop
                # deeper; bump the bound tail rather than renumbering
                self.hop[child_e] += 1
                self._bump_hops_from(int(self.depth[mid]))
                state = mid
                i += match
            self._set_col(self._ENDF, state, fid)
        except PatchOverflow:
            self.broken = True
            raise

    def _walk(self, words) -> int:
        """Follow ``words`` through the mirror; -1 if the path is
        absent. Returns the terminal state id."""
        state = 0
        i = 0
        while i < len(words):
            w = words[i]
            if w == T.PLUS:
                state = int(self.node2[state, self._PLUS])
                if state < 0:
                    return -1
                i += 1
                continue
            found = self._ht_find(state, self.intern(w))
            if found is None:
                return -1
            take_e, child_e, cw_e = self._edge_fields(*found)
            for t in range(take_e - 1):
                j = i + 1 + t
                if (j >= len(words) or words[j] in (T.PLUS, T.HASH)
                        or self.intern(words[j]) != int(cw_e[t])):
                    return -1
            state = child_e
            i += take_e
        return state

    def delete(self, filter_: str) -> bool:
        """Tombstone ``filter_``'s terminal marker; the path stays
        (compacted by the next full flatten). False = not found."""
        if self.broken:
            raise PatchOverflow("state", "patcher broken")
        ws = T.words(filter_)
        if ws and ws[-1] == T.HASH:
            state = self._walk(ws[:-1])
            if state < 0 or int(self.node2[state, self._HASHF]) < 0:
                return False
            self._set_col(self._HASHF, state, -1)
        else:
            state = self._walk(ws)
            if state < 0 or int(self.node2[state, self._ENDF]) < 0:
                return False
            self._set_col(self._ENDF, state, -1)
        self.tombstones += 1
        return True

    def note_hop_fallbacks(self, n: int) -> None:
        """Record ``n`` host-fallback matches. Counted only while the
        hop bound has grown since the flatten (the stale-hop regime):
        overflow from an undersized active set is ``boost_k``'s
        problem, not a rebuild trigger."""
        if self.hops_grown:
            self.hop_fallbacks += n

    def needs_compaction(self, live_filters: int) -> bool:
        """Tombstones, accumulated splits, OR stale-hop host
        fallbacks dominate: the automaton is still correct, just
        wasteful/slower — rebuild off-stream."""
        bound = max(1024, live_filters)
        return self.tombstones > bound or self.splits > bound \
            or self.hop_fallbacks > bound

    # -- device replay -----------------------------------------------------

    @property
    def dirty(self) -> bool:
        return bool(self._col or self._slot)

    @property
    def queued(self) -> int:
        """Pending device updates (the router's drain-batch signal)."""
        return len(self._col) + len(self._slot)

    def apply_updates(self, auto: Automaton) -> Automaton:
        """Replay queued host mutations onto the device automaton,
        returning a NEW automaton (old buffers untouched — matchers
        holding them are safe; the caller swaps atomically).

        Updates go in FIXED-size chunks (padded with out-of-range
        indices, ``mode="drop"``): the scatter jits exactly once and
        is reused for every drain — variable pow2 padding would pay a
        fresh XLA compile per new queue size (measured as a 40x p99
        spike in the churn bench)."""
        assert not self.broken, \
            "partial mutations must not reach the device (re-flatten)"
        if not self.dirty:
            return auto
        for chunk in self._drain_chunks():
            auto = _apply_jit(auto, *chunk)
        return auto._replace(v2_states=self.n_states,
                             v2_edges=self.n_edges)

    def _drain_deduped(self):
        """Consume + dedup the raw queues, last write wins: repeated
        indices inside one ``.at[].set`` chunk apply in
        implementation-defined order (a delete+re-add of the same
        filter, or a cuckoo slot written twice, could otherwise
        resurrect the stale value on device). Slot updates read the
        mirror's CURRENT row — later host writes to the same slot are
        naturally folded."""
        col, self._col = self._col, []
        sl, self._slot = self._slot, []
        col_d = {(c, idx): val for c, idx, val in col}
        sl_d = {}
        for b, s in sl:
            sl_d[(b, s)] = self._slot_view(b, s).copy()
        return ([(c, i, v) for (c, i), v in col_d.items()],
                [(b, s, row) for (b, s), row in sl_d.items()])

    def _drain_chunks(self):
        """Consume the update queues as fixed-size padded chunks."""
        col, sl = self._drain_deduped()
        while col or sl:
            rem = max(len(col), len(sl))
            n = _CHUNKS[-1]
            for size in _CHUNKS:
                if rem >= size:
                    n = size
                    break
            c_part, col = col[:n], col[n:]
            s_part, sl = sl[:n], sl[n:]
            ci = np.full((3, n), _OOB, dtype=np.int32)
            cv = np.zeros((3, n), dtype=np.int32)
            counts = [0, 0, 0]
            for c, idx, val in c_part:
                ci[c, counts[c]] = idx
                cv[c, counts[c]] = val
                counts[c] += 1
            sb = np.full((n,), _OOB, dtype=np.int32)
            so = np.zeros((n,), dtype=np.int32)
            sv = np.zeros((n, self.sw), dtype=np.int32)
            for i, (b, s, row) in enumerate(s_part):
                sb[i] = b
                so[i] = s * self.sw
                sv[i] = row
            yield ci, cv, sb, so, sv


# drain chunk ladder, largest first: bounded compile count (one
# specialization per rung), small steady-state pad, few passes for
# a large idle-accumulated backlog. Floor 512 ≥ the router's
# patch_drain_batch so a mutator-paid drain is ONE scatter pass —
# every .at[].set chunk copy-on-writes the full table buffers, so
# chunk count, not chunk size, is the cost that matters.
_CHUNKS = (32768, 4096, 512)


@jax.jit
def _apply_jit(auto: Automaton, ci, cv, sb, so, sv):
    node2 = auto.node2
    for c in range(3):
        node2 = node2.at[ci[c], c].set(cv[c], mode="drop")
    sw = sv.shape[1]
    wt = auto.wt.at[sb[:, None],
                    so[:, None] + np.arange(sw)[None, :]].set(
        sv, mode="drop")
    return auto._replace(node2=node2, wt=wt)


def apply_stacked_multi(patchers, stacked):
    """Drain EVERY listed ``(shard_row, patcher)``'s queue into the
    stacked sharded automaton with SHARED chunks — one scatter pass
    per chunk regardless of how many shards are dirty (each
    ``.at[].set`` copy-on-writes the whole stacked buffer, so a
    per-shard loop would pay T full copies for a T-shard storm).
    Entries carry their shard row as an extra index column."""
    col = []  # (t, col, idx, val)
    sl = []   # (t, bucket, base, row)
    sw = None
    for t, p in patchers:
        assert not p.broken, \
            "partial mutations must not reach the device (re-flatten)"
        sw = p.sw
        c_, s_ = p._drain_deduped()
        col.extend((t, c, i, v) for c, i, v in c_)
        sl.extend((t, b, s * p.sw, row) for b, s, row in s_)
    while col or sl:
        rem = max(len(col), len(sl))
        n = _CHUNKS[-1]
        for size in _CHUNKS:
            if rem >= size:
                n = size
                break
        c_part, col = col[:n], col[n:]
        s_part, sl = sl[:n], sl[n:]
        ti = np.zeros((3, n), dtype=np.int32)
        ci = np.full((3, n), _OOB, dtype=np.int32)
        cv = np.zeros((3, n), dtype=np.int32)
        counts = [0, 0, 0]
        for t, c, idx, val in c_part:
            ti[c, counts[c]] = t
            ci[c, counts[c]] = idx
            cv[c, counts[c]] = val
            counts[c] += 1
        st = np.zeros((n,), dtype=np.int32)
        sb = np.full((n,), _OOB, dtype=np.int32)
        so = np.zeros((n,), dtype=np.int32)
        sv = np.zeros((n, sw), dtype=np.int32)
        for i, (t, b, base, row) in enumerate(s_part):
            st[i], sb[i], so[i] = t, b, base
            sv[i] = row
        stacked = _apply_jit_stacked(stacked, ti, ci, cv, st, sb, so, sv)
    return stacked


@jax.jit
def _apply_jit_stacked(stacked, ti, ci, cv, st, sb, so, sv):
    """The stacked-shard form of :func:`_apply_jit`: scatter one
    chunk into ``[T, ...]`` arrays with a per-entry shard row. Pad
    entries keep the OOB index convention (any out-of-bounds index
    drops the write)."""
    node2 = stacked.node2
    for c in range(3):
        node2 = node2.at[ti[c], ci[c], c].set(cv[c], mode="drop")
    sw = sv.shape[1]
    wt = stacked.wt.at[st[:, None], sb[:, None],
                       so[:, None] + np.arange(sw)[None, :]].set(
        sv, mode="drop")
    return stacked._replace(node2=node2, wt=wt)
