"""ctypes binding for the native C++ runtime (word table, batch
encoder, trie + CSR flattener, host-side oracle match).

The library is built on demand from ``native/emqx_native.cpp`` with
g++ (no pybind11 in this image — the C API + ctypes keeps the binding
dependency-free). When the toolchain or .so is unavailable every
caller falls back to the pure-Python implementations, so the native
path is a strict accelerator, not a requirement.
"""

from __future__ import annotations

import ctypes as C
import logging
import os
import subprocess
import threading
from typing import Optional, Sequence, Tuple

import numpy as np

log = logging.getLogger("emqx_tpu.native")

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_SRC_DIR = os.path.join(_REPO, "native")
_SO = os.path.join(_SRC_DIR, "libemqx_native.so")

_lib = None
_lib_lock = threading.Lock()
_build_failed = False

_i16p = np.ctypeslib.ndpointer(np.int16, flags="C_CONTIGUOUS")
_i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
_i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
_u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")


def _build() -> bool:
    src = os.path.join(_SRC_DIR, "emqx_native.cpp")
    if not os.path.exists(src):
        return False
    try:
        subprocess.run(["make", "-C", _SRC_DIR], check=True,
                       capture_output=True, timeout=120)
        return os.path.exists(_SO)
    except Exception as e:
        log.warning("native build failed: %s", e)
        return False


def _stale() -> bool:
    src = os.path.join(_SRC_DIR, "emqx_native.cpp")
    try:
        return (not os.path.exists(_SO)
                or os.path.getmtime(_SO) < os.path.getmtime(src))
    except OSError:
        return not os.path.exists(_SO)


def load_library():
    """The shared library, (re)building it if missing or older than
    the source; None on failure."""
    global _lib, _build_failed
    with _lib_lock:
        if _lib is not None or _build_failed:
            return _lib
        if _stale() and not _build() and not os.path.exists(_SO):
            _build_failed = True
            return None
        lib = C.CDLL(_SO)
        lib.wt_new.restype = C.c_void_p
        lib.wt_free.argtypes = [C.c_void_p]
        lib.wt_size.argtypes = [C.c_void_p]
        lib.wt_size.restype = C.c_int32
        lib.wt_intern.argtypes = [C.c_void_p, C.c_char_p, C.c_int32]
        lib.wt_intern.restype = C.c_int32
        lib.wt_lookup.argtypes = [C.c_void_p, C.c_char_p, C.c_int32]
        lib.wt_lookup.restype = C.c_int32
        lib.wt_word_at.argtypes = [C.c_void_p, C.c_int32, C.c_char_p,
                                   C.c_int32]
        lib.wt_word_at.restype = C.c_int32
        lib.encode_topics.argtypes = [
            C.c_void_p, C.c_char_p, _i64p, C.c_int32, C.c_int32,
            _i32p, _i32p, _u8p]
        lib.trie_new.argtypes = [C.c_void_p]
        lib.trie_new.restype = C.c_void_p
        lib.trie_free.argtypes = [C.c_void_p]
        lib.trie_num_filters.argtypes = [C.c_void_p]
        lib.trie_num_filters.restype = C.c_int32
        lib.trie_insert.argtypes = [C.c_void_p, C.c_char_p, C.c_int32,
                                    C.c_int32]
        lib.trie_insert.restype = C.c_int32
        lib.trie_delete.argtypes = [C.c_void_p, C.c_char_p, C.c_int32]
        lib.trie_delete.restype = C.c_int32
        lib.trie_counts.argtypes = [C.c_void_p,
                                    C.POINTER(C.c_int64),
                                    C.POINTER(C.c_int64)]
        lib.trie_counts_scan.argtypes = [C.c_void_p,
                                         C.POINTER(C.c_int64),
                                         C.POINTER(C.c_int64)]
        lib.trie_flatten.argtypes = [
            C.c_void_p, C.c_int64, C.c_int64, _i32p, _i32p, _i32p,
            _i32p, _i32p, _i32p]
        lib.trie_flatten.restype = C.c_int64
        lib.mqtt_scan.argtypes = [C.c_char_p, C.c_int64, C.c_int64,
                                  C.c_int32, C.POINTER(C.c_int32),
                                  C.POINTER(C.c_int64)]
        lib.mqtt_scan.restype = C.c_int32
        lib.trie_match.argtypes = [C.c_void_p, C.c_char_p, C.c_int32,
                                   _i32p, C.c_int32]
        lib.trie_match.restype = C.c_int32
        try:
            # stateful per-connection frame parser (absent in a
            # pre-rebuild .so: connections fall back to the Python
            # parser and count frame.fallback)
            lib.mqtt_parser_new.argtypes = [C.c_int64]
            lib.mqtt_parser_new.restype = C.c_void_p
            lib.mqtt_parser_free.argtypes = [C.c_void_p]
            lib.mqtt_parser_pending.argtypes = [C.c_void_p]
            lib.mqtt_parser_pending.restype = C.c_int64
            lib.mqtt_parser_feed.argtypes = [
                C.c_void_p, C.c_char_p, C.c_int64, C.c_int32,
                C.POINTER(C.c_int32), C.POINTER(C.c_int64)]
            lib.mqtt_parser_feed.restype = C.c_int32
            lib.mqtt_parser_consume.argtypes = [C.c_void_p, C.c_int64]
            lib.has_mqtt_parser = True
        except AttributeError:
            lib.has_mqtt_parser = False
        try:
            # level compression (absent in a pre-rebuild .so: the
            # flatten then compresses in numpy, same result)
            lib.csr_compress.argtypes = [
                _i32p, _i32p, _i32p, _i32p, _i32p, _i32p,
                C.c_int64, C.c_int32, C.c_int64, C.c_int64, C.c_int64,
                _i32p, _i32p, _i32p, _i32p, _i32p,
                _i32p, _i16p, _i16p, _i32p, _i64p]
            lib.csr_compress.restype = C.c_int32
            lib.has_csr_compress = True
        except AttributeError:
            lib.has_csr_compress = False
        _lib = lib
        return _lib


def available() -> bool:
    return load_library() is not None


_SCAN_CAP = 512  # frames per scan call (the parser loops on more)
_scan_tls = threading.local()


def mqtt_scan(buf, max_size: int):
    """Scan MQTT frames out of ``buf`` (bytes-like) with the C
    scanner. Returns ``(flat int list [n*7], n, consumed, err,
    err_size)``; err: 0 ok, -1 malformed varint, -2 frame over
    ``max_size`` (with its total in err_size). None when the native
    library is absent (callers use the Python framing loop).

    Scratch buffers are per-thread and reused: a parser feed runs
    this on every socket read, so per-call allocation is the
    difference between helping and hurting the single-frame path."""
    lib = load_library()
    if lib is None:
        return None
    scratch = getattr(_scan_tls, "v", None)
    if scratch is None:
        scratch = ((C.c_int32 * (_SCAN_CAP * 7))(),
                   (C.c_int64 * 2)())
        _scan_tls.v = scratch
    out, state = scratch
    if isinstance(buf, bytearray):
        # zero-copy view of the parser's accumulation buffer (only
        # held for the duration of the C call)
        cbuf = (C.c_char * len(buf)).from_buffer(buf)
    else:
        cbuf = bytes(buf)
    rc = lib.mqtt_scan(cbuf, len(buf), max_size, _SCAN_CAP, out, state)
    if rc < 0:
        return [], 0, int(state[0]), int(rc), int(state[1])
    return out[: rc * 7], rc, int(state[0]), 0, 0


def has_frame_parser() -> bool:
    """True when the .so exports the stateful per-connection parser
    (the ``[node] frame = "native"`` path's availability probe)."""
    lib = load_library()
    return bool(lib is not None and lib.has_mqtt_parser)


# zero-copy read view over the handle's C-side buffer (released by
# the caller before the next feed/consume — the vector may realloc)
_view_from_memory = C.pythonapi.PyMemoryView_FromMemory
_view_from_memory.restype = C.py_object
_view_from_memory.argtypes = [C.c_void_p, C.c_ssize_t, C.c_int]
_PyBUF_READ = 0x100


class FrameHandle:
    """Raw ctypes surface of one per-connection C parser handle.

    Owns the retained partial-frame remainder C-side, so each socket
    read ships only its NEW bytes across the FFI boundary (the
    stateless :func:`mqtt_scan` seam re-marshalled the whole
    accumulation buffer per read — measured slower than Python).
    Packet-body semantics stay in :class:`emqx_tpu.mqtt.frame.
    NativeParser`, which drives this handle."""

    __slots__ = ("_lib", "_h", "out", "state", "cap")

    def __init__(self, max_size: int) -> None:
        lib = load_library()
        if lib is None or not lib.has_mqtt_parser:
            raise RuntimeError("native frame parser unavailable")
        self._lib = lib
        self.cap = _SCAN_CAP
        self.out = (C.c_int32 * (_SCAN_CAP * 7))()
        self.state = (C.c_int64 * 5)()
        self._h = lib.mqtt_parser_new(max_size)

    def close(self) -> None:
        h, self._h = getattr(self, "_h", None), None
        if h:
            self._lib.mqtt_parser_free(h)

    __del__ = close

    def feed(self, data) -> int:
        """Append ``data``, scan, fill ``self.out``/``self.state``;
        returns the complete-frame count (never negative — scan
        errors ride ``state[4]`` after their preceding frames)."""
        if isinstance(data, bytearray):
            cbuf = (C.c_char * len(data)).from_buffer(data) \
                if data else b""
        elif isinstance(data, bytes):
            cbuf = data
        else:
            cbuf = bytes(data)
        return self._lib.mqtt_parser_feed(
            self._h, cbuf, len(data), self.cap, self.out, self.state)

    def view(self):
        """Zero-copy read-only memoryview of the buffered bytes."""
        return _view_from_memory(self.state[2], self.state[3],
                                 _PyBUF_READ)

    def consume(self, n: int) -> None:
        self._lib.mqtt_parser_consume(self._h, n)

    def pending(self) -> int:
        """Bytes currently retained (partial-frame remainder)."""
        return int(self._lib.mqtt_parser_pending(self._h))


class NativeEngine:
    """Owns a native word table + trie; produces Automaton arrays.

    Drop-in replacement for the WordTable + TrieOracle + CSR-flatten
    trio on the router's hot path. The Python TrieOracle remains the
    cross-checked oracle; parity is pinned by tests/test_native.py.
    """

    def __init__(self) -> None:
        lib = load_library()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self._wt = lib.wt_new()
        self._trie = lib.trie_new(self._wt)

    def __del__(self):
        lib = getattr(self, "_lib", None)
        if lib is not None:
            if getattr(self, "_trie", None):
                lib.trie_free(self._trie)
            if getattr(self, "_wt", None):
                lib.wt_free(self._wt)

    # -- word table -------------------------------------------------------

    def intern(self, word: str) -> int:
        b = word.encode()
        return self._lib.wt_intern(self._wt, b, len(b))

    def lookup(self, word: str) -> int:
        b = word.encode()
        return self._lib.wt_lookup(self._wt, b, len(b))

    def words(self):
        """All interned words in id order (checkpoint export)."""
        import ctypes as C
        out = []
        buf = C.create_string_buffer(4096)
        for i in range(self.vocab_size()):
            n = self._lib.wt_word_at(self._wt, i, buf, len(buf))
            if n < 0:
                break
            if n > len(buf):
                big = C.create_string_buffer(n)
                self._lib.wt_word_at(self._wt, i, big, n)
                out.append(big.raw[:n].decode())
            else:
                out.append(buf.raw[:n].decode())
        return out

    def vocab_size(self) -> int:
        return self._lib.wt_size(self._wt)

    # -- trie -------------------------------------------------------------

    def insert(self, filter_: str, filter_id: int) -> bool:
        b = filter_.encode()
        return bool(self._lib.trie_insert(self._trie, b, len(b),
                                          filter_id))

    def delete(self, filter_: str) -> bool:
        b = filter_.encode()
        return bool(self._lib.trie_delete(self._trie, b, len(b)))

    def num_filters(self) -> int:
        return self._lib.trie_num_filters(self._trie)

    def counts(self) -> Tuple[int, int]:
        """Live (states, edges) — O(1) incremental counters (the
        capacity sizing every flatten pays)."""
        s, e = C.c_int64(), C.c_int64()
        self._lib.trie_counts(self._trie, C.byref(s), C.byref(e))
        return s.value, e.value

    def counts_scan(self) -> Tuple[int, int]:
        """The full-DFS count — the parity oracle for :meth:`counts`
        (tests only; O(nodes))."""
        s, e = C.c_int64(), C.c_int64()
        self._lib.trie_counts_scan(self._trie, C.byref(s), C.byref(e))
        return s.value, e.value

    def match(self, topic: str, cap: int = 4096) -> np.ndarray:
        """All matching filter ids — grows the buffer until complete
        (the fallback path must be exact, never truncated)."""
        b = topic.encode()
        while True:
            out = np.empty((cap,), dtype=np.int32)
            n = self._lib.trie_match(self._trie, b, len(b), out, cap)
            if n < cap:
                return out[:n].copy()
            cap *= 4

    # -- flatten ----------------------------------------------------------

    def flatten(self, state_capacity: Optional[int] = None,
                edge_capacity: Optional[int] = None,
                v2_state_capacity: Optional[int] = None,
                n_buckets: Optional[int] = None,
                skip_hash: bool = False):
        from emqx_tpu.ops.csr import (Automaton, capacity_for,
                                      finalize_automaton)

        S, E = self.counts()
        s_cap = capacity_for(S, state_capacity)
        e_cap = capacity_for(E + 1, edge_capacity)
        row_ptr = np.empty((s_cap + 1,), dtype=np.int32)
        edge_word = np.empty((e_cap,), dtype=np.int32)
        edge_child = np.empty((e_cap,), dtype=np.int32)
        plus_child = np.empty((s_cap,), dtype=np.int32)
        hash_filter = np.empty((s_cap,), dtype=np.int32)
        end_filter = np.empty((s_cap,), dtype=np.int32)
        n_states = self._lib.trie_flatten(
            self._trie, s_cap, e_cap, row_ptr, edge_word, edge_child,
            plus_child, hash_filter, end_filter)
        if n_states < 0:
            raise RuntimeError("flatten capacity underestimated")
        auto = Automaton(
            row_ptr=row_ptr, edge_word=edge_word, edge_child=edge_child,
            plus_child=plus_child, hash_filter=hash_filter,
            end_filter=end_filter, n_states=int(n_states), n_edges=E)
        if skip_hash:
            return auto
        compressed = _compress_native(
            self._lib, auto, state_capacity=v2_state_capacity)
        if compressed is not None:
            from emqx_tpu.ops.csr import attach_walk_tables
            auto2, edges = compressed
            return attach_walk_tables(auto2, edges,
                                      n_buckets=n_buckets)
        return finalize_automaton(auto,
                                  state_capacity=v2_state_capacity,
                                  n_buckets=n_buckets)

    # -- batch encode -----------------------------------------------------

    def encode_batch(self, topics: Sequence[str], max_levels: int):
        return _encode_batch(self._lib, self._wt, topics, max_levels)


class ShardedNativeEngine:
    """The native engine for the MESH router: one shared word table,
    one C++ trie per trie shard (the same stable ``shard_of``
    assignment the Python builder uses), flattened into the stacked
    :class:`~emqx_tpu.parallel.sharded.ShardedAutomaton` without ever
    touching the Python TrieOracle. Round-3 left the mesh rebuild on
    the Python builder (VERDICT r3 item 8); at 1M+ filters the C++
    insert+flatten is the difference between a sub-second and a
    multi-second shard rebuild."""

    def __init__(self, n_shards: int) -> None:
        lib = load_library()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self._wt = lib.wt_new()
        self.n_shards = n_shards
        self._tries = [lib.trie_new(self._wt) for _ in range(n_shards)]

    def __del__(self):
        lib = getattr(self, "_lib", None)
        if lib is not None:
            for t in getattr(self, "_tries", []):
                if t:
                    lib.trie_free(t)
            if getattr(self, "_wt", None):
                lib.wt_free(self._wt)

    def _shard(self, filter_: str) -> int:
        from emqx_tpu.parallel.sharded import shard_of

        return shard_of(filter_, self.n_shards)

    # engine surface (same as NativeEngine) ------------------------------

    def intern(self, word: str) -> int:
        b = word.encode()
        return self._lib.wt_intern(self._wt, b, len(b))

    def words(self):
        return NativeEngine.words(self)

    def vocab_size(self) -> int:
        return self._lib.wt_size(self._wt)

    def insert(self, filter_: str, filter_id: int) -> bool:
        b = filter_.encode()
        return bool(self._lib.trie_insert(
            self._tries[self._shard(filter_)], b, len(b), filter_id))

    def delete(self, filter_: str) -> bool:
        b = filter_.encode()
        return bool(self._lib.trie_delete(
            self._tries[self._shard(filter_)], b, len(b)))

    def num_filters(self) -> int:
        return sum(self._lib.trie_num_filters(t) for t in self._tries)

    def match(self, topic: str, cap: int = 4096) -> np.ndarray:
        """Union of every shard's matches (host fallback path)."""
        b = topic.encode()
        parts = []
        for t in self._tries:
            c = cap
            while True:
                out = np.empty((c,), dtype=np.int32)
                n = self._lib.trie_match(t, b, len(b), out, c)
                if n < c:
                    parts.append(out[:n])
                    break
                c *= 4
        return np.concatenate(parts) if parts else \
            np.empty((0,), dtype=np.int32)

    def encode_batch(self, topics: Sequence[str], max_levels: int):
        return _encode_batch(self._lib, self._wt, topics, max_levels)

    # -- sharded flatten --------------------------------------------------

    def flatten_sharded(self, state_capacity: Optional[int] = None,
                        n_buckets: Optional[int] = None):
        """All shards flattened, compressed at COMMON shapes and
        stacked — the native analogue of
        ``parallel.sharded.build_sharded(..., return_parts=True)``:
        returns ``(ShardedAutomaton, parts)`` where ``parts`` are the
        per-shard host Automatons that seed the per-shard AutoPatcher
        mirrors."""
        from emqx_tpu.ops.csr import Automaton, capacity_for
        from emqx_tpu.parallel.sharded import (_stack_sharded,
                                               finalize_parts)

        counts = []
        for t in self._tries:
            s, e = C.c_int64(), C.c_int64()
            self._lib.trie_counts(t, C.byref(s), C.byref(e))
            counts.append((s.value, e.value))
        s_cap = capacity_for(max(s for s, _ in counts))
        e_cap = capacity_for(max(e for _, e in counts) + 1)
        autos = []
        for t, (_, n_e) in zip(self._tries, counts):
            row_ptr = np.empty((s_cap + 1,), dtype=np.int32)
            edge_word = np.empty((e_cap,), dtype=np.int32)
            edge_child = np.empty((e_cap,), dtype=np.int32)
            plus_child = np.empty((s_cap,), dtype=np.int32)
            hash_filter = np.empty((s_cap,), dtype=np.int32)
            end_filter = np.empty((s_cap,), dtype=np.int32)
            n_states = self._lib.trie_flatten(
                t, s_cap, e_cap, row_ptr, edge_word, edge_child,
                plus_child, hash_filter, end_filter)
            if n_states < 0:
                raise RuntimeError("flatten capacity underestimated")
            autos.append(Automaton(
                row_ptr=row_ptr, edge_word=edge_word,
                edge_child=edge_child, plus_child=plus_child,
                hash_filter=hash_filter, end_filter=end_filter,
                n_states=int(n_states), n_edges=int(n_e)))
        parts = finalize_parts(autos, state_capacity=state_capacity,
                               n_buckets=n_buckets)
        return _stack_sharded(parts), parts


def _compress_native(lib, auto, state_capacity: Optional[int] = None):
    """Level-compress ``auto`` with the C++ chain fuser.

    Returns ``(compressed_auto, V2Edges)`` byte-identical to
    ``csr.compress_automaton`` (parity pinned field-for-field by
    tests/test_walk_pallas.py::test_native_compress_parity)
    or None when the numpy path should run instead: narrow-mode tries
    (no deep chains worth fusing — the numpy narrow path is a cheap
    renumber) or a pre-rebuild .so without the symbol."""
    if not getattr(lib, "has_csr_compress", False):
        return None
    from emqx_tpu.ops.csr import (MAX_TAKE, WIDE_SLOTS, V2Edges,
                                  capacity_for)

    S = int(auto.n_states)
    E = int(auto.n_edges)
    R = MAX_TAKE
    e_cap = max(E, 1)
    e_src = np.empty(e_cap, np.int32)
    e_word = np.empty(e_cap, np.int32)
    e_take = np.empty(e_cap, np.int32)
    e_child = np.empty(e_cap, np.int32)
    e_cw = np.empty((e_cap, R - 1), np.int32)
    node2 = np.empty((S, 4), np.int32)
    v2_hop = np.empty(S, np.int16)
    v2_depth = np.empty(S, np.int16)
    hl = np.empty(S + 1, np.int32)
    info = np.zeros(4, np.int64)
    rc = lib.csr_compress(
        np.ascontiguousarray(auto.row_ptr[:S + 1], np.int32),
        np.ascontiguousarray(auto.edge_word, np.int32),
        np.ascontiguousarray(auto.edge_child, np.int32),
        np.ascontiguousarray(auto.plus_child[:S], np.int32),
        np.ascontiguousarray(auto.hash_filter[:S], np.int32),
        np.ascontiguousarray(auto.end_filter[:S], np.int32),
        S, R, e_cap, S, S + 1,
        e_src, e_word, e_take, e_child, e_cw.reshape(-1),
        node2.reshape(-1), v2_hop, v2_depth, hl, info)
    if rc != 0:
        return None
    S2, E2, maxdepth, mode = (int(x) for x in info)
    if mode != 1:
        return None
    edges = V2Edges(src=e_src[:E2].copy(), word=e_word[:E2].copy(),
                    take=e_take[:E2].copy(), child=e_child[:E2].copy(),
                    cw=e_cw[:E2].copy())
    S2_cap = capacity_for(S2, state_capacity)
    node2_p = np.full((S2_cap, 4), -1, np.int32)
    node2_p[:S2] = node2[:S2]
    hop_p = np.full(S2_cap, -1, np.int16)
    hop_p[:S2] = v2_hop[:S2]
    depth_p = np.full(S2_cap, -1, np.int16)
    depth_p[:S2] = v2_depth[:S2]
    return auto._replace(
        node2=node2_p, hops_for_level=hl[:maxdepth + 1].copy(),
        v2_hop=hop_p, v2_depth=depth_p,
        v2_states=S2, v2_edges=E2,
        wt_slots=WIDE_SLOTS, wt_take=R), edges


def _encode_batch(lib, wt, topics: Sequence[str], max_levels: int):
    n = len(topics)
    blobs = [t.encode() for t in topics]
    offsets = np.zeros((n + 1,), dtype=np.int64)
    for i, b in enumerate(blobs):
        offsets[i + 1] = offsets[i] + len(b)
    blob = b"".join(blobs)
    ids = np.empty((n, max_levels), dtype=np.int32)
    out_n = np.empty((n,), dtype=np.int32)
    sysm = np.empty((n,), dtype=np.uint8)
    lib.encode_topics(wt, blob, offsets, n, max_levels,
                      ids.reshape(-1), out_n, sysm)
    return ids, out_n, sysm.astype(bool)
