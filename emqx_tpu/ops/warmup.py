"""Kernel re-warm planning for device-loss recovery.

After :meth:`Router.rebuild_device_state` publishes fresh tables on a
fresh backend, the walk/fetch jit kernels for the batch shapes live
traffic actually uses must be executed once OFF the hot path — the
first post-recovery publish batch must pay zero compile
(docs/ROBUSTNESS.md "Device-loss recovery"; the devloss bench's
``first_batch_p99_ms`` column is the proof).

This module is pure host planning (no jax imports, nothing to sync —
the device work happens in ``Broker.warm_device_path``, which drives
the REAL ``_begin_device``/``_fetch_device`` seams over the batches
planned here, so exactly the production kernel set compiles: encode →
walk (cache-miss shape) → pack → fan-out expand → bundle → fetch).

Synthetic warm topics are rooted at ``"\\x00devloss"`` — no real
filter matches them (MQTT topics cannot contain NUL), so a warm batch
delivers nothing, and their match-cache entries are ordinary slots
that age out under the clock sweep.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Tuple

#: bound on warm batches per recovery: the floor bucket plus the
#: largest observed live buckets (each is one compile family)
MAX_WARM_BUCKETS = 4


def warm_buckets(observed: Iterable[int], min_batch: int,
                 cap: int = MAX_WARM_BUCKETS) -> List[int]:
    """The padded-batch buckets worth warming: the configured floor
    bucket (every small batch lands there) plus the largest buckets
    live traffic was actually seen using (``Broker._pack_budgets``
    keys — the budget table is learned per bucket, so its key set IS
    the observed shape set)."""
    buckets = sorted({int(b) for b in observed if int(b) > 0}
                     | {int(min_batch)})
    return buckets[-max(1, cap):]


def warm_topics(bucket: int, min_batch: int,
                levels: int = 4) -> List[str]:
    """A unique-topic list whose padded dispatch lands exactly in
    ``bucket``: the dispatch pads to the smallest power-of-two bucket
    ≥ the topic count (floored at ``min_batch``), so ``bucket//2 + 1``
    topics select ``bucket`` for any bucket above the floor.

    ``levels`` pins the batch's level-bucket shape: the walk slices
    its level axis to the batch's deepest topic (``depth_bucket``)
    and compiles per resulting depth, so the FIRST topic carries
    exactly ``levels`` levels — one deep spine is enough to select
    the compile family, the rest stay short."""
    n = 1 if bucket <= min_batch else bucket // 2 + 1
    out = ["\x00devloss/warm/%d/%d" % (bucket, i) for i in range(n)]
    spine = ["\x00devloss", "warm", str(bucket), "0"][:max(2, levels)]
    spine += ["d"] * (max(2, levels) - len(spine))
    out[0] = "/".join(spine)
    return out


def warm_plan(observed: Iterable[int], min_batch: int,
              cap: int = MAX_WARM_BUCKETS,
              levels: Iterable[int] = ()
              ) -> List[Tuple[int, List[str]]]:
    """``(bucket, topics)`` warm batches, smallest bucket first (the
    floor bucket compiles fastest — recovery reaches "some shape is
    warm" as early as possible). ``levels`` is the set of observed
    level-bucket shapes (``Router.observed_levels``) — each is its
    own compile family, so every bucket replays every depth; the
    compressed-walk deep buckets (16-level spines, ISSUE 16) warm
    here exactly like the shallow ones. Empty = the historical
    4-level shape only."""
    lvls = sorted({int(l) for l in levels if int(l) >= 2}) or [4]
    return [(b, warm_topics(b, min_batch, lv))
            for b in warm_buckets(observed, min_batch, cap)
            for lv in lvls]


def stamp_first_batch(record: Dict[str, object],
                      first_batch_ms: float) -> None:
    """Fold the first post-recovery batch latency into a devloss
    bench record (one seam so the bench and the smoke assert the
    same field name)."""
    record["first_batch_p99_ms"] = round(float(first_batch_ms), 3)
