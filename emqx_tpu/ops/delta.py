"""Online delta automaton: storm-rate route churn without touching
the main walk tables.

The patch-in-place path (:mod:`emqx_tpu.ops.patch`) keeps the main
automaton current by splitting edges and queueing device scatters per
mutation — O(depth) per op, but a sustained reconnect storm decays
the walk (splits lengthen paths, stale hop bounds pin hot topics to
the host oracle) and every drain copy-on-writes the full walk tables.
The reference broker never pays any of this: its trie writes are
O(topic depth) Mnesia ops and reads never degrade
(src/emqx_trie.erl:82-116).

This module is the churn-plane answer (ROADMAP item 5): batch route
**adds** into a small *side-automaton* probed alongside the main walk
(two-probe, terminal-id union), and handle **deletes** as a
post-match tombstone-id mask — the main tables stay byte-identical
between compactions, so the walk never decays no matter how hard the
route set churns. The side structures are tiny (bounded by
``[matcher] delta_max_filters``), so:

  - inserts patch the side-automaton's own :class:`AutoPatcher`
    mirror — the copy-on-write apply touches kilobytes, not the main
    tables' hundreds of megabytes;
  - the side-automaton is always **narrow** (take ≡ 1): no chains,
    therefore no splits and no hop decay — a filter's walk cost is
    exactly its depth, and the automaton rebuilds from its own small
    trie in milliseconds when capacity doubles;
  - deletes never touch any automaton: the fid lands in a tombstone
    set, compiled into a device mask applied to the merged match ids
    (``-1``-ing them before the fan-out gathers — the id→filter map's
    ``None`` translation remains the exact host-side backstop).

A background compaction folds the delta into the main tables
(``Router`` flattens its persistent trie OFF-lock and swaps under a
short lock); the delta's ordered mutation **log** is what makes that
seamless — mutations landing mid-flatten replay into a fresh delta
via :meth:`DeltaAutomaton.split_after`, so the published
(main, delta) pair is exact on both sides of the swap. See
docs/DELTA.md.
"""

from __future__ import annotations

import functools
from typing import Callable, Dict, List, NamedTuple, Optional, Set, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from emqx_tpu import topic as T
from emqx_tpu.oracle import TrieOracle
from emqx_tpu.ops.csr import (Automaton, build_automaton, device_view,
                              finalize_automaton)
from emqx_tpu.ops.match import match_batch
from emqx_tpu.ops.patch import AutoPatcher, PatchOverflow


class _InternTable:
    """Adapter giving :func:`build_automaton` the one method it uses
    (``intern``) over whichever engine owns the word table — the
    delta MUST share the main automaton's word ids (both walks
    consume the same encoded batch)."""

    __slots__ = ("intern",)

    def __init__(self, intern: Callable[[str], int]) -> None:
        self.intern = intern


class DeltaSnapshot(NamedTuple):
    """One consistent, immutable view for lock-free matchers. ``auto``
    is None when there are no pending adds (tombstone-only delta);
    ``mask`` is None when there are no tombstones."""

    auto: Optional[Automaton]     # walkable device view (narrow)
    hops: Optional[np.ndarray]    # host hops_for_level of the view
    k: int                        # active-set lanes the delta walk needs
    mask: Optional[jax.Array]     # bool[cap] True = tombstoned fid
    version: int
    n_pending: int

    def steps_for(self, lb: int) -> int:
        hl = self.hops
        if hl is None or len(hl) == 0:
            return 1
        return int(hl[min(lb, len(hl) - 1)])


class DeltaAutomaton:
    """Pending route mutations relative to the last main flatten.

    All mutation methods are called under the router's lock (the
    word-table lock additionally guards interning, same as the main
    patch path); :meth:`snapshot` publishes an immutable view."""

    def __init__(self, intern: Callable[[str], int],
                 use_device: bool = True) -> None:
        self.intern = intern
        self.use_device = use_device
        self.trie = TrieOracle()          # pending adds, host authority
        self.fids: Dict[str, int] = {}    # pending filter → fid
        self.tombs: Set[int] = set()      # fids tombstoned in MAIN tables
        self.tomb_filters: Set[str] = set()
        #: ordered mutation log — the replay seam the off-lock
        #: compaction splits at (docs/DELTA.md "Mutation-log replay")
        self.log: List[Tuple[str, str, int]] = []
        self.has_plus = False
        self.version = 0
        self._host_auto: Optional[Automaton] = None
        self._dev_auto: Optional[Automaton] = None
        self._patcher: Optional[AutoPatcher] = None
        self._flatten_dirty = False   # side-tables need a re-flatten
        self._grow = 1                # capacity growth on overflow
        self._mask_dirty = True
        self._mask_dev: Optional[jax.Array] = None
        self._mask_cap = 0
        self._snap: Optional[DeltaSnapshot] = None
        self._snap_key = None

    # -- mutation (under the router lock) ---------------------------------

    @property
    def n_pending(self) -> int:
        return len(self.fids)

    @property
    def n_tombstones(self) -> int:
        return len(self.tombs)

    def mark(self) -> int:
        """Current log position — compaction records it at freeze
        time; entries before it are folded into the flatten."""
        return len(self.log)

    def add(self, filter_: str, fid: int) -> None:
        self.trie.insert(filter_)
        self.fids[filter_] = fid
        self.log.append(("+", filter_, fid))
        if T.PLUS in T.words(filter_):
            self.has_plus = True
        self.version += 1
        if self._flatten_dirty or self._patcher is None:
            self._flatten_dirty = True
            return
        try:
            self._patcher.insert(filter_, fid)
        except PatchOverflow:
            # side tables are small: just re-flatten them (ms) at the
            # next snapshot, with doubled capacity
            self._grow = 2
            self._flatten_dirty = True

    def delete(self, filter_: str, fid: int) -> None:
        """A route delete: retract a pending add, or tombstone a
        main-table fid."""
        self.log.append(("-", filter_, fid))
        self.version += 1
        if filter_ in self.fids:
            self.trie.delete(filter_)
            del self.fids[filter_]
            if not self._flatten_dirty and self._patcher is not None:
                try:
                    self._patcher.delete(filter_)
                except PatchOverflow:
                    self._flatten_dirty = True
            return
        self.tombs.add(fid)
        self.tomb_filters.add(filter_)
        self._mask_dirty = True

    def split_after(self, mark: int) -> "Optional[DeltaAutomaton]":
        """A fresh delta holding only the mutations after ``mark`` —
        everything before it is in the new main tables (the off-lock
        compaction flattened the trie they had already been applied
        to). Replays with live semantics, so an add+delete pair
        inside the window cancels and a delete of a pre-mark add
        becomes a tombstone against the NEW tables."""
        fresh = DeltaAutomaton(self.intern, self.use_device)
        for op, f, fid in self.log[mark:]:
            if op == "+":
                fresh.add(f, fid)
            else:
                fresh.delete(f, fid)
        if not fresh.fids and not fresh.tombs:
            return None
        return fresh

    def needs_compaction(self, max_filters: int, live: int) -> bool:
        """Pending adds at the configured bound, or tombstones
        dominating the live set — fold into the main tables."""
        return (len(self.fids) >= max_filters
                or len(self.tombs) > max(1024, live))

    def invalidate_device(self) -> None:
        """Device-loss recovery (docs/ROBUSTNESS.md): the staged
        device view — side walk tables, tombstone mask, cached
        snapshot — references a dead backend's HBM. Drop it all and
        mark dirty; the next :meth:`snapshot` re-flattens the side
        trie and re-stages the mask on the fresh backend. Host
        authority (trie, fids, tombs, log) is untouched."""
        self._host_auto = None
        self._dev_auto = None
        self._patcher = None
        self._flatten_dirty = bool(self.fids)
        self._mask_dev = None
        self._mask_cap = 0
        self._mask_dirty = bool(self.tombs)
        self._snap = None
        self._snap_key = None

    # -- host match (oracle-fallback union) -------------------------------

    def host_match(self, topic: str) -> List[str]:
        """Pending-add filters matching ``topic`` (host side of the
        two-probe union; tombstones are the caller's id-map ``None``
        translation)."""
        if not self.fids:
            return []
        return self.trie.match(topic)

    # -- snapshot (side tables + tombstone mask) --------------------------

    def _flatten(self) -> None:
        cap = nb = None
        if self._host_auto is not None \
                and self._host_auto.node2 is not None:
            cap = self._host_auto.node2.shape[0] * self._grow
            nb = self._host_auto.wt.shape[0] * self._grow
        table = _InternTable(self.intern)
        base = build_automaton(self.trie, self.fids, table,
                               skip_hash=True)
        host = finalize_automaton(base, force_mode="narrow",
                                  state_capacity=cap, n_buckets=nb)
        self._host_auto = host
        auto = device_view(host)
        if self.use_device:
            auto = jax.device_put(auto)
        self._dev_auto = auto
        self._patcher = AutoPatcher(host, self.intern)
        self._flatten_dirty = False
        self._grow = 1

    def snapshot(self, id_cap: int, k_cap: int) -> DeltaSnapshot:
        """The current immutable view (cached by version; call under
        the router lock). ``id_cap`` sizes the tombstone mask (the
        id→filter map length); ``k_cap`` is the active-set capacity a
        wildcard-bearing delta walk gets."""
        key = (self.version, id_cap > self._mask_cap, k_cap)
        if self._snap is not None and self._snap_key == key \
                and not self._flatten_dirty and not self._mask_dirty \
                and (self._patcher is None or not self._patcher.dirty):
            return self._snap
        auto = hops = None
        if self.fids:
            if self._flatten_dirty or self._host_auto is None:
                self._flatten()
            elif self._patcher is not None and self._patcher.dirty:
                self._dev_auto = self._patcher.apply_updates(
                    self._dev_auto)
            auto = self._dev_auto
            hops = (self._patcher.hops_for_level
                    if self._patcher is not None
                    else self._host_auto.hops_for_level)
        if self.tombs:
            cap = self._mask_cap
            if cap < id_cap or cap == 0:
                cap = 16
                while cap < id_cap:
                    cap *= 2
            if self._mask_dirty or cap != self._mask_cap:
                m = np.zeros(cap, bool)
                m[np.fromiter(self.tombs, np.int64,
                              len(self.tombs))] = True
                self._mask_dev = jax.device_put(m) if self.use_device \
                    else jnp.asarray(m)
                self._mask_cap = cap
                self._mask_dirty = False
            mask = self._mask_dev
        else:
            mask = None
        self._snap = DeltaSnapshot(
            auto=auto, hops=hops, k=(k_cap if self.has_plus else 1),
            mask=mask, version=self.version, n_pending=len(self.fids))
        self._snap_key = key
        return self._snap


# -- two-probe device merge -------------------------------------------------


@jax.jit
def _mask_ids(ids: jax.Array, mask: jax.Array) -> jax.Array:
    """Post-match tombstone mask: ``-1`` every id whose mask bit is
    set (deleted-but-not-yet-compacted fids never reach the fan-out
    gathers)."""
    hit = mask[jnp.clip(ids, 0, mask.shape[0] - 1)]
    return jnp.where((ids >= 0) & hit, -1, ids)


@functools.partial(jax.jit, static_argnames=("m",))
def _union_packed(a: jax.Array, b: jax.Array, *, m: int):
    """Row-wise union of two packed id arrays into ``m`` slots.
    Trie terminals are disjoint between the main tables and the delta
    (a filter lives in exactly one), so union is pure packing; rows
    whose combined set exceeds ``m`` flag overflow (host fallback,
    same contract as the walk)."""
    cat = jnp.concatenate([a, b], axis=1)

    def one(row):
        valid = row >= 0
        cnt = jnp.sum(valid)
        pos = jnp.cumsum(valid) - 1
        out = jnp.full((m,), -1, row.dtype).at[
            jnp.where(valid, pos, m)].set(row, mode="drop")
        return out, cnt > m

    return jax.vmap(one)(cat)


def probe_raw(snap: DeltaSnapshot, word_ids, n_words, sys_mask,
              main_ids, main_ovf, *, m: int):
    """Two-probe merge for the RAW (``pack_ids=False``) dispatch:
    walk the side-automaton over the already-encoded batch, CONCAT
    its raw emit slots onto the main walk's (downstream packing
    subsumes the union), OR the overflows, then tombstone-mask."""
    ids, ovf = main_ids, main_ovf
    if snap.auto is not None:
        res = match_batch(
            snap.auto, word_ids, n_words, sys_mask, k=snap.k, m=m,
            pack_ids=False, steps=snap.steps_for(word_ids.shape[1]),
            slots=2, take=1)
        ids = jnp.concatenate([ids, res.ids], axis=1)
        ovf = ovf | res.overflow
    if snap.mask is not None:
        ids = _mask_ids(ids, snap.mask)
    return ids, ovf


def probe_packed(snap: DeltaSnapshot, word_ids, n_words, sys_mask,
                 main_ids, main_ovf, *, m: int):
    """Two-probe merge for the PACKED (``pack_ids=True``) dispatch —
    the match-cache miss walk: union into the fixed ``[B, m]`` row
    shape cache entries carry, then tombstone-mask."""
    ids, ovf = main_ids, main_ovf
    if snap.auto is not None:
        res = match_batch(
            snap.auto, word_ids, n_words, sys_mask, k=snap.k, m=m,
            pack_ids=True, steps=snap.steps_for(word_ids.shape[1]),
            slots=2, take=1)
        ids, u_ovf = _union_packed(ids, res.ids, m=m)
        ovf = ovf | res.overflow | u_ovf
    if snap.mask is not None:
        ids = _mask_ids(ids, snap.mask)
    return ids, ovf
