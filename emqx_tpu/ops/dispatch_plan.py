"""Batch dispatch planner: subscriber-grouped delivery tail.

The packed device results (CSR subscriber slots + bitmap union rows,
ops/pack.py) used to be walked one ``(filter, subscriber)`` pair at a
time through ``Broker._route_packed`` → ``_deliver_one`` →
``Session.deliver`` — one registry lookup, one subopts dict fetch and
one notify wakeup **per delivery**. At live fan-outs that Python walk
is the whole publish tail (BENCH ``live_socket_throughput``); the
reference's own hot loop 2 is the same walk (``emqx_broker:dispatch/2``,
src/emqx_broker.erl:283-309), and its ``emqx_batch.erl``
accumulate-then-flush idea applies to the tail as much as to ingress.

This module builds the whole batch's delivery plan with numpy on the
**already-fetched** packed arrays — no broker state, no device work —
so :meth:`~emqx_tpu.broker.Broker.publish_fetch` can run it on the
ingress executor thread:

  1. expand the CSR slices ``(f_ptr, subs_packed, src_packed)`` per
     live message (vectorized repeat/arange arithmetic, one scatter);
  2. append the bitmap-path deliveries (union-row set bits, attributed
     to their matched big filters);
  3. stable-argsort the ``(sub_id, fid, row)`` triples **by
     subscriber** and cut group boundaries.

Stability is the correctness keystone: triples are laid out in the
legacy walk order (row-major; CSR slots then bitmap bits within a
row), so after the stable sort every subscriber's deliveries are in
exactly the order the per-delivery walk would have produced — the
grouped enqueue is a permutation **across** subscribers only, which no
connection can observe. The broker then resolves each subscriber's
session once per batch, hands it its whole group in one
``deliver_many`` call, and fires one notify wakeup per connection per
batch.

A batch with any match/bitmap capacity overflow row plans as ``None``
and takes the legacy per-delivery path unchanged (overflow rows host-
re-match mid-walk; interleaving that with grouped delivery would
reorder a subscriber's stream). Overflow self-corrects via boost_k /
pack-budget growth, so steady state always plans.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from emqx_tpu.broker_helper import unpack_sids


class DispatchPlan:
    """One batch's subscriber-grouped delivery order.

    Per-delivery sequences (all length ``n_deliveries``, sorted so
    each subscriber's deliveries are contiguous and in legacy walk
    order). The grouping math is numpy; the stored fields are plain
    Python lists because the delivery loop consumes them one element
    at a time, and list indexing + int dict hashing beat numpy
    scalar access several-fold there:

      - ``fids``  matched filter id (automaton snapshot id)
      - ``rows``  live-row index into ``PendingBatch.live``

    Groups: ``g_ptr[g]:g_ptr[g+1]`` slices group ``g``; ``g_sids[g]``
    is its subscriber id. ``n_groups`` is the chunking unit the
    ingress yields between (one group = one session's whole batch).
    """

    __slots__ = ("fids", "rows", "g_ptr", "g_sids", "n_deliveries")

    def __init__(self, sids: np.ndarray, fids: np.ndarray,
                 rows: np.ndarray) -> None:
        self.n_deliveries = int(sids.shape[0])
        if self.n_deliveries:
            order = np.argsort(sids, kind="stable")
            sids = sids[order]
            self.fids = fids[order].tolist()
            self.rows = rows[order].tolist()
            cuts = np.flatnonzero(sids[1:] != sids[:-1]) + 1
            self.g_ptr = np.concatenate(
                ([0], cuts, [self.n_deliveries])).tolist()
            self.g_sids = sids[np.concatenate(([0], cuts))].tolist()
        else:
            self.fids = self.rows = []
            self.g_ptr = [0]
            self.g_sids = []

    @property
    def n_groups(self) -> int:
        return len(self.g_sids)


def big_rows_for(ids_packed: Sequence[int], m_ptr: np.ndarray,
                 sel: np.ndarray, rows_packed: np.ndarray,
                 urows: Sequence[int], big_set: frozenset,
                 members_of) -> Dict[int, List[Tuple[int, np.ndarray]]]:
    """Per-unique-row bitmap deliveries: ``urow -> [(fid, sids)]``.

    ``members_of(fid) -> sorted int64 array`` attributes a union
    row's set bits when several big filters matched the same topic
    (the union OR'd their rows together); with a single matched big
    filter every set bit is its delivery, no membership test — the
    exact split ``Broker._deliver_big`` makes per message, hoisted to
    once per unique topic."""
    out: Dict[int, List[Tuple[int, np.ndarray]]] = {}
    if sel is None or not big_set:
        return out
    for urow in urows:
        if sel[urow] < 0:
            continue
        row_ids = ids_packed[m_ptr[urow]:m_ptr[urow + 1]]
        matched = [j for j in row_ids if j in big_set]
        if not matched:
            continue
        sids = unpack_sids(rows_packed[sel[urow]]).astype(np.int64)
        if len(matched) == 1:
            out[urow] = [(matched[0], sids)]
            continue
        parts: List[Tuple[int, np.ndarray]] = []
        for fid in matched:
            members = members_of(fid)
            parts.append((fid, sids[np.isin(sids, members,
                                            assume_unique=True)]))
        out[urow] = parts
    return out


def build_plan(inv: Sequence[int], n_uniq: int,
               ovf: np.ndarray, bovf: Optional[np.ndarray],
               f_ptr: Optional[np.ndarray],
               subs_packed: Optional[np.ndarray],
               src_packed: Optional[np.ndarray],
               big_by_urow: Dict[int, List[Tuple[int, np.ndarray]]],
               ) -> Optional[DispatchPlan]:
    """The numpy grouping pass. ``None`` = batch not plannable (a
    capacity-overflow row needs the legacy mid-walk host fallback).

    ``inv`` maps live rows to unique-topic rows; ``ovf``/``bovf`` are
    the fetched per-unique-row overflow flags; the CSR triple comes
    straight from the fetched pack (numpy, NOT the legacy ``tolist``
    copies); ``big_by_urow`` from :func:`big_rows_for`.
    """
    n_live = len(inv)
    if n_uniq and bool(ovf[:n_uniq].any()):
        return None
    if bovf is not None and n_uniq and bool(bovf[:n_uniq].any()):
        return None
    u = np.asarray(inv, dtype=np.int64)
    if f_ptr is not None:
        fp = np.asarray(f_ptr, dtype=np.int64)
        start = fp[u]
        cnt = fp[u + 1] - start
    else:
        start = cnt = np.zeros(n_live, np.int64)
    bm_cnt = np.zeros(n_live, np.int64)
    if big_by_urow:
        totals = {urow: sum(len(s) for _, s in parts)
                  for urow, parts in big_by_urow.items()}
        for r, urow in enumerate(inv):
            t = totals.get(urow)
            if t:
                bm_cnt[r] = t
    row_tot = cnt + bm_cnt
    out_ptr = np.concatenate(([0], np.cumsum(row_tot)))
    total = int(out_ptr[-1])
    sids = np.empty(total, np.int64)
    fids = np.empty(total, np.int64)
    rows = np.empty(total, np.int64)
    n_csr = int(cnt.sum())
    if n_csr:
        cum = np.concatenate(([0], np.cumsum(cnt)))
        ar = np.arange(n_csr)
        intra = ar - np.repeat(cum[:-1], cnt)
        src_idx = intra + np.repeat(start, cnt)
        dst = intra + np.repeat(out_ptr[:-1], cnt)
        sids[dst] = np.asarray(subs_packed, np.int64)[src_idx]
        fids[dst] = np.asarray(src_packed, np.int64)[src_idx]
        rows[dst] = np.repeat(np.arange(n_live), cnt)
    if big_by_urow:
        for r, urow in enumerate(inv):
            parts = big_by_urow.get(urow)
            if not parts:
                continue
            off = int(out_ptr[r] + cnt[r])
            for fid, part in parts:
                n = len(part)
                sids[off:off + n] = part
                fids[off:off + n] = fid
                rows[off:off + n] = r
                off += n
    return DispatchPlan(sids, fids, rows)
