"""Batch dispatch planner: subscriber-grouped delivery tail.

The packed device results (CSR subscriber slots + bitmap union rows,
ops/pack.py) used to be walked one ``(filter, subscriber)`` pair at a
time through ``Broker._route_packed`` → ``_deliver_one`` →
``Session.deliver`` — one registry lookup, one subopts dict fetch and
one notify wakeup **per delivery**. At live fan-outs that Python walk
is the whole publish tail (BENCH ``live_socket_throughput``); the
reference's own hot loop 2 is the same walk (``emqx_broker:dispatch/2``,
src/emqx_broker.erl:283-309), and its ``emqx_batch.erl``
accumulate-then-flush idea applies to the tail as much as to ingress.

This module builds the whole batch's delivery plan with numpy on the
**already-fetched** packed arrays — no broker state, no device work —
so :meth:`~emqx_tpu.broker.Broker.publish_fetch` can run it on the
ingress executor thread:

  1. expand the CSR slices ``(f_ptr, subs_packed, src_packed)`` per
     live message (vectorized repeat/arange arithmetic, one scatter);
  2. append the bitmap-path deliveries (union-row set bits, attributed
     to their matched big filters);
  3. stable-argsort the ``(sub_id, fid, row)`` triples **by
     subscriber** and cut group boundaries.

Stability is the correctness keystone: triples are laid out in the
legacy walk order (row-major; CSR slots then bitmap bits within a
row), so after the stable sort every subscriber's deliveries are in
exactly the order the per-delivery walk would have produced — the
grouped enqueue is a permutation **across** subscribers only, which no
connection can observe. The broker then resolves each subscriber's
session once per batch, hands it its whole group in one
``deliver_many`` call, and fires one notify wakeup per connection per
batch.

A batch with any match/bitmap capacity overflow row plans as ``None``
and takes the legacy per-delivery path unchanged (overflow rows host-
re-match mid-walk; interleaving that with grouped delivery would
reorder a subscriber's stream). Overflow self-corrects via boost_k /
pack-budget growth, so steady state always plans.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from emqx_tpu.broker_helper import unpack_sids
from emqx_tpu.mqtt.constants import MQTT_V5
from emqx_tpu.mqtt.frame import publish_template
from emqx_tpu.mqtt.frame import serialize as wire_serialize
from emqx_tpu.mqtt.packet import Publish, from_message


class DispatchPlan:
    """One batch's subscriber-grouped delivery order.

    Per-delivery sequences (all length ``n_deliveries``, sorted so
    each subscriber's deliveries are contiguous and in legacy walk
    order). The grouping math is numpy; the stored fields are plain
    Python lists because the delivery loop consumes them one element
    at a time, and list indexing + int dict hashing beat numpy
    scalar access several-fold there:

      - ``fids``  matched filter id (automaton snapshot id)
      - ``rows``  live-row index into ``PendingBatch.live``

    Groups: ``g_ptr[g]:g_ptr[g+1]`` slices group ``g``; ``g_sids[g]``
    is its subscriber id. ``n_groups`` is the chunking unit the
    ingress yields between (one group = one session's whole batch).
    """

    __slots__ = ("fids", "rows", "g_ptr", "g_sids", "n_deliveries")

    def __init__(self, sids: np.ndarray, fids: np.ndarray,
                 rows: np.ndarray) -> None:
        self.n_deliveries = int(sids.shape[0])
        if self.n_deliveries:
            order = np.argsort(sids, kind="stable")
            sids = sids[order]
            self.fids = fids[order].tolist()
            self.rows = rows[order].tolist()
            cuts = np.flatnonzero(sids[1:] != sids[:-1]) + 1
            self.g_ptr = np.concatenate(
                ([0], cuts, [self.n_deliveries])).tolist()
            self.g_sids = sids[np.concatenate(([0], cuts))].tolist()
        else:
            self.fids = self.rows = []
            self.g_ptr = [0]
            self.g_sids = []

    @property
    def n_groups(self) -> int:
        return len(self.g_sids)


#: ftab memo sentinel — a filter whose subscriber table resolved to
#: None must not be re-resolved per delivery
_NO_FTAB = object()


def preserialize_plan(plan: "DispatchPlan",
                      live: Sequence[Tuple[int, object]],
                      id_map: Sequence[Optional[str]],
                      subscribers: Dict[str, dict],
                      lookup) -> int:
    """Egress pre-serialization: collect the plan's distinct
    subscriber-filter classes, then prime each live message's wire
    caches BEFORE the finish tail runs
    (docs/DISPATCH.md "Egress pre-serialization"):

      - QoS0 broadcast deliveries share one serialized frame per
        (proto_ver, flags variant) through the message's ``_wire``
        dict — built here instead of lazily on-loop by
        ``Channel._wire_cached``;
      - QoS1/2 deliveries get a packet-id-placeholder template per
        (proto_ver, effective qos, retain, dup) in ``_wiretpl``
        (:func:`~emqx_tpu.mqtt.frame.publish_template`): the pid is
        always 2 bytes at a fixed offset, so the loop-side tail is a
        ``bytearray`` copy + 2-byte patch per subscriber.

    Per-session rewrites the template cannot carry — shared-group
    redispatch state, Subscription-Identifier, the Message-Expiry
    countdown — are detected here and skipped; those deliveries take
    the existing per-delivery serialize path unchanged.

    Runs wherever :meth:`~emqx_tpu.broker.Broker.publish_fetch` runs
    (possibly an ingress executor thread): every broker read is a
    plain dict get (GIL-atomic, same discipline as the plan build's
    member snapshot), the session hints (``proto_ver`` /
    ``wire_fast_hint``) are stamped once at CONNECT, and the primed
    caches are best-effort — a variant the finish tail needs but
    doesn't find simply builds on-loop (counted by
    ``delivery.serialize.onloop``). Returns the number of frames
    built."""
    # Pass 1 — subscriber-filter CLASSES. The wire variant a delivery
    # needs is fully determined by (proto_ver, upgrade_qos, granted
    # qos, rap) plus the message's own flags, so instead of walking
    # every (subscriber, delivery) pair — O(deliveries) Python work
    # per batch — collect the distinct classes over the plan's
    # (group, fid) pairs and build per (class, message) in pass 2.
    # Variants dedupe by cache key, so a class that happens not to
    # touch a message over-builds a frame at worst (harmless); every
    # ACTUAL delivery's variant is covered. The delivery walk itself
    # shrinks to a fid-change probe per slot.
    classes: Dict[tuple, None] = {}
    g_ptr = plan.g_ptr
    fids = plan.fids
    ftab_of: Dict[int, object] = {}
    for g in range(plan.n_groups):
        sub = lookup(plan.g_sids[g])
        if sub is None:
            continue
        ver = getattr(sub, "proto_ver", None)
        if ver is None or not getattr(sub, "wire_fast_hint", False):
            continue
        upgrade = getattr(sub, "upgrade_qos", False)
        last_fid = -1          # within a group the same fid repeats
        seen: Optional[set] = None   # row-major — catch runs cheaply
        for k in range(g_ptr[g], g_ptr[g + 1]):
            fid = fids[k]
            if fid == last_fid:
                continue
            last_fid = fid
            if seen is None:
                seen = set()
            elif fid in seen:
                continue
            seen.add(fid)
            ftab = ftab_of.get(fid)
            if ftab is None:
                flt = id_map[fid]
                ftab = (subscribers.get(flt) or _NO_FTAB) \
                    if flt is not None else _NO_FTAB
                ftab_of[fid] = ftab
            opts = ftab.get(sub) if ftab is not _NO_FTAB else None
            if opts is None or opts.share is not None \
                    or opts.subid is not None:
                continue  # per-session rewrites: slow path
            classes[(ver, upgrade, opts.qos, opts.rap)] = None
    if not classes:
        return 0
    # Pass 2 — build per (class, live message): O(classes × batch)
    # serializes, each shared by every subscriber of that variant.
    built = 0
    class_list = list(classes)
    for _i, msg in live:
        headers = msg.headers
        props = headers.get("properties")
        if props and ("Message-Expiry-Interval" in props
                      or "Subscription-Identifier" in props):
            continue  # per-delivery countdown / per-session subid
        flags = msg.flags
        mqos = msg.qos
        retain = flags.get("retain", False)
        dup = flags.get("dup", False)
        retained = bool(headers.get("retained"))
        wire = tpl = None
        for ver, upgrade, oqos, rap in class_list:
            qos = max(oqos, mqos) if upgrade else min(oqos, mqos)
            if qos == 0:
                if mqos == 0 and not retain:
                    # broadcast fast path: the ORIGINAL message is
                    # shared, its own flags key the image
                    key = (ver, 0, retain, dup)
                else:
                    # downgraded-to-QoS0 enriched copy: _enrich
                    # clears retain unless rap/retained; the qos-in-
                    # key rule keeps it apart from any QoS>0 frame
                    key = (ver, 0,
                           retain and bool(rap or retained), dup)
                if wire is None:
                    wire = headers.get("_wire")
                    if wire is None:
                        wire = headers["_wire"] = {}
                if key not in wire:
                    pub = from_message(None, msg)
                    pub.qos = 0
                    pub.retain = key[2]
                    if ver != MQTT_V5:
                        pub.properties = {}
                    wire[key] = wire_serialize(pub, ver)
                    built += 1
                continue
            key = (ver, qos,
                   retain and bool(rap or retained), dup)
            if tpl is None:
                tpl = headers.get("_wiretpl")
                if tpl is None:
                    tpl = headers["_wiretpl"] = {}
            if key not in tpl:
                pub = Publish(
                    dup=dup, qos=qos, retain=key[2], topic=msg.topic,
                    packet_id=0,
                    properties=dict(props)
                    if (ver == MQTT_V5 and props) else {},
                    payload=msg.payload)
                tpl[key] = publish_template(pub, ver)
                built += 1
    return built


def big_rows_for(ids_packed: Sequence[int], m_ptr: np.ndarray,
                 sel: np.ndarray, rows_packed: np.ndarray,
                 urows: Sequence[int], big_set: frozenset,
                 members_of) -> Dict[int, List[Tuple[int, np.ndarray]]]:
    """Per-unique-row bitmap deliveries: ``urow -> [(fid, sids)]``.

    ``members_of(fid) -> sorted int64 array`` attributes a union
    row's set bits when several big filters matched the same topic
    (the union OR'd their rows together); with a single matched big
    filter every set bit is its delivery, no membership test — the
    exact split ``Broker._deliver_big`` makes per message, hoisted to
    once per unique topic."""
    out: Dict[int, List[Tuple[int, np.ndarray]]] = {}
    if sel is None or not big_set:
        return out
    for urow in urows:
        if sel[urow] < 0:
            continue
        row_ids = ids_packed[m_ptr[urow]:m_ptr[urow + 1]]
        matched = [j for j in row_ids if j in big_set]
        if not matched:
            continue
        sids = unpack_sids(rows_packed[sel[urow]]).astype(np.int64)
        if len(matched) == 1:
            out[urow] = [(matched[0], sids)]
            continue
        parts: List[Tuple[int, np.ndarray]] = []
        for fid in matched:
            members = members_of(fid)
            parts.append((fid, sids[np.isin(sids, members,
                                            assume_unique=True)]))
        out[urow] = parts
    return out


def build_plan(inv: Sequence[int], n_uniq: int,
               ovf: np.ndarray, bovf: Optional[np.ndarray],
               f_ptr: Optional[np.ndarray],
               subs_packed: Optional[np.ndarray],
               src_packed: Optional[np.ndarray],
               big_by_urow: Dict[int, List[Tuple[int, np.ndarray]]],
               ) -> Optional[DispatchPlan]:
    """The numpy grouping pass. ``None`` = batch not plannable (a
    capacity-overflow row needs the legacy mid-walk host fallback).

    ``inv`` maps live rows to unique-topic rows; ``ovf``/``bovf`` are
    the fetched per-unique-row overflow flags; the CSR triple comes
    straight from the fetched pack (numpy, NOT the legacy ``tolist``
    copies); ``big_by_urow`` from :func:`big_rows_for`.
    """
    n_live = len(inv)
    if n_uniq and bool(ovf[:n_uniq].any()):
        return None
    if bovf is not None and n_uniq and bool(bovf[:n_uniq].any()):
        return None
    u = np.asarray(inv, dtype=np.int64)
    if f_ptr is not None:
        fp = np.asarray(f_ptr, dtype=np.int64)
        start = fp[u]
        cnt = fp[u + 1] - start
    else:
        start = cnt = np.zeros(n_live, np.int64)
    bm_cnt = np.zeros(n_live, np.int64)
    if big_by_urow:
        totals = {urow: sum(len(s) for _, s in parts)
                  for urow, parts in big_by_urow.items()}
        for r, urow in enumerate(inv):
            t = totals.get(urow)
            if t:
                bm_cnt[r] = t
    row_tot = cnt + bm_cnt
    out_ptr = np.concatenate(([0], np.cumsum(row_tot)))
    total = int(out_ptr[-1])
    sids = np.empty(total, np.int64)
    fids = np.empty(total, np.int64)
    rows = np.empty(total, np.int64)
    n_csr = int(cnt.sum())
    if n_csr:
        cum = np.concatenate(([0], np.cumsum(cnt)))
        ar = np.arange(n_csr)
        intra = ar - np.repeat(cum[:-1], cnt)
        src_idx = intra + np.repeat(start, cnt)
        dst = intra + np.repeat(out_ptr[:-1], cnt)
        sids[dst] = np.asarray(subs_packed, np.int64)[src_idx]
        fids[dst] = np.asarray(src_packed, np.int64)[src_idx]
        rows[dst] = np.repeat(np.arange(n_live), cnt)
    if big_by_urow:
        for r, urow in enumerate(inv):
            parts = big_by_urow.get(urow)
            if not parts:
                continue
            off = int(out_ptr[r] + cnt[r])
            for fid, part in parts:
                n = len(part)
                sids[off:off + n] = part
                fids[off:off + n] = fid
                rows[off:off + n] = r
                off += n
    return DispatchPlan(sids, fids, rows)
