"""Flatten the subscription trie into CSR device tables.

The reference stores the trie as two Mnesia tables — edges keyed by
``{node_id, word}`` and nodes carrying the terminal topic
(src/emqx_trie.erl:53-74, include/emqx.hrl:96-113). For the TPU the
trie becomes a static automaton in HBM:

  - literal edges:  CSR ``row_ptr[S+1]`` / ``edge_word[E]`` /
    ``edge_child[E]`` with words sorted per row (binary-searched by the
    match kernel);
  - ``+`` edges:    a dense ``plus_child[S]`` column (-1 = none);
  - ``#`` edges:    ``hash_filter[S]`` — the filter id terminating at
    the ``#`` child (``#`` is always a leaf, so the child node is
    collapsed into its filter id);
  - terminals:      ``end_filter[S]`` — filter id ending exactly at a
    state (-1 = none).

State 0 is the root. Arrays are padded to capacity (growth factor 2)
so that incremental rebuilds keep static shapes and avoid XLA
recompilation; padded rows are empty and padded edge words are
INT32_MAX sentinels.
"""

from __future__ import annotations

from typing import Dict, NamedTuple

import numpy as np

from emqx_tpu import topic as T
from emqx_tpu.oracle import TrieOracle, _Node
from emqx_tpu.ops.tokenize import WordTable

_WORD_PAD = np.int32(2**31 - 1)


class Automaton(NamedTuple):
    """CSR topic automaton (numpy or jax arrays; shapes are padded)."""

    row_ptr: np.ndarray      # int32[S_cap + 1]
    edge_word: np.ndarray    # int32[E_cap], sorted within each row
    edge_child: np.ndarray   # int32[E_cap]
    plus_child: np.ndarray   # int32[S_cap]
    hash_filter: np.ndarray  # int32[S_cap]
    end_filter: np.ndarray   # int32[S_cap]
    n_states: int            # live states (root included); static python int
    n_edges: int             # live literal edges


def capacity_for(n: int, cap: int | None = None) -> int:
    """Next power-of-two capacity ≥ n (min 16), honoring a floor."""
    c = 16
    while c < n:
        c *= 2
    if cap is not None and cap > c:
        c = cap
    return c


_capacity = capacity_for


def build_automaton(
    trie: TrieOracle,
    filter_ids: Dict[str, int],
    table: WordTable,
    state_capacity: int | None = None,
    edge_capacity: int | None = None,
) -> Automaton:
    """Flatten ``trie`` into an :class:`Automaton`.

    ``filter_ids`` maps every inserted filter to its dense route id
    (assigned by the router); ``table`` interns filter words. ``#``
    child nodes are collapsed (never walked into); ``+`` children are
    ordinary states.
    """
    # BFS assigning dense state ids; root = 0.
    states: list[_Node] = [trie.root]
    index: dict[int, int] = {id(trie.root): 0}
    edges_per_state: list[list[tuple[int, int]]] = []  # (word_id, child_state)
    plus: list[int] = []
    hashf: list[int] = []
    endf: list[int] = []

    i = 0
    while i < len(states):
        node = states[i]
        i += 1
        lits: list[tuple[int, int]] = []
        p = -1
        h = -1
        for w, child in node.children.items():
            if w == T.HASH:
                if child.filter is not None:
                    h = filter_ids[child.filter]
                continue
            sid = index.get(id(child))
            if sid is None:
                sid = len(states)
                index[id(child)] = sid
                states.append(child)
            if w == T.PLUS:
                p = sid
            else:
                lits.append((table.intern(w), sid))
        lits.sort()
        edges_per_state.append(lits)
        plus.append(p)
        hashf.append(h)
        endf.append(-1 if node.filter is None else filter_ids[node.filter])

    S = len(states)
    E = sum(len(e) for e in edges_per_state)
    S_cap = _capacity(S, state_capacity)
    E_cap = _capacity(E + 1, edge_capacity)  # +1: binary search may read [E]

    row_ptr = np.full((S_cap + 1,), E, dtype=np.int32)
    edge_word = np.full((E_cap,), _WORD_PAD, dtype=np.int32)
    edge_child = np.full((E_cap,), -1, dtype=np.int32)
    plus_child = np.full((S_cap,), -1, dtype=np.int32)
    hash_filter = np.full((S_cap,), -1, dtype=np.int32)
    end_filter = np.full((S_cap,), -1, dtype=np.int32)

    pos = 0
    for s in range(S):
        row_ptr[s] = pos
        for wid, child in edges_per_state[s]:
            edge_word[pos] = wid
            edge_child[pos] = child
            pos += 1
    row_ptr[S:] = pos  # live-end and padded rows all point at E

    plus_child[:S] = plus
    hash_filter[:S] = hashf
    end_filter[:S] = endf

    return Automaton(
        row_ptr=row_ptr,
        edge_word=edge_word,
        edge_child=edge_child,
        plus_child=plus_child,
        hash_filter=hash_filter,
        end_filter=end_filter,
        n_states=S,
        n_edges=E,
    )
