"""Flatten the subscription trie into CSR device tables.

The reference stores the trie as two Mnesia tables — edges keyed by
``{node_id, word}`` and nodes carrying the terminal topic
(src/emqx_trie.erl:53-74, include/emqx.hrl:96-113). For the TPU the
trie becomes a static automaton in HBM:

  - literal edges:  CSR ``row_ptr[S+1]`` / ``edge_word[E]`` /
    ``edge_child[E]`` with words sorted per row (binary-searched by the
    match kernel);
  - ``+`` edges:    a dense ``plus_child[S]`` column (-1 = none);
  - ``#`` edges:    ``hash_filter[S]`` — the filter id terminating at
    the ``#`` child (``#`` is always a leaf, so the child node is
    collapsed into its filter id);
  - terminals:      ``end_filter[S]`` — filter id ending exactly at a
    state (-1 = none).

State 0 is the root. Arrays are padded to capacity (growth factor 2)
so that incremental rebuilds keep static shapes and avoid XLA
recompilation; padded rows are empty and padded edge words are
INT32_MAX sentinels.
"""

from __future__ import annotations

from typing import Dict, NamedTuple

import numpy as np

from emqx_tpu import topic as T
from emqx_tpu.oracle import TrieOracle, _Node
from emqx_tpu.ops.tokenize import WordTable

_WORD_PAD = np.int32(2**31 - 1)


class Automaton(NamedTuple):
    """CSR topic automaton (numpy or jax arrays; shapes are padded).

    Literal-edge lookup has two device encodings:
      - CSR rows (``row_ptr``/``edge_word``/``edge_child``), walked by
        per-row binary search (~2·log2 E gathers per step);
      - a bucketed 2-choice hash table (``ht_*``, 4 slots per bucket)
        keyed by (state, word) — the hot-path encoding: a lookup is two
        4-wide row gathers per table (6 gathers total), independent of
        automaton size.
    The hash bucket count derives from the *edge capacity*, so
    incremental rebuilds keep every shape static (no recompiles).
    """

    row_ptr: np.ndarray      # int32[S_cap + 1]
    edge_word: np.ndarray    # int32[E_cap], sorted within each row
    edge_child: np.ndarray   # int32[E_cap]
    plus_child: np.ndarray   # int32[S_cap]
    hash_filter: np.ndarray  # int32[S_cap]
    end_filter: np.ndarray   # int32[S_cap]
    n_states: int            # live states (root included); static python int
    n_edges: int             # live literal edges
    ht_state: np.ndarray | None = None  # int32[NB, 4] (-1 = empty slot)
    ht_word: np.ndarray | None = None   # int32[NB, 4]
    ht_child: np.ndarray | None = None  # int32[NB, 4]
    ht_seed: np.ndarray | None = None   # uint32[1] — the mix seed used
    # packed mirrors for the match kernel: TPU gather cost is per ROW
    # (~flat up to width ≥24), so one wide gather replaces three
    # narrow ones — the walk drops from 9 to 3 gathers per
    # (state, level)
    ht_packed: np.ndarray | None = None    # int32[NB, 12] = s0..3|w0..3|c0..3
    node_packed: np.ndarray | None = None  # int32[S_cap, 4] = plus|hash|end|-1


def capacity_for(n: int, cap: int | None = None) -> int:
    """Next power-of-two capacity ≥ n (min 16), honoring a floor."""
    c = 16
    while c < n:
        c *= 2
    if cap is not None and cap > c:
        c = cap
    return c


_capacity = capacity_for


def build_automaton(
    trie: TrieOracle,
    filter_ids: Dict[str, int],
    table: WordTable,
    state_capacity: int | None = None,
    edge_capacity: int | None = None,
    skip_hash: bool = False,
) -> Automaton:
    """Flatten ``trie`` into an :class:`Automaton`.

    ``filter_ids`` maps every inserted filter to its dense route id
    (assigned by the router); ``table`` interns filter words. ``#``
    child nodes are collapsed (never walked into); ``+`` children are
    ordinary states.
    """
    # BFS assigning dense state ids; root = 0.
    states: list[_Node] = [trie.root]
    index: dict[int, int] = {id(trie.root): 0}
    edges_per_state: list[list[tuple[int, int]]] = []  # (word_id, child_state)
    plus: list[int] = []
    hashf: list[int] = []
    endf: list[int] = []

    i = 0
    while i < len(states):
        node = states[i]
        i += 1
        lits: list[tuple[int, int]] = []
        p = -1
        h = -1
        for w, child in node.children.items():
            if w == T.HASH:
                if child.filter is not None:
                    h = filter_ids[child.filter]
                continue
            sid = index.get(id(child))
            if sid is None:
                sid = len(states)
                index[id(child)] = sid
                states.append(child)
            if w == T.PLUS:
                p = sid
            else:
                lits.append((table.intern(w), sid))
        lits.sort()
        edges_per_state.append(lits)
        plus.append(p)
        hashf.append(h)
        endf.append(-1 if node.filter is None else filter_ids[node.filter])

    S = len(states)
    E = sum(len(e) for e in edges_per_state)
    S_cap = _capacity(S, state_capacity)
    E_cap = _capacity(E + 1, edge_capacity)  # +1: binary search may read [E]

    row_ptr = np.full((S_cap + 1,), E, dtype=np.int32)
    edge_word = np.full((E_cap,), _WORD_PAD, dtype=np.int32)
    edge_child = np.full((E_cap,), -1, dtype=np.int32)
    plus_child = np.full((S_cap,), -1, dtype=np.int32)
    hash_filter = np.full((S_cap,), -1, dtype=np.int32)
    end_filter = np.full((S_cap,), -1, dtype=np.int32)

    pos = 0
    for s in range(S):
        row_ptr[s] = pos
        for wid, child in edges_per_state[s]:
            edge_word[pos] = wid
            edge_child[pos] = child
            pos += 1
    row_ptr[S:] = pos  # live-end and padded rows all point at E

    plus_child[:S] = plus
    hash_filter[:S] = hashf
    end_filter[:S] = endf

    auto = Automaton(
        row_ptr=row_ptr,
        edge_word=edge_word,
        edge_child=edge_child,
        plus_child=plus_child,
        hash_filter=hash_filter,
        end_filter=end_filter,
        n_states=S,
        n_edges=E,
    )
    # skip_hash: sharded builds pad first, then attach with a bucket
    # count shared across shards (parallel/sharded.py:build_sharded)
    return auto if skip_hash else attach_edge_hash(auto)


# -- bucketed 2-choice edge hash ------------------------------------------

_BUCKET = 4


def hash_mix(state, word, seed):
    """The (state, word) → (h1, h2) mix — uint32 wraparound arithmetic,
    written so numpy (build) and jnp (match kernel) agree bit-for-bit."""
    s = state.astype("uint32")
    w = word.astype("uint32")
    h = s * np.uint32(0x9E3779B9) + w * np.uint32(0x85EBCA6B) + seed
    h = h ^ (h >> np.uint32(16))
    h = h * np.uint32(0x7FEB352D)
    h = h ^ (h >> np.uint32(15))
    h2 = h * np.uint32(0x846CA68B)
    h2 = h2 ^ (h2 >> np.uint32(16))
    return h, h2


def buckets_for_capacity(edge_capacity: int) -> int:
    """Bucket count giving ≤0.5 fill at full edge capacity (pow2)."""
    nb = 4
    while nb * _BUCKET < 2 * edge_capacity:
        nb *= 2
    return nb


def _greedy_place(b, avail, fill, order_keys):
    """Vectorized capacity-bounded placement of keys into buckets ``b``
    (one pass). Returns (placed_key_idx, bucket, slot, leftover_idx)."""
    order = np.argsort(b, kind="stable")
    bs = b[order]
    rank = np.arange(len(bs)) - np.searchsorted(bs, bs)
    slot = fill[bs] + rank
    ok = slot < avail
    return order_keys[order[ok]], bs[ok], slot[ok], order_keys[order[~ok]]


def build_edge_hash(
    row_ptr: np.ndarray,
    edge_word: np.ndarray,
    edge_child: np.ndarray,
    n_states: int,
    n_edges: int,
    n_buckets: int,
    max_seeds: int = 32,
):
    """(ht_state, ht_word, ht_child, ht_seed) for the live edges.

    Two vectorized greedy passes (first-choice bucket, then
    second-choice) place ~all keys; the tail is fixed up with bounded
    cuckoo evictions. On pathological seeds the whole build retries
    with the next seed (keys are unique, so success at ≤50% fill is
    essentially certain).
    """
    E = int(n_edges)
    lens = np.diff(row_ptr[: n_states + 1].astype(np.int64))
    states = np.repeat(np.arange(n_states, dtype=np.int32), lens)[:E]
    words = np.asarray(edge_word[:E], dtype=np.int32)
    children = np.asarray(edge_child[:E], dtype=np.int32)
    mask = np.uint32(n_buckets - 1)

    for seed_i in range(max_seeds):
        seed = np.uint32(0xA5A5A5A5 + 0x9E37 * seed_i)
        ht_s = np.full((n_buckets, _BUCKET), -1, dtype=np.int32)
        ht_w = np.full((n_buckets, _BUCKET), -1, dtype=np.int32)
        ht_c = np.full((n_buckets, _BUCKET), -1, dtype=np.int32)
        if E == 0:
            return ht_s, ht_w, ht_c, np.array([seed], dtype=np.uint32)
        h1, h2 = hash_mix(states, words, seed)
        b1 = (h1 & mask).astype(np.int64)
        b2 = (h2 & mask).astype(np.int64)
        fill = np.zeros((n_buckets,), dtype=np.int64)

        keys = np.arange(E, dtype=np.int64)
        placed_k, placed_b, placed_s, left = _greedy_place(
            b1, _BUCKET, fill, keys)
        np.add.at(fill, placed_b, 1)
        ht_s[placed_b, placed_s] = states[placed_k]
        ht_w[placed_b, placed_s] = words[placed_k]
        ht_c[placed_b, placed_s] = children[placed_k]
        if len(left):
            placed_k, placed_b, placed_s, left = _greedy_place(
                b2[left], _BUCKET, fill, left)
            np.add.at(fill, placed_b, 1)
            ht_s[placed_b, placed_s] = states[placed_k]
            ht_w[placed_b, placed_s] = words[placed_k]
            ht_c[placed_b, placed_s] = children[placed_k]

        # cuckoo-eviction fixup for keys whose both buckets were full
        ok = True
        for k in left:
            cs, cw, cc = int(states[k]), int(words[k]), int(children[k])
            cb = int(b1[k])
            for step in range(500):
                row = ht_s[cb]
                free = np.nonzero(row < 0)[0]
                if len(free):
                    ht_s[cb, free[0]] = cs
                    ht_w[cb, free[0]] = cw
                    ht_c[cb, free[0]] = cc
                    break
                # evict the slot this key's path rotates through
                victim = step % _BUCKET
                vs, vw, vc = (int(ht_s[cb, victim]), int(ht_w[cb, victim]),
                              int(ht_c[cb, victim]))
                ht_s[cb, victim] = cs
                ht_w[cb, victim] = cw
                ht_c[cb, victim] = cc
                cs, cw, cc = vs, vw, vc
                with np.errstate(over="ignore"):
                    # uint32 wraparound is the point of the mix
                    a1, a2 = hash_mix(np.array(cs, np.int32),
                                      np.array(cw, np.int32), seed)
                alt1, alt2 = int(a1 & mask), int(a2 & mask)
                cb = alt2 if cb == alt1 else alt1
            else:
                ok = False
                break
        if ok:
            return ht_s, ht_w, ht_c, np.array([seed], dtype=np.uint32)
    raise RuntimeError("edge-hash build failed for all seeds")


def pack_tables(auto: Automaton) -> Automaton:
    """Build the wide packed mirrors the match kernel gathers from
    (see the field comments on :class:`Automaton`)."""
    ht_packed = None
    if auto.ht_state is not None:
        ht_packed = np.concatenate(
            [np.asarray(auto.ht_state), np.asarray(auto.ht_word),
             np.asarray(auto.ht_child)], axis=1).astype(np.int32)
    node_packed = np.stack(
        [np.asarray(auto.plus_child), np.asarray(auto.hash_filter),
         np.asarray(auto.end_filter),
         np.full_like(np.asarray(auto.plus_child), -1)],
        axis=1).astype(np.int32)
    return auto._replace(ht_packed=ht_packed, node_packed=node_packed)


def attach_edge_hash(auto: Automaton, n_buckets: int | None = None) -> Automaton:
    """Return ``auto`` with hash tables built (bucket count derived
    from edge capacity unless given — sharded builds pass a shared
    count so stacked shards agree on shapes)."""
    if n_buckets is None:
        n_buckets = buckets_for_capacity(auto.edge_word.shape[0])
    ht_s, ht_w, ht_c, seed = build_edge_hash(
        np.asarray(auto.row_ptr), np.asarray(auto.edge_word),
        np.asarray(auto.edge_child), auto.n_states, auto.n_edges,
        n_buckets)
    return pack_tables(auto._replace(
        ht_state=ht_s, ht_word=ht_w, ht_child=ht_c, ht_seed=seed))
