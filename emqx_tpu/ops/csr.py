"""Flatten the subscription trie into device walk tables.

The reference stores the trie as two Mnesia tables — edges keyed by
``{node_id, word}`` and nodes carrying the terminal topic
(src/emqx_trie.erl:53-74, include/emqx.hrl:96-113). For the TPU the
trie becomes a static automaton in HBM, built in two passes:

1. **Flatten** (:func:`build_automaton`): BFS over the host trie into
   CSR arrays (``row_ptr``/``edge_word``/``edge_child``) plus dense
   per-state columns (``plus_child``/``hash_filter``/``end_filter``).
   This is the rebuild artifact — the walk never reads it.

2. **Compress + pack** (:func:`compress_automaton` /
   :func:`attach_walk_tables`): single-child literal chains are
   collapsed into multi-word edges (up to ``max_take`` words per hop,
   the chain words stored *inline* in the edge row and verified
   exactly — parity never rests on a hash), states are renumbered to
   the surviving set, and edges land in a bucketed 2-choice hash
   table ``wt`` whose row width is chosen for the TPU gather unit:

     - **narrow** rows (2 slots × 4 ints = 32 B) when the trie is
       shallow — measured ~5.6 ns/row on v5e;
     - **wide** rows (4 slots × 16 ints = 256 B) when chains are deep
       — the 64-int row rides XLA's fast wide-gather path (~10 ns/row)
       while widths 12–48 sit in a 23–53 ns/row dead zone.

   A 16-level literal chain that cost 16 serial walk steps in the
   uncompressed automaton (the round-4 ``hash_1m_deep`` 0.197×
   finding; reference cost model src/emqx_trie.erl:161-186) becomes
   ≤ 3 hops.

State 0 is the root. Arrays are padded to pow2 capacity so
incremental rebuilds keep static shapes (no XLA recompiles); padded
rows are empty.
"""

from __future__ import annotations

from typing import Dict, NamedTuple

import numpy as np

from emqx_tpu import topic as T
from emqx_tpu.oracle import TrieOracle, _Node
from emqx_tpu.ops.tokenize import WordTable

_WORD_PAD = np.int32(2**31 - 1)

#: chain-word pad inside a wide slot (never a word id, UNKNOWN or PAD)
CW_PAD = -3

#: slot layouts: [state, word, child, pad] (narrow) /
#: [state, word, take, child, cw0..cw6, pad×5] (wide)
NARROW_SLOT = 4
WIDE_SLOT = 16
NARROW_SLOTS = 2
WIDE_SLOTS = 4

#: max words one wide edge consumes (1 key word + 7 inline chain words)
MAX_TAKE = 8


class Automaton(NamedTuple):
    """Trie automaton: CSR flatten artifact + compiled walk tables.

    The v1 CSR arrays (``row_ptr``/``edge_word``/``edge_child`` and
    the dense state columns) are the flatten output in *original*
    state ids — the input to compression and the thing rebuilds
    produce. The walk reads only the v2 tables (renumbered,
    chain-compressed ids):

      - ``wt`` — bucketed 2-choice edge hash rows (layout above);
      - ``node2`` — ``[S2_cap, 4]`` per-state ``plus|hashf|endf|-1``;
      - ``hops_for_level[d]`` — scan steps needed for topics of ≤ d
        words (static per compile; grows only via deep patches);
      - ``v2_hop``/``v2_depth`` — host-only per-state hop/depth used
        by the patcher's hop accounting (stripped before device_put).

    ``wt_slots``/``wt_take`` are python ints (static at trace time —
    callers read them from the HOST automaton, never through jit).
    """

    row_ptr: np.ndarray      # int32[S_cap + 1]
    edge_word: np.ndarray    # int32[E_cap], sorted within each row
    edge_child: np.ndarray   # int32[E_cap]
    plus_child: np.ndarray   # int32[S_cap]
    hash_filter: np.ndarray  # int32[S_cap]
    end_filter: np.ndarray   # int32[S_cap]
    n_states: int            # live v1 states (root included)
    n_edges: int             # live v1 literal edges
    wt: np.ndarray | None = None            # int32[NB, slots*SW]
    wt_seed: np.ndarray | None = None       # uint32[1]
    node2: np.ndarray | None = None         # int32[S2_cap, 4]
    hops_for_level: np.ndarray | None = None  # int32[maxdepth + 1]
    v2_hop: np.ndarray | None = None        # int16[S2_cap] host-only
    v2_depth: np.ndarray | None = None      # int16[S2_cap] host-only
    v2_states: int = 0
    v2_edges: int = 0
    wt_slots: int = 0        # 2 = narrow, 4 = wide
    wt_take: int = 1         # max words per literal hop (R)


class V2Edges(NamedTuple):
    """Compressed edge list in v2 state ids (compression output, hash
    placement input — the seam the sharded builder splits on)."""

    src: np.ndarray    # int32[E2]
    word: np.ndarray   # int32[E2] first word (the hash key word)
    take: np.ndarray   # int32[E2] words consumed (1..MAX_TAKE)
    child: np.ndarray  # int32[E2]
    cw: np.ndarray     # int32[E2, MAX_TAKE-1] inline chain words


#: Automaton fields the compiled walk never reads — stripped before
#: device placement (the CSR flatten artifact and patcher-only arrays
#: would otherwise squat HBM at 10M-sub scale).
HOST_ONLY_FIELDS = ("row_ptr", "edge_word", "edge_child", "plus_child",
                    "hash_filter", "end_filter", "v2_hop", "v2_depth")


def device_view(auto: Automaton) -> Automaton:
    """The walkable subset of ``auto`` (host-only fields dropped)."""
    return auto._replace(**{f: None for f in HOST_ONLY_FIELDS})


def capacity_for(n: int, cap: int | None = None) -> int:
    """Next power-of-two capacity ≥ n (min 16), honoring a floor."""
    c = 16
    while c < n:
        c *= 2
    if cap is not None and cap > c:
        c = cap
    return c


_capacity = capacity_for


def build_automaton(
    trie: TrieOracle,
    filter_ids: Dict[str, int],
    table: WordTable,
    state_capacity: int | None = None,
    edge_capacity: int | None = None,
    skip_hash: bool = False,
    v2_state_capacity: int | None = None,
    v2_n_buckets: int | None = None,
) -> Automaton:
    """Flatten ``trie`` and (unless ``skip_hash``) build walk tables.

    ``filter_ids`` maps every inserted filter to its dense route id
    (assigned by the router); ``table`` interns filter words. ``#``
    child nodes are collapsed (never walked into); ``+`` children are
    ordinary states. ``skip_hash=True`` returns the bare flatten —
    the sharded builder compresses each shard with shared capacities
    (parallel/sharded.py) before packing.
    """
    # BFS assigning dense state ids; root = 0.
    states: list[_Node] = [trie.root]
    index: dict[int, int] = {id(trie.root): 0}
    edges_per_state: list[list[tuple[int, int]]] = []  # (word_id, child)
    plus: list[int] = []
    hashf: list[int] = []
    endf: list[int] = []

    i = 0
    while i < len(states):
        node = states[i]
        i += 1
        lits: list[tuple[int, int]] = []
        p = -1
        h = -1
        for w, child in node.children.items():
            if w == T.HASH:
                if child.filter is not None:
                    h = filter_ids[child.filter]
                continue
            sid = index.get(id(child))
            if sid is None:
                sid = len(states)
                index[id(child)] = sid
                states.append(child)
            if w == T.PLUS:
                p = sid
            else:
                lits.append((table.intern(w), sid))
        lits.sort()
        edges_per_state.append(lits)
        plus.append(p)
        hashf.append(h)
        endf.append(-1 if node.filter is None else filter_ids[node.filter])

    S = len(states)
    E = sum(len(e) for e in edges_per_state)
    S_cap = _capacity(S, state_capacity)
    E_cap = _capacity(E + 1, edge_capacity)

    row_ptr = np.full((S_cap + 1,), E, dtype=np.int32)
    edge_word = np.full((E_cap,), _WORD_PAD, dtype=np.int32)
    edge_child = np.full((E_cap,), -1, dtype=np.int32)
    plus_child = np.full((S_cap,), -1, dtype=np.int32)
    hash_filter = np.full((S_cap,), -1, dtype=np.int32)
    end_filter = np.full((S_cap,), -1, dtype=np.int32)

    pos = 0
    for s in range(S):
        row_ptr[s] = pos
        for wid, child in edges_per_state[s]:
            edge_word[pos] = wid
            edge_child[pos] = child
            pos += 1
    row_ptr[S:] = pos  # live-end and padded rows all point at E

    plus_child[:S] = plus
    hash_filter[:S] = hashf
    end_filter[:S] = endf

    auto = Automaton(
        row_ptr=row_ptr,
        edge_word=edge_word,
        edge_child=edge_child,
        plus_child=plus_child,
        hash_filter=hash_filter,
        end_filter=end_filter,
        n_states=S,
        n_edges=E,
    )
    if skip_hash:
        return auto
    return finalize_automaton(
        auto, state_capacity=v2_state_capacity,
        n_buckets=v2_n_buckets)


def finalize_automaton(
    auto: Automaton,
    *,
    max_take: int = MAX_TAKE,
    force_mode: str | None = None,
    state_capacity: int | None = None,
    edge_capacity: int | None = None,
    n_buckets: int | None = None,
) -> Automaton:
    """Compress + pack in one step (the single-chip build path)."""
    auto, edges = compress_automaton(
        auto, max_take=max_take, force_mode=force_mode,
        state_capacity=state_capacity, edge_capacity=edge_capacity)
    return attach_walk_tables(auto, edges, n_buckets=n_buckets)


# -- compression -----------------------------------------------------------


def _csr_depths(rp, ec, plus, S):
    """Per-state depth via level-synchronous BFS (vectorized)."""
    depth = np.full(S, -1, np.int32)
    depth[0] = 0
    frontier = np.array([0], dtype=np.int64)
    d = 0
    while frontier.size:
        d += 1
        starts = rp[frontier].astype(np.int64)
        ends = rp[frontier + 1].astype(np.int64)
        counts = ends - starts
        total = int(counts.sum())
        if total:
            # flat CSR indices of every frontier edge
            offs = np.repeat(starts, counts) + (
                np.arange(total) - np.repeat(
                    np.cumsum(counts) - counts, counts))
            kids = ec[offs].astype(np.int64)
        else:
            kids = np.empty(0, np.int64)
        pc = plus[frontier]
        kids = np.concatenate([kids, pc[pc >= 0].astype(np.int64)])
        depth[kids] = d
        frontier = kids
    return depth


def _csr_edge_indices(rp, frontier):
    """(flat edge indices, repeated sources) of ``frontier``'s rows."""
    starts = rp[frontier].astype(np.int64)
    counts = (rp[frontier + 1] - rp[frontier]).astype(np.int64)
    total = int(counts.sum())
    if not total:
        return np.empty(0, np.int64), np.empty(0, np.int64)
    offs = np.repeat(starts, counts) + (
        np.arange(total) - np.repeat(np.cumsum(counts) - counts, counts))
    return offs, np.repeat(frontier, counts)


def compress_automaton(
    auto: Automaton,
    *,
    max_take: int = MAX_TAKE,
    force_mode: str | None = None,
    state_capacity: int | None = None,
    edge_capacity: int | None = None,
) -> tuple[Automaton, V2Edges]:
    """Collapse single-child literal chains and renumber states.

    A state is a *chain interior* when it has exactly one literal
    child and no ``+`` child, no ``#`` terminal and no end terminal —
    the same structural fact the reference's per-level ETS walk pays
    one read for (src/emqx_trie.erl:161-186); here the walk skips it
    entirely. Interiors are absorbed into the incoming edge (its
    ``take`` grows, the skipped words land in ``cw``); everything
    else is materialized and renumbered in hop-BFS order.

    Mode: **wide** when compression shortens the deepest walk by ≥ 2
    scan steps (deep-hierarchy tries), else **narrow** (``take ≡ 1``,
    no window machinery in the kernel — shallow tries pay nothing for
    a feature they can't use). ``force_mode`` pins it for tests.
    """
    S, E = auto.n_states, auto.n_edges
    rp = np.asarray(auto.row_ptr[:S + 1], np.int64)
    ew = np.asarray(auto.edge_word)
    ec = np.asarray(auto.edge_child)
    plus = np.asarray(auto.plus_child[:S])
    hashf = np.asarray(auto.hash_filter[:S])
    endf = np.asarray(auto.end_filter[:S])
    deg = np.diff(rp)

    depth = _csr_depths(rp, ec, plus, S)
    maxdepth = int(depth.max()) if S > 1 else 0

    elig = (deg == 1) & (plus < 0) & (hashf < 0) & (endf < 0)
    elig[0] = False

    # links[s] = skippable single-edge hops below s (0 if not elig)
    links = np.zeros(S, np.int32)
    for d in range(maxdepth, 0, -1):
        idx = np.nonzero((depth == d) & elig)[0]
        if idx.size:
            kids = ec[rp[idx]]
            links[idx] = 1 + links[kids]

    R = max_take
    # hop-BFS over the compressed graph: discover materialized states
    # and emit one compressed edge per (materialized src, literal edge)
    hop = np.full(S, -1, np.int16)
    hop[0] = 0
    order = [np.array([0], np.int64)]  # materialized, discovery order
    e_src, e_word, e_take, e_child = [], [], [], []
    e_cw = []
    frontier = np.array([0], np.int64)
    while frontier.size:
        eidx, src = _csr_edge_indices(rp, frontier)
        nxt_parts = []
        if eidx.size:
            w = ew[eidx]
            c = ec[eidx].astype(np.int64)
            j = np.minimum(links[c], R - 1).astype(np.int64)
            cw = np.full((len(c), R - 1), CW_PAD, np.int32)
            cur = c.copy()
            for i in range(R - 1):
                m = i < j
                if not m.any():
                    break
                e0 = rp[cur[m]]
                cw[m, i] = ew[e0]
                cur[m] = ec[e0]
            land = cur
            hop[land] = hop[src] + 1
            e_src.append(src)
            e_word.append(w)
            e_take.append((1 + j).astype(np.int32))
            e_child.append(land)
            e_cw.append(cw)
            nxt_parts.append(land)
        pc = plus[frontier]
        pm = pc >= 0
        if pm.any():
            pk = pc[pm].astype(np.int64)
            hop[pk] = hop[frontier[pm]] + 1
            nxt_parts.append(pk)
        frontier = (np.concatenate(nxt_parts) if nxt_parts
                    else np.empty(0, np.int64))
        if frontier.size:
            order.append(frontier)

    mat = np.concatenate(order)
    S2 = len(mat)
    newid = np.full(S, -1, np.int32)
    newid[mat] = np.arange(S2, dtype=np.int32)

    hops_full = np.zeros(maxdepth + 1, np.int32)
    md = depth[mat].astype(np.int64)
    mh = hop[mat].astype(np.int64)
    np.maximum.at(hops_full, md, mh + 1)
    hops_full = np.maximum.accumulate(hops_full)
    hops_full = np.maximum(hops_full, 1)

    mode = force_mode
    if mode is None:
        # wide only when compression actually shortens the walk: the
        # narrow kernel skips the window/level machinery entirely
        saved = (maxdepth + 1) - int(hops_full[maxdepth])
        mode = "wide" if saved >= 2 else "narrow"
    # the wide kernel packs (state << 5 | level) into one int32 lane:
    # state ids past 2^26 or depths past 31 don't fit — such tries
    # (far beyond any configured max_levels / 10M-sub scale) walk
    # narrow, which carries no packed level
    if mode == "wide" and (S2 >= (1 << 26) or maxdepth > 31):
        mode = "narrow"

    if mode == "narrow":
        # no chain skipping: identity renumbering, take ≡ 1 (the
        # flatten's BFS order is already dense)
        S2 = S
        S2_cap = _capacity(S2, state_capacity)
        node2 = np.full((S2_cap, 4), -1, np.int32)
        node2[:S, 0] = plus
        node2[:S, 1] = hashf
        node2[:S, 2] = endf
        v2_hop = np.full(S2_cap, -1, np.int16)
        v2_hop[:S] = depth.astype(np.int16)
        v2_depth = np.full(S2_cap, -1, np.int16)
        v2_depth[:S] = depth.astype(np.int16)
        eidx, src = _csr_edge_indices(rp, np.arange(S, dtype=np.int64))
        edges = V2Edges(
            src=src.astype(np.int32), word=ew[eidx].astype(np.int32),
            take=np.ones(len(src), np.int32),
            child=ec[eidx].astype(np.int32),
            cw=np.full((len(src), R - 1), CW_PAD, np.int32))
        return auto._replace(
            node2=node2,
            hops_for_level=np.arange(1, maxdepth + 2, dtype=np.int32),
            v2_hop=v2_hop, v2_depth=v2_depth,
            v2_states=S2, v2_edges=len(src),
            wt_slots=NARROW_SLOTS, wt_take=1,
        ), edges

    src = np.concatenate(e_src) if e_src else np.empty(0, np.int64)
    edges = V2Edges(
        src=newid[src].astype(np.int32),
        word=(np.concatenate(e_word) if e_word
              else np.empty(0, np.int32)).astype(np.int32),
        take=(np.concatenate(e_take) if e_take
              else np.empty(0, np.int32)),
        child=newid[np.concatenate(e_child)].astype(np.int32)
        if e_child else np.empty(0, np.int32),
        cw=(np.concatenate(e_cw) if e_cw
            else np.empty((0, R - 1), np.int32)),
    )
    S2_cap = _capacity(S2, state_capacity)
    node2 = np.full((S2_cap, 4), -1, np.int32)
    pc = plus[mat]
    node2[:S2, 0] = np.where(pc >= 0, newid[np.maximum(pc, 0)], -1)
    node2[:S2, 1] = hashf[mat]
    node2[:S2, 2] = endf[mat]
    v2_hop = np.full(S2_cap, -1, np.int16)
    v2_hop[:S2] = hop[mat]
    v2_depth = np.full(S2_cap, -1, np.int16)
    v2_depth[:S2] = depth[mat].astype(np.int16)
    return auto._replace(
        node2=node2, hops_for_level=hops_full,
        v2_hop=v2_hop, v2_depth=v2_depth,
        v2_states=S2, v2_edges=len(edges.src),
        wt_slots=WIDE_SLOTS, wt_take=R,
    ), edges


# -- bucketed 2-choice edge hash ------------------------------------------


def hash_mix(state, word, seed):
    """The (state, word) → (h1, h2) mix — uint32 wraparound arithmetic,
    written so numpy (build) and jnp (match kernel) agree bit-for-bit."""
    s = state.astype("uint32")
    w = word.astype("uint32")
    h = s * np.uint32(0x9E3779B9) + w * np.uint32(0x85EBCA6B) + seed
    h = h ^ (h >> np.uint32(16))
    h = h * np.uint32(0x7FEB352D)
    h = h ^ (h >> np.uint32(15))
    h2 = h * np.uint32(0x846CA68B)
    h2 = h2 ^ (h2 >> np.uint32(16))
    return h, h2


def buckets_for_capacity(edge_capacity: int, slots: int) -> int:
    """Bucket count giving ≤ 0.5 fill at full edge capacity (pow2)."""
    nb = 4
    while nb * slots < 2 * edge_capacity:
        nb *= 2
    return nb


def _greedy_place(b, avail, fill, order_keys):
    """Vectorized capacity-bounded placement of keys into buckets ``b``
    (one pass). Returns (placed_key_idx, bucket, slot, leftover_idx)."""
    order = np.argsort(b, kind="stable")
    bs = b[order]
    rank = np.arange(len(bs)) - np.searchsorted(bs, bs)
    slot = fill[bs] + rank
    ok = slot < avail
    return order_keys[order[ok]], bs[ok], slot[ok], order_keys[order[~ok]]


def place_edges(
    states: np.ndarray,
    words: np.ndarray,
    n_buckets: int,
    slots: int,
    max_seeds: int = 32,
):
    """Cuckoo placement of (state, word) keys into ``n_buckets`` ×
    ``slots``. Returns ``(bucket[E], slot[E], seed)``.

    Two vectorized greedy passes (first-choice bucket, then second)
    place ~all keys; the tail is fixed with bounded cuckoo evictions.
    On pathological seeds the whole build retries with the next seed
    (keys are unique, so success at ≤ 50% fill is essentially
    certain)."""
    E = len(states)
    mask = np.uint32(n_buckets - 1)
    for seed_i in range(max_seeds):
        seed = np.uint32(0xA5A5A5A5 + 0x9E37 * seed_i)
        out_b = np.full(E, -1, np.int64)
        out_s = np.full(E, -1, np.int64)
        if E == 0:
            return out_b, out_s, np.array([seed], dtype=np.uint32)
        h1, h2 = hash_mix(states, words, seed)
        b1 = (h1 & mask).astype(np.int64)
        b2 = (h2 & mask).astype(np.int64)
        fill = np.zeros((n_buckets,), dtype=np.int64)
        occ = np.full((n_buckets, slots), -1, np.int64)  # edge index

        keys = np.arange(E, dtype=np.int64)
        pk, pb, ps, left = _greedy_place(b1, slots, fill, keys)
        np.add.at(fill, pb, 1)
        out_b[pk], out_s[pk] = pb, ps
        occ[pb, ps] = pk
        if len(left):
            pk, pb, ps, left = _greedy_place(b2[left], slots, fill, left)
            np.add.at(fill, pb, 1)
            out_b[pk], out_s[pk] = pb, ps
            occ[pb, ps] = pk

        ok = True
        for k in left:
            ck = int(k)
            cb = int(b1[ck])
            for step in range(500):
                row = occ[cb]
                free = np.nonzero(row < 0)[0]
                if len(free):
                    occ[cb, free[0]] = ck
                    out_b[ck], out_s[ck] = cb, free[0]
                    break
                victim = step % slots
                vk = int(occ[cb, victim])
                occ[cb, victim] = ck
                out_b[ck], out_s[ck] = cb, victim
                ck = vk
                alt1, alt2 = int(b1[ck]), int(b2[ck])
                cb = alt2 if cb == alt1 else alt1
            else:
                ok = False
                break
        if ok:
            return out_b, out_s, np.array([seed], dtype=np.uint32)
    raise RuntimeError("edge-hash build failed for all seeds")


def pack_slot_rows(edges: V2Edges, bucket, slot, n_buckets: int,
                   slots: int, take_max: int) -> np.ndarray:
    """Scatter the placed edges into the flat ``wt`` row array."""
    sw = NARROW_SLOT if take_max == 1 else WIDE_SLOT
    wt = np.full((n_buckets, slots * sw), -1, np.int32)
    base = slot * sw
    if take_max == 1:
        wt[bucket, base + 0] = edges.src
        wt[bucket, base + 1] = edges.word
        wt[bucket, base + 2] = edges.child
    else:
        wt[bucket, base + 0] = edges.src
        wt[bucket, base + 1] = edges.word
        wt[bucket, base + 2] = edges.take
        wt[bucket, base + 3] = edges.child
        for i in range(take_max - 1):
            wt[bucket, base + 4 + i] = edges.cw[:, i]
    return wt


def attach_walk_tables(
    auto: Automaton,
    edges: V2Edges,
    n_buckets: int | None = None,
    edge_capacity: int | None = None,
) -> Automaton:
    """Build ``wt`` from a compressed edge list (bucket count derived
    from edge capacity unless given — sharded builds pass a shared
    count so stacked shards agree on shapes)."""
    slots = auto.wt_slots
    e_cap = _capacity(len(edges.src) + 1, edge_capacity)
    need = buckets_for_capacity(e_cap, slots)
    # a caller-provided count is a retention FLOOR (shape stability
    # across rebuilds), never a shrink below what the live edge set
    # needs at ≤50% fill
    n_buckets = need if n_buckets is None else max(n_buckets, need)
    bucket, slot, seed = place_edges(
        edges.src, edges.word, n_buckets, slots)
    wt = pack_slot_rows(edges, bucket, slot, n_buckets, slots,
                        auto.wt_take)
    return auto._replace(wt=wt, wt_seed=seed)
