"""Device subscriber fan-out: matched filter ids → subscriber ids.

Replaces the reference's subscriber fold ("HOT LOOP 2",
src/emqx_broker.erl:283-309 + topic shards
src/emqx_broker_helper.erl:82-92): subscriber ids per filter live in a
CSR table in HBM and a compiled gather expands a match batch into flat
delivery lists. The per-output-slot row assignment uses a searchsorted
over the per-match cumulative lengths — fully static shapes, no
scatter.

Capacity model: each topic yields at most ``d`` deliveries per call;
larger fan-outs set the overflow flag and the caller chunks or falls
back (the reference shards topics >1024 subscribers for the same
reason — bounded work per dispatch unit).
"""

from __future__ import annotations

import functools
from typing import Dict, NamedTuple, Sequence

import jax
import jax.numpy as jnp
import numpy as np


class FanoutTable(NamedTuple):
    row_ptr: np.ndarray  # int32[F_cap + 1]
    sub_ids: np.ndarray  # int32[N_cap]
    n_filters: int
    n_entries: int
    # packed (start, end) pairs: ONE row gather per matched filter
    # instead of two row_ptr lookups (TPU gather cost is per row)
    row_pairs: np.ndarray | None = None  # int32[F_cap, 2]


def build_fanout(
    rows: Dict[int, Sequence[int]],
    num_filters: int,
    filter_capacity: int | None = None,
    entry_capacity: int | None = None,
) -> FanoutTable:
    """CSR from ``{filter_id: [subscriber ids]}``."""
    from emqx_tpu.ops.csr import capacity_for

    total = sum(len(v) for v in rows.values())
    f_cap = capacity_for(num_filters, filter_capacity)
    e_cap = capacity_for(total + 1, entry_capacity)
    row_ptr = np.zeros((f_cap + 1,), dtype=np.int32)
    sub_ids = np.full((e_cap,), -1, dtype=np.int32)
    pos = 0
    for fid in range(num_filters):
        row_ptr[fid] = pos
        for s in rows.get(fid, ()):
            sub_ids[pos] = s
            pos += 1
    row_ptr[num_filters:] = pos
    pairs = np.stack([row_ptr[:-1], row_ptr[1:]], axis=1)
    return FanoutTable(row_ptr, sub_ids, num_filters, total, pairs)


@jax.jit
def pick_shared(
    fan: FanoutTable,
    match_ids: jax.Array,  # int32[B, M] shared-group filter ids (-1 pad)
    seed: jax.Array,       # int32[B] per-message pick seed (e.g. guid hash)
) -> jax.Array:
    """One member per matched shared-group filter — the device form of
    the reference's `hash` dispatch strategy
    (src/emqx_shared_sub.erl:229-275): member = seed mod group size,
    read straight out of the group-membership CSR. Round-robin/sticky
    keep host state and stay host-side; hash is stateless and batches.

    Returns int32[B, M] subscriber ids (-1 where no pick).
    """
    def one(ids, s):
        # ids beyond the table's filter capacity (patched into the
        # automaton after this table was built) must drop, not clamp:
        # a clamp would deliver to the last row's unrelated group
        in_range = (ids >= 0) & (ids < fan.row_ptr.shape[0] - 1)
        safe = jnp.where(in_range, ids, 0)
        lens = fan.row_ptr[safe + 1] - fan.row_ptr[safe]
        starts = fan.row_ptr[safe]
        valid = in_range & (lens > 0)
        idx = starts + jnp.where(
            valid, s % jnp.maximum(lens, 1), 0)
        idx = jnp.clip(idx, 0, fan.sub_ids.shape[0] - 1)
        return jnp.where(valid, fan.sub_ids[idx], -1)

    return jax.vmap(one)(match_ids, seed)


@functools.partial(jax.jit, static_argnames=("d",))
def gather_subscribers_src(
    fan: FanoutTable,
    match_ids: jax.Array,  # int32[B, M] (-1 padded)
    *,
    d: int = 1024,
) -> tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Like :func:`gather_subscribers` but also returns the *source
    filter id* per output slot — the broker's delivery tail needs the
    matched filter to resolve per-subscription options (the reference
    dispatches per ``{Topic, SubPid}`` pair, src/emqx_broker.erl:298).

    Returns ``(subs[B, d], src[B, d], count[B], overflow[B])``; both
    ``subs`` and ``src`` are -1 padded.
    """
    M = match_ids.shape[1]

    def one(ids):
        # out-of-capacity ids (automaton patched past this table's
        # build) contribute zero length — never clamp into a row
        in_range = (ids >= 0) & (ids < fan.row_ptr.shape[0] - 1)
        safe = jnp.where(in_range, ids, 0)
        if fan.row_pairs is not None:
            pairs = fan.row_pairs[safe]          # ONE [M, 2] gather
            starts = pairs[:, 0]
            lens = jnp.where(in_range, pairs[:, 1] - pairs[:, 0], 0)
        else:
            starts = fan.row_ptr[safe]
            lens = jnp.where(
                in_range, fan.row_ptr[safe + 1] - starts, 0)
        cum = jnp.cumsum(lens)
        total = cum[-1]
        slots = jnp.arange(d, dtype=jnp.int32)
        # row assignment by compare-sum, NOT searchsorted: the
        # binary-search lowering emits log(M) gathers per slot, while
        # a [d, M] compare + row-sum is pure vector work
        row = jnp.sum(cum[None, :] <= slots[:, None],
                      axis=1, dtype=jnp.int32)
        row_c = jnp.minimum(row, M - 1)
        # the four per-row values each slot needs, packed into ONE
        # [M, 4] local table: one [d]-row gather instead of four
        local = jnp.stack([cum, lens, starts, ids], axis=1)
        g = local[row_c]                       # [d, 4]
        base = g[:, 0] - g[:, 1]
        idx = g[:, 2] + (slots - base)
        idx = jnp.clip(idx, 0, fan.sub_ids.shape[0] - 1)
        valid = slots < jnp.minimum(total, d)
        subs = jnp.where(valid, fan.sub_ids[idx], -1)
        src = jnp.where(valid, g[:, 3], -1)
        return subs, src, total, total > d

    return jax.vmap(one)(match_ids)


@functools.partial(jax.jit, static_argnames=("q",))
def expand_packed(
    fan: FanoutTable,
    m_ptr: jax.Array,       # int32[B+1] row pointers (pack_matches)
    packed_ids: jax.Array,  # int32[P] matched filter ids, -1 padded
    *,
    q: int,
) -> tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Sparse CSR expansion: packed matched ids → packed deliveries.

    The dense per-topic gather materializes ``B×d`` slots that are
    mostly ``-1`` padding; this fused form works entirely in packed
    space — its gather count is proportional to ACTUAL matches (P)
    and deliveries (q budget), not to capacity:

      1. per-match (start, len) from the pairs table (P rows);
      2. parallel CSR expansion via marker-scatter + running max: the
         slot→match assignment comes from scattering each match's
         exclusive offset and taking ``cummax`` — no per-slot search;
      3. one packed local gather (q rows) resolves each slot's
         (start, base, source id), one more (q rows) the subscriber.

    Returns ``(f_ptr[B+1], subs[q], src[q], total)`` — the exact
    output contract of ``pack_fanout``; ``total`` > q means the
    budget overflowed (re-expand with the next bucket).
    """
    B = m_ptr.shape[0] - 1
    P = packed_ids.shape[0]
    in_range = (packed_ids >= 0) & \
        (packed_ids < fan.row_ptr.shape[0] - 1)
    safe = jnp.where(in_range, packed_ids, 0)
    if fan.row_pairs is not None:
        pairs = fan.row_pairs[safe]               # [P, 2]
        starts = pairs[:, 0]
        lens = jnp.where(in_range, pairs[:, 1] - pairs[:, 0], 0)
    else:
        starts = fan.row_ptr[safe]
        lens = jnp.where(in_range, fan.row_ptr[safe + 1] - starts, 0)
    cume = jnp.cumsum(lens)
    total = cume[-1]
    cums = cume - lens                            # exclusive offsets
    pidx = jnp.arange(P, dtype=jnp.int32)
    # slot → match assignment: scatter each non-empty match's index at
    # its first output slot, then running-max fills the runs
    marker = jnp.zeros((q,), jnp.int32).at[
        jnp.where(lens > 0, cums, q)].max(pidx, mode="drop")
    row = jax.lax.cummax(marker)
    local = jnp.stack([starts, cums, packed_ids], axis=1)  # [P, 3]
    g = local[row]                                # [q, 3]
    slots = jnp.arange(q, dtype=jnp.int32)
    idx = jnp.clip(g[:, 0] + (slots - g[:, 1]), 0,
                   fan.sub_ids.shape[0] - 1)
    valid = slots < jnp.minimum(total, q)
    subs = jnp.where(valid, fan.sub_ids[idx], -1)
    src = jnp.where(valid, g[:, 2], -1)
    # per-topic delivery counts → f_ptr: match→topic via the same
    # marker trick over m_ptr, then a segment add
    tmarker = jnp.zeros((P,), jnp.int32).at[
        jnp.clip(m_ptr[:B], 0, P)].max(
        jnp.arange(B, dtype=jnp.int32), mode="drop")
    t_of_p = jax.lax.cummax(tmarker)              # topic row per match
    counts = jnp.zeros((B,), jnp.int32).at[t_of_p].add(
        lens, mode="drop")
    f_ptr = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32),
         jnp.cumsum(counts, dtype=jnp.int32)])
    return f_ptr, subs, src, total


@functools.partial(jax.jit, static_argnames=("d",))
def gather_subscribers(
    fan: FanoutTable,
    match_ids: jax.Array,  # int32[B, M] (-1 padded)
    *,
    d: int = 1024,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Expand matches to subscriber ids.

    Returns ``(subs[B, d], count[B], overflow[B])`` where ``subs`` is
    -1 padded and ``count`` is the true delivery count (may exceed
    ``d`` — then overflow is set and only d are materialized).

    Delegates to :func:`gather_subscribers_src`, dropping the source
    ids (XLA dead-code-eliminates the unused gather under jit).
    """
    subs, _, count, overflow = gather_subscribers_src(fan, match_ids, d=d)
    return subs, count, overflow
