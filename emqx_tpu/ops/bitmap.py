"""Subscriber-bitmap fan-out for huge-fan-out filters — Pallas kernel.

The reference bounds per-dispatch work by sharding a topic's
subscribers once they exceed 1024 (src/emqx_broker_helper.erl:55,
82-92; dispatch walks ``{shard, Topic, I}`` records,
src/emqx_broker.erl:305-309). The TPU analogue (SURVEY §2.2): filters
past the threshold store their subscriber set as a *bitmap row* in
HBM (bit i = subscriber id i), and fan-out for a publish batch is a
bitwise OR of its matched rows:

    out[b, :] = OR over m of bitmaps[row(match_ids[b, m]), :]

This is pure HBM bandwidth (the OR is trivial), so the kernel is a
streaming Pallas program: grid ``(B, W_tiles)``; each program loops
over the topic's matched rows, DMA-ing the row's tile HBM→VMEM with
double buffering and OR-accumulating in registers. Matched ids are
per-topic scalars in SMEM driving the DMA source index — the
data-dependent gather XLA would materialize as a ``[B, M, W]``
intermediate never exists.

Small-fan-out filters stay on the CSR id-gather path
(:mod:`emqx_tpu.ops.fanout`); the broker routes each matched filter
by class, mirroring the reference's flat-bag / sharded split.
"""

from __future__ import annotations

import functools
from typing import Dict, NamedTuple, Sequence

import jax
import jax.numpy as jnp
import numpy as np

_LANES = 128          # last-dim tile unit (uint32 words)
_DEFAULT_TILE = 2048  # words per DMA tile (8 KB)


class BitmapTable(NamedTuple):
    """Per-filter subscriber bitmaps for 'big' filters.

    ``big_row[fid]`` maps a global filter id to its bitmap row
    (-1 = filter is small / unknown → CSR path).
    """

    bitmaps: np.ndarray  # uint32[R_cap, W] — W padded to the tile size
    big_row: np.ndarray  # int32[F_cap]
    n_rows: int
    n_subs: int


def words_for(n_subs: int, tile: int = _DEFAULT_TILE) -> int:
    """Row width in uint32 words: next power of two ≥ the bit count
    (min one tile). Pow2 keeps the kernel's row-chunk size an exact
    divisor of the row for any capacity."""
    w = (n_subs + 31) // 32
    out = max(tile, 1024)
    while out < w:
        out *= 2
    return out


def build_bitmaps(
    rows: Dict[int, Sequence[int]],
    num_filters: int,
    n_subs: int,
    row_capacity: int | None = None,
    tile: int = _DEFAULT_TILE,
) -> BitmapTable:
    """Pack ``{filter_id: [subscriber ids]}`` into bitmap rows."""
    from emqx_tpu.ops.csr import capacity_for

    W = words_for(n_subs, tile)
    f_cap = capacity_for(num_filters)
    r_cap = capacity_for(max(1, len(rows)), row_capacity)
    bitmaps = np.zeros((r_cap, W), dtype=np.uint32)
    big_row = np.full((f_cap,), -1, dtype=np.int32)
    for r, (fid, subs) in enumerate(sorted(rows.items())):
        big_row[fid] = r
        ids = np.asarray(list(subs), dtype=np.int64)
        np.bitwise_or.at(bitmaps[r], ids // 32,
                         np.uint32(1) << (ids % 32).astype(np.uint32))
    return BitmapTable(bitmaps=bitmaps, big_row=big_row,
                       n_rows=len(rows), n_subs=n_subs)


def rows_for_matches(table: BitmapTable, match_ids: jax.Array,
                     mb: int = 16) -> tuple[jax.Array, jax.Array]:
    """Translate matched filter ids [B, M] to bitmap rows [B, mb]
    (-1 padded, packed to the front; small/unmatched filters drop
    out). ``mb`` bounds the number of big filters one topic can
    match; the overflow flag [B] marks topics that exceeded it
    (host fallback, as in ops.match)."""
    # ids at/above the table's filter capacity (patched into the
    # automaton after this table was built) have no row; clamping
    # would alias them onto the LAST filter's bitmap — an entire
    # unrelated subscriber set
    in_range = (match_ids >= 0) & (match_ids < table.big_row.shape[0])
    safe = jnp.where(in_range, match_ids, 0)
    rows = jnp.where(in_range, table.big_row[safe], -1)
    # pack valid rows to the front (cumsum+scatter, as in ops.match)
    valid = rows >= 0
    pos = jnp.cumsum(valid, axis=1) - 1
    out = jnp.full((rows.shape[0], mb), -1, dtype=jnp.int32)
    out = out.at[
        jnp.arange(rows.shape[0])[:, None],
        jnp.where(valid, jnp.minimum(pos, mb), mb)].set(rows, mode="drop")
    overflow = jnp.sum(valid, axis=1) > mb
    return out, overflow


# -- XLA reference implementation ------------------------------------------

@jax.jit
def or_bitmaps_xla(bitmaps: jax.Array, rows: jax.Array) -> jax.Array:
    """OR of bitmap rows per topic — lax.scan over the row slots (the
    no-Pallas fallback; materializes one [B, W] gather per slot)."""
    B = rows.shape[0]
    W = bitmaps.shape[1]

    def step(acc, r):
        tile = jnp.where(r[:, None] >= 0, bitmaps[jnp.maximum(r, 0)],
                         jnp.zeros((1, W), jnp.uint32))
        return acc | tile, None

    acc0 = jnp.zeros((B, W), dtype=jnp.uint32)
    acc, _ = jax.lax.scan(step, acc0, jnp.swapaxes(rows, 0, 1))
    return acc


# -- Pallas kernel ----------------------------------------------------------

_SUB = 8          # sublanes per block
_TILE2D = _SUB * _LANES  # 1024 words per (8, 128) block


def _or_kernel(ids_ref, bm_ref, out_ref):
    """One program = one (topic, tile, match-slot). The match slot is
    the innermost grid dim, so the output block stays resident in
    VMEM across the reduction; the input block for each slot is the
    matched row's tile, selected by the scalar-prefetched ids in the
    index_map (Pallas pipelines those HBM→VMEM streams)."""
    import jax.experimental.pallas as pl

    b = pl.program_id(0)
    m = pl.program_id(2)

    @pl.when(m == 0)
    def _():
        out_ref[...] = jnp.zeros_like(out_ref)

    @pl.when(ids_ref[b, m] >= 0)
    def _():
        out_ref[...] = out_ref[...] | bm_ref[...]


@functools.partial(jax.jit, static_argnames=("interpret",))
def or_bitmaps(bitmaps: jax.Array, rows: jax.Array,
               interpret: bool = False) -> jax.Array:
    """``out[b] = OR of bitmaps[rows[b, m]] for rows[b, m] >= 0``.

    ``rows`` is [B, mb] from :func:`rows_for_matches` (packed, -1
    padded; -1 slots are skipped). ``bitmaps`` is [R, W] with W a
    multiple of 1024 words (words_for guarantees this).
    """
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, mb = rows.shape
    R, W = bitmaps.shape
    assert W % _TILE2D == 0, (W, _TILE2D)
    wt = W // _TILE2D
    # chunk several (8, 128) tiles per program: per-program overhead
    # dominated at 1-tile blocks (measured 65ms → see commit); 64
    # tiles = 256 KB per stream block, and pow2 widths divide evenly
    blk = min(wt, 64)
    assert wt % blk == 0, (wt, blk)
    bm4 = bitmaps.reshape(R, wt, _SUB, _LANES)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(B, wt // blk, mb),
        in_specs=[
            pl.BlockSpec(
                (1, blk, _SUB, _LANES),
                lambda b, j, m, ids: (jnp.maximum(ids[b, m], 0), j, 0, 0)),
        ],
        out_specs=pl.BlockSpec(
            (1, blk, _SUB, _LANES), lambda b, j, m, ids: (b, j, 0, 0)),
    )
    out = pl.pallas_call(
        _or_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, wt, _SUB, _LANES), jnp.uint32),
        interpret=interpret,
    )(rows, bm4)
    return out.reshape(B, W)


def _or_kernel_dma(ids_ref, bm_ref, out_ref, buf, sem):
    """Manual double-buffered variant: the whole match-row loop runs
    inside one program; row tiles are DMA'd HBM→VMEM with two slots
    so slot m+1 streams while slot m is OR'd."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b = pl.program_id(0)
    j = pl.program_id(1)
    mb = ids_ref.shape[1]
    blk = out_ref.shape[1]

    nbuf = buf.shape[0]

    def dma(slot, m):
        row = jnp.maximum(ids_ref[b, m], 0)
        return pltpu.make_async_copy(
            bm_ref.at[row, pl.ds(j * blk, blk)],
            buf.at[slot], sem.at[slot])

    for w in range(min(nbuf - 1, mb)):
        @pl.when(ids_ref[b, w] >= 0)
        def _(w=w):
            dma(w, w).start()

    def body(m, acc):
        live = ids_ref[b, m] >= 0
        nxt = jnp.minimum(m + nbuf - 1, mb - 1)

        @pl.when(live & (m + nbuf - 1 < mb) & (ids_ref[b, nxt] >= 0))
        def _():
            dma((m + nbuf - 1) % nbuf, m + nbuf - 1).start()

        @pl.when(live)
        def _():
            dma(m % nbuf, m).wait()
        return jnp.where(live, acc | buf[m % nbuf], acc)

    acc = jax.lax.fori_loop(
        0, mb, body,
        jnp.zeros((blk, out_ref.shape[2], out_ref.shape[3]), jnp.uint32))
    out_ref[0] = acc


@functools.partial(jax.jit, static_argnames=("interpret",))
def or_bitmaps_dma(bitmaps: jax.Array, rows: jax.Array,
                   interpret: bool = False) -> jax.Array:
    """Same contract as :func:`or_bitmaps`, manual-DMA variant."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, mb = rows.shape
    R, W = bitmaps.shape
    assert W % _TILE2D == 0, (W, _TILE2D)
    wt = W // _TILE2D
    blk = min(wt, 64)
    assert wt % blk == 0, (wt, blk)
    bm4 = bitmaps.reshape(R, wt, _SUB, _LANES)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(B, wt // blk),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(
            (1, blk, _SUB, _LANES), lambda b, j, ids: (b, j, 0, 0)),
        scratch_shapes=[
            # 2 slots measured best on v5e (4 slots regressed ~8x —
            # deeper in-flight DMA windows serialize on this part)
            pltpu.VMEM((2, blk, _SUB, _LANES), jnp.uint32),
            pltpu.SemaphoreType.DMA((2,)),
        ],
    )
    out = pl.pallas_call(
        _or_kernel_dma,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, wt, _SUB, _LANES), jnp.uint32),
        interpret=interpret,
    )(rows, bm4)
    return out.reshape(B, W)


def or_bitmaps_auto(bitmaps: jax.Array, rows: jax.Array) -> jax.Array:
    """Manual-DMA Pallas on TPU; interpret-mode elsewhere (CPU tests)."""
    interp = jax.default_backend() not in ("tpu", "axon")
    return or_bitmaps_dma(bitmaps, rows, interpret=interp)
