"""Device-side compaction of match + fan-out results for transfer.

The product publish path ends with a device→host hand-off: the host
delivery tail needs each message's matched filter ids and gathered
subscriber ids. Fetching the *dense* kernel outputs (``ids[B, M]``,
``subs/src[B, d]`` with d=1024) moves megabytes of ``-1`` padding per
batch — pure waste on the host link, which is the classic accelerator
serving bottleneck (and the reference never materializes padding at
all: its trie match returns exactly the matched set,
``src/emqx_trie.erl:161-186``).

So the last device step packs the sparse results into CSR-style
buffers sized by a static *budget*: a global cumsum assigns each valid
element its output slot, a drop-mode scatter writes them, and the
per-row counts become a row-pointer array. The host then transfers

    m_ptr[B+1], packed_ids[PM], f_ptr[B+1], packed_subs[PQ],
    packed_src[PQ]

— tens of kilobytes instead of megabytes. Budgets are power-of-two
bucketed (one compiled program per bucket, like the batch buckets);
when a batch's true totals exceed the budget the caller re-packs with
the next bucket (the totals are ``m_ptr[-1]``/``f_ptr[-1]``, so
detection costs nothing extra).

Big-filter (bitmap) fan-out rows compact the same way: only rows that
actually matched a big filter transfer (``pack_union_rows``), so a
batch with no big-fan-out traffic moves zero bitmap bytes.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


@jax.jit
def mask_pad_rows(ids: jax.Array, n_rows: jax.Array) -> jax.Array:
    """Blank the batch's padding rows (row index ≥ ``n_rows``) to -1.

    The matcher pads batches to a power-of-two bucket with a dummy
    topic; wildcard filters (``#``) can match it, and without this
    mask those phantom rows inflate the packed totals — and the
    learned budgets — by (bucket − B) × fan-out. ``n_rows`` is a
    traced scalar so every batch size in a bucket shares one compile.
    """
    row = jnp.arange(ids.shape[0], dtype=jnp.int32)
    return jnp.where((row < n_rows)[:, None], ids, -1)


@jax.jit
def mask_pad_flags(flags: jax.Array, n_rows: jax.Array) -> jax.Array:
    """Clear per-row bool flags on the batch's padding rows (the
    bool analogue of :func:`mask_pad_rows`)."""
    row = jnp.arange(flags.shape[0], dtype=jnp.int32)
    return flags & (row < n_rows)


def budget_for(n_rows: int, per_row: int, floor: int = 64) -> int:
    """Power-of-two packed-buffer budget for ``n_rows`` rows at an
    expected ``per_row`` average occupancy."""
    need = max(floor, n_rows * per_row)
    out = floor
    while out < need:
        out *= 2
    return out


@functools.partial(jax.jit, static_argnames=("pm",))
def pack_matches(ids: jax.Array, *, pm: int):
    """Compact ``ids[B, M]`` (-1 padded) into a CSR pair.

    Returns ``(m_ptr[B+1], packed_ids[pm])``; ``m_ptr[-1]`` is the
    true total — if it exceeds ``pm`` the tail was dropped and the
    caller must re-pack with a larger budget.
    """
    flat = ids.reshape(-1)
    valid = flat >= 0
    cnt = (ids >= 0).sum(axis=1, dtype=jnp.int32)
    m_ptr = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(cnt, dtype=jnp.int32)])
    pos = jnp.cumsum(valid.astype(jnp.int32)) - 1
    tgt = jnp.where(valid, pos, pm)  # pm = out of range → dropped
    packed = jnp.full((pm,), -1, jnp.int32).at[tgt].set(flat, mode="drop")
    return m_ptr, packed


@functools.partial(jax.jit, static_argnames=("pq",))
def pack_fanout(subs: jax.Array, src: jax.Array, *, pq: int):
    """Compact the gathered ``(subs, src)[B, d]`` pair (same -1
    padding positions in both) into one CSR triple.

    Returns ``(f_ptr[B+1], packed_subs[pq], packed_src[pq])`` with the
    same overflow contract as :func:`pack_matches`.
    """
    flat_subs = subs.reshape(-1)
    flat_src = src.reshape(-1)
    valid = flat_subs >= 0
    cnt = (subs >= 0).sum(axis=1, dtype=jnp.int32)
    f_ptr = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(cnt, dtype=jnp.int32)])
    pos = jnp.cumsum(valid.astype(jnp.int32)) - 1
    tgt = jnp.where(valid, pos, pq)
    packed_subs = jnp.full((pq,), -1, jnp.int32).at[tgt].set(
        flat_subs, mode="drop")
    packed_src = jnp.full((pq,), -1, jnp.int32).at[tgt].set(
        flat_src, mode="drop")
    return f_ptr, packed_subs, packed_src


@jax.jit
def bundle_i32(*parts: jax.Array) -> jax.Array:
    """Concatenate heterogeneous packed outputs into ONE int32 vector.

    A device→host fetch pays per-buffer round-trip latency on the
    host link; bundling the whole packed result set (row pointers,
    packed ids/subs/src, overflow flags, bitmap rows — bools widen,
    uint32 bitcasts) into a single buffer makes the publish path's
    fetch exactly one transfer. The host slices it apart with the
    statically known section sizes (see ``Broker.publish_fetch``).
    """
    flat = []
    for p in parts:
        if p.dtype == jnp.uint32:
            p = jax.lax.bitcast_convert_type(p, jnp.int32)
        elif p.dtype != jnp.int32:
            p = p.astype(jnp.int32)
        flat.append(p.reshape(-1))
    return jnp.concatenate(flat)


@functools.partial(jax.jit, static_argnames=("pr",))
def pack_union_rows(union: jax.Array, has_big: jax.Array, *, pr: int):
    """Compact the bitmap-union rows: only rows with ``has_big`` set
    (the row matched ≥1 big filter) are materialized.

    Returns ``(sel[B], rows[pr, W], total)`` where ``sel[b]`` is the
    packed row index for message ``b`` (-1 = no big match) and
    ``total`` > ``pr`` signals budget overflow (re-pack bigger).
    """
    hb = has_big.astype(jnp.int32)
    pos = jnp.cumsum(hb) - 1
    sel = jnp.where(has_big, pos, -1).astype(jnp.int32)
    tgt = jnp.where(has_big, pos, pr)
    rows = jnp.zeros((pr, union.shape[1]), union.dtype).at[tgt].set(
        union, mode="drop")
    return sel, rows, jnp.sum(hb)
