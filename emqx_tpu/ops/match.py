"""The compiled NFA-walk topic matcher — the publish hot loop.

This replaces the reference's per-word ETS trie walk
(src/emqx_trie.erl:161-186, "HOT LOOP 1" in SURVEY §3.1) with a
batched, fixed-shape automaton walk under ``jit``:

  - a publish batch ``[B, L]`` of interned word ids is matched
    against the CSR automaton (:mod:`emqx_tpu.ops.csr`) with one
    ``lax.scan`` over topic levels;
  - the NFA active set (≤ K states) advances by literal edges
    (per-row binary search) and ``+`` edges; ``#`` terminals are
    collected at every level (including the end-of-topic level — the
    reference's ``'match_#'`` at match_node/3 :161-186);
  - topics whose first word starts with ``$`` suppress root-level
    wildcards (emqx_trie.erl:162-163);
  - results are the matched filter ids ``[B, M]`` (-1 padded) plus a
    per-topic overflow flag. Overflowed topics (active set > K or
    matches > M or levels > L) must be re-matched on the host oracle —
    parity is preserved by fallback, never silently truncated.

All shapes are static; there is no data-dependent control flow, so XLA
tiles and fuses the walk. ``vmap`` supplies the batch dimension.
"""

from __future__ import annotations

import functools
import math
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from emqx_tpu.ops.csr import Automaton


class MatchResult(NamedTuple):
    ids: jax.Array       # int32[B, M] matched filter ids, -1 padded
    count: jax.Array     # int32[B] number of valid ids (clamped to M)
    overflow: jax.Array  # bool[B] — host-oracle fallback required


def _edge_lookup(auto: Automaton, iters: int, state: jax.Array, word: jax.Array) -> jax.Array:
    """Child state via binary search in the state's CSR row, -1 if none.

    ``state`` may be -1 (inactive); ``word`` may be negative
    (UNKNOWN/PAD) — both yield -1.
    """
    e_cap = auto.edge_word.shape[0]
    s = jnp.maximum(state, 0)
    lo = auto.row_ptr[s]
    hi = auto.row_ptr[s + 1]
    row_end = hi

    def body(_, lh):
        lo, hi = lh
        mid = jnp.minimum((lo + hi) // 2, e_cap - 1)
        pred = lo < hi
        less = auto.edge_word[mid] < word
        new_lo = jnp.where(pred & less, mid + 1, lo)
        new_hi = jnp.where(pred & ~less, mid, hi)
        return new_lo, new_hi

    lo, hi = lax.fori_loop(0, iters, body, (lo, hi))
    idx = jnp.minimum(lo, e_cap - 1)
    found = (state >= 0) & (word >= 0) & (lo < row_end) & (auto.edge_word[idx] == word)
    return jnp.where(found, auto.edge_child[idx], -1)


def _compact(cands: jax.Array, k: int) -> tuple[jax.Array, jax.Array]:
    """Compact candidate states [2K] (-1 invalid) into [K]; overflow if >K.

    Trie children are unique (each node has one parent), so no dedup is
    needed — compaction is pure packing.
    """
    count = jnp.sum(cands >= 0)
    # Descending sort packs valid states to the front; -1s sink.
    packed = -jnp.sort(-cands)[:k]
    return packed, count > k


@functools.partial(jax.jit, static_argnames=("k", "m"))
def match_batch(
    auto: Automaton,
    word_ids: jax.Array,   # int32[B, L]
    n_words: jax.Array,    # int32[B] (-1 = too many levels → overflow)
    sys_mask: jax.Array,   # bool[B]
    *,
    k: int = 64,
    m: int = 128,
) -> MatchResult:
    """Match a publish batch against the automaton. See module doc."""
    L = word_ids.shape[1]
    iters = max(1, math.ceil(math.log2(auto.edge_word.shape[0] + 1)))

    def one(words: jax.Array, n: jax.Array, is_sys: jax.Array):
        active0 = jnp.full((k,), -1, dtype=jnp.int32).at[0].set(0)
        # Pad the level axis: step L sees PAD words only (end-of-topic).
        words_ext = jnp.concatenate([words, jnp.full((1,), -2, dtype=jnp.int32)])

        def step(carry, xs):
            active, ovf = carry
            word, l = xs
            alive = active >= 0
            at_root_sys = (l == 0) & is_sys
            walking = l < n
            ending = l == n

            # '#'-child terminals at every live level (match_# semantics)
            emit_h = jnp.where(
                alive & (walking | ending) & ~at_root_sys,
                auto.hash_filter[jnp.maximum(active, 0)], -1)
            # exact terminals at end-of-topic
            emit_e = jnp.where(
                alive & ending, auto.end_filter[jnp.maximum(active, 0)], -1)

            lit = jax.vmap(lambda s: _edge_lookup(auto, iters, s, word))(active)
            plus = jnp.where(
                alive & ~at_root_sys, auto.plus_child[jnp.maximum(active, 0)], -1)
            cands = jnp.where(walking, jnp.concatenate([lit, plus]), -1)
            nxt, over = _compact(cands, k)
            return (nxt, ovf | over), jnp.concatenate([emit_h, emit_e])

        levels = jnp.arange(L + 1, dtype=jnp.int32)
        (_, ovf), emits = lax.scan(
            step, (active0, jnp.asarray(False)), (words_ext, levels))
        flat = emits.reshape(-1)
        cnt = jnp.sum(flat >= 0)
        ids = -jnp.sort(-flat)[:m]
        too_long = n < 0
        return MatchResult(
            ids=jnp.where(too_long, -1, ids),
            count=jnp.where(too_long, 0, jnp.minimum(cnt, m)).astype(jnp.int32),
            overflow=ovf | (cnt > m) | too_long,
        )

    return jax.vmap(one)(word_ids, n_words, sys_mask)
