"""The compiled NFA-walk topic matcher — the publish hot loop.

This replaces the reference's per-word ETS trie walk
(src/emqx_trie.erl:161-186, "HOT LOOP 1" in SURVEY §3.1) with a
batched, fixed-shape automaton walk under ``jit``:

  - a publish batch ``[B, L]`` of interned word ids is matched
    against the CSR automaton (:mod:`emqx_tpu.ops.csr`) with one
    ``lax.scan`` over topic levels;
  - the NFA active set (≤ K states) advances by literal edges
    (per-row binary search) and ``+`` edges; ``#`` terminals are
    collected at every level (including the end-of-topic level — the
    reference's ``'match_#'`` at match_node/3 :161-186);
  - topics whose first word starts with ``$`` suppress root-level
    wildcards (emqx_trie.erl:162-163);
  - results are the matched filter ids ``[B, M]`` (-1 padded) plus a
    per-topic overflow flag. Overflowed topics (active set > K or
    matches > M or levels > L) must be re-matched on the host oracle —
    parity is preserved by fallback, never silently truncated.

All shapes are static; there is no data-dependent control flow, so XLA
tiles and fuses the walk. ``vmap`` supplies the batch dimension.
"""

from __future__ import annotations

import functools
import math
import os
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from emqx_tpu.ops.csr import Automaton


class MatchResult(NamedTuple):
    ids: jax.Array       # int32[B, M] matched filter ids, -1 padded
    count: jax.Array     # int32[B] number of valid ids (clamped to M)
    overflow: jax.Array  # bool[B] — host-oracle fallback required


def _edge_lookup(auto: Automaton, iters: int, state: jax.Array, word: jax.Array) -> jax.Array:
    """Child state via binary search in the state's CSR row, -1 if none.

    ``state`` may be -1 (inactive); ``word`` may be negative
    (UNKNOWN/PAD) — both yield -1.
    """
    e_cap = auto.edge_word.shape[0]
    s = jnp.maximum(state, 0)
    lo = auto.row_ptr[s]
    hi = auto.row_ptr[s + 1]
    row_end = hi

    def body(_, lh):
        lo, hi = lh
        mid = jnp.minimum((lo + hi) // 2, e_cap - 1)
        pred = lo < hi
        less = auto.edge_word[mid] < word
        new_lo = jnp.where(pred & less, mid + 1, lo)
        new_hi = jnp.where(pred & ~less, mid, hi)
        return new_lo, new_hi

    lo, hi = lax.fori_loop(0, iters, body, (lo, hi))
    idx = jnp.minimum(lo, e_cap - 1)
    found = (state >= 0) & (word >= 0) & (lo < row_end) & (auto.edge_word[idx] == word)
    return jnp.where(found, auto.edge_child[idx], -1)


def _edge_lookup_hash(auto: Automaton, states: jax.Array, word: jax.Array) -> jax.Array:
    """Child states for the whole active set via the bucketed 2-choice
    hash table — vs ~2·log2(E) scalar gathers for the CSR binary
    search. With the packed mirror present each choice is ONE
    [K, 12]-row gather of (state|word|child) triples (TPU gather cost
    is per row, nearly independent of width — measured flat to width
    ≥24); otherwise two 4-wide gathers per table.

    ``states`` is the active set [K] (-1 = inactive); ``word`` a scalar
    (may be UNKNOWN/PAD < 0). Returns [K] child ids, -1 = no edge.
    """
    from emqx_tpu.ops.csr import hash_mix

    packed = auto.ht_packed is not None
    nb = (auto.ht_packed if packed else auto.ht_state).shape[0]
    seed = auto.ht_seed[0]
    h1, h2 = hash_mix(states, jnp.broadcast_to(word, states.shape), seed)
    b1 = (h1 & jnp.uint32(nb - 1)).astype(jnp.int32)
    b2 = (h2 & jnp.uint32(nb - 1)).astype(jnp.int32)

    def probe(b):
        if packed:
            row = auto.ht_packed[b]    # [K, 12]
            rs, rw, rc = row[:, 0:4], row[:, 4:8], row[:, 8:12]
        else:
            rs, rw, rc = (auto.ht_state[b], auto.ht_word[b],
                          auto.ht_child[b])
        hit = (rs == states[:, None]) & (rw == word)
        return jnp.max(jnp.where(hit, rc, -1), axis=1)

    child = jnp.maximum(probe(b1), probe(b2))
    live = (states >= 0) & (word >= 0)
    return jnp.where(live, child, -1)


# Active-set compaction strategy, read once at import. The scatter
# path (cumsum + drop-mode scatter) measured ~60% faster than the
# bitonic sort on v5e for the per-level compaction; EMQX_COMPACT=sort
# keeps the sort variant selectable for A/B on other hardware.
_COMPACT_SCATTER = os.environ.get("EMQX_COMPACT", "scatter") == "scatter"


def _compact(cands: jax.Array, k: int) -> tuple[jax.Array, jax.Array]:
    """Compact candidate states [2K] (-1 invalid) into [K]; overflow if >K.

    Trie children are unique (each node has one parent), so no dedup is
    needed — compaction is pure packing.
    """
    valid = cands >= 0
    count = jnp.sum(valid)
    if _COMPACT_SCATTER:
        pos = jnp.cumsum(valid) - 1
        packed = jnp.full((k,), -1, dtype=cands.dtype).at[
            jnp.where(valid, pos, k)].set(cands, mode="drop")
    else:
        # Descending sort packs valid states to the front; -1s sink.
        packed = -jnp.sort(-cands)[:k]
    return packed, count > k


def depth_bucket(word_ids, n_words, min_levels: int = 2):
    """Slice the level axis to exactly the batch's deepest topic.

    The scan runs L+1 steps whether or not any topic uses them
    (static shapes), so every padded level is pure waste — 9 steps
    instead of 6 for 5-level traffic costs ~45% extra walk. Exact
    depths give at most ``max_levels`` jit variants (≤16), all
    persistent-cache friendly; that beats paying pow2 padding on
    every batch forever.

    Call with host (numpy) arrays, before device transfer. Topics
    flagged too-deep (n_words < 0) stay on the overflow path.
    """
    import numpy as _np

    L = word_ids.shape[1]
    max_n = int(_np.max(n_words)) if n_words.size else 0
    lb = min(max(max_n, min_levels, 1), L)
    return word_ids[:, :lb], n_words


@functools.partial(jax.jit, static_argnames=("k", "m"))
def match_batch(
    auto: Automaton,
    word_ids: jax.Array,   # int32[B, L]
    n_words: jax.Array,    # int32[B] (-1 = too many levels → overflow)
    sys_mask: jax.Array,   # bool[B]
    *,
    k: int = 64,
    m: int = 128,
) -> MatchResult:
    """Match a publish batch against the automaton. See module doc."""
    L = word_ids.shape[1]
    iters = max(1, math.ceil(math.log2(auto.edge_word.shape[0] + 1)))

    def one(words: jax.Array, n: jax.Array, is_sys: jax.Array):
        active0 = jnp.full((k,), -1, dtype=jnp.int32).at[0].set(0)
        # Pad the level axis: step L sees PAD words only (end-of-topic).
        words_ext = jnp.concatenate([words, jnp.full((1,), -2, dtype=jnp.int32)])

        def step(carry, xs):
            active, ovf = carry
            word, l = xs
            alive = active >= 0
            at_root_sys = (l == 0) & is_sys
            walking = l < n
            ending = l == n

            if auto.node_packed is not None:
                # one [K, 4]-row gather: plus | hash_filter | end_filter
                node = auto.node_packed[jnp.maximum(active, 0)]
                plus_col = node[:, 0]
                hashf_col = node[:, 1]
                endf_col = node[:, 2]
            else:
                plus_col = auto.plus_child[jnp.maximum(active, 0)]
                hashf_col = auto.hash_filter[jnp.maximum(active, 0)]
                endf_col = auto.end_filter[jnp.maximum(active, 0)]

            # '#'-child terminals at every live level (match_# semantics)
            emit_h = jnp.where(
                alive & (walking | ending) & ~at_root_sys, hashf_col, -1)
            # exact terminals at end-of-topic
            emit_e = jnp.where(alive & ending, endf_col, -1)

            if auto.ht_packed is not None or auto.ht_state is not None:
                lit = _edge_lookup_hash(auto, active, word)
            else:
                lit = jax.vmap(
                    lambda s: _edge_lookup(auto, iters, s, word))(active)
            plus = jnp.where(alive & ~at_root_sys, plus_col, -1)
            cands = jnp.where(walking, jnp.concatenate([lit, plus]), -1)
            nxt, over = _compact(cands, k)
            return (nxt, ovf | over), jnp.concatenate([emit_h, emit_e])

        levels = jnp.arange(L + 1, dtype=jnp.int32)
        (_, ovf), emits = lax.scan(
            step, (active0, jnp.asarray(False)), (words_ext, levels))
        flat = emits.reshape(-1)
        valid = flat >= 0
        cnt = jnp.sum(valid)
        # final emit-packing: cumsum + drop-mode scatter into the m
        # output slots (same packing as _compact; the old descending
        # sort re-measured ~L·K·log² slower once timings forced true
        # device completion)
        pos = jnp.cumsum(valid) - 1
        ids = jnp.full((m,), -1, dtype=flat.dtype).at[
            jnp.where(valid, pos, m)].set(flat, mode="drop")
        too_long = n < 0
        return MatchResult(
            ids=jnp.where(too_long, -1, ids),
            count=jnp.where(too_long, 0, jnp.minimum(cnt, m)).astype(jnp.int32),
            overflow=ovf | (cnt > m) | too_long,
        )

    return jax.vmap(one)(word_ids, n_words, sys_mask)
