"""The compiled NFA-walk topic matcher — the publish hot loop.

This replaces the reference's per-word ETS trie walk
(src/emqx_trie.erl:161-186, "HOT LOOP 1" in SURVEY §3.1) with a
batched, fixed-shape automaton walk under ``jit``:

  - a publish batch ``[B, L]`` of interned word ids is matched
    against the compressed walk tables (:mod:`emqx_tpu.ops.csr`) with
    one ``lax.scan`` over *hops* (compressed levels);
  - the NFA active set (≤ K states) advances by literal edges (one
    bucketed 2-choice hash probe pair per hop) and ``+`` edges; ``#``
    terminals are collected at every reached state (the reference's
    ``'match_#'`` at match_node/3 :161-186);
  - in **wide** mode an edge consumes up to ``take`` words per hop;
    the skipped chain words ride inline in the edge row and are
    compared exactly against the topic's word window — parity never
    rests on a hash value;
  - topics whose first word starts with ``$`` suppress root-level
    wildcards (emqx_trie.erl:162-163);
  - results are the matched filter ids ``[B, M]`` (-1 padded) plus a
    per-topic overflow flag. Overflowed topics (active set > K,
    matches > M, levels > L, or a walk that needed more hops than the
    compiled scan — possible only after deep patches) must be
    re-matched on the host oracle: parity is preserved by fallback,
    never silently truncated.

All shapes are static; there is no data-dependent control flow, so XLA
tiles and fuses the walk. ``vmap`` supplies the batch dimension. Row
widths (8 ints narrow / 64 ints wide) sit on the TPU's fast gather
paths — see the layout rationale in :mod:`emqx_tpu.ops.csr`.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from emqx_tpu.ops.csr import (NARROW_SLOT, WIDE_SLOT, Automaton,
                              hash_mix)

#: bits of the packed lane word reserved for the carried level
#: (wide mode): packed = state * 32 + level, level ≤ 31
_LVL_BITS = 5
_LVL_MASK = (1 << _LVL_BITS) - 1


class MatchResult(NamedTuple):
    ids: jax.Array       # int32[B, M] matched filter ids, -1 padded
    count: jax.Array     # int32[B] number of valid ids (clamped to M)
    overflow: jax.Array  # bool[B] — host-oracle fallback required


def walk_params(host_auto: Automaton, lb: int) -> dict:
    """Static kernel parameters for a batch sliced to ``lb`` levels.

    Read from the HOST automaton (``wt_slots``/``wt_take`` are python
    ints; ``hops_for_level`` a host array) — never through jit."""
    hl = host_auto.hops_for_level
    steps = int(hl[min(lb, len(hl) - 1)])
    return {"steps": steps, "slots": int(host_auto.wt_slots),
            "take": int(host_auto.wt_take)}


def depth_bucket(word_ids, n_words, min_levels: int = 2):
    """Slice the level axis to exactly the batch's deepest topic.

    The scan's step count derives from the automaton's hop depth AND
    the batch's deepest topic (walk_params), so every padded level is
    pure waste. Exact depths give at most ``max_levels`` jit variants
    (≤16), all persistent-cache friendly; that beats paying pow2
    padding on every batch forever.

    Call with host (numpy) arrays, before device transfer. Topics
    flagged too-deep (n_words < 0) stay on the overflow path.
    """
    import numpy as _np

    L = word_ids.shape[1]
    max_n = int(_np.max(n_words)) if n_words.size else 0
    lb = min(max(max_n, min_levels, 1), L)
    return word_ids[:, :lb], n_words


@functools.lru_cache(maxsize=None)
def _oddeven_network(n: int):
    """Batcher odd-even mergesort comparator pairs for pow2 ``n``."""
    pairs = []

    def merge(lo, length, r):
        step = r * 2
        if step < length:
            merge(lo, length, step)
            merge(lo + r, length, step)
            for i in range(lo + r, lo + length - r, step):
                pairs.append((i, i + r))
        else:
            pairs.append((lo, lo + r))

    def sort(lo, length):
        if length > 1:
            mid = length // 2
            sort(lo, mid)
            sort(lo + mid, mid)
            merge(lo, length, 1)

    sort(0, n)
    return tuple(pairs)


def _compact(cands: jax.Array, k: int) -> tuple[jax.Array, jax.Array]:
    """Compact candidate lanes [2K] (-1 invalid) into [K]; overflow if
    more than K valid. Trie children are unique (each node has one
    parent), so no dedup is needed — compaction is pure packing.

    Small sets sort on a fixed Batcher network: pure elementwise
    max/min on the VPU (descending — -1 lanes sink), measured well
    under the cumsum+scatter compact's per-element scatter cost at
    the 100K-unique batch scale. Wide sets (boosted k) fall back to
    the scatter (comparator count grows as n·log²n)."""
    n = cands.shape[0]
    count = jnp.sum(cands >= 0)
    if n <= 32:
        p2 = 1
        while p2 < n:
            p2 *= 2
        lanes = [cands[i] for i in range(n)] + \
            [jnp.full((), -1, cands.dtype)] * (p2 - n)
        for a, b in _oddeven_network(p2):
            hi = jnp.maximum(lanes[a], lanes[b])
            lo = jnp.minimum(lanes[a], lanes[b])
            lanes[a], lanes[b] = hi, lo
        packed = jnp.stack(lanes[:k])
    else:
        valid = cands >= 0
        pos = jnp.cumsum(valid) - 1
        packed = jnp.full((k,), -1, dtype=cands.dtype).at[
            jnp.where(valid, pos, k)].set(cands, mode="drop")
    return packed, count > k


@functools.partial(jax.jit,
                   static_argnames=("k", "m", "steps", "slots", "take",
                                    "pack_ids"))
def match_batch(
    auto: Automaton,
    word_ids: jax.Array,   # int32[B, L]
    n_words: jax.Array,    # int32[B] (-1 = too many levels → overflow)
    sys_mask: jax.Array,   # bool[B]
    *,
    k: int = 16,
    m: int = 64,
    steps: int | None = None,
    slots: int = 2,
    take: int = 1,
    pack_ids: bool = True,
) -> MatchResult:
    """Match a publish batch against the walk tables. See module doc.

    ``steps``/``slots``/``take`` are the static kernel parameters from
    :func:`walk_params` (defaults suit narrow tables and a full-depth
    walk).

    ``pack_ids=False`` returns ``ids`` as the RAW emit slots
    ``[B, steps*2k]`` (-1 holes, unordered) instead of compacting
    into ``m``: callers that feed :func:`~emqx_tpu.ops.pack
    .pack_matches` next would pay the per-topic cumsum+scatter twice
    (~22ms/batch at the 100K-unique scale) — the global pack subsumes
    it. Keep packing where a consumer's cost scales with the id
    width (per-slot fan-out gathers: the sharded publish step, the
    shared-group pick)."""
    L = word_ids.shape[1]
    if steps is None:
        steps = L + 1
    wide = take > 1
    if wide and L > _LVL_MASK:
        # the packed lane word carries the level in _LVL_BITS bits;
        # deeper batches must use narrow tables (compress_automaton
        # never emits wide ones for them)
        raise ValueError(
            f"wide walk supports at most {_LVL_MASK} levels, got {L}")
    sw = WIDE_SLOT if wide else NARROW_SLOT
    nb = auto.wt.shape[0]
    seed = auto.wt_seed[0]

    def one(words: jax.Array, n: jax.Array, is_sys: jax.Array):
        if wide:
            # word windows: win_mat[l] = words padded beyond the topic
            # [l : l+take] — the probe key word + the chain-compare
            # window (static shifts; one row gather per lane per hop)
            wp = jnp.concatenate(
                [words, jnp.full((take,), -2, dtype=jnp.int32)])
            win_mat = jnp.stack(
                [wp[l:l + take] for l in range(L)])  # [L, take]
        # narrow: level == step for every lane; word comes from xs
        words_ext = jnp.concatenate(
            [words, jnp.full((1,), -2, dtype=jnp.int32)])[:steps]

        def probe_narrow(state, word, b):
            row = auto.wt[b].reshape((k, slots, NARROW_SLOT))
            hit = (row[..., 0] == state[:, None]) & (
                row[..., 1] == word[:, None])
            return jnp.max(jnp.where(hit, row[..., 2], -1), axis=1)

        def probe_wide(state, lvl, win, b):
            row = auto.wt[b].reshape((k, slots, WIDE_SLOT))
            stake = row[..., 2]
            hit = (row[..., 0] == state[:, None]) & (
                row[..., 1] == win[:, None, 0])
            # exact chain verify: every consumed word beyond the first
            # must equal the inline chain word
            for i in range(take - 1):
                hit &= (stake <= i + 1) | (
                    row[..., 4 + i] == win[:, None, 1 + i])
            hit &= lvl[:, None] + stake <= n
            child = jnp.max(jnp.where(hit, row[..., 3], -1), axis=1)
            adv = jnp.max(jnp.where(hit, stake, 0), axis=1)
            return child, adv

        def step_fn(carry, xs):
            active, ovf = carry
            if wide:
                state = jnp.where(active >= 0,
                                  active >> _LVL_BITS, -1)
                lvl = active & _LVL_MASK
            else:
                state = active
                word, lvl_s = xs
            alive = state >= 0
            s_idx = jnp.maximum(state, 0)
            node = auto.node2[s_idx]          # [K, 4] w4 gather
            plus_col, hashf_col, endf_col = (
                node[:, 0], node[:, 1], node[:, 2])
            if wide:
                at_root_sys = (active == 0) & is_sys
                walking = alive & (lvl < n)
                ending = alive & (lvl == n)
            else:
                at_root_sys = (lvl_s == 0) & is_sys & alive
                walking = alive & (lvl_s < n)
                ending = alive & (lvl_s == n)
            # '#'-child terminals at every reached state (match_#),
            # exact terminals at end-of-topic
            emit_h = jnp.where(
                (walking | ending) & ~at_root_sys, hashf_col, -1)
            emit_e = jnp.where(ending, endf_col, -1)

            if wide:
                win = win_mat[jnp.minimum(lvl, L - 1)]
                w0 = win[:, 0]
            else:
                win = None
                w0 = jnp.broadcast_to(word, state.shape)
            h1, h2 = hash_mix(state, w0, seed)
            b1 = (h1 & jnp.uint32(nb - 1)).astype(jnp.int32)
            b2 = (h2 & jnp.uint32(nb - 1)).astype(jnp.int32)
            if wide:
                c1, a1 = probe_wide(state, lvl, win, b1)
                c2, a2 = probe_wide(state, lvl, win, b2)
                child = jnp.maximum(c1, c2)
                adv = jnp.maximum(a1, a2)
                lit_ok = walking & (w0 >= 0) & (child >= 0)
                lit = jnp.where(
                    lit_ok,
                    (child << _LVL_BITS) | (lvl + adv), -1)
                plus_ok = walking & ~at_root_sys & (plus_col >= 0)
                plus = jnp.where(
                    plus_ok,
                    (jnp.maximum(plus_col, 0) << _LVL_BITS) | (lvl + 1),
                    -1)
            else:
                lit = jnp.maximum(probe_narrow(state, w0, b1),
                                  probe_narrow(state, w0, b2))
                lit = jnp.where(walking & (w0 >= 0), lit, -1)
                plus = jnp.where(walking & ~at_root_sys, plus_col, -1)
            cands = jnp.concatenate([lit, plus])
            nxt, over = _compact(cands, k)
            return (nxt, ovf | over), jnp.concatenate([emit_h, emit_e])

        active0 = jnp.full((k,), -1, dtype=jnp.int32).at[0].set(0)
        if wide:
            xs = None
        else:
            xs = (words_ext, jnp.arange(steps, dtype=jnp.int32))
        (residue, ovf), emits = lax.scan(
            step_fn, (active0, jnp.asarray(False)), xs, length=steps)
        # lanes still alive after the last step were produced but
        # never processed — their emits are missing. With a correct
        # hop bound this cannot happen; a patch that deepened the
        # automaton past the compiled bound flags those topics for
        # the exact host fallback instead of silently missing.
        if wide:
            r_lvl = residue & _LVL_MASK
            ovf = ovf | jnp.any((residue >= 0) & (r_lvl <= n))
        else:
            ovf = ovf | jnp.any((residue >= 0) & (steps <= n))
        flat = emits.reshape(-1)
        valid = flat >= 0
        cnt = jnp.sum(valid)
        too_long = n < 0
        if pack_ids:
            # emit-packing: cumsum + drop-mode scatter into the m
            # output slots (same packing as _compact's fallback)
            pos = jnp.cumsum(valid) - 1
            ids = jnp.full((m,), -1, dtype=flat.dtype).at[
                jnp.where(valid, pos, m)].set(flat, mode="drop")
            return MatchResult(
                ids=jnp.where(too_long, -1, ids),
                count=jnp.where(too_long, 0,
                                jnp.minimum(cnt, m)).astype(jnp.int32),
                overflow=ovf | (cnt > m) | too_long,
            )
        # raw slots: nothing can truncate, so m never overflows
        return MatchResult(
            ids=jnp.where(too_long, -1, flat),
            count=jnp.where(too_long, 0, cnt).astype(jnp.int32),
            overflow=ovf | too_long,
        )

    return jax.vmap(one)(word_ids, n_words, sys_mask)
