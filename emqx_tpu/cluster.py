"""Cluster layer: membership, replicated routes, cross-node
forwarding, node-down cleanup — behind one RPC seam.

Maps the reference's distribution stack (SURVEY §2.3):
  - ekka membership           → :class:`Cluster` join/leave/nodedown,
    transitive (membership is a set agreed by all members)
  - Mnesia route replication  → one logical route per (filter, dest)
    replicated to every member (bag semantics; local refcounts stay
    node-private and only edge transitions broadcast), reads stay
    node-local like replicated ram_copies (src/emqx_router.erl:77-86)
  - gen_rpc data plane        → :class:`Transport` — in-process
    :class:`LocalTransport` for tests/single-host multi-node; a real
    socket transport plugs in the same seam (the reference isolates
    RPC behind emqx_rpc for the same reason, SURVEY §4)
  - node-down route purge     → :meth:`Cluster.handle_nodedown`
    (emqx_router_helper:135-144, emqx_cm_registry:123-128)
  - shared groups             → one delivery per group cluster-wide:
    the publishing node picks ONE member node per (group, filter)
    (round-robin over nodes) and forwards; the picked node runs its
    local strategy (the reference picks over a replicated global
    member table, src/emqx_shared_sub.erl:229-244 — node-level
    round-robin then local pick approximates it without replicating
    member pids)

The TPU angle: each member keeps its own device automaton; route
replication means every chip's automaton covers the full cluster
filter set, so any node matches locally in one device call and only
*deliveries* cross nodes.
"""

from __future__ import annotations

import dataclasses
import hashlib
import logging
import queue
import threading
import time
from typing import Dict, List, Optional, Tuple

from emqx_tpu.concurrency import (any_thread, bg_thread,
                                  owner_loop, shared_state)
from emqx_tpu.types import Message

log = logging.getLogger("emqx_tpu.cluster")


class PeerUnavailableError(ConnectionError):
    """A call was refused WITHOUT touching the wire because the
    failure detector holds the peer suspect/down (docs/CLUSTER.md).
    Distinct from a plain ConnectionError on purpose: a suspect peer
    is *unconfirmed* — callers must degrade (skip the vote, hand out
    a fresh session) but never purge, which is exactly what the
    generic ``except ConnectionError: handle_nodedown`` sites do."""

    def __init__(self, node: str, state: str) -> None:
        super().__init__(f"peer {node} is {state} (fast-fail)")
        self.node = node
        self.state = state


@dataclasses.dataclass
class ClusterConfig:
    """``[cluster]`` TOML section: failure detector + auto-heal knobs
    (docs/CLUSTER.md). ``detector = false`` reproduces the EOF-only
    legacy behavior byte-for-byte — no heartbeats, no suspect state,
    no fast-fail, no bounded-coroutine calls, no auto-heal."""

    #: heartbeat failure detector (ok → suspect → down state machine
    #: over periodic per-peer pings). Off = legacy link-EOF detection.
    detector: bool = True
    #: seconds between heartbeat rounds
    heartbeat_interval_s: float = 1.0
    #: per-ping RTT deadline; a reply slower than this is a miss
    heartbeat_timeout_s: float = 1.0
    #: consecutive misses before ok → suspect (casts park, nothing
    #: is purged)
    suspect_after: int = 2
    #: consecutive misses before suspect → down (nodedown dispatched)
    down_after: int = 5
    #: consecutive successes before suspect → ok (hysteresis up)
    ok_after: int = 2
    #: a downed peer that reappears triggers an automatic rejoin
    #: handshake + anti-entropy reconciliation
    auto_heal: bool = True
    #: background anti-entropy sweep period (repairs missed
    #: at-most-once casts); 0 disables the sweep (heal-triggered
    #: syncs still run)
    anti_entropy_interval_s: float = 30.0
    #: per-peer RPC deadline — bounds the CALLER's wait and the
    #: in-flight coroutine (the link is dropped on expiry so a stale
    #: late reply can never desync the frame stream)
    call_timeout_s: float = 10.0
    #: calls to a suspect/down member raise PeerUnavailableError
    #: immediately instead of dialing into the timeout
    suspect_fast_fail: bool = True
    #: redial backoff to a peer whose dials keep failing
    #: (exponential from the base, capped at the max)
    redial_backoff_s: float = 0.5
    redial_backoff_max_s: float = 5.0

    #: live-reloadable knobs (emqx_tpu/reload.py, docs/OPERATIONS.md):
    #: the detector loop and the call gate read these per round /
    #: per call off the shared config object. ``detector`` decides
    #: what gets built; ``call_timeout_s`` is captured by the
    #: transport at construction; ``anti_entropy_interval_s`` by the
    #: heal worker's queue timeout (not a dataclass field:
    #: unannotated)
    RELOADABLE = frozenset({
        "heartbeat_interval_s", "heartbeat_timeout_s",
        "suspect_after", "down_after", "ok_after", "auto_heal",
        "suspect_fast_fail", "redial_backoff_s",
        "redial_backoff_max_s"})

    def __post_init__(self) -> None:
        if self.heartbeat_interval_s <= 0:
            raise ValueError("cluster.heartbeat_interval_s must be > 0")
        if self.heartbeat_timeout_s <= 0:
            raise ValueError("cluster.heartbeat_timeout_s must be > 0")
        if self.suspect_after < 1:
            raise ValueError("cluster.suspect_after must be >= 1")
        if self.down_after < self.suspect_after:
            raise ValueError(
                "cluster.down_after must be >= suspect_after")
        if self.ok_after < 1:
            raise ValueError("cluster.ok_after must be >= 1")
        if self.anti_entropy_interval_s < 0:
            raise ValueError(
                "cluster.anti_entropy_interval_s must be >= 0")
        if self.call_timeout_s <= 0:
            raise ValueError("cluster.call_timeout_s must be > 0")
        if self.redial_backoff_s <= 0:
            raise ValueError("cluster.redial_backoff_s must be > 0")
        if self.redial_backoff_max_s < self.redial_backoff_s:
            raise ValueError(
                "cluster.redial_backoff_max_s must be >= "
                "redial_backoff_s")


class Transport:
    """RPC seam (emqx_rpc): deliver opaque calls to peer nodes."""

    def cast(self, node: str, op: str, *args) -> None:
        raise NotImplementedError

    def call(self, node: str, op: str, *args):
        raise NotImplementedError

    # -- failure-detector seam (no-ops for transports without one) --------

    def peer_state(self, node: str) -> str:
        """``ok`` | ``suspect`` | ``down`` — transports without a
        detector report every peer healthy."""
        return "ok"

    def health_info(self) -> Dict[str, dict]:
        """Per-peer detector state for operators (ctl/stats)."""
        return {}

    def drain_counters(self) -> Dict[str, int]:
        """Transport-level event counters since the last drain."""
        return {}

    def set_departed(self, node: str) -> None:
        """Mark a peer as having LEFT deliberately: the detector must
        stop probing it for reappearance (a left node answering pings
        must not be dragged back into the cluster)."""


class LocalTransport(Transport):
    """In-process transport: peers are Cluster objects in this
    process (the reference tests fake remote nodes the same way,
    test/emqx_broker_SUITE)."""

    def __init__(self) -> None:
        self._peers: Dict[str, "Cluster"] = {}

    def register(self, node: str, cluster: "Cluster") -> None:
        self._peers[node] = cluster

    def unregister(self, node: str) -> None:
        self._peers.pop(node, None)

    def cast(self, node: str, op: str, *args) -> None:
        peer = self._peers.get(node)
        if peer is None:
            raise ConnectionError(f"node down: {node}")
        peer.handle_rpc(op, *args)

    def call(self, node: str, op: str, *args):
        peer = self._peers.get(node)
        if peer is None:
            raise ConnectionError(f"node down: {node}")
        return peer.handle_rpc(op, *args)


@shared_state(lock="_lock", attrs=("members", "_registry"))
class Cluster:
    """Per-node cluster agent: wires a Node's broker/router into the
    membership + replication + forwarding protocol."""

    def __init__(self, node, transport: Optional[Transport] = None,
                 config: Optional[ClusterConfig] = None) -> None:
        self.node = node            # emqx_tpu.node.Node
        self.name = node.name
        self.transport = transport or LocalTransport()
        self.config = config
        self.members: List[str] = [self.name]
        self._lock = threading.Lock()
        # cluster-plane event counters, drained into Metrics by the
        # node's stats tick (names land as ``cluster.<key>``)
        self._counters: Dict[str, int] = {}
        # anti-entropy bookkeeping for ctl/stats (docs/CLUSTER.md)
        self._ae_info: Dict[str, object] = {
            "sweeps": 0, "repairs": 0, "last_sweep_ts": None,
            "last_repairs": 0, "last_peer": None}
        # auto-heal / background-sweep worker: heal requests from the
        # failure detector queue here; queue timeouts pace the sweep.
        # Only a configured cluster WITH the detector on spawns the
        # thread — the bare Cluster(node, transport) construction
        # every existing test uses stays thread-free, and
        # ``detector = false`` reproduces the legacy EOF-only build
        # in full (no heal worker, no background sweep)
        self._heal_q: "queue.Queue" = queue.Queue()
        self._healing: set = set()
        self._stopping = False
        self._heal_thread: Optional[threading.Thread] = None
        if config is not None and config.detector and (
                config.auto_heal or config.anti_entropy_interval_s > 0):
            self._heal_thread = threading.Thread(
                target=self._heal_main, daemon=True,
                name=f"cluster-heal-{self.name}")
            self._heal_thread.start()
        self._shared_rr: Dict[Tuple[str, str], int] = {}
        # replicated per-node shared-group member counts: the
        # reference picks over the full replicated member table
        # (src/emqx_shared_sub.erl:229-244); replicating COUNTS gives
        # the same distribution without replicating member pids
        self._shared_weights: Dict[Tuple[str, str, str], int] = {}
        # replicated clientid -> node registry (emqx_cm_registry:
        # Mnesia bag emqx_channel_registry); covers live and detached
        # sessions so cross-node takeover can find the owner
        self._registry: Dict[str, str] = {}
        # takeover parking (docs/OPERATIONS.md): a session handed out
        # by ``takeover_client`` whose REPLY is lost (stale link mid
        # rolling-restart) must not evaporate — the owner parks it
        # until the taker's client_up confirms custody; the taker's
        # retry (paced by the ServerBusy answer) collects it.
        # cid -> (session, parked_at); TTL-pruned, client_up-cleared
        self._takeover_parked: Dict[str, tuple] = {}
        # distributed per-clientid lock (emqx_cm_locker / ekka_locker
        # quorum) — taken by cm around open/discard/takeover
        from emqx_tpu.cm_locker import ClusterLocker
        self.locker = ClusterLocker(self)
        node.cm.cluster = self
        if hasattr(node, "cluster"):
            node.cluster = self  # node-level accessor (ctl, config)
        # replicated durability (replication.py, docs/DURABILITY.md):
        # every clustered node can hold warm standby replicas for its
        # peers; a node whose [durability] standby names a peer also
        # arms the journal shipper
        from emqx_tpu.replication import ReplicationManager
        self.replication = ReplicationManager(node, self)
        node.replication = self.replication
        dur = getattr(node, "durability", None)
        if dur is not None and dur.cfg.standby_list:
            self.replication.arm_shipper(dur)
        # intercept local route mutations for replication
        self._orig_add = node.router.add_route
        self._orig_del = node.router.delete_route
        node.router.add_route = self._add_route_replicated
        node.router.delete_route = self._del_route_replicated
        node.broker.forwarder = self._forward
        node.broker.shared_router = self._route_shared
        # intercept shared-membership mutations to replicate weights
        shared = node.broker.shared
        self._orig_shared_sub = shared.subscribe
        self._orig_shared_unsub = shared.unsubscribe
        self._orig_shared_down = shared.subscriber_down
        shared.subscribe = self._shared_sub_replicated
        shared.unsubscribe = self._shared_unsub_replicated
        shared.subscriber_down = self._shared_down_replicated
        # replicate the ban table (the reference's emqx_banned is a
        # replicated Mnesia table: a ban on one node bans everywhere)
        banned = node.broker.banned
        if banned is not None:
            self._orig_ban_create = banned.create
            self._orig_ban_delete = banned.delete
            self._orig_ban_auto = banned.create_unless_outlasted
            banned.create = self._ban_create_replicated
            banned.delete = self._ban_delete_replicated
            banned.create_unless_outlasted = self._ban_auto_replicated
        # retained-store replication seam: the retainer module (if
        # loaded, now or later) broadcasts its stores/deletes (the
        # reference plugin replicates via Mnesia)
        node.retain_replicate = (
            lambda topic, msg, ts=None: self._broadcast(
                "retain_set", topic, msg, ts))
        if isinstance(self.transport, LocalTransport):
            self.transport.register(self.name, self)
        elif hasattr(self.transport, "cluster"):
            # socket transport: inbound RPCs route back through us
            self.transport.cluster = self

    # -- membership (ekka) ------------------------------------------------

    def join(self, other: "Cluster") -> None:
        """Merge the two membership sets cluster-wide and sync routes
        to/from every member (transitive: all members of both sides
        learn the union)."""
        union = sorted(set(self.members) | set(other.members))
        self._propagate_union(union)

    def join_remote(self, host: str, port: int) -> None:
        """Join a cluster through a peer's socket address (the
        ``emqx_ctl cluster join`` flow over the wire): fetch the
        peer's member + address book, merge, propagate the union to
        every member, then sync routes all around — the same protocol
        :meth:`join` runs for in-process peers."""
        tr = self.transport
        info = tr.call_addr((host, port), "cluster_info")
        addrs = dict(info["addrs"])
        # the peer self-reports its bind address, which may be
        # unroutable from here (0.0.0.0, loopback on another host);
        # the dialed address demonstrably works — use it, and
        # propagate it to the rest of the cluster
        addrs[info["name"]] = (host, port)
        addrs.update(tr.addr_book())
        if tr.host in ("0.0.0.0", "::", ""):
            # same problem in reverse: advertise the local interface
            # the working dial went out of, not the wildcard bind
            local_ip = tr.local_ip_for((host, port))
            if local_ip:
                addrs[self.name] = (local_ip, tr.port)
        union = sorted(set(self.members) | set(info["members"]))
        for m, a in addrs.items():
            if m != self.name:
                tr.register_peer(m, *a)
        self._propagate_union(union, addrs)

    def _propagate_union(self, union: List[str],
                         addrs: Optional[Dict] = None,
                         sync_routes: bool = True) -> None:
        """Tell every member the merged membership (and, over a
        socket transport, the address book), then sync routes all
        around — shared by in-process join and join_remote.
        ``sync_routes=False`` (the auto-heal path) skips the blunt
        full route push: anti-entropy re-pushes only the diff.

        A member that died moments ago may still be in the book its
        peers handed us (their probe hasn't declared nodedown yet):
        an unreachable member must not abort the join — it is skipped
        and the membership machinery reaps it (round-4 finding: a
        restarted worker crashed joining through a survivor because
        the book still listed its own dead predecessor)."""
        unreachable: List[str] = []
        suspect: List[str] = []
        for m in union:
            if m == self.name:
                self._set_members(union)
                continue
            try:
                if addrs is not None:
                    self.transport.call(m, "set_members_net", union,
                                        addrs)
                else:
                    self.transport.call(m, "set_members", union)
            except PeerUnavailableError as e:
                # suspect ≠ dead: skip it (the heal/anti-entropy
                # machinery re-merges once the detector clears it)
                # but NEVER purge on suspicion
                log.warning("join: member %s suspect (%s); skipping",
                            m, e)
                suspect.append(m)
            except ConnectionError as e:
                log.warning("join: member %s unreachable (%s); "
                            "skipping", m, e)
                unreachable.append(m)
        for m in union if sync_routes else ():
            if m == self.name:
                self._push_owned_routes()
            elif m not in unreachable and m not in suspect:
                try:
                    self.transport.call(m, "push_routes")
                except PeerUnavailableError as e:
                    log.warning("join: member %s suspect (%s); "
                                "skipping push", m, e)
                    suspect.append(m)
                except ConnectionError as e:
                    log.warning("join: push_routes to %s failed (%s)",
                                m, e)
                    unreachable.append(m)
        # reap what we just proved dead, the way every other
        # ConnectionError site here does — the dead name must not
        # linger as a member/broadcast target until some later cast
        # happens to fail. Suspect members are NOT reaped, and with
        # the detector armed the verdict is deferred to it.
        for m in unreachable:
            self._peer_call_failed(m)

    @any_thread
    def _set_members(self, members: List[str]) -> None:
        with self._lock:
            self.members = list(members)

    def _push_owned_routes(self) -> None:
        for flt in self.node.router.topics():
            for r in self.node.router.lookup_routes(flt):
                if self._owned(r.dest, self.name):
                    self._broadcast("route_add", flt, r.dest)
        # ...and this node's clientid-registry claims, batched (ONE
        # cast per peer). The registry was the only replicated plane
        # the join sync skipped: a freshly restarted node served
        # reconnects with a FRESH session (session-present false,
        # stranding the real session's queued messages on its
        # holder) for the anti-entropy interval — the rolling-restart
        # proof tripped exactly this window
        with self._lock:
            owned = [c for c, n in self._registry.items()
                     if n == self.name]
        if owned:
            self._broadcast("registry_sync", self.name, owned)
        # new joiners also need our shared-group weights
        for (group, flt), members in \
                self.node.broker.shared._subs.items():
            if members:
                self._broadcast("shared_weight", group, flt,
                                self.name, len(members))
        # ...and the ban table (idempotent: every member pushes, the
        # receiving apply() merges longest-ban-wins). Expired rules
        # are swept first so a stale entry is never pushed at all.
        banned = self.node.broker.banned
        if banned is not None:
            banned.expire()
            for rule in banned.info():
                # sync push: merge (longest wins), never overwrite
                self._broadcast("ban_add", rule.who[0], rule.who[1],
                                rule.by, rule.reason, rule.until, False)
        # ...and the retained store: ONE batched cast per peer
        # (idempotent timestamp-LWW on the receiver; entry-per-cast
        # would pickle a Message per entry per peer)
        ret = self._retainer()
        if ret is not None:
            entries = ret.entries()
            tombs = ret.tombstones()
            if entries or tombs:
                self._broadcast("retain_sync", entries, tombs)

    def _retainer(self):
        mods = getattr(self.node, "modules", None)
        return mods._loaded.get("retainer") if mods is not None else None

    @staticmethod
    def _owned(dest, name: str) -> bool:
        return dest == name or (isinstance(dest, tuple) and dest[1] == name)

    def leave(self) -> None:
        """Leave the cluster: tell everyone, purge every ex-member's
        routes locally (the symmetric half of nodedown). The
        ``leaving`` announcement (vs a detector-observed death) also
        tells each peer's failure detector to stop probing us for
        reappearance — a deliberately departed node answering pings
        must not be auto-healed back in."""
        ex = [m for m in self.members if m != self.name]
        for m in ex:
            try:
                self.transport.cast(m, "leaving", self.name)
            except ConnectionError:
                pass
        with self._lock:
            self.members = [self.name]
        for m in ex:
            self._purge_node_routes(m)

    @any_thread
    def _peer_call_failed(self, name: str) -> None:
        """A call/cast to a member failed with a transport error.
        With the failure detector armed, one transient error is NOT
        a death verdict: the failed dial already dropped the link
        (straight to suspect) and the detector's miss counting
        delivers the real verdict — the legacy instant
        ``handle_nodedown`` here used to purge a LIVE peer's registry
        entries and spuriously promote against it off one stale-link
        error during a rolling restart (caught live by
        tests/test_drain.py). Detector-less transports keep the
        legacy behavior: the error IS the only failure detection."""
        tr = self.transport
        if getattr(tr, "_hb_enabled", False):
            self._count("rpc.errors")
            return
        self.handle_nodedown(name)

    @any_thread
    def handle_nodedown(self, name: str) -> None:
        """Purge a dead member's routes + registry entries
        (emqx_router_helper cleanup + emqx_cm_registry
        cleanup_channels, §3.5)."""
        with self._lock:
            if name in self.members:
                self.members.remove(name)
            dead = [c for c, n in self._registry.items() if n == name]
            for c in dead:
                del self._registry[c]
            for k in [k for k in self._shared_weights if k[2] == name]:
                del self._shared_weights[k]
        # a dead node's clientid locks release NOW (ekka_locker's
        # monitored-lock cleanup) — waiters unblock immediately
        self.locker.drop_owner(name)
        self._purge_node_routes(name)
        # warm-standby failover (replication.py): AFTER the purge —
        # the promotion re-installs the dead primary's durable state
        # remapped to this node with exact refcounts. On its own
        # thread: nodedown is dispatched on the transport IO loop,
        # and the promotion ARBITRATION makes synchronous calls to
        # co-standbys that must not block that loop against itself
        if self.replication is not None \
                and name in self.replication.replicas:
            def _promote_check(repl=self.replication, dead=name):
                try:
                    repl.maybe_promote(dead)
                except Exception:
                    log.exception("standby promotion check for %s "
                                  "failed", dead)
            t = threading.Thread(
                target=_promote_check, daemon=True,
                name=f"repl-promote-{self.name}")
            t.start()

    # -- clientid registry + cross-node takeover (emqx_cm_registry) -------

    @any_thread
    def client_up(self, client_id: str) -> None:
        with self._lock:
            self._registry[client_id] = self.name
        self._broadcast("client_up", client_id, self.name)

    @any_thread
    def client_down(self, client_id: str) -> None:
        with self._lock:
            if self._registry.get(client_id) == self.name:
                self._registry.pop(client_id, None)
        self._broadcast("client_down", client_id, self.name)

    def locate_client(self, client_id: str) -> Optional[str]:
        return self._registry.get(client_id)

    def claim_parked(self, client_id: str):
        """Collect a reply-loss-parked takeover copy locally (a
        client dialing the parking node directly must find its
        session, not a fresh one)."""
        ent = self._takeover_parked.pop(client_id, None)
        return ent[0] if ent is not None else None

    @any_thread
    def reassign_client(self, client_id: str, owner: str) -> None:
        """Point the registry at ``owner`` on every member (the
        replication layer's custody-chain repair: a node dropping
        its stale copy of a session must also retract its
        owner-authoritative registry claim, or anti-entropy
        propagates the wrong owner forever)."""
        with self._lock:
            self._registry[client_id] = owner
        self._broadcast("client_up", client_id, owner)

    def remote_discard(self, client_id: str, node: str) -> None:
        """Old session on another node must die (clean start)."""
        try:
            self.transport.call(node, "discard_client", client_id)
        except PeerUnavailableError:
            # suspect owner: proceed without the discard (the CONNECT
            # must not block); anti-entropy reconciles the registry
            # once the peer recovers or is confirmed down
            log.warning("remote discard of %s skipped: owner %s "
                        "suspect", client_id, node)
        except ConnectionError:
            self._peer_call_failed(node)

    def remote_takeover(self, client_id: str, node: str):
        """Pull the session from its current owner node
        (emqx_cm:takeover_session RPC, src/emqx_cm.erl:263-272). The
        caller's name rides along so the owner can move the route
        contributions with the session (see _local_takeover)."""
        try:
            return self.transport.call(node, "takeover_client",
                                       client_id, self.name)
        except PeerUnavailableError as e:
            if e.state == "suspect":
                # suspect ≠ dead: the registry NAMES this owner, so
                # the session exists — let the caller (the cm chase)
                # wait out the detector's hysteresis bounded instead
                # of instantly minting a fresh session (a transient
                # heartbeat blip at reconnect time used to cost the
                # client its session — caught by the rolling-restart
                # proof). A confirmed-down owner still degrades to a
                # fresh session immediately.
                return {"suspect": node}
            log.warning("remote takeover of %s skipped: owner %s "
                        "%s — fresh session", client_id, node,
                        e.state)
            return None
        except ConnectionError:
            self._peer_call_failed(node)
            if getattr(self.transport, "_hb_enabled", False):
                # the call may have EXECUTED with the reply lost (the
                # owner parked the handed session): answer BUSY so
                # the client's retry re-chases and collects it —
                # returning None here minted a fresh session over a
                # parked live one (rolling-restart proof)
                return {"suspect": node}
            return None
        except Exception:
            # a takeover failure must degrade to a fresh session,
            # never kill the CONNECT (the reference's badrpc path)
            log.exception("remote takeover of %s from %s failed",
                          client_id, node)
            return None

    def _local_takeover(self, client_id: str, taker=None):
        cm = self.node.cm
        # TTL prune of the parking lot (bounded bookkeeping)
        now = time.time()
        for cid in [c for c, (_s, ts) in
                    self._takeover_parked.items() if now - ts > 60.0]:
            self._takeover_parked.pop(cid, None)
        chan = cm.lookup_channel(client_id)
        if chan is None and self.replication is not None \
                and self.replication.adopting(client_id):
            # mid-hand-off adopted copy (see ReplicationManager
            # .adopting): not serveable until the final marker lands
            return {"suspect": self.name}
        dr = getattr(self.node, "drain", None)
        if chan is None and dr is not None and dr.active \
                and dr.target is not None \
                and (client_id in cm._detached
                     or client_id in self._takeover_parked):
            # custody is ALREADY moving through the drain hand-off
            # (dual-route, digest-verified — loss-free under live
            # traffic). A client-initiated pull racing it would rip
            # the session out mid-transfer and drop every forward in
            # the pull window; defer instead — the caller answers
            # ServerBusy and the client's retry lands on the target
            return {"suspect": self.name}
        sess = None
        if chan is not None:
            sess = cm._takeover(chan)
        elif client_id in cm._detached:
            sess, _ts, _exp = cm._detached.pop(client_id)
        if sess is None and client_id in self._takeover_parked:
            # a previous hand-out's reply was lost: the taker's retry
            # collects the parked copy instead of finding nothing
            return self._takeover_parked.pop(client_id)[0]
        cm.cancel_will(client_id)  # connection re-established elsewhere
        if sess is None:
            # not held here (anymore): if OUR registry already knows
            # the new custodian — a drain hand-off or failback moved
            # it while the caller still held a stale claim — answer
            # with a forwarding marker so the caller chases the
            # custody chain instead of minting a fresh session
            # (docs/OPERATIONS.md; the rolling-restart proof tripped
            # exactly this window)
            with self._lock:
                owner = self._registry.get(client_id)
            if owner is not None and owner != self.name:
                return {"moved": owner}
            return None
        if taker:
            # move the route contributions WITH the session — BEFORE
            # detaching its dispatch wiring: a stale self-dest here
            # silently swallowed every locally-routed message until
            # anti-entropy (the publish was acked with routes >= 1
            # but the dispatch found no subscriber), and the taker's
            # own route_add broadcast is an at-most-once cast that
            # can park behind a suspect blip. Install the taker's
            # dest locally NOW (idempotent; its broadcast confirms)
            # and drop this node's refs through the replicated
            # wrapper (the zero-edge broadcasts route_del). Ordering:
            # with routes moved first, a local publish in the
            # detach window routes to the taker; one landing just
            # before still reaches the (still-wired) session object
            # and travels with it.
            from emqx_tpu.replication import _sub_route
            for key in list(getattr(sess, "subscriptions", {})):
                try:
                    flt, dest = _sub_route(key, taker)
                    self._apply_route("add", flt, dest)
                    flt2, dest2 = _sub_route(key, self.name)
                    if self.node.router.route_refs(flt2, dest2) > 0:
                        self.node.router.delete_route(flt2,
                                                      dest=dest2)
                except Exception:
                    log.exception("moving route of %r for %r failed",
                                  key, client_id)
        # hand-off: drop table entries here without death-path
        # side effects; the new node's resume() resubscribes.
        # The broker/notify references MUST be severed: over a
        # socket transport the session travels pickled, and a
        # broker drags thread locks + device arrays with it
        self.node.broker.detach_subscriber(sess)
        sess.notify = None
        sess.broker = None
        d = getattr(self.node, "durability", None)
        if d is not None and getattr(sess, "durable", False):
            # the session now lives on the taking node: a stale
            # sess.state left in OUR journal would ship to our
            # standbys and resurrect a zombie copy when we die
            d.session_closed(client_id)
        # park until the taker's client_up confirms custody: if the
        # REPLY below is lost to a broken link, the severed session
        # would otherwise be gone from every node
        self._takeover_parked[client_id] = (sess, time.time())
        return sess

    def _purge_node_routes(self, name: str) -> None:
        self.node.router.cleanup_routes(name)
        # shared-group routes carry (group, node) dests
        for flt in list(self.node.router.topics()):
            for r in self.node.router.lookup_routes(flt):
                if isinstance(r.dest, tuple) and r.dest[1] == name:
                    self._orig_del(flt, dest=r.dest)

    # -- route replication (mnesia ram_copies analogue) -------------------

    def _add_route_replicated(self, flt: str, dest=None):
        dest = self.name if dest is None else dest
        fresh = not self.node.router.has_dest(flt, dest)
        fid = self._orig_add(flt, dest=dest)
        if fresh:  # only edge transitions replicate (bag semantics)
            self._broadcast("route_add", flt, dest)
        return fid

    def _del_route_replicated(self, flt: str, dest=None) -> None:
        dest = self.name if dest is None else dest
        self._orig_del(flt, dest=dest)
        if not self.node.router.has_dest(flt, dest):
            self._broadcast("route_del", flt, dest)

    def _broadcast(self, op: str, *args) -> None:
        for m in list(self.members):
            if m == self.name:
                continue
            try:
                self.transport.cast(m, op, *args)
            except ConnectionError:
                self._peer_call_failed(m)

    def _apply_route(self, op: str, flt: str, dest) -> None:
        """Idempotent remote apply — always through the ORIGINAL
        router methods (a replicated apply must never re-broadcast)."""
        if op == "add":
            if not self.node.router.has_dest(flt, dest):
                self._orig_add(flt, dest=dest)
        else:
            dests = self.node.router._routes.get(flt)
            if dests is not None and dest in dests:
                dests[dest] = 1
                self._orig_del(flt, dest=dest)

    # -- data plane (gen_rpc analogue) ------------------------------------

    def _forward(self, node: str, flt: str, msg: Message) -> None:
        if "_wire" in msg.headers:
            # the local-delivery wire cache must not be pickled onto
            # the wire: rebuild with the headers minus the cache (a
            # full msg.copy() would deep-copy the very bytes being
            # discarded, once per destination node)
            msg = Message(
                topic=msg.topic, payload=msg.payload, qos=msg.qos,
                from_=msg.from_, flags=dict(msg.flags),
                headers={k: v for k, v in msg.headers.items()
                         if k != "_wire"},
                id=msg.id, timestamp=msg.timestamp)
        try:
            self.transport.cast(node, "forward", flt, msg)
        except ConnectionError:
            self._peer_call_failed(node)

    def _local_shared_count(self, group: str, flt: str) -> int:
        return len(self.node.broker.shared._subs.get((group, flt), ()))

    def _broadcast_weight(self, group: str, flt: str) -> None:
        self._broadcast("shared_weight", group, flt, self.name,
                        self._local_shared_count(group, flt))

    def _shared_sub_replicated(self, group, flt, sub) -> None:
        self._orig_shared_sub(group, flt, sub)
        self._broadcast_weight(group, flt)

    def _shared_unsub_replicated(self, group, flt, sub) -> None:
        self._orig_shared_unsub(group, flt, sub)
        self._broadcast_weight(group, flt)

    def _shared_down_replicated(self, sub) -> None:
        before = [k for k, m in self.node.broker.shared._subs.items()
                  if sub in m]
        self._orig_shared_down(sub)
        for group, flt in before:
            self._broadcast_weight(group, flt)

    def _ban_create_replicated(self, kind, value, by="admin",
                               reason="", duration=None):
        rule = self._orig_ban_create(kind, value, by=by, reason=reason,
                                     duration=duration)
        # live create: peers overwrite, as this node's own create()
        # just did — LWW everywhere keeps the tables convergent
        self._broadcast("ban_add", kind, value, by, reason,
                        rule.until, True)
        return rule

    def _ban_auto_replicated(self, kind, value, by="auto", reason="",
                             duration=None):
        rule = self._orig_ban_auto(kind, value, by=by, reason=reason,
                                   duration=duration)
        if rule is not None:  # only an actual install replicates —
            # and with MERGE semantics (overwrite=False), matching
            # create_unless_outlasted's own never-downgrade contract:
            # an auto ban racing a replicated operator ban must not
            # replace it on the peers
            self._broadcast("ban_add", kind, value, by, reason,
                            rule.until, False)
        return rule

    def _ban_delete_replicated(self, kind, value) -> None:
        self._orig_ban_delete(kind, value)
        self._broadcast("ban_del", kind, value)

    def _weight(self, group: str, flt: str, node: str) -> int:
        if node == self.name:
            return max(1, self._local_shared_count(group, flt))
        return max(1, self._shared_weights.get((group, flt, node), 1))

    def _route_shared(self, group: str, flt: str, nodes: List[str],
                      msg: Message) -> int:
        """One delivery per (group, filter) cluster-wide: weighted
        round-robin over the member nodes (weight = that node's
        member count, replicated on membership changes), then the
        picked node's local strategy chooses the subscriber — a node
        with 100 members gets 100x the share of a node with 1,
        matching the reference's pick over the global member table
        (src/emqx_shared_sub.erl:229-244)."""
        if not nodes:
            return 0
        key = (group, flt)
        ordered = sorted(nodes)
        # under the lock: the IO thread (forwarded publishes) and the
        # serving loop both route shared messages — the rr counter is
        # a read-modify-write, and weights are written by handle_rpc
        with self._lock:
            weights = [self._weight(group, flt, x) for x in ordered]
            total = sum(weights)
            n = (self._shared_rr.get(key, -1) + 1) % total
            self._shared_rr[key] = n
        target = ordered[-1]
        acc = 0
        for node_name, w in zip(ordered, weights):
            acc += w
            if n < acc:
                target = node_name
                break
        if target == self.name:
            return self.node.broker.shared.dispatch(group, flt, msg)
        try:
            self.transport.cast(target, "forward_shared", group, flt, msg)
            return 0  # remote delivery, not counted locally
        except ConnectionError:
            # availability: re-route this delivery around the failed
            # member either way; the death verdict is the detector's
            self._peer_call_failed(target)
            rest = [x for x in nodes if x != target]
            return self._route_shared(group, flt, rest, msg)

    # -- counters / observability -----------------------------------------

    def _count(self, key: str, n: int = 1) -> None:
        with self._lock:
            self._counters[key] = self._counters.get(key, 0) + n

    def drain_counters(self) -> Dict[str, int]:
        """Cluster + transport event-counter deltas since the last
        drain; the node's stats tick folds them into Metrics as
        ``cluster.<key>`` (docs/OBSERVABILITY.md)."""
        with self._lock:
            out = dict(self._counters)
            self._counters.clear()
        for k, v in self.transport.drain_counters().items():
            out[k] = out.get(k, 0) + v
        return out

    def ae_info(self) -> dict:
        """Anti-entropy sweep/repair summary for ctl + stats."""
        with self._lock:
            return dict(self._ae_info)

    # -- auto-heal + anti-entropy (docs/CLUSTER.md) -----------------------
    #
    # The replication casts above are at-most-once (gen_rpc async
    # cast semantics): a dropped cast silently diverges the replica
    # planes FOREVER in the pre-heal design. Anti-entropy closes the
    # loop: per-plane digests are exchanged and diffed, and only the
    # differing entries cross the wire again. It runs (a) as the
    # reconciliation half of an auto-heal rejoin and (b) as a
    # low-frequency background sweep (one peer per round).
    #
    # Consistency contract per plane:
    #   routes / registry / weights — OWNER-authoritative: each
    #     node's view of node X's entries is replaced by X's own set
    #     (adds AND stale deletes repaired, no tombstones needed);
    #   bans      — longest-ban-wins merge (Banned.apply sync rules);
    #   retained  — timestamp LWW with delete tombstones (the
    #     retainer's join-sync rules).

    def schedule_heal(self, name: str) -> None:
        """Queue an auto-heal rejoin with a reappeared peer.
        Thread-safe — called from the transport's IO loop."""
        if self._heal_thread is None or self.config is None \
                or not self.config.auto_heal or self._stopping:
            return
        self._heal_q.put(name)

    def close(self) -> None:
        """Stop the heal/anti-entropy worker and the journal shipper
        (Node.stop)."""
        self._stopping = True
        if self.replication is not None:
            self.replication.close()
        if self._heal_thread is not None:
            self._heal_q.put(None)
            self._heal_thread.join(timeout=5)
            self._heal_thread = None

    @bg_thread
    def _heal_main(self) -> None:
        interval = self.config.anti_entropy_interval_s or None
        while True:
            try:
                item = self._heal_q.get(timeout=interval)
            except queue.Empty:
                item = None  # sweep tick
            if self._stopping:
                return
            try:
                if item is None:
                    self._ae_sweep_once()
                else:
                    self._heal_rejoin(item)
            except Exception:
                log.exception("cluster heal/anti-entropy pass failed")

    def _heal_rejoin(self, name: str) -> None:
        """The auto-heal handshake with a reappeared peer: re-merge
        membership (the join protocol, minus its blunt full route
        push) and reconcile every replicated plane via anti-entropy.
        Both sides typically run this concurrently — every step is
        idempotent."""
        if name in self._healing:
            return
        self._healing.add(name)
        try:
            addr = getattr(self.transport, "_peers", {}).get(name)
            call_addr = getattr(self.transport, "call_addr", None)
            if addr is None or call_addr is None:
                return
            info = call_addr(addr, "cluster_info")
            addrs = dict(info["addrs"])
            addrs[info["name"]] = addr
            addrs.update(self.transport.addr_book())
            union = sorted(set(self.members) | set(info["members"]))
            for m, a in addrs.items():
                if m != self.name:
                    self.transport.register_peer(m, *a)
            self._propagate_union(union, addrs, sync_routes=False)
            n = self.anti_entropy_sync(name)
            self._count("heal.rejoins")
            with self._lock:
                self._ae_info["repairs"] += n
                self._ae_info["last_sweep_ts"] = time.time()
                self._ae_info["last_repairs"] = n
                self._ae_info["last_peer"] = name
            log.warning("cluster auto-heal: rejoined %s "
                        "(%d entries repaired)", name, n)
        except ConnectionError as e:
            log.warning("cluster auto-heal with %s failed: %s",
                        name, e)
        finally:
            # FAILBACK (replication.py): a healed peer we promoted
            # for gets its adopted state handed back — even when the
            # anti-entropy half of the rejoin failed transiently
            # (the sweep below retries it periodically regardless)
            if self.replication is not None:
                try:
                    self.replication.maybe_failback(name)
                except Exception:
                    log.exception("failback scheduling for %s "
                                  "failed", name)
            self._healing.discard(name)

    def _ae_sweep_once(self) -> None:
        """One background anti-entropy round: sync with ONE live
        peer (round-robin) — N nodes sweeping all-to-all every
        interval would be O(N²) traffic for no extra convergence.
        Also the failback retry tick: a promoted replica whose
        primary is back and healthy hands its state back even if
        every event-driven trigger was lost."""
        if self.replication is not None:
            try:
                self.replication.retry_failbacks()
            except Exception:
                log.exception("failback retry sweep failed")
        peers = sorted(m for m in list(self.members)
                       if m != self.name
                       and self.transport.peer_state(m) == "ok")
        if not peers:
            return
        self._ae_rr = getattr(self, "_ae_rr", -1) + 1
        peer = peers[self._ae_rr % len(peers)]
        try:
            n = self.anti_entropy_sync(peer)
        except ConnectionError as e:
            log.debug("anti-entropy with %s failed: %s", peer, e)
            return
        self._count("ae.sweeps")
        with self._lock:
            self._ae_info["sweeps"] += 1
            self._ae_info["repairs"] += n
            self._ae_info["last_sweep_ts"] = time.time()
            self._ae_info["last_repairs"] = n
            self._ae_info["last_peer"] = peer

    #: planes where each entry has an authoritative owner node
    _OWNER_PLANES = ("routes", "registry", "weights")

    @any_thread
    def anti_entropy_sync(self, peer: str) -> int:
        """Reconcile all five replicated planes with ``peer``; returns
        the number of entries repaired (pushed + pulled). One digest
        round-trip when everything already matches."""
        tr = self.transport
        mine = {p: self._plane_digest(p, self.name)
                for p in self._OWNER_PLANES}
        merged = {"bans": self._plane_digest("bans", None),
                  "retained": self._plane_digest("retained", None)}
        reply = tr.call(peer, "ae_digests", self.name, mine, merged)
        repairs = 0
        # push: planes where the peer's replica of OUR entries drifted
        for plane in reply.get("want", ()):
            entries = self._plane_entries(plane, self.name)
            n = tr.call(peer, "ae_apply", self.name, plane, entries)
            repairs += int(n or 0)
        # pull: planes where our replica of the PEER's entries drifted
        for plane, dg in reply.get("mine", {}).items():
            if dg != self._plane_digest(plane, peer):
                entries = tr.call(peer, "ae_entries", plane)
                repairs += self._ae_reconcile(plane, peer, entries)
        pm = reply.get("merged", {})
        if pm.get("bans") != merged["bans"]:
            repairs += self._ae_reconcile(
                "bans", peer, tr.call(peer, "ae_entries", "bans"))
            n = tr.call(peer, "ae_apply", self.name, "bans",
                        self._plane_entries("bans", None))
            repairs += int(n or 0)
        if pm.get("retained") != merged["retained"]:
            repairs += self._retained_sync(peer)
        if repairs:
            self._count("ae.repairs", repairs)
        return repairs

    @staticmethod
    def _digest(entries) -> str:
        """Stable digest over a canonically ordered entry list."""
        h = hashlib.sha1()
        for e in entries:
            h.update(repr(e).encode())
            h.update(b"\x00")
        return h.hexdigest()

    def plane_digests(self) -> Dict[str, str]:
        """Whole-table digest per replicated plane — equal digests
        across members == converged cluster (the chaos matrix's and
        the partition bench's convergence predicate)."""
        return {"routes": self._plane_digest("routes", None),
                "registry": self._plane_digest("registry", None),
                "weights": self._plane_digest("weights", None),
                "bans": self._plane_digest("bans", None),
                "retained": self._plane_digest("retained", None)}

    def _route_entries(self, owner: Optional[str]) -> list:
        out = []
        for flt in self.node.router.topics():
            for r in self.node.router.lookup_routes(flt):
                if owner is None or self._owned(r.dest, owner):
                    out.append((flt, r.dest))
        out.sort(key=repr)
        return out

    def _plane_entries(self, plane: str, owner: Optional[str]):
        """Canonical transferable entry list for one plane. ``owner``
        scopes the owner-authoritative planes; None = whole table
        (merge planes + digest oracles)."""
        if plane == "routes":
            return self._route_entries(owner)
        if plane == "registry":
            with self._lock:
                if owner is None:
                    return sorted(self._registry.items())
                return sorted(c for c, n in self._registry.items()
                              if n == owner)
        if plane == "weights":
            local = {(g, f): len(m) for (g, f), m in
                     self.node.broker.shared._subs.items() if m}
            if owner == self.name:
                return sorted((g, f, c) for (g, f), c in local.items())
            with self._lock:
                if owner is not None:
                    return sorted((g, f, c) for (g, f, n), c in
                                  self._shared_weights.items()
                                  if n == owner)
                out = [(g, f, self.name, c)
                       for (g, f), c in local.items()]
                out += [(g, f, n, c) for (g, f, n), c in
                        self._shared_weights.items() if n != self.name]
                return sorted(out)
        if plane == "bans":
            banned = self.node.broker.banned
            if banned is None:
                return []
            banned.expire()
            return sorted(
                (r.who[0], r.who[1], r.by, r.reason, r.until)
                for r in banned.info())
        raise ValueError(f"bad anti-entropy plane: {plane}")

    def _retained_idx(self) -> Dict[str, tuple]:
        """topic -> (timestamp, payload hash): the retained plane's
        per-entry diff index (full messages only cross the wire for
        topics whose index entry differs)."""
        ret = self._retainer()
        if ret is None:
            return {}
        return {t: (float(m.timestamp),
                    hashlib.sha1(bytes(m.payload)).hexdigest())
                for t, m in ret.entries()}

    def _plane_digest(self, plane: str, owner: Optional[str]) -> str:
        if plane == "retained":
            ret = self._retainer()
            tombs = sorted(ret.tombstones()) if ret is not None else []
            return self._digest(
                sorted(self._retained_idx().items()) + tombs)
        return self._digest(self._plane_entries(plane, owner))

    def _ae_reconcile(self, plane: str, owner: str, entries) -> int:
        """Apply a peer's authoritative entry set for one plane;
        returns the number of local entries changed. Owner planes
        REPLACE our replica of ``owner``'s entries (repairing stale
        survivors of missed deletes); bans MERGE."""
        if owner == self.name:
            return 0  # nobody rewrites our view of our own entries
        repairs = 0
        if plane == "routes":
            want = {(flt, tuple(d) if isinstance(d, (list, tuple))
                     else d) for flt, d in entries}
            cur = {(flt, tuple(d) if isinstance(d, (list, tuple))
                    else d) for flt, d in self._route_entries(owner)}
            for flt, dest in want - cur:
                self._apply_route("add", flt, dest)
                repairs += 1
            for flt, dest in cur - want:
                self._apply_route("del", flt, dest)
                repairs += 1
            return repairs
        if plane == "registry":
            want = set(entries)
            with self._lock:
                stale = [c for c, n in self._registry.items()
                         if n == owner and c not in want]
                for c in stale:
                    del self._registry[c]
                    repairs += 1
                for c in want:
                    if self._registry.get(c) != owner:
                        self._registry[c] = owner
                        repairs += 1
            return repairs
        if plane == "weights":
            want = {(g, f): int(c) for g, f, c in entries}
            with self._lock:
                stale = [k for k in self._shared_weights
                         if k[2] == owner and (k[0], k[1]) not in want]
                for k in stale:
                    del self._shared_weights[k]
                    repairs += 1
                for (g, f), c in want.items():
                    if c > 0 and \
                            self._shared_weights.get((g, f, owner)) != c:
                        self._shared_weights[(g, f, owner)] = c
                        repairs += 1
            return repairs
        if plane == "bans":
            banned = self.node.broker.banned
            if banned is None:
                return 0
            for kind, value, by, reason, until in entries:
                cur = banned.look_up(kind, value)
                banned.apply(kind, value, by, reason, until,
                             overwrite=False)
                if banned.look_up(kind, value) is not cur:
                    repairs += 1
            return repairs
        if plane == "retained":
            ret = self._retainer()
            if ret is None or not isinstance(entries, dict):
                return 0
            for topic, ts in entries.get("tombs", ()):
                ret.apply_tombstone(topic, float(ts))
            for topic, msg in entries.get("entries", ()):
                ret.apply_remote(topic, msg, sync=True)
                repairs += 1
            return repairs
        raise ValueError(f"bad anti-entropy plane: {plane}")

    def _retained_sync(self, peer: str) -> int:
        """Entry-level retained reconciliation: exchange (timestamp,
        payload-hash) indexes, transfer full messages only for
        differing topics, merge tombstones both ways — LWW on both
        sides makes over-transfer harmless and order irrelevant."""
        ret = self._retainer()
        if ret is None:
            return 0
        tr = self.transport
        remote = tr.call(peer, "ae_retained_idx")
        if not isinstance(remote, dict):
            return 0  # peer has no retainer loaded
        ridx = {t: (float(ts), ph) for t, ts, ph in remote["idx"]}
        mine = self._retained_idx()
        repairs = 0
        for t, ts in remote.get("tombs", ()):
            ret.apply_tombstone(t, float(ts))
        pull = [t for t, v in ridx.items() if mine.get(t) != v]
        if pull:
            for topic, msg in tr.call(peer, "ae_fetch_retained", pull):
                if msg is not None:
                    ret.apply_remote(topic, msg, sync=True)
                    repairs += 1
        push = [t for t, v in mine.items() if ridx.get(t) != v]
        entries = [(t, ret._store[t]) for t in push
                   if t in ret._store]
        tombs = ret.tombstones()
        if entries or tombs:
            n = tr.call(peer, "ae_apply", self.name, "retained",
                        {"entries": entries, "tombs": tombs})
            repairs += int(n or 0)
        return repairs

    @owner_loop
    def handle_rpc(self, op: str, *args):
        if op == "route_add":
            return self._apply_route("add", args[0], args[1])
        if op == "route_del":
            return self._apply_route("del", args[0], args[1])
        if op == "forward":
            flt, msg = args
            b = self.node.broker
            b.metrics.inc("messages.received")
            # dispatch by the already-matched filter (no re-match,
            # no shared dispatch — shared goes via forward_shared)
            n = b.dispatch(flt, msg)
            if not n and getattr(msg, "qos", 0) > 0 \
                    and not msg.headers.get("fwd_bounce"):
                # the session this forward targeted MOVED between
                # the cast and its delivery (drain hand-off,
                # takeover, a parked cast replayed after a heal):
                # re-route once to the filter's CURRENT owners —
                # scoped to this filter's routes, one bounce max, so
                # a QoS>0 delivery survives a custody move instead
                # of dying on the stale owner (docs/OPERATIONS.md)
                msg.headers["fwd_bounce"] = True
                for r in self.node.router.lookup_routes(flt):
                    if isinstance(r.dest, tuple):
                        continue  # shared groups pick per-dispatch
                    if r.dest != self.name:
                        self._forward(r.dest, flt, msg)
            return n
        if op == "forward_shared":
            group, flt, msg = args
            return self.node.broker.shared.dispatch(group, flt, msg)
        if op == "client_up":
            cid, name = args
            with self._lock:
                self._registry[cid] = name
            # custody confirmed elsewhere: a parked takeover copy
            # (reply-loss insurance) is no longer needed
            if name != self.name:
                self._takeover_parked.pop(cid, None)
            return None
        if op == "client_down":
            cid, name = args
            with self._lock:
                if self._registry.get(cid) == name:
                    self._registry.pop(cid, None)
            return None
        if op == "registry_sync":
            # join-time batched registry push (owner-authoritative,
            # idempotent — the per-entry analogue of client_up)
            owner, cids = args
            with self._lock:
                for cid in cids:
                    self._registry[cid] = owner
            return None
        if op == "discard_client":
            # the REQUESTING node holds the cluster lock for this
            # clientid — re-acquiring here would deadlock on it
            return self.node.cm.discard_session(args[0],
                                                cluster_lock=False)
        if op == "takeover_client":
            return self._local_takeover(
                args[0], args[1] if len(args) > 1 else None)
        if op == "lock_acquire":
            return self.locker.grant(args[0], args[1])
        if op == "lock_release":
            return self.locker.release_local(args[0], args[1])
        if op == "set_members":
            return self._set_members(args[0])
        if op == "ping":
            return "pong"
        if op == "retain_set":
            ret = self._retainer()
            if ret is not None:
                ret.apply_remote(args[0], args[1],
                                 ts=args[2] if len(args) > 2 else None)
            return None
        if op == "retain_sync":
            ret = self._retainer()
            if ret is not None:
                for topic, msg in args[0]:
                    ret.apply_remote(topic, msg, sync=True)
                for topic, ts in (args[1] if len(args) > 1 else []):
                    ret.apply_tombstone(topic, ts)
            return None
        if op == "ban_add":
            kind, value, by, reason, until, overwrite = args
            banned = self.node.broker.banned
            if banned is not None:
                banned.apply(kind, value, by, reason, until,
                             overwrite=overwrite)
            return None
        if op == "ban_del":
            # remote apply MUST bypass the replicated wrapper — going
            # through banned.delete would re-broadcast and ping-pong
            # between the members forever
            if getattr(self, "_orig_ban_delete", None) is not None:
                self._orig_ban_delete(*args)
            return None
        if op == "shared_weight":
            group, flt, node, count = args
            with self._lock:
                if count > 0:
                    self._shared_weights[(group, flt, node)] = count
                else:
                    self._shared_weights.pop((group, flt, node), None)
            return None
        if op == "cluster_info":
            return {"name": self.name, "members": list(self.members),
                    "addrs": self.transport.addr_book()}
        if op == "set_members_net":
            members, addrs = args
            for m, a in addrs.items():
                if m != self.name:
                    self.transport.register_peer(m, *a)
            return self._set_members(members)
        if op == "push_routes":
            return self._push_owned_routes()
        if op == "nodedown":
            return self.handle_nodedown(args[0])
        if op == "leaving":
            # a DELIBERATE departure (vs a detector-observed death):
            # same purge, but the failure detector must also stop
            # probing the leaver for reappearance
            self.transport.set_departed(args[0])
            return self.handle_nodedown(args[0])
        if op == "ae_digests":
            from_name, owned, merged = args
            want = [p for p, dg in owned.items()
                    if p in self._OWNER_PLANES
                    and dg != self._plane_digest(p, from_name)]
            return {
                "want": want,
                "mine": {p: self._plane_digest(p, self.name)
                         for p in self._OWNER_PLANES},
                "merged": {
                    "bans": self._plane_digest("bans", None),
                    "retained": self._plane_digest("retained", None)},
            }
        if op == "ae_entries":
            plane = args[0]
            return self._plane_entries(
                plane, self.name if plane in self._OWNER_PLANES
                else None)
        if op == "ae_apply":
            from_name, plane, entries = args
            return self._ae_reconcile(plane, from_name, entries)
        if op == "ae_retained_idx":
            ret = self._retainer()
            if ret is None:
                return None
            return {"idx": [(t, ts, ph) for t, (ts, ph) in
                            self._retained_idx().items()],
                    "tombs": ret.tombstones()}
        if op == "ae_fetch_retained":
            ret = self._retainer()
            if ret is None:
                return []
            return [(t, ret._store.get(t)) for t in args[0]
                    if t in ret._store]
        if op == "repl_hello":
            # replicated durability (replication.py): arm/resync the
            # warm standby replica for the calling primary
            return self.replication.handle_hello(args[0], args[1],
                                                 int(args[2]))
        if op == "repl_ship":
            return self.replication.handle_ship(args[0], int(args[1]),
                                                args[2])
        if op == "repl_bye":
            return self.replication.handle_bye(args[0], bool(args[1]))
        if op == "repl_replica_info":
            # promotion arbitration (replication.py): a co-standby
            # compares warm-replica offsets before promoting
            return self.replication.handle_replica_info(args[0])
        if op == "repl_failback":
            # FAILBACK: the promoted standby hands the adopted state
            # back to this (restarted) primary — also the receive
            # side of a DRAIN custody hand-off (drain.py): same
            # chunked full-state adoption, same journaling
            return self.replication.handle_failback(args[0], args[1])
        if op == "overload_level":
            # drain wave pacing (drain.py): the draining peer adapts
            # its disconnect budget to THIS node's overload level
            ov = getattr(self.node, "overload", None)
            return int(ov.level) if ov is not None else 0
        if op == "drain_digest":
            # drain custody verification: digest of the named
            # sessions as THIS node now holds them (replication.py)
            from emqx_tpu.replication import sessions_digest
            return sessions_digest(self.node, args[0])
        raise ValueError(f"bad rpc op: {op}")
