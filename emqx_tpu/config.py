"""Config-file layer: TOML → zones, listeners, node settings.

The reference boots from a 2,257-line ``etc/emqx.conf`` rendered by
cuttlefish into app env, then snapshotted into zones for lock-free
per-connection reads (src/emqx_zone.erl:89-95; zone sections at
etc/emqx.conf:698-907; listeners carry their zone,
src/emqx_listeners.erl:43-76). This module is that pipeline with
TOML (stdlib ``tomllib``) as the schema language:

    [node]
    name = "emqx_tpu@127.0.0.1"
    sys_interval = 60.0
    cookie = "secret"          # cluster transport cookie
    cluster_port = 4370        # 0 = ephemeral, omit = no transport

    [zones.default]
    max_packet_size = 1048576
    allow_anonymous = true

    [zones.external]
    idle_timeout = 10.0
    ratelimit_bytes_in = [102400, 204800]   # (rate/sec, burst)

    [[listeners]]
    type = "tcp"               # tcp | ws | ssl | wss
    port = 1883
    zone = "external"

    [[listeners]]
    type = "ssl"
    port = 8883
    certfile = "etc/certs/cert.pem"
    keyfile = "etc/certs/key.pem"
    cacertfile = "etc/certs/cacert.pem"
    verify = "verify_peer"
    fail_if_no_peer_cert = true

Unknown zone keys are rejected (a typo must not silently fall back
to a default — the cuttlefish schema gives the reference the same
property).
"""

from __future__ import annotations

import dataclasses

try:
    import tomllib
except ModuleNotFoundError:  # py<3.11: tomllib IS tomli, vendored
    import tomli as tomllib
from typing import Any, Dict, List, Optional

from emqx_tpu.zone import Zone, set_zone

#: Zone fields that arrive from TOML as 2-lists but are tuples in the
#: dataclass ((rate, burst) pairs; force_gc_policy is (count, bytes))
_TUPLE_FIELDS = {"ratelimit_msg_in", "ratelimit_bytes_in",
                 "quota_conn_messages", "force_gc_policy"}

_LISTENER_TYPES = {"tcp", "ws", "ssl", "wss"}
_TLS_KEYS = {"certfile", "keyfile", "cacertfile", "verify",
             "fail_if_no_peer_cert", "ciphers", "tls_version"}


class ConfigError(ValueError):
    pass


@dataclasses.dataclass
class ListenerConfig:
    type: str
    port: int
    host: str = "127.0.0.1"
    zone: str = "default"
    name: Optional[str] = None
    path: str = "/mqtt"          # ws/wss
    max_connections: int = 1024000
    tls: Optional[dict] = None   # ssl/wss: TlsOptions kwargs
    # PROXY protocol v1/v2 (fronting LB carries the real client
    # address; reference listener.tcp.*.proxy_protocol)
    proxy_protocol: bool = False
    proxy_protocol_timeout: float = 3.0
    # esockd-style accept controls (reference listener.*.access.N,
    # listener.*.max_conn_rate) — tcp/ssl listeners
    access: Optional[List[str]] = None
    max_conn_rate: float = 0.0
    # ssl listeners: CONNECT username from the client cert (cn | dn)
    peer_cert_as_username: Optional[str] = None


@dataclasses.dataclass
class NodeConfig:
    name: str = "emqx_tpu@127.0.0.1"
    sys_interval: float = 60.0
    cookie: Optional[str] = None
    cluster_port: Optional[int] = None
    # multi-loop front door (docs/DISPATCH.md "Multi-loop front
    # door"): shard accepted connections over this many event loops
    # inside the node. 1 = today's single-loop behavior, exactly.
    loops: int = 1
    # MQTT frame parser engine: "py" (pure-Python Parser) or "native"
    # (C++ incremental parser, falls back to "py" when the shared
    # library lacks the symbols). Boot-only.
    frame: str = "py"
    zones: Dict[str, Zone] = dataclasses.field(default_factory=dict)
    listeners: List[ListenerConfig] = dataclasses.field(
        default_factory=list)
    load_default_modules: bool = False
    # [modules.<name>] sections: module env dicts by name (the
    # reference's data/loaded_modules + per-module cuttlefish config)
    modules: Dict[str, Dict[str, Any]] = dataclasses.field(
        default_factory=dict)
    # directory of the config file: relative paths inside it (module
    # files, certs) resolve against this, not the process cwd
    base_dir: Optional[str] = None
    # [matcher] section: device matcher / publish-path knobs
    # (emqx_tpu.router.MatcherConfig — match-cache sizing and off
    # switch, kernel bounds, host/device threshold). None = defaults.
    matcher: Optional[Any] = None
    # [telemetry] section: publish-path stage histograms + slow-
    # publish log (emqx_tpu.telemetry.TelemetryConfig). None =
    # defaults (enabled).
    telemetry: Optional[Any] = None
    # [tracing] section: sampled end-to-end message spans, slow-
    # subscriber ranking, per-loop profiler
    # (emqx_tpu.tracing.TracingConfig, docs/OBSERVABILITY.md
    # "Tracing"). None = defaults (sampling off).
    tracing: Optional[Any] = None
    # [dispatch] section: publish delivery-tail knobs
    # (emqx_tpu.broker.DispatchConfig — batch dispatch planner and
    # egress pre-serialization on/off, docs/DISPATCH.md). None =
    # defaults (planner + preserialize on).
    dispatch: Optional[Any] = None
    # [overload] section: overload monitor levels/shedding + the
    # device-path circuit breaker (emqx_tpu.overload.OverloadConfig,
    # docs/ROBUSTNESS.md). None = defaults (enabled).
    overload: Optional[Any] = None
    # [faults] section: deterministic fault injection
    # (emqx_tpu.faults.FaultsConfig, docs/ROBUSTNESS.md). None = the
    # registry untouched (disabled).
    faults: Optional[Any] = None
    # [durability] section: write-ahead journal + atomic checkpoints
    # + crash recovery (emqx_tpu.durability.DurabilityConfig,
    # docs/DURABILITY.md). None = disabled (today's in-memory-only
    # behavior, byte-for-byte).
    durability: Optional[Any] = None
    # [cluster] section: heartbeat failure detector + auto-heal /
    # anti-entropy knobs (emqx_tpu.cluster.ClusterConfig,
    # docs/CLUSTER.md). None = the legacy EOF-only failure story,
    # byte-for-byte. Only takes effect on a node with a cluster
    # transport ([node] cluster_port).
    cluster: Optional[Any] = None
    # [drain] section: graceful-drain wave pacing, default target,
    # SIGTERM drain mode (emqx_tpu.drain.DrainConfig,
    # docs/OPERATIONS.md). None = defaults (drain available via ctl,
    # passive until started).
    drain: Optional[Any] = None


#: zone fields with a closed value set — a typo must be a startup
#: ConfigError, not a silently-permissive default (a misspelled
#: acl_deny_action would disable a security knob without a trace)
_ENUM_FIELDS = {
    "acl_nomatch": ("allow", "deny"),
    "acl_deny_action": ("ignore", "disconnect"),
}


def _build_zone(name: str, raw: Dict[str, Any]) -> Zone:
    known = {f.name for f in dataclasses.fields(Zone)}
    kwargs: Dict[str, Any] = {}
    for key, val in raw.items():
        if key not in known:
            raise ConfigError(f"unknown zone setting: zones.{name}.{key}")
        if key in _TUPLE_FIELDS and isinstance(val, list):
            val = tuple(val)
        if key in _ENUM_FIELDS and val not in _ENUM_FIELDS[key]:
            raise ConfigError(
                f"zones.{name}.{key} must be one of "
                f"{_ENUM_FIELDS[key]}, got {val!r}")
        kwargs[key] = val
    return Zone(name=name, **kwargs)


def _build_matcher(raw: Dict[str, Any]):
    """``[matcher]`` table → :class:`~emqx_tpu.router.MatcherConfig`.
    Unknown keys are startup errors (same closed-schema rule as
    zones: a typo'd ``match_cache = false`` must not silently leave
    the cache on); ``mesh`` is runtime-only and not configurable
    from a file."""
    import dataclasses as _dc

    from emqx_tpu.router import MatcherConfig

    known = {f.name for f in _dc.fields(MatcherConfig)} - {"mesh"}
    kwargs: Dict[str, Any] = {}
    for key, val in raw.items():
        if key not in known:
            raise ConfigError(f"unknown matcher setting: matcher.{key}")
        want = MatcherConfig.__dataclass_fields__[key].type
        if want == "bool" and not isinstance(val, bool):
            raise ConfigError(f"matcher.{key} must be a boolean")
        if want == "int" and (isinstance(val, bool)
                              or not isinstance(val, int)):
            raise ConfigError(f"matcher.{key} must be an integer")
        kwargs[key] = val
    p = kwargs.get("cache_partitions")
    if p is not None and (p < 1 or (p & (p - 1))):
        # Router would reject this too (ValueError at node build);
        # catching it here makes it a startup ConfigError with the
        # file location semantics of every other [matcher] typo
        raise ConfigError(
            f"matcher.cache_partitions must be a power of two >= 1, "
            f"got {p}")
    return MatcherConfig(**kwargs)


def _build_telemetry(raw: Dict[str, Any]):
    """``[telemetry]`` table → :class:`~emqx_tpu.telemetry
    .TelemetryConfig`. Closed schema like zones/matcher: a typo'd
    ``enabled = false`` silently leaving span recording on (or off)
    is exactly the drift this rule exists to catch."""
    import dataclasses as _dc

    from emqx_tpu.telemetry import TelemetryConfig

    known = {f.name for f in _dc.fields(TelemetryConfig)}
    kwargs: Dict[str, Any] = {}
    for key, val in raw.items():
        if key not in known:
            raise ConfigError(f"unknown telemetry setting: "
                              f"telemetry.{key}")
        want = TelemetryConfig.__dataclass_fields__[key].type
        if want == "bool" and not isinstance(val, bool):
            raise ConfigError(f"telemetry.{key} must be a boolean")
        if want == "int" and (isinstance(val, bool)
                              or not isinstance(val, int)):
            raise ConfigError(f"telemetry.{key} must be an integer")
        if want == "float":
            if isinstance(val, bool) or not isinstance(val, (int, float)):
                raise ConfigError(f"telemetry.{key} must be a number")
            val = float(val)
        kwargs[key] = val
    if kwargs.get("slow_threshold_ms", 1.0) < 0:
        raise ConfigError("telemetry.slow_threshold_ms must be >= 0")
    if kwargs.get("ring_size", 1) <= 0:
        raise ConfigError("telemetry.ring_size must be > 0")
    return TelemetryConfig(**kwargs)


def _build_tracing(raw: Dict[str, Any]):
    """``[tracing]`` table → :class:`~emqx_tpu.tracing
    .TracingConfig`. Closed schema like zones/matcher/telemetry: a
    typo'd ``sample_rate`` silently tracing nothing (or everything)
    is the drift this rule catches."""
    import dataclasses as _dc

    from emqx_tpu.tracing import TracingConfig

    known = {f.name for f in _dc.fields(TracingConfig)}
    kwargs: Dict[str, Any] = {}
    for key, val in raw.items():
        if key not in known:
            raise ConfigError(f"unknown tracing setting: "
                              f"tracing.{key}")
        want = TracingConfig.__dataclass_fields__[key].type
        if want == "bool" and not isinstance(val, bool):
            raise ConfigError(f"tracing.{key} must be a boolean")
        if want == "int" and (isinstance(val, bool)
                              or not isinstance(val, int)):
            raise ConfigError(f"tracing.{key} must be an integer")
        if want == "float":
            if isinstance(val, bool) or not isinstance(val, (int, float)):
                raise ConfigError(f"tracing.{key} must be a number")
            val = float(val)
        kwargs[key] = val
    rate = kwargs.get("sample_rate", 0.0)
    if not 0.0 <= rate <= 1.0:
        raise ConfigError("tracing.sample_rate must be in [0, 1]")
    if kwargs.get("ring_size", 1) <= 0:
        raise ConfigError("tracing.ring_size must be > 0")
    if kwargs.get("export_keep", 1) <= 0:
        raise ConfigError("tracing.export_keep must be > 0")
    if kwargs.get("slow_subs_top", 1) <= 0:
        raise ConfigError("tracing.slow_subs_top must be > 0")
    if kwargs.get("slow_subs_threshold_ms", 0.0) < 0:
        raise ConfigError(
            "tracing.slow_subs_threshold_ms must be >= 0")
    if kwargs.get("slow_subs_expiry_s", 1.0) <= 0:
        raise ConfigError("tracing.slow_subs_expiry_s must be > 0")
    if kwargs.get("slow_subs_alarm_ticks", 1) < 1:
        raise ConfigError(
            "tracing.slow_subs_alarm_ticks must be >= 1")
    if kwargs.get("profile_interval_ms", 1.0) <= 0:
        raise ConfigError("tracing.profile_interval_ms must be > 0")
    return TracingConfig(**kwargs)


def _build_dispatch(raw: Dict[str, Any]):
    """``[dispatch]`` table → :class:`~emqx_tpu.broker
    .DispatchConfig`. Closed schema like zones/matcher/telemetry: a
    typo'd ``planner = false`` silently leaving the planner on is the
    drift this rule catches."""
    import dataclasses as _dc

    from emqx_tpu.broker import DispatchConfig

    known = {f.name for f in _dc.fields(DispatchConfig)}
    kwargs: Dict[str, Any] = {}
    for key, val in raw.items():
        if key not in known:
            raise ConfigError(f"unknown dispatch setting: "
                              f"dispatch.{key}")
        want = DispatchConfig.__dataclass_fields__[key].type
        if want == "bool" and not isinstance(val, bool):
            raise ConfigError(f"dispatch.{key} must be a boolean")
        kwargs[key] = val
    return DispatchConfig(**kwargs)


def _build_overload(raw: Dict[str, Any]):
    """``[overload]`` table → :class:`~emqx_tpu.overload
    .OverloadConfig`. Closed schema like zones/matcher: a typo'd
    ``enabled = false`` silently leaving shedding armed (or off) is
    the drift this rule catches."""
    import dataclasses as _dc

    from emqx_tpu.overload import OverloadConfig

    known = {f.name for f in _dc.fields(OverloadConfig)}
    kwargs: Dict[str, Any] = {}
    for key, val in raw.items():
        if key not in known:
            raise ConfigError(f"unknown overload setting: "
                              f"overload.{key}")
        want = OverloadConfig.__dataclass_fields__[key].type
        if want == "bool" and not isinstance(val, bool):
            raise ConfigError(f"overload.{key} must be a boolean")
        if want == "int" and (isinstance(val, bool)
                              or not isinstance(val, int)):
            raise ConfigError(f"overload.{key} must be an integer")
        if want == "float":
            if isinstance(val, bool) or not isinstance(val, (int, float)):
                raise ConfigError(f"overload.{key} must be a number")
            val = float(val)
        kwargs[key] = val
    try:
        return OverloadConfig(**kwargs)
    except ValueError as e:
        # threshold-ordering violations become startup errors with
        # file-location semantics, like every other section typo
        raise ConfigError(str(e)) from e


def _build_faults(raw: Dict[str, Any]):
    """``[faults]`` table → :class:`~emqx_tpu.faults.FaultsConfig`.
    Arm specs are validated against the point catalog here — a typo'd
    chaos config must fail the boot, not silently test nothing."""
    from emqx_tpu.faults import FaultsConfig, parse_arm

    known = {"enabled", "seed", "arm"}
    for key in raw:
        if key not in known:
            raise ConfigError(f"unknown faults setting: faults.{key}")
    if not isinstance(raw.get("enabled", False), bool):
        raise ConfigError("faults.enabled must be a boolean")
    seed = raw.get("seed", 0)
    if isinstance(seed, bool) or not isinstance(seed, int):
        raise ConfigError("faults.seed must be an integer")
    arm = raw.get("arm", [])
    if not isinstance(arm, list) \
            or not all(isinstance(a, str) for a in arm):
        raise ConfigError("faults.arm must be a list of spec strings")
    for spec in arm:
        try:
            parse_arm(spec)
        except ValueError as e:
            raise ConfigError(f"faults.arm: {e}") from e
    return FaultsConfig(enabled=raw.get("enabled", False), seed=seed,
                        arm=list(arm))


def _build_durability(raw: Dict[str, Any]):
    """``[durability]`` table → :class:`~emqx_tpu.durability
    .DurabilityConfig`. Closed schema like zones/matcher: a typo'd
    ``enabled = true`` silently leaving the broker volatile is the
    exact drift this rule exists to catch."""
    import dataclasses as _dc

    from emqx_tpu.durability import DurabilityConfig

    known = {f.name for f in _dc.fields(DurabilityConfig)}
    kwargs: Dict[str, Any] = {}
    for key, val in raw.items():
        if key not in known:
            raise ConfigError(f"unknown durability setting: "
                              f"durability.{key}")
        want = DurabilityConfig.__dataclass_fields__[key].type
        if want == "bool" and not isinstance(val, bool):
            raise ConfigError(f"durability.{key} must be a boolean")
        if want == "int" and (isinstance(val, bool)
                              or not isinstance(val, int)):
            raise ConfigError(f"durability.{key} must be an integer")
        if want == "float":
            if isinstance(val, bool) or not isinstance(val, (int, float)):
                raise ConfigError(f"durability.{key} must be a number")
            val = float(val)
        if want == "str" and not isinstance(val, str):
            raise ConfigError(f"durability.{key} must be a string")
        kwargs[key] = val
    try:
        return DurabilityConfig(**kwargs)
    except ValueError as e:
        raise ConfigError(str(e)) from e


def _build_cluster(raw: Dict[str, Any]):
    """``[cluster]`` table → :class:`~emqx_tpu.cluster
    .ClusterConfig`. Closed schema like zones/matcher: a typo'd
    ``detector = false`` silently leaving the failure detector armed
    (or off) is the drift this rule catches; knob-ordering violations
    (down_after < suspect_after) become startup errors."""
    import dataclasses as _dc

    from emqx_tpu.cluster import ClusterConfig

    known = {f.name for f in _dc.fields(ClusterConfig)}
    kwargs: Dict[str, Any] = {}
    for key, val in raw.items():
        if key not in known:
            raise ConfigError(f"unknown cluster setting: "
                              f"cluster.{key}")
        want = ClusterConfig.__dataclass_fields__[key].type
        if want == "bool" and not isinstance(val, bool):
            raise ConfigError(f"cluster.{key} must be a boolean")
        if want == "int" and (isinstance(val, bool)
                              or not isinstance(val, int)):
            raise ConfigError(f"cluster.{key} must be an integer")
        if want == "float":
            if isinstance(val, bool) or not isinstance(val, (int, float)):
                raise ConfigError(f"cluster.{key} must be a number")
            val = float(val)
        kwargs[key] = val
    try:
        return ClusterConfig(**kwargs)
    except ValueError as e:
        raise ConfigError(str(e)) from e


def _build_drain(raw: Dict[str, Any]):
    """``[drain]`` table → :class:`~emqx_tpu.drain.DrainConfig`.
    Closed schema like zones/matcher: a typo'd ``on_sigterm = true``
    silently leaving SIGTERM a hard stop is the drift this rule
    catches."""
    import dataclasses as _dc

    from emqx_tpu.drain import DrainConfig

    known = {f.name for f in _dc.fields(DrainConfig)}
    kwargs: Dict[str, Any] = {}
    for key, val in raw.items():
        if key not in known:
            raise ConfigError(f"unknown drain setting: drain.{key}")
        want = DrainConfig.__dataclass_fields__[key].type
        if want == "bool" and not isinstance(val, bool):
            raise ConfigError(f"drain.{key} must be a boolean")
        if want == "int" and (isinstance(val, bool)
                              or not isinstance(val, int)):
            raise ConfigError(f"drain.{key} must be an integer")
        if want == "float":
            if isinstance(val, bool) or not isinstance(val, (int, float)):
                raise ConfigError(f"drain.{key} must be a number")
            val = float(val)
        if want == "str" and not isinstance(val, str):
            raise ConfigError(f"drain.{key} must be a string")
        kwargs[key] = val
    try:
        return DrainConfig(**kwargs)
    except ValueError as e:
        raise ConfigError(str(e)) from e


def _build_listener(i: int, raw: Dict[str, Any]) -> ListenerConfig:
    raw = dict(raw)
    ltype = raw.pop("type", None)
    if ltype not in _LISTENER_TYPES:
        raise ConfigError(
            f"listeners[{i}].type must be one of {sorted(_LISTENER_TYPES)},"
            f" got {ltype!r}")
    if "port" not in raw:
        raise ConfigError(f"listeners[{i}] needs a port")
    tls = {k: raw.pop(k) for k in list(raw) if k in _TLS_KEYS}
    if ltype in ("ssl", "wss") and "certfile" not in tls:
        raise ConfigError(f"listeners[{i}] ({ltype}) needs a certfile")
    if ltype in ("tcp", "ws") and tls:
        # an operator who sets certfile on a tcp listener meant ssl;
        # serving plaintext on a port believed TLS-terminated is the
        # worst possible silent fallback
        raise ConfigError(
            f"listeners[{i}] ({ltype}) does not take TLS settings "
            f"({sorted(tls)}); did you mean type = \"ssl\"/\"wss\"?")
    known = {f.name for f in dataclasses.fields(ListenerConfig)}
    for key in raw:
        if key not in known:
            raise ConfigError(f"unknown listener setting: "
                              f"listeners[{i}].{key}")
    if float(raw.get("proxy_protocol_timeout", 3.0)) <= 0:
        # wait_for(..., 0) times out every accept instantly with only
        # a debug log — make the foot-gun a startup error instead
        raise ConfigError(
            f"listeners[{i}].proxy_protocol_timeout must be > 0")
    if raw.get("access") is not None:
        if ltype not in ("tcp", "ssl"):
            raise ConfigError(
                f"listeners[{i}]: access rules only apply to "
                f"tcp/ssl listeners")
        from emqx_tpu.connection import parse_access_rules
        try:
            parse_access_rules(raw["access"])
        except ValueError as e:
            raise ConfigError(f"listeners[{i}].access: {e}") from e
    rate = float(raw.get("max_conn_rate", 0) or 0)
    if rate < 0:
        raise ConfigError(f"listeners[{i}].max_conn_rate must be >= 0")
    if rate > 0 and ltype not in ("tcp", "ssl"):
        # ws/wss listeners don't carry the accept bucket yet — a
        # config-accepted-but-unenforced rate limit is a silent noop
        raise ConfigError(
            f"listeners[{i}]: max_conn_rate only applies to "
            f"tcp/ssl listeners")
    pcu = raw.get("peer_cert_as_username")
    if pcu is not None:
        if ltype != "ssl":
            raise ConfigError(
                f"listeners[{i}]: peer_cert_as_username needs a "
                f"client-cert-bearing ssl listener")
        if pcu not in ("cn", "dn"):
            raise ConfigError(
                f"listeners[{i}].peer_cert_as_username must be "
                f"\"cn\" or \"dn\", got {pcu!r}")
        if tls.get("verify") != "verify_peer":
            # without peer verification no client ever presents a
            # cert: every username would stay self-asserted while the
            # operator believes it is cert-backed
            raise ConfigError(
                f"listeners[{i}]: peer_cert_as_username requires "
                f"verify = \"verify_peer\"")
    if raw.get("proxy_protocol") and ltype != "tcp":
        # silently ignoring it would leave the LB's real-client
        # addresses unseen — the worst kind of security-adjacent noop
        raise ConfigError(
            f"listeners[{i}]: proxy_protocol is only supported on "
            f"type = \"tcp\" listeners")
    return ListenerConfig(type=ltype, tls=tls or None, **raw)


def load_config(path: str) -> NodeConfig:
    """Parse + validate a TOML config file into a NodeConfig."""
    import os

    with open(path, "rb") as f:
        raw = tomllib.load(f)
    cfg = parse_config(raw)
    cfg.base_dir = os.path.dirname(os.path.abspath(path))
    return cfg


def parse_config(raw: Dict[str, Any]) -> NodeConfig:
    cfg = NodeConfig()
    node = raw.get("node", {})
    for key in node:
        if key not in ("name", "sys_interval", "cookie", "cluster_port",
                       "load_default_modules", "loops", "frame"):
            raise ConfigError(f"unknown node setting: node.{key}")
    cfg.name = node.get("name", cfg.name)
    cfg.sys_interval = float(node.get("sys_interval", cfg.sys_interval))
    cfg.cookie = node.get("cookie")
    cfg.cluster_port = node.get("cluster_port")
    cfg.load_default_modules = bool(
        node.get("load_default_modules", False))
    loops = node.get("loops", 1)
    if isinstance(loops, bool) or not isinstance(loops, int) \
            or loops < 1:
        raise ConfigError(
            f"node.loops must be an integer >= 1, got {loops!r}")
    cfg.loops = loops
    frame = node.get("frame", "py")
    if frame not in ("py", "native"):
        raise ConfigError(
            f'node.frame must be "py" or "native", got {frame!r}')
    cfg.frame = frame
    mraw = raw.get("matcher")
    if mraw is not None:
        if not isinstance(mraw, dict):
            raise ConfigError("matcher must be a table")
        cfg.matcher = _build_matcher(mraw)
    traw = raw.get("telemetry")
    if traw is not None:
        if not isinstance(traw, dict):
            raise ConfigError("telemetry must be a table")
        cfg.telemetry = _build_telemetry(traw)
    trcraw = raw.get("tracing")
    if trcraw is not None:
        if not isinstance(trcraw, dict):
            raise ConfigError("tracing must be a table")
        cfg.tracing = _build_tracing(trcraw)
    draw = raw.get("dispatch")
    if draw is not None:
        if not isinstance(draw, dict):
            raise ConfigError("dispatch must be a table")
        cfg.dispatch = _build_dispatch(draw)
    oraw = raw.get("overload")
    if oraw is not None:
        if not isinstance(oraw, dict):
            raise ConfigError("overload must be a table")
        cfg.overload = _build_overload(oraw)
    fraw = raw.get("faults")
    if fraw is not None:
        if not isinstance(fraw, dict):
            raise ConfigError("faults must be a table")
        cfg.faults = _build_faults(fraw)
    duraw = raw.get("durability")
    if duraw is not None:
        if not isinstance(duraw, dict):
            raise ConfigError("durability must be a table")
        cfg.durability = _build_durability(duraw)
    craw = raw.get("cluster")
    if craw is not None:
        if not isinstance(craw, dict):
            raise ConfigError("cluster must be a table")
        cfg.cluster = _build_cluster(craw)
    drraw = raw.get("drain")
    if drraw is not None:
        if not isinstance(drraw, dict):
            raise ConfigError("drain must be a table")
        cfg.drain = _build_drain(drraw)
    for name, zraw in raw.get("zones", {}).items():
        cfg.zones[name] = _build_zone(name, zraw)
    for i, lraw in enumerate(raw.get("listeners", [])):
        lc = _build_listener(i, lraw)
        if lc.zone != "default" and lc.zone not in cfg.zones:
            # same invariant as unknown keys: a zone typo must not
            # silently run the listener with default limits
            raise ConfigError(
                f"listeners[{i}].zone {lc.zone!r} is not defined "
                f"(zones: {sorted(cfg.zones) or ['default']})")
        cfg.listeners.append(lc)
    for name, env in raw.get("modules", {}).items():
        if name not in _module_classes():
            raise ConfigError(
                f"unknown module: modules.{name} "
                f"(available: {sorted(_module_classes())})")
        if not isinstance(env, dict):
            raise ConfigError(f"modules.{name} must be a table")
        cfg.modules[name] = env
    return cfg


def _module_classes() -> Dict[str, type]:
    from emqx_tpu.modules.acl_file import AclFileModule
    from emqx_tpu.modules.delayed import DelayedModule
    from emqx_tpu.modules.presence import PresenceModule
    from emqx_tpu.modules.prometheus import PrometheusModule
    from emqx_tpu.modules.retainer import RetainerModule
    from emqx_tpu.modules.rewrite import RewriteModule
    from emqx_tpu.modules.subscription import SubscriptionModule
    from emqx_tpu.modules.topic_metrics import TopicMetricsModule

    return {cls.name: cls for cls in (
        AclFileModule, DelayedModule, PresenceModule, PrometheusModule,
        RetainerModule, RewriteModule, SubscriptionModule,
        TopicMetricsModule)}


def build_node(cfg: NodeConfig):
    """Instantiate a Node (listeners attached, not yet started) from
    a parsed config; registers the zones globally so ``get_zone``
    resolves them (the reference's ETS zone snapshot)."""
    from emqx_tpu.node import Node
    from emqx_tpu.tls import TlsOptions

    import os as _os

    for zone in cfg.zones.values():
        set_zone(zone)
    if cfg.durability is not None and cfg.base_dir \
            and not _os.path.isabs(cfg.durability.dir):
        # like module files: a relative data dir anchors at the
        # config file, not the process cwd
        cfg.durability.dir = _os.path.join(cfg.base_dir,
                                           cfg.durability.dir)
    default = cfg.zones.get("default")
    node = Node(name=cfg.name, zone=default,
                matcher=cfg.matcher,
                telemetry=cfg.telemetry,
                tracing=cfg.tracing,
                dispatch_config=cfg.dispatch,
                sys_interval=cfg.sys_interval,
                load_default_modules=cfg.load_default_modules,
                loops=cfg.loops,
                frame=cfg.frame,
                overload=cfg.overload,
                faults_config=cfg.faults,
                durability=cfg.durability,
                drain=cfg.drain,
                boot_listeners=False)
    # the live-reload diff's baseline (emqx_tpu/reload.py): listener
    # topology is only comparable against what the node booted from
    node.boot_config = cfg
    for i, lc in enumerate(cfg.listeners):
        zone = cfg.zones.get(lc.zone)
        name = lc.name or f"{lc.type}:{i}"
        kw = dict(host=lc.host, port=lc.port, zone=zone, name=name,
                  max_connections=lc.max_connections)
        if lc.type == "tcp":
            node.add_listener(
                proxy_protocol=lc.proxy_protocol,
                proxy_protocol_timeout=lc.proxy_protocol_timeout,
                access_rules=lc.access,
                max_conn_rate=lc.max_conn_rate,
                **kw)
        elif lc.type == "ws":
            node.add_ws_listener(path=lc.path, **kw)
        elif lc.type == "ssl":
            node.add_tls_listener(
                tls_options=TlsOptions(**lc.tls),
                access_rules=lc.access,
                max_conn_rate=lc.max_conn_rate,
                peer_cert_as_username=lc.peer_cert_as_username,
                **kw)
        else:  # wss
            node.add_wss_listener(path=lc.path,
                                  tls_options=TlsOptions(**lc.tls), **kw)
    import os

    classes = _module_classes()
    for name, env in cfg.modules.items():
        env = dict(env)
        f = env.get("file")
        if isinstance(f, str) and not os.path.isabs(f) and cfg.base_dir:
            env["file"] = os.path.join(cfg.base_dir, f)
        if isinstance(env.get("file"), str) and \
                not os.path.exists(env["file"]):
            raise ConfigError(
                f"modules.{name}.file not found: {env['file']}")
        node.modules.load(classes[name], env=env)
    if cfg.cluster_port is not None:
        # socket transport + cluster agent come up inside
        # node.start() (the transport needs the serving loop)
        node.enable_cluster(port=cfg.cluster_port,
                            cookie=cfg.cookie or "emqxtpu",
                            config=cfg.cluster)
    return node


def reload_zones(path: str, node=None) -> dict:
    """Runtime zone reload (the reference's emqx_zone:force_reload:
    re-copy config into the lock-free snapshot registry). Re-parses
    the file, validates it in full, republishes every zone, and —
    given a node — REBINDS running listeners to the new Zone objects
    by name, so connections accepted from now on get the new limits.
    Existing connections keep the snapshot they were built with (the
    reference's semantics). Listener/cluster/module topology changes
    require a restart and are ignored here.

    Returns ``{"zones": [...], "listeners": [rebound...],
    "stale": [...]}`` — ``stale`` lists previously published zones
    the new file no longer defines (kept: a listener may still hold
    them; the report makes the drift visible)."""
    from emqx_tpu.zone import _zones

    cfg = load_config(path)
    for zone in cfg.zones.values():
        set_zone(zone)
    rebound = []
    if node is not None:
        for lst in node.listeners:
            nz = cfg.zones.get(lst.zone.name)
            if nz is not None and lst.zone is not nz:
                lst.zone = nz
                rebound.append(lst.name)
    stale = sorted(n for n in _zones
                   if n != "default" and n not in cfg.zones)
    return {"zones": sorted(cfg.zones), "listeners": rebound,
            "stale": stale}


def boot_from_file(path: str):
    """Build a Node from a config file (listeners attached, not yet
    started): ``node = boot_from_file(path); await node.start()``."""
    return build_node(load_config(path))
