"""MQTT v3.1/v3.1.1/v5.0 wire protocol: packets, codec, properties,
reason codes (reference: src/emqx_frame.erl, emqx_packet.erl,
emqx_mqtt_props.erl, emqx_reason_codes.erl)."""
