"""MQTT v5 property table: ids, names, wire types, packet validity.

Mirrors ``src/emqx_mqtt_props.erl`` (id/name table :30-120, packet
filter, validation). Properties travel as ``{Name: value}`` dicts;
``User-Property`` is a list of (key, value) pairs.
"""

from __future__ import annotations

from typing import Dict, Tuple

from emqx_tpu.mqtt import constants as C

# id -> (name, wire_type, allowed packet types)
BYTE = "byte"
TWO_BYTE = "two_byte"
FOUR_BYTE = "four_byte"
VARINT = "varint"
BINARY = "binary"
UTF8 = "utf8"
UTF8_PAIR = "utf8_pair"

_ALL = None  # allowed anywhere

PROPS: Dict[int, Tuple[str, str, object]] = {
    0x01: ("Payload-Format-Indicator", BYTE, {C.PUBLISH}),
    0x02: ("Message-Expiry-Interval", FOUR_BYTE, {C.PUBLISH}),
    0x03: ("Content-Type", UTF8, {C.PUBLISH}),
    0x08: ("Response-Topic", UTF8, {C.PUBLISH}),
    0x09: ("Correlation-Data", BINARY, {C.PUBLISH}),
    0x0B: ("Subscription-Identifier", VARINT, {C.PUBLISH, C.SUBSCRIBE}),
    0x11: ("Session-Expiry-Interval", FOUR_BYTE,
           {C.CONNECT, C.CONNACK, C.DISCONNECT}),
    0x12: ("Assigned-Client-Identifier", UTF8, {C.CONNACK}),
    0x13: ("Server-Keep-Alive", TWO_BYTE, {C.CONNACK}),
    0x15: ("Authentication-Method", UTF8, {C.CONNECT, C.CONNACK, C.AUTH}),
    0x16: ("Authentication-Data", BINARY, {C.CONNECT, C.CONNACK, C.AUTH}),
    0x17: ("Request-Problem-Information", BYTE, {C.CONNECT}),
    0x18: ("Will-Delay-Interval", FOUR_BYTE, {C.CONNECT}),
    0x19: ("Request-Response-Information", BYTE, {C.CONNECT}),
    0x1A: ("Response-Information", UTF8, {C.CONNACK}),
    0x1C: ("Server-Reference", UTF8, {C.CONNACK, C.DISCONNECT}),
    0x1F: ("Reason-String", UTF8, _ALL),
    0x21: ("Receive-Maximum", TWO_BYTE, {C.CONNECT, C.CONNACK}),
    0x22: ("Topic-Alias-Maximum", TWO_BYTE, {C.CONNECT, C.CONNACK}),
    0x23: ("Topic-Alias", TWO_BYTE, {C.PUBLISH}),
    0x24: ("Maximum-QoS", BYTE, {C.CONNACK}),
    0x25: ("Retain-Available", BYTE, {C.CONNACK}),
    0x26: ("User-Property", UTF8_PAIR, _ALL),
    0x27: ("Maximum-Packet-Size", FOUR_BYTE, {C.CONNECT, C.CONNACK}),
    0x28: ("Wildcard-Subscription-Available", BYTE, {C.CONNACK}),
    0x29: ("Subscription-Identifier-Available", BYTE, {C.CONNACK}),
    0x2A: ("Shared-Subscription-Available", BYTE, {C.CONNACK}),
}

NAME_TO_ID = {name: pid for pid, (name, _t, _p) in PROPS.items()}
NAME_TO_TYPE = {name: t for _pid, (name, t, _p) in PROPS.items()}


def prop_id(name: str) -> int:
    return NAME_TO_ID[name]


def prop_name(pid: int) -> str:
    return PROPS[pid][0]


def validate(props: dict, packet_type: int | None = None) -> None:
    """Raise ValueError on unknown names, wrong value types, or
    properties not allowed for the packet type."""
    for name, val in props.items():
        pid = NAME_TO_ID.get(name)
        if pid is None:
            raise ValueError(f"bad_property: {name}")
        pname, ptype, allowed = PROPS[pid]
        if packet_type is not None and allowed is not None \
                and packet_type not in allowed:
            raise ValueError(f"property_not_allowed: {name}")
        if ptype in (BYTE, TWO_BYTE, FOUR_BYTE, VARINT):
            if not isinstance(val, int) or val < 0:
                raise ValueError(f"bad_property_value: {name}={val!r}")
        elif ptype == UTF8:
            if not isinstance(val, str):
                raise ValueError(f"bad_property_value: {name}={val!r}")
        elif ptype == BINARY:
            if not isinstance(val, (bytes, bytearray)):
                raise ValueError(f"bad_property_value: {name}={val!r}")
        elif ptype == UTF8_PAIR:
            if not isinstance(val, list) or not all(
                    isinstance(p, tuple) and len(p) == 2 for p in val):
                raise ValueError(f"bad_property_value: {name}={val!r}")


def filter_for(packet_type: int, props: dict) -> dict:
    """Drop properties not valid for the packet type
    (emqx_mqtt_props:filter/2)."""
    out = {}
    for name, val in props.items():
        pid = NAME_TO_ID.get(name)
        if pid is None:
            continue
        allowed = PROPS[pid][2]
        if allowed is None or packet_type in allowed:
            out[name] = val
    return out
