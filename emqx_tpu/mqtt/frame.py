"""MQTT binary codec: incremental parser + serializer for v3.1,
v3.1.1 and v5.0.

Mirrors ``src/emqx_frame.erl``: the parser is incremental — feed it
byte chunks, it yields complete packets and retains partial state
(the reference's continuation closures :84-156 become an explicit
buffer + state struct); oversized frames raise ``FrameTooLarge``
before the body arrives (:113-136); the v5 property table is in
:mod:`emqx_tpu.mqtt.props` (reference :323-393); serialization
mirrors :401-749.
"""

from __future__ import annotations

import struct
from typing import Any, Dict, List, Optional, Tuple

from emqx_tpu.mqtt import constants as C
from emqx_tpu.mqtt import props as P
from emqx_tpu.mqtt.packet import (
    Auth, Connack, Connect, Disconnect, Packet, PubAck, Publish,
    Pingreq, Pingresp, Suback, Subscribe, Unsuback, Unsubscribe)


class FrameError(ValueError):
    pass


class FrameTooLarge(FrameError):
    pass


# native C frame scanner (ops/native.py) — resolved on first use;
# False = unavailable, stick with the Python framing loop
_scan = None


def _get_scan():
    """The C frame scanner is OPT-IN (EMQX_TPU_NATIVE_FRAME=1):
    measured on the live mixed workload the ctypes call boundary
    costs more than the C parse saves (~8% slower end-to-end; it
    only wins clean bulk-parse microbenches by ~13%). Kept correct
    under the fuzz suites for interpreters/workloads where the
    trade-off differs."""
    global _scan
    if _scan is None:
        import os

        if os.environ.get("EMQX_TPU_NATIVE_FRAME", "0") != "1":
            _scan = False
            return _scan
        try:
            from emqx_tpu.ops import native as _nat

            _scan = _nat.mqtt_scan if _nat.available() else False
        except Exception:
            _scan = False
    return _scan


# -- primitive readers -----------------------------------------------------

def _read_u8(b: bytes, i: int) -> Tuple[int, int]:
    if i + 1 > len(b):
        raise FrameError("truncated")
    return b[i], i + 1


def _read_u16(b: bytes, i: int) -> Tuple[int, int]:
    if i + 2 > len(b):
        raise FrameError("truncated")
    return (b[i] << 8) | b[i + 1], i + 2


def _read_u32(b: bytes, i: int) -> Tuple[int, int]:
    if i + 4 > len(b):
        raise FrameError("truncated")
    return struct.unpack_from(">I", b, i)[0], i + 4


def _read_varint(b: bytes, i: int) -> Tuple[int, int]:
    mult, val = 1, 0
    for _ in range(4):
        byte, i = _read_u8(b, i)
        val += (byte & 0x7F) * mult
        if not byte & 0x80:
            return val, i
        mult *= 128
    raise FrameError("malformed_variable_byte_integer")


def _read_bin(b: bytes, i: int) -> Tuple[bytes, int]:
    n, i = _read_u16(b, i)
    if i + n > len(b):
        raise FrameError("truncated")
    return b[i:i + n], i + n


def _read_str(b: bytes, i: int) -> Tuple[str, int]:
    raw, i = _read_bin(b, i)
    try:
        return raw.decode("utf-8"), i
    except UnicodeDecodeError as e:
        raise FrameError("utf8_string_invalid") from e


# -- primitive writers -----------------------------------------------------

def _w_u16(n: int) -> bytes:
    return struct.pack(">H", n)


def _w_u32(n: int) -> bytes:
    return struct.pack(">I", n)


def _w_varint(n: int) -> bytes:
    if n < 0 or n > C.MAX_PACKET_SIZE:
        raise FrameError("bad_varint")
    out = bytearray()
    while True:
        n, digit = divmod(n, 128)
        out.append(digit | (0x80 if n else 0))
        if not n:
            return bytes(out)


def _w_bin(b: bytes) -> bytes:
    return _w_u16(len(b)) + b


def _w_str(s: str) -> bytes:
    return _w_bin(s.encode("utf-8"))


# -- properties ------------------------------------------------------------

def _parse_props(b: bytes, i: int) -> Tuple[Dict[str, Any], int]:
    total, i = _read_varint(b, i)
    end = i + total
    if end > len(b):
        raise FrameError("truncated")
    out: Dict[str, Any] = {}
    while i < end:
        pid, i = _read_varint(b, i)
        entry = P.PROPS.get(pid)
        if entry is None:
            raise FrameError(f"bad_property_id: {pid:#x}")
        name, ptype, _allowed = entry
        if ptype == P.BYTE:
            val, i = _read_u8(b, i)
        elif ptype == P.TWO_BYTE:
            val, i = _read_u16(b, i)
        elif ptype == P.FOUR_BYTE:
            val, i = _read_u32(b, i)
        elif ptype == P.VARINT:
            val, i = _read_varint(b, i)
        elif ptype == P.BINARY:
            val, i = _read_bin(b, i)
        elif ptype == P.UTF8:
            val, i = _read_str(b, i)
        else:  # UTF8_PAIR
            k, i = _read_str(b, i)
            v, i = _read_str(b, i)
            out.setdefault(name, []).append((k, v))
            continue
        if name == "Subscription-Identifier":
            # may repeat; keep a list once repeated
            if name in out:
                prev = out[name]
                out[name] = (prev if isinstance(prev, list) else [prev]) + [val]
            else:
                out[name] = val
        else:
            out[name] = val
    return out, i


def _ser_props(props: Optional[Dict[str, Any]]) -> bytes:
    if not props:
        return _w_varint(0)
    body = bytearray()
    for name, val in props.items():
        pid = P.NAME_TO_ID.get(name)
        if pid is None:
            raise FrameError(f"bad_property: {name}")
        ptype = P.NAME_TO_TYPE[name]
        if ptype == P.UTF8_PAIR:
            for k, v in val:
                body += _w_varint(pid) + _w_str(k) + _w_str(v)
            continue
        vals = val if (name == "Subscription-Identifier"
                       and isinstance(val, list)) else [val]
        for v in vals:
            body += _w_varint(pid)
            if ptype == P.BYTE:
                body.append(v & 0xFF)
            elif ptype == P.TWO_BYTE:
                body += _w_u16(v)
            elif ptype == P.FOUR_BYTE:
                body += _w_u32(v)
            elif ptype == P.VARINT:
                body += _w_varint(v)
            elif ptype == P.BINARY:
                body += _w_bin(bytes(v))
            else:
                body += _w_str(v)
    return _w_varint(len(body)) + bytes(body)


# -- parser ----------------------------------------------------------------

class Parser:
    """Incremental packet parser. ``feed(data)`` returns complete
    packets; partial frames are buffered across calls."""

    def __init__(self, version: int = C.MQTT_V4,
                 max_size: int = C.MAX_PACKET_SIZE,
                 strict: bool = True) -> None:
        self.version = version
        self.max_size = max_size
        self.strict = strict
        self._buf = bytearray()

    def pending(self) -> int:
        """Bytes buffered awaiting the rest of a partial frame."""
        return len(self._buf)

    # below this buffer size the ctypes call overhead exceeds the C
    # scanner's parse savings (measured: single small frames parse
    # ~2x faster in pure Python; bulk pipelined reads ~15% faster
    # through the scanner) — the server's loaded reads are bulk
    _NATIVE_MIN = 1024

    def feed(self, data: bytes) -> List[Packet]:
        self._buf += data
        if len(self._buf) >= self._NATIVE_MIN:
            scan = _get_scan()
            if scan is not False:
                return self._feed_native(scan)
        out = []
        # moving offset + ONE compaction at the end: B packets in a
        # read cost O(buflen), not O(B·buflen) of per-packet del-shift.
        # On a body-parse error `pos` still points at the failing
        # frame's first byte, so the finally keeps it buffered —
        # raise-before-consume, same as always.
        pos = 0
        try:
            while True:
                pkt, consumed = self._try_parse(pos)
                if pkt is None:
                    return out
                pos += consumed
                out.append(pkt)
                if isinstance(pkt, Connect):
                    self.version = pkt.proto_ver
        finally:
            if pos:
                del self._buf[:pos]

    def _feed_native(self, scan) -> List[Packet]:
        """Framing through the C scanner; PUBLISH frames build from
        the pre-sliced (topic, pid, payload) layout, everything else
        (and every error) goes through the same Python body parsers
        as the pure-Python loop — identical observable behavior."""
        out: List[Packet] = []
        while True:
            flat, nf, consumed, err, err_size = scan(self._buf,
                                                     self.max_size)
            view = memoryview(self._buf)
            fstart = 0  # current frame's first byte (error semantics:
            # a frame whose BODY parse fails stays in the buffer,
            # exactly like the Python loop's raise-before-consume)
            try:
                for k in range(nf):
                    (header, boff, blen, toff, tlen,
                     pid, pp) = flat[k * 7:k * 7 + 7]
                    ptype = header >> 4
                    if toff >= 0 and ptype == C.PUBLISH:
                        qos = (header >> 1) & 0x03
                        if qos > 0 and self.strict and pid == 0:
                            raise FrameError("bad_packet_id")
                        try:
                            topic = bytes(
                                view[toff:toff + tlen]).decode("utf-8")
                        except UnicodeDecodeError as e:
                            raise FrameError(
                                "utf8_string_invalid") from e
                        props: Dict[str, Any] = {}
                        if self.version == C.MQTT_V5:
                            body = bytes(view[boff:boff + blen])
                            props, j = _parse_props(body, pp - boff)
                            payload = body[j:]
                        else:
                            payload = bytes(view[pp:boff + blen])
                        pkt = Publish(
                            dup=bool(header & 0x08), qos=qos,
                            retain=bool(header & 0x01), topic=topic,
                            packet_id=pid if qos > 0 else None,
                            properties=props, payload=payload)
                    else:
                        body = bytes(view[boff:boff + blen])
                        pkt = self._parse_packet(header, body)
                    out.append(pkt)
                    if isinstance(pkt, Connect):
                        self.version = pkt.proto_ver
                    fstart = boff + blen
            except Exception:
                view.release()
                del self._buf[:fstart]
                raise
            view.release()
            del self._buf[:consumed]
            if err == -1:
                raise FrameError("malformed_variable_byte_integer")
            if err == -2:
                raise FrameTooLarge(f"frame_too_large: {err_size}")
            if nf == 0 or not self._buf:
                return out

    def _try_parse(self, pos: int = 0) -> Tuple[Optional[Packet], int]:
        buf = self._buf
        if len(buf) - pos < 2:
            return None, 0
        # remaining length varint (1..4 bytes after the header byte)
        rl, mult, i = 0, 1, pos + 1
        while True:
            if i >= len(buf):
                if i - pos > 4:
                    raise FrameError("malformed_variable_byte_integer")
                return None, 0
            byte = buf[i]
            rl += (byte & 0x7F) * mult
            i += 1
            if not byte & 0x80:
                break
            if i - pos > 4:
                raise FrameError("malformed_variable_byte_integer")
            mult *= 128
        hlen = i - pos
        # v5 Maximum-Packet-Size covers the WHOLE packet, fixed
        # header included (hlen = header + varint bytes already read)
        if hlen + rl > self.max_size:
            raise FrameTooLarge(f"frame_too_large: {hlen + rl}")
        if len(buf) < i + rl:
            return None, 0
        header = buf[pos]
        # memoryview slice → ONE copy of the body (a bare bytearray
        # slice would copy twice: bytearray copy, then bytes copy)
        with memoryview(buf) as view:
            body = bytes(view[i:i + rl])
        pkt = self._parse_packet(header, body)
        return pkt, hlen + rl

    def _parse_packet(self, header: int, b: bytes) -> Packet:
        ptype = header >> 4
        flags = header & 0x0F
        v5 = self.version == C.MQTT_V5
        if ptype == C.CONNECT:
            return self._parse_connect(b)
        if ptype == C.CONNACK:
            ack_flags, i = _read_u8(b, 0)
            rc, i = _read_u8(b, i)
            props: Dict[str, Any] = {}
            if v5 and len(b) > i:
                props, i = _parse_props(b, i)
            return Connack(session_present=bool(ack_flags & 0x01),
                           reason_code=rc, properties=props)
        if ptype == C.PUBLISH:
            dup = bool(flags & 0x08)
            qos = (flags >> 1) & 0x03
            retain = bool(flags & 0x01)
            if qos > 2:
                raise FrameError("bad_qos")
            topic, i = _read_str(b, 0)
            pid = None
            if qos > 0:
                pid, i = _read_u16(b, i)
                if self.strict and pid == 0:
                    raise FrameError("bad_packet_id")
            props: Dict[str, Any] = {}
            if v5:
                props, i = _parse_props(b, i)
            return Publish(dup=dup, qos=qos, retain=retain, topic=topic,
                           packet_id=pid, properties=props, payload=b[i:])
        if ptype in (C.PUBACK, C.PUBREC, C.PUBREL, C.PUBCOMP):
            if ptype == C.PUBREL and self.strict and flags != 0x02:
                raise FrameError("bad_frame_flags")
            pid, i = _read_u16(b, 0)
            rc, props = 0, {}
            if v5 and len(b) > i:
                rc, i = _read_u8(b, i)
                if len(b) > i:
                    props, i = _parse_props(b, i)
            return PubAck(type=ptype, packet_id=pid, reason_code=rc,
                          properties=props)
        if ptype == C.SUBSCRIBE:
            if self.strict and flags != 0x02:
                raise FrameError("bad_frame_flags")
            pid, i = _read_u16(b, 0)
            if self.strict and pid == 0:
                raise FrameError("bad_packet_id")
            props = {}
            if v5:
                props, i = _parse_props(b, i)
            filters = []
            while i < len(b):
                flt, i = _read_str(b, i)
                opts, i = _read_u8(b, i)
                qos = opts & 0x03
                if self.strict and qos > 2:
                    raise FrameError("bad_subqos")
                if v5:
                    filters.append((flt, {
                        "qos": qos,
                        "nl": (opts >> 2) & 0x01,
                        "rap": (opts >> 3) & 0x01,
                        "rh": (opts >> 4) & 0x03,
                    }))
                else:
                    # v3/v3.1.1: the byte is Requested QoS only; the
                    # upper bits are reserved [MQTT-3.8.3-4]
                    if self.strict and opts & 0xFC:
                        raise FrameError("bad_subopts_reserved_bits")
                    filters.append((flt, {"qos": qos, "nl": 0,
                                          "rap": 0, "rh": 0}))
            if self.strict and not filters:
                raise FrameError("empty_topic_filters")
            return Subscribe(packet_id=pid, properties=props,
                             topic_filters=filters)
        if ptype == C.SUBACK:
            pid, i = _read_u16(b, 0)
            props = {}
            if v5:
                props, i = _parse_props(b, i)
            return Suback(packet_id=pid, properties=props,
                          reason_codes=list(b[i:]))
        if ptype == C.UNSUBSCRIBE:
            if self.strict and flags != 0x02:
                raise FrameError("bad_frame_flags")
            pid, i = _read_u16(b, 0)
            props = {}
            if v5:
                props, i = _parse_props(b, i)
            filters = []
            while i < len(b):
                flt, i = _read_str(b, i)
                filters.append(flt)
            if self.strict and not filters:
                raise FrameError("empty_topic_filters")
            return Unsubscribe(packet_id=pid, properties=props,
                               topic_filters=filters)
        if ptype == C.UNSUBACK:
            pid, i = _read_u16(b, 0)
            props = {}
            rcs: List[int] = []
            if v5:
                props, i = _parse_props(b, i)
                rcs = list(b[i:])
            return Unsuback(packet_id=pid, properties=props,
                            reason_codes=rcs)
        if ptype == C.PINGREQ:
            return Pingreq()
        if ptype == C.PINGRESP:
            return Pingresp()
        if ptype == C.DISCONNECT:
            rc, props, i = 0, {}, 0
            if v5 and len(b) > 0:
                rc, i = _read_u8(b, 0)
                if len(b) > i:
                    props, i = _parse_props(b, i)
            return Disconnect(reason_code=rc, properties=props)
        if ptype == C.AUTH:
            rc, props, i = 0, {}, 0
            if len(b) > 0:
                rc, i = _read_u8(b, 0)
                if len(b) > i:
                    props, i = _parse_props(b, i)
            return Auth(reason_code=rc, properties=props)
        raise FrameError(f"bad_packet_type: {ptype}")

    def _parse_connect(self, b: bytes) -> Connect:
        name, i = _read_str(b, 0)
        ver, i = _read_u8(b, i)
        # bridge mode rides the proto level's high bit
        # (src/emqx_frame.erl:177-185 BridgeTag)
        is_bridge = bool(ver & 0x80)
        ver &= 0x7F
        if (ver, name) not in ((3, "MQIsdp"), (4, "MQTT"), (5, "MQTT")):
            raise FrameError("bad_protocol")
        flags, i = _read_u8(b, i)
        if self.strict and flags & 0x01:
            raise FrameError("reserved_connect_flag")
        clean_start = bool(flags & 0x02)
        will_flag = bool(flags & 0x04)
        will_qos = (flags >> 3) & 0x03
        will_retain = bool(flags & 0x20)
        has_password = bool(flags & 0x40)
        has_username = bool(flags & 0x80)
        if self.strict and not will_flag and will_qos:
            raise FrameError("bad_will_qos")
        keepalive, i = _read_u16(b, i)
        props: Dict[str, Any] = {}
        if ver == C.MQTT_V5:
            props, i = _parse_props(b, i)
        client_id, i = _read_str(b, i)
        will_topic, will_payload, will_props = None, b"", {}
        if will_flag:
            if ver == C.MQTT_V5:
                will_props, i = _parse_props(b, i)
            will_topic, i = _read_str(b, i)
            will_payload, i = _read_bin(b, i)
        username = password = None
        if has_username:
            username, i = _read_str(b, i)
        if has_password:
            password, i = _read_bin(b, i)
        return Connect(
            proto_name=name, proto_ver=ver, is_bridge=is_bridge,
            clean_start=clean_start,
            keepalive=keepalive, client_id=client_id,
            will_flag=will_flag, will_qos=will_qos,
            will_retain=will_retain, will_topic=will_topic,
            will_payload=will_payload, will_props=will_props,
            username=username, password=password, properties=props)


class NativeParser(Parser):
    """:class:`Parser` backed by the stateful per-connection C handle
    (``mqtt_parser_new/feed/consume`` in native/emqx_native.cpp).

    The retained partial-frame remainder lives C-side; each feed
    ships only the new bytes across the ctypes boundary and gets back
    frame descriptors (the mqtt_scan 7-int rows) over the handle's
    buffer, which PUBLISH topic/payload slice zero-copy through a
    memoryview. Only packet bodies are decoded in Python — by exactly
    the same ``_parse_packet`` code the pure parser runs, so parity
    is structural for everything but the framing itself (which the
    differential fuzz suite pins byte-for-byte).

    Construct via :func:`make_parser` — raises when the library or
    the symbols are unavailable."""

    def __init__(self, version: int = C.MQTT_V4,
                 max_size: int = C.MAX_PACKET_SIZE,
                 strict: bool = True) -> None:
        super().__init__(version=version, max_size=max_size,
                         strict=strict)
        from emqx_tpu.ops import native as _nat

        self._h = _nat.FrameHandle(max_size)
        #: frames framed natively since the last harvest — the
        #: connection folds this into the frame.native.frames counter
        self.native_frames = 0

    def pending(self) -> int:
        """Bytes buffered C-side (the Python parser's len(_buf))."""
        return self._h.pending()

    def feed(self, data) -> List[Packet]:
        out: List[Packet] = []
        h = self._h
        chunk = data
        while True:
            nf = h.feed(chunk)
            chunk = b""
            state = h.state
            err, err_size = int(state[4]), int(state[1])
            consumed = 0
            view = h.view() if nf else None
            try:
                for k in range(nf):
                    row = h.out[k * 7:k * 7 + 7]
                    (header, boff, blen, toff, tlen, pid, pp) = row
                    ptype = header >> 4
                    if toff >= 0 and ptype == C.PUBLISH:
                        qos = (header >> 1) & 0x03
                        if qos > 0 and self.strict and pid == 0:
                            raise FrameError("bad_packet_id")
                        try:
                            topic = bytes(
                                view[toff:toff + tlen]).decode("utf-8")
                        except UnicodeDecodeError as e:
                            raise FrameError(
                                "utf8_string_invalid") from e
                        props: Dict[str, Any] = {}
                        if self.version == C.MQTT_V5:
                            body = bytes(view[boff:boff + blen])
                            props, j = _parse_props(body, pp - boff)
                            payload = body[j:]
                        else:
                            payload = bytes(view[pp:boff + blen])
                        pkt = Publish(
                            dup=bool(header & 0x08), qos=qos,
                            retain=bool(header & 0x01), topic=topic,
                            packet_id=pid if qos > 0 else None,
                            properties=props, payload=payload)
                    else:
                        body = bytes(view[boff:boff + blen])
                        pkt = self._parse_packet(header, body)
                    out.append(pkt)
                    if isinstance(pkt, Connect):
                        self.version = pkt.proto_ver
                    consumed = boff + blen
            except Exception:
                # raise-before-consume: the failed frame (and
                # everything after it) stays buffered, exactly like
                # the Python loop
                if view is not None:
                    view.release()
                h.consume(consumed)
                self.native_frames += nf
                raise
            if view is not None:
                view.release()
            h.consume(consumed)
            self.native_frames += nf
            if nf >= h.cap:
                # descriptor array full — more complete frames may
                # remain buffered; rescan without new bytes
                continue
            if err == -1:
                raise FrameError("malformed_variable_byte_integer")
            if err == -2:
                raise FrameTooLarge(f"frame_too_large: {err_size}")
            return out


def resolve_frame_mode(configured: str = "py") -> str:
    """The effective parser variant: ``EMQX_TPU_FRAME=py|native``
    overrides the ``[node] frame`` config knob."""
    import os

    env = os.environ.get("EMQX_TPU_FRAME")
    return env if env in ("py", "native") else configured


def make_parser(version: int = C.MQTT_V4,
                max_size: int = C.MAX_PACKET_SIZE,
                strict: bool = True,
                mode: str = "py") -> Parser:
    """Parser factory behind the ``[node] frame`` dispatch seam.

    ``mode="native"`` returns a :class:`NativeParser` when the shared
    library exports the handle symbols, else falls back to the Python
    :class:`Parser` (the caller detects the downgrade via isinstance
    and counts ``frame.fallback``)."""
    if mode == "native":
        try:
            return NativeParser(version=version, max_size=max_size,
                                strict=strict)
        except Exception:
            pass
    return Parser(version=version, max_size=max_size, strict=strict)


# -- serializer ------------------------------------------------------------

def publish_template(pkt: Publish,
                     version: int = C.MQTT_V4) -> Tuple[bytes, int]:
    """Serialize a QoS>0 PUBLISH as a packet-id template: returns
    ``(frame, pid_offset)`` where ``frame[pid_offset:pid_offset+2]``
    is the big-endian packet id. The pid is ALWAYS exactly two bytes,
    so the remaining-length varint is invariant across patches — one
    ``bytearray(frame)`` copy plus a 2-byte write per subscriber
    replaces a full :func:`serialize` on the egress fast lane
    (docs/DISPATCH.md "Egress pre-serialization").

    Offset derivation: 1 fixed-header byte, the remaining-length
    varint (its last byte has the continuation bit clear), the 2-byte
    topic length prefix, then the UTF-8 topic — the pid comes next
    on every protocol version (v5 properties follow it)."""
    if pkt.qos <= 0:
        raise FrameError("publish_template needs qos > 0")
    data = serialize(pkt, version)
    i = 1
    while data[i] & 0x80:
        i += 1
    off = i + 1 + 2 + len(pkt.topic.encode("utf-8"))
    return data, off


def serialize(pkt: Packet, version: int = C.MQTT_V4) -> bytes:
    v5 = version == C.MQTT_V5
    t = pkt.type
    flags = 0
    if isinstance(pkt, Publish):
        flags = ((0x08 if pkt.dup else 0) | (pkt.qos << 1)
                 | (0x01 if pkt.retain else 0))
        body = _w_str(pkt.topic)
        if pkt.qos > 0:
            body += _w_u16(pkt.packet_id or 0)
        if v5:
            body += _ser_props(pkt.properties)
        body += pkt.payload
    elif isinstance(pkt, Connect):
        flags_b = ((0x80 if pkt.username is not None else 0)
                   | (0x40 if pkt.password is not None else 0)
                   | (0x20 if pkt.will_retain else 0)
                   | (pkt.will_qos << 3)
                   | (0x04 if pkt.will_flag else 0)
                   | (0x02 if pkt.clean_start else 0))
        ver_b = pkt.proto_ver | (0x80 if getattr(pkt, "is_bridge",
                                                 False) else 0)
        body = (_w_str(C.PROTOCOL_NAMES[pkt.proto_ver])
                + bytes([ver_b, flags_b]) + _w_u16(pkt.keepalive))
        if pkt.proto_ver == C.MQTT_V5:
            body += _ser_props(pkt.properties)
        body += _w_str(pkt.client_id)
        if pkt.will_flag:
            if pkt.proto_ver == C.MQTT_V5:
                body += _ser_props(pkt.will_props)
            body += _w_str(pkt.will_topic or "") + _w_bin(pkt.will_payload)
        if pkt.username is not None:
            body += _w_str(pkt.username)
        if pkt.password is not None:
            body += _w_bin(pkt.password)
    elif isinstance(pkt, Connack):
        body = bytes([1 if pkt.session_present else 0, pkt.reason_code])
        if v5:
            body += _ser_props(pkt.properties)
    elif isinstance(pkt, PubAck):
        if pkt.type == C.PUBREL:
            flags = 0x02
        body = _w_u16(pkt.packet_id)
        if v5 and (pkt.reason_code or pkt.properties):
            body += bytes([pkt.reason_code]) + _ser_props(pkt.properties)
    elif isinstance(pkt, Subscribe):
        flags = 0x02
        body = _w_u16(pkt.packet_id)
        if v5:
            body += _ser_props(pkt.properties)
        for flt, opts in pkt.topic_filters:
            if v5:
                o = (opts.get("qos", 0) | (opts.get("nl", 0) << 2)
                     | (opts.get("rap", 0) << 3)
                     | (opts.get("rh", 0) << 4))
            else:
                # v3/v3.1.1: QoS only; upper bits reserved-zero
                # [MQTT-3.8.3-4]
                o = opts.get("qos", 0)
            body += _w_str(flt) + bytes([o])
    elif isinstance(pkt, Suback):
        body = _w_u16(pkt.packet_id)
        if v5:
            body += _ser_props(pkt.properties)
        body += bytes(pkt.reason_codes)
    elif isinstance(pkt, Unsubscribe):
        flags = 0x02
        body = _w_u16(pkt.packet_id)
        if v5:
            body += _ser_props(pkt.properties)
        for flt in pkt.topic_filters:
            body += _w_str(flt)
    elif isinstance(pkt, Unsuback):
        body = _w_u16(pkt.packet_id)
        if v5:
            body += _ser_props(pkt.properties) + bytes(pkt.reason_codes)
    elif isinstance(pkt, (Pingreq, Pingresp)):
        body = b""
    elif isinstance(pkt, Disconnect):
        body = b""
        if v5 and (pkt.reason_code or pkt.properties):
            body = bytes([pkt.reason_code]) + _ser_props(pkt.properties)
    elif isinstance(pkt, Auth):
        body = b""
        if pkt.reason_code or pkt.properties:
            body = bytes([pkt.reason_code]) + _ser_props(pkt.properties)
    else:
        raise FrameError(f"cannot_serialize: {pkt!r}")
    return bytes([(t << 4) | flags]) + _w_varint(len(body)) + body
