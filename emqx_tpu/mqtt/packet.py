"""MQTT control packets as dataclasses + packet-level helpers.

Mirrors the records of ``include/emqx_mqtt.hrl`` and the helpers of
``src/emqx_packet.erl``: validation (``check``), packet↔message
conversion (``to_message``/``from_message``), will-message extraction.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from emqx_tpu import topic as T
from emqx_tpu.mqtt import constants as C
from emqx_tpu.mqtt import reason_codes as RC
from emqx_tpu.types import Message


@dataclass
class Packet:
    """Base; `type` overridden per subclass."""
    type: int = 0


@dataclass
class Connect(Packet):
    type: int = C.CONNECT
    proto_name: str = "MQTT"
    proto_ver: int = C.MQTT_V4
    # MQTT bridge mode: the CONNECT proto level's high bit
    # (src/emqx_frame.erl:185 BridgeTag); bridges get rap=1 so
    # retained flags survive re-publication across brokers
    is_bridge: bool = False
    clean_start: bool = True
    keepalive: int = 60
    client_id: str = ""
    will_flag: bool = False
    will_qos: int = 0
    will_retain: bool = False
    will_topic: Optional[str] = None
    will_payload: bytes = b""
    will_props: Dict[str, Any] = field(default_factory=dict)
    username: Optional[str] = None
    password: Optional[bytes] = None
    properties: Dict[str, Any] = field(default_factory=dict)


@dataclass
class Connack(Packet):
    type: int = C.CONNACK
    session_present: bool = False
    reason_code: int = RC.SUCCESS
    properties: Dict[str, Any] = field(default_factory=dict)


@dataclass
class Publish(Packet):
    type: int = C.PUBLISH
    dup: bool = False
    qos: int = 0
    retain: bool = False
    topic: str = ""
    packet_id: Optional[int] = None
    properties: Dict[str, Any] = field(default_factory=dict)
    payload: bytes = b""


@dataclass
class PubAck(Packet):
    """Shared shape for PUBACK/PUBREC/PUBREL/PUBCOMP."""
    type: int = C.PUBACK
    packet_id: int = 0
    reason_code: int = RC.SUCCESS
    properties: Dict[str, Any] = field(default_factory=dict)


@dataclass
class Subscribe(Packet):
    type: int = C.SUBSCRIBE
    packet_id: int = 0
    properties: Dict[str, Any] = field(default_factory=dict)
    # [(topic_filter, {qos, nl, rap, rh})]
    topic_filters: List[Tuple[str, Dict[str, int]]] = field(default_factory=list)


@dataclass
class Suback(Packet):
    type: int = C.SUBACK
    packet_id: int = 0
    properties: Dict[str, Any] = field(default_factory=dict)
    reason_codes: List[int] = field(default_factory=list)


@dataclass
class Unsubscribe(Packet):
    type: int = C.UNSUBSCRIBE
    packet_id: int = 0
    properties: Dict[str, Any] = field(default_factory=dict)
    topic_filters: List[str] = field(default_factory=list)


@dataclass
class Unsuback(Packet):
    type: int = C.UNSUBACK
    packet_id: int = 0
    properties: Dict[str, Any] = field(default_factory=dict)
    reason_codes: List[int] = field(default_factory=list)


@dataclass
class Pingreq(Packet):
    type: int = C.PINGREQ


@dataclass
class Pingresp(Packet):
    type: int = C.PINGRESP


@dataclass
class Disconnect(Packet):
    type: int = C.DISCONNECT
    reason_code: int = RC.NORMAL_DISCONNECTION
    properties: Dict[str, Any] = field(default_factory=dict)


@dataclass
class Auth(Packet):
    type: int = C.AUTH
    reason_code: int = RC.SUCCESS
    properties: Dict[str, Any] = field(default_factory=dict)


class PacketError(ValueError):
    pass


def check(pkt: Packet) -> None:
    """Packet-level validity checks (emqx_packet:check/1).
    Raises PacketError (topic problems included)."""
    try:
        _check(pkt)
    except T.TopicError as e:
        raise PacketError(str(e)) from e


def _check(pkt: Packet) -> None:
    if isinstance(pkt, Publish):
        if pkt.qos > 0 and pkt.packet_id is None:
            raise PacketError("missing_packet_id")
        if pkt.topic == "" and "Topic-Alias" not in pkt.properties:
            raise PacketError("empty_topic")
        if pkt.topic:
            T.validate(pkt.topic, "name")
    elif isinstance(pkt, Subscribe):
        if not pkt.topic_filters:
            raise PacketError("empty_topic_filters")
        for flt, opts in pkt.topic_filters:
            T.validate(flt, "filter")
            if not 0 <= opts.get("qos", 0) <= 2:
                raise PacketError("bad_qos")
    elif isinstance(pkt, Unsubscribe):
        if not pkt.topic_filters:
            raise PacketError("empty_topic_filters")
        for flt in pkt.topic_filters:
            T.validate(flt, "filter")


def to_message(pkt: Publish, client_id: str,
               headers: Optional[dict] = None) -> Message:
    """PUBLISH packet -> routable message (emqx_packet:to_message/2)."""
    msg = Message(
        topic=pkt.topic, payload=pkt.payload, qos=pkt.qos,
        from_=client_id,
        flags={"dup": pkt.dup, "retain": pkt.retain},
    )
    if pkt.properties:
        msg.set_header("properties", dict(pkt.properties))
    for k, v in (headers or {}).items():
        msg.set_header(k, v)
    return msg


def from_message(packet_id: Optional[int], msg: Message) -> Publish:
    """Message -> PUBLISH packet for delivery
    (emqx_message:to_packet/2)."""
    return Publish(
        dup=msg.get_flag("dup"), qos=msg.qos,
        retain=msg.get_flag("retain"), topic=msg.topic,
        packet_id=packet_id,
        properties=dict(msg.get_header("properties") or {}),
        payload=msg.payload,
    )


def will_msg(pkt: Connect) -> Optional[Message]:
    """Extract the will message from CONNECT
    (emqx_packet:will_msg/1)."""
    if not pkt.will_flag:
        return None
    msg = Message(
        topic=pkt.will_topic or "", payload=pkt.will_payload,
        qos=pkt.will_qos, from_=pkt.client_id,
        flags={"dup": False, "retain": pkt.will_retain},
    )
    if pkt.will_props:
        msg.set_header("properties", dict(pkt.will_props))
    return msg
