"""MQTT v5 reason codes + v3 compatibility mapping
(reference: src/emqx_reason_codes.erl)."""

from __future__ import annotations

SUCCESS = 0x00
NORMAL_DISCONNECTION = 0x00
GRANTED_QOS_0 = 0x00
GRANTED_QOS_1 = 0x01
GRANTED_QOS_2 = 0x02
DISCONNECT_WITH_WILL = 0x04
NO_MATCHING_SUBSCRIBERS = 0x10
NO_SUBSCRIPTION_EXISTED = 0x11
CONTINUE_AUTHENTICATION = 0x18
REAUTHENTICATE = 0x19
UNSPECIFIED_ERROR = 0x80
MALFORMED_PACKET = 0x81
PROTOCOL_ERROR = 0x82
IMPLEMENTATION_SPECIFIC_ERROR = 0x83
UNSUPPORTED_PROTOCOL_VERSION = 0x84
CLIENT_IDENTIFIER_NOT_VALID = 0x85
BAD_USERNAME_OR_PASSWORD = 0x86
NOT_AUTHORIZED = 0x87
SERVER_UNAVAILABLE = 0x88
SERVER_BUSY = 0x89
BANNED = 0x8A
SERVER_SHUTTING_DOWN = 0x8B
BAD_AUTHENTICATION_METHOD = 0x8C
KEEPALIVE_TIMEOUT = 0x8D
SESSION_TAKEN_OVER = 0x8E
TOPIC_FILTER_INVALID = 0x8F
TOPIC_NAME_INVALID = 0x90
PACKET_IDENTIFIER_IN_USE = 0x91
PACKET_IDENTIFIER_NOT_FOUND = 0x92
RECEIVE_MAXIMUM_EXCEEDED = 0x93
TOPIC_ALIAS_INVALID = 0x94
PACKET_TOO_LARGE = 0x95
MESSAGE_RATE_TOO_HIGH = 0x96
QUOTA_EXCEEDED = 0x97
ADMINISTRATIVE_ACTION = 0x98
PAYLOAD_FORMAT_INVALID = 0x99
RETAIN_NOT_SUPPORTED = 0x9A
QOS_NOT_SUPPORTED = 0x9B
USE_ANOTHER_SERVER = 0x9C
SERVER_MOVED = 0x9D
SHARED_SUBSCRIPTIONS_NOT_SUPPORTED = 0x9E
CONNECTION_RATE_EXCEEDED = 0x9F
MAXIMUM_CONNECT_TIME = 0xA0
SUBSCRIPTION_IDENTIFIERS_NOT_SUPPORTED = 0xA1
WILDCARD_SUBSCRIPTIONS_NOT_SUPPORTED = 0xA2

_NAMES = {
    0x00: "success",
    0x01: "granted_qos1",
    0x02: "granted_qos2",
    0x04: "disconnect_with_will_message",
    0x10: "no_matching_subscribers",
    0x11: "no_subscription_existed",
    0x18: "continue_authentication",
    0x19: "re_authenticate",
    0x80: "unspecified_error",
    0x81: "malformed_packet",
    0x82: "protocol_error",
    0x83: "implementation_specific_error",
    0x84: "unsupported_protocol_version",
    0x85: "client_identifier_not_valid",
    0x86: "bad_username_or_password",
    0x87: "not_authorized",
    0x88: "server_unavailable",
    0x89: "server_busy",
    0x8A: "banned",
    0x8B: "server_shutting_down",
    0x8C: "bad_authentication_method",
    0x8D: "keepalive_timeout",
    0x8E: "session_taken_over",
    0x8F: "topic_filter_invalid",
    0x90: "topic_name_invalid",
    0x91: "packet_identifier_in_use",
    0x92: "packet_identifier_not_found",
    0x93: "receive_maximum_exceeded",
    0x94: "topic_alias_invalid",
    0x95: "packet_too_large",
    0x96: "message_rate_too_high",
    0x97: "quota_exceeded",
    0x98: "administrative_action",
    0x99: "payload_format_invalid",
    0x9A: "retain_not_supported",
    0x9B: "qos_not_supported",
    0x9C: "use_another_server",
    0x9D: "server_moved",
    0x9E: "shared_subscriptions_not_supported",
    0x9F: "connection_rate_exceeded",
    0xA0: "maximum_connect_time",
    0xA1: "subscription_identifiers_not_supported",
    0xA2: "wildcard_subscriptions_not_supported",
}


def name(code: int) -> str:
    return _NAMES.get(code, "unknown_error")


# v5 connack code -> v3 connack return code (emqx_reason_codes:compat/2)
_CONNACK_COMPAT = {
    0x00: 0,
    0x80: 3, 0x81: 3, 0x82: 3, 0x83: 3,
    0x84: 1,
    0x85: 2,
    0x86: 4,
    0x87: 5,
    0x88: 3, 0x89: 3,
    0x8A: 5,
    0x8C: 4,
    0x97: 3,
    0x9C: 3, 0x9D: 3, 0x9F: 3,
}


def compat(kind: str, code: int) -> int | None:
    """Map a v5 reason code onto the v3 wire equivalent."""
    if kind == "connack":
        return _CONNACK_COMPAT.get(code, 3)
    if kind == "suback":
        return 0x80 if code >= 0x80 else code
    if kind == "unsuback":
        return None
    return None
