"""Multi-loop front door: N asyncio event loops inside one Node.

The reference broker's front door scales inside one BEAM node because
every connection is a process and the schedulers own every core
(src/emqx_connection.erl one-process-per-socket, esockd acceptor
pools). The asyncio build had ONE event loop serving every socket —
``docs/ROADMAP.md`` names that single loop as the binding limit — and
PRs 3+5 moved plan construction and wire-byte construction off-loop,
leaving the on-loop delivery tail as little more than buffer writes.
This module supplies the missing piece: a :class:`LoopGroup` of
``n`` event loops (index 0 is the node's main loop; indices 1..n-1
run on their own threads), over which the listener shards accepted
connections (``connection.Listener._start_dispatch``) and through
which the dispatch planner's subscriber groups are handed to their
owning loop (``broker.Broker._post_xloop_handoffs`` — the cross-loop
delivery ring, docs/DISPATCH.md "Multi-loop front door").

Ownership rules (the invariants everything else leans on):

  - a connection — its read loop, parser, channel FSM, timers, and
    delivery flushes — runs entirely on the loop that accepted it;
  - a session is owned by its connection's loop (``Session.
    owner_loop``, stamped at CONNECT); its inflight window, mqueue
    and outbox are only touched from that loop while connected —
    the delivery ring routes each planned subscriber group to the
    owning loop instead of enqueueing from the main loop;
  - the main loop (index 0) keeps the node-wide state: ingress
    batcher, device plane, route tables (mutations serialized by the
    broker's route lock), metrics fold, housekeeping;
  - cross-loop channel operations (takeover/kick of a session owned
    by another loop) marshal onto the owning loop and wait, bounded
    (``cm.ConnectionManager._call_channel``).

``loops = 1`` constructs no LoopGroup at all — every code path is
byte-for-byte the single-loop build.
"""

from __future__ import annotations

import asyncio
import logging
import threading
import time
from typing import List, Optional, Set

from emqx_tpu.concurrency import any_thread, bg_thread, owner_loop

log = logging.getLogger("emqx_tpu.loops")

#: strong references to in-flight shutdown drains: the loop holds
#: only a weak reference to a task (lint rule CD104), and the drain
#: must survive until it stops its own loop
_DRAIN_TASKS: Set = set()


class LoopGroup:
    """``n`` event loops: the node's main loop plus ``n - 1`` peer
    loop threads. Started inside ``Node.start()`` (index 0 must be
    the running loop); peer threads are daemons, stopped by
    :meth:`stop` after the listeners and ingress drain."""

    def __init__(self, n: int) -> None:
        if n < 1:
            raise ValueError(f"loop count must be >= 1, got {n}")
        self.n = n
        self.loops: List[asyncio.AbstractEventLoop] = []
        self._threads: List[threading.Thread] = []
        self._idx = {}  # id(loop) -> index
        self._home_tid: Optional[int] = None
        self._started = False
        # peer loops whose thread died (overload monitor heal sweep):
        # posts to them raise, index_of maps their sessions home
        self._dead: Set[int] = set()

    @property
    def home(self) -> Optional[asyncio.AbstractEventLoop]:
        """The node's main loop (index 0)."""
        return self.loops[0] if self.loops else None

    @owner_loop
    def start(self, main_loop: asyncio.AbstractEventLoop) -> None:
        if self._started:
            return
        self.loops = [main_loop]
        self._idx = {id(main_loop): 0}
        self._home_tid = threading.get_ident()
        ready = threading.Event()
        for i in range(1, self.n):
            loop = asyncio.new_event_loop()
            t = threading.Thread(target=self._run_loop,
                                 args=(loop, ready),
                                 name=f"frontdoor-loop-{i}",
                                 daemon=True)
            self.loops.append(loop)
            self._idx[id(loop)] = i
            self._threads.append(t)
            ready.clear()
            t.start()
            # wait until the loop is actually spinning: a socket
            # handed to a not-yet-running loop would sit unserved
            ready.wait(5.0)
        self._started = True
        log.info("front door sharded over %d event loops", self.n)

    @staticmethod
    @bg_thread
    def _run_loop(loop: asyncio.AbstractEventLoop,
                  ready: threading.Event) -> None:
        asyncio.set_event_loop(loop)
        loop.call_soon(ready.set)
        try:
            loop.run_forever()
        finally:
            try:
                loop.close()
            except Exception:
                pass

    @owner_loop
    def stop(self, timeout: float = 10.0) -> None:
        """Cancel every peer loop's tasks, stop the loops, join the
        threads. The main loop (index 0) is the caller's — untouched."""
        for loop in self.loops[1:]:
            if loop.is_running():
                try:
                    loop.call_soon_threadsafe(self._shutdown_loop, loop)
                except RuntimeError:
                    pass
        for t in self._threads:
            t.join(timeout)
            if t.is_alive():
                log.warning("front-door loop thread %s did not stop "
                            "within %.0fs", t.name, timeout)
        self._threads.clear()
        self._started = False

    @staticmethod
    def _shutdown_loop(loop: asyncio.AbstractEventLoop) -> None:
        async def _drain():
            tasks = [t for t in asyncio.all_tasks(loop)
                     if t is not asyncio.current_task()]
            for t in tasks:
                t.cancel()
            if tasks:
                await asyncio.gather(*tasks, return_exceptions=True)
            loop.stop()

        t = loop.create_task(_drain())
        _DRAIN_TASKS.add(t)
        t.add_done_callback(_DRAIN_TASKS.discard)

    # -- addressing --------------------------------------------------------

    def index_of(self, loop) -> int:
        """Loop → index; unknown/None map to 0 (home): a session
        without a stamped owner is delivered from the main loop,
        exactly like the single-loop build."""
        if loop is None:
            return 0
        return self._idx.get(id(loop), 0)

    def on_home_thread(self) -> bool:
        return threading.get_ident() == self._home_tid

    @any_thread
    def post(self, idx: int, cb, *args) -> None:
        """Schedule ``cb(*args)`` on loop ``idx`` (thread-safe).
        Raises ``RuntimeError`` if that loop is closed or marked dead
        — callers fall back to running the work in place. The dead
        check matters: a loop whose THREAD died but whose loop object
        was never closed still accepts ``call_soon_threadsafe``, and
        the callback would silently never run (a hung join)."""
        if idx in self._dead:
            raise RuntimeError(f"front-door loop {idx} is dead")
        self.loops[idx].call_soon_threadsafe(cb, *args)

    # -- liveness (overload monitor heal sweep, docs/ROBUSTNESS.md) --------

    def alive(self, idx: int) -> bool:
        """Is loop ``idx`` serviceable? The home loop always is (it
        is the caller's); a peer is alive while its thread runs and
        it is not marked dead."""
        if idx == 0:
            return True
        if idx in self._dead or not self._started:
            return False
        t = self._threads[idx - 1] if idx - 1 < len(self._threads) \
            else None
        return t is not None and t.is_alive()

    def dead_peer_indices(self) -> List[int]:
        """Peer loops whose thread died but are not yet marked dead
        — the monitor marks + heals each exactly once."""
        if not self._started:
            return []
        return [i for i in range(1, len(self._threads) + 1)
                if i not in self._dead
                and not self._threads[i - 1].is_alive()]

    @owner_loop
    def mark_dead(self, idx: int) -> None:
        """Route around a dead loop: its sessions map home
        (``index_of`` → 0), future posts to it raise."""
        self._dead.add(idx)
        self._idx.pop(id(self.loops[idx]), None)

    # -- chaos helpers (tests/test_chaos.py; NOT part of the fault
    # registry — these simulate a loop dying/wedging from outside) --------

    def crash(self, idx: int) -> None:
        """Stop peer loop ``idx``: its run_forever returns and its
        thread exits, leaving its connection tasks frozen — exactly
        the state a crashed loop thread leaves behind."""
        loop = self.loops[idx]
        try:
            loop.call_soon_threadsafe(loop.stop)
        except RuntimeError:
            pass

    def stall(self, idx: int, seconds: float) -> None:
        """Wedge peer loop ``idx`` for ``seconds`` (a blocking sleep
        ON the loop): every task it owns — read loops, keepalive
        timers, cross-loop marshals — stalls with it."""
        self.loops[idx].call_soon_threadsafe(time.sleep, seconds)
