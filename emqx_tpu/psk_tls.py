"""Native TLS-PSK termination via ctypes-bound OpenSSL memory BIOs.

The reference serves TLS-PSK through esockd's ssl options with the
``'tls_handshake.psk_lookup'`` hook resolving identities
(``src/emqx_psk.erl:31``). CPython grew server-side PSK APIs only in
3.13; rather than leave the hookpoint dangling on older interpreters,
this module drives ``libssl`` directly: an :class:`PskTlsEngine` owns
an OpenSSL ``SSL`` object wired to two memory BIOs (ciphertext in /
ciphertext out), and an asyncio pump shuttles bytes between the real
socket and the engine, presenting a plain ``(StreamReader, writer)``
pair to the normal MQTT connection loop. PSK cipher suites are a
TLS ≤ 1.2 feature, so the engine pins the protocol to TLS 1.2 and the
``PSK`` cipher-list family (as the reference's psk_ciphers config,
``etc/emqx.conf``).

No OpenSSL headers are required — every entry point is declared via
``ctypes`` against the runtime ``libssl.so.3``/``libcrypto.so.3``
(the same libraries CPython's own ``ssl`` links). If the libraries
are absent, :func:`available` is False and the PSK listener refuses
to start with a clear error.
"""

from __future__ import annotations

import asyncio
import ctypes
import ctypes.util
import logging
from typing import Callable, Optional, Tuple

log = logging.getLogger("emqx_tpu.psk_tls")

# -- libssl / libcrypto binding ------------------------------------------

_SSL_ERROR_NONE = 0
_SSL_ERROR_SSL = 1
_SSL_ERROR_WANT_READ = 2
_SSL_ERROR_WANT_WRITE = 3
_SSL_ERROR_ZERO_RETURN = 6
_SSL_CTRL_SET_MIN_PROTO_VERSION = 123
_SSL_CTRL_SET_MAX_PROTO_VERSION = 124
_TLS1_2_VERSION = 0x0303

# unsigned int cb(SSL*, const char *identity, unsigned char *psk, max)
_SERVER_CB = ctypes.CFUNCTYPE(
    ctypes.c_uint, ctypes.c_void_p, ctypes.c_char_p,
    ctypes.POINTER(ctypes.c_ubyte), ctypes.c_uint)
# unsigned int cb(SSL*, const char *hint, char *identity, max_id,
#                 unsigned char *psk, max_psk)
_CLIENT_CB = ctypes.CFUNCTYPE(
    ctypes.c_uint, ctypes.c_void_p, ctypes.c_char_p,
    ctypes.POINTER(ctypes.c_char), ctypes.c_uint,
    ctypes.POINTER(ctypes.c_ubyte), ctypes.c_uint)

_lib = None


def _load():
    global _lib
    if _lib is not None:
        return _lib
    names = [("libssl.so.3", "libcrypto.so.3"),
             ("libssl.so.1.1", "libcrypto.so.1.1")]
    found = ctypes.util.find_library("ssl")
    if found:
        names.insert(0, (found, ctypes.util.find_library("crypto")))
    last = None
    for ssl_name, crypto_name in names:
        try:
            crypto = ctypes.CDLL(crypto_name or "libcrypto.so.3")
            ssl = ctypes.CDLL(ssl_name)
            _lib = _declare(ssl, crypto)
            return _lib
        except OSError as e:
            last = e
    raise RuntimeError(f"libssl not loadable: {last}")


def _declare(ssl, crypto):
    c = ctypes
    for name, args, res in [
        ("BIO_s_mem", [], c.c_void_p),
        ("BIO_new", [c.c_void_p], c.c_void_p),
        ("BIO_read", [c.c_void_p, c.c_void_p, c.c_int], c.c_int),
        ("BIO_write", [c.c_void_p, c.c_void_p, c.c_int], c.c_int),
        ("BIO_ctrl_pending", [c.c_void_p], c.c_size_t),
        ("ERR_get_error", [], c.c_ulong),
        ("ERR_error_string_n",
         [c.c_ulong, c.c_char_p, c.c_size_t], None),
        ("ERR_clear_error", [], None),
    ]:
        f = getattr(crypto, name)
        f.argtypes, f.restype = args, res
    for name, args, res in [
        ("TLS_server_method", [], c.c_void_p),
        ("TLS_client_method", [], c.c_void_p),
        ("SSL_CTX_new", [c.c_void_p], c.c_void_p),
        ("SSL_CTX_free", [c.c_void_p], None),
        ("SSL_CTX_ctrl",
         [c.c_void_p, c.c_int, c.c_long, c.c_void_p], c.c_long),
        ("SSL_CTX_set_cipher_list", [c.c_void_p, c.c_char_p], c.c_int),
        ("SSL_CTX_use_psk_identity_hint",
         [c.c_void_p, c.c_char_p], c.c_int),
        ("SSL_CTX_set_psk_server_callback",
         [c.c_void_p, _SERVER_CB], None),
        ("SSL_CTX_set_psk_client_callback",
         [c.c_void_p, _CLIENT_CB], None),
        ("SSL_new", [c.c_void_p], c.c_void_p),
        ("SSL_free", [c.c_void_p], None),
        ("SSL_set_accept_state", [c.c_void_p], None),
        ("SSL_set_connect_state", [c.c_void_p], None),
        ("SSL_set_bio", [c.c_void_p, c.c_void_p, c.c_void_p], None),
        ("SSL_do_handshake", [c.c_void_p], c.c_int),
        ("SSL_is_init_finished", [c.c_void_p], c.c_int),
        ("SSL_read", [c.c_void_p, c.c_void_p, c.c_int], c.c_int),
        ("SSL_write", [c.c_void_p, c.c_void_p, c.c_int], c.c_int),
        ("SSL_get_error", [c.c_void_p, c.c_int], c.c_int),
        ("SSL_get_psk_identity", [c.c_void_p], c.c_char_p),
    ]:
        f = getattr(ssl, name)
        f.argtypes, f.restype = args, res
    return (ssl, crypto)


def available() -> bool:
    try:
        _load()
        return True
    except Exception:
        return False


class PskTlsError(Exception):
    pass


def _err_text(crypto) -> str:
    buf = ctypes.create_string_buffer(256)
    parts = []
    while True:
        code = crypto.ERR_get_error()
        if not code:
            break
        crypto.ERR_error_string_n(code, buf, len(buf))
        parts.append(buf.value.decode("ascii", "replace"))
    return "; ".join(parts) or "unknown OpenSSL error"


class PskTlsContext:
    """A shared ``SSL_CTX`` (the OpenSSL per-listener object): cipher
    list, protocol pin, and the PSK callback thunk live here — one
    allocation + cipher parse per listener, ``SSL_new`` per
    connection."""

    def __init__(self, *, server: bool,
                 lookup: Optional[Callable[[str], Optional[bytes]]] = None,
                 identity: Optional[str] = None,
                 key: Optional[bytes] = None,
                 hint: str = "emqx_tpu",
                 ciphers: str = "PSK") -> None:
        self._ssl_lib, self._crypto = _load()
        self.server = server
        s = self._ssl_lib
        method = (s.TLS_server_method() if server
                  else s.TLS_client_method())
        self._ctx = s.SSL_CTX_new(method)
        if not self._ctx:
            raise PskTlsError("SSL_CTX_new failed")
        s.SSL_CTX_ctrl(self._ctx, _SSL_CTRL_SET_MIN_PROTO_VERSION,
                       _TLS1_2_VERSION, None)
        s.SSL_CTX_ctrl(self._ctx, _SSL_CTRL_SET_MAX_PROTO_VERSION,
                       _TLS1_2_VERSION, None)
        if not s.SSL_CTX_set_cipher_list(self._ctx,
                                         ciphers.encode("ascii")):
            raise PskTlsError(
                f"no PSK ciphers available: {_err_text(self._crypto)}")
        if server:
            if lookup is None:
                raise ValueError("server context needs a lookup fn")

            def _server_cb(_ssl, ident, psk_buf, max_len):
                try:
                    key_ = lookup((ident or b"").decode("utf-8",
                                                        "replace"))
                    if not key_ or len(key_) > max_len:
                        return 0
                    ctypes.memmove(psk_buf, key_, len(key_))
                    return len(key_)
                except Exception:
                    log.exception("psk lookup callback failed")
                    return 0

            self._cb = _SERVER_CB(_server_cb)  # keep alive
            s.SSL_CTX_set_psk_server_callback(self._ctx, self._cb)
            s.SSL_CTX_use_psk_identity_hint(self._ctx,
                                            hint.encode("utf-8"))
        else:
            if identity is None or key is None:
                raise ValueError("client context needs identity + key")
            ident_z = identity.encode("utf-8") + b"\x00"

            def _client_cb(_ssl, _hint, id_buf, max_id, psk_buf,
                           max_psk):
                if len(ident_z) > max_id or len(key) > max_psk:
                    return 0
                ctypes.memmove(id_buf, ident_z, len(ident_z))
                ctypes.memmove(psk_buf, key, len(key))
                return len(key)

            self._cb = _CLIENT_CB(_client_cb)
            s.SSL_CTX_set_psk_client_callback(self._ctx, self._cb)

    def close(self) -> None:
        if getattr(self, "_ctx", None):
            self._ssl_lib.SSL_CTX_free(self._ctx)
            self._ctx = None

    def __del__(self) -> None:  # pragma: no cover - GC safety net
        try:
            self.close()
        except Exception:
            pass


class PskTlsEngine:
    """One TLS-PSK endpoint over memory BIOs (sans-IO).

    Built either from a shared :class:`PskTlsContext` (``context=``,
    the listener path) or from the same keyword set (owns a private
    context — convenient for clients/tests). The caller pumps:
    :meth:`feed` ciphertext in, :meth:`outgoing` ciphertext out,
    :meth:`read`/:meth:`write` for plaintext.
    """

    def __init__(self, *, context: Optional[PskTlsContext] = None,
                 server: Optional[bool] = None, **ctx_kw) -> None:
        self._owns_ctx = context is None
        if context is None:
            if server is None:
                raise ValueError("need context= or server=")
            context = PskTlsContext(server=server, **ctx_kw)
        self._context = context  # keeps the callback thunk alive
        self._ssl_lib = context._ssl_lib
        self._crypto = context._crypto
        s = self._ssl_lib
        self._eof = False
        self._hs_done = False
        self._ssl = s.SSL_new(context._ctx)
        if not self._ssl:
            raise PskTlsError("SSL_new failed")
        (s.SSL_set_accept_state if context.server
         else s.SSL_set_connect_state)(self._ssl)
        mem = self._crypto.BIO_s_mem
        self._rbio = self._crypto.BIO_new(mem())
        self._wbio = self._crypto.BIO_new(mem())
        # SSL_set_bio transfers BIO ownership to the SSL object
        s.SSL_set_bio(self._ssl, self._rbio, self._wbio)

    def _check_open(self) -> None:
        if self._ssl is None:
            # a late write/read after close must be a Python error,
            # not a NULL pointer into libssl
            raise PskTlsError("TLS engine is closed")

    # -- byte pumps -------------------------------------------------------

    def feed(self, data: bytes) -> None:
        """Ciphertext from the network into the engine."""
        self._check_open()
        if data:
            n = self._crypto.BIO_write(self._rbio, data, len(data))
            if n != len(data):
                raise PskTlsError("BIO_write short write")

    def outgoing(self) -> bytes:
        """Drain ciphertext the engine wants on the wire."""
        self._check_open()
        out = b""
        while True:
            pending = self._crypto.BIO_ctrl_pending(self._wbio)
            if not pending:
                return out
            buf = ctypes.create_string_buffer(int(pending))
            n = self._crypto.BIO_read(self._wbio, buf, int(pending))
            if n <= 0:
                return out
            out += buf.raw[:n]

    def handshake(self) -> bool:
        """Advance the handshake; True once established. Raises
        :class:`PskTlsError` on fatal alert (bad key / no identity)."""
        if self._hs_done:
            return True
        self._check_open()
        self._crypto.ERR_clear_error()
        ret = self._ssl_lib.SSL_do_handshake(self._ssl)
        if ret == 1:
            self._hs_done = True
            return True
        err = self._ssl_lib.SSL_get_error(self._ssl, ret)
        if err in (_SSL_ERROR_WANT_READ, _SSL_ERROR_WANT_WRITE):
            return False
        raise PskTlsError(
            f"TLS-PSK handshake failed: {_err_text(self._crypto)}")

    @property
    def handshake_done(self) -> bool:
        return self._hs_done

    @property
    def psk_identity(self) -> Optional[str]:
        if self._ssl is None:
            return None
        ident = self._ssl_lib.SSL_get_psk_identity(self._ssl)
        return ident.decode("utf-8", "replace") if ident else None

    def read(self) -> bytes:
        """All decrypted plaintext currently available."""
        self._check_open()
        out = b""
        buf = ctypes.create_string_buffer(16384)
        while True:
            self._crypto.ERR_clear_error()
            n = self._ssl_lib.SSL_read(self._ssl, buf, len(buf))
            if n > 0:
                out += buf.raw[:n]
                continue
            err = self._ssl_lib.SSL_get_error(self._ssl, n)
            if err == _SSL_ERROR_ZERO_RETURN:
                self._eof = True  # close_notify
                return out
            if err in (_SSL_ERROR_WANT_READ, _SSL_ERROR_WANT_WRITE):
                return out
            raise PskTlsError(
                f"TLS read failed: {_err_text(self._crypto)}")

    @property
    def eof(self) -> bool:
        return self._eof

    def write(self, data: bytes) -> None:
        """Encrypt plaintext (collect ciphertext via
        :meth:`outgoing`). Memory BIOs grow, so this never blocks."""
        self._check_open()
        view = memoryview(data)
        while view:
            self._crypto.ERR_clear_error()
            n = self._ssl_lib.SSL_write(self._ssl, bytes(view[:16384]),
                                        min(len(view), 16384))
            if n <= 0:
                raise PskTlsError(
                    f"TLS write failed: {_err_text(self._crypto)}")
            view = view[n:]

    def close(self) -> None:
        if getattr(self, "_ssl", None):
            self._ssl_lib.SSL_free(self._ssl)  # frees both BIOs
            self._ssl = None
            self._rbio = self._wbio = None
        if getattr(self, "_owns_ctx", False) and \
                getattr(self, "_context", None) is not None:
            self._context.close()

    def __del__(self) -> None:  # pragma: no cover - GC safety net
        try:
            self.close()
        except Exception:
            pass

    # ssl.SSLObject-compatible surface for Connection's peercert probe
    def getpeercert(self):
        return None


# -- asyncio integration --------------------------------------------------


class PskStreamWriter:
    """Writer facade: encrypts through the engine, forwards ciphertext
    to the real socket writer. Implements the subset of
    ``asyncio.StreamWriter`` the connection loop uses."""

    def __init__(self, engine: PskTlsEngine, writer, pump_task) -> None:
        self._engine = engine
        self._writer = writer
        self._pump = pump_task
        self._closed = False

    def write(self, data: bytes) -> None:
        if self._closed:
            return  # asyncio writers ignore late writes; so do we
        self._engine.write(data)
        out = self._engine.outgoing()
        if out:
            self._writer.write(out)

    def writelines(self, data) -> None:
        """The egress fast lane flushes runs of pre-serialized frames
        through one writelines() call (Connection._send_packets);
        through TLS they still encrypt frame-by-frame, but the
        ciphertext forwards as one write."""
        if self._closed:
            return
        for chunk in data:
            self._engine.write(chunk)
        out = self._engine.outgoing()
        if out:
            self._writer.write(out)

    async def drain(self) -> None:
        await self._writer.drain()

    def close(self) -> None:
        self._closed = True
        if self._pump is not None:
            self._pump.cancel()
        try:
            self._writer.close()
        finally:
            self._engine.close()

    def is_closing(self) -> bool:
        return self._writer.is_closing()

    async def wait_closed(self) -> None:
        await self._writer.wait_closed()

    def get_extra_info(self, name, default=None):
        if name == "ssl_object":
            return self._engine
        if name == "psk_identity":
            return self._engine.psk_identity
        return self._writer.get_extra_info(name, default)


#: decrypt-pump high-water mark: above this much un-consumed
#: plaintext the pump stops reading the socket, re-engaging TCP
#: backpressure (the plain-TCP path gets this for free by reading
#: the socket directly; the zone's rate limiter then works again)
_PUMP_HIGH_WATER = 1 << 20


async def _pump(engine: PskTlsEngine, sock_reader,
                plain: asyncio.StreamReader, writer) -> None:
    """Socket → engine → plaintext reader (and any engine-generated
    ciphertext — renegotiation, close_notify replies — back out)."""
    try:
        while True:
            while len(plain._buffer) > _PUMP_HIGH_WATER:
                # connection loop hasn't consumed the plaintext yet:
                # stop pulling off the socket so the peer's TCP
                # window closes instead of our memory growing
                await asyncio.sleep(0.02)
            data = await sock_reader.read(65536)
            if not data:
                plain.feed_eof()
                return
            engine.feed(data)
            pt = engine.read()
            if pt:
                plain.feed_data(pt)
            out = engine.outgoing()
            if out:
                writer.write(out)
            if engine.eof:
                plain.feed_eof()
                return
    except asyncio.CancelledError:
        raise
    except Exception as e:
        # a mid-connection TLS failure (bad record MAC, protocol
        # violation) must leave a diagnostic trail, and the alert
        # OpenSSL queued belongs on the wire before the close
        log.info("TLS-PSK connection error: %s", e)
        try:
            out = engine.outgoing()
            if out:
                writer.write(out)
        except Exception:
            pass
        try:
            plain.feed_eof()
        except Exception:
            pass


async def handshake_streams(
        engine: PskTlsEngine, reader, writer,
        timeout: float = 10.0,
) -> Tuple[asyncio.StreamReader, PskStreamWriter]:
    """Complete the TLS handshake over (reader, writer) and return the
    plaintext stream pair; raises on failure/timeout."""
    loop = asyncio.get_running_loop()
    deadline = loop.time() + timeout

    while True:
        try:
            done = engine.handshake()
        except PskTlsError:
            # flush the alert OpenSSL queued (unknown_psk_identity /
            # decrypt_error) so the peer can tell a bad key from a
            # network failure, then re-raise
            out = engine.outgoing()
            if out:
                try:
                    writer.write(out)
                    await writer.drain()
                except Exception:
                    pass
            raise
        out = engine.outgoing()
        if out:
            writer.write(out)
            await writer.drain()
        if done:
            break
        remaining = deadline - loop.time()
        if remaining <= 0:
            # hard deadline: a drip-feeding client must not hold a
            # handshake slot past the timeout (slow-loris)
            raise asyncio.TimeoutError("TLS-PSK handshake deadline")
        data = await asyncio.wait_for(reader.read(65536), remaining)
        if not data:
            raise PskTlsError("peer closed during TLS-PSK handshake")
        engine.feed(data)

    plain = asyncio.StreamReader()
    pt = engine.read()  # early data arriving with the final flight
    if pt:
        plain.feed_data(pt)
    task = asyncio.ensure_future(_pump(engine, reader, plain, writer))
    return plain, PskStreamWriter(engine, writer, task)


async def open_psk_connection(
        host: str, port: int, identity: str, key: bytes,
        timeout: float = 10.0):
    """Client side: TCP connect + TLS-PSK handshake; returns a
    ``(reader, writer)`` pair speaking plaintext (what emqtt's
    ``{psk, ...}`` ssl opts give the reference's suites)."""
    reader, writer = await asyncio.open_connection(host, port)
    engine = PskTlsEngine(server=False, identity=identity, key=key)
    try:
        return await handshake_streams(engine, reader, writer,
                                       timeout=timeout)
    except Exception:
        writer.close()
        engine.close()
        raise


from emqx_tpu.connection import Listener  # noqa: E402  (cycle-free)


class PskTlsListener(Listener):
    """MQTT listener terminating TLS-PSK natively (no fronting
    proxy): handshake via the ctypes OpenSSL engine, identities
    resolved through the ``'tls_handshake.psk_lookup'`` hook chain
    (:class:`emqx_tpu.psk.PskAuth`). One shared ``SSL_CTX`` per
    listener (cipher list parsed once); ``SSL_new`` per connection."""

    def __init__(self, *args, psk=None, psk_identity_hint="emqx_tpu",
                 psk_ciphers="PSK", handshake_timeout=10.0, **kw):
        super().__init__(*args, **kw)
        if psk is None:
            raise ValueError("PskTlsListener needs a psk store")
        if not available():
            raise RuntimeError(
                "native TLS-PSK needs libssl; none loadable")
        self.psk = psk
        self.handshake_timeout = handshake_timeout
        # misconfiguration (bad cipher string, restricted provider)
        # surfaces HERE, at listener build, not per-connection
        self.tls_context = PskTlsContext(
            server=True, lookup=psk.lookup, hint=psk_identity_hint,
            ciphers=psk_ciphers)

    async def _handshake(self, reader, writer):
        engine = None
        try:
            engine = PskTlsEngine(context=self.tls_context)
            return await handshake_streams(
                engine, reader, writer,
                timeout=self.handshake_timeout)
        except (PskTlsError, asyncio.TimeoutError, OSError) as e:
            log.info("TLS-PSK handshake rejected: %s", e)
            if engine is not None:
                engine.close()
            return False
