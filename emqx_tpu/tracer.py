"""Per-clientid / per-topic tracing via logging handlers
(reference: src/emqx_tracer.erl:102-151 — OTP logger handlers with
metadata/topic filters; here: logging.Handler instances filtered on
record attributes, plus an in-memory tap for tests/CLI).

Each Tracer owns a private, non-propagating logger so traces on one
broker node never capture another node's traffic in multi-node
processes."""

from __future__ import annotations

import itertools
import json
import logging
from typing import Dict, List, Tuple

from emqx_tpu import topic as T

_ids = itertools.count()


class _TraceHandler(logging.Handler):
    def __init__(self, kind: str, value: str, sink) -> None:
        super().__init__(level=logging.DEBUG)
        self.kind = kind      # "clientid" | "topic"
        self.value = value
        self.sink = sink      # list or file-like
        self.dead = False     # sink failed — emit is a no-op
        # set by the owning Tracer: detaches this handler on a sink
        # failure so a closed file doesn't stay subscribed forever
        self.on_error = None

    def match(self, record: logging.LogRecord) -> bool:
        if self.kind == "clientid":
            return getattr(record, "clientid", None) == self.value
        topic = getattr(record, "topic", None)
        return topic is not None and T.match(topic, self.value)

    def emit(self, record: logging.LogRecord) -> None:
        if self.dead or not self.match(record):
            return
        line = self.format(record)
        try:
            if hasattr(self.sink, "write"):
                self.sink.write(line + "\n")
            else:
                self.sink.append(line)
        except Exception:
            # a closed/broken sink must not bubble out of the
            # logging call on the PUBLISH path (trace_publish runs
            # inside publish_begin): go dead immediately, then let
            # the tracer unhook us cleanly
            self.dead = True
            if self.on_error is not None:
                self.on_error(self)


class Tracer:
    def __init__(self) -> None:
        self._log = logging.getLogger(
            f"emqx_tpu.trace.{next(_ids)}")
        self._log.setLevel(logging.DEBUG)
        self._log.propagate = False
        self._traces: Dict[Tuple[str, str], _TraceHandler] = {}

    def start_trace(self, kind: str, value: str, sink=None):
        """sink: a list (in-memory) or open file; returns the sink."""
        assert kind in ("clientid", "topic")
        key = (kind, value)
        if key in self._traces:
            raise ValueError("already_traced")
        sink = [] if sink is None else sink
        h = _TraceHandler(kind, value, sink)
        h.setFormatter(logging.Formatter(
            "%(asctime)s [%(levelname)s] %(message)s"))
        h.on_error = self._detach
        self._log.addHandler(h)
        self._traces[key] = h
        return sink

    def _detach(self, h: _TraceHandler) -> None:
        """A handler's sink failed mid-emit: unhook it from the
        logger and the registry. REBIND the handler list rather than
        mutating it — this runs from inside the logger's own
        callHandlers iteration, and an in-place removal would shift
        the list under the loop and skip the NEXT handler for the
        current record."""
        self._traces.pop((h.kind, h.value), None)
        self._log.handlers = [x for x in self._log.handlers
                              if x is not h]

    def stop_trace(self, kind: str, value: str) -> bool:
        h = self._traces.pop((kind, value), None)
        if h is None:
            return False
        self._log.removeHandler(h)
        flush = getattr(h.sink, "flush", None)
        if callable(flush):
            # a file sink's buffered tail must land when the operator
            # stops the trace — they read the file next
            try:
                flush()
            except Exception:
                pass
        return True

    def lookup_traces(self) -> List[Tuple[str, str]]:
        return list(self._traces)

    def trace_publish(self, msg) -> None:
        """Tee a publish into the trace log (emqx_broker.erl:202)."""
        if self._traces:
            self._log.debug("PUBLISH to %s: %r", msg.topic,
                            msg.payload[:64],
                            extra={"topic": msg.topic,
                                   "clientid": msg.from_})

    def trace_packet(self, direction: str, clientid: str, pkt) -> None:
        if self._traces:
            # outbound PUBLISH/inbound packets that carry a topic must
            # stamp it, or topic-filter traces miss them entirely (the
            # filter matches on the record's `topic` extra)
            topic = getattr(pkt, "topic", None)
            extra = {"clientid": clientid}
            if topic:
                extra["topic"] = topic
            self._log.debug("%s %s", direction, pkt, extra=extra)

    def trace_slow_publish(self, record: dict) -> None:
        """Tee a slow-publish telemetry record (telemetry.Telemetry)
        into the trace log: a topic trace whose filter matches the
        batch's sample topic captures the per-stage breakdown inline
        with that topic's publishes."""
        if self._traces:
            self._log.warning("SLOW PUBLISH %s", json.dumps(record),
                              extra={"topic": record.get("topic")})
