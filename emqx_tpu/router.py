"""The router: authoritative route state + the compiled device matcher.

Replaces the reference's ``emqx_router``/``emqx_trie`` pair
(src/emqx_router.erl:113-133, src/emqx_trie.erl): routes are a host
map ``filter → {dest: refcount}`` (the Mnesia ``emqx_route`` bag), and
the *match* side is a TPU-resident CSR automaton rebuilt incrementally
from the host trie. Differences by design (SURVEY §7):

  - the reference keeps exact-match routes out of the trie and unions
    a direct ETS lookup at match time (emqx_router.erl:127-133); here
    *all* filters live in the automaton, so one device walk returns
    the full route set — an exact filter is just a literal path;
  - rebuilds are double-buffered: matching continues against the live
    automaton while the new one is flattened; the swap is atomic from
    the caller's perspective (the reference's transactional trie
    insert, emqx_router.erl:229-234);
  - topics that exceed the kernel's static bounds fall back to the
    host oracle (exact parity, never truncation).

Thread-safety follows the reference's serialization model: writes go
through one writer (the reference hashes topics onto router_pool
workers, emqx_router.erl:185-186); here a mutex serializes mutations.
"""

from __future__ import annotations

import logging
import threading
import time
import zlib
from collections import deque
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from emqx_tpu import faults
from emqx_tpu import topic as T
from emqx_tpu.oracle import TrieOracle
from emqx_tpu.ops.csr import Automaton, build_automaton, device_view
from emqx_tpu.ops.match import depth_bucket
from emqx_tpu.ops.walk_pallas import match_batch_auto, walk_variant
from emqx_tpu.ops.patch import AutoPatcher, PatchOverflow
from emqx_tpu.ops.tokenize import WordTable, encode_batch
from emqx_tpu.types import Route

log = logging.getLogger("emqx_tpu.router")


@dataclass
class MatcherConfig:
    max_levels: int = 16    # L — deeper topics fall back to the oracle
    # NFA active-set capacity: the walk's cost is ~linear in K (3
    # packed gathers per state-level), and real active sets are tiny
    # (≤ matching prefix paths). Overflow → exact host fallback.
    active_k: int = 16
    max_matches: int = 64   # match output capacity
    min_batch: int = 8      # batch padding bucket floor (pow2 buckets)
    use_device: bool = True
    use_native: bool = True  # C++ trie/encoder when the .so is present
    # multi-chip: a (data × trie) jax Mesh shards the filter set over
    # the 'trie' axis and the publish batch over 'data'; matching goes
    # through parallel.sharded.publish_step (ICI all-gather of match
    # ids). BASELINE config 5's product path.
    mesh: Optional[object] = None
    # device fan-out (broker_helper): filters with more subscribers
    # than the threshold move from the CSR gather to bitmap rows
    # (the reference's ?SHARD=1024, src/emqx_broker_helper.erl:55)
    fanout_threshold: int = 1024
    # per-message small-filter delivery slots: gather cost is ~linear
    # in d; a message exceeding it host-dispatches (and >threshold
    # filters ride the bitmap path, so d only covers the small tail)
    fanout_d: int = 128
    fanout_mb: int = 16      # per-message big(bitmap)-filter slots
    # below this many live filters the broker matches on HOST (the
    # C++ trie): a device dispatch + result transfer costs fixed
    # round-trip latency that only amortizes at scale, while the host
    # walk is O(depth) hash lookups. The device automaton still
    # maintains itself (patching/rebuilds) so crossing the threshold
    # is usually just a branch flip — unless host-regime churn piled
    # more than host_reclaim_pending freed ids, in which case the
    # stale automaton is dropped (reclaim_host_regime) and the next
    # device use re-flattens.
    device_min_filters: int = 1024
    # host-regime quarantined-id bound before the stale automaton is
    # dropped and ids recycle (bounded hysteresis; round-4 leak fix)
    host_reclaim_pending: int = 1024
    # packed-transfer budgets (ops/pack.py): expected average matched
    # filters / deliveries per message and bitmap rows per batch; the
    # publish path re-packs with the next pow2 bucket on overflow
    pack_m: int = 8
    pack_q: int = 16
    pack_rows: int = 8
    # mutation-side patch drain: once this many device updates are
    # queued, the MUTATOR applies them (amortized O(1) per route
    # change). Matchers then find at most one small chunk to drain —
    # under 10K route-mutations/s the round-4 churn bench showed the
    # match path paying a multi-chunk drain (each chunk copy-on-
    # writes the full walk tables) on nearly every call, a 90ms p99
    # tail the reference's O(levels) dirty inserts never had
    # (src/emqx_router.erl:226-234).
    patch_drain_batch: int = 256
    # publish match cache (ops/match_cache.py): epoch-guarded HBM
    # memo of per-topic match rows — a repeat topic across batches
    # costs one gather instead of an NFA walk. A route add/delete
    # bumps the affected partition's epoch (or the global one — see
    # cache_partitions below), rebuilds/capacity boosts bump
    # globally, so stale entries self-invalidate; overflow topics are
    # never served from it (exact host fallback, as always). False
    # restores the pre-cache dispatch byte-for-byte. Slot count is a
    # power of two; footprint ≈ slots × (max_matches + 1) × 4 B
    # (default 64K slots × 65 ints ≈ 16 MB of HBM).
    match_cache: bool = True
    match_cache_slots: int = 65536
    # match-cache invalidation granularity: P-way partitioned epoch
    # keys over the topic's FIRST LEVEL. A filter mutation whose root
    # is a literal bumps only its partition's revision (a filter
    # `a/+/c` can only change the match set of topics rooted at `a`),
    # so disjoint-prefix subscribe/unsubscribe churn no longer
    # collapses the hit rate to zero; root `+`/`#` filters (and
    # rebuilds, reclaims) still bump the global revision — exactly as
    # safe as whole-epoch. Power of two; 1 = legacy whole-epoch
    # invalidation byte-for-byte (the PR-1 behavior).
    cache_partitions: int = 64
    # online delta automaton (ops/delta.py, docs/DELTA.md): route adds
    # batch into a small side-automaton probed alongside the main walk
    # (terminal-id union), deletes become a post-match tombstone-id
    # mask — the main tables stay PRISTINE during storms (no patch
    # splits, no hop decay, no full-table scatter copies), and the
    # background compaction flattens the persistent trie OFF-lock
    # (route ops during the flatten complete in ms and land in the
    # next delta generation via the mutation log). False restores the
    # patch-in-place path byte-for-byte. A configured mesh keeps
    # per-shard patch-in-place regardless (the delta is single-chip).
    delta: bool = True
    # pending delta adds that trigger the background merge compaction
    # (also bounds the side-automaton walk cost)
    delta_max_filters: int = 4096

    #: live-reloadable knobs (emqx_tpu/reload.py,
    #: docs/OPERATIONS.md): only fields the match/mutation paths read
    #: at use time — everything else is kernel/table geometry copied
    #: into built device structures at flatten time (not a dataclass
    #: field: unannotated)
    RELOADABLE = frozenset({
        "delta", "delta_max_filters", "device_min_filters",
        "patch_drain_batch", "host_reclaim_pending"})


def topic_partition(topic: str, parts: int) -> int:
    """Match-cache partition of a concrete topic: a stable hash of
    its first level (``parts`` is a power of two). Stable across
    processes (crc32, not ``hash``) so bench A/B runs and checkpoint
    restores key identically."""
    return zlib.crc32(topic.partition("/")[0].encode()) & (parts - 1)


def filter_partitions(filter_: str, parts: int) -> Optional[Tuple[int, ...]]:
    """Invalidation scope of a filter mutation under partitioned
    epochs: the partition indices to bump, or ``None`` when only a
    global bump is safe.

    A filter whose first level is a **literal** ``L`` can only change
    the match set of topics whose first level is exactly ``L`` (the
    automaton descends level-by-level; ``+``/``#`` deeper in the
    filter never widen the root), so bumping partition ``h(L)``
    suffices. A root ``+`` or ``#`` matches topics of any root →
    ``None``. A ``$share``/``$queue`` prefix is group routing, not
    matching — the broker strips it before ``add_route`` — so a
    prefixed filter reaching the router verbatim partitions on the
    level AFTER the prefix (the root of the filter that actually
    matches subscribers' topics) *plus* the raw ``$share`` root
    (covering the literal interpretation: a trie handed the prefixed
    string matches topics rooted ``$share``). A malformed or
    wildcard-rooted inner filter falls back to ``None`` —
    conservatively correct, never stale."""
    root = filter_.partition("/")[0]
    if root == T.PLUS or root == T.HASH:
        return None
    p0 = zlib.crc32(root.encode()) & (parts - 1)
    if not filter_.startswith((T.SHARE_PREFIX, T.QUEUE_PREFIX)):
        return (p0,)
    try:
        inner, _opts = T.parse(filter_)
    except T.TopicError:
        return None
    iroot = inner.partition("/")[0]
    if iroot == T.PLUS or iroot == T.HASH:
        return None
    p1 = zlib.crc32(iroot.encode()) & (parts - 1)
    return (p0,) if p1 == p0 else (p0, p1)


class Router:
    """Cluster route table + compiled matcher (one per node)."""

    def __init__(self, config: Optional[MatcherConfig] = None,
                 node: str = "local") -> None:
        self.config = config or MatcherConfig()
        self.node = node
        self._lock = threading.RLock()
        # word-table guard, finer than _lock: interning rehashes the
        # word map, which must not race the match path's encode reads.
        # Matchers take ONLY this lock (briefly, around encode), so a
        # long flatten under _lock — background compaction — never
        # stalls them. Order: _lock before _wt_lock, never the reverse.
        self._wt_lock = threading.RLock()
        self._native = None
        # C++ engine on both layouts: one monolithic trie single-chip,
        # one trie per trie shard on a mesh (ShardedNativeEngine —
        # same stable shard_of assignment as the Python builder)
        if self.config.use_native:
            try:
                from emqx_tpu.ops import native as _native_mod
                if _native_mod.available():
                    if self.config.mesh is None:
                        self._native = _native_mod.NativeEngine()
                    else:
                        self._native = _native_mod.ShardedNativeEngine(
                            self.config.mesh.shape["trie"])
            except Exception:
                self._native = None
        # pure-Python structures double as the fallback path when the
        # native engine is absent (parity pinned in tests/test_native)
        self._trie = TrieOracle() if self._native is None else None
        self._table = WordTable() if self._native is None else None
        # filter -> {dest: refcount}; bag semantics (emqx_route)
        self._routes: Dict[str, Dict[object, int]] = {}
        self._filter_ids: Dict[str, int] = {}
        self._id_to_filter: List[Optional[str]] = []
        # ids are recycled only across rebuild generations: a freed id
        # quarantines in _pending_free until the next full flatten
        # (which replaces the published id-map object), so any map a
        # matcher holds is append-only + tombstone-only — a recycled
        # id can never retranslate to a different filter mid-read
        self._free_ids: List[int] = []
        self._pending_free: List[int] = []
        self._auto: Optional[Automaton] = None  # live device automaton
        # id→filter list the live automaton encodes: appended/tombstoned
        # in place by the patcher, REPLACED (new object) on rebuild
        self._auto_map: List[Optional[str]] = []
        # (auto, map, epoch) snapshot: one-reference read for matchers
        # (attribute assignment is atomic — no lock on the match path)
        self._published: Optional[tuple] = None
        self._dirty = True
        self._rebuilds = 0
        self._patches = 0
        # vocabulary revision: bumped when a filter INSERT completes
        # (inserts intern new words; the word table is append-only,
        # so deletes never invalidate an encoding). A batch encoded
        # at revision R is only valid to dispatch at R —
        # encode_place_sharded stamps it, publish_dispatch_sharded
        # verifies and re-encodes on mismatch
        self._mut_rev = 0
        # O(delta) maintenance (ops/patch.py): host mirror of the live
        # automaton; None until the first flatten. Mesh mode keeps ONE
        # PATCHER PER TRIE SHARD (stable hash assignment — a mutation
        # patches exactly its shard's row of the stacked automaton)
        self._patcher: Optional[AutoPatcher] = None
        self._shard_patchers: List[AutoPatcher] = []
        self._sharded_caps = {"state": None, "nb": None}
        self._grow = {"state": 1, "edge": 1}  # rebuild growth factors
        # static walk parameters of the LIVE tables (read host-side,
        # never through jit): slot layout, max take, step bounds, and
        # whether any '+' edge exists (no '+' ⇒ the active set is
        # provably ≤1 lane, so the walk runs k=1)
        self._walk_meta = {"slots": 2, "take": 1, "hops": None,
                           "has_plus": True}
        # level-compression facts of the LIVE tables (set alongside
        # _walk_meta at rebuild/restore): chains = compressed edges
        # carrying a fused run (take > 1), fused_edges = interior
        # states those runs absorbed, ratio = permille of walk steps
        # compression shaved off the deepest level
        self._compaction = {"mode": "narrow", "chains": 0,
                            "fused_edges": 0, "ratio": 0}
        # level-bucket shapes live dispatches have compiled (lb after
        # depth_bucket) — devloss rewarm replays exactly these so a
        # deep post-recovery batch pays zero compile (ops/warmup.py)
        self._seen_levels: set = set()
        self._compacting = False  # background compaction in flight
        # crashed-compaction supervision (docs/ROBUSTNESS.md): a
        # background flatten that raised arms an exponential backoff
        # before the next attempt; on_bg_error(exc|None) reports the
        # outcome (Node turns it into the alarm) — the callback may
        # run ON the compaction thread, so it must only store
        self._compact_failures = 0
        self._compact_backoff_until = 0.0
        self.on_bg_error = None
        self._dummy_fan = None    # sharded publish_step filler fan
        # learned active-set boost: an overflow-storm batch (many
        # topics exceeding active_k) doubles the effective K (bounded)
        # instead of host-matching that workload forever — one extra
        # compile per growth step, exact fallback in the meantime;
        # _d_boost is the same mechanism for the mesh gather's
        # per-topic delivery slots
        self._k_boost = 0
        self._d_boost = 0
        # device stat accumulators (sharded publish_step psums),
        # drained asynchronously by the stats flush — appending the
        # jax scalars defers the host transfer to drain time
        self._dev_stats: deque = deque(maxlen=65536)
        # publish match cache (ops/match_cache.py), lazily built on
        # first device match. _cache_rev is the GLOBAL epoch guard:
        # bumped on rebuild (ids recycle), host-regime reclaim, and
        # any mutation whose invalidation scope can't be narrowed —
        # cached rows are only served while their insert-time
        # (epoch, rev[, partition_rev], boosts) key matches exactly.
        # _part_revs scopes literal-rooted filter mutations to the
        # one partition owning that first level (docs/MATCH_CACHE.md
        # "Partitioned epochs"); sized at construction, bumped under
        # _lock, snapshotted (tuple copy) by probes BEFORE the
        # automaton snapshot so a racing mutation can only make
        # entries look stale, never fresh
        P = self.config.cache_partitions
        if P < 1 or (P & (P - 1)):
            raise ValueError(
                f"cache_partitions must be a power of two >= 1, "
                f"got {P}")
        if self.config.delta_max_filters < 1:
            raise ValueError(
                f"delta_max_filters must be >= 1, "
                f"got {self.config.delta_max_filters}")
        self._cache_rev = 0
        self._part_revs: List[int] = [0] * P
        # epoch-bump accounting (cache.match.bump.* counters): how
        # much of the invalidation traffic was scoped vs global — the
        # churn-diagnosis split (a hit-rate collapse with bump.global
        # racing means root-wildcard churn; with bump.partition it
        # means literal churn colliding into hot partitions)
        self._bump_global = 0
        self._bump_partition = 0
        self._bump_drained = (0, 0)
        self._match_cache_obj = None
        self._sharded_cache_obj = None
        self._sharded_cache_meta = None  # (T, m, d) the table is sized for
        # publish-path telemetry (telemetry.Telemetry), wired by Node
        # alongside broker.telemetry. When enabled, the cache-split
        # dispatch leaves its per-batch probe/merge timing + hit/miss
        # split in _last_dispatch for the broker's span to consume
        # (PublishSpan.stamp_match pops it) — None otherwise, and the
        # dispatch path pays nothing
        self.telemetry = None
        self._last_dispatch: Optional[dict] = None
        # online delta automaton (ops/delta.py, docs/DELTA.md): the
        # side structures holding route mutations the main tables
        # haven't absorbed yet. Lazily created on the first delta-mode
        # mutation against a live automaton; None = empty. _pub2 is
        # the atomically-published (main snapshot, delta snapshot,
        # delta version, k_boost) pair matchers read in ONE reference
        # (reading main and delta separately could double- or
        # zero-count a filter across a compaction swap). _freeze is
        # the trie defer-log active while an off-lock flatten reads
        # the (frozen) trie; _rebuild_inflight gates inline rebuilds
        # away from the flatten window.
        self._delta = None
        self._delta_ver = 0
        self._pub2: Optional[tuple] = None
        self._freeze: Optional[dict] = None
        self._rebuild_inflight = False
        # device-loss recovery (devloss.py, docs/ROBUSTNESS.md
        # "Device-loss recovery"): while True every match routes
        # through the host trie — the published device snapshots
        # reference a dead backend's HBM and must not be touched.
        # Set by suspend_device() at lost-backend classification,
        # cleared when rebuild_device_state() publishes fresh tables
        self._device_suspended = False
        # automaton.delta.* / automaton.rebuild.* counters, drained by
        # the stats flush (drain_automaton_stats)
        self._delta_probes = 0
        self._delta_filters = 0
        self._delta_merges = 0
        self._rebuild_stall_ms = 0.0
        self._auto_drained = (0, 0, 0, 0, 0, 0)

    # -- engine dispatch (native C++ or pure Python) ----------------------

    @property
    def _delta_active(self) -> bool:
        """Delta mode in effect: configured on and single-chip (the
        mesh keeps per-shard patch-in-place — its collective step has
        no two-probe seam). Read per call so :meth:`set_delta` can
        flip it at runtime (bench A/B on one router)."""
        return self.config.delta and self.config.mesh is None

    def _intern_fn(self):
        """The engine's word-intern callable (the delta's side
        structures must share the main word-id space — both walks
        consume the same encoded batch)."""
        if self._native is not None:
            return self._native.intern
        return self._table.intern

    def _ensure_delta(self):
        if self._delta is None:
            from emqx_tpu.ops.delta import DeltaAutomaton

            self._delta = DeltaAutomaton(self._intern_fn(),
                                         self.config.use_device)
        return self._delta

    def _t_insert(self, filter_: str, fid: int) -> None:
        with self._wt_lock:  # interning mutates the word table
            if self._native is not None:
                self._native.insert(filter_, fid)
            else:
                self._trie.insert(filter_)
                # pre-intern literal words so the flatten (which may
                # run on the compaction thread concurrently with
                # encode reads) never mutates the word table
                for w in T.words(filter_):
                    if w not in (T.PLUS, T.HASH):
                        self._table.intern(w)

    def _t_delete(self, filter_: str) -> None:
        if self._native is not None:
            self._native.delete(filter_)
        else:
            self._trie.delete(filter_)

    # -- freeze protocol (off-lock compaction, docs/DELTA.md) -------------
    #
    # While a background flatten reads the persistent trie OFF-lock,
    # the trie must not be mutated (the flatten is read-only, so
    # concurrent host matches stay safe — concurrent inserts would
    # not). Route ops landing in that window defer into _freeze: the
    # ordered log replays into the trie at swap time, and the small
    # side trie/set compensate host matches meanwhile. Word interning
    # still happens immediately (the word table is not the trie — the
    # flatten never reads it on the native engine, and on the Python
    # engine all its words are pre-interned), so concurrently encoded
    # batches resolve the new vocabulary.

    def _t_insert_route(self, filter_: str, fid: int) -> None:
        fz = self._freeze
        if fz is None:
            self._t_insert(filter_, fid)
            return
        fz["log"].append(("+", filter_, fid))
        fz["adds"].insert(filter_)
        fz["add_fids"][filter_] = fid
        fz["dels"].discard(filter_)
        with self._wt_lock:
            intern = self._intern_fn()
            for w in T.words(filter_):
                if w not in (T.PLUS, T.HASH):
                    intern(w)

    def _t_delete_route(self, filter_: str, fid: int) -> None:
        fz = self._freeze
        if fz is None:
            self._t_delete(filter_)
            return
        fz["log"].append(("-", filter_, fid))
        if filter_ in fz["add_fids"]:
            fz["adds"].delete(filter_)
            del fz["add_fids"][filter_]
        else:
            fz["dels"].add(filter_)

    def _unfreeze_locked(self) -> None:
        """Replay the deferred trie mutations in order and lift the
        freeze (call under the lock, after the off-lock flatten is
        done with the trie)."""
        fz = self._freeze
        if fz is None:
            return
        self._freeze = None
        self._rebuild_inflight = False
        for op, f, fid in fz["log"]:
            if op == "+":
                self._t_insert(f, fid)
            else:
                self._t_delete(f)

    def _t_match(self, topic: str) -> List[str]:
        """Host-side exact match (fallback path); call under lock."""
        if self._native is not None:
            out = []
            for fid in self._native.match(topic):
                f = self._id_to_filter[fid] \
                    if fid < len(self._id_to_filter) else None
                if f is not None:
                    out.append(f)
            return out
        return self._trie.match(topic)

    def _host_match_locked(self, topic: str) -> List[str]:
        """:meth:`_t_match` plus the freeze-window compensation: while
        an off-lock flatten holds the trie frozen, deferred adds come
        from the freeze side-trie and deferred deletes are subtracted
        (the native engine's are already dropped by the id map's
        ``None`` translation). Exact at every instant."""
        out = self._t_match(topic)
        fz = self._freeze
        if fz is not None:
            if self._native is None and fz["dels"]:
                out = [f for f in out if f not in fz["dels"]]
            if fz["add_fids"]:
                seen = set(out)
                out = out + [f for f in fz["adds"].match(topic)
                             if f not in seen]
        return out

    def _encode(self, topics: Sequence[str], max_levels: int):
        if self._native is not None:
            return self._native.encode_batch(topics, max_levels)
        return encode_batch(self._table, topics, max_levels)

    # -- route table mutation (emqx_router:do_add_route/do_delete_route) --

    def _assign_id(self, filter_: str) -> int:
        fid = self._filter_ids.get(filter_)
        if fid is None:
            if self._free_ids:
                fid = self._free_ids.pop()
                self._id_to_filter[fid] = filter_
            else:
                fid = len(self._id_to_filter)
                self._id_to_filter.append(filter_)
            self._filter_ids[filter_] = fid
        return fid

    def _bump_cache_rev(self, filter_: Optional[str] = None) -> None:
        """Invalidate cached match rows a mutation can affect (call
        under the lock). ``filter_=None`` — or any filter whose
        invalidation scope can't be narrowed (root wildcard, malformed
        share prefix), or legacy ``cache_partitions = 1`` — bumps the
        global revision; a literal-rooted filter bumps only its
        partition(s)."""
        if filter_ is not None and self.config.cache_partitions > 1:
            parts = filter_partitions(filter_,
                                      self.config.cache_partitions)
            if parts is not None:
                for p in parts:
                    self._part_revs[p] += 1
                self._bump_partition += 1
                return
        self._cache_rev += 1
        self._bump_global += 1

    def add_route(self, filter_: str, dest: object = None) -> int:
        """Add a route; returns the filter's dense id."""
        dest = self.node if dest is None else dest
        with self._lock:
            dests = self._routes.get(filter_)
            fid = self._assign_id(filter_)
            if dests is None:
                dests = {}
                self._routes[filter_] = dests
                self._t_insert_route(filter_, fid)
                if self._delta_active and self._auto is not None \
                        and not self._dirty:
                    # delta mode: the main tables stay pristine — the
                    # add lands in the side-automaton probed alongside
                    # the main walk (docs/DELTA.md)
                    self._delta_add_locked(filter_, fid)
                else:
                    self._patch_insert(filter_, fid)
                # bump AFTER the insert interned its words: a batch
                # encoded concurrently (encode takes _wt_lock only)
                # then reads the OLD revision and looks stale at
                # dispatch — re-encoded, safe. Bumping first would
                # let it carry the new revision over a pre-intern
                # word table: accepted stale, silent match miss
                self._mut_rev += 1
                # the new filter may change cached topics' match sets
                # — invalidate its partition (literal root) or the
                # whole epoch (root wildcard); see ops/match_cache.py
                self._bump_cache_rev(filter_)
            dests[dest] = dests.get(dest, 0) + 1
            return fid

    def _delta_add_locked(self, filter_: str, fid: int) -> None:
        d = self._ensure_delta()
        with self._wt_lock:  # side-patcher insert interns new words
            d.add(filter_, fid)
        self._map_set(fid, filter_)
        self._delta_ver += 1
        self._delta_filters += 1
        if d.n_pending >= self.config.delta_max_filters:
            self._maybe_compact_locked()

    def _delta_delete_locked(self, filter_: str, fid: int) -> None:
        d = self._ensure_delta()
        with self._wt_lock:  # retracting a pending add walks words
            d.delete(filter_, fid)
        self._map_set(fid, None)
        self._delta_ver += 1
        if d.needs_compaction(self.config.delta_max_filters,
                              len(self._filter_ids)):
            self._maybe_compact_locked()

    def _maybe_compact_locked(self) -> None:
        if not self._compacting and not self._dirty \
                and self._needs_compaction_locked():
            self._schedule_compaction()

    def _patcher_for(self, filter_: str) -> Optional[AutoPatcher]:
        """The patcher owning ``filter_`` (per-shard on a mesh, the
        single mirror otherwise); None = no live patcher."""
        if self.config.mesh is not None:
            if not self._shard_patchers:
                return None
            from emqx_tpu.parallel.sharded import shard_of

            return self._shard_patchers[
                shard_of(filter_, len(self._shard_patchers))]
        return self._patcher

    def _shard_live_estimate(self) -> int:
        """Per-shard live-filter estimate (compaction thresholds on a
        mesh must compare a shard's tombstones against ITS share of
        the filter set, not the global count)."""
        n = len(self._shard_patchers)
        return len(self._filter_ids) // n if n else len(self._filter_ids)

    def _patch_insert(self, filter_: str, fid: int) -> None:
        """O(depth) patch of the live automaton; falls back to a full
        rebuild flag on capacity overflow (call under the lock)."""
        # a '+' edge revokes the k=1 fast path BEFORE the patch can
        # reach any matcher (same lock; lock-free readers see the
        # patch only after a locked sync, which follows this write)
        if not self._walk_meta["has_plus"] and T.PLUS in T.words(filter_):
            self._walk_meta["has_plus"] = True
        p = None if self._dirty else self._patcher_for(filter_)
        if p is None:
            self._dirty = True
            return
        try:
            with self._wt_lock:  # patcher.insert interns new words
                p.insert(filter_, fid)
            self._map_set(fid, filter_)
            self._patches += 1
            self._drain_if_backlogged()
        except PatchOverflow as e:
            # the patcher may hold a dangling partial insert now
            # (broken flag set); _dirty forces a re-flatten before
            # any apply, so the partial queue is discarded
            self._grow[e.kind] = 2
            self._dirty = True

    def _patch_delete(self, filter_: str, fid: int) -> None:
        p = None if self._dirty else self._patcher_for(filter_)
        if p is None:
            self._dirty = True
            return
        with self._wt_lock:  # delete's word walk may intern
            p.delete(filter_)
        self._map_set(fid, None)
        self._patches += 1
        self._drain_if_backlogged()
        live = (self._shard_live_estimate()
                if self.config.mesh is not None
                else len(self._filter_ids))
        if p.needs_compaction(live):
            # tombstones dominate. The tombstoned automaton is still
            # CORRECT (just wasteful), so compaction runs on a
            # background thread and swaps atomically — matchers never
            # stall on it (only capacity overflows rebuild inline)
            self._schedule_compaction()

    def _drain_if_backlogged(self) -> None:
        """Apply queued device patches once the backlog reaches the
        drain batch — on the MUTATOR's thread, under the lock it
        already holds. The published snapshot stays hot for lock-free
        matchers; a matcher that does hit the dirty branch drains at
        most one chunk. Skipped when no automaton is live (_dirty)."""
        if self._dirty or self._auto is None:
            return
        q = 0
        if self._patcher is not None:
            q = self._patcher.queued
        elif self._shard_patchers:
            q = max(p.queued for p in self._shard_patchers)
        if q >= self.config.patch_drain_batch:
            self._apply_patches_locked()

    def _map_set(self, fid: int, filter_: Optional[str]) -> None:
        while fid >= len(self._auto_map):
            self._auto_map.append(None)
        self._auto_map[fid] = filter_

    def delete_route(self, filter_: str, dest: object = None) -> None:
        dest = self.node if dest is None else dest
        with self._lock:
            dests = self._routes.get(filter_)
            if dests is None or dest not in dests:
                return
            dests[dest] -= 1
            if dests[dest] <= 0:
                del dests[dest]
            if not dests:
                # no revision bump: the word table is append-only, so
                # removing a filter can never invalidate an existing
                # encoding — bumping here would spuriously stale every
                # in-flight pre-placed batch under unsubscribe churn
                del self._routes[filter_]
                self._drop_filter_locked(filter_)

    def _drop_filter_locked(self, filter_: str) -> None:
        """The last route for ``filter_`` went away: tombstone it out
        of the matcher (delta tombstone mask or patch-in-place,
        depending on mode) and retire its id. Call under the lock,
        AFTER removing it from ``_routes``."""
        self._t_delete_route(filter_, self._filter_ids[filter_])
        fid = self._filter_ids.pop(filter_)
        self._id_to_filter[fid] = None
        self._retire_id(fid)
        if self._delta_active and self._auto is not None \
                and not self._dirty:
            self._delta_delete_locked(filter_, fid)
        else:
            self._patch_delete(filter_, fid)
        # cached rows may hold this fid — but only rows whose
        # topic the filter matched, all inside its partition
        self._bump_cache_rev(filter_)

    def _retire_id(self, fid: int) -> None:
        """Freed filter id → quarantine or immediate recycle.

        Quarantine exists because published device snapshots hold the
        id→filter map; the id may only recycle after the next flatten
        replaces them. In the HOST regime no automaton was ever
        built, so nothing references the id — recycle now. (Round-4
        soak: below the device threshold nothing ever rebuilds, and
        pending_free grew by ~200K ids/minute of subscribe churn,
        a linear leak.)"""
        if self._auto is None:
            self._free_ids.append(fid)
        else:
            self._pending_free.append(fid)

    def has_route(self, filter_: str) -> bool:
        return filter_ in self._routes

    def has_dest(self, filter_: str, dest: object) -> bool:
        return dest in self._routes.get(filter_, ())

    def ensure_route(self, filter_: str, dest: object) -> None:
        """Idempotent add — one logical route per (filter, dest), used
        by replication (Mnesia-bag semantics, no refcount)."""
        with self._lock:
            if not self.has_dest(filter_, dest):
                self.add_route(filter_, dest=dest)

    def drop_route(self, filter_: str, dest: object) -> None:
        """Remove a (filter, dest) route regardless of refcount."""
        with self._lock:
            dests = self._routes.get(filter_)
            if dests is not None and dest in dests:
                dests[dest] = 1
                self.delete_route(filter_, dest=dest)

    def topics(self) -> List[str]:
        return list(self._routes)

    def has_routes(self) -> bool:
        """O(1) emptiness probe for the publish hot path."""
        return bool(self._routes)

    def lookup_routes(self, filter_: str) -> List[Route]:
        dests = self._routes.get(filter_, {})
        return [Route(filter_, d) for d in dests]

    # -- durability seams (wal.py / durability.py) ------------------------

    def route_refs(self, filter_: str, dest: object) -> int:
        """Current refcount for ``(filter, dest)`` — the absolute
        value the journal records after every route mutation, so a
        doubly-replayed record is idempotent (docs/DURABILITY.md)."""
        with self._lock:
            return self._routes.get(filter_, {}).get(dest, 0)

    def route_table(self) -> Dict[str, Dict[object, int]]:
        """Consistent copy of the full (filter → dest → refs) table
        (recovery's orphan-ref pruning pass reads it)."""
        with self._lock:
            return {f: dict(d) for f, d in self._routes.items()}

    def set_route_refs(self, filter_: str, dest: object,
                       refs: int) -> None:
        """Drive ``(filter, dest)`` to an absolute refcount — journal
        replay's idempotent apply (the lock is reentrant; add/delete
        below keep every automaton/delta/cache side effect)."""
        with self._lock:
            cur = self._routes.get(filter_, {}).get(dest, 0)
            for _ in range(refs - cur):
                self.add_route(filter_, dest=dest)
            for _ in range(cur - refs):
                self.delete_route(filter_, dest=dest)

    def filter_id(self, filter_: str) -> Optional[int]:
        return self._filter_ids.get(filter_)

    def cleanup_routes(self, node: object) -> None:
        """Purge all routes pointing at a dead node
        (emqx_router_helper.erl:173-177)."""
        with self._lock:
            for f in [f for f, d in self._routes.items() if node in d]:
                dests = self._routes[f]
                del dests[node]
                if not dests:
                    del self._routes[f]
                    self._drop_filter_locked(f)

    def stats(self) -> Dict[str, int]:
        return {
            "routes.count": sum(len(d) for d in self._routes.values()),
            "topics.count": len(self._routes),
            "rebuilds": self._rebuilds,
            "patches": self._patches,
        }

    # -- automaton lifecycle ---------------------------------------------

    def rebuild(self) -> Automaton:
        """Flatten the trie to a fresh automaton (double-buffered: the
        previous one stays live for concurrent matchers until swap).
        While an off-lock compaction flatten is in flight the trie is
        frozen — that compaction IS the rebuild, so return the live
        automaton instead of racing it."""
        with self._lock:
            if self._freeze is not None:
                return self._auto
            return self._rebuild_locked()

    def _rebuild_locked(self):
        import time as _time

        from emqx_tpu.profiling import timer as _ktimer

        t0 = _time.perf_counter()
        try:
            if self.config.mesh is not None:
                return self._rebuild_sharded_locked()
            return self._rebuild_single_locked()
        finally:
            _ktimer.record("automaton.rebuild",
                           (_time.perf_counter() - t0) * 1000.0)

    def _rebuild_single_locked(self) -> Automaton:
        prev = self._auto
        cap_s2 = nb = None
        if prev is not None and prev.node2 is not None:
            # honor the growth factors a PatchOverflow requested, so
            # near-full generations don't re-overflow immediately
            # (what must stay shape-stable are the WALK tables — the
            # CSR flatten arrays never reach the device)
            cap_s2 = prev.node2.shape[0] * self._grow["state"]
            nb = prev.wt.shape[0] * self._grow["edge"]
        if self._native is not None:
            host_auto = self._native.flatten(
                v2_state_capacity=cap_s2, n_buckets=nb)
            intern = self._native.intern
        else:
            host_auto = build_automaton(
                self._trie, self._filter_ids, self._table,
                v2_state_capacity=cap_s2, v2_n_buckets=nb)
            intern = self._table.intern
        self._install_walk_meta(host_auto)
        auto = device_view(host_auto)
        if self.config.use_device:
            auto = jax.device_put(auto)
        if self._delta_active:
            # delta mode keeps no main-table mirror (the mirror copies
            # the full walk tables — dead weight when nothing patches
            # them); the trie had every mutation applied, so any
            # pending delta is folded by this flatten
            self._patcher = None
            self._delta = None
            self._delta_ver += 1
        else:
            # the mirror copies host arrays (no device→host readback)
            self._patcher = AutoPatcher(host_auto, intern)
        self._auto = auto
        self._auto_map = list(self._id_to_filter)  # NEW object: old
        # snapshots freeze, so quarantined ids may recycle now
        self._free_ids.extend(self._pending_free)
        self._pending_free.clear()
        self._dirty = False
        self._grow = {"state": 1, "edge": 1}
        self._rebuilds += 1
        self._bump_cache_rev()  # fresh id map: quarantined ids recycle
        self._published = (auto, self._auto_map, self._rebuilds,
                           self._cache_rev)
        self._publish_pair_locked()
        return auto

    def _rebuild_sharded_locked(self):
        """Flatten the filter set into per-shard automatons stacked
        over the mesh's trie axis (parallel/sharded.py), and seed one
        :class:`AutoPatcher` per shard so subsequent route churn
        patches only the affected shard's row — O(delta) on the mesh,
        same as single-chip (the shard assignment is a stable filter
        hash, so a mutation never reshuffles other shards)."""
        from emqx_tpu.parallel.sharded import (
            ShardedFanout, build_sharded, place_sharded, shard_filters)

        mesh = self.config.mesh
        n_trie = mesh.shape["trie"]
        caps = self._sharded_caps
        grow_s = caps["state"] * self._grow["state"] \
            if caps["state"] else None
        grow_nb = caps["nb"] * self._grow["edge"] if caps["nb"] else None
        if self._native is not None:
            # C++ per-shard tries flatten straight into the stacked
            # device layout (VERDICT r3 item 8: the mesh rebuild was
            # the last Python-builder path)
            host_auto, parts = self._native.flatten_sharded(
                state_capacity=grow_s, n_buckets=grow_nb)
            intern = self._native.intern
        else:
            shards = shard_filters(sorted(self._routes), n_trie)
            host_auto, parts = build_sharded(
                shards, self._filter_ids, self._table,
                state_capacity=grow_s, n_buckets=grow_nb,
                return_parts=True)
            intern = self._table.intern
        caps["state"] = parts[0].node2.shape[0]
        caps["nb"] = parts[0].wt.shape[0]
        self._install_walk_meta(parts[0], parts=parts)
        auto = place_sharded(mesh, host_auto) \
            if self.config.use_device else host_auto
        self._shard_patchers = [AutoPatcher(p, intern) for p in parts]
        if self._dummy_fan is None:
            # publish_step's fan input when the caller only matches
            # (with_fanout=False): minimal, never read
            self._dummy_fan = place_sharded(mesh, ShardedFanout(
                row_ptr=np.zeros((n_trie, 2), np.int32),
                sub_ids=np.full((n_trie, 1), -1, np.int32),
                row_pairs=np.zeros((n_trie, 1, 2), np.int32)))
        self._auto = auto
        self._auto_map = list(self._id_to_filter)
        self._free_ids.extend(self._pending_free)
        self._pending_free.clear()
        self._patcher = None
        self._dirty = False
        self._grow = {"state": 1, "edge": 1}
        self._rebuilds += 1
        self._bump_cache_rev()  # fresh id map: quarantined ids recycle
        self._published = (auto, self._auto_map, self._rebuilds,
                           self._cache_rev)
        self._publish_pair_locked()
        return auto

    def _install_walk_meta(self, host_auto: Automaton,
                           parts=None) -> None:
        """Record the live tables' static walk parameters (call under
        the lock, at rebuild/restore time). ``parts`` = per-shard host
        automatons on a mesh."""
        pool = parts if parts is not None else [host_auto]
        has_plus = any(
            bool((np.asarray(p.node2)[:max(p.v2_states, 1), 0] >= 0)
                 .any()) for p in pool)
        self._walk_meta = {
            "slots": int(host_auto.wt_slots),
            "take": int(host_auto.wt_take),
            "hops": np.array(host_auto.hops_for_level),
            "has_plus": has_plus,
        }
        chains = fused = 0
        if int(host_auto.wt_take) > 1:
            from emqx_tpu.ops.csr import WIDE_SLOT
            for p in pool:
                wt = np.asarray(p.wt).reshape(-1, WIDE_SLOT)
                takes = wt[wt[:, 0] >= 0, 2]
                chains += int((takes > 1).sum())
                fused += int((takes - 1).sum())
        hops = self._walk_meta["hops"]
        levels = len(hops)
        deepest = int(hops[-1]) if levels else 0
        self._compaction = {
            "mode": "wide" if int(host_auto.wt_take) > 1 else "narrow",
            "chains": chains,
            "fused_edges": fused,
            "ratio": (1000 * (levels - deepest)) // levels
            if levels else 0,
        }

    def _steps_for(self, lb: int) -> int:
        """Scan-step bound for a batch sliced to ``lb`` levels — read
        from the live patchers (they grow the bound when a patch
        deepens a walk path) or the rebuild-time snapshot."""
        if self._shard_patchers:
            return max(
                int(p.hops_for_level[min(lb, len(p.hops_for_level) - 1)])
                for p in self._shard_patchers)
        p = self._patcher
        hl = (p.hops_for_level if p is not None
              else self._walk_meta["hops"])
        if hl is None:
            return lb + 1
        return int(hl[min(lb, len(hl) - 1)])

    def _walk_kw(self, lb: int) -> dict:
        """Static kernel kwargs for the live tables at batch depth
        ``lb``."""
        self._seen_levels.add(int(lb))  # GIL-atomic; rewarm reads it
        m = self._walk_meta
        return {"steps": self._steps_for(lb), "slots": m["slots"],
                "take": m["take"]}

    def observed_levels(self) -> List[int]:
        """Level-bucket shapes live dispatches have used (each is one
        jit compile family) — the devloss rewarm's level axis."""
        return sorted(self._seen_levels)

    def _patchers_dirty(self) -> bool:
        """Any live patcher holding queued device updates?"""
        if self._patcher is not None and self._patcher.dirty:
            return True
        return any(p.dirty for p in self._shard_patchers)

    def _needs_compaction_locked(self) -> bool:
        if self._delta_active and self._delta is not None \
                and self._auto is not None:
            return self._delta.needs_compaction(
                self.config.delta_max_filters, len(self._filter_ids))
        if self._patcher is not None:
            return self._patcher.needs_compaction(len(self._filter_ids))
        if self._shard_patchers:
            per = self._shard_live_estimate()
            return any(p.needs_compaction(per)
                       for p in self._shard_patchers)
        return False

    def _apply_patches_locked(self) -> None:
        """Drain every dirty patcher's update queue into a fresh
        device automaton and publish it (call under the lock). On a
        mesh each dirty shard scatters into its own row of the
        stacked automaton."""
        if self._patcher is not None:
            self._auto = self._patcher.apply_updates(self._auto)
        else:
            from emqx_tpu.ops.patch import apply_stacked_multi

            dirty = [(t, p) for t, p in enumerate(self._shard_patchers)
                     if p.dirty]
            if dirty:
                self._auto = apply_stacked_multi(dirty, self._auto)
        self._published = (self._auto, self._auto_map,
                           self._rebuilds, self._cache_rev)

    def _schedule_compaction(self) -> None:
        if self._compacting:
            return
        if self._compact_failures \
                and time.monotonic() < self._compact_backoff_until:
            # a recent compaction crashed: hold the retry until the
            # backoff elapses (route ops keep landing in the delta /
            # patch queue meanwhile — correctness never depends on
            # the flatten, only memory/latency headroom does)
            return
        self._compacting = True
        offlock = self._delta_active

        def _bg():
            try:
                if offlock:
                    # delta mode: flatten OFF-lock with the freeze
                    # protocol — route ops and matchers never wait on
                    # the multi-second build (docs/DELTA.md)
                    self._compact_offlock()
                else:
                    with self._lock:
                        # a sync rebuild may have beaten us to it
                        # (fresh patcher, tombstones gone): re-check,
                        # don't re-flatten for nothing
                        if not self._dirty \
                                and self._needs_compaction_locked():
                            # drain queued patches FIRST: with the
                            # queue clean, matchers arriving during
                            # the long flatten stay on the lock-free
                            # fast path (patcher.dirty would send
                            # them to the locked branch — stalling
                            # the whole match plane for the flatten)
                            if self._patchers_dirty():
                                self._apply_patches_locked()
                            self._rebuild_locked()
                self._compact_failures = 0
                cb = self.on_bg_error
                if cb is not None:
                    cb(None)
            except Exception as e:
                # the compaction thread must not die silently (the
                # BEAM restarts its crashed workers; here the crash
                # arms a backoff-retry and surfaces through the
                # router_compaction_failed alarm). The freeze paths
                # already unfroze on their own error handling.
                log.exception("background compaction crashed")
                self._compact_failures += 1
                self._compact_backoff_until = time.monotonic() + min(
                    2.0 ** self._compact_failures, 60.0)
                cb = self.on_bg_error
                if cb is not None:
                    cb(e)
            finally:
                self._compacting = False

        threading.Thread(target=_bg, daemon=True,
                         name="router-compaction").start()

    def retry_compaction(self) -> None:
        """Re-attempt a crashed background compaction once its
        backoff elapsed (overload monitor tick) — without this, a
        traffic lull after the crash would leave the rebuild pending
        until the next route op."""
        if not self._compact_failures or self._compacting \
                or time.monotonic() < self._compact_backoff_until:
            return
        with self._lock:
            need = self._auto is not None \
                and self._needs_compaction_locked()
        if need:
            self._schedule_compaction()

    def _flatten_main(self, cap_s2, nb):
        """Flatten the persistent trie into a fresh host automaton —
        the ONLY long step of a compaction, and (under the freeze
        protocol) the only one that runs off-lock. Split out so tests
        can interpose a slow build."""
        if faults.enabled:
            faults.fire("compaction.flatten")
        if self._native is not None:
            return self._native.flatten(
                v2_state_capacity=cap_s2, n_buckets=nb)
        return build_automaton(
            self._trie, self._filter_ids, self._table,
            v2_state_capacity=cap_s2, v2_n_buckets=nb)

    def _compact_offlock(self) -> None:
        """Delta-mode background compaction: freeze the trie + mark
        the delta log under a SHORT lock, flatten OFF-lock (the
        multi-second step at scale — concurrent route ops defer into
        the freeze log and the next delta generation, concurrent
        matchers keep the published (main, delta) pair), then swap +
        replay under another short lock. The lock is held for
        milliseconds total — `automaton.rebuild.stall_ms` counts
        exactly that."""
        import time as _time

        from emqx_tpu.profiling import timer as _ktimer

        t_begin = _time.perf_counter()
        with self._lock:
            t0 = _time.perf_counter()
            if self._dirty or self._auto is None \
                    or not self._delta_active \
                    or not self._needs_compaction_locked():
                return
            self._freeze = {"log": [], "adds": TrieOracle(),
                            "add_fids": {}, "dels": set()}
            self._rebuild_inflight = True
            mark = self._delta.mark() if self._delta is not None else 0
            n_pend = len(self._pending_free)
            prev = self._auto
            cap_s2 = nb = None
            if prev is not None and prev.node2 is not None:
                cap_s2 = prev.node2.shape[0] * self._grow["state"]
                nb = prev.wt.shape[0] * self._grow["edge"]
            stall = _time.perf_counter() - t0
        try:
            t_fl = _time.perf_counter()
            host_auto = self._flatten_main(cap_s2, nb)
            auto = device_view(host_auto)
            if self.config.use_device:
                auto = jax.device_put(auto)
            _ktimer.record("automaton.rebuild",
                           (_time.perf_counter() - t_fl) * 1000.0)
        except BaseException:
            with self._lock:
                self._unfreeze_locked()
            raise
        with self._lock:
            t1 = _time.perf_counter()
            self._install_walk_meta(host_auto)
            self._auto = auto
            self._patcher = None  # delta mode: no main-table mirror
            self._auto_map = list(self._id_to_filter)
            # recycle ONLY ids quarantined before the freeze: an id
            # freed DURING the flatten may still be emitted by the
            # new tables (its path was in the snapshot) — it waits a
            # generation
            self._free_ids.extend(self._pending_free[:n_pend])
            del self._pending_free[:n_pend]
            self._dirty = False
            self._grow = {"state": 1, "edge": 1}
            self._rebuilds += 1
            self._bump_cache_rev()
            self._published = (auto, self._auto_map, self._rebuilds,
                               self._cache_rev)
            # fold: log entries before the mark are in the new tables;
            # the rest replay into a fresh delta generation
            if self._delta is not None:
                self._delta = self._delta.split_after(mark)
            self._delta_ver += 1
            self._delta_merges += 1
            self._unfreeze_locked()
            self._publish_pair_locked()
            stall += _time.perf_counter() - t1
        self._rebuild_stall_ms += stall * 1000.0
        tel = self.telemetry
        if tel is not None and tel.enabled:
            tel.observe_stage(
                "rebuild", (_time.perf_counter() - t_begin) * 1000.0)

    def automaton(self) -> tuple:
        """(automaton, id→filter snapshot, epoch) — a consistent
        triple. The epoch (rebuild counter) keys derived device state
        (fan-out tables) to this snapshot's id space.

        Fast path is lock-free: one reference read of the published
        snapshot. The lock is taken only to re-flatten (automaton
        dirty — capacity overflow or first build) or to drain queued
        O(delta) patches into a new buffer generation. The dirty check
        always precedes the patch drain: a broken patcher (partial
        insert after overflow) is discarded by the rebuild before its
        queue could ever reach the device."""
        return self.snapshot_cached()[:3]

    def snapshot_cached(self) -> tuple:
        """:meth:`automaton` plus the snapshot's cache revision —
        ``(automaton, id→filter map, epoch, cache_rev)``. The rev is
        stamped into the published tuple AT publish time (under the
        lock), so it names exactly the mutation set the snapshot
        includes: the match cache keys entries on it, and a mutation
        concurrent with a probe can only make entries look stale
        (re-walked, safe) — never serve pre-mutation rows as
        fresh."""
        pub = self._published
        if pub is not None and not self._dirty \
                and not self._patchers_dirty():
            return pub
        with self._lock:
            return self._sync_locked()

    def _sync_locked(self) -> tuple:
        """Bring the published snapshot current (call under the
        lock). Dirty check FIRST — that ordering is the invariant
        that discards a broken patcher's partial queue via the
        rebuild before it could ever be applied. A frozen trie
        (off-lock compaction flatten in flight) defers the rebuild to
        that compaction's swap — the published pair stays exact
        meanwhile (delta mode never dirties a live automaton)."""
        if self._dirty or self._auto is None:
            if self._freeze is None:
                self._rebuild_locked()
        elif self._patchers_dirty():
            self._apply_patches_locked()
        return self._published

    # -- published (main, delta) pair (delta mode, docs/DELTA.md) ---------

    def _publish_pair_locked(self) -> None:
        """Re-publish the (main snapshot, delta snapshot, version,
        k_boost) tuple matchers read in one reference. Call under the
        lock after any main swap or (lazily, from the match path)
        after delta mutations."""
        if not self._delta_active:
            self._pub2 = None
            return
        main = self._published
        if main is not None and main[3] != self._cache_rev:
            # re-stamp the published snapshot's cache revision: in
            # delta mode a mutation never dirties the main tables, so
            # the 4-tuple would otherwise keep its flatten-time rev
            # forever and globally-bumped cache entries (root
            # wildcards, partitions=1) would probe as FRESH — a stale
            # serve. The pair published below includes the delta, so
            # the current rev names exactly what matchers see.
            main = (main[0], main[1], main[2], self._cache_rev)
            self._published = main
        d = self._delta
        snap = None
        if d is not None and (d.n_pending or d.tombs):
            k_cap = max(self.config.active_k, self._k_boost)
            with self._wt_lock:  # a deferred-build flatten may intern
                snap = d.snapshot(len(self._id_to_filter), k_cap)
        self._pub2 = (main, snap, self._delta_ver,
                      self._k_boost)

    def _snapshot_pair(self):
        """Consistent ``((auto, id_map, epoch, rev), delta_snap)``
        for the two-probe match path. Fast path is one reference
        read; the lock is taken only to refresh a stale delta
        snapshot (small apply/flatten — milliseconds) or to build the
        first automaton."""
        pair = self._pub2
        if pair is not None and not self._dirty \
                and pair[0] is self._published \
                and pair[2] == self._delta_ver \
                and pair[3] == self._k_boost:
            return pair[0], pair[1]
        with self._lock:
            self._sync_locked()
            self._publish_pair_locked()
            pair = self._pub2
            return pair[0], pair[1]

    # -- matching (emqx_router:match_routes/1) ----------------------------

    def match_routes(self, topic: str) -> List[Route]:
        """All routes whose filter matches ``topic``."""
        [filters] = self.match_filters([topic])
        out: List[Route] = []
        for f in filters:
            out.extend(self.lookup_routes(f))
        return out

    def host_match(self, topic: str) -> List[str]:
        """Host-side exact match (the oracle fallback path)."""
        with self._lock:
            return self._host_match_locked(topic)

    def use_device_now(self) -> bool:
        """The host/device matching policy for the product publish
        path: the device automaton pays fixed dispatch + transfer
        latency per call, so it only wins past a filter-count
        threshold (below it the C++ trie walk is microseconds — the
        reference's regime, where ETS reads are always 'host'). A
        configured mesh is an explicit opt-in to sharded device
        matching, so it bypasses the threshold (the dryrun exercises
        tiny shapes); ``use_device=False`` wins over everything (the
        debugging escape hatch)."""
        cfg = self.config
        if not cfg.use_device or not self._routes:
            return False
        if self._device_suspended:
            # lost backend: every published device snapshot points at
            # dead HBM — host trie until the rebuild publishes fresh
            # tables (devloss.py)
            return False
        if cfg.mesh is not None:
            return True
        return len(self._filter_ids) >= cfg.device_min_filters

    def reclaim_host_regime(self) -> None:
        """Called by the publish path when it chose the HOST regime:
        if a previously published automaton's id quarantine has grown
        past ``host_reclaim_pending``, drop the automaton (the next
        device use re-flattens from scratch) and drain the ids.

        The size bound is hysteresis: a filter count oscillating
        around ``device_min_filters`` must not pay a full re-flatten
        per crossing — a stale automaton pins at most the bound
        (~28B/id) until churn actually accumulates. Without any
        reclaim, a broker that crossed the threshold once and fell
        back would pin ``_pending_free`` forever (the round-4 leak's
        second head). In-flight matchers are safe: they hold their
        own (auto, map) snapshot references, and recycling only
        mutates the live list."""
        if self._auto is None or \
                len(self._pending_free) <= self.config.host_reclaim_pending:
            return
        with self._lock:
            if self._auto is None or len(self._pending_free) <= \
                    self.config.host_reclaim_pending:
                return
            if self._freeze is not None:
                # an off-lock compaction flatten is mid-flight; its
                # swap will recycle the quarantine anyway
                return
            self._auto = None
            self._published = None
            self._patcher = None
            self._shard_patchers = []
            # the delta's pending adds/deletes are all in the trie
            # (mutations apply immediately outside a freeze), so the
            # next flatten re-derives them — drop the side structures
            self._delta = None
            self._delta_ver += 1
            self._pub2 = None
            self._dirty = True  # next device use must re-flatten
            self._free_ids.extend(self._pending_free)
            self._pending_free.clear()
            self._bump_cache_rev()  # drained ids may recycle

    # -- device-loss recovery (devloss.py, docs/ROBUSTNESS.md) ------------

    def suspend_device(self) -> None:
        """Lost-backend classification, step 0: route every match
        through the host trie until :meth:`rebuild_device_state`
        publishes fresh tables. One attribute write — matchers that
        would have gathered from dead HBM buffers (publish dispatch,
        retained replay, ``match_routes``) take the exact host path
        instead."""
        self._device_suspended = True
        log.error("device matching suspended: backend lost — host "
                  "trie serves until the rebuild publishes")

    def device_suspended(self) -> bool:
        return self._device_suspended

    def match_filters_host(self, topics: Sequence[str]) -> List[List[str]]:
        """Host-only batch match — the breaker's exact oracle
        fallback. Unlike :meth:`match_filters` this NEVER consults
        the device, whatever ``use_device_now()`` says: an open or
        rebuilding breaker means the device plane is suspect, and
        the fallback must not re-execute against it."""
        if not topics:
            return []
        with self._lock:
            return [self._host_match_locked(t) for t in topics]

    def _quarantine_locked(self) -> None:
        """Drop every published reference to the dead backend's HBM
        state (call under the lock, device already suspended): the
        published (main, delta) snapshots, the match caches (their
        table gathers would read dead buffers — cold start), the
        mesh filler fan, the delta's staged device view. The
        host-authoritative structures — persistent trie, route
        table, word table, filter-id assignment — are untouched:
        they are exactly what the rebuild reads."""
        self._published = None
        self._pub2 = None
        self._match_cache_obj = None
        self._sharded_cache_obj = None
        self._sharded_cache_meta = None
        self._dummy_fan = None
        if self._delta is not None:
            self._delta.invalidate_device()
        self._bump_cache_rev()

    def rebuild_device_state(self) -> dict:
        """Device-loss recovery (devloss.DeviceRecovery): quarantine
        the dead published snapshot and rebuild ALL device-resident
        state from the host-authoritative structures — the
        persistent trie re-flattens to fresh tables placed straight
        into HBM (the ``checkpoint.load`` path), the delta
        side-automaton and tombstone mask re-stage against the new
        id map, and the match cache cold-starts under a global epoch
        bump so no stale cached row can ever serve.

        Delta mode reuses the PR 7 off-lock freeze protocol: the
        flatten runs OFF the router lock, so route ops arriving
        mid-rebuild complete in ms (deferred into the freeze log +
        the next delta generation) and host matches stay exact
        throughout. Non-delta and mesh configurations rebuild under
        the lock — route ops stall for the flatten (documented
        degrade, docs/ROBUSTNESS.md; the mesh rebuild is best-effort
        per-shard via the stacked flatten).

        Raises when the fresh placement fails (backend still dead,
        or died again mid-rebuild) — the recovery loop retries with
        backoff. On success the device suspension lifts and the
        published snapshot serves again."""
        import time as _time

        # claim the compaction slot: a background flatten may be
        # mid-flight against the dead device — wait it out (its own
        # error handling arms the compaction backoff)
        deadline = _time.monotonic() + 120.0
        while True:
            with self._lock:
                if not self._compacting and self._freeze is None:
                    self._compacting = True
                    break
            if _time.monotonic() > deadline:
                raise RuntimeError(
                    "device-state rebuild: background compaction "
                    "would not yield")
            _time.sleep(0.01)
        t0 = _time.perf_counter()
        try:
            if faults.enabled:
                faults.fire("device.lost")
            with self._lock:
                offlock = (self._delta_active
                           and self._auto is not None
                           and not self._dirty)
            if offlock:
                self._rebuild_devloss_offlock()
            else:
                with self._lock:
                    self._quarantine_locked()
                    self._dirty = True
                    self._rebuild_locked()
                    self._device_suspended = False
        finally:
            self._compacting = False
        return {"rebuild_s": _time.perf_counter() - t0,
                "epoch": self._rebuilds,
                "filters": len(self._filter_ids)}

    def _rebuild_devloss_offlock(self) -> None:
        """The delta-mode rebuild body: freeze + quarantine under a
        short lock, flatten off-lock, place fresh tables, swap +
        replay under another short lock — :meth:`_compact_offlock`'s
        protocol with the quarantine folded into the freeze window
        (route ops landing mid-rebuild go to the freeze log AND the
        live delta, so the swap's ``split_after`` re-stages them
        against the fresh id map exactly as a compaction would)."""
        with self._lock:
            self._quarantine_locked()
            self._freeze = {"log": [], "adds": TrieOracle(),
                            "add_fids": {}, "dels": set()}
            self._rebuild_inflight = True
            mark = self._delta.mark() if self._delta is not None else 0
            n_pend = len(self._pending_free)
            prev = self._auto
            cap_s2 = nb = None
            if prev is not None and prev.node2 is not None:
                cap_s2 = prev.node2.shape[0] * self._grow["state"]
                nb = prev.wt.shape[0] * self._grow["edge"]
        try:
            host_auto = self._flatten_main(cap_s2, nb)
            if faults.enabled:
                faults.fire("device.lost")
            auto = device_view(host_auto)
            if self.config.use_device:
                # straight to HBM — the checkpoint.load restore path
                auto = jax.device_put(auto)
        except BaseException:
            with self._lock:
                self._unfreeze_locked()
            raise
        with self._lock:
            self._install_walk_meta(host_auto)
            self._auto = auto
            self._patcher = None  # delta mode: no main-table mirror
            self._auto_map = list(self._id_to_filter)
            # recycle ONLY ids quarantined before the freeze (the
            # compaction rule: an id freed mid-flatten waits a
            # generation)
            self._free_ids.extend(self._pending_free[:n_pend])
            del self._pending_free[:n_pend]
            self._dirty = False
            self._grow = {"state": 1, "edge": 1}
            self._rebuilds += 1
            self._bump_cache_rev()
            self._published = (auto, self._auto_map, self._rebuilds,
                               self._cache_rev)
            if self._delta is not None:
                self._delta = self._delta.split_after(mark)
            self._delta_ver += 1
            self._unfreeze_locked()
            self._publish_pair_locked()
            self._device_suspended = False

    def match_dispatch(self, topics: Sequence[str]):
        """Dispatch-only device match: encode + enqueue the compiled
        walk and return WITHOUT any device→host sync.

        Returns ``(ids_dev, ovf_dev, id_map, epoch)`` — both arrays
        are in-flight device values ([B_pad, M] / [B_pad]); feed
        ``ids_dev`` straight into the fan-out/pack kernels and fetch
        everything in one coalesced transfer later
        (:meth:`Broker.publish_fetch`). ``(id_map, epoch)`` is the
        automaton snapshot giving the ids meaning. On a mesh the
        match runs the sharded ICI publish step ([B_pad, T·m] ids).
        """
        cfg = self.config
        if cfg.mesh is not None:
            return self._match_dispatch_sharded(topics)
        cache = self._match_cache()
        if cache is not None:
            return self._match_dispatch_cached(topics, cache)
        dsnap = None
        if self._delta_active:
            main, dsnap = self._snapshot_pair()
            auto, id_map, epoch = main[:3]
        else:
            auto, id_map, epoch = self.automaton()
        bucket = cfg.min_batch
        while bucket < len(topics):
            bucket *= 2
        padded = list(topics) + ["\x00/pad"] * (bucket - len(topics))
        # the word table must not be read (wt_lookup) while a
        # concurrent add_route interns into it — ctypes calls drop
        # the GIL, so the map can rehash mid-read. The fine-grained
        # _wt_lock (not _lock) keeps matchers running through a long
        # background-compaction flatten
        with self._wt_lock:
            ids, n, sysm = self._encode(padded, cfg.max_levels)
        ids, n = depth_bucket(ids, n)
        res = match_batch_auto(auto, ids, n, sysm,
                               k=self.effective_k(),
                               m=cfg.max_matches, pack_ids=False,
                               **self._walk_kw(ids.shape[1]))
        out_ids, out_ovf = res.ids, res.overflow
        if dsnap is not None:
            # two-probe: union the side-automaton's raw emits +
            # tombstone-mask deleted fids (ops/delta.py)
            from emqx_tpu.ops.delta import probe_raw

            self._delta_probes += 1
            out_ids, out_ovf = probe_raw(dsnap, ids, n, sysm,
                                         out_ids, out_ovf,
                                         m=cfg.max_matches)
        return out_ids, out_ovf, id_map, epoch

    # -- publish match cache (ops/match_cache.py) -------------------------

    def _match_cache(self):
        """The single-chip publish match cache, lazily built (None =
        disabled by config)."""
        cfg = self.config
        if not cfg.match_cache or cfg.match_cache_slots <= 0:
            return None
        if self._match_cache_obj is None:
            from emqx_tpu.ops.match_cache import MatchCache

            self._match_cache_obj = MatchCache(
                cfg.match_cache_slots, cfg.max_matches)
        return self._match_cache_obj

    def _match_dispatch_cached(self, topics: Sequence[str], cache):
        """Cache-split device match: probe the epoch-guarded cache,
        walk ONLY the misses (``pack_ids=True`` — the per-topic
        compaction buys fixed-width rows the cache and merge reuse),
        merge one combined ``[B_pad, max_matches]`` id array and
        insert the fresh rows. Same contract as the plain dispatch:
        all device values in flight, no sync.

        Ordering: the revision is read BEFORE the automaton snapshot,
        so a racing mutation can only make fresh results look stale
        (re-walked, safe) — never stale results look fresh."""
        cfg = self.config
        k_boost = self._k_boost  # read BEFORE the snapshot/walk: a
        # concurrent boost then stales these entries, never the reverse
        # partition revisions: same read-before-snapshot rule (a
        # mutation landing after this copy makes the probed keys look
        # stale — re-walked, safe). Tuple copy = a consistent host
        # snapshot the per-topic keys index into
        part_snap = (tuple(self._part_revs)
                     if cfg.cache_partitions > 1 else None)
        dsnap = None
        if self._delta_active:
            main, dsnap = self._snapshot_pair()
            auto, id_map, epoch, rev = main
        else:
            auto, id_map, epoch, rev = self.snapshot_cached()
        key = (epoch, rev, k_boost)
        keys = None
        if part_snap is not None:
            mask = cfg.cache_partitions - 1
            keys = [key + (part_snap[zlib.crc32(
                t.partition("/")[0].encode()) & mask],)
                for t in topics]
        bucket = cfg.min_batch
        while bucket < len(topics):
            bucket *= 2
        tel = self.telemetry
        timed = tel is not None and tel.enabled
        t0 = time.perf_counter() if timed else 0.0
        probe = cache.probe(topics, key, keys)
        t1 = time.perf_counter() if timed else 0.0
        miss_rows = miss_ovf = None
        if probe.miss_topics:
            mb = cfg.min_batch
            while mb < len(probe.miss_topics):
                mb *= 2
            padded = list(probe.miss_topics) + \
                ["\x00/pad"] * (mb - len(probe.miss_topics))
            with self._wt_lock:
                ids, n, sysm = self._encode(padded, cfg.max_levels)
            ids, n = depth_bucket(ids, n)
            res = match_batch_auto(auto, ids, n, sysm,
                                   k=self.effective_k(),
                                   m=cfg.max_matches, pack_ids=True,
                                   **self._walk_kw(ids.shape[1]))
            miss_rows, miss_ovf = res.ids, res.overflow
            if dsnap is not None:
                # two-probe: fold the side-automaton + tombstone mask
                # into the rows the cache stores — a later delta
                # mutation bumps the partition/global revision, so
                # these merged rows can never be served stale
                from emqx_tpu.ops.delta import probe_packed

                self._delta_probes += 1
                miss_rows, miss_ovf = probe_packed(
                    dsnap, ids, n, sysm, miss_rows, miss_ovf,
                    m=cfg.max_matches)
            cache.insert(probe, miss_rows, miss_ovf)
        t2 = time.perf_counter() if timed else 0.0
        ids_dev, ovf_dev, _movf = cache.merge(bucket, probe,
                                              miss_rows, miss_ovf)
        if timed:
            # probe (host hash walk) + merge (HBM-gather dispatch) =
            # the cache_gather share of this dispatch; the remainder
            # (encode + miss walk) is the match share
            self._last_dispatch = {
                "hit": len(probe.hit_pos),
                "miss": len(probe.miss_topics),
                "cache_gather_ms": ((t1 - t0) + (
                    time.perf_counter() - t2)) * 1000.0,
            }
        return ids_dev, ovf_dev, id_map, epoch

    def drain_cache_stats(self) -> Dict[str, int]:
        """Match-cache counter deltas since the last drain (hit/miss/
        insert/stale, summed over the single-chip and sharded caches)
        plus the router-level epoch-bump split (``bump.global`` /
        ``bump.partition``) — folded into Metrics by the stats flush
        under the ``cache.match.`` prefix."""
        out: Dict[str, int] = {}
        for c in (self._match_cache_obj, self._sharded_cache_obj):
            if c is None:
                continue
            for k2, v in c.drain_stats().items():
                out[k2] = out.get(k2, 0) + v
        cfg = self.config
        if cfg.match_cache and cfg.match_cache_slots > 0:
            g, p = self._bump_global, self._bump_partition
            out["bump.global"] = g - self._bump_drained[0]
            out["bump.partition"] = p - self._bump_drained[1]
            self._bump_drained = (g, p)
        return out

    def cache_bump_totals(self) -> Dict[str, int]:
        """Cumulative epoch-bump split (not deltas — `ctl cache` and
        bench introspection; the metrics fold uses
        :meth:`drain_cache_stats`)."""
        return {"global": self._bump_global,
                "partition": self._bump_partition}

    def cache_entries(self) -> int:
        """Live entries across the publish match caches (gauge)."""
        return sum(c.entries() for c in
                   (self._match_cache_obj, self._sharded_cache_obj)
                   if c is not None)

    def cache_partitions_live(self) -> int:
        """Partition epoch keys in effect for the publish match cache
        (the ``match.cache.partition.live`` gauge): 0 = cache
        disabled, 1 = legacy whole-epoch, else ``cache_partitions``."""
        cfg = self.config
        if not cfg.match_cache or cfg.match_cache_slots <= 0:
            return 0
        return cfg.cache_partitions

    def quarantined_ids(self) -> int:
        """Freed filter ids quarantined until the next flatten (the
        ``router.ids.quarantined`` gauge — the round-4 soak leak's
        visibility: between flattens this is the linear-growth
        regime, and sustained growth without a rebuild means churn
        is outpacing compaction)."""
        return len(self._pending_free)

    def effective_k(self) -> int:
        """Active-set capacity: configured + any learned boost — or 1
        when the live automaton has no ``+`` edges at all (the walk
        is then a deterministic trie descent: the active set is
        provably ≤ 1 lane, and gather volume scales with k)."""
        if not self._walk_meta["has_plus"]:
            return max(1, self._k_boost)
        return max(self.config.active_k, self._k_boost)

    def boost_k(self, cap: int = 64) -> bool:
        """Double the effective active-set capacity (≤ ``cap``);
        called by the publish path when a batch's overflow rate shows
        the configured K undersizes the live workload. Returns
        whether a grow happened."""
        with self._lock:
            k = self.effective_k()
            if k >= cap:
                return False
            self._k_boost = min(k * 2, cap)
            return True

    def effective_d(self) -> int:
        """Configured per-topic fan-out slots plus any learned boost
        (mesh publish step; learned like K, from fan-only overflow)."""
        return max(self.config.fanout_d, self._d_boost)

    def boost_d(self, cap: int = 1024) -> bool:
        """Double the mesh gather's per-topic delivery slots (≤
        ``cap``) when a batch's FAN-ONLY overflow rate shows ``d``
        undersizes the live fan-out (one recompile per growth step,
        exact host fallback in the meantime — same contract as
        :meth:`boost_k`)."""
        with self._lock:
            d = self.effective_d()
            if d >= cap:
                return False
            self._d_boost = min(d * 2, cap)
            return True

    def note_match_fallbacks(self, n: int) -> None:
        """The publish path resolved ``n`` topics on the host oracle
        because their device walk overflowed. In the stale-hop regime
        (a patch split deepened walk paths past what the mirror's hop
        accounting tracks, ADVICE r5) those fallbacks are the only
        signal the automaton needs a compacting rebuild — forward the
        count to the live patcher(s), which count it alongside
        splits/tombstones, and schedule compaction once it dominates.
        Keeps hot deep topics eligible for the match cache instead of
        pinned to the host oracle until 1024 splits accumulate."""
        if n <= 0:
            return
        with self._lock:
            pool = ([self._patcher] if self._patcher is not None
                    else self._shard_patchers)
            for p in pool:
                p.note_hop_fallbacks(n)
            if pool and not self._dirty and not self._compacting \
                    and self._needs_compaction_locked():
                self._schedule_compaction()

    def set_delta(self, enabled: bool) -> None:
        """Flip delta mode at runtime with a clean transition (bench
        A/B on one router/filter set): wait out any in-flight
        background compaction, then one synchronous rebuild folds
        whatever the outgoing mode had pending (the trie always has
        everything) and re-publishes under the new mode."""
        while self._compacting:
            time.sleep(0.005)
        with self._lock:
            self.config.delta = bool(enabled)
            if self._auto is not None and self._freeze is None:
                self._rebuild_locked()
            else:
                self._publish_pair_locked()

    def drain_automaton_stats(self) -> Dict[str, int]:
        """Delta/rebuild counter deltas since the last drain — folded
        into Metrics by the stats flush under the ``automaton.``
        prefix (docs/OBSERVABILITY.md)."""
        comp = self._compaction
        cur = (self._delta_probes, self._delta_filters,
               self._delta_merges, int(self._rebuild_stall_ms),
               comp["fused_edges"], comp["chains"])
        prev = self._auto_drained
        self._auto_drained = cur
        return {
            "delta.probes": cur[0] - prev[0],
            "delta.filters": cur[1] - prev[1],
            "delta.merges": cur[2] - prev[2],
            "rebuild.stall_ms": cur[3] - prev[3],
            # table-state gauges carried as deltas (GAUGE_METRICS —
            # a rebuild may shrink them)
            "compaction.fused_edges": cur[4] - prev[4],
            "compaction.chains": cur[5] - prev[5],
        }

    def walk_info(self) -> Dict[str, object]:
        """Live walk-kernel facts for `ctl cache` / bench: the variant
        dispatch would pick right now (pallas | lax) and the level-
        compression snapshot of the live tables (mode, fused chains,
        permille of deepest-walk steps saved)."""
        return {"variant": walk_variant(), **self._compaction}

    def delta_info(self) -> Dict[str, object]:
        """Live delta-automaton state for `ctl cache` / bench
        introspection (cumulative counters, not deltas)."""
        d = self._delta
        return {
            "active": self._delta_active,
            "pending": d.n_pending if d is not None else 0,
            "tombstones": d.n_tombstones if d is not None else 0,
            "probes": self._delta_probes,
            "filters": self._delta_filters,
            "merges": self._delta_merges,
            "rebuild_stall_ms": round(self._rebuild_stall_ms, 3),
            "rebuild_inflight": self._rebuild_inflight,
        }

    def match_ids(self, topics: Sequence[str]):
        """Device match of a topic batch in snapshot-id space.

        Returns ``(ids_dev, ids_np, ovf_np, id_map, epoch)``:
        ``ids_dev`` is the device int32[B_pad, M] match array (feed it
        straight into the fan-out gather — no host round-trip),
        ``ids_np``/``ovf_np`` are host copies sliced to ``len(topics)``,
        and ``(id_map, epoch)`` is the automaton snapshot that gives
        the ids meaning. Rows with ``ovf_np`` set exceeded a kernel
        bound — resolve those topics via :meth:`host_match`.
        """
        if self.config.mesh is not None:
            return self._match_ids_sharded(topics)
        B = len(topics)
        ids_dev, ovf_dev, id_map, epoch = self.match_dispatch(topics)
        ids_np = np.asarray(ids_dev)[:B]
        ovf_np = np.asarray(ovf_dev)[:B]
        return ids_dev, ids_np, ovf_np, id_map, epoch

    def _match_dispatch_sharded(self, topics: Sequence[str]):
        """Multi-chip match dispatch: the batch is sharded over the
        mesh's 'data' axis, each trie shard matches its slice, match
        ids are all-gathered over ICI; no device→host sync (same
        contract as :meth:`match_dispatch`, ids are [B_pad, T·m])."""
        all_ids, _subs, _src, ovf, _movf, id_map, epoch = \
            self._dispatch_sharded(topics, fan=None)
        return all_ids, ovf, id_map, epoch

    def publish_dispatch_sharded(self, topics: Sequence[str],
                                 fan_provider, placed=None):
        """The PRODUCT multi-chip publish dispatch: match AND fan-out
        in one collective step (``parallel.sharded.publish_step`` with
        real per-shard fan tables, ``with_fanout=True``).

        ``fan_provider(epoch, id_map) -> ShardedFanoutState | None``
        supplies fan tables (CSR + big-filter bitmaps) consistent
        with the automaton snapshot (the broker's FanoutManager).
        ``placed`` (from :meth:`encode_place_sharded`) skips the host
        encode + host→device transfer — a pipelined caller overlaps
        that host half with in-flight device steps instead of paying
        a synchronous transfer per call.
        Returns ``(ids_dev [B_pad, T·m], subs_dev [B_pad, T·d],
        src_dev [B_pad, T·d], bm [(union, has_big, bovf) | None],
        ovf_dev [B_pad], movf_dev [B_pad], id_map, epoch, big_fids)``
        — ``movf_dev`` is the match-only overflow (the ``boost_k``
        signal; fan overflow must not grow k); no device→host sync.
        Reference: the dispatch fold src/emqx_broker.erl:283-309 run
        as one compiled mesh program.

        With the publish match cache enabled (and no big-filter
        bitmaps live), repeat topics skip the collective step: the
        cached (ids, subs, src) rows gather from HBM and only the
        misses walk. A pre-``placed`` batch bypasses the cache (its
        host half was already paid, and splitting it would re-encode)."""
        if placed is None and topics is not None:
            out = self._sharded_dispatch_cached(topics, fan_provider)
            if out is not None:
                return out
        return self._dispatch_sharded(topics, fan=fan_provider,
                                      with_big=True, placed=placed)

    def _sharded_cache_for(self, n_trie: int, d: int):
        """The mesh publish cache, sized for the CURRENT (T, m, d)
        row widths — a ``boost_d`` regrows it (entries drop; they
        were keyed to the old d anyway)."""
        from emqx_tpu.ops.match_cache import MatchCache

        cfg = self.config
        meta = (n_trie, cfg.max_matches, d)
        if self._sharded_cache_obj is None \
                or self._sharded_cache_meta != meta:
            width = n_trie * cfg.max_matches + 2 * n_trie * d
            self._sharded_cache_obj = MatchCache(
                cfg.match_cache_slots, width)
            self._sharded_cache_meta = meta
        return self._sharded_cache_obj

    def _sharded_dispatch_cached(self, topics: Sequence[str],
                                 fan_provider):
        """Cache-split mesh publish dispatch, or None when the cache
        does not apply (disabled, no fan state, or big-filter bitmaps
        live — a bitmap union row is megabytes at 10M subs, far past
        any sane per-entry budget, so that regime stays uncached).

        One cache entry is a topic's concatenated (match ids [T·m],
        gathered subs [T·d], src [T·d]) rows — everything the
        collective step produces for it except the per-step stats
        psums (device.match counters therefore count WALKED topics
        only; the host-side hit counters carry the rest)."""
        import jax.numpy as jnp

        cfg = self.config
        if not cfg.match_cache or cfg.match_cache_slots <= 0:
            return None
        boosts = (self._k_boost, self._d_boost)
        # partition revisions snapshot BEFORE the automaton snapshot
        # (same stale-not-fresh ordering as the single-chip path)
        part_snap = (tuple(self._part_revs)
                     if cfg.cache_partitions > 1 else None)
        auto, id_map, epoch, rev = self.snapshot_cached()
        st = fan_provider(epoch, id_map)
        if st is None or st.fan is None or st.bm is not None \
                or st.big_fids:
            return None
        d = self.effective_d()
        n_trie = cfg.mesh.shape["trie"]
        cache = self._sharded_cache_for(n_trie, d)
        key = (epoch, rev, boosts, st.version)
        keys = None
        if part_snap is not None:
            mask = cfg.cache_partitions - 1
            keys = [key + (part_snap[zlib.crc32(
                t.partition("/")[0].encode()) & mask],)
                for t in topics]
        unit = cfg.min_batch * cfg.mesh.shape["data"]
        bucket = unit
        while bucket < len(topics):
            bucket *= 2
        tel = self.telemetry
        timed = tel is not None and tel.enabled
        t0 = time.perf_counter() if timed else 0.0
        probe = cache.probe(topics, key, keys)
        t1 = time.perf_counter() if timed else 0.0
        miss_rows = miss_ovf = miss_movf = None
        if probe.miss_topics:
            (m_ids, m_subs, m_src, m_bm, m_ovf, m_movf, m_map,
             m_epoch, m_big) = self._dispatch_sharded(
                probe.miss_topics, fan=lambda e, im: st,
                with_big=True)
            if m_bm is not None or m_big or m_subs is None \
                    or m_epoch != epoch:
                # the snapshot moved (or big filters appeared) while
                # we split: abandon the cached path for this batch —
                # the pending miss slots stay keyless (permanent
                # miss), and the caller re-runs the legacy dispatch
                return None
            miss_rows = jnp.concatenate([m_ids, m_subs, m_src], axis=1)
            miss_ovf, miss_movf = m_ovf, m_movf
            cache.insert(probe, miss_rows, miss_ovf, miss_movf)
        t2 = time.perf_counter() if timed else 0.0
        merged, ovf, movf = cache.merge(bucket, probe, miss_rows,
                                        miss_ovf, miss_movf)
        mw = n_trie * cfg.max_matches
        dw = n_trie * d
        ids = merged[:, :mw]
        subs = merged[:, mw:mw + dw]
        src = merged[:, mw + dw:]
        if timed:
            self._last_dispatch = {
                "hit": len(probe.hit_pos),
                "miss": len(probe.miss_topics),
                "cache_gather_ms": ((t1 - t0) + (
                    time.perf_counter() - t2)) * 1000.0,
            }
        return (ids, subs, src, None, ovf, movf, id_map, epoch,
                frozenset())

    def encode_place_sharded(self, topics: Sequence[str]):
        """Host half of the sharded dispatch: encode a topic batch
        (padded to a bucket that splits evenly over the data axis)
        and place it on the mesh. Returns ``(ids, n, sysm, rev)``
        where ``rev`` is the route-table mutation revision the batch
        was encoded at — :meth:`publish_dispatch_sharded` verifies it
        and re-encodes if routes changed in between (a filter added
        after encode may intern words the stale encoding mapped to
        the unknown sentinel: its matches would silently miss)."""
        from emqx_tpu.parallel.sharded import place_batch

        cfg = self.config
        mesh = cfg.mesh
        # capture BEFORE encoding: a mutation racing the encode makes
        # the batch look stale (re-encoded at dispatch) — never the
        # reverse
        rev = self._mut_rev
        B = len(topics)
        unit = cfg.min_batch * mesh.shape["data"]
        bucket = unit  # bucket must split evenly over the data axis
        while bucket < B:
            bucket *= 2
        padded = list(topics) + ["\x00/pad"] * (bucket - B)
        with self._wt_lock:
            ids, n, sysm = self._encode(padded, cfg.max_levels)
        return (*place_batch(mesh, ids, n, sysm), rev)

    def _dispatch_sharded(self, topics: Sequence[str], fan=None,
                          with_big: bool = False, placed=None):
        from emqx_tpu.parallel.sharded import publish_step

        cfg = self.config
        mesh = cfg.mesh
        auto, id_map, epoch = self.automaton()
        big_fids = frozenset()
        fan_tables = None
        bmt = None
        if fan is not None:
            st = fan(epoch, id_map)
            if st is not None:
                fan_tables = st.fan
                bmt = st.bm
                big_fids = st.big_fids
        if placed is not None:
            ids, n, sysm, rev = placed
            if rev != self._mut_rev:
                # routes changed since the batch was encoded — its
                # word ids may predate newly interned vocabulary.
                # Re-encode (correct, costs the transfer the caller
                # tried to hide); requires the original topics
                if topics is None:
                    raise ValueError(
                        "stale placed batch (routes changed since "
                        "encode) and no topics to re-encode from")
                ids, n, sysm, _ = self.encode_place_sharded(topics)
        else:
            ids, n, sysm, _ = self.encode_place_sharded(topics)
        use_fan = fan_tables is not None
        all_ids, subs, src, bm, ovf, movf, stats = publish_step(
            mesh, auto, fan_tables if use_fan else self._dummy_fan,
            ids, n, sysm, bmt, k=self.effective_k(), m=cfg.max_matches,
            d=self.effective_d() if use_fan else 8,
            mb=cfg.fanout_mb, with_fanout=use_fan,
            **self._walk_kw(int(ids.shape[-1])))
        self._dev_stats.append(stats)
        if with_big:
            return (all_ids, subs if use_fan else None,
                    src if use_fan else None, bm, ovf, movf, id_map,
                    epoch, big_fids)
        return all_ids, subs, src, ovf, movf, id_map, epoch

    def _match_ids_sharded(self, topics: Sequence[str]):
        """Sharded :meth:`match_ids` (host copies synced)."""
        B = len(topics)
        all_ids, ovf, id_map, epoch = self._match_dispatch_sharded(topics)
        ids_np = np.asarray(all_ids)[:B]
        ovf_np = np.asarray(ovf)[:B]
        return all_ids, ids_np, ovf_np, id_map, epoch

    def drain_device_stats(self) -> Dict[str, int]:
        """Sum and clear the accumulated device-side counters (one
        host transfer per pending step — called from the periodic
        stats flush, not the publish path)."""
        out = {"matches": 0, "deliveries": 0, "overflows": 0}
        while self._dev_stats:
            st = self._dev_stats.popleft()
            for k in out:
                out[k] += int(st[k])
        return out

    def match_filters(self, topics: Sequence[str]) -> List[List[str]]:
        """Batch: matched filter list per topic (device + oracle
        fallback)."""
        if not topics:
            return []
        if not self.use_device_now():
            with self._lock:
                return [self._host_match_locked(t) for t in topics]
        _, mid, ovf, id_map, _ = self.match_ids(topics)
        out: List[List[str]] = []
        for i in range(len(topics)):
            if ovf[i]:
                out.append(self.host_match(topics[i]))
            else:
                row = [id_map[j] for j in mid[i] if j >= 0]
                out.append([f for f in row if f is not None])
        return out
