"""MQTT-over-WebSocket transport (RFC 6455, server side).

Mirrors ``src/emqx_ws_connection.erl``: the same channel FSM and
connection loop as the TCP transport — :class:`WsConnection` subclasses
:class:`emqx_tpu.connection.Connection`, overriding only the framing
seams — with the byte stream wrapped in WebSocket binary frames and
the HTTP upgrade handshake (cowboy's role in the reference) done
inline on the accepted socket. MQTT requires the ``mqtt`` subprotocol
and binary frames; client frames MUST be masked, server frames MUST
NOT be.
"""

from __future__ import annotations

import asyncio
import base64
import hashlib
import logging
from typing import List, Optional, Tuple

from emqx_tpu.connection import Connection, Listener
from emqx_tpu.zone import Zone

log = logging.getLogger("emqx_tpu.ws_connection")

_WS_GUID = b"258EAFA5-E914-47DA-95CA-C5AB0DC85B11"

OP_CONT = 0x0
OP_TEXT = 0x1
OP_BINARY = 0x2
OP_CLOSE = 0x8
OP_PING = 0x9
OP_PONG = 0xA


def accept_key(key: str) -> str:
    """Sec-WebSocket-Accept for a client Sec-WebSocket-Key."""
    digest = hashlib.sha1(key.encode() + _WS_GUID).digest()
    return base64.b64encode(digest).decode()


def frame_header(opcode: int, n: int) -> bytes:
    """Server→client frame header for an ``n``-byte payload: FIN set,
    never masked. Split from :func:`encode_frame` so the coalesced
    egress flush can writev ``(header, payload, …)`` runs without
    concatenating (= copying) every payload into a fresh frame."""
    head = bytearray([0x80 | opcode])
    if n < 126:
        head.append(n)
    elif n < 65536:
        head.append(126)
        head += n.to_bytes(2, "big")
    else:
        head.append(127)
        head += n.to_bytes(8, "big")
    return bytes(head)


def encode_frame(opcode: int, payload: bytes) -> bytes:
    """Server→client frame: FIN set, never masked."""
    return frame_header(opcode, len(payload)) + payload


def _unmask(data: bytes, mask: bytes) -> bytes:
    """XOR-unmask as one big-int op (no per-byte Python loop)."""
    n = len(data)
    if n == 0:
        return b""
    full = (mask * (n // 4 + 1))[:n]
    return (int.from_bytes(data, "big")
            ^ int.from_bytes(full, "big")).to_bytes(n, "big")


class WsParseError(Exception):
    pass


class WsFrameParser:
    """Incremental client→server frame parser (masked frames).

    Yields ``(opcode, payload)`` per complete message; continuation
    frames are reassembled onto the initial opcode.
    """

    def __init__(self, max_size: int = 16 * 1024 * 1024) -> None:
        self.buf = bytearray()
        self.max_size = max_size
        self._frag_op: Optional[int] = None
        self._frag_data = bytearray()
        # set instead of raised mid-batch so messages parsed before a
        # malformed frame still reach the caller (a clean DISCONNECT
        # ahead of garbage must not be dropped)
        self.error: Optional[WsParseError] = None

    def feed(self, data: bytes) -> List[Tuple[int, bytes]]:
        if self.error is not None:
            raise self.error
        self.buf += data
        out: List[Tuple[int, bytes]] = []
        while True:
            try:
                frame = self._next_frame()
            except WsParseError as e:
                self.error = e
                return out
            if frame is None:
                return out
            fin, opcode, payload = frame
            if opcode in (OP_CLOSE, OP_PING, OP_PONG):
                if not fin:
                    self.error = WsParseError("fragmented control frame")
                    return out
                out.append((opcode, payload))
                continue
            if opcode == OP_CONT:
                if self._frag_op is None:
                    self.error = WsParseError("continuation without start")
                    return out
                self._frag_data += payload
            else:
                if self._frag_op is not None:
                    self.error = WsParseError("interleaved data message")
                    return out
                self._frag_op = opcode
                self._frag_data = bytearray(payload)
            if len(self._frag_data) > self.max_size:
                self.error = WsParseError("message too large")
                return out
            if fin:
                out.append((self._frag_op, bytes(self._frag_data)))
                self._frag_op = None
                self._frag_data = bytearray()

    def _next_frame(self):
        buf = self.buf
        if len(buf) < 2:
            return None
        b0, b1 = buf[0], buf[1]
        fin = bool(b0 & 0x80)
        if b0 & 0x70:
            raise WsParseError("RSV bits set")
        opcode = b0 & 0x0F
        masked = bool(b1 & 0x80)
        if not masked:
            raise WsParseError("client frame not masked")
        n = b1 & 0x7F
        if opcode >= 0x8 and n > 125:
            # RFC 6455 §5.5: control frames MUST be ≤125 bytes
            raise WsParseError("control frame too large")
        pos = 2
        if n == 126:
            if len(buf) < 4:
                return None
            n = int.from_bytes(buf[2:4], "big")
            pos = 4
        elif n == 127:
            if len(buf) < 10:
                return None
            n = int.from_bytes(buf[2:10], "big")
            pos = 10
        if n > self.max_size:
            raise WsParseError("frame too large")
        end = pos + 4 + n
        if len(buf) < end:
            return None
        mask = bytes(buf[pos:pos + 4])
        payload = _unmask(bytes(buf[pos + 4:end]), mask)
        del self.buf[:end]
        return fin, opcode, payload


async def _read_http_request(reader: asyncio.StreamReader,
                             timeout: float) -> Optional[dict]:
    """Read one HTTP/1.1 request head; returns {path, headers} or None."""
    try:
        head = await asyncio.wait_for(
            reader.readuntil(b"\r\n\r\n"), timeout)
    except (asyncio.TimeoutError, asyncio.IncompleteReadError,
            asyncio.LimitOverrunError):
        return None
    lines = head.decode("latin-1").split("\r\n")
    try:
        method, path, _version = lines[0].split(" ", 2)
    except ValueError:
        return None
    if method.upper() != "GET":
        return None
    headers = {}
    for line in lines[1:]:
        if ":" in line:
            k, v = line.split(":", 1)
            headers[k.strip().lower()] = v.strip()
    return {"path": path, "headers": headers}


class WsConnection(Connection):
    """One WebSocket client <-> one Channel (post-handshake).

    Shares the TCP connection loop; only the framing seams differ:
    outbound MQTT bytes are wrapped in binary frames, inbound bytes
    route through :class:`WsFrameParser` (with ping/pong/close
    handling) before the MQTT parser.
    """

    def __init__(self, reader: asyncio.StreamReader,
                 writer: asyncio.StreamWriter,
                 broker, cm, zone: Optional[Zone] = None,
                 listener: str = "ws:default", peername=None,
                 peer_cert_as_username=None, frame: str = "py") -> None:
        super().__init__(reader, writer, broker, cm, zone=zone,
                         listener=listener, peername=peername,
                         peer_cert_as_username=peer_cert_as_username,
                         frame=frame)
        # one WS message may batch MULTIPLE MQTT packets (MQTT 5 §6.0),
        # so the reassembly bound is a multiple of the per-packet limit
        # (which the MQTT parser itself enforces), not the limit + slack
        self.ws_parser = WsFrameParser(
            max_size=8 * self.zone.max_packet_size)
        self._sent_close = False

    def _wrap_out(self, data: bytes) -> bytes:
        return encode_frame(OP_BINARY, data)

    def _writev(self, frames) -> None:
        """Writev-coalesced egress for the WS transport: a run of
        pre-serialized MQTT frames becomes one flat
        ``(header, payload, header, payload, …)`` ``writelines`` —
        one transport write per drain (like the TCP path since PR 5)
        and zero per-frame payload copies (``encode_frame`` would
        concatenate header + payload per frame)."""
        parts: list = []
        ap = parts.append
        for data in frames:
            ap(frame_header(OP_BINARY, len(data)))
            ap(data)
        self.writer.writelines(parts)

    async def _drain_and_close(self) -> None:
        if not self._closing and not self._sent_close:
            self._sent_close = True
            try:
                self.writer.write(encode_frame(OP_CLOSE, b"\x03\xe8"))
            except Exception:
                pass
        await super()._drain_and_close()

    async def _decode(self, data: bytes):
        try:
            msgs = self.ws_parser.feed(data)
        except WsParseError as e:
            log.debug("ws error from %s: %s", self.channel.peername, e)
            return None
        if self.ws_parser.error is not None:
            # malformed frame behind valid ones: process what parsed
            # cleanly, then finish (feed() raises from here on); the
            # run loop drains responses and closes after the batch
            log.debug("ws error from %s: %s", self.channel.peername,
                      self.ws_parser.error)
            self._finish_after_batch = True
        pkts = []
        for opcode, payload in msgs:
            if opcode == OP_PING:
                self.writer.write(encode_frame(OP_PONG, payload))
                continue
            if opcode == OP_PONG:
                continue
            if opcode == OP_CLOSE:
                if not self._sent_close:
                    self._sent_close = True
                    self.writer.write(encode_frame(OP_CLOSE, payload[:2]))
                try:
                    await self.writer.drain()
                except Exception:
                    pass
                # MQTT packets decoded before the CLOSE (e.g. a clean
                # DISCONNECT in the same read) still get processed
                self._finish_after_batch = True
                return pkts
            if opcode != OP_BINARY:
                # MQTT over WS MUST use binary frames
                self._finish_after_batch = True
                return pkts
            mqtt_pkts = await super()._decode(payload)
            if mqtt_pkts is None:
                self._finish_after_batch = True
                return pkts
            pkts.extend(mqtt_pkts)
        return pkts


class WsListener(Listener):
    """WebSocket listener: HTTP upgrade → WsConnection
    (reference: cowboy router /mqtt → emqx_ws_connection).

    Shares the TCP Listener lifecycle; only the handshake differs."""

    connection_class = WsConnection

    def __init__(self, broker, cm, host: str = "127.0.0.1",
                 port: int = 8083, path: str = "/mqtt",
                 zone: Optional[Zone] = None, name: str = "ws:default",
                 max_connections: int = 1024000,
                 ssl_context=None, frame: str = "py") -> None:
        super().__init__(broker, cm, host=host, port=port, zone=zone,
                         name=name, max_connections=max_connections,
                         ssl_context=ssl_context, frame=frame)
        self.path = path

    async def _handshake(self, reader, writer) -> bool:
        req = await _read_http_request(reader, self.zone.idle_timeout)
        if req is None or not self._check_upgrade(req):
            writer.write(b"HTTP/1.1 400 Bad Request\r\n"
                         b"Connection: close\r\n\r\n")
            await writer.drain()
            writer.close()
            return False
        h = req["headers"]
        resp = (
            "HTTP/1.1 101 Switching Protocols\r\n"
            "Upgrade: websocket\r\n"
            "Connection: Upgrade\r\n"
            f"Sec-WebSocket-Accept: "
            f"{accept_key(h['sec-websocket-key'])}\r\n"
            "Sec-WebSocket-Protocol: mqtt\r\n\r\n")
        writer.write(resp.encode("latin-1"))
        await writer.drain()
        return True

    def _check_upgrade(self, req: dict) -> bool:
        h = req["headers"]
        if req["path"].split("?")[0] != self.path:
            return False
        if h.get("upgrade", "").lower() != "websocket":
            return False
        if "upgrade" not in h.get("connection", "").lower():
            return False
        if h.get("sec-websocket-version") != "13":
            return False
        if "sec-websocket-key" not in h:
            return False
        protos = [p.strip() for p in
                  h.get("sec-websocket-protocol", "").split(",")]
        return "mqtt" in protos
