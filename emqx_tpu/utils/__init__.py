"""Utility substrate (reference: emqx_guid/base62/sequence/batch/misc)."""
