"""Small control-flow helpers shared across layers.

Mirrors ``src/emqx_misc.erl``: ``pipeline/3`` (the CONNECT/PUBLISH
processing chains thread state through fallible stages) and
``run_fold/3``. The drain/OOM helpers there are BEAM-mailbox specific
and have no analogue here.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, Tuple

OK = "ok"
ERROR = "error"


def pipeline(funs: Iterable[Callable], packet: Any,
             state: Any) -> Tuple[str, Any, Any]:
    """Run stages over (packet, state); each returns one of
      - ``None`` / ``("ok",)``: keep both
      - ``("ok", new_packet)`` or ``("ok", new_packet, new_state)``
      - ``("error", reason)`` / ``("error", reason, new_state)``: halt
    Returns ``("ok", packet, state)`` or ``("error", reason, state)``
    (emqx_misc:pipeline/3)."""
    for fun in funs:
        ret = fun(packet, state)
        if ret is None or ret == (OK,):
            continue
        tag = ret[0]
        if tag == OK:
            if len(ret) == 2:
                packet = ret[1]
            else:
                packet, state = ret[1], ret[2]
        elif tag == ERROR:
            if len(ret) == 3:
                state = ret[2]
            return (ERROR, ret[1], state)
        else:
            raise ValueError(f"bad pipeline return: {ret!r}")
    return (OK, packet, state)


def run_fold(funs: Iterable[Callable], acc: Any, state: Any) -> Any:
    """Thread ``acc`` through funs(acc, state) (emqx_misc:run_fold/3)."""
    for fun in funs:
        acc = fun(acc, state)
    return acc
