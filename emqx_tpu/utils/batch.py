"""Generic size/interval-triggered batch accumulator.

Mirrors ``src/emqx_batch.erl``: items accumulate until either the
batch size cap or the linger interval fires, then the commit function
runs on the whole batch. This is the host-side ingress shape the
device matcher wants: publishes collected across connections within a
tick become one ``[B, L]`` match batch (SURVEY §2.2 process-per-conn
mapping).
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Any, Callable, List, Optional


class Batch:
    """Synchronous accumulator: ``push`` returns the batch to commit
    when the size cap is hit; ``flush`` drains unconditionally;
    ``due(now)`` says whether the linger interval expired."""

    def __init__(self, batch_size: int = 1000,
                 linger_ms: float = 10.0,
                 commit_fun: Optional[Callable[[List[Any]], Any]] = None
                 ) -> None:
        self.batch_size = batch_size
        self.linger_ms = linger_ms
        self.commit_fun = commit_fun
        self._items: List[Any] = []
        self._first_at: Optional[float] = None

    def __len__(self) -> int:
        return len(self._items)

    def push(self, item: Any):
        if not self._items:
            self._first_at = time.monotonic()
        self._items.append(item)
        if len(self._items) >= self.batch_size:
            return self.flush()
        return None

    def due(self, now: Optional[float] = None) -> bool:
        if not self._items:
            return False
        now = time.monotonic() if now is None else now
        return (now - self._first_at) * 1000.0 >= self.linger_ms

    def flush(self):
        if not self._items:
            return None
        items, self._items = self._items, []
        self._first_at = None
        if self.commit_fun is not None:
            return self.commit_fun(items)
        return items


class AsyncBatcher:
    """asyncio wrapper: background linger timer commits partial
    batches; ``push`` commits full ones inline."""

    def __init__(self, commit_fun: Callable[[List[Any]], Any],
                 batch_size: int = 1000, linger_ms: float = 10.0) -> None:
        self.batch = Batch(batch_size, linger_ms, commit_fun)
        self._task: Optional[asyncio.Task] = None

    def start(self) -> None:
        if self._task is None:
            self._task = asyncio.get_event_loop().create_task(
                self._linger_loop())

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None
        self.batch.flush()

    def push(self, item: Any):
        return self.batch.push(item)

    async def _linger_loop(self) -> None:
        interval = max(self.batch.linger_ms / 1000.0, 0.001)
        while True:
            await asyncio.sleep(interval)
            try:
                if self.batch.due():
                    self.batch.flush()
            except Exception:
                # a transient commit failure must not kill the linger
                # task — that would silently stall partial batches
                logging.getLogger("emqx_tpu.batch").exception(
                    "batch commit failed")


def dedup_topics(topics):
    """Collapse duplicate topics, first occurrence wins: returns
    ``(unique_topics, inverse_index)`` with
    ``unique_topics[inverse_index[i]] == topics[i]``. The publish
    path collapses hot topics to one device row per tick and expands
    results per message (broker.publish_begin / bench pipeline)."""
    seen = {}
    uniq = []
    inv = []
    for t in topics:
        j = seen.get(t)
        if j is None:
            j = len(uniq)
            seen[t] = j
            uniq.append(t)
        inv.append(j)
    return uniq, inv
