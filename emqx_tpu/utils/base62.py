"""Base62 encode/decode (reference: src/emqx_base62.erl) — used for
auto-assigned client ids."""

from __future__ import annotations

_ALPHABET = "0123456789ABCDEFGHIJKLMNOPQRSTUVWXYZabcdefghijklmnopqrstuvwxyz"
_INDEX = {c: i for i, c in enumerate(_ALPHABET)}


def encode(n: int) -> str:
    if n == 0:
        return "0"
    if n < 0:
        raise ValueError("negative")
    out = []
    while n:
        n, r = divmod(n, 62)
        out.append(_ALPHABET[r])
    return "".join(reversed(out))


def decode(s: str) -> int:
    n = 0
    for c in s:
        n = n * 62 + _INDEX[c]
    return n
