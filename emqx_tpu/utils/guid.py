"""128-bit time-ordered global unique message ids.

Mirrors the reference's GUID layout (src/emqx_guid.erl:1-150): 64-bit
microsecond timestamp | node/pid entropy | per-process sequence. Ids
are monotonically increasing per generator, unique across generators.
"""

from __future__ import annotations

import os
import threading
import time

_lock = threading.Lock()
_seq = 0
_node_bits = (os.getpid() & 0xFFFF) << 16 | (
    int.from_bytes(os.urandom(2), "big"))


def new_guid() -> int:
    """A 128-bit int: ts_us(64) | node+pid entropy(32) | seq(32)."""
    global _seq
    ts = int(time.time() * 1_000_000)
    with _lock:
        _seq = (_seq + 1) & 0xFFFFFFFF
        seq = _seq
    return (ts << 64) | (_node_bits << 32) | seq


def guid_timestamp(guid: int) -> float:
    """Microsecond timestamp embedded in a guid, as seconds."""
    return (guid >> 64) / 1_000_000
