"""128-bit time-ordered global unique message ids.

Mirrors the reference's GUID layout (src/emqx_guid.erl:1-150): 64-bit
microsecond timestamp | node/pid entropy | per-process sequence. Ids
are monotonically increasing per generator, unique across generators.
"""

from __future__ import annotations

import os
import threading
import time

_lock = threading.Lock()
_seq = 0
_last_ts = 0
_node_bits = (os.getpid() & 0xFFFF) << 16 | (
    int.from_bytes(os.urandom(2), "big"))


def new_guid() -> int:
    """A 128-bit int: ts_us(64) | node+pid entropy(32) | seq(32).

    Monotonic per generator: the timestamp is read and clamped UNDER
    the lock — a wall-clock step backwards holds the last timestamp
    rather than emitting a smaller id, and no interleaving can pair
    an older ts with a newer seq. This clamp deliberately STRENGTHENS
    the reference (src/emqx_guid.erl takes a fresh erlang:system_time
    per call with no last-ts guard, so its ids are only
    timestamp-ordered while the clock is): same layout and ordering
    intent, stronger guarantee under clock steps."""
    global _seq, _last_ts
    with _lock:
        ts = int(time.time() * 1_000_000)
        if ts < _last_ts:
            ts = _last_ts  # clock stepped back: hold, stay monotonic
        _seq = (_seq + 1) & 0xFFFFFFFF
        if _seq == 0:
            # seq wrapped: advance the timestamp so the (ts, seq)
            # pair can never repeat under a held clock (the reference
            # advances ts on sequence exhaustion the same way)
            ts += 1
        _last_ts = ts
        seq = _seq
    return (ts << 64) | (_node_bits << 32) | seq


def guid_timestamp(guid: int) -> float:
    """Microsecond timestamp embedded in a guid, as seconds."""
    return (guid >> 64) / 1_000_000
