"""Per-key monotonic counters.

Mirrors ``src/emqx_sequence.erl`` (nextval/reclaim over an ETS
table): the broker uses one to number a topic's subscribers so shard
assignment is stable (src/emqx_broker_helper.erl:94-100).
"""

from __future__ import annotations

from typing import Dict, Hashable


class Sequence:
    def __init__(self) -> None:
        self._vals: Dict[Hashable, int] = {}

    def nextval(self, key: Hashable) -> int:
        """Increment and return (1 on first call — the reference's
        update_counter semantics)."""
        v = self._vals.get(key, 0) + 1
        self._vals[key] = v
        return v

    def currval(self, key: Hashable) -> int:
        return self._vals.get(key, 0)

    def reclaim(self, key: Hashable) -> int:
        """Decrement; at zero the key is deleted (so an idle topic
        frees its counter)."""
        v = self._vals.get(key, 0) - 1
        if v <= 0:
            self._vals.pop(key, None)
            return 0
        self._vals[key] = v
        return v
