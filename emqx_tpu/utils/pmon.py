"""Task-monitor dictionary.

Mirrors ``src/emqx_pmon.erl`` (process-monitor refs with batch
erase): the broker monitors subscriber processes so their table
entries can be cleaned in batch when they die. Here the monitored
unit is an asyncio task (or any object with ``add_done_callback``);
the owner drains finished items and erases them in one pass — the
``demonitor/erase_all`` shape the cleanup pools rely on
(src/emqx_broker_helper.erl:134-139, src/emqx_cm.erl:396-400).
"""

from __future__ import annotations

from typing import Any, Dict, Hashable, List, Optional, Tuple

_MISSING = object()


class PMon:
    def __init__(self) -> None:
        self._items: Dict[Hashable, Any] = {}
        self._down: List[Hashable] = []

    def monitor(self, key: Hashable, val: Any = None,
                task=None) -> None:
        """Watch ``key``; if ``task`` is given its completion queues
        the key as down."""
        self._items[key] = val
        if task is not None:
            # bind the monitored generation: a stale task's callback
            # must not queue a key that was re-registered since (e.g.
            # client reconnected between old-task death and callback)
            task.add_done_callback(
                lambda _t, k=key, v=val: self._mark_down(k, v))

    def _mark_down(self, key: Hashable, val: Any = _MISSING) -> None:
        if key not in self._items:
            return
        if val is not _MISSING and self._items[key] is not val:
            return  # entry was re-registered; the down is stale
        self._down.append(key)

    def notify_down(self, key: Hashable) -> None:
        """Explicit down signal (no task attached)."""
        self._mark_down(key)

    def demonitor(self, key: Hashable) -> None:
        self._items.pop(key, None)

    def find(self, key: Hashable) -> Optional[Any]:
        return self._items.get(key)

    def __contains__(self, key: Hashable) -> bool:
        return key in self._items

    def count(self) -> int:
        return len(self._items)

    def erase_all(self) -> List[Tuple[Hashable, Any]]:
        """Drain queued downs in one batch: [(key, val)] of entries
        erased (emqx_pmon:erase_all/2)."""
        out = []
        for key in self._down:
            if key in self._items:
                out.append((key, self._items.pop(key)))
        self._down.clear()
        return out
