"""Config zones: named bundles of per-listener/per-connection settings.

Mirrors ``src/emqx_zone.erl`` + the zone sections of etc/emqx.conf:
a zone snapshot is read lock-free by every connection (here: a frozen
dataclass). Defaults follow etc/emqx.conf:698-907.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional


@dataclass
class Zone:
    name: str = "default"
    # connection
    idle_timeout: float = 15.0
    max_packet_size: int = 1024 * 1024
    max_clientid_len: int = 65535
    max_topic_levels: int = 0          # 0 = unlimited
    max_topic_alias: int = 65535
    max_qos_allowed: int = 2
    retain_available: bool = True
    wildcard_subscription: bool = True
    shared_subscription: bool = True
    server_keepalive: Optional[int] = None
    keepalive_backoff: float = 0.75
    # session
    max_subscriptions: int = 0
    upgrade_qos: bool = False
    max_inflight: int = 32
    retry_interval: float = 30.0
    max_awaiting_rel: int = 100
    await_rel_timeout: float = 300.0
    session_expiry_interval: float = 7200.0
    max_mqueue_len: int = 1000
    mqueue_priorities: Optional[Dict[str, int]] = None
    mqueue_default_priority: float = 0
    mqueue_store_qos0: bool = True
    # auth/acl
    allow_anonymous: bool = True
    acl_nomatch: str = "allow"          # allow | deny
    # what an ACL deny does to the connection: "ignore" answers with
    # the reason code, "disconnect" drops the client
    # (etc/emqx.conf:617, src/emqx_channel.erl:372,470)
    acl_deny_action: str = "ignore"     # ignore | disconnect
    enable_acl: bool = True
    # skip the client.authenticate hook chain for this zone (internal
    # listeners; src/emqx_access_control.erl:37-41)
    bypass_auth_plugins: bool = False
    # CONNECT enrichment: the username becomes the clientid
    # (src/emqx_channel.erl:1385-1389)
    use_username_as_clientid: bool = False
    # v3/v4 subscriptions get nl=1 so a client never receives its own
    # publishes (v5 clients set nl themselves;
    # src/emqx_channel.erl:1386-1390 enrich_subopts)
    ignore_loop_deliver: bool = False
    # v5 Response-Information returned when the client CONNECTs with
    # Request-Response-Information=1 (src/emqx_channel.erl:1432-1437)
    response_information: str = ""
    # Deliberately NOT knobs (the full emqx_zone accessor sweep,
    # round 4): `strict_mode` — the wire codec here validates UTF-8,
    # reserved header bits and packet ids UNCONDITIONALLY
    # (mqtt/frame.py; the reference only does so when strict_mode is
    # set, src/emqx_frame.erl:92-94,215), so a knob would only add a
    # lax mode nothing wants; `force_shutdown_policy` — per-process
    # queue/heap kill thresholds assume BEAM-style per-process heaps;
    # the analogues here are the bounded per-session mqueue
    # (max_mqueue_len), the bytes/msgs limiters above, and the
    # host-level watermark alarms (monitors.py).
    enable_ban: bool = True
    # flapping
    enable_flapping_detect: bool = False
    # stats
    enable_stats: bool = True
    mountpoint: Optional[str] = None
    # rate limits (None = unlimited): (rate/sec, burst)
    ratelimit_msg_in: Optional[tuple] = None
    ratelimit_bytes_in: Optional[tuple] = None
    quota_conn_messages: Optional[tuple] = None
    # slow-consumer guard (reference listener.*.send_timeout +
    # send_timeout_close): once the transport write buffer crosses
    # high_watermark, the peer has send_timeout seconds to drain it
    # or the connection closes (0 disables)
    send_timeout: float = 15.0
    send_timeout_close: bool = True
    high_watermark: int = 1024 * 1024
    # forced-GC trigger (count, bytes), None disables
    # (etc/emqx.conf force_gc_policy, src/emqx_gc.erl)
    force_gc_policy: Optional[tuple] = (16000, 16 * 1024 * 1024)


_zones: Dict[str, Zone] = {}


def get_zone(name: str = "default") -> Zone:
    z = _zones.get(name)
    if z is None:
        z = Zone(name=name)
        _zones[name] = z
    return z


def set_zone(zone: Zone) -> None:
    _zones[zone.name] = zone


def force_reload() -> None:
    _zones.clear()
