"""Multi-host bring-up: the jax.distributed control plane.

The reference's cluster substrate is ekka membership + gen_rpc data
plane (SURVEY §2.3). On TPU pods the equivalent split is:

  - **host control plane** — ``emqx_tpu.cluster`` (membership,
    replication, takeover) over its socket transport, exactly as on
    one host;
  - **device data plane** — a global mesh spanning every host's
    chips: ICI inside a slice, DCN between slices, with XLA inserting
    the collectives. ``jax.distributed.initialize`` is the
    coordination service that makes ``jax.devices()`` global.

This module is the thin, test-friendly seam over that bring-up: a
single-process call is a no-op (the common single-host case, and what
CI exercises), a multi-process call wires the coordinator and returns
the global mesh. The GSPMD partitioner then treats DCN like slow ICI
— the sharded publish step (parallel/sharded.py) runs unchanged, with
the ``data`` axis preferred across slices (publish batches shard
cleanly over DCN; the ``trie`` axis all-gathers match ids every step,
so it belongs inside a slice's ICI domain).
"""

from __future__ import annotations

import logging
from typing import Optional

from emqx_tpu.parallel.mesh import default_mesh, make_mesh

log = logging.getLogger("emqx_tpu.distributed")


def initialize(coordinator_address: Optional[str] = None,
               num_processes: int = 1,
               process_id: int = 0) -> bool:
    """Join the jax.distributed coordination service.

    Single-process (``num_processes == 1``) is a no-op returning
    False — local ``jax.devices()`` is already the whole world.
    Multi-process: process 0 serves as coordinator; every process
    must call this before any other JAX API touches the backend.
    """
    if num_processes <= 1:
        return False
    if coordinator_address is None:
        raise ValueError("multi-process init needs coordinator_address")
    import jax

    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id)
    log.info("joined jax.distributed: process %d/%d via %s",
             process_id, num_processes, coordinator_address)
    return True


def global_mesh(n_data: Optional[int] = None,
                n_trie: Optional[int] = None):
    """The broker mesh over every visible device (all hosts after
    :func:`initialize`). With explicit factors the product must cover
    the device count; default puts the whole DCN-crossing factor on
    ``data`` (batch sharding tolerates slow links; the trie axis
    all-gathers every step and should stay inside one slice)."""
    import jax

    devs = jax.devices()
    if n_data is None and n_trie is None:
        return default_mesh(len(devs))
    if n_data is None:
        n_data = len(devs) // int(n_trie)
    if n_trie is None:
        n_trie = len(devs) // int(n_data)
    if int(n_data) * int(n_trie) != len(devs):
        # silently dropping devices would desynchronize collectives
        # across hosts (some processes' chips outside the mesh)
        raise ValueError(
            f"mesh {n_data}x{n_trie} does not cover {len(devs)} devices")
    return make_mesh(int(n_data), int(n_trie), devices=devs)
