"""Sharded automaton + the collective publish step.

Multi-chip design (replaces the reference's replicated-Mnesia reads +
gen_rpc forwarding, SURVEY §2.3):

  - the filter set is partitioned round-robin into T *trie shards*;
    each shard is flattened into its own CSR automaton whose tables
    carry GLOBAL filter ids, padded to common capacities and stacked
    along a leading shard axis sharded over the mesh's ``trie`` axis;
  - the publish batch is sharded over the ``data`` axis and
    *replicated* over ``trie`` (every trie shard sees every topic in
    its data slice);
  - inside ``shard_map`` each chip matches its batch slice against its
    automaton shard, then match ids are all-gathered over ``trie``
    (ICI collective — the analogue of aggre/forward,
    src/emqx_broker.erl:243-281) giving every data shard its full
    route set;
  - per-batch counters are ``psum``-reduced over the whole mesh (the
    metrics fold, src/emqx_metrics.erl:230-271).

The walk is identical to the single-chip kernel — sharding composes
around :func:`emqx_tpu.ops.match.match_batch`.
"""

from __future__ import annotations

import functools
import zlib
from typing import Dict, List, NamedTuple, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from emqx_tpu.oracle import TrieOracle
from emqx_tpu.ops.csr import Automaton, build_automaton
from emqx_tpu.ops.match import match_batch
from emqx_tpu.ops.fanout import (FanoutTable, build_fanout,
                                 gather_subscribers_src)
from emqx_tpu.ops.tokenize import WordTable


def shard_map_available() -> bool:
    """Whether this JAX build carries a shard_map implementation at
    all (the mesh suites skip — not error — without one)."""
    if hasattr(jax, "shard_map"):
        return True
    try:
        from jax.experimental.shard_map import shard_map  # noqa: F401

        return True
    except Exception:
        return False


def _shard_map(fn, mesh, in_specs, out_specs):
    """Version-portable shard_map: ``jax.shard_map`` (new API,
    ``check_vma``) when present, else the ``jax.experimental`` form
    (``check_rep``). Replication checking is off either way — the
    walk's scan carries start replicated and becomes varying."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)
    from jax.experimental.shard_map import shard_map as _sm

    return _sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=False)


class ShardedAutomaton(NamedTuple):
    """T stacked walk tables; leading axis is the trie-shard axis.

    Only the fields the compiled walk reads are stacked (the CSR
    flatten artifacts stay host-side with the per-shard patchers).
    All shards share the bucket count, state capacity, slot layout
    and step bound — the shard_map program is one compiled walk."""

    wt: jax.Array        # int32[T, NB, slots*SW]
    wt_seed: jax.Array   # uint32[T, 1]
    node2: jax.Array     # int32[T, S2_cap, 4]


class ShardedFanout(NamedTuple):
    row_ptr: jax.Array  # [T, F_cap+1] — filter-id -> local sub rows
    sub_ids: jax.Array  # [T, N_cap]
    row_pairs: jax.Array | None = None  # [T, F_cap, 2] packed pairs


class ShardedBitmaps(NamedTuple):
    """Per-trie-shard subscriber bitmaps for big (> d) filters: a
    filter's bitmap row lives in ITS shard (same stable assignment as
    the automaton), so HBM for huge subscriber sets scales with the
    mesh instead of replicating (BASELINE config 5 at multi-chip)."""

    bitmaps: jax.Array  # uint32[T, R_cap, W]
    big_row: jax.Array  # int32[T, F_cap] — global fid -> local row | -1


def build_sharded_bitmaps(
    rows_per_shard: Sequence[Dict[int, Sequence[int]]],
    num_filters: int,
    n_subs: int,
    row_capacity: int | None = None,
) -> ShardedBitmaps:
    from emqx_tpu.ops.bitmap import build_bitmaps

    r_cap = max(1, max(len(r) for r in rows_per_shard))
    if row_capacity is not None:
        r_cap = max(r_cap, row_capacity)
    tables = [build_bitmaps(rows, num_filters, n_subs,
                            row_capacity=r_cap)
              for rows in rows_per_shard]
    return ShardedBitmaps(
        bitmaps=np.stack([t.bitmaps for t in tables]),
        big_row=np.stack([t.big_row for t in tables]))


def shard_of(filter_: str, n_shards: int) -> int:
    """STABLE filter→shard assignment (crc32 + avalanche finalizer,
    not Python's salted hash): a filter keeps its shard across route
    churn and across processes, so a mutation touches exactly one
    shard's automaton — the precondition for per-shard O(delta)
    patching (round-robin over the sorted set would reshuffle every
    assignment on insert). The murmur-style finalizer matters: CRC32
    is LINEAR, so near-identical filter names (``a/x`` vs ``a/+``)
    keep correlated low bits and ``crc % 2^k`` collapses structured
    name families into one shard."""
    h = zlib.crc32(filter_.encode("utf-8"))
    h ^= h >> 16
    h = (h * 0x7FEB352D) & 0xFFFFFFFF
    h ^= h >> 15
    h = (h * 0x846CA68B) & 0xFFFFFFFF
    h ^= h >> 16
    return h % n_shards


def shard_filters(filters: Sequence[str], n_shards: int) -> List[List[str]]:
    """Partition by :func:`shard_of` (uniform in expectation; stable
    under mutation)."""
    shards: List[List[str]] = [[] for _ in range(n_shards)]
    for f in filters:
        shards[shard_of(f, n_shards)].append(f)
    return shards


def finalize_parts(
    autos: Sequence[Automaton],
    state_capacity: int | None = None,
    n_buckets: int | None = None,
) -> List[Automaton]:
    """Compress + pack a list of per-shard flattened automatons with
    SHARED shapes (state capacity, bucket count, slot layout, step
    bound): the stacked shard_map program is one compiled walk, so
    every shard must agree on every static. Mode is voted — if any
    shard's trie is deep enough to want wide rows, all shards use
    them (wide is correct for shallow tries, just wider gathers)."""
    from emqx_tpu.ops.csr import (attach_walk_tables,
                                  buckets_for_capacity, capacity_for,
                                  compress_automaton)

    comp = [compress_automaton(a) for a in autos]
    if len({c[0].wt_slots for c in comp}) > 1:
        comp = [compress_automaton(a, force_mode="wide") for a in autos]
        if len({c[0].wt_slots for c in comp}) > 1:
            # a shard hit compress_automaton's wide-mode fallback
            # guard (packed-lane capacity: states ≥ 2^26 or depth >
            # 31) and stayed narrow despite the force — mixed row
            # widths would crash the np.stack below, so demote EVERY
            # shard to narrow (correct for any trie, just unskipped)
            comp = [compress_automaton(a, force_mode="narrow")
                    for a in autos]
    assert len({c[0].wt_slots for c in comp}) == 1, \
        "per-shard walk tables must agree on slot layout"
    s2_cap = max(c[0].node2.shape[0] for c in comp)
    if state_capacity is not None:
        s2_cap = max(s2_cap, state_capacity)
    e2_cap = capacity_for(max(len(c[1].src) for c in comp) + 1)
    slots = comp[0][0].wt_slots
    nb = buckets_for_capacity(e2_cap, slots)
    if n_buckets is not None:
        nb = max(nb, n_buckets)
    # one merged step bound: the stacked walk runs every shard for the
    # max hop depth (per-shard patchers keep accounting on the merged
    # array so a deep patch on one shard grows the shared bound)
    hlen = max(len(c[0].hops_for_level) for c in comp)
    merged = np.zeros(hlen, np.int32)
    for a, _ in comp:
        hl = a.hops_for_level
        ext = np.concatenate(
            [hl, np.minimum(int(hl[-1]) + np.arange(1, hlen - len(hl) + 1),
                            np.arange(len(hl), hlen) + 1)]) \
            if len(hl) < hlen else hl
        merged = np.maximum(merged, ext.astype(np.int32))
    parts = []
    for a, edges in comp:
        a = _pad_v2(a, s2_cap)
        a = a._replace(hops_for_level=merged.copy())
        parts.append(attach_walk_tables(a, edges, n_buckets=nb))
    return parts


def _pad_v2(a: Automaton, s2_cap: int) -> Automaton:
    """Grow the v2 state-indexed arrays to a shared capacity."""
    def pad2(arr, fill):
        if arr.shape[0] == s2_cap:
            return arr
        out = np.full((s2_cap,) + arr.shape[1:], fill, dtype=arr.dtype)
        out[: arr.shape[0]] = arr
        return out

    return a._replace(node2=pad2(a.node2, -1),
                      v2_hop=pad2(a.v2_hop, -1),
                      v2_depth=pad2(a.v2_depth, -1))


def build_sharded(
    filter_shards: Sequence[Sequence[str]],
    filter_ids: Dict[str, int],
    table: WordTable,
    state_capacity: int | None = None,
    n_buckets: int | None = None,
    return_parts: bool = False,
) -> ShardedAutomaton:
    """Build one automaton per shard (global filter ids), compress
    with shared shapes, and stack.

    ``state_capacity``/``n_buckets`` are retention floors (the router
    passes its previous caps so rebuilds keep device shapes — and jit
    specializations — stable). ``return_parts=True`` also returns the
    per-shard HOST automatons: they seed the per-shard
    :class:`~emqx_tpu.ops.patch.AutoPatcher` mirrors."""
    autos = []
    for shard in filter_shards:
        trie = TrieOracle()
        for f in shard:
            trie.insert(f)
        autos.append(build_automaton(trie, filter_ids, table,
                                     skip_hash=True))
    parts = finalize_parts(autos, state_capacity=state_capacity,
                           n_buckets=n_buckets)
    stacked = _stack_sharded(parts)
    if return_parts:
        return stacked, parts
    return stacked


def _stack_sharded(parts: Sequence[Automaton]) -> ShardedAutomaton:
    return ShardedAutomaton(
        wt=np.stack([a.wt for a in parts]),
        wt_seed=np.stack([a.wt_seed for a in parts]),
        node2=np.stack([a.node2 for a in parts]),
    )


def build_sharded_fanout(
    rows_per_shard: Sequence[Dict[int, Sequence[int]]],
    num_filters: int,
    filter_capacity: int | None = None,
    entry_capacity: int | None = None,
) -> ShardedFanout:
    fans = [build_fanout(rows, num_filters) for rows in rows_per_shard]
    f_cap = max(f.row_ptr.shape[0] - 1 for f in fans)
    e_cap = max(f.sub_ids.shape[0] for f in fans)
    if filter_capacity is not None:
        f_cap = max(f_cap, filter_capacity)
    if entry_capacity is not None:
        e_cap = max(e_cap, entry_capacity)
    fans = [
        build_fanout(rows, num_filters, filter_capacity=f_cap,
                     entry_capacity=e_cap)
        for rows in rows_per_shard
    ]
    return ShardedFanout(
        row_ptr=np.stack([f.row_ptr for f in fans]),
        sub_ids=np.stack([f.sub_ids for f in fans]),
        row_pairs=np.stack([f.row_pairs for f in fans]),
    )


def place_sharded(mesh: Mesh, sharded: NamedTuple):
    """Put stacked shard arrays onto the mesh: leading axis on 'trie',
    replicated over 'data'."""
    spec = NamedSharding(mesh, P("trie"))
    return type(sharded)(*[jax.device_put(x, spec) for x in sharded])


def place_batch(mesh: Mesh, word_ids, n_words, sys_mask):
    spec = NamedSharding(mesh, P("data"))
    return (jax.device_put(word_ids, spec),
            jax.device_put(n_words, spec),
            jax.device_put(sys_mask, spec))


def _local_auto(auto_t: ShardedAutomaton) -> Automaton:
    """This shard's walkable Automaton view inside shard_map (the
    leading shard axis is length 1 locally)."""
    return Automaton(
        row_ptr=None, edge_word=None, edge_child=None,
        plus_child=None, hash_filter=None, end_filter=None,
        n_states=0, n_edges=0,
        wt=auto_t.wt[0], wt_seed=auto_t.wt_seed[0],
        node2=auto_t.node2[0])


@functools.partial(
    jax.jit,
    static_argnames=("mesh", "k", "m", "d", "mb", "with_fanout",
                     "steps", "slots", "take"))
def publish_step(
    mesh: Mesh,
    auto: ShardedAutomaton,
    fan: ShardedFanout,
    word_ids: jax.Array,   # [B, L] sharded over 'data'
    n_words: jax.Array,    # [B]
    sys_mask: jax.Array,   # [B]
    bmt: ShardedBitmaps | None = None,
    *,
    k: int = 64,
    m: int = 128,
    d: int = 128,
    mb: int = 16,
    with_fanout: bool = True,
    steps: int | None = None,
    slots: int = 2,
    take: int = 1,
):
    """The full multi-chip publish step.

    Returns ``(match_ids [B, T*m], sub_ids [B, T*d], src_ids [B, T*d],
    bm [(union [B, W], has_big [B], bovf [B]) | None],
    overflow [B], match_overflow [B], stats)``:

    - ``src_ids`` carries the source filter id per gathered subscriber
      slot (the delivery tail resolves per-subscription options by
      matched filter, the reference's ``{Topic, SubPid}`` dispatch
      pairs);
    - with a :class:`ShardedBitmaps` table, each trie shard ORs its
      matched big filters' bitmap rows (the >d regime,
      src/emqx_broker_helper.erl:82-92) and the per-topic unions
      OR-combine over ICI — ``bovf`` flags topics matching more than
      ``mb`` big filters on some shard (host fallback, like the
      single-chip bitmap path);
    - per-row ``overflow`` marks topics whose match or CSR fan-out
      exceeded a kernel bound on ANY trie shard (the caller resolves
      those host-side — same contract as the single-chip
      ``match_batch``), while ``match_overflow`` isolates the match
      (active-set/m) bound — the only overflow a ``boost_k`` grow can
      help with (a fan-out ``d`` overflow must not trigger k
      recompiles). ``stats`` is a dict of mesh-summed counters
      (matches, deliveries, overflows) — the device metric
      accumulator.

    A 1×1 mesh runs the SAME local computation as a plain jit program
    (every collective is the identity on one device): shard_map
    dispatch does not pipeline through this environment's tunnel
    (~1.6× overlap vs deep plain-jit pipelining — round-4's 9×
    sharded-row gap), and a single-device mesh has nothing to
    exchange. The multi-device path is byte-identical modulo the
    collectives and stays exercised by the 8-device dryrun.
    """
    with_bitmap = bmt is not None
    # Pallas manual-DMA on real accelerators; the scan fallback on the
    # virtual CPU mesh (interpret-mode Pallas inside shard_map is not
    # supported). Static at trace time.
    use_dma = jax.default_backend() in ("tpu", "axon")
    single = mesh.shape["data"] == 1 and mesh.shape["trie"] == 1

    class _NullAxes:
        """Collective ops on a 1-device mesh: identities/local sums."""
        @staticmethod
        def ag_tiled(x):
            return x

        @staticmethod
        def or_over_trie(union):
            return union

        @staticmethod
        def any_over_trie(x):
            return x

        @staticmethod
        def sum_over_mesh(x):
            return x

        @staticmethod
        def sum_over_data(x):
            return x

    class _MeshAxes:
        @staticmethod
        def ag_tiled(x):
            return jax.lax.all_gather(x, "trie", axis=1, tiled=True)

        @staticmethod
        def or_over_trie(union):
            ug = jax.lax.all_gather(union, "trie")       # [T, b, W]
            return jax.lax.reduce(
                ug, jnp.uint32(0), jax.lax.bitwise_or, (0,))

        @staticmethod
        def any_over_trie(x):
            return jax.lax.psum(x.astype(jnp.int32), "trie") > 0

        @staticmethod
        def sum_over_mesh(x):
            return jax.lax.psum(x, ("data", "trie"))

        @staticmethod
        def sum_over_data(x):
            return jax.lax.psum(x, "data")

    def local(auto_t, fan_t, ids, n, sysm, bmt_t=None, C=_MeshAxes):
        from emqx_tpu.ops.bitmap import (BitmapTable, or_bitmaps_dma,
                                         or_bitmaps_xla,
                                         rows_for_matches)

        a = _local_auto(auto_t)
        res = match_batch(a, ids, n, sysm, k=k, m=m, steps=steps,
                          slots=slots, take=take)
        if with_fanout:
            f = FanoutTable(
                fan_t.row_ptr[0], fan_t.sub_ids[0], 0, 0,
                row_pairs=(None if fan_t.row_pairs is None
                           else fan_t.row_pairs[0]))
            subs, src, dcount, dovf = gather_subscribers_src(
                f, res.ids, d=d)
        else:
            subs = jnp.zeros((ids.shape[0], d), jnp.int32)
            src = jnp.full((ids.shape[0], d), -1, jnp.int32)
            dcount = jnp.zeros((ids.shape[0],), jnp.int32)
            dovf = jnp.zeros((ids.shape[0],), bool)
        # exchange shard-local matches over ICI: every data shard gets
        # the union of all trie shards' match ids
        all_ids = C.ag_tiled(res.ids)
        all_subs = C.ag_tiled(subs)
        all_src = C.ag_tiled(src)
        bm_out = None
        big_deliv = None
        if with_bitmap:
            bt = BitmapTable(bmt_t.bitmaps[0], bmt_t.big_row[0], 0, 0)
            rows_b, b_ovf = rows_for_matches(bt, res.ids, mb=mb)
            union = (or_bitmaps_dma(bt.bitmaps, rows_b) if use_dma
                     else or_bitmaps_xla(bt.bitmaps, rows_b))
            # per-topic union OR-combined over the trie axis (each
            # shard contributes its own big filters' members)
            union = C.or_over_trie(union)
            has_big = C.any_over_trie((rows_b >= 0).any(axis=1))
            bovf = C.any_over_trie(b_ovf)
            big_deliv = jnp.sum(
                jax.lax.population_count(union), dtype=jnp.int32)
            bm_out = (union, has_big, bovf)
        # per-row overflow, OR-reduced over the trie axis: one shard
        # overflowing means the row's union is incomplete
        row_movf = C.any_over_trie(res.overflow)
        row_ovf = row_movf | C.any_over_trie(dovf)
        deliv = C.sum_over_mesh(jnp.sum(dcount))
        if big_deliv is not None:
            # the OR-reduced union is IDENTICAL on every trie shard —
            # sum it over 'data' only (a trie psum would count each
            # big delivery T times)
            deliv = deliv + C.sum_over_data(big_deliv)
        stats = {
            "matches": C.sum_over_mesh(jnp.sum(res.count)),
            "deliveries": deliv,
            "overflows": C.sum_over_mesh(jnp.sum(res.overflow | dovf)),
        }
        return all_ids, all_subs, all_src, bm_out, row_ovf, row_movf, stats

    args = [auto, fan, word_ids, n_words, sys_mask]
    if with_bitmap:
        args.append(bmt)
    if single:
        out = local(*args, C=_NullAxes)
        # the 1×1 outputs already carry the T=1 global shapes; cast
        # the bool reductions to match the mesh path's dtypes
        return out
    in_specs = [P("trie"), P("trie"), P("data"), P("data"), P("data")]
    bm_spec = (P("data"), P("data"), P("data")) if with_bitmap else None
    if with_bitmap:
        in_specs.append(P("trie"))
    return _shard_map(
        local, mesh,
        tuple(in_specs),
        (P("data"), P("data"), P("data"), bm_spec,
         P("data"), P("data"), P()),
    )(*args)


@functools.partial(jax.jit, static_argnames=("mesh", "k", "m", "steps",
                                             "slots", "take"))
def shared_pick_step(
    mesh: Mesh,
    auto: ShardedAutomaton,
    gfan: ShardedFanout,     # per-shard GROUP membership CSR
    word_ids: jax.Array,     # [B, L] sharded over 'data'
    n_words: jax.Array,
    sys_mask: jax.Array,
    seeds: jax.Array,        # int32[B] per-message pick seed
    *,
    k: int = 16,
    m: int = 32,
    steps: int | None = None,
    slots: int = 2,
    take: int = 1,
):
    """Multi-chip $share dispatch: match + the device hash-strategy
    member pick (src/emqx_shared_sub.erl:229-275) in one collective
    step. Each trie shard picks members for ITS groups' matches
    (``gfan`` rows live with their filter's shard — same stable
    assignment as the automaton); picks are all-gathered over ICI.

    Returns ``(picks [B, T*m], match_ids [B, T*m], overflow [B])``;
    picks are subscriber ids aligned with ``match_ids`` slots (-1 =
    slot empty or group not on that shard). The pick is stateless
    (hash strategy); round-robin/sticky keep host state and stay
    host-side, exactly as on one chip."""
    from emqx_tpu.ops.fanout import pick_shared

    def local(auto_t, gfan_t, ids, n, sysm, s):
        a = _local_auto(auto_t)
        res = match_batch(a, ids, n, sysm, k=k, m=m, steps=steps,
                          slots=slots, take=take)
        f = FanoutTable(
            gfan_t.row_ptr[0], gfan_t.sub_ids[0], 0, 0,
            row_pairs=(None if gfan_t.row_pairs is None
                       else gfan_t.row_pairs[0]))
        picks = pick_shared(f, res.ids, s)
        all_picks = jax.lax.all_gather(picks, "trie", axis=1, tiled=True)
        all_ids = jax.lax.all_gather(res.ids, "trie", axis=1, tiled=True)
        ovf = jax.lax.psum(res.overflow.astype(jnp.int32), "trie") > 0
        return all_picks, all_ids, ovf

    return _shard_map(
        local, mesh,
        (P("trie"), P("trie"), P("data"), P("data"), P("data"),
         P("data")),
        (P("data"), P("data"), P("data")),
    )(auto, gfan, word_ids, n_words, sys_mask, seeds)
