"""Device mesh construction for the broker.

Two mesh axes, mirroring the reference's two scale dimensions
(SURVEY §5 "long-context"):

  - ``data``: publish-batch sharding — the analogue of EMQX's hashed
    broker/router worker pools (each worker handles a slice of
    traffic, src/emqx_broker.erl:428-429);
  - ``trie``: subscription-table sharding — the analogue of topic
    shards + replicated Mnesia tables (src/emqx_broker_helper.erl:
    82-92, src/emqx_router.erl:77-86): each chip holds a slice of the
    filter set and match results are all-gathered over ICI.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh


def make_mesh(n_data: int, n_trie: int,
              devices: Optional[Sequence] = None) -> Mesh:
    devs = list(devices if devices is not None else jax.devices())
    need = n_data * n_trie
    if len(devs) < need:
        raise ValueError(f"need {need} devices, have {len(devs)}")
    grid = np.array(devs[:need]).reshape(n_data, n_trie)
    return Mesh(grid, ("data", "trie"))


def default_mesh(n_devices: Optional[int] = None) -> Mesh:
    """Prefer sharding the batch; put leftover factor on the trie axis.

    For n a power of two: (n, 1) for n ≤ 2 else (n // 2, 2) — both
    axes exercised whenever possible.
    """
    n = n_devices if n_devices is not None else len(jax.devices())
    if n <= 2:
        return make_mesh(n, 1)
    return make_mesh(n // 2, 2)
