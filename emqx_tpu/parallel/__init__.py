"""Multi-chip operation: device meshes, sharded automatons, and the
collective match step (the reference's cluster routing layer mapped
onto ICI, SURVEY §2.3)."""
