"""Alarm management: activate/deactivate with history, hooks and
``$SYS`` publication (reference: src/emqx_alarm.erl +
emqx_alarm_handler.erl)."""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional


@dataclass
class Alarm:
    name: str
    details: dict = field(default_factory=dict)
    message: str = ""
    activated_at: float = field(default_factory=time.time)
    deactivated_at: Optional[float] = None

    @property
    def active(self) -> bool:
        return self.deactivated_at is None


class AlarmManager:
    def __init__(self, broker=None, node: str = "emqx_tpu@127.0.0.1",
                 history_size: int = 1000) -> None:
        self.broker = broker
        self.node = node
        self.history_size = history_size
        self._active: Dict[str, Alarm] = {}
        self._history: List[Alarm] = []

    def activate(self, name: str, details: Optional[dict] = None,
                 message: str = "") -> bool:
        if name in self._active:
            return False  # already_existed
        alarm = Alarm(name=name, details=details or {}, message=message)
        self._active[name] = alarm
        self._publish(alarm, "alert")
        return True

    def deactivate(self, name: str) -> bool:
        alarm = self._active.pop(name, None)
        if alarm is None:
            return False
        alarm.deactivated_at = time.time()
        self._history.append(alarm)
        del self._history[:-self.history_size]
        self._publish(alarm, "clear")
        return True

    def get_alarms(self, which: str = "all") -> List[Alarm]:
        if which == "activated":
            return list(self._active.values())
        if which == "deactivated":
            return list(self._history)
        return list(self._active.values()) + list(self._history)

    def delete_all_deactivated(self) -> None:
        self._history.clear()

    def _publish(self, alarm: Alarm, kind: str) -> None:
        if self.broker is None:
            return
        from emqx_tpu.types import Message
        import json
        payload = json.dumps({
            "name": alarm.name, "message": alarm.message,
            "details": alarm.details,
            "activated_at": alarm.activated_at,
            "deactivated_at": alarm.deactivated_at,
        }).encode()
        topic = f"$SYS/brokers/{self.node}/alarms/{kind}"
        self.broker.publish(Message(topic=topic, payload=payload,
                                    flags={"sys": True}))
        self.broker.hooks.run(
            "alarm.activated" if kind == "alert" else "alarm.deactivated",
            (alarm,))
