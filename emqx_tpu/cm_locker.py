"""Distributed per-clientid lock — the cluster CM locker.

Mirrors ``src/emqx_cm_locker.erl:41-49`` (ekka_locker with the
``quorum`` strategy): every session open/discard/takeover for a
clientid runs under a cluster-wide lock (taken at
``src/emqx_cm.erl:209-236``), so two nodes racing the SAME clientid
serialize — the second open observes the first's registry entry and
takes over / discards it instead of double-owning the session.

Semantics:

- a lock is granted when a STRICT MAJORITY of the current membership
  accepts it (self counts); grants are owner-reentrant;
- grants are tied to the OWNER NODE's liveness, exactly like
  ekka_locker's monitored locks: ``handle_nodedown`` drops every
  grant the dead node held, so a crashed owner never deadlocks a
  clientid (the ``LEASE`` is only a generous backstop against
  same-node leaks — release runs in a ``finally``);
- an unreachable peer during acquisition triggers the normal
  nodedown path (membership shrinks — the quorum is over LIVE
  members, like ekka's after a netsplit verdict), and grant RPCs fan
  out CONCURRENTLY (ekka_locker multicall) so an uncontended open
  pays one round-trip, not N;
- a lock HELD by a live owner is waited on up to
  ``ACQUIRE_TIMEOUT``; only past that (a pathological critical
  section) does :meth:`acquire` return False and the caller proceed
  under its node-local mutex only — availability over consistency,
  the reference's post-ekka behavior once a holder is unresponsive.
"""

from __future__ import annotations

import logging
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, Tuple

from emqx_tpu.cluster import PeerUnavailableError

log = logging.getLogger("emqx_tpu.cm_locker")

LEASE = 60.0            # backstop expiry for a leaked same-node grant
ACQUIRE_TIMEOUT = 10.0  # max wait on a lock held by a live owner
RETRY_DELAY = 0.05


class ClusterLocker:
    def __init__(self, cluster) -> None:
        self.cluster = cluster
        self._lock = threading.Lock()
        # client_id -> (owner node, lease expiry)
        self._table: Dict[str, Tuple[str, float]] = {}
        self._pool = ThreadPoolExecutor(
            max_workers=8, thread_name_prefix="cm-locker")

    # -- local grant table (self + RPC from peers) ------------------------

    def grant(self, client_id: str, owner: str) -> bool:
        """Grant (or refresh) this node's vote for ``owner`` holding
        ``client_id``; False while a live different-owner lease holds."""
        now = time.time()
        with self._lock:
            ent = self._table.get(client_id)
            if ent is not None and ent[0] != owner and ent[1] > now:
                return False
            self._table[client_id] = (owner, now + LEASE)
            return True

    def release_local(self, client_id: str, owner: str) -> None:
        with self._lock:
            ent = self._table.get(client_id)
            if ent is not None and ent[0] == owner:
                del self._table[client_id]

    # -- cluster acquire/release ------------------------------------------

    def _ask_peer(self, m: str, client_id: str, me: str):
        try:
            return m, bool(self.cluster.transport.call(
                m, "lock_acquire", client_id, me))
        except PeerUnavailableError:
            # suspect ≠ dead: no vote, no nodedown — the member is
            # skipped this round (subclass check must come first)
            return m, PeerUnavailableError
        except ConnectionError:
            return m, ConnectionError
        except Exception:
            log.exception("lock rpc to %s failed", m)
            return m, False

    def acquire(self, client_id: str) -> bool:
        """Take the cluster lock: majority of the LIVE membership.

        Blocks while another LIVE owner holds it — that wait IS the
        serialization that prevents double-owned sessions; a crashed
        holder's grants drop on its nodedown, so the wait tracks the
        holder's actual critical section, not a timer.

        A suspect/down member (PeerUnavailableError from the failure
        detector's fast-fail gate, docs/CLUSTER.md) is neither a vote
        nor a death: it is excluded from the quorum denominator for
        this attempt, so a CONNECT never blocks ``call_timeout`` on a
        peer the detector already holds unhealthy — quorum proceeds
        over the responsive membership and ``cluster.locker.degraded``
        counts the degradation."""
        me = self.cluster.name
        deadline = time.monotonic() + ACQUIRE_TIMEOUT
        while True:
            peers = [m for m in list(self.cluster.members) if m != me]
            granted = []
            suspect = []
            if self.grant(client_id, me):
                granted.append(me)
            # concurrent fan-out (ekka_locker multicall): one
            # round-trip per attempt regardless of cluster size
            for m, res in self._pool.map(
                    lambda p: self._ask_peer(p, client_id, me), peers):
                if res is ConnectionError:
                    # unreachable peer: with the failure detector
                    # armed the verdict is DEFERRED to it (a
                    # transient call error to a LIVE member used to
                    # shrink the membership and trigger spurious
                    # promotions, cluster.py _peer_call_failed);
                    # legacy transports keep the nodedown-now path
                    self.cluster._peer_call_failed(m)
                elif res is PeerUnavailableError:
                    # suspect ≠ dead: no vote, no nodedown, no wait
                    suspect.append(m)
                elif res:
                    granted.append(m)
            live = set(self.cluster.members)
            votes = len([g for g in granted if g in live])
            if votes * 2 > len(live):
                return True
            responsive = live - set(suspect)
            if suspect and responsive and votes * 2 > len(responsive):
                # majority of the members that can answer at all:
                # proceed (availability over a full quorum — the
                # suspect peer is either dead, in which case nodedown
                # will shrink the membership anyway, or partitioned,
                # in which case anti-entropy reconciles the registry
                # after heal), but make the degradation observable
                self.cluster._count("locker.degraded")
                log.warning(
                    "cluster lock on %r granted by %d/%d with %r "
                    "suspect — degraded quorum", client_id, votes,
                    len(live), suspect)
                return True
            # held elsewhere: release partial grants so the competing
            # owner can win, then retry until the deadline
            for g in granted:
                if g == me:
                    self.release_local(client_id, me)
                else:
                    try:
                        self.cluster.transport.cast(
                            g, "lock_release", client_id, me)
                    except ConnectionError:
                        pass
            if time.monotonic() >= deadline:
                break
            # jittered backoff: two nodes racing in lockstep must
            # not retry in lockstep forever
            import random

            time.sleep(RETRY_DELAY * (0.5 + random.random()))
        log.warning("cluster lock on %r unattainable within %.0fs "
                    "(members=%r) — proceeding under the local mutex "
                    "only", client_id, ACQUIRE_TIMEOUT,
                    self.cluster.members)
        return False

    def release(self, client_id: str) -> None:
        me = self.cluster.name
        self.release_local(client_id, me)
        self.cluster._broadcast("lock_release", client_id, me)

    def drop_owner(self, node: str) -> int:
        """Drop every grant a dead node holds (called from the
        cluster's nodedown path — the ekka_locker monitored-lock
        cleanup): a crashed holder releases immediately instead of
        deadlocking its clientids until the lease backstop."""
        with self._lock:
            dead = [c for c, (o, _e) in self._table.items()
                    if o == node]
            for c in dead:
                del self._table[c]
        return len(dead)

    def sweep(self) -> int:
        """Drop expired leases (housekeeping; grant() also treats an
        expired lease as free)."""
        now = time.time()
        with self._lock:
            dead = [c for c, (_o, exp) in self._table.items()
                    if exp <= now]
            for c in dead:
                del self._table[c]
        return len(dead)

    def info(self) -> Dict[str, Tuple[str, float]]:
        with self._lock:
            return dict(self._table)
