"""CLI command registry + the built-in management commands
(reference: src/emqx_ctl.erl + the ctl hooks in broker/cm/plugins).

Commands operate on a live :class:`~emqx_tpu.node.Node`; the registry
is extensible the same way the reference's `emqx_ctl:register_command`
is."""

from __future__ import annotations

import json
import time
from typing import Callable, Dict, List


class Ctl:
    def __init__(self, node) -> None:
        self.node = node
        self._commands: Dict[str, Callable] = {}
        self._usage: Dict[str, str] = {}
        self._register_builtins()

    def register_command(self, name: str, fn: Callable,
                         usage: str = "") -> None:
        self._commands[name] = fn
        self._usage[name] = usage

    def unregister_command(self, name: str) -> None:
        self._commands.pop(name, None)
        self._usage.pop(name, None)

    def run(self, argv: List[str]) -> str:
        if not argv or argv[0] in ("help", "--help"):
            return self.usage()
        cmd = self._commands.get(argv[0])
        if cmd is None:
            return f"unknown command: {argv[0]}\n" + self.usage()
        try:
            return cmd(argv[1:])
        except Exception as e:  # operator input errors become text
            usage = self._usage.get(argv[0], "")
            return f"error: {e}\nusage: {argv[0]} {usage}"

    def usage(self) -> str:
        lines = ["commands:"]
        for name in sorted(self._commands):
            lines.append(f"  {name:<14} {self._usage.get(name, '')}")
        return "\n".join(lines)

    # -- built-ins --------------------------------------------------------

    def _register_builtins(self) -> None:
        self.register_command("status", self._status, "broker status")
        self.register_command("broker", self._broker, "broker info")
        self.register_command("clients", self._clients,
                              "list | show <clientid> | kick <clientid>")
        self.register_command("sessions", self._sessions, "session count")
        self.register_command("topics", self._topics, "list routed topics")
        self.register_command("subscriptions", self._subs,
                              "show <clientid>")
        self.register_command("metrics", self._metrics, "all counters")
        self.register_command("stats", self._stats, "all gauges")
        self.register_command("routes", self._routes, "list routes")
        self.register_command("plugins", self._plugins,
                              "list | load <name> | unload <name>")
        self.register_command("banned", self._banned,
                              "list | add <kind> <value> [secs] | del <kind> <value>")
        self.register_command("checkpoint", self._checkpoint,
                              "save|load <path>")
        self.register_command(
            "reload", self._reload,
            "<config.toml> — diff the running config and apply "
            "reloadable knobs + zones atomically; boot-only edits "
            "are rejected with a per-knob report "
            "(docs/OPERATIONS.md)")
        self.register_command(
            "drain", self._drain,
            "start [--target <peer>] [--ref <host:port>] | status | "
            "stop — graceful node drain: redirect clients in paced "
            "waves, hand session custody to the target "
            "(docs/OPERATIONS.md)")
        self.register_command("trace", self._trace,
                              "list | start client|topic <v> | "
                              "stop client|topic <v> | export <path>")
        self.register_command(
            "slow_subs", self._slow_subs,
            "top-N slowest subscribers by moving delivery latency "
            "(docs/OBSERVABILITY.md) | reset")
        self.register_command("vm", self._vm,
                              "host/runtime introspection (emqx_vm)")
        self.register_command(
            "cluster", self._cluster,
            "status | join <host:port> | leave  (emqx_ctl cluster)")
        self.register_command("listeners", self._listeners,
                              "list listeners + connection counts")
        self.register_command("log", self._log,
                              "set-level <debug|info|warning|error> | show")
        self.register_command(
            "telemetry", self._telemetry,
            "stages | slow | reset — publish-path stage latency")
        self.register_command(
            "cache", self._cache,
            "publish match-cache: hit/miss/stale, epoch-bump split, "
            "partitions, fid quarantine")
        self.register_command(
            "overload", self._overload,
            "overload level, samples, shed counters, breaker state "
            "incl. device-loss recovery (rebuilds, last_rebuild_s)")
        self.register_command(
            "faults", self._faults,
            "list | arm <point[:action[:times[:delay_ms]]]> | "
            "disarm <point> | clear | on | off")
        self.register_command(
            "durability", self._durability,
            "journal/checkpoint/recovery state | checkpoint — "
            "commit a generation now")
        self.register_command(
            "retained", self._retained,
            "retained store/index state: store/deep/tombstone "
            "counts, device epoch + dirty rows, fallback/breaker "
            "state, replay batch counters (docs/OBSERVABILITY.md)")
        from emqx_tpu.profiling import register_ctl
        register_ctl(self)

    def _overload(self, args) -> str:
        """One-stop overload diagnosis (docs/ROBUSTNESS.md): current
        level + last sample set, the cumulative shed/heal counters,
        and the device-path breaker state — with the device-loss
        recovery fields (state incl. ``rebuilding``, classification,
        rebuilds, rebuild_failures, last_rebuild_s) when the
        recovery manager is attached."""
        from emqx_tpu.metrics import BREAKER_METRICS, OVERLOAD_METRICS
        ov = self.node.overload
        out = {"enabled": ov is not None}
        if ov is not None:
            out.update(ov.info())
        m = self.node.metrics
        out["counters"] = {
            k: m.val(k) for k in OVERLOAD_METRICS + BREAKER_METRICS
            if m.val(k)}
        out["orphaned_xloop"] = m.val("delivery.xloop.orphaned")
        br = self.node.broker.breaker
        out["breaker"] = br.info() if br is not None else "disabled"
        return json.dumps(out, indent=2)

    def _durability(self, args) -> str:
        """One-stop durability diagnosis (docs/DURABILITY.md):
        generation, journal shards/bytes/records/degraded state, last
        fsync latency, checkpoint chain + age, the last recovery
        summary, and the replication block (role, the replication-
        group topology with per-standby link state + shipped/acked
        offsets, aggregate lag, ack-quorum status, last promotion/
        failback; warm replicas this node holds for its peers)."""
        dur = self.node.durability
        repl = getattr(self.node, "replication", None)
        if dur is None:
            if repl is not None and repl.replicas:
                # a pure standby: durability off locally, but warm
                # replicas for peers are operator-relevant state
                return json.dumps({"enabled": False,
                                   "replication": repl.info()},
                                  indent=2, default=str)
            return ("durability not enabled "
                    "([durability] enabled = true in the config)")
        if args and args[0] == "checkpoint":
            return json.dumps(dur.checkpoint_now(), indent=2)
        out = dur.info()
        if repl is not None and "replication" not in out:
            out["replication"] = repl.info()
        return json.dumps(out, indent=2, default=str)

    def _faults(self, args) -> str:
        from emqx_tpu import faults
        if not args or args[0] == "list":
            return json.dumps(faults.info(), indent=2)
        if args[0] == "arm" and len(args) > 1:
            faults.arm_spec(args[1])
            return "ok"
        if args[0] == "disarm" and len(args) > 1:
            return "ok" if faults.disarm(args[1]) else "not armed"
        if args[0] == "clear":
            faults.clear()
            return "ok"
        if args[0] in ("on", "off"):
            faults.set_master(args[0] == "on")
            return "ok"
        raise ValueError(f"bad subcommand: {args[0]}")

    def _cache(self, args) -> str:
        """Everything needed to diagnose a hit-rate collapse from one
        command (docs/MATCH_CACHE.md "Partitioned epochs"): per-cache
        cumulative counters + hit rate, the bump.global/bump.partition
        split, the live partition count, and the fid-quarantine
        depth."""
        r = self.node.router
        out = {
            "partitions": r.cache_partitions_live(),
            "bumps": r.cache_bump_totals(),
            "entries": r.cache_entries(),
            "quarantined_ids": r.quarantined_ids(),
            # online delta automaton (docs/DELTA.md): pending side-
            # automaton size, tombstones, merge count, and the
            # cumulative lock-stall the off-lock compaction design
            # keeps near zero
            "delta": r.delta_info(),
            # walk kernel variant (pallas | lax) + the live tables'
            # level-compression snapshot (docs/PERF_NOTES.md
            # "Round 6: path compression and the VMEM walk")
            "walk": r.walk_info(),
        }
        for name, c in (("single", r._match_cache_obj),
                        ("sharded", r._sharded_cache_obj)):
            if c is not None:
                st = c.stats()
                st["hit_rate"] = round(st["hit_rate"], 4)
                out[name] = st
        if r._match_cache_obj is None and r._sharded_cache_obj is None:
            out["state"] = ("disabled" if not r.config.match_cache
                            or r.config.match_cache_slots <= 0
                            else "cold (no device match yet)")
        return json.dumps(out, indent=2)

    def _retained(self, args) -> str:
        """One-stop retained-path diagnosis (docs/OBSERVABILITY.md
        "Retained replay"): the store/replay counters (entries,
        tombstones, dropped/expired, replay batches + last batch
        size) and the reverse index's device state (live/deep rows,
        capacity, epoch, dirty-row backlog, breaker/suspension
        fallback, walk variant)."""
        mod = self.node.modules._loaded.get("retainer") \
            if hasattr(self.node, "modules") else None
        if mod is None:
            return "retainer module not loaded"
        out = mod.replay_info()
        out["index"] = mod._index.device_info()
        return json.dumps(out, indent=2)

    def _telemetry(self, args) -> str:
        tel = getattr(self.node, "telemetry", None)
        if tel is None:
            return "telemetry not available on this node"
        if not args or args[0] == "stages":
            if not tel.enabled:
                return "telemetry: disabled ([telemetry] enabled = false)"
            from emqx_tpu.telemetry import STAGES
            stats = tel.stage_stats()
            lines = [f"{'stage':<14}{'count':>8}{'p50_ms':>10}"
                     f"{'p95_ms':>10}{'p99_ms':>10}"]
            for s in STAGES:
                st = stats[s]
                lines.append(f"{s:<14}{st['count']:>8}"
                             f"{st['p50_ms']:>10.3f}"
                             f"{st['p95_ms']:>10.3f}"
                             f"{st['p99_ms']:>10.3f}")
            lines.append(f"spans: {tel.spans_total}  slow: "
                         f"{tel.slow_total} (threshold "
                         f"{tel.config.slow_threshold_ms}ms)")
            return "\n".join(lines)
        if args[0] == "slow":
            recs = tel.slow_records()
            return json.dumps(recs, indent=2) if recs else "(none)"
        if args[0] == "reset":
            tel.reset()
            return "ok"
        raise ValueError(f"bad subcommand: {args[0]}")

    def _log(self, args) -> str:
        import logging
        root = logging.getLogger("emqx_tpu")
        if not args or args[0] == "show":
            return f"level: {logging.getLevelName(root.level)}"
        if args[0] == "set-level":
            if len(args) < 2:
                raise ValueError("set-level needs a level")
            level = getattr(logging, args[1].upper(), None)
            if not isinstance(level, int):
                raise ValueError(f"bad level: {args[1]}")
            from emqx_tpu.logger import set_level
            set_level(level)
            return f"level: {logging.getLevelName(root.level)}"
        raise ValueError(f"bad subcommand: {args[0]}")

    def _listeners(self, args) -> str:
        out = []
        for lst in self.node.listeners:
            out.append({
                "name": lst.name,
                "bind": f"{lst.host}:{lst.port}",
                "tls": lst.ssl_context is not None,
                "zone": lst.zone.name,
                "current_connections": lst.current_connections(),
                "max_connections": lst.max_connections,
            })
        return json.dumps(out, indent=2)

    def _cluster(self, args) -> str:
        cl = getattr(self.node, "cluster", None)
        if cl is None:
            return ("clustering not enabled "
                    "(set [node] cluster_port in the config, or "
                    "attach a Cluster)")
        if not args or args[0] == "status":
            peers = {}
            book = getattr(cl.transport, "addr_book", None)
            if book is not None:
                peers = {k: f"{v[0]}:{v[1]}" for k, v in book().items()}
            # per-member failure-detector health (docs/CLUSTER.md):
            # state (ok/suspect/down), last heartbeat RTT, detector
            # transitions since state entry; plus the anti-entropy
            # sweep/repair summary
            health = {}
            for name, h in cl.transport.health_info().items():
                rtt = h.get("rtt_ms")
                health[name] = {
                    "state": h["state"],
                    "rtt_ms": round(rtt, 3) if rtt else None,
                    "misses": h.get("misses", 0),
                    "since": h.get("since"),
                    "departed": h.get("departed", False),
                }
            ae = cl.ae_info()
            return json.dumps({"node": cl.name,
                               "members": sorted(cl.members),
                               "addresses": peers,
                               "health": health,
                               "anti_entropy": ae}, indent=2)
        if args[0] == "join":
            import asyncio
            import threading

            host, _, port = args[1].rpartition(":")
            host = host or "127.0.0.1"
            try:
                asyncio.get_running_loop()
            except RuntimeError:
                cl.join_remote(host, int(port))  # management shell
                return f"joined; members: {sorted(cl.members)}"
            # called ON the serving loop: join_remote blocks on
            # network calls (up to the transport timeout per member)
            # — run it on a worker so MQTT serving never stalls
            threading.Thread(
                target=lambda: cl.join_remote(host, int(port)),
                daemon=True, name="ctl-cluster-join").start()
            return ("join started in background; "
                    "run 'cluster status' to confirm")
        if args[0] == "leave":
            cl.leave()
            return "left the cluster"
        raise ValueError(f"bad subcommand: {args[0]}")

    def _vm(self, args) -> str:
        from emqx_tpu import vm
        return json.dumps(vm.get_system_info(), indent=2, default=str)

    def _status(self, args) -> str:
        n = self.node
        return (f"node: {n.name}\n"
                f"connections: {n.cm.connection_count()}\n"
                f"sessions: {n.cm.session_count()}\n"
                f"topics: {len(n.router.topics())}")

    def _broker(self, args) -> str:
        from emqx_tpu import __version__
        from emqx_tpu.sys_topics import SYSDESCR
        return f"{self.node.name} {__version__} — {SYSDESCR}"

    def _clients(self, args) -> str:
        cm = self.node.cm
        if not args or args[0] == "list":
            return "\n".join(cm._channels) or "(none)"
        if args[0] == "show" and len(args) > 1:
            chan = cm.lookup_channel(args[1])
            if chan is None:
                return "not found"
            return json.dumps(dict(chan.clientinfo), default=str)
        if args[0] == "kick" and len(args) > 1:
            return "ok" if cm.kick_session(args[1]) else "not found"
        return "usage: clients list | show <id> | kick <id>"

    def _sessions(self, args) -> str:
        return str(self.node.cm.session_count())

    def _topics(self, args) -> str:
        return "\n".join(sorted(self.node.router.topics())) or "(none)"

    def _subs(self, args) -> str:
        if args and args[0] == "show" and len(args) > 1:
            chan = self.node.cm.lookup_channel(args[1])
            if chan is None or chan.session is None:
                return "not found"
            return json.dumps({f: o.to_dict()
                               for f, o in chan.session.subscriptions.items()})
        out = []
        for cid, chan in self.node.cm._channels.items():
            if getattr(chan, "session", None):
                for f in chan.session.subscriptions:
                    out.append(f"{cid} -> {f}")
        return "\n".join(out) or "(none)"

    def _metrics(self, args) -> str:
        return "\n".join(f"{k:<40} {v}"
                         for k, v in self.node.metrics.all().items() if v)

    def _stats(self, args) -> str:
        return "\n".join(f"{k:<30} {v}"
                         for k, v in self.node.stats.all().items())

    def _routes(self, args) -> str:
        out = []
        for t in self.node.router.topics():
            for r in self.node.router.lookup_routes(t):
                out.append(f"{r.topic} -> {r.dest}")
        return "\n".join(out) or "(none)"

    def _plugins(self, args) -> str:
        p = self.node.plugins
        if not args or args[0] == "list":
            return "\n".join(f"{d['name']} ({'active' if d['active'] else 'inactive'})"
                             for d in p.list()) or "(none)"
        if args[0] == "load" and len(args) > 1:
            return "ok" if p.load(args[1]) else "already loaded"
        if args[0] == "unload" and len(args) > 1:
            return "ok" if p.unload(args[1]) else "not loaded"
        return "usage: plugins list | load <name> | unload <name>"

    def _banned(self, args) -> str:
        b = self.node.broker.banned
        if not args or args[0] == "list":
            return "\n".join(f"{r.who[0]}:{r.who[1]} until={r.until}"
                             for r in b.info()) or "(none)"
        if args[0] == "add" and len(args) >= 3:
            dur = float(args[3]) if len(args) > 3 else None
            b.create(args[1], args[2], duration=dur)
            return "ok"
        if args[0] == "del" and len(args) >= 3:
            b.delete(args[1], args[2])
            return "ok"
        return "usage: banned list | add <kind> <value> [secs] | del <kind> <value>"

    def _reload(self, args) -> str:
        """Diff-based live reload (emqx_tpu/reload.py,
        docs/OPERATIONS.md): re-parse + validate the file in full,
        then all-or-nothing — any boot-only edit rejects the whole
        reload with a per-knob report; otherwise zones re-publish
        (the legacy reload, output shape preserved) and every changed
        reloadable knob applies atomically."""
        from emqx_tpu.config import load_config
        from emqx_tpu.reload import apply_reload
        if len(args) != 1:
            return "usage: reload <config.toml>"
        info = apply_reload(self.node, load_config(args[0]))
        if info["rejected"]:
            lines = ["reload rejected (boot-only changes; nothing "
                     "applied):"]
            for r in info["rejected"]:
                lines.append(f"  {r['knob']}: {r['old']!r} -> "
                             f"{r['new']!r} ({r['reason']})")
            return "\n".join(lines)
        out = f"zones reloaded: {', '.join(info['zones']) or '(none)'}"
        if info["listeners"]:
            out += f"; listeners rebound: {', '.join(info['listeners'])}"
        if info["stale"]:
            out += (f"; stale (no longer in config, kept): "
                    f"{', '.join(info['stale'])}")
        for a in info["applied"]:
            out += (f"\napplied: {a['knob']} {a['old']!r} -> "
                    f"{a['new']!r}")
        return out

    def _drain(self, args) -> str:
        """Graceful drain control (drain.py, docs/OPERATIONS.md)."""
        dr = self.node.drain
        if not args or args[0] == "status":
            return json.dumps(dr.info(), indent=2)
        if args[0] == "start":
            target = ref = None
            rest = list(args[1:])
            while rest:
                flag = rest.pop(0)
                if flag == "--target" and rest:
                    target = rest.pop(0)
                elif flag == "--ref" and rest:
                    ref = rest.pop(0)
                else:
                    raise ValueError(f"bad drain option: {flag}")
            dr.start(target=target, ref=ref)
            return json.dumps(dr.info(), indent=2)
        if args[0] == "stop":
            dr.stop()
            return json.dumps(dr.info(), indent=2)
        raise ValueError(f"bad subcommand: {args[0]}")

    def _checkpoint(self, args) -> str:
        from emqx_tpu import checkpoint
        if len(args) != 2 or args[0] not in ("save", "load"):
            return "usage: checkpoint save|load <path>"
        if args[0] == "save":
            info = checkpoint.save(self.node.router, args[1])
            return (f"saved {info['routes']} routes"
                    f"{' + tables' if info['tables'] else ''}")
        info = checkpoint.load(self.node.router, args[1])
        return (f"restored {info['routes']} routes"
                f"{' + tables' if info['tables_restored'] else ''}")

    def _trace(self, args) -> str:
        tr = self.node.tracer
        if not args or args[0] == "list":
            return "\n".join(f"{k}:{v}" for k, v in tr.lookup_traces()) \
                or "(none)"
        if args[0] == "start" and len(args) >= 3:
            kind = "clientid" if args[1] == "client" else "topic"
            tr.start_trace(kind, args[2])
            return "ok"
        if args[0] == "stop" and len(args) >= 3:
            kind = "clientid" if args[1] == "client" else "topic"
            return "ok" if tr.stop_trace(kind, args[2]) else "not found"
        if args[0] == "export" and len(args) >= 2:
            # drain any spans still sitting in the per-thread rings
            # first, so a just-published message's chain is complete
            trc = self.node.tracing
            trc.drain_tick(self.node.stats)
            n = trc.export(args[1])
            return (f"exported {n} trace events to {args[1]} "
                    f"(Chrome trace-event JSON — chrome://tracing, "
                    f"Perfetto)")
        return ("usage: trace list | start client|topic <v> | "
                "stop client|topic <v> | export <path>")

    def _slow_subs(self, args) -> str:
        trc = self.node.tracing
        if args and args[0] == "reset":
            trc.slow.reset()
            return "ok"
        # fold anything pending so the ranking reflects now
        trc.drain_tick(self.node.stats)
        rows = trc.slow.top()
        if not rows:
            return ("(none traced — slow_subs ranks sampled "
                    "deliveries; set [tracing] sample_rate > 0)")
        cfg = trc.config
        lines = [f"{'clientid':<24}{'avg_ms':>10}{'max_ms':>10}"
                 f"{'flushes':>9}{'age_s':>7}"]
        now = time.time()
        for cid, avg, mx, n, last in rows:
            lines.append(f"{cid:<24}{avg:>10.2f}{mx:>10.2f}"
                         f"{n:>9}{now - last:>7.0f}")
        lines.append(f"threshold {cfg.slow_subs_threshold_ms:g}ms, "
                     f"expiry {cfg.slow_subs_expiry_s:g}s, "
                     f"tracked {len(trc.slow.clients)}")
        return "\n".join(lines)
