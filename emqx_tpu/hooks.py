"""Priority-ordered hook chains — the extension seam of the broker.

Mirrors ``src/emqx_hooks.erl``: callbacks registered per hookpoint
with a priority (higher runs first, equal priority keeps registration
order, emqx_hooks.erl:119-178); ``run`` chains until a callback
returns STOP; ``run_fold`` threads an accumulator. Callbacks are
crash-isolated (safe_execute, emqx_hooks.erl:163-170): an exception
logs and the chain continues.

Hookpoint names follow the reference ('client.connected',
'message.publish', 'session.subscribed', ...).
"""

from __future__ import annotations

import logging
from typing import Any, Callable, Dict, List, NamedTuple, Optional, Tuple

log = logging.getLogger("emqx_tpu.hooks")

OK = "ok"
STOP = "stop"


class Callback(NamedTuple):
    action: Callable
    filter: Optional[Callable]
    priority: int
    seq: int


class Hooks:
    def __init__(self) -> None:
        self._chains: Dict[str, List[Callback]] = {}
        self._seq = 0

    def add(self, name: str, action: Callable, priority: int = 0,
            filter_: Optional[Callable] = None) -> None:
        self._seq += 1
        cb = Callback(action, filter_, priority, self._seq)
        chain = self._chains.setdefault(name, [])
        if any(c.action == action for c in chain):
            return  # already_exists (reference returns an error tuple)
        chain.append(cb)
        # higher priority first; stable on insertion order
        chain.sort(key=lambda c: (-c.priority, c.seq))

    def delete(self, name: str, action: Callable) -> None:
        chain = self._chains.get(name)
        if chain:
            self._chains[name] = [c for c in chain if c.action != action]

    def lookup(self, name: str) -> List[Callback]:
        return list(self._chains.get(name, ()))

    def run(self, name: str, args: Tuple = ()) -> None:
        """Run the chain; a callback returning STOP halts it
        (emqx_hooks.erl do_run/2:123-135)."""
        for cb in self._chains.get(name, ()):
            try:
                if cb.filter is not None and not cb.filter(*args):
                    continue
                if cb.action(*args) == STOP:
                    return
            except Exception:
                log.exception("hook %s callback failed", name)

    def run_fold(self, name: str, args: Tuple, acc: Any) -> Any:
        """Thread ``acc`` through the chain; callbacks return
        (OK|STOP, new_acc), a bare new acc, or None to leave it
        (emqx_hooks.erl do_run_fold/3:137-155)."""
        for cb in self._chains.get(name, ()):
            try:
                if cb.filter is not None and not cb.filter(*args, acc):
                    continue
                ret = cb.action(*args, acc)
            except Exception:
                log.exception("hook %s callback failed", name)
                continue
            if ret is None:
                continue
            if isinstance(ret, tuple) and len(ret) == 2 and ret[0] in (OK, STOP):
                acc = ret[1]
                if ret[0] == STOP:
                    return acc
            else:
                acc = ret
        return acc


_global = Hooks()


def global_hooks() -> Hooks:
    return _global
