"""Connect/disconnect flap detection → auto-ban
(reference: src/emqx_flapping.erl: threshold of state changes within
a window bans the clientid for ban_time)."""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, Optional

from emqx_tpu.banned import Banned


@dataclass
class FlappingConfig:
    max_count: int = 15          # disconnects within window
    window: float = 60.0         # seconds (detect_window)
    ban_time: float = 300.0      # seconds


@dataclass
class _Track:
    started: float = field(default_factory=time.time)
    count: int = 0


#: disconnect reasons the BROKER caused (drain redirect wave,
#: graceful node shutdown): they say nothing about the client's
#: stability, and counting them would let a rolling restart auto-ban
#: a well-behaved fleet — the ban replicates cluster-wide, so the
#: receiving peer would refuse the very reconnects the drain
#: redirected to it (docs/OPERATIONS.md; regression-pinned by
#: tests/test_drain.py)
SERVER_INITIATED = frozenset({"drained", "server_shutdown"})


class Flapping:
    def __init__(self, banned: Optional[Banned] = None,
                 config: Optional[FlappingConfig] = None,
                 metrics=None) -> None:
        self.banned = banned
        self.config = config or FlappingConfig()
        self.metrics = metrics
        self._tracks: Dict[str, _Track] = {}

    def connected(self, clientid: str, peerhost: str = "") -> None:
        pass  # tracked on disconnect (reference counts state changes)

    def disconnected(self, clientid: str, peerhost: str = "",
                     reason: Optional[str] = None) -> None:
        if reason in SERVER_INITIATED:
            return
        now = time.time()
        t = self._tracks.get(clientid)
        if t is None or now - t.started > self.config.window:
            t = _Track(started=now)
            self._tracks[clientid] = t
        t.count += 1
        if t.count >= self.config.max_count:
            del self._tracks[clientid]
            if self.banned is not None:
                # atomic check-and-create: never DOWNGRADE an
                # existing longer/permanent ban (e.g. an operator
                # rule) — the auto-ban replicates with live-create
                # overwrite semantics, so a short flapping ban would
                # replace it cluster-wide. The compare lives inside
                # Banned under its lock (a permanent ban applied
                # between a look_up and a create must win).
                self.banned.create_unless_outlasted(
                    "clientid", clientid, by="flapping",
                    reason=f"flapping: {t.count} in {self.config.window}s",
                    duration=self.config.ban_time)

    def gc(self, now: Optional[float] = None) -> None:
        now = time.time() if now is None else now
        for cid in [c for c, t in self._tracks.items()
                    if now - t.started > self.config.window]:
            del self._tracks[cid]
