"""Headline benchmark: publish→match→fan-out throughput on TPU.

Reproduces BASELINE.json config 2/3 (wildcard subscriptions over a
5-level topic tree, Zipf publish mix): builds a subscription trie of
``BENCH_SUBS`` filters (60% literal / 25% single-level ``+`` / 15%
multi-level ``#``), compiles the CSR automaton + fan-out tables to the
device, and measures steady-state matched publishes/sec through the
jitted NFA-walk + subscriber-gather pipeline.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "msgs/sec", "vs_baseline": N}

vs_baseline is measured against the north-star target of 1M publishes/
sec (BASELINE.md — the reference publishes no measured numbers, so the
target is the baseline).
"""

import json
import os
import random
import time

import numpy as np


def build_filters(rng, n_subs, words_per_level, levels=5):
    filters = set()
    vocab = [[f"w{lvl}_{i}" for i in range(words_per_level)]
             for lvl in range(levels)]
    while len(filters) < n_subs:
        depth = rng.randint(2, levels)
        ws = [rng.choice(vocab[i]) for i in range(depth)]
        r = rng.random()
        if r < 0.25:  # single-level '+'
            ws[rng.randrange(depth)] = "+"
        elif r < 0.40:  # multi-level '#'
            ws = ws[: rng.randint(1, depth)] + ["#"]
        filters.add("/".join(ws))
    return list(filters), vocab


def zipf_choice(rng, items, a=1.3):
    # Zipf-ish publish mix (BASELINE config 2)
    n = len(items)
    while True:
        k = int(rng.paretovariate(a)) - 1
        if k < n:
            return items[k]


def main():
    n_subs = int(os.environ.get("BENCH_SUBS", "1000000"))
    batch = int(os.environ.get("BENCH_BATCH", "8192"))
    iters = int(os.environ.get("BENCH_ITERS", "30"))
    k = int(os.environ.get("BENCH_K", "48"))
    m = int(os.environ.get("BENCH_M", "64"))
    d = int(os.environ.get("BENCH_D", "128"))
    levels = 5

    import jax

    from emqx_tpu.ops import native
    from emqx_tpu.ops.fanout import build_fanout, gather_subscribers
    from emqx_tpu.ops.match import match_batch

    rng = random.Random(0)
    t0 = time.time()
    filters, vocab = build_filters(rng, n_subs, words_per_level=60,
                                   levels=levels)
    use_native = native.available()
    if use_native:
        eng = native.NativeEngine()
        for i, f in enumerate(filters):
            eng.insert(f, i)
        auto = eng.flatten()
        encode = eng.encode_batch
    else:
        from emqx_tpu.oracle import TrieOracle
        from emqx_tpu.ops.csr import build_automaton
        from emqx_tpu.ops.tokenize import WordTable, encode_batch as _eb
        trie = TrieOracle()
        table = WordTable()
        fids = {}
        for f in filters:
            trie.insert(f)
            fids[f] = len(fids)
            for w in f.split("/"):
                table.intern(w)
        auto = build_automaton(trie, fids, table)
        encode = lambda ts, L: _eb(table, ts, L)  # noqa: E731
    # one subscriber per subscription (10M-sub scale is sub-id bitmaps
    # over the same CSR; bench config keeps 1:1)
    fan = build_fanout({i: [i] for i in range(len(filters))}, len(filters))
    build_s = time.time() - t0

    auto = jax.device_put(auto)
    fan = jax.device_put(fan)

    # publish batches: Zipf over the filter tree's own vocabulary
    n_batches = 8
    batches = []
    for _ in range(n_batches):
        topics = [
            "/".join(zipf_choice(rng, vocab[i])
                     for i in range(rng.randint(2, levels)))
            for _ in range(batch)
        ]
        batches.append(encode(topics, 16))

    def step(ids, n, sysm):
        res = match_batch(auto, ids, n, sysm, k=k, m=m)
        subs, dcount, dovf = gather_subscribers(fan, res.ids, d=d)
        return res.count, dcount, res.overflow | dovf

    # warmup / compile
    out = step(*batches[0])
    jax.block_until_ready(out)

    # The chip is reached through a shared tunnel with transient
    # stalls, so one long timing window is unstable (observed 5x
    # swings run-to-run). Time several independent windows and report
    # the median window throughput.
    windows = max(1, int(os.environ.get("BENCH_WINDOWS", "5")))
    rates = []
    outs = None
    for w in range(windows):
        t1 = time.time()
        outs = []
        for i in range(iters):
            outs.append(step(*batches[i % n_batches]))
        jax.block_until_ready(outs)
        rates.append(batch * iters / (time.time() - t1))
    throughput = float(np.median(rates))
    total_msgs = batch * iters
    counts = np.asarray(outs[0][0])
    deliv = np.asarray(outs[0][1])
    ovf = sum(int(np.asarray(o[2]).sum()) for o in outs)
    info = {
        "subs": len(filters),
        "batch": batch,
        "native": use_native,
        "build_s": round(build_s, 1),
        "avg_matches_per_msg": round(float(counts.mean()), 2),
        "avg_deliveries_per_msg": round(float(deliv.mean()), 2),
        "overflow_frac": round(ovf / total_msgs, 6),
        "device": str(jax.devices()[0]),
        "window_mmsgs": [round(r / 1e6, 2) for r in rates],
    }
    import sys
    print(json.dumps(info), file=sys.stderr, flush=True)
    print(json.dumps({
        "metric": "publish_match_fanout_throughput",
        "value": round(throughput, 1),
        "unit": "msgs/sec",
        "vs_baseline": round(throughput / 1_000_000, 3),
    }), flush=True)


if __name__ == "__main__":
    main()
