"""Headline benchmark: publish→match→fan-out throughput on TPU.

Reproduces BASELINE.json config 2/3 (wildcard subscriptions over a
5-level topic tree, Zipf publish mix): builds a subscription trie of
``BENCH_SUBS`` filters (60% literal / 25% single-level ``+`` / 15%
multi-level ``#``), compiles the CSR automaton + fan-out tables to the
device, and measures steady-state matched publishes/sec through the
jitted NFA-walk + subscriber-gather pipeline.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "msgs/sec", "vs_baseline": N}

vs_baseline is measured against the north-star target of 1M publishes/
sec (BASELINE.md — the reference publishes no measured numbers, so the
target is the baseline).
"""

import json
import os
import random
import time

import numpy as np


class BenchInitError(RuntimeError):
    """Backend initialization failed/hung — distinguishes a chip-
    unreachable condition (eligible for the labeled CPU fallback)
    from genuine workload bugs, which must surface as errors."""


def _jax_with_retry(tries: int = None, delay: float = 8.0,
                    attempt_timeout: float = None):
    """Initialize the JAX backend with bounded retry/backoff.

    The chip is reached through a shared tunnel; round-1's official
    bench run died on a transient 'Unable to initialize backend'
    error (and the dryrun on an init *hang*). Each attempt runs the
    first device query on a daemon thread with a timeout, so a wedged
    tunnel becomes a retryable failure instead of an rc=124.

    ``BENCH_PLATFORM`` (e.g. ``cpu``) overrides the platform before
    init — the environment's sitecustomize pins ``jax_platforms`` to
    the TPU plugin, so a plain env var cannot.
    """
    import queue
    import threading

    import jax

    if tries is None:
        tries = int(os.environ.get("BENCH_INIT_TRIES", "3"))
    if attempt_timeout is None:
        attempt_timeout = float(os.environ.get("BENCH_INIT_TIMEOUT", "150"))
    plat = os.environ.get("BENCH_PLATFORM")
    if plat:
        jax.config.update("jax_platforms", plat)
    from emqx_tpu.profiling import enable_compile_cache
    enable_compile_cache()
    deadline = time.monotonic() + attempt_timeout
    attempt = 0
    while True:
        attempt += 1
        q: "queue.Queue" = queue.Queue()
        threading.Thread(
            target=lambda: q.put(_try_devices(jax)), daemon=True).start()
        got = None
        while time.monotonic() < deadline:
            try:
                got = q.get(timeout=min(
                    5.0, max(0.1, deadline - time.monotonic())))
                break
            except queue.Empty:
                continue
        if got is None:
            # a hung init thread still holds jax's global backend
            # lock: further in-process attempts (and clear_backends)
            # would block on it, so give up for the whole process
            raise BenchInitError(
                f"backend init hung > {attempt_timeout:.0f}s total")
        ok, res = got
        if ok:
            return jax
        if attempt >= tries:
            # `from res` keeps the real init traceback in the
            # fail-soft record's stderr dump
            raise BenchInitError(
                f"backend init failed: {res!r}") from res
        try:
            from jax.extend.backend import clear_backends
            clear_backends()
        except Exception:
            pass
        wait = min(delay * (2 ** (attempt - 1)), 60.0)
        print(f"jax init attempt {attempt}/{tries} failed: {res!r}; "
              f"retrying in {wait:.0f}s", flush=True)
        time.sleep(wait)


def _try_devices(jax):
    try:
        jax.devices()
        return (True, None)
    except Exception as e:
        return (False, e)


def _first_leaf(out):
    import jax as _jax

    return _jax.tree_util.tree_leaves(out)[0]


TPU_LAST_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "BENCH_TPU_LAST.json")

#: the headline metric shared by the configs aggregate and solo mixed
#: mode — the one metric where staging must distinguish the two
_MATRIX_METRIC = "publish_match_fanout_throughput"

#: aggregate fields lifted from the headline config row — one list
#: shared by the emit path, the merge-inherit path, and the fallback
#: cpu_ relabeling
_HEADLINE_FIELDS = ("value", "vs_baseline", "p50_batch_ms",
                    "p99_batch_ms")


def _good_row(r: dict) -> bool:
    """A config row that carries a real measurement — the single
    definition shared by merge, resume, and the probe loop's
    completeness check (they must never disagree on 'done')."""
    return r.get("value") is not None and "error" not in r


def _merge_staged_configs(prev: dict, rec: dict) -> dict:
    """Row-level merge of a new aggregate into the staged one: a row
    that errored in THIS run (tunnel wedged mid-matrix — the round-4
    failure mode: 2 rows landed, then 6 init-hangs) inherits the
    prior staged row's good measurement instead of erasing it. Good
    new rows always win; `carried_ts` marks inherited ones. (Solo-
    mode records stage under a separate ":solo" key — see
    _stage_tpu_record — so prev and rec either both carry configs or
    the merge is a no-op.)"""
    if not (prev and prev.get("configs") and rec.get("configs")):
        return rec
    cur_specs = {name: _row_spec(name, extra, mode, subs_tpu)
                 for name, extra, mode, subs_tpu, _ in _CONFIG_MATRIX}

    def _ts_of(old: dict) -> str:
        # original measurement time survives reuse cycles: carried_ts
        # may have been folded into measured_ts by the resume path
        return old.get("carried_ts", old.get(
            "measured_ts", prev.get("ts", "unknown")))

    def _inheritable(old: dict) -> bool:
        # same spec rule as resume reuse: a row measured under an
        # edited matrix spec must not satisfy the current one
        # (rows missing "spec" predate stamping — accepted, same
        # grace the resume path grants)
        cur = cur_specs.get(old.get("name"))
        return cur is None or old.get("spec", cur) == cur

    def _inherit(old: dict) -> dict:
        row = dict(old, carried_ts=_ts_of(old))
        # same once-only pre-spec grace as resume reuse: record the
        # acceptance so it expires on re-staging (resume stamps at
        # reuse time; the merge paths must not re-grant it forever)
        cur = cur_specs.get(row.get("name"))
        if cur is not None:
            row.setdefault("spec", cur)
        return row

    prior = {r.get("name"): r for r in prev["configs"] if _good_row(r)}
    merged = []
    for row in rec["configs"]:
        old = prior.pop(row.get("name"), None)
        if not _good_row(row) and old is not None and _inheritable(old):
            row = _inherit(old)
        merged.append(row)
    # staged good rows the new record doesn't even mention (matrix
    # reshuffle, partial record) stay — evidence is never dropped;
    # the completeness check keys off the CURRENT matrix, so orphan
    # rows are inert
    for old in prior.values():
        merged.append(_inherit(old))
    # resume-cycle presentation flags must not persist as artifact
    # state (a re-staged reused row is not "reused" in the artifact)
    merged = [{k: v for k, v in r.items() if k != "reused_staged"}
              for r in merged]
    rec = dict(rec, configs=merged)
    # top-level headline fields follow the (possibly inherited)
    # headline row: a run whose headline failed but measured OTHER
    # rows must stage those without nulling the aggregate value
    head = next((r for r in merged if r.get("name") == _HEADLINE_ROW),
                None)
    if rec.get("value") is None and head is not None and _good_row(head):
        for fld in _HEADLINE_FIELDS:
            if fld in head:
                rec[fld] = head[fld]
        rec["headline_carried_ts"] = head.get(
            "carried_ts", prev.get("ts", "unknown"))
    # the p99_deliver keys ride the live_paced row the same way: a
    # run that skipped/errored that row (BENCH_ONLY refresh, deadline)
    # must re-derive them from the merged (inherited) row instead of
    # erasing them from the aggregate
    live = next((r for r in merged if r.get("name") == "live_paced"),
                None)
    if rec.get("p99_deliver_ms") is None and live is not None \
            and _good_row(live) and "p99_deliver_ms" in live:
        rec["p99_deliver_ms"] = live["p99_deliver_ms"]
        rec["p99_deliver_platform"] = live.get("platform", "unknown")
    return rec


def _stage_tpu_record(rec: dict):
    """Merge ``rec`` into the last-good TPU artifact under its metric
    key and return the staged (merged, ts-stamped) record — or None
    when persistence failed. A failed run never erases prior
    evidence: errored rows and a failed headline inherit the staged
    measurements via _merge_staged_configs. Swallows everything:
    persistence must never break the bench line."""
    try:
        existing = {}
        if os.path.exists(TPU_LAST_PATH):
            with open(TPU_LAST_PATH) as f:
                existing = json.load(f)
        # a solo mixed-mode run (same metric as the matrix aggregate,
        # no configs array) is staged under its own ":solo" slot: its
        # workload shape is operator-chosen (BENCH_SUBS=anything), so
        # it must neither erase the matrix aggregate nor have its
        # fresher top-level value clobbered by a later resume window
        # reusing matrix rows. Named modes have distinct metrics and
        # stage unqualified.
        key = rec["metric"]
        if key == _MATRIX_METRIC and not rec.get("configs"):
            key += ":solo"
        rec = _merge_staged_configs(existing.get(key), rec)
        staged = dict(rec, ts=time.strftime("%Y-%m-%dT%H:%M:%S%z"))
        existing[key] = staged
        tmp = TPU_LAST_PATH + ".tmp"
        with open(tmp, "w") as f:
            json.dump(existing, f, indent=1, sort_keys=True)
        os.replace(tmp, TPU_LAST_PATH)
        return staged
    except Exception:
        return None


#: row provenance (ISSUE 7 satellite): every emitted row carries the
#: matcher configuration that produced it — `walk_mode`
#: (narrow/wide compressed walk), the settled active-set `k`
#: (configured + learned boosts at emit time), the trie `builder`
#: (native C++ vs python), and whether the `delta` automaton was
#: live. Stale staged rows become *detectable* (e.g. a pre-
#: compressed-walk `hash_1m_deep` row shows walk_mode narrow where
#: the current tree would stamp wide) instead of silently riding
#: along. Modes call `_set_prov(router)` once their router settles.
_PROV: dict = {}


def _set_prov(router) -> None:
    global _PROV
    try:
        slots = router._walk_meta.get("slots", 2)
        _PROV = {
            "walk_mode": "wide" if slots == 4 else "narrow",
            "settled_k": int(router.effective_k()),
            "builder": ("native" if router._native is not None
                        else "python"),
            "delta": bool(router.config.delta
                          and router.config.mesh is None),
        }
    except Exception:
        _PROV = {}


def _emit(rec: dict) -> None:
    """Print the headline JSON line; when the run executed on a real
    accelerator (not the CPU fallback), persist it into the last-good
    TPU artifact so a chip that wedges later can't erase the
    evidence (VERDICT r2: a CPU fallback once impersonated a TPU
    number because nothing staged successful runs).

    ``BENCH_NO_STAGE`` suppresses staging: the configs orchestrator's
    children all report the shared headline metric under different
    workload shapes, and a child staging directly could impersonate
    the headline if the parent dies mid-matrix."""
    for k, v in _PROV.items():
        rec.setdefault(k, v)
    try:
        import jax as _jax

        plat = _jax.default_backend()
    except Exception:
        plat = "unknown"
    rec["platform"] = plat
    if plat not in ("cpu", "unknown") and rec.get("value") is not None \
            and not os.environ.get("BENCH_NO_STAGE"):
        _stage_tpu_record(rec)
    print(json.dumps(rec), flush=True)


def _last_good_tpu(metric: str):
    try:
        with open(TPU_LAST_PATH) as f:
            return json.load(f).get(metric)
    except Exception:
        return None


def _latency_pass(step, batches, iters: int = 20):
    """p50/p99 per-batch latency (ms): run ``step`` synchronously,
    forcing completion with a result READBACK per call. On this
    environment's tunneled chip, ``block_until_ready`` returns before
    the device has actually finished — only a device→host transfer of
    the output is a true completion barrier, so every latency (and
    throughput) sample here ends in one."""
    lat = []
    for i in range(iters):
        t = time.perf_counter()
        np.asarray(_first_leaf(step(*batches[i % len(batches)])))
        lat.append((time.perf_counter() - t) * 1000.0)
    return (float(np.percentile(lat, 50)), float(np.percentile(lat, 99)))


def _throughput_windows(step, batches, windows, iters):
    """Median window throughput in batches/sec, honestly: each window
    dispatches ``iters`` steps and ends with a readback of the LAST
    output — a data dependency that forces every dispatched step to
    complete inside the timed window (block_until_ready is NOT a
    completion barrier through the tunnel). The first readback of a
    process carries a large one-time finalization cost, so one
    warm-up readback happens before timing."""
    np.asarray(_first_leaf(step(*batches[0])))  # absorb first-read cost
    rates = []
    outs = None
    for _ in range(windows):
        t0 = time.perf_counter()
        outs = [step(*batches[i % len(batches)]) for i in range(iters)]
        np.asarray(_first_leaf(outs[-1]))
        rates.append(iters / (time.perf_counter() - t0))
    return float(np.median(rates)), rates, outs


from emqx_tpu.utils.batch import dedup_topics  # noqa: E402


def build_filters(rng, n_subs, words_per_level, levels=5, mix="mixed"):
    """Subscription filters per BASELINE config shape: ``mix`` is
    "mixed" (60/25/15 literal/`+`/`#` — configs 2+3 blended),
    "literal" (config 1), "plus" (config 2) or "hash" (config 3)."""
    filters = set()
    vocab = [[f"w{lvl}_{i}" for i in range(words_per_level)]
             for lvl in range(levels)]
    lo = 1 if levels == 1 else 2
    while len(filters) < n_subs:
        depth = rng.randint(lo, levels)
        ws = [rng.choice(vocab[i]) for i in range(depth)]
        if mix == "mixed":
            r = rng.random()
            if r < 0.25:  # single-level '+'
                ws[rng.randrange(depth)] = "+"
            elif r < 0.40:  # multi-level '#'
                ws = ws[: rng.randint(1, depth)] + ["#"]
        elif mix == "plus":
            ws[rng.randrange(depth)] = "+"
        elif mix == "hash":
            ws = ws[: rng.randint(1, depth)] + ["#"]
        elif mix != "literal":
            raise ValueError(f"unknown filter mix {mix!r}")
        filters.add("/".join(ws))
    return list(filters), vocab


#: bump when BUILD SEMANTICS change (build_filters mix ratios,
#: zipf_choice shape, dedup, encode levels, depth_bucket) — the cache
#: key only sees shapes, so an unbumped semantic change would silently
#: replay the previous round's workload under the new label
_BUILD_REV = 1


def _build_cache_dir():
    """Cache root (BENCH_BUILD_CACHE=0 disables, =<dir> relocates).
    Footprint warning: the full matrix is ~2.7GB (the 10M row alone
    >1GB) — point this at real disk, not a RAM-backed tmpfs."""
    d = os.environ.get("BENCH_BUILD_CACHE", "/tmp/emqx_bench_cache")
    return None if d == "0" else d


def _build_cache_load(key: str):
    """Host-array build cache: the big-subs builds (filters, trie
    insert, flatten, batch encode) cost minutes of pure-host work
    that is IDENTICAL run to run (seeded rng). Caching the device
    inputs makes a TPU-recovery matrix far more likely to fit its
    row budget. Returns the array dict or None. Opt-out:
    BENCH_BUILD_CACHE=0 (=<dir> relocates)."""
    d = _build_cache_dir()
    if d is None:
        return None
    try:
        return dict(np.load(os.path.join(d, key + ".npz"),
                            allow_pickle=False))
    except Exception:
        return None


def _build_cache_save(key: str, arrs: dict) -> None:
    d = _build_cache_dir()
    if d is None:
        return
    tmp = None
    try:
        os.makedirs(d, exist_ok=True)
        # pid-unique tmp: a prewarm and a recovery bench may build
        # the same key concurrently; sharing one tmp name would let
        # them corrupt each other's half-written file
        tmp = os.path.join(d, f"{key}.{os.getpid()}.tmp.npz")
        np.savez(tmp, **arrs)
        os.replace(tmp, os.path.join(d, key + ".npz"))
    except Exception:
        # cache is best-effort — but a half-written tmp must not
        # squat multi-hundred-MB of the cache volume (ENOSPC is
        # self-reinforcing otherwise)
        if tmp is not None:
            try:
                os.unlink(tmp)
            except OSError:
                pass


def build_main_inputs(n_subs: int, batch: int, levels: int, mix: str,
                      traffic: str, wpl: int, n_batches: int = 8):
    """The main-mode host build — filters, automaton, fan table and
    8 encoded publish batches — through the array cache (a pure
    function of the seeded rng, so a cache hit is exact). JAX-free:
    ``scripts/prewarm_bench_cache.py`` runs this without touching any
    backend to pre-stage the TPU-recovery rows. Returns
    ``(use_native, cached, auto, fan, host_batches, uniques,
    n_filters, topic_lists)`` — ``topic_lists`` is each batch's
    unique-topic strings (the match-cache rows key on them; artifacts
    written before the field existed miss on load and rebuild)."""
    import random as _random

    from emqx_tpu.ops import native
    from emqx_tpu.ops.csr import Automaton
    from emqx_tpu.ops.fanout import FanoutTable, build_fanout
    from emqx_tpu.ops.match import depth_bucket

    use_native = native.available()
    # key carries a schema version + which engine built the arrays:
    # a field added next round or a native/python provenance mix must
    # miss, not crash or mislabel the measurement. The cache stores
    # only the CSR flatten artifact (v1 fields) — the v2 walk tables
    # (compression, hashing) are a deterministic post-pass re-derived
    # on load, so a kernel-layout change never invalidates the
    # minutes-long host build.
    cache_key = (f"mixed_v2r{_BUILD_REV}"
                 f"_{'nat' if use_native else 'py'}"
                 f"_s{n_subs}_b{batch}_l{levels}_{mix}_{traffic}"
                 f"_w{wpl}_n{n_batches}")
    _V1_FIELDS = ("row_ptr", "edge_word", "edge_child", "plus_child",
                  "hash_filter", "end_filter", "n_states", "n_edges")
    cached = _build_cache_load(cache_key)
    if cached is not None:
        try:
            from emqx_tpu.ops.csr import finalize_automaton
            auto = Automaton(**{
                f: (cached[f"a_{f}"] if f"a_{f}" in cached
                    else int(cached[f"s_{f}"]))
                for f in _V1_FIELDS})
            auto = finalize_automaton(auto)
            fan = FanoutTable(**{
                f: (cached[f"f_{f}"] if f"f_{f}" in cached
                    else (int(cached[f"fs_{f}"]) if f"fs_{f}" in cached
                          else None))
                for f in FanoutTable._fields})
            host_batches = [
                (cached[f"b{i}_ids"], cached[f"b{i}_n"],
                 cached[f"b{i}_sysm"].astype(bool))
                for i in range(n_batches)]
            topic_lists = [cached[f"b{i}_topics"].tolist()
                           for i in range(n_batches)]
            uniques = [int(u) for u in cached["uniques"]]
            n_filters = int(cached["n_filters"])
            return (use_native, True, auto, fan, host_batches,
                    uniques, n_filters, topic_lists)
        except Exception:
            pass  # schema-drifted file: fall through to a rebuild

    rng = _random.Random(0)
    filters, vocab = build_filters(rng, n_subs, words_per_level=wpl,
                                   levels=levels, mix=mix)
    if use_native:
        eng = native.NativeEngine()
        for i, f in enumerate(filters):
            eng.insert(f, i)
        auto = eng.flatten()
        encode = eng.encode_batch
    else:
        insert, flatten, encode = _python_engine()
        for i, f in enumerate(filters):
            insert(f, i)
        auto = flatten()
    # one subscriber per subscription (10M-sub scale is sub-id
    # bitmaps over the same CSR; bench config keeps 1:1)
    fan = build_fanout({i: [i] for i in range(len(filters))},
                       len(filters))
    n_filters = len(filters)

    # publish batches: `batch` LOGICAL messages each, Zipf over the
    # filter tree's own vocabulary, deduplicated to unique topics
    # before the device (the product ingress does the same per tick —
    # hot topics collapse; throughput counts logical messages, and
    # per-unique rates are reported alongside)
    host_batches = []
    uniques = []
    topic_lists = []
    lo = 1 if levels == 1 else 2
    pick = (zipf_choice if traffic == "zipf"
            else lambda r, items: r.choice(items))
    for _ in range(n_batches):
        topics = [
            "/".join(pick(rng, vocab[i])
                     for i in range(rng.randint(lo, levels)))
            for _ in range(batch)
        ]
        uniq, _inv = dedup_topics(topics)
        uniques.append(len(uniq))
        topic_lists.append(uniq)
        ids_, n_, sysm_ = encode(uniq, 16)
        ids_, n_ = depth_bucket(ids_, n_)
        host_batches.append((ids_, n_, sysm_))
    arrs = {"uniques": np.asarray(uniques, np.int64),
            "n_filters": np.int64(n_filters)}
    for f, v in zip(Automaton._fields, auto):
        if f not in _V1_FIELDS:
            continue  # walk tables re-derive from the flatten on load
        arrs[f"a_{f}" if isinstance(v, np.ndarray) else f"s_{f}"] = v
    for f, v in zip(FanoutTable._fields, fan):
        if isinstance(v, np.ndarray):
            arrs[f"f_{f}"] = v
        elif v is not None:
            arrs[f"fs_{f}"] = np.int64(v)
    for i, (ids_, n_, sysm_) in enumerate(host_batches):
        arrs[f"b{i}_ids"] = ids_
        arrs[f"b{i}_n"] = n_
        arrs[f"b{i}_sysm"] = sysm_
        # unicode array, not object dtype: the cache loads with
        # allow_pickle=False
        arrs[f"b{i}_topics"] = np.asarray(topic_lists[i])
    _build_cache_save(cache_key, arrs)
    return (use_native, False, auto, fan, host_batches, uniques,
            n_filters, topic_lists)


def _python_engine():
    """(insert, flatten, encode) on the pure-Python builder — the
    toolchain-less fallback shared by main() and shared()."""
    from emqx_tpu.oracle import TrieOracle
    from emqx_tpu.ops.csr import build_automaton
    from emqx_tpu.ops.tokenize import WordTable
    from emqx_tpu.ops.tokenize import encode_batch as _eb

    trie, table, fids = TrieOracle(), WordTable(), {}

    def insert(f, i):
        trie.insert(f)
        fids[f] = i
        for w in f.split("/"):
            table.intern(w)

    def flatten():
        return build_automaton(trie, fids, table)

    def encode(topics, max_levels):
        return _eb(table, topics, max_levels)

    return insert, flatten, encode


def zipf_choice(rng, items, a=1.3):
    # Zipf-ish publish mix (BASELINE config 2)
    n = len(items)
    while True:
        k = int(rng.paretovariate(a)) - 1
        if k < n:
            return items[k]


def bigfan():
    """BENCH_MODE=bigfan — the >1024-subscriber sharded-topic regime
    (BASELINE config 5 scale): huge per-filter subscriber sets stored
    as bitmap rows; fan-out = Pallas OR-streaming kernel
    (emqx_tpu.ops.bitmap). Reports effective deliveries/sec."""
    import time as _t

    jax = _jax_with_retry()
    import jax.numpy as jnp

    from emqx_tpu.ops.bitmap import (or_bitmaps_dma, or_bitmaps_xla,
                                     words_for)

    n_subs = int(os.environ.get("BENCH_SUBS", "10000000"))
    n_big = int(os.environ.get("BENCH_BIG", "64"))
    B = int(os.environ.get("BENCH_BATCH", "256"))
    mb = int(os.environ.get("BENCH_MB", "8"))
    iters = int(os.environ.get("BENCH_ITERS", "20"))
    windows = max(1, int(os.environ.get("BENCH_WINDOWS", "5")))
    density = float(os.environ.get("BENCH_DENSITY", "0.05"))

    rng = np.random.default_rng(0)
    W = words_for(n_subs)
    # random member masks at the target density (building 64 x 10M-bit
    # rows via explicit id lists would just bench numpy). Only real
    # subscriber positions < n_subs get bits — the pow2 pad region
    # stays zero, exactly as build_bitmaps leaves it — and rows are
    # generated one at a time in float32 to bound host RAM
    bitmaps = np.zeros((n_big, W), dtype=np.uint32)
    for r in range(n_big):
        bits = (rng.random(n_subs, dtype=np.float32) < density)
        packed = np.packbits(bits, bitorder="little")
        packed = np.pad(packed, (0, W * 4 - packed.size))
        bitmaps[r] = packed.view(np.uint32)
    rows = np.full((B, mb), -1, np.int32)
    for b in range(B):
        k = rng.integers(1, mb + 1)
        rows[b, :k] = rng.choice(n_big, size=k, replace=False)
    bm = jax.device_put(bitmaps)
    rows_d = jax.device_put(rows)

    # the timed step reduces to per-topic counts on device: holding
    # iters x [B, W] fan-out bitmaps in the async queue exhausts HBM
    # at 10M subs (2 MB per topic row). Per-topic popcounts fit int32
    # (<= W*32 bits < 2^31); the batch total sums on the host in
    # int64 — jnp int64 would be silently demoted without x64
    # Pallas manual-DMA on real accelerators; XLA gather-OR on the
    # CPU fallback (interpret-mode Pallas there measures nothing)
    or_fn = (or_bitmaps_dma
             if jax.default_backend() in ("tpu", "axon")
             else or_bitmaps_xla)
    step = jax.jit(lambda b_, r_: jnp.sum(
        jax.lax.population_count(or_fn(b_, r_)),
        axis=1, dtype=jnp.int32))
    jax.block_until_ready(step(bm, rows_d))  # compile
    batches_per_s, rates, outs = _throughput_windows(
        step, [(bm, rows_d)], windows, iters)
    deliveries_per_batch = int(
        np.asarray(outs[-1]).astype(np.int64).sum())
    deliveries_per_s = batches_per_s * deliveries_per_batch
    p50, p99 = _latency_pass(step, [(bm, rows_d)], iters=10)
    import sys
    print(json.dumps({
        "mode": "bigfan", "subs": n_subs, "big_filters": n_big,
        "batch": B, "deliveries_per_batch": deliveries_per_batch,
        "device": str(jax.devices()[0]),
        "window_batches": [round(r, 1) for r in rates],
    }), file=sys.stderr, flush=True)
    _emit({
        "metric": "bigfan_bitmap_deliveries",
        "value": round(deliveries_per_s, 1),
        "unit": "deliveries/sec",
        # north star counts 1M msgs/s; one delivery >= one matched msg
        "vs_baseline": round(deliveries_per_s / 1_000_000, 3),
        "p50_batch_ms": round(p50, 3),
        "p99_batch_ms": round(p99, 3),
    })


def shared():
    """BENCH_MODE=shared — BASELINE config 4: $share/<group>
    load-balanced dispatch at 1M shared subscribers, in ONE fused
    device step: match over the batch's UNIQUE topics (hot topics
    collapse exactly as the main publish path dedups), a device
    inverse-index gather expands match ids back to per-message rows,
    then the hash-strategy group pick draws per MESSAGE
    (ops.fanout.pick_shared — per-message semantics preserved, the
    reference picks per publish, src/emqx_shared_sub.erl:229-275)."""
    import time as _t

    jax = _jax_with_retry()
    import jax.numpy as jnp

    from emqx_tpu.ops import native
    from emqx_tpu.ops.csr import device_view
    from emqx_tpu.ops.fanout import build_fanout, pick_shared
    from emqx_tpu.ops.match import depth_bucket, match_batch, walk_params

    n_subs = int(os.environ.get("BENCH_SUBS", "1000000"))
    n_groups = int(os.environ.get("BENCH_GROUPS", "1000"))
    batch = int(os.environ.get("BENCH_BATCH", "65536"))
    iters = int(os.environ.get("BENCH_ITERS", "20"))
    windows = max(1, int(os.environ.get("BENCH_WINDOWS", "5")))
    k = int(os.environ.get("BENCH_K", "8"))
    m = int(os.environ.get("BENCH_M", "16"))
    levels = 5

    rng = random.Random(0)
    t0 = time.time()
    # one shared filter per group; members spread evenly (the
    # reference stores {group, topic} -> member rows the same way)
    filters, vocab = build_filters(rng, n_groups, words_per_level=60,
                                   levels=levels)
    if native.available():
        eng = native.NativeEngine()
        insert, flatten, encode = eng.insert, eng.flatten, \
            eng.encode_batch
    else:
        # toolchain-less host: the Python builder (slower build, same
        # device program — the row must not error out of the matrix)
        insert, flatten, encode = _python_engine()
    rows = {}
    per = n_subs // n_groups
    for i, f in enumerate(filters):
        insert(f, i)
        rows[i] = range(i * per, (i + 1) * per)
    host_auto = flatten()
    fan = build_fanout(rows, len(filters))
    build_s = time.time() - t0

    auto = jax.device_put(device_view(host_auto))
    fan = jax.device_put(fan)
    batches = []
    uniques = []
    seed_rng = np.random.default_rng(1)
    for _ in range(8):
        topics = ["/".join(zipf_choice(rng, vocab[i])
                           for i in range(rng.randint(2, levels)))
                  for _ in range(batch)]
        uniq, inv = dedup_topics(topics)
        uniques.append(len(uniq))
        ids_, n_, sysm_ = encode(uniq, 16)
        ids_, n_ = depth_bucket(ids_, n_)
        inv_ = np.asarray(inv, dtype=np.int32)
        seeds = seed_rng.integers(0, 2**31 - 1, size=batch,
                                  dtype=np.int32)
        batches.append(jax.device_put((ids_, n_, sysm_, inv_, seeds)))

    def step(ids, n, sysm, inv, seeds):
        res = match_batch(auto, ids, n, sysm, k=k, m=m,
                          **walk_params(host_auto, ids.shape[1]))
        # unique-topic match ids -> per-message rows: ONE [B, M]
        # gather, then the per-message member draw
        ids_full = res.ids[inv]
        picks = pick_shared(fan, ids_full, seeds)
        return jnp.sum(picks >= 0, dtype=jnp.int32), res.overflow

    for b_ in batches:  # one compile per distinct unique-shape bucket
        jax.block_until_ready(step(*b_))
    batches_per_s, rates_b, outs = _throughput_windows(
        step, batches, windows, iters)
    throughput = batches_per_s * batch
    rates = [r * batch for r in rates_b]
    picked = int(outs[0][0])
    p50, p99 = _latency_pass(step, batches)
    import sys
    print(json.dumps({
        "mode": "shared", "subs": n_subs, "groups": n_groups,
        "batch": batch, "build_s": round(build_s, 1),
        "avg_unique_topics": round(float(np.mean(uniques)), 1),
        "picks_per_batch": picked,
        "device": str(jax.devices()[0]),
        "window_mmsgs": [round(r / 1e6, 2) for r in rates],
    }), file=sys.stderr, flush=True)
    _emit({
        "metric": "shared_dispatch_throughput",
        # the round-5 walk rewrite redefines the device program: a
        # staged pre-rewrite record must not satisfy this mode
        "workload": "walkv2",
        "value": round(throughput, 1),
        "unit": "msgs/sec",
        "vs_baseline": round(throughput / 1_000_000, 3),
        "p50_batch_ms": round(p50, 3),
        "p99_batch_ms": round(p99, 3),
    })


def main():
    n_subs = int(os.environ.get("BENCH_SUBS", "1000000"))
    batch = int(os.environ.get("BENCH_BATCH", "131072"))
    iters = int(os.environ.get("BENCH_ITERS", "20"))
    # active-set capacity: adaptive like the product (Router.boost_k).
    # Start narrow — gather volume scales with k, and the round-4 A/B
    # measured k=4 at +33% (headline) / +61% (16-level hash, zero
    # overflow) vs the old fixed 8 — then grow once if the warmup
    # shows the product's boost threshold (>1/8 of unique rows
    # match-overflowed: the 10M-sub trie is dense enough to need 8).
    # BENCH_K pins it for A/B.
    k_env = os.environ.get("BENCH_K")
    k = int(k_env) if k_env else 4
    m = int(os.environ.get("BENCH_M", "64"))
    d = int(os.environ.get("BENCH_D", "32"))
    # BASELINE-config shape knobs (the `configs` orchestrator drives
    # these; defaults reproduce the historical blended workload)
    levels = int(os.environ.get("BENCH_LEVELS", "5"))
    mix = os.environ.get("BENCH_MIX", "mixed")
    traffic = os.environ.get("BENCH_TRAFFIC", "zipf")
    wpl = int(os.environ.get("BENCH_WPL", "60"))

    jax = _jax_with_retry()

    from emqx_tpu.ops.csr import device_view
    from emqx_tpu.ops.fanout import expand_packed
    from emqx_tpu.ops.match import match_batch, walk_params
    from emqx_tpu.ops.pack import budget_for, pack_matches

    t0 = time.time()
    use_native, cached, host_auto, fan, host_batches, uniques, \
        n_filters, topic_lists = build_main_inputs(
            n_subs, batch, levels, mix, traffic, wpl)
    build_s = time.time() - t0

    # the walk's k bound follows the trie's algebra: no '+' edges ⇒
    # the active set is provably ≤1 lane (the adaptive boost below
    # still covers any workload the bound mis-sizes)
    has_plus = bool(
        (np.asarray(host_auto.node2)[:max(host_auto.v2_states, 1), 0]
         >= 0).any())
    if k_env is None and not has_plus:
        k = 1

    # device_put once — the steady-state path matches device-resident
    # arrays produced by the ingress batcher, and re-shipping numpy
    # per step would time the host link, not the kernel. Only the
    # walkable tables ship (the CSR flatten artifact stays on host).
    auto = jax.device_put(device_view(host_auto))
    fan = jax.device_put(fan)
    batches = [jax.device_put(b) for b in host_batches]

    # the PRODUCT pipeline: match → pack → fused sparse expansion
    # (broker.publish_begin runs exactly this); budgets start sized
    # off the batch and then SHRINK to the warmup's observed totals —
    # the broker's learned buckets work the same way (grow on
    # overflow, so steady state runs the fitting bucket). The packed
    # buffers' cummax/gather costs scale with the BUDGET, not the
    # actual traffic, so a worst-case budget taxes every batch.
    bucket_rows = max(b[0].shape[0] for b in batches)
    PM = budget_for(bucket_rows, max(8, k))
    Q = budget_for(bucket_rows, int(os.environ.get("BENCH_PACKQ", "16")))

    # BENCH_CACHE=1 — the product's epoch-guarded publish match
    # cache in front of the walk (ops/match_cache.py): per batch,
    # probe the unique topics, walk ONLY the misses (pack_ids=True —
    # fixed-width rows the cache stores), merge hits from HBM, insert
    # fresh rows. The cache-off rows keep the raw-kernel pipeline
    # above byte-for-byte, so on/off pairs isolate the cache's win.
    use_cache = os.environ.get("BENCH_CACHE") == "1"
    cache = None
    if use_cache:
        from emqx_tpu.ops.match_cache import MatchCache

        cache = MatchCache(
            int(os.environ.get("BENCH_CACHE_SLOTS", str(1 << 18))), m)

    def make_step(k_, pm_, q_):
        def step(ids, n, sysm):
            res = match_batch(auto, ids, n, sysm, k=k_, m=m,
                              pack_ids=False,
                              **walk_params(host_auto, ids.shape[1]))
            m_ptr, packed = pack_matches(res.ids, pm=pm_)
            f_ptr, subs, src, total = expand_packed(fan, m_ptr,
                                                    packed, q=q_)
            return res.count, f_ptr, res.overflow, total, m_ptr[-1]
        return step

    def make_cache_step(k_, pm_, q_):
        import jax.numpy as jnp

        key = ("bench", k_)  # k growth must re-walk negative entries

        def step(i):
            ids_, n_, sysm_ = host_batches[i]
            b_pad = ids_.shape[0]
            probe = cache.probe(topic_lists[i], key)
            miss_rows = miss_ovf = None
            if probe.miss_topics:
                # host slice + pad of the pre-encoded rows — the
                # product encodes only its misses the same way
                rows = np.asarray(probe.miss_pos)
                mb_pad = 8
                while mb_pad < len(rows):
                    mb_pad *= 2
                mi = np.zeros((mb_pad, ids_.shape[1]), ids_.dtype)
                mi[:len(rows)] = ids_[rows]
                mn = np.zeros((mb_pad,), n_.dtype)
                mn[:len(rows)] = n_[rows]
                ms = np.zeros((mb_pad,), bool)
                ms[:len(rows)] = sysm_[rows]
                res = match_batch(
                    auto, mi, mn, ms, k=k_, m=m, pack_ids=True,
                    **walk_params(host_auto, ids_.shape[1]))
                miss_rows, miss_ovf = res.ids, res.overflow
                cache.insert(probe, miss_rows, miss_ovf)
            merged, ovf, _movf = cache.merge(b_pad, probe,
                                             miss_rows, miss_ovf)
            m_ptr, packed = pack_matches(merged, pm=pm_)
            f_ptr, subs, src, total = expand_packed(fan, m_ptr,
                                                    packed, q=q_)
            count = jnp.sum(merged >= 0, axis=1, dtype=jnp.int32)
            return count, f_ptr, ovf, total, m_ptr[-1]
        return step

    make = make_cache_step if use_cache else make_step
    step_batches = [(i,) for i in range(len(batches))] if use_cache \
        else batches
    step = make(k, PM, Q)
    ovf_w = uniq_w = 0
    tot_m = tot_q = 0
    for b_, u in zip(step_batches, uniques):  # one compile per shape
        out = step(*b_)
        jax.block_until_ready(out)
        ovf_w += int(np.asarray(out[2])[:u].sum())
        uniq_w += u
        tot_m = max(tot_m, int(np.asarray(out[4])))
        tot_q = max(tot_q, int(np.asarray(out[3])))
    # first full pass = the cross-batch (cold) repeat rate; steady
    # state below re-visits the same batches and measures hot hits
    warm_hit_rate = cache.stats()["hit_rate"] if use_cache else None
    if k_env is None and ovf_w * 8 > uniq_w:
        # the product's boost_k response to the same >1/8 signal:
        # grow once and re-warm (overflowed rows would otherwise be
        # host-resolved — exact, but not what steady state runs)
        k = k * 2
        step = make(k, PM, Q)
        tot_m = tot_q = 0
        for b_ in step_batches:
            out = step(*b_)
            jax.block_until_ready(out)
            tot_m = max(tot_m, int(np.asarray(out[4])))
            tot_q = max(tot_q, int(np.asarray(out[3])))
    # shrink to fitting buckets (1.3x headroom; overflow accounting
    # below still flags any batch that outgrows them)
    fit_m = budget_for(1, 1, floor=64)
    while fit_m < tot_m * 1.3:
        fit_m *= 2
    fit_q = budget_for(1, 1, floor=64)
    while fit_q < tot_q * 1.3:
        fit_q *= 2
    if fit_m < PM or fit_q < Q:
        PM, Q = min(PM, fit_m), min(Q, fit_q)
        step = make(k, PM, Q)
        for b_ in step_batches:
            jax.block_until_ready(step(*b_))
    if use_cache:
        st0 = cache.stats()  # steady-state hit rate = windows only

    # The chip is reached through a shared tunnel with transient
    # stalls, so one long timing window is unstable (observed 5x
    # swings run-to-run). Time several independent windows and report
    # the median window throughput; every window ends in a readback
    # (true completion barrier — see _throughput_windows).
    windows = max(1, int(os.environ.get("BENCH_WINDOWS", "5")))
    batches_per_s, rates, outs = _throughput_windows(
        step, step_batches, windows, iters)
    throughput = batches_per_s * batch
    p50, p99 = _latency_pass(step, step_batches)

    # per-stage attribution columns (ISSUE 2; docs/OBSERVABILITY.md):
    # time nested pipeline PREFIXES — match only, match+pack, full —
    # and difference them, attributing the row's latency to a stage
    # instead of a vibe. Two small extra compiles + a few timed
    # iterations; BENCH_BREAKDOWN=0 skips. Cache rows skip it too:
    # their step is host-orchestrated (probe/merge around the walk)
    # and the cache_* info fields already carry that split.
    stage_ms = None
    if not use_cache and os.environ.get("BENCH_BREAKDOWN", "1") == "1":
        def step_match(ids, n, sysm):
            res = match_batch(auto, ids, n, sysm, k=k, m=m,
                              pack_ids=False,
                              **walk_params(host_auto, ids.shape[1]))
            return res.ids

        def step_mp(ids, n, sysm):
            res = match_batch(auto, ids, n, sysm, k=k, m=m,
                              pack_ids=False,
                              **walk_params(host_auto, ids.shape[1]))
            m_ptr, packed = pack_matches(res.ids, pm=PM)
            return packed, m_ptr

        for s_ in (step_match, step_mp):  # compile outside the timing
            for b_ in step_batches:
                jax.block_until_ready(s_(*b_))
        p50_m, _ = _latency_pass(step_match, step_batches, iters=8)
        p50_mp, _ = _latency_pass(step_mp, step_batches, iters=8)
        stage_ms = {
            "match": round(p50_m, 3),
            "pack": round(max(0.0, p50_mp - p50_m), 3),
            "expand": round(max(0.0, p50 - p50_mp), 3),
        }

    # walk-cost columns (ISSUE 16): per-topic hop count under the
    # compressed automaton — the quantity path compression shrinks.
    # hops_for_level[L] is the walk's step bound for an L-level
    # topic; per-topic gathers follow the kernel's own cost model
    # (GATHERS_PER_HOP fetches per hop per active lane).
    from emqx_tpu.ops.walk_pallas import GATHERS_PER_HOP
    hl_ = np.asarray(host_auto.hops_for_level)
    lv_ = np.concatenate([np.asarray(b_[1])[:u]
                          for b_, u in zip(host_batches, uniques)])
    lv_ = lv_[lv_ > 0]
    steps_per_topic = hl_[np.minimum(lv_, len(hl_) - 1)]
    walk_levels_p50 = int(np.percentile(steps_per_topic, 50))
    gathers_per_topic = round(
        float(steps_per_topic.mean()) * GATHERS_PER_HOP * k, 1)

    # compaction A/B (ISSUE 16): re-finalize the SAME flatten with
    # compression forced off, time the match stage on both tables,
    # report the off-p50 and the speedup. Only on rows that ask
    # (deep/uniform — _CONFIG_MATRIX sets BENCH_COMPRESS_AB) and only
    # when the live tables actually compressed (wide mode).
    compress_ab = None
    if (os.environ.get("BENCH_COMPRESS_AB") == "1"
            and not use_cache and int(host_auto.wt_take) > 1):
        from emqx_tpu.ops.csr import finalize_automaton
        off_host = finalize_automaton(host_auto, force_mode="narrow")
        off_dev = jax.device_put(device_view(off_host))

        def step_off(ids, n, sysm):
            res = match_batch(off_dev, ids, n, sysm, k=k, m=m,
                              pack_ids=False,
                              **walk_params(off_host, ids.shape[1]))
            return res.ids

        def step_on(ids, n, sysm):
            res = match_batch(auto, ids, n, sysm, k=k, m=m,
                              pack_ids=False,
                              **walk_params(host_auto, ids.shape[1]))
            return res.ids

        for s_ in (step_off, step_on):  # compile outside the timing
            for b_ in step_batches:
                jax.block_until_ready(s_(*b_))
        off_p50, _ = _latency_pass(step_off, step_batches, iters=8)
        on_p50, _ = _latency_pass(step_on, step_batches, iters=8)
        compress_ab = {
            "compress_off_p50_ms": round(off_p50, 3),
            "compress_speedup": (round(off_p50 / on_p50, 2)
                                 if on_p50 > 0 else None),
        }

    counts = np.asarray(outs[0][0])[:uniques[0]]
    deliv = np.diff(np.asarray(outs[0][1]))[:uniques[0]]
    ovf = sum(int(np.asarray(o[2]).sum()) for o in outs)
    # budget truncation counts as overflow too (silent undercount
    # otherwise): packed matches past PM, deliveries past Q
    ovf += sum(int(np.asarray(o[3]) > Q) for o in outs)
    ovf += sum(int(np.asarray(o[4]) > PM) for o in outs)
    avg_unique = float(np.mean(uniques))
    info = {
        "mix": mix, "traffic": traffic, "levels": levels,
        "subs": n_filters,
        "batch": batch,
        "k": k,  # active-set capacity the run settled on (adaptive)
        "avg_unique_topics": round(avg_unique, 1),
        "native": use_native,
        "build_cached": bool(cached),
        "build_s": round(build_s, 1),
        "avg_matches_per_unique": round(float(counts.mean()), 2),
        "avg_deliveries_per_unique": round(float(deliv.mean()), 2),
        "overflow_frac": round(ovf / (avg_unique * iters), 6),
        "device": str(jax.devices()[0]),
        "unique_kmsgs_per_s": round(batches_per_s * avg_unique / 1e3, 1),
        "window_mmsgs": [round(r * batch / 1e6, 2) for r in rates],
        "walk_levels_p50": walk_levels_p50,
        "gathers_per_topic": gathers_per_topic,
    }
    if stage_ms is not None:
        info["stage_p50_ms"] = stage_ms
    if compress_ab is not None:
        info.update(compress_ab)
    if use_cache:
        st1 = cache.stats()
        probed = (st1["hit"] - st0["hit"]) + (st1["miss"] - st0["miss"])
        info["cache"] = True
        info["cache_slots"] = cache.slots
        info["cache_entries"] = st1["entries"]
        # cold = the first pass over distinct batches (true
        # cross-batch repetition); steady = the timed windows
        info["cache_warm_hit_rate"] = round(warm_hit_rate, 4)
        info["cache_hit_rate"] = round(
            (st1["hit"] - st0["hit"]) / probed, 4) if probed else 0.0
    import sys
    print(json.dumps(info), file=sys.stderr, flush=True)
    # row provenance (mode builds raw automatons, no Router): stamp
    # from the settled walk itself
    global _PROV
    _PROV = {
        "walk_mode": "wide" if host_auto.wt_slots == 4 else "narrow",
        "settled_k": int(k),
        "builder": "native" if use_native else "python",
        "delta": False,  # raw-automaton mode: no route-churn plane
    }
    rec = {
        "metric": "publish_match_fanout_throughput",
        "value": round(throughput, 1),
        "unit": "msgs/sec",
        "vs_baseline": round(throughput / 1_000_000, 3),
        "p50_batch_ms": round(p50, 3),
        "p99_batch_ms": round(p99, 3),
        "walk_levels_p50": walk_levels_p50,
        "gathers_per_topic": gathers_per_topic,
    }
    if stage_ms is not None:
        rec["stage_p50_ms"] = stage_ms
    if compress_ab is not None:
        rec.update(compress_ab)
    _emit(rec)


def live():
    """BENCH_MODE=live — socket-to-deliver over loopback TCP through
    the full broker stack (see emqx_tpu/bench_live.py)."""
    from emqx_tpu.bench_live import live as _live
    _live(emit=_emit)


def deep_smoke():
    """BENCH_MODE=deep_smoke — the path-compression CI gate
    (ISSUE 16, scripts/ci.sh): a 16-level workload must (a) actually
    level-compress — the walk's hop bound strictly below the raw
    level count — and (b) hold exact host-oracle parity through the
    compressed tables and the product fetch seam. Numbers are not
    gated here; the compression + correctness booleans ARE."""
    import random as _random

    n_filters = int(os.environ.get("DEEP_FILTERS", "400"))
    n_topics = int(os.environ.get("DEEP_TOPICS", "256"))
    levels = 16

    jax = _jax_with_retry()

    from emqx_tpu.oracle import TrieOracle
    from emqx_tpu.ops import native
    from emqx_tpu.ops.csr import device_view
    from emqx_tpu.ops.match import depth_bucket, walk_params
    from emqx_tpu.ops.walk_pallas import (fetch_walk_result,
                                          match_batch_auto)

    rng = _random.Random(6)
    filters = set()
    while len(filters) < n_filters:
        ws = ["w%d" % rng.randint(0, 3) for _ in range(levels)]
        r = rng.random()
        if r < 0.25:
            ws[rng.randint(0, levels - 1)] = "+"
        elif r < 0.4:
            ws = ws[:rng.randint(4, levels - 1)] + ["#"]
        filters.add("/".join(ws))
    filters = sorted(filters)

    oracle = TrieOracle()
    use_native = native.available()
    if use_native:
        eng = native.NativeEngine()
        for i, f in enumerate(filters):
            eng.insert(f, i)
            oracle.insert(f)
        host_auto = eng.flatten()
        encode = eng.encode_batch
    else:
        insert, flatten, encode = _python_engine()
        for i, f in enumerate(filters):
            insert(f, i)
            oracle.insert(f)
        host_auto = flatten()

    hl = np.asarray(host_auto.hops_for_level)
    deep_hops = int(hl[min(levels, len(hl) - 1)])
    # the gate: a 16-level literal-spined trie MUST compress — the
    # walk takes strictly fewer hops than the topic has levels
    assert int(host_auto.wt_take) > 1, \
        "deep workload did not take the wide (compressed) layout"
    assert deep_hops < levels, \
        f"no compression: {deep_hops} hops for {levels} levels"

    topics = ["/".join("w%d" % rng.randint(0, 3)
                       for _ in range(levels))
              for _ in range(n_topics)]
    # seed guaranteed-match probes (wildcard rows above cover misses)
    for f in rng.sample(filters, min(32, len(filters))):
        topics.append("/".join(
            "w0" if w == "+" else w
            for w in f.split("/")).replace("/#", "/w0"))
    ids_, n_, sysm_ = encode(topics, levels)
    ids_, n_ = depth_bucket(ids_, n_)
    auto = jax.device_put(device_view(host_auto))
    t0 = time.time()
    res = match_batch_auto(auto, ids_, n_, sysm_, k=16, m=64,
                           pack_ids=True,
                           **walk_params(host_auto, ids_.shape[1]))
    r_ids, r_cnt, r_ovf = fetch_walk_result(res)
    walk_s = time.time() - t0
    inv = {i: f for i, f in enumerate(filters)}
    mismatch = 0
    for i, t in enumerate(topics):
        want = sorted(oracle.match(t))
        if r_ovf[i]:
            continue  # flagged rows host-resolve in the product
        got = sorted(inv[j] for j in r_ids[i] if j >= 0)
        if got != want:
            mismatch += 1
    assert mismatch == 0, f"{mismatch} topics diverged from oracle"

    _emit({
        "metric": "deep_smoke_parity",
        "value": 1,
        "unit": "ok",
        "filters": len(filters),
        "topics": len(topics),
        "levels": levels,
        "walk_hops_deep": deep_hops,
        "compressed": True,
        "parity_ok": True,
        "native": use_native,
        "walk_s": round(walk_s, 3),
    })


def retained():
    """BENCH_MODE=retained — subscribe-time retained replay
    (ISSUE 19, docs/DISPATCH.md "Retained replay"). Two phases:

    (a) index A/B: BENCH_SUBS retained NAMES in the RetainIndex,
        mixed literal/wildcard SUBSCRIBE bursts matched through the
        batched ``[F, L] × [cap, L]`` device kernel
        (ops/retained_match.py, device_threshold=0) vs the per-filter
        host scan. The host path IS ``T.match`` over every live name,
        so device==host on the shared burst is the exact-oracle
        parity gate. Host subs/s is measured on a small filter
        subset (RETAINED_HOST_FILTERS) — at 1M names one host filter
        costs seconds, and per-filter cost is the comparable number.

    (b) wire smoke: a live loopback node replays RETAINED_WIRE_TOPICS
        retained messages to RETAINED_WIRE_SUBS simultaneous wildcard
        subscribers through the planner-egress path — every owed
        frame must arrive (zero lost replays), ``retained.replay``
        must count ≤1 batch per SUBSCRIBE, and
        ``delivery.serialize.onloop`` must stay 0 (scripts/ci.sh
        gates these booleans at toy scale).
    """
    import asyncio
    import random as _random

    _jax_with_retry()

    from emqx_tpu.modules.retainer import RetainIndex
    from emqx_tpu.ops.walk_pallas import walk_variant

    n_names = int(os.environ.get("BENCH_SUBS") or "1000000")
    burst = int(os.environ.get("RETAINED_BURST", "64"))
    n_bursts = int(os.environ.get("RETAINED_BURSTS", "8"))
    host_f = int(os.environ.get("RETAINED_HOST_FILTERS", "4"))
    rng = _random.Random(19)

    t0 = time.time()
    idx = RetainIndex()
    names = [f"s{i % 499}/g{(i // 499) % 97}/d{i}/state"
             for i in range(n_names)]
    for t in names:
        idx.add(t)
    build_s = time.time() - t0

    def mk_burst(k):
        flts = []
        for _ in range(k):
            ws = names[rng.randrange(n_names)].split("/")
            r = rng.random()
            if r < 0.5:
                pass  # literal: exact store probe shape
            elif r < 0.8:
                ws[rng.randrange(len(ws))] = "+"
            else:
                ws = ws[:rng.randint(1, len(ws) - 1)] + ["#"]
            flts.append("/".join(ws))
        return flts

    bursts = [mk_burst(burst) for _ in range(n_bursts)]
    # warm pass: compiles for the (padded-F, cap) shape land here
    idx.match_many(bursts[0], device_threshold=0)
    t0 = time.time()
    dev_hits = [idx.match_many(b, device_threshold=0)
                for b in bursts]
    dev_s = time.time() - t0
    dev_rate = (n_bursts * burst) / dev_s if dev_s else 0.0
    matched = sum(len(h) for hs in dev_hits for h in hs)

    # host half of the A/B + the exact-oracle parity gate: the same
    # filters through the T.match scan must produce the same sets
    probe = bursts[0][:host_f]
    t0 = time.time()
    host_hits = idx.match_many(probe,
                               device_threshold=n_names + 1)
    host_s = time.time() - t0
    host_rate = len(probe) / host_s if host_s else 0.0
    parity_n = len(probe)
    for flt, want in zip(probe, host_hits):
        got = dev_hits[0][bursts[0].index(flt)]
        assert sorted(got) == sorted(want), \
            f"device/host divergence on {flt!r}"
    if n_names <= 20_000:
        # toy scale: full-burst parity is cheap — gate ALL of it
        for b, hs in zip(bursts, dev_hits):
            oracle = idx.match_many(b, device_threshold=n_names + 1)
            assert [sorted(h) for h in hs] \
                == [sorted(h) for h in oracle], "burst parity"
            parity_n += len(b)

    wire = asyncio.run(_retained_wire_smoke())
    assert wire["wire_received"] == wire["wire_expected"], \
        f"lost replays: {wire}"
    assert wire["wire_onloop"] == 0, wire
    assert wire["wire_batches"] <= wire["wire_subs"], wire

    _emit({
        "metric": "retained_subs_per_s",
        "value": round(dev_rate, 1),
        "unit": "subs/sec",
        "workload": "retained_v1",
        "names": n_names,
        "burst": burst,
        "bursts": n_bursts,
        "build_s": round(build_s, 3),
        "matched": matched,
        "host_subs_per_s": round(host_rate, 2),
        "speedup_vs_host": (round(dev_rate / host_rate, 2)
                            if host_rate else None),
        "parity_ok": True,
        "parity_filters": parity_n,
        "walk": walk_variant(),
        **wire,
    })


async def _retained_wire_smoke() -> dict:
    """Phase (b) of BENCH_MODE=retained: live loopback replay with
    the delivery contract pinned (fixed toy scale — it checks
    booleans, not throughput)."""
    import asyncio

    from emqx_tpu.bench_live import _Peer, _count_recv
    from emqx_tpu.modules.retainer import RetainerModule
    from emqx_tpu.mqtt import constants as C
    from emqx_tpu.mqtt.frame import serialize
    from emqx_tpu.mqtt.packet import Publish, Subscribe
    from emqx_tpu.node import Node

    n_topics = int(os.environ.get("RETAINED_WIRE_TOPICS", "64"))
    n_subs = int(os.environ.get("RETAINED_WIRE_SUBS", "8"))
    node = Node(boot_listeners=False)
    node.modules.load(RetainerModule)
    lst = node.add_listener(port=0)
    await node.start()
    try:
        node.modules._loaded["retainer"].index_device_threshold = 0
        pub = _Peer("retw-pub")
        await pub.connect(lst.port)
        for i in range(n_topics):
            pub.writer.write(serialize(Publish(
                topic=f"rw/{i}/s", payload=b"r%d" % i, retain=True),
                C.MQTT_V4))
        await pub.writer.drain()
        deadline = time.time() + 10.0
        while node.metrics.val("retained.count") < n_topics \
                and time.time() < deadline:
            await asyncio.sleep(0.02)
        onloop0 = node.metrics.val("delivery.serialize.onloop")
        subs = [_Peer(f"retw-s{i}") for i in range(n_subs)]
        for i, s in enumerate(subs):
            await s.connect(lst.port)
        tasks = []
        for s in subs:
            # SUBSCRIBE without awaiting the SUBACK: replayed frames
            # can land in the same read as the ack, and the counting
            # loop must see every one of them
            s.writer.write(serialize(Subscribe(
                packet_id=1,
                topic_filters=[("rw/#", {"qos": 0})]), C.MQTT_V4))
            tasks.append(asyncio.ensure_future(_count_recv(s)))
        for s in subs:
            await s.writer.drain()
        expected = n_topics * n_subs
        deadline = time.time() + 30.0
        while sum(s.received for s in subs) < expected \
                and time.time() < deadline:
            await asyncio.sleep(0.02)
        for t in tasks:
            t.cancel()
        for s in subs + [pub]:
            s.close()
        return {
            "wire_topics": n_topics,
            "wire_subs": n_subs,
            "wire_expected": expected,
            "wire_received": sum(s.received for s in subs),
            "wire_onloop":
                node.metrics.val("delivery.serialize.onloop")
                - onloop0,
            "wire_batches":
                node.metrics.val("retained.replay.batches"),
        }
    finally:
        await node.stop()


def overload():
    """BENCH_MODE=overload — the saturation degradation curve
    (offered load vs delivered msgs/s vs shed fraction) through a
    live loopback node with the overload monitor armed
    (emqx_tpu/bench_live.py, docs/ROBUSTNESS.md)."""
    from emqx_tpu.bench_live import overload_curve
    overload_curve(emit=_emit)


def devloss():
    """BENCH_MODE=devloss — the device-loss recovery window: a
    device-regime node loses its backend mid-batch, rides the exact
    host oracle, and auto-recovers (rebuild + kernel rewarm +
    half-open probe). Records host-fallback msgs/s, rebuild_s,
    time-to-breaker-closed, and first-batch-after-recovery p99
    (emqx_tpu/bench_live.py, docs/ROBUSTNESS.md "Device-loss
    recovery")."""
    from emqx_tpu.bench_live import devloss as _devloss
    _devloss(emit=_emit)


def drain():
    """BENCH_MODE=drain — the zero-downtime graceful-drain operation
    (docs/OPERATIONS.md): a 2-node cluster, DRAIN_SESSIONS detached
    persistent sessions + DRAIN_LIVE live clients on the draining
    node; records sessions drained/s, redirect wave p99,
    time-to-empty, and the zero-RPO boolean (digest-verified custody
    hand-off, exactly one holder)."""
    from emqx_tpu.bench_live import drain as _drain
    _drain(emit=_emit)


def fleet():
    """BENCH_MODE=fleet — the connection-fleet row (ISSUE 18):
    FLEET_CONNS real sockets (mostly-idle devices with wills,
    persistent sessions, keepalive pings, reconnect churn) around a
    mixed QoS0/1 + retained + shared-sub traffic core, against
    FLEET_LOOPS event loops / FLEET_WORKERS SO_REUSEPORT processes /
    FLEET_NODES cluster nodes. Records delivered msgs/s, delivery
    p99, RSS per 10K conns, and the counted-blast zero-lost boolean
    (emqx_tpu/bench_live.py; scripts/ci.sh gates a toy-scale run)."""
    from emqx_tpu.bench_live import fleet as _fleet
    _fleet(emit=_emit)


def latency():
    """BENCH_MODE=latency — the small-batch low-latency operating
    point (VERDICT r4 item 4): per-step device latency of the full
    match→pack→expand pipeline at a small batch against the 1M-sub
    trie. A broker is judged on tail latency (the reference bounds
    per-message tails with active_n, src/emqx_connection.erl:99);
    every other row is a throughput batch.

    Methodology: the tunnel adds ~65ms per device→host readback, so a
    single small step cannot be timed directly. The timed unit is ONE
    compiled program that runs the step CHAIN times sequentially
    (lax.scan lowers to a while loop — strictly serial iterations);
    per-step latency = wall / CHAIN, amortizing the readback to
    65/CHAIN ms. Reported p50/p99 are over repeated chained samples.
    Fixed bound (BASELINE.md): p99 < 10ms.
    """
    import sys

    chain = int(os.environ.get("BENCH_CHAIN", "32"))
    n_subs = int(os.environ.get("BENCH_SUBS", "1000000"))
    batch = int(os.environ.get("BENCH_BATCH", "8192"))
    iters = int(os.environ.get("BENCH_ITERS", "12"))
    windows = max(1, int(os.environ.get("BENCH_WINDOWS", "3")))
    m = int(os.environ.get("BENCH_M", "64"))
    levels = int(os.environ.get("BENCH_LEVELS", "5"))

    jax = _jax_with_retry()
    from jax import lax

    from emqx_tpu.ops.csr import device_view
    from emqx_tpu.ops.fanout import expand_packed
    from emqx_tpu.ops.match import match_batch, walk_params
    from emqx_tpu.ops.pack import budget_for, pack_matches

    t0 = time.time()
    use_native, cached, host_auto, fan, host_batches, uniques, \
        n_filters, _topics = build_main_inputs(
            n_subs, batch, levels, "mixed", "zipf", 60)
    build_s = time.time() - t0
    k = int(os.environ.get("BENCH_K", "4"))
    auto = jax.device_put(device_view(host_auto))
    fan_d = jax.device_put(fan)
    batches = [jax.device_put(b) for b in host_batches]
    rows = max(b[0].shape[0] for b in batches)
    PM = budget_for(rows, max(8, k))
    Q = budget_for(rows, 16)

    import jax.numpy as jnp

    def jnp_sum32(x):
        return jnp.sum(x, dtype=jnp.int32)

    def one_step(ids, n, sysm):
        res = match_batch(auto, ids, n, sysm, k=k, m=m,
                          pack_ids=False,
                          **walk_params(host_auto, ids.shape[1]))
        m_ptr, packed = pack_matches(res.ids, pm=PM)
        f_ptr, _subs, _src, total = expand_packed(fan_d, m_ptr,
                                                  packed, q=Q)
        return (jnp_sum32(res.count) + jnp_sum32(f_ptr[-1:])
                + jnp_sum32(total[None]))

    def chained(ids, n, sysm):
        def body(carry, _):
            # scan lowers to a while loop: iterations are strictly
            # sequential, so wall/CHAIN is honest per-step latency
            return carry + one_step(ids, n, sysm), None
        out, _ = lax.scan(body, jnp.int32(0), None, length=chain)
        return out

    step = jax.jit(chained)
    for b_ in batches:
        np.asarray(step(*b_))  # compile + warm
    lat = []
    for w in range(windows):
        for i in range(iters):
            t1 = time.perf_counter()
            np.asarray(step(*batches[i % len(batches)]))
            lat.append((time.perf_counter() - t1) * 1000.0 / chain)
    p50 = float(np.percentile(lat, 50))
    p99 = float(np.percentile(lat, 99))
    thr = batch / (p50 / 1000.0)
    info = {
        "mode": "latency", "subs": n_filters, "batch": batch,
        "chain": chain, "k": k, "build_s": round(build_s, 1),
        "build_cached": bool(cached), "native": use_native,
        "avg_unique_topics": round(float(np.mean(uniques)), 1),
        "thr_logical_msgs_per_s": round(thr, 1),
        "device": str(jax.devices()[0]),
    }
    print(json.dumps(info), file=sys.stderr, flush=True)
    _emit({
        "metric": "latency_8k_p99_ms",
        "value": round(p99, 3),
        "unit": "ms",
        # fixed bound: p99 < 10ms at the small-batch operating point
        "vs_baseline": round(10.0 / p99, 3) if p99 > 0 else 0.0,
        "p50_batch_ms": round(p50, 3),
        "p99_batch_ms": round(p99, 3),
        "thr_msgs_per_s": round(thr, 1),
        "chain": chain,
    })


def sharded():
    """BENCH_MODE=sharded — the product multi-chip path: match AND
    per-shard subscriber fan-out through
    ``Router.publish_dispatch_sharded`` (publish_step with real fan
    tables, ``with_fanout=True`` — VERDICT r2 item 3). On the single
    real chip this is mesh (1,1); BENCH_MESH=N uses N devices (the
    virtual CPU mesh in tests). Reports matched+fanned publishes/sec."""
    import sys

    jax = _jax_with_retry()

    from emqx_tpu.parallel.mesh import default_mesh
    from emqx_tpu.parallel.sharded import (build_sharded_fanout,
                                           place_sharded, shard_of)
    from emqx_tpu.router import MatcherConfig, Router

    rng = random.Random(0)
    n_subs = int(os.environ.get("BENCH_SUBS", "1000000"))
    # default batch = a realistic ingress tick (main() uses 131072
    # logical; the sharded step sees the deduped rows either way)
    B = int(os.environ.get("BENCH_BATCH", "65536"))
    iters = int(os.environ.get("BENCH_ITERS", "30"))
    n_dev = int(os.environ.get("BENCH_MESH", str(len(jax.devices()))))
    d = int(os.environ.get("BENCH_D", "64"))

    mesh = default_mesh(n_dev)
    n_trie = mesh.shape["trie"]
    filters, vocab = build_filters(rng, n_subs, 64)
    r = Router(MatcherConfig(mesh=mesh, fanout_d=d))
    t0 = time.time()
    for f in filters:
        r.add_route(f)
    topics = ["/".join(zipf_choice(rng, lvl) for lvl in vocab[:4])
              for _ in range(B * 4)]
    batches = [(topics[i * B:(i + 1) * B],) for i in range(4)]
    r.match_ids(batches[0][0])  # flatten + match jit warm
    _set_prov(r)
    # one subscriber per subscription, rows on the automaton's own
    # stable shard assignment (what FanoutManager.sharded_state builds
    # in the product; built directly here to skip 1M host sub objects)
    rows = [{} for _ in range(n_trie)]
    for f in filters:
        fid = r.filter_id(f)
        rows[shard_of(f, n_trie)][fid] = [fid]
    from emqx_tpu.broker_helper import ShardedFanoutState

    fan = place_sharded(mesh, build_sharded_fanout(
        rows, len(r._id_to_filter)))
    fan_state = ShardedFanoutState(0, 0, fan, None, frozenset(), d)
    provider = (lambda epoch, id_map: fan_state)

    # the product ingress dedups hot topics per tick BEFORE the device
    # (ingress.py; main() measures the same way, reporting logical
    # msgs with the unique rate alongside) — the sharded step gets the
    # same treatment: dedup each batch, pre-encode + pre-place the
    # UNIQUE rows outside the timed window (through the tunnel a
    # synchronous per-call host→device transfer would serialize the
    # stream; the ingress overlaps this host half with in-flight
    # device steps)
    prepped = []
    uniques = []
    encode_ms = []
    for (b,) in batches:
        t_enc = time.perf_counter()
        uniq, inv = dedup_topics(b)
        uniques.append(len(uniq))
        prepped.append((uniq, r.encode_place_sharded(uniq),
                        jax.device_put(np.asarray(inv, np.int32))))
        # per-tick host half, reported so the overlap claim is
        # checkable: the ingress can hide this behind a device step
        # only if it is SHORTER than one (see encode_ms vs p50)
        encode_ms.append((time.perf_counter() - t_enc) * 1000.0)

    def step(batch, pl, inv):
        all_ids, subs, src, _bm, ovf, _movf, _, _, _ = \
            r.publish_dispatch_sharded(batch, provider, placed=pl)
        # per-LOGICAL-message expansion: the dedup inverse gathers
        # every duplicate's match row (what broker.publish_fetch does
        # per tick), so the 65536-logical rate carries per-duplicate
        # device work in the timed window (ADVICE r4 item 2)
        import jax.numpy as _jnp

        ids_full = all_ids[inv]
        logical_matches = _jnp.sum(ids_full >= 0, dtype=_jnp.int32)
        # tiny data-dependent views: reading them back forces the
        # whole step (match + gather + collectives + expansion) to
        # completion without shipping the full arrays through the
        # host link
        return subs[:2, :2], ovf[:8], logical_matches

    # warm EVERY batch: deduped batches can straddle a pow-2 padding
    # bucket boundary, and a publish_step compile for the second
    # bucket must not land inside a timed window (same guard as
    # shared(): one compile per distinct unique-shape bucket)
    for p in prepped:
        step(*p)
    build_s = time.time() - t0
    batches_per_s, rates, outs = _throughput_windows(
        step, prepped, max(1, int(os.environ.get("BENCH_WINDOWS", "5"))),
        iters)
    thr = batches_per_s * B
    p50, p99 = _latency_pass(step, prepped, min(iters, 20))
    st = r.drain_device_stats()
    info = {
        "subs": n_subs, "batch": B, "mesh": dict(mesh.shape),
        "fanout": True, "d": d,
        "build_s": round(build_s, 1),
        "avg_unique_topics": round(sum(uniques) / len(uniques), 1),
        "unique_kmsgs_per_s": round(
            batches_per_s * sum(uniques) / len(uniques) / 1e3, 1),
        "encode_ms": round(sum(encode_ms) / len(encode_ms), 1),
        "dev_matches": st["matches"],
        "dev_deliveries": st["deliveries"],
        "dev_overflows": st["overflows"],
        "device": str(jax.devices()[0]),
        "window_mmsgs": [round(w * B / 1e6, 2) for w in rates],
    }
    print(json.dumps(info), file=sys.stderr, flush=True)
    _emit({
        # renamed from round-2's match-only 'sharded_match_throughput':
        # this mode now measures match+fanout — a different workload
        # must not share a metric key with the old one. The round-4
        # methodology change (raw batches → product-faithful deduped
        # ticks, default tick 4096 → 65536) keeps the key but stamps
        # `workload` so values across the change are distinguishable
        # (the same-series rule, carried by a field instead of a
        # rename: the mode's staged-skip and fail-soft records key on
        # the metric name)
        "metric": "sharded_publish_throughput",
        # v3: 1×1 mesh runs the plain-jit fast path (same program,
        # collectives are identity on one device) and the timed step
        # now includes the per-logical-message dedup-inverse
        # expansion (ADVICE r4 item 2) — a methodology change, so the
        # stamp invalidates staged v2 records
        "workload": "deduped_tick_v3_invexp",
        "value": round(thr, 1),
        "unit": "msgs/sec",
        "vs_baseline": round(thr / 1e6, 3),
        "p50_batch_ms": round(p50, 3),
        "p99_batch_ms": round(p99, 3),
        # the host half per tick, in the staged record so the overlap
        # claim (encode hides behind a device step) is checkable
        # against p50_batch_ms from the artifact alone
        "encode_ms": info["encode_ms"],
        "avg_unique_topics": info["avg_unique_topics"],
    })


def churn():
    """BENCH_MODE=churn — match latency under route churn (VERDICT
    round-1 item 4: 10k subscribe/s against a large filter set must
    leave match p99 unaffected; rebuild cost amortized by O(delta)
    patches, reference O(depth) semantics src/emqx_trie.erl:82-116).

    Three churn shapes (ISSUE 4, docs/MATCH_CACHE.md "Partitioned
    epochs"), all against the same router/filter set:

      - **disjoint** (the headline): literal-rooted churn filters
        (``churn/{i}/leaf``) whose first level is disjoint from the
        matched topics' roots — partitioned epoch keys keep the other
        partitions' cached entries valid, so the hit rate survives;
      - **root_wildcard**: ``+/churnrw/{i}`` — every mutation is a
        global epoch bump (the conservative fallback), hit rate
        collapses by design, exactly as safe as whole-epoch;
      - **share**: ``$share/<group>/churnsh{i}/leaf`` — partitions on
        the level AFTER the share prefix.

    Plus a partitioned-vs-whole-epoch A/B column: the disjoint pass
    re-run with whole-epoch invalidation (``CHURN_PARTITIONS=1``
    semantics, the PR-1 behavior) on the identical filter set.
    ``CHURN_PARTITIONS=<n>`` pins the main passes' granularity (``1``
    makes the headline itself whole-epoch and skips the A/B).

    Reports p99 batch-match latency WITH churn; ``vs_baseline`` is
    the no-churn p99 / churn p99 ratio (1.0 = unaffected)."""
    import sys
    import threading

    jax = _jax_with_retry()

    from emqx_tpu.router import MatcherConfig, Router

    rng = random.Random(0)
    n_subs = int(os.environ.get("BENCH_SUBS", "1000000"))
    B = int(os.environ.get("BENCH_BATCH", "256"))
    rate = int(os.environ.get("BENCH_CHURN_RATE", "10000"))
    iters = int(os.environ.get("BENCH_ITERS", "60"))
    p_env = int(os.environ.get("CHURN_PARTITIONS", "0"))

    cfg = MatcherConfig() if p_env <= 0 \
        else MatcherConfig(cache_partitions=p_env)
    filters, vocab = build_filters(rng, n_subs, 64)
    r = Router(cfg)
    t0 = time.time()
    for f in filters:
        r.add_route(f)
    topics = ["/".join(zipf_choice(rng, lvl) for lvl in vocab[:4])
              for _ in range(B * 8)]
    batches = [(topics[i * B:(i + 1) * B],) for i in range(8)]
    r.match_ids(batches[0][0])  # flatten + match-kernel jit warm
    r.add_route("warm/patch/path")  # drain-scatter jit warm (fixed
    r.match_ids(batches[0][0])      # chunk shape: compiles once, here)
    r.delete_route("warm/patch/path")
    r.match_ids(batches[0][0])

    # warm every (hit-pad, miss-pad) cache shape the churn passes can
    # produce: with partitioned epochs a churn batch is a PARTIAL
    # hit/miss split (pre-partition churn was all-miss), and each new
    # pow2 pad combo recompiles the merge/insert jits + the walk's
    # miss bucket. One small batch per distinct shape here, so the
    # timed p99 measures steady state, not first-touch XLA.
    hot = list(dict.fromkeys(topics))[:B]
    r.match_ids(hot)  # all cached now
    def _p2(n, floor=8):
        out = floor
        while out < n:
            out *= 2
        return out
    fresh_i = [0]
    seen_sigs = set()
    for m in range(1, B + 1):
        sig = (_p2(max(B - m, 1)), _p2(m))
        if sig in seen_sigs:
            continue
        seen_sigs.add(sig)
        fresh = [f"wfresh/{fresh_i[0] + j}/x" for j in range(m)]
        fresh_i[0] += m
        r.match_ids(hot[:B - m] + fresh)
    build_s = time.time() - t0

    def step(batch):
        _, ids_np, _, _, _ = r.match_ids(batch)
        return ids_np

    p50_base, p99_base = _latency_pass(step, batches, iters)

    def churn_pass(mk):
        """One timed pass under a churner adding/deleting ``mk(i)``
        filters at `rate`/s. Strict add→delete pairing (the old
        alternating loop's ``churn/{i-1}`` arithmetic could delete a
        route it never added); the trailing add is cleaned up after
        join so every pass leaves the filter set exactly as it found
        it (the A/B passes must measure identical sets). Returns
        (p50, p99, achieved rate, cache hit rate DURING the pass,
        route-op p99 ms) — the route-op percentile is the churn
        plane's own latency, the number the off-lock compaction and
        delta batching exist to hold down (ISSUE 7)."""
        c = r._match_cache_obj
        h0, m0 = (c.hits, c.misses) if c is not None else (0, 0)
        stop = threading.Event()
        churned = [0]
        holder = {"pending": None}
        op_lat = []

        def churner():
            i = 0
            interval = 1.0 / max(1, rate)
            next_t = time.perf_counter()
            while not stop.is_set():
                t_op = time.perf_counter()
                if holder["pending"] is None:
                    holder["pending"] = mk(i)
                    r.add_route(holder["pending"])
                    i += 1
                else:
                    r.delete_route(holder["pending"])
                    holder["pending"] = None
                op_lat.append(time.perf_counter() - t_op)
                churned[0] += 1
                next_t += interval
                pause = next_t - time.perf_counter()
                if pause > 0:
                    time.sleep(pause)

        th = threading.Thread(target=churner, daemon=True)
        t1 = time.time()
        th.start()
        p50c, p99c = _latency_pass(step, batches, iters)
        stop.set()
        th.join(timeout=5)
        wall = time.time() - t1
        if holder["pending"] is not None:
            r.delete_route(holder["pending"])
            holder["pending"] = None
        c = r._match_cache_obj
        hd = (c.hits - h0) if c is not None else 0
        md = (c.misses - m0) if c is not None else 0
        hit_rate = hd / max(1, hd + md)
        route_p99 = (float(np.percentile(
            np.array(op_lat) * 1000.0, 99)) if op_lat else 0.0)
        return (p50c, p99c, round(churned[0] / max(wall, 1e-9), 1),
                round(hit_rate, 4), round(route_p99, 3))

    _set_prov(r)
    # warm the delta plane with one UNTIMED churn pass: the side-
    # automaton's capacity-growth ladder, the packed-union and
    # tombstone-mask kernels, and each wildcard shape all compile
    # here — the timed passes measure steady state, not first-touch
    # XLA (same discipline as the cache-shape sweep above)
    churn_pass(lambda i: f"warmd/{i}/leaf")
    churn_pass(lambda i: f"+/warmrw/{i}")
    r.rebuild()  # fold warm deltas: every pass starts from the same
    # compacted tables (shapes stay compiled; state does not linger)
    for b_, in batches:  # re-warm the cache the fold invalidated
        r.match_ids(b_)
    p50_churn, p99_churn, rate_disj, hit_disj, route_p99 = \
        churn_pass(lambda i: f"churn/{i}/leaf")
    _, p99_rw, _, hit_rw, _ = churn_pass(lambda i: f"+/churnrw/{i}")
    _, p99_sh, _, hit_sh, _ = \
        churn_pass(lambda i: f"$share/churngrp/churnsh{i}/leaf")
    # whole-epoch A/B on the SAME router/filter set: the bump
    # granularity is read from the config at mutation time, so
    # flipping it to 1 measures exactly the legacy invalidation on an
    # identical automaton (existing partitioned-key entries go stale
    # on first probe — irrelevant under churn, where whole-epoch
    # invalidates everything every mutation anyway)
    p99_whole = hit_whole = None
    if r.config.cache_partitions > 1:
        parts_used = r.config.cache_partitions
        r.config.cache_partitions = 1
        _, p99_whole, _, hit_whole, _ = \
            churn_pass(lambda i: f"churn/{i}/leaf")
        r.config.cache_partitions = parts_used

    # delta on/off A/B on the SAME router/filter set (ISSUE 7):
    # set_delta folds pending state through one rebuild, so both
    # passes measure an identical automaton — only the churn-plane
    # machinery differs (side-automaton two-probe vs patch-in-place)
    p99_delta_off = hit_delta_off = route_p99_off = None
    delta_was = r.config.delta
    if delta_was:
        r.set_delta(False)
        r.add_route("warm/patch/path")   # drain-scatter jit warm for
        r.match_ids(batches[0][0])       # the patch-in-place pass
        r.delete_route("warm/patch/path")
        r.match_ids(batches[0][0])
        _, p99_delta_off, _, hit_delta_off, route_p99_off = \
            churn_pass(lambda i: f"churn/{i}/leaf")
        r.set_delta(True)

    # steady-state compaction cost: the persistent trie makes a
    # rebuild FLATTEN-ONLY — A/B against a fresh-engine rebuild that
    # must re-insert the whole filter set first (what an off-lock
    # design without the freeze protocol would pay per compaction)
    t_c = time.perf_counter()
    r.rebuild()
    compaction_flatten_s = time.perf_counter() - t_c
    fresh_rebuild_s = fresh_insert_s = None
    if os.environ.get("CHURN_FRESH_AB", "1") != "0":
        from emqx_tpu.ops.csr import device_view as _dview

        t_f = time.perf_counter()
        if r._native is not None:
            from emqx_tpu.ops import native as _native_mod

            eng = _native_mod.NativeEngine()
            for i, f in enumerate(r.topics()):
                eng.insert(f, i)
            fresh_insert_s = time.perf_counter() - t_f
            host = eng.flatten()
            del eng
        else:
            from emqx_tpu.ops.csr import build_automaton as _build
            from emqx_tpu.oracle import TrieOracle as _TO
            from emqx_tpu.ops.tokenize import WordTable as _WT

            trie, table = _TO(), _WT()
            fids = {}
            for i, f in enumerate(r.topics()):
                trie.insert(f)
                fids[f] = i
                for w in f.split("/"):
                    if w not in ("+", "#"):
                        table.intern(w)
            fresh_insert_s = time.perf_counter() - t_f
            host = _build(trie, fids, table)
        # a usable rebuild ends with tables ON DEVICE, exactly like
        # the persistent path's rebuild() — excluding placement would
        # flatter the fresh baseline
        if r.config.use_device:
            jax.block_until_ready(jax.device_put(_dview(host)))
        fresh_rebuild_s = time.perf_counter() - t_f
    st = r.stats()
    bumps = r.cache_bump_totals()
    info = {
        "subs": n_subs, "batch": B, "build_s": round(build_s, 1),
        "churn_target_rate": rate,
        "churn_achieved_rate": rate_disj,
        "p50_ms_no_churn": round(p50_base, 3),
        "p99_ms_no_churn": round(p99_base, 3),
        "p50_ms_churn": round(p50_churn, 3),
        "rebuilds": st["rebuilds"], "patches": st["patches"],
        "bump_global": bumps["global"],
        "bump_partition": bumps["partition"],
        "device": str(jax.devices()[0]),
    }
    print(json.dumps(info), file=sys.stderr, flush=True)
    _emit({
        "metric": "churn_match_p99_ms",
        # ISSUE 7: the online delta automaton — the headline is now
        # measured with route churn absorbed by the side-automaton
        # (main tables pristine) and compaction off-lock; the stamp
        # invalidates staged partitioned_epochs_v1 rows (different
        # churn-plane machinery under the same metric name)
        "workload": "delta_automaton_v1",
        "value": round(p99_churn, 3),
        "unit": "ms",
        "vs_baseline": round(p99_base / p99_churn, 3)
        if p99_churn > 0 else 0.0,
        "p50_batch_ms": round(p50_churn, 3),
        "p99_batch_ms": round(p99_churn, 3),
        "cache_partitions": r.config.cache_partitions,
        "cache_hit_rate_churn": hit_disj,
        # churn-plane latency: the route op itself (ISSUE 7 — the
        # number the delta/off-lock design holds down)
        "route_op_p99_ms": route_p99,
        # variant rows: conservative global-bump shapes
        "root_wildcard_p99_ms": round(p99_rw, 3),
        "root_wildcard_hit_rate": hit_rw,
        "share_p99_ms": round(p99_sh, 3),
        "share_hit_rate": hit_sh,
        # whole-epoch A/B (None when CHURN_PARTITIONS=1 made the
        # headline itself whole-epoch)
        "whole_epoch_p99_ms": round(p99_whole, 3)
        if p99_whole is not None else None,
        "whole_epoch_hit_rate": hit_whole,
        "partition_speedup": round(p99_whole / p99_churn, 3)
        if p99_whole and p99_churn > 0 else None,
        # delta on/off A/B on the identical router/filter set
        "delta_off_p99_ms": round(p99_delta_off, 3)
        if p99_delta_off is not None else None,
        "delta_off_hit_rate": hit_delta_off,
        "delta_speedup": round(p99_delta_off / p99_churn, 3)
        if p99_delta_off and p99_churn > 0 else None,
        "route_op_p99_ms_delta_off": route_p99_off,
        "route_op_speedup": round(route_p99_off / route_p99, 3)
        if route_p99_off and route_p99 > 0 else None,
        "delta_merges": r.delta_info()["merges"],
        "rebuild_stall_ms": r.delta_info()["rebuild_stall_ms"],
        # steady-state compaction: persistent-trie flatten-only vs a
        # fresh-engine re-insert rebuild (the ≥3× acceptance row)
        "compaction_flatten_s": round(compaction_flatten_s, 3),
        "fresh_rebuild_s": round(fresh_rebuild_s, 3)
        if fresh_rebuild_s is not None else None,
        "fresh_insert_s": round(fresh_insert_s, 3)
        if fresh_insert_s is not None else None,
        "persistent_speedup": round(
            fresh_rebuild_s / compaction_flatten_s, 2)
        if fresh_rebuild_s and compaction_flatten_s > 0 else None,
    })


def flapstorm():
    """BENCH_MODE=flapstorm — sustained reconnect storm of a large
    subscriber population (ISSUE 7 acceptance): ``FLAP_PCT_PER_MIN``
    (default 10) percent of ``BENCH_SUBS`` churns per minute — each
    reconnect unsubscribes and resubscribes its filter, the
    mobile-fleet shape — while the publish match plane keeps serving
    with bounded p99 and a stable cache hit rate. A dedicated hot
    subset crash-loops hard enough to cross the ``emqx_flapping``
    threshold and gets auto-banned (every reconnect consults
    ``Banned.check``, as the product CONNECT path does), and session
    takeovers keep flowing through the ConnectionManager against
    channels of churning clients. Reports storm-time match p99 (vs a
    storm-free base), hit rate, route-op p99, ban count and takeover
    p99."""
    import sys
    import threading

    jax = _jax_with_retry()

    from emqx_tpu.banned import Banned
    from emqx_tpu.cm import ConnectionManager
    from emqx_tpu.flapping import Flapping, FlappingConfig
    from emqx_tpu.router import MatcherConfig, Router
    from emqx_tpu.session import Session

    rng = random.Random(0)
    n_subs = int(os.environ.get("BENCH_SUBS", "1000000"))
    B = int(os.environ.get("BENCH_BATCH", "256"))
    duration = float(os.environ.get("FLAP_SECONDS", "30"))
    pct_min = float(os.environ.get("FLAP_PCT_PER_MIN", "10"))

    filters, vocab = build_filters(rng, n_subs, 64)
    r = Router(MatcherConfig())
    t0 = time.time()
    for f in filters:
        r.add_route(f)
    topics = ["/".join(zipf_choice(rng, lvl) for lvl in vocab[:4])
              for _ in range(B * 8)]
    batches = [(topics[i * B:(i + 1) * B],) for i in range(8)]
    r.match_ids(batches[0][0])  # flatten + match jit warm
    # warm the partial hit/miss cache shapes a storm batch can take
    # (same sweep as BENCH_MODE=churn — without it the timed p99
    # measures first-touch XLA, not the storm)
    hot = list(dict.fromkeys(topics))[:B]
    r.match_ids(hot)
    # make the DELTA active before the shape sweep: flap a depth-
    # representative sample of the population (delete+re-add), so the
    # sweep below compiles the tombstone mask, side-automaton walk
    # and packed-union kernels at every (hit-pad, miss-pad) combo —
    # not the timed window. The pending warm deltas stay live so the
    # storm continues on the same compiled shapes.
    wrng = random.Random(9)
    for idx in wrng.sample(range(len(filters)), min(32, len(filters))):
        r.delete_route(filters[idx])
        r.add_route(filters[idx])

    def _p2(n, floor=8):
        out = floor
        while out < n:
            out *= 2
        return out

    fresh_i = [0]
    seen_sigs = set()
    for m in range(1, B + 1):
        sig = (_p2(max(B - m, 1)), _p2(m))
        if sig in seen_sigs:
            continue
        seen_sigs.add(sig)
        fresh = [f"wfresh/{fresh_i[0] + j}/x" for j in range(m)]
        fresh_i[0] += m
        r.match_ids(hot[:B - m] + fresh)
    for (b,) in batches:
        r.match_ids(b)
    build_s = time.time() - t0
    _set_prov(r)

    def step(batch):
        _, ids_np, _, _, _ = r.match_ids(batch)
        return ids_np

    p50_base, p99_base = _latency_pass(step, batches, 30)

    flapping = Flapping(
        banned=Banned(),
        config=FlappingConfig(max_count=15, window=60.0,
                              ban_time=300.0))
    cm = ConnectionManager()

    class _Chan:
        __slots__ = ("client_id", "session")

        def __init__(self, cid, sess):
            self.client_id = cid
            self.session = sess

        def takeover_begin(self):
            return self.session

        def takeover_end(self, rc):
            pass

    stop = threading.Event()
    counts = {"reconnects": 0, "ban_rejects": 0, "takeovers": 0}
    op_lat: list = []
    tko_lat: list = []
    # the crash-loopers: a small fleet stuck in a tight
    # connect/crash cycle — their rate is a property of the crash
    # loop (~5 reconnects/s each), NOT of the population size, so
    # they cross the flapping threshold (15-in-60s) within seconds
    # at any scale
    flap_ids = [f"flap-{i}" for i in range(8)]
    churn_rate = max(1.0, n_subs * pct_min / 100.0 / 60.0)

    c = r._match_cache_obj
    h0, m0 = (c.hits, c.misses) if c is not None else (0, 0)

    def storm():
        srng = random.Random(1)
        interval = 1.0 / churn_rate
        i = 0
        next_t = time.perf_counter()
        while not stop.is_set():
            idx = srng.randrange(len(filters))
            cid = f"c-{idx}"
            f = filters[idx]
            t_op = time.perf_counter()
            # the reconnect: session drops (unsubscribe), flap
            # tracking, ban gate, resubscribe
            r.delete_route(f)
            flapping.disconnected(cid)
            if flapping.banned.check(clientid=cid):
                counts["ban_rejects"] += 1
            r.add_route(f)  # population clients never cross the bar
            op_lat.append(time.perf_counter() - t_op)
            counts["reconnects"] += 1
            i += 1
            next_t += interval
            pause = next_t - time.perf_counter()
            if pause > 0:
                time.sleep(pause)

    def crash_loop():
        i = 0
        while not stop.is_set():
            fcid = flap_ids[i % len(flap_ids)]
            flapping.disconnected(fcid)
            if flapping.banned.check(clientid=fcid):
                counts["ban_rejects"] += 1
            i += 1
            time.sleep(0.025)  # ~5 reconnects/s per flapper

    def takeovers():
        j = 0
        while not stop.is_set():
            cid = f"tko-{j % 256}"
            old = cm.lookup_channel(cid)
            ch = _Chan(cid, Session(cid, clean_start=False))
            t_op = time.perf_counter()
            if old is None:
                cm.register_channel(cid, ch)
            else:
                cm.open_session(cid, clean_start=False, channel=ch)
                counts["takeovers"] += 1
                tko_lat.append(time.perf_counter() - t_op)
            j += 1
            time.sleep(0.002)

    th_storm = threading.Thread(target=storm, daemon=True)
    th_flap = threading.Thread(target=crash_loop, daemon=True)
    th_tko = threading.Thread(target=takeovers, daemon=True)
    t1 = time.time()
    th_storm.start()
    th_flap.start()
    th_tko.start()
    lat = []
    while time.time() - t1 < duration:
        for i in range(len(batches)):
            t_b = time.perf_counter()
            np.asarray(step(*batches[i]))
            lat.append((time.perf_counter() - t_b) * 1000.0)
    stop.set()
    th_storm.join(timeout=5)
    th_flap.join(timeout=5)
    th_tko.join(timeout=5)
    wall = time.time() - t1
    p50_storm = float(np.percentile(lat, 50))
    p99_storm = float(np.percentile(lat, 99))
    c = r._match_cache_obj
    hd = (c.hits - h0) if c is not None else 0
    md = (c.misses - m0) if c is not None else 0
    hit_rate = hd / max(1, hd + md)
    banned_n = sum(
        1 for fc in flap_ids
        if flapping.banned.look_up("clientid", fc) is not None)
    route_p99 = (float(np.percentile(np.array(op_lat) * 1000.0, 99))
                 if op_lat else 0.0)
    tko_p99 = (float(np.percentile(np.array(tko_lat) * 1000.0, 99))
               if tko_lat else 0.0)
    info = {
        "mode": "flapstorm", "subs": n_subs,
        "build_s": round(build_s, 1),
        "pct_per_min": pct_min,
        "achieved_churn_per_s": round(
            counts["reconnects"] / max(wall, 1e-9), 1),
        "reconnects": counts["reconnects"],
        "delta": r.delta_info(),
        "device": str(jax.devices()[0]),
    }
    print(json.dumps(info), file=sys.stderr, flush=True)
    _emit({
        "metric": "flapstorm_match_p99_ms",
        "workload": "flapstorm_v1",
        "value": round(p99_storm, 3),
        "unit": "ms",
        # 1.0 = the storm is invisible to the match plane
        "vs_baseline": round(p99_base / p99_storm, 3)
        if p99_storm > 0 else 0.0,
        "p50_batch_ms": round(p50_storm, 3),
        "p99_batch_ms": round(p99_storm, 3),
        "p99_ms_no_storm": round(p99_base, 3),
        "pct_per_min": pct_min,
        "achieved_churn_per_s": info["achieved_churn_per_s"],
        "cache_hit_rate_storm": round(hit_rate, 4),
        "route_op_p99_ms": round(route_p99, 3),
        "flappers_banned": banned_n,
        "ban_rejects": counts["ban_rejects"],
        "takeovers": counts["takeovers"],
        "takeover_p99_ms": round(tko_p99, 3),
        "delta_merges": r.delta_info()["merges"],
        "rebuild_stall_ms": r.delta_info()["rebuild_stall_ms"],
    })


def recovery():
    """BENCH_MODE=recovery — the durability layer's two costs
    (ISSUE 9): journal-append overhead on the live publish path
    (durability on/off A/B msgs/s with a durable QoS1 subscriber
    fleet — every delivery/ack dirties session state, every batch
    pays one coalesced journal flush) and crash-recovery time vs
    route count (``recovery_replay_s`` / ``recovery_routes``: full
    journal replay + session resurrection + baseline checkpoint,
    the kill -9 worst case with no checkpoint to shortcut)."""
    import asyncio
    import shutil
    import sys
    import tempfile

    jax = _jax_with_retry()

    from emqx_tpu.durability import DurabilityConfig
    from emqx_tpu.node import Node
    from emqx_tpu.session import Session
    from emqx_tpu.types import Message, SubOpts

    n_routes = int(os.environ.get(
        "RECOVERY_ROUTES", os.environ.get("BENCH_SUBS", "100000")))
    B = int(os.environ.get("BENCH_BATCH", "256"))
    pub_iters = int(os.environ.get("RECOVERY_PUB_ITERS", "20"))
    use_fsync = os.environ.get("RECOVERY_FSYNC", "1") == "1"
    wal_shards = int(os.environ.get("RECOVERY_SHARDS", "4"))
    ckpt_churn = int(os.environ.get("RECOVERY_CKPT_CHURN", "64"))
    n_sessions = min(int(os.environ.get("RECOVERY_SESSIONS", "1000")),
                     n_routes)
    rng = random.Random(0)
    filters = [f"rb/{i}/s" for i in range(n_routes)]
    pub_topics = [filters[rng.randrange(n_routes)]
                  for _ in range(B * 8)]
    batches = [pub_topics[i * B:(i + 1) * B] for i in range(8)]

    def _drain_acks(sessions):
        for s in sessions:
            for pid, item in s.drain_outbox():
                if isinstance(pid, int):
                    s.puback(pid)

    async def _build(durable, d):
        cfg = (DurabilityConfig(enabled=True, dir=d, fsync=use_fsync,
                                wal_shards=wal_shards)
               if durable else None)
        node = Node(boot_listeners=False, durability=cfg,
                    load_default_modules=True)
        await node.start()
        sessions = []
        per = n_routes // n_sessions
        for i in range(n_sessions):
            s = Session(f"dev-{i}", broker=node.broker,
                        clean_start=False, max_inflight=0)
            if durable:
                node.durability.session_opened(s, 3600.0)

                class _Ch:
                    def __init__(self, sess):
                        self.session = sess
                node.cm.register_channel(s.client_id, _Ch(s))
            for f in filters[i * per:(i + 1) * per]:
                s.subscribe(f, SubOpts(qos=1))
            sessions.append(s)
        return node, sessions

    def _window(node, sessions, durable, iters):
        sent = 0
        t1 = time.perf_counter()
        for it in range(iters):
            b = batches[it % len(batches)]
            node.broker.publish_batch(
                [Message(topic=t, payload=b"x", qos=1) for t in b])
            _drain_acks(sessions)
            if durable:
                # the batched journal flush the ingress executor
                # pays per tick on the socket path
                node.durability.on_batch()
            sent += len(b)
        return sent / max(time.perf_counter() - t1, 1e-9)

    async def _run():
        out = {}
        dirs = [tempfile.mkdtemp(prefix="emqx_dur_bench_")
                for _ in range(2)]
        # both nodes built and warmed BEFORE either timed window —
        # process-level XLA compile caching must not subsidize
        # whichever variant runs second
        node_off, sess_off = await _build(False, dirs[0])
        node_on, sess_on = await _build(True, dirs[1])
        for node, sessions, durable in ((node_off, sess_off, False),
                                        (node_on, sess_on, True)):
            _window(node, sessions, durable, len(batches))
        out["msgs_per_s_off"] = _window(node_off, sess_off, False,
                                        pub_iters)
        out["msgs_per_s_on"] = _window(node_on, sess_on, True,
                                       pub_iters)
        wi = node_on.durability.wal.info()
        out["journal_records"] = wi["records"]
        out["journal_mb"] = round(wi["bytes"] / 1e6, 2)
        out["last_fsync_ms"] = wi["last_fsync_ms"]
        out["group_commits"] = wi["group_commits"]
        # crash the durable node: abandon without graceful shutdown
        # — the recovery below replays the whole journal
        node_on.broker.durability = None
        node_on.cm.durability = None
        node_on.durability = None
        crash_dir = dirs[1]
        await node_off.stop()
        await node_on.stop()

        t2 = time.perf_counter()
        node2 = Node(boot_listeners=False,
                     durability=DurabilityConfig(
                         enabled=True, dir=crash_dir,
                         fsync=use_fsync),
                     load_default_modules=True)
        await node2.start()
        out["recovery_total_s"] = round(time.perf_counter() - t2, 3)
        rec = node2.durability.last_recovery
        out["recovery_replay_s"] = rec["duration_s"]
        out["recovered_sessions"] = rec["sessions"]
        out["replayed_records"] = rec["replayed_records"]
        out["recovered_routes"] = rec["routes"]
        # incremental-checkpoint cost A/B on the recovered node (it
        # holds the full-scale table): a FULL rebase pays the whole
        # table; a DELTA after a small churn burst must cost ~the
        # churn — the acceptance gate is that delta time tracks
        # churn, not route count (docs/DURABILITY.md)
        t_f0 = time.perf_counter()
        node2.durability.checkpoint_now(full=True)
        out["ckpt_full_s"] = round(time.perf_counter() - t_f0, 4)
        det = [ent[0] for ent in node2.cm._detached.values()]
        for i in range(ckpt_churn if det else 0):
            det[i % len(det)].subscribe(
                f"ckpt/churn/{i}", SubOpts(qos=1))
        node2.durability.on_batch()
        t_d0 = time.perf_counter()
        ck = node2.durability.checkpoint_now(full=False)
        out["ckpt_delta_s"] = round(time.perf_counter() - t_d0, 4)
        out["ckpt_delta_records"] = ck.get("records")
        await node2.stop()
        for d in dirs:
            shutil.rmtree(d, ignore_errors=True)
        return out

    r = asyncio.run(_run())

    def _gc_window_sweep():
        """ROADMAP item 5d: measure what group_commit_window_ms
        actually buys. T concurrent flushers (the multi-loop shape)
        hammer one fsync-armed WalGroup per window value; the sweep
        records fsyncs per flush call (coalescing win) against the
        added p50/p99 flush latency (the window's cost) — the
        docs/DURABILITY.md recommendation table is generated from
        exactly these columns."""
        import tempfile
        import threading as th

        from emqx_tpu.wal import WalGroup

        windows = [float(x) for x in os.environ.get(
            "RECOVERY_GC_WINDOWS", "0,1,3,10").split(",")]
        T = int(os.environ.get("RECOVERY_GC_THREADS", "4"))
        flushes = int(os.environ.get("RECOVERY_GC_FLUSHES", "50"))
        recs = int(os.environ.get("RECOVERY_GC_RECS", "32"))
        rows = []
        for w_ms in windows:
            d = tempfile.mkdtemp(prefix="emqx_gc_sweep_")
            wg = WalGroup(d, 1, shards=max(2, T), fsync=True,
                          group_window_ms=w_ms)
            lats: list = []
            lk = th.Lock()

            def _worker(ti):
                mine = []
                for i in range(flushes):
                    for j in range(recs):
                        wg.append(("route", f"g/{ti}/{i}/{j}",
                                   "bench", 1), key=f"k{ti}-{j}")
                    t0 = time.perf_counter()
                    wg.flush()
                    mine.append(
                        (time.perf_counter() - t0) * 1000.0)
                with lk:
                    lats.extend(mine)

            threads = [th.Thread(target=_worker, args=(t,))
                       for t in range(T)]
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            wall = time.perf_counter() - t0
            wi = wg.info()
            wg.close()
            shutil.rmtree(d, ignore_errors=True)
            lats.sort()
            n = len(lats)
            rows.append({
                "window_ms": w_ms,
                "fsyncs": wi["fsyncs"],
                "fsyncs_per_flush": round(
                    wi["fsyncs"] / max(n, 1), 3),
                "group_commits": wi["group_commits"],
                "coalesced": wi["group_coalesced"],
                "flush_p50_ms": round(lats[n // 2], 3),
                "flush_p99_ms": round(
                    lats[min(n - 1, int(n * 0.99))], 3),
                "flushes_per_s": round(n / max(wall, 1e-9)),
                "last_commit_ms": wi["last_commit_ms"],
            })
        return rows

    gc_sweep = None
    if os.environ.get("RECOVERY_GC_SWEEP", "1") == "1":
        gc_sweep = _gc_window_sweep()
    on, off = r["msgs_per_s_on"], r["msgs_per_s_off"]
    info = {"mode": "recovery", "routes": n_routes,
            "sessions": n_sessions, "fsync": use_fsync,
            "wal_shards": wal_shards,
            "device": str(jax.devices()[0])}
    print(json.dumps(info), file=sys.stderr, flush=True)
    _emit({
        "metric": "recovery_replay_s",
        "workload": "durability_sharded_v1",
        "value": r["recovery_replay_s"],
        "unit": "s",
        "recovery_routes": r["recovered_routes"],
        "recovery_sessions": r["recovered_sessions"],
        "recovery_records": r["replayed_records"],
        "recovery_total_s": r["recovery_total_s"],
        "recovery_records_per_s": round(
            r["replayed_records"] / max(r["recovery_replay_s"],
                                        1e-9)),
        "durability_on_msgs_per_s": round(on),
        "durability_off_msgs_per_s": round(off),
        "durability_overhead_pct": round(
            100.0 * (1.0 - on / max(off, 1e-9)), 1),
        "journal_records": r["journal_records"],
        "journal_mb": r["journal_mb"],
        "last_fsync_ms": r["last_fsync_ms"],
        "fsync": use_fsync,
        "wal_shards": wal_shards,
        "group_commits": r["group_commits"],
        "ckpt_full_s": r["ckpt_full_s"],
        "ckpt_delta_s": r["ckpt_delta_s"],
        "ckpt_delta_records": r["ckpt_delta_records"],
        "ckpt_churn": ckpt_churn,
        "ckpt_speedup": round(
            r["ckpt_full_s"] / max(r["ckpt_delta_s"], 1e-9), 2),
        "gc_window_sweep": gc_sweep,
    })


def _failover_probe():
    """The BENCH_MODE=partition failover + FAILBACK rows
    (docs/DURABILITY.md "Replicated durability" / "Failback"): a
    durable primary journals ``FAILOVER_SESSIONS`` persistent
    sessions (default 5000 — a real fleet, not a toy) + retained +
    routes and ships the stream to a warm standby; the primary is
    killed (kill -9 analogue: durability hooks severed, transport
    dropped) and the standby's heartbeat detector drives promotion.
    Measures failover time (kill → promoted), RPO in records for
    acked traffic (must be 0), and digest-verifies the promoted
    durable planes against the primary's pre-kill state. Then the
    primary RESTARTS from its own directory, rejoins, and the
    promoted standby hands the (post-promotion-churned) state back:
    ``failback_s`` = restart → standby demoted + stream resynced,
    digest-verified against the standby's pre-failback state.
    ``PARTITION_FAILBACK=0`` skips the second hop."""
    import shutil
    import tempfile

    from emqx_tpu.cluster import Cluster, ClusterConfig
    from emqx_tpu.cluster_net import SocketTransport
    from emqx_tpu.durability import DurabilityConfig
    from emqx_tpu.modules.retainer import RetainerModule
    from emqx_tpu.node import Node
    from emqx_tpu.replication import durable_digest
    from emqx_tpu.session import Session
    from emqx_tpu.types import Message, SubOpts

    n_sess = int(os.environ.get("FAILOVER_SESSIONS", "5000"))
    n_ret = int(os.environ.get("FAILOVER_RETAINED", "100"))
    cfg = ClusterConfig(
        heartbeat_interval_s=0.1, heartbeat_timeout_s=0.5,
        suspect_after=1, down_after=3, ok_after=1,
        anti_entropy_interval_s=0.5, call_timeout_s=10.0,
        redial_backoff_s=0.1, redial_backoff_max_s=0.5)

    def _wait(pred, timeout, what):
        deadline = time.perf_counter() + timeout
        while time.perf_counter() < deadline:
            if pred():
                return
            time.sleep(0.02)
        raise RuntimeError(f"failover probe: {what} not reached "
                           f"within {timeout}s")

    class _Ch:
        def __init__(self, s):
            self.session = s
            self.client_id = s.client_id

    tmp = tempfile.mkdtemp(prefix="emqx_failover_")
    nodes, trs, cls = [], [], []
    try:
        for i in range(2):
            dcfg = None
            if i == 0:
                dcfg = DurabilityConfig(
                    enabled=True, dir=os.path.join(tmp, "d0"),
                    fsync=False, standby="fb1", wal_shards=4)
            node = Node(name=f"fb{i}", boot_listeners=False,
                        durability=dcfg)
            node.modules.load(RetainerModule)
            if node.durability is not None:
                node.durability.recover()
            tr = SocketTransport(f"fb{i}", cookie="bench-failover",
                                 config=cfg)
            tr.serve()
            cls.append(Cluster(node, transport=tr, config=cfg))
            nodes.append(node)
            trs.append(tr)
        cls[1].join_remote("127.0.0.1", trs[0].port)
        n0 = nodes[0]
        sessions = []
        for i in range(n_sess):
            s = Session(f"fdev-{i}", broker=n0.broker,
                        clean_start=False)
            n0.durability.session_opened(s, 3600.0)
            n0.cm.register_channel(s.client_id, _Ch(s))
            s.subscribe(f"fb/{i}/+", SubOpts(qos=1))
            sessions.append(s)
        for i in range(n_ret):
            n0.broker.publish(Message(
                topic=f"fb/{i % max(n_sess, 1)}/state",
                payload=b"v%d" % i, qos=1, flags={"retain": True}))
        n0.durability.on_batch()  # flush + ship: this is the acked set
        r = n0.replication
        _wait(lambda: r.state == "replicating"
              and r.acked_seq >= r.offered_seq, 60, "journal sync")
        acked = r.acked_seq
        for s in sessions:  # digest compares the sessions detached
            n0.cm._detached[s.client_id] = (s, 0, 3600.0)
        want = durable_digest(n0)
        # kill -9: no graceful path, no final ship
        n0.broker.durability = None
        n0.cm.durability = None
        t_kill = time.perf_counter()
        trs[0].close()
        rep1 = nodes[1].replication
        _wait(lambda: "fb0" in rep1.replicas
              and rep1.replicas["fb0"].promoted, 60, "promotion")
        failover_s = time.perf_counter() - t_kill
        got = durable_digest(nodes[1])
        lp = rep1.last_promotion
        out = {
            "failover_s": round(failover_s, 3),
            "failover_promote_s": lp["failover_s"],
            "failover_sessions": lp["sessions"],
            "failover_routes": lp["routes"],
            "rpo_records": max(
                0, acked - rep1.replicas["fb0"].applied_seq),
            "failover_digest_ok": bool(got == want),
            "failback_s": None,
            "failback_sessions": None,
            "failback_digest_ok": None,
        }
        if os.environ.get("PARTITION_FAILBACK", "1") == "1":
            # post-promotion churn the failback must carry home
            nodes[1].broker.publish(Message(
                topic="fb/0/state", payload=b"post-promo", qos=1,
                flags={"retain": True}))
            want2 = durable_digest(nodes[1])
            t_fb = time.perf_counter()
            n0b = Node(name="fb0", boot_listeners=False,
                       durability=DurabilityConfig(
                           enabled=True,
                           dir=os.path.join(tmp, "d0"),
                           fsync=False, standby="fb1",
                           wal_shards=4))
            n0b.modules.load(RetainerModule)
            n0b.durability.recover()
            tr0b = SocketTransport("fb0", cookie="bench-failover",
                                   config=cfg)
            tr0b.serve()
            cl0b = Cluster(n0b, transport=tr0b, config=cfg)
            nodes.append(n0b)
            trs.append(tr0b)
            cls.append(cl0b)
            cl0b.join_remote("127.0.0.1", trs[1].port)
            _wait(lambda: not rep1.replicas["fb0"].promoted, 120,
                  "failback demotion")
            r0 = n0b.replication

            def _resynced():
                # tick the journal flush the started-node timer
                # would run (records journaled by the failback apply
                # must flush to ship)
                n0b.durability.on_batch()
                return (r0.state == "replicating"
                        and r0.acked_seq >= r0.offered_seq)

            _wait(_resynced, 120, "post-failback resync")
            out["failback_s"] = round(
                time.perf_counter() - t_fb, 3)
            out["failback_sessions"] = len(n0b.cm._detached)
            try:
                _wait(lambda: durable_digest(n0b) == want2, 60,
                      "failback digest")
                out["failback_digest_ok"] = True
            except RuntimeError:
                out["failback_digest_ok"] = False
            fb = nodes[1].replication.last_failback
            if fb:
                out["failback_handoff_s"] = fb.get("failback_s")
        return out
    finally:
        for node in nodes:
            d = node.durability
            if d is not None and d.wal is not None:
                d.wal.close()
        for c in cls:
            c.close()
        for tr in trs:
            tr.close()
        shutil.rmtree(tmp, ignore_errors=True)


def partition():
    """BENCH_MODE=partition — the cluster plane's three failure
    numbers (ISSUE 10, docs/CLUSTER.md): detection latency (partition
    armed → both sides observe the membership split via the heartbeat
    detector), heal-to-convergence time (partition disarmed → all
    five replicated plane digests byte-equal across members, zero
    manual rejoin), and data-plane forwards dropped during a timed
    partition window with route churn on BOTH sides of the split.
    Plus (ISSUE 11) the warm-standby FAILOVER row: primary kill →
    standby promotion time, RPO records for acked traffic (0), and a
    digest-verified byte-exactness check — ``PARTITION_FAILOVER=0``
    skips it.

    3 nodes in one process over real sockets, the partition injected
    through the net.partition fault point scoped per transport —
    the same machinery the chaos matrix (tests/test_cluster_heal.py)
    gates, at bench scale."""
    import sys

    jax = _jax_with_retry()

    from emqx_tpu import faults
    from emqx_tpu.cluster import Cluster, ClusterConfig
    from emqx_tpu.cluster_net import SocketTransport
    from emqx_tpu.node import Node

    n_routes = int(os.environ.get(
        "PARTITION_ROUTES", os.environ.get("BENCH_SUBS", "3000")))
    window_s = float(os.environ.get("PARTITION_SECONDS", "3"))
    cfg = ClusterConfig(
        heartbeat_interval_s=0.1, heartbeat_timeout_s=0.5,
        suspect_after=1, down_after=3, ok_after=1,
        anti_entropy_interval_s=0.5, call_timeout_s=2.0,
        redial_backoff_s=0.1, redial_backoff_max_s=0.5)

    class _Sub:
        def __init__(self, cid):
            self.client_id = cid

        def deliver(self, t, m):
            pass

    def _wait(pred, timeout, what):
        deadline = time.perf_counter() + timeout
        while time.perf_counter() < deadline:
            if pred():
                return time.perf_counter()
            time.sleep(0.02)
        raise RuntimeError(f"partition bench: {what} not reached "
                           f"within {timeout}s")

    def _converged(cls):
        digests = [c.plane_digests() for c in cls]
        return all(d == digests[0] for d in digests[1:])

    nodes, trs, cls = [], [], []
    try:
        for i in range(3):
            node = Node(name=f"bn{i}", boot_listeners=False)
            tr = SocketTransport(f"bn{i}", cookie="bench-part",
                                 config=cfg)
            tr.serve()
            cls.append(Cluster(node, transport=tr, config=cfg))
            nodes.append(node)
            trs.append(tr)
        for i in (1, 2):
            cls[i].join_remote("127.0.0.1", trs[0].port)
        subs = []
        for i in range(n_routes):
            s = _Sub(f"bsub-{i}")
            nodes[i % 3].broker.subscribe(s, f"bench/p/{i}")
            subs.append(s)
        _wait(lambda: _converged(cls), 60, "pre-partition sync")
        for c in cls:
            c.drain_counters()  # window counters start clean

        # -- partition {bn0, bn1} | {bn2}, churn on both sides -----
        trs[0].fault_peers = trs[1].fault_peers = {"bn2"}
        trs[2].fault_peers = {"bn0", "bn1"}
        faults.set_master(True)
        t0 = time.perf_counter()
        faults.arm("net.partition", times=0)
        t_detect = _wait(
            lambda: cls[0].members == ["bn0", "bn1"]
            and cls[2].members == ["bn2"], 30, "detection")
        detect_s = t_detect - t0
        churn = 0
        t_end = time.perf_counter() + window_s
        while time.perf_counter() < t_end:
            i = churn % n_routes
            side = nodes[0] if churn % 2 else nodes[2]
            s = _Sub(f"churn-{churn}")
            side.broker.subscribe(s, f"bench/c/{i}")
            side.broker.unsubscribe(s, f"bench/c/{i}")
            churn += 1
            time.sleep(0.002)

        # -- heal: zero manual rejoin --------------------------------
        t1 = time.perf_counter()
        faults.disarm("net.partition")
        _wait(lambda: all(sorted(c.members) == ["bn0", "bn1", "bn2"]
                          for c in cls), 60, "membership re-merge")
        t_conv = _wait(lambda: _converged(cls), 60,
                       "plane-digest convergence")
        heal_s = t_conv - t1
        counters = {}
        for c in cls:
            for k, v in c.drain_counters().items():
                counters[k] = counters.get(k, 0) + v
    finally:
        faults.clear()
        for c in cls:
            c.close()
        for tr in trs:
            tr.close()

    failover = {"failover_s": None, "rpo_records": None,
                "failover_digest_ok": None}
    if os.environ.get("PARTITION_FAILOVER", "1") == "1":
        failover = _failover_probe()

    info = {"mode": "partition", "routes": n_routes,
            "window_s": window_s, "churn_ops": churn,
            "device": str(jax.devices()[0])}
    print(json.dumps(info), file=sys.stderr, flush=True)
    _emit(dict({
        "metric": "partition_heal_converge_s",
        "workload": "cluster_failover_v1",
        "value": round(heal_s, 3),
        "unit": "s",
        "partition_detect_s": round(detect_s, 3),
        "partition_window_s": window_s,
        "partition_churn_ops": churn,
        "forwards_dropped": counters.get("forward.dropped", 0),
        "heal_rejoins": counters.get("heal.rejoins", 0),
        "ae_repairs": counters.get("ae.repairs", 0),
        "hb_downs": counters.get("hb.downs", 0),
        "routes": n_routes,
    }, **failover))


# The BASELINE.json config matrix (VERDICT r3 item 3): one row per
# driver-defined config, plus the uniform-traffic variant (no
# batch-dedup advantage) and a paced live row for per-message p99
# delivery latency. Each entry: (row name, extra env, BENCH_MODE,
# subs on TPU, subs on the CPU fallback — bounded so a fallback run
# finishes inside the driver's patience).
_CONFIG_MATRIX = [
    # headline FIRST: if the driver's patience runs out mid-matrix,
    # the round-over-round metric must already be in the row list.
    # It keeps the historical 5-window/20-iter effort — r02/r03
    # records were measured that way and the comparison must hold
    ("mixed_1m_zipf", {"BENCH_ITERS": "20", "BENCH_WINDOWS": "5"},
     None, 1_000_000, 100_000),
    ("literal_100k", {"BENCH_MIX": "literal", "BENCH_LEVELS": "1",
                      "BENCH_WPL": "100000"}, None, 100_000, 100_000),
    ("plus_1m", {"BENCH_MIX": "plus"}, None, 1_000_000, 200_000),
    # the two compaction A/B rows (ISSUE 16): the deep row is where
    # path compression lives (16-level spines, hops ≪ levels), the
    # uniform row is the guard against the flat-tree regression
    ("hash_1m_deep", {"BENCH_MIX": "hash", "BENCH_LEVELS": "16",
                      "BENCH_COMPRESS_AB": "1"},
     None, 1_000_000, 200_000),
    ("share_1m", {}, "shared", 1_000_000, 200_000),
    ("mixed_10m", {}, None, 10_000_000, 500_000),
    ("mixed_1m_uniform",
     {"BENCH_TRAFFIC": "uniform", "BENCH_COMPRESS_AB": "1"}, None,
     1_000_000, 100_000),
    # match-cache A/B rows (same workloads as the two rows above;
    # the cache-off rows ARE the baseline half of the pair): the
    # Zipf 10M row is the cache's home turf (hot topics repeat
    # across ticks), the uniform row its worst case (today's worst
    # bench row, 0.525x — every topic pays walk + compaction)
    ("mixed_10m_cache", {"BENCH_CACHE": "1"}, None,
     10_000_000, 500_000),
    ("mixed_1m_uniform_cache",
     {"BENCH_TRAFFIC": "uniform", "BENCH_CACHE": "1"}, None,
     1_000_000, 100_000),
    # small-batch tail-latency operating point: per-step device
    # latency with the tunnel RTT amortized over a compiled chain
    ("latency_8k", {"BENCH_BATCH": "8192", "BENCH_CHAIN": "32"},
     "latency", 1_000_000, 100_000),
    # subscribe-time retained replay (ISSUE 19): 1M retained names,
    # mixed literal/wildcard bursts, batched-device vs host-scan A/B
    # + the wire-replay contract booleans (zero lost, onloop 0)
    ("retained_1m", {"RETAINED_BURST": "64", "RETAINED_BURSTS": "8"},
     "retained", 1_000_000, 100_000),
    # live row pinned to the CPU backend: it measures the HOST wire
    # path (socket→deliver, host-regime filters — no device work at
    # these counts), and in the round-4 TPU run a half-wedged tunnel
    # made its in-process jax init hang for the row's full 900s
    # budget. Pinning is labeled (row platform reads "cpu").
    ("live_paced", {"LIVE_RATE": "400", "LIVE_SECS": "5",
                    "LIVE_PIPELINE": "4", "BENCH_PLATFORM": "cpu",
                    # host regime: the dispatch planner never engages
                    # below device_min_filters, so an off-pass would
                    # measure the same tail twice
                    "LIVE_AB": "0"},
     "live", 0, 0),
    # dispatch-planner A/B (docs/DISPATCH.md): the DEVICE live regime
    # (background filters past device_min_filters) at saturating
    # fan-out — the one record carries both tails' msgs/sec and
    # wakeups/batch (planner_off_* columns). The planner pass runs
    # FIRST, so any residual in-process warmup cost lands on the new
    # tail, not the baseline — conservative for the speedup column
    ("live_fan_ab", {"LIVE_FILTERS": "1200", "LIVE_SUBS": "32",
                     "LIVE_TOPICS": "16", "LIVE_SECS": "5",
                     "BENCH_PLATFORM": "cpu"},
     "live", 0, 0),
]

_HEADLINE_ROW = "mixed_1m_zipf"


#: matrix-wide methodology revision, folded into every row's spec: a
#: change that redefines what ALL rows measure (round 5: the
#: compressed-walk kernel + algebra-derived k) must invalidate staged
#: rows mechanically, the way _MODE_WORKLOADS does for modes — round
#: 4's adaptive-K change relied on a manual full re-run instead
#: (ADVICE r4 item 1). Round 6: the native builder level-compresses
#: the automaton and the TPU walk runs the VMEM-resident Pallas
#: kernel — every row measures a different walk.
_METHOD_REV = "walkv3_compact"


def _row_spec(name: str, extra: dict, mode, subs_tpu) -> str:
    """Stable fingerprint of a matrix row's workload spec. Resume
    reuse requires the staged row to match: editing a row's
    parameters (subs, mix, levels…) or bumping _METHOD_REV must
    invalidate its staged measurement, not silently satisfy the new
    spec with old data."""
    import hashlib

    blob = json.dumps([name, extra, mode, subs_tpu, _METHOD_REV],
                      sort_keys=True)
    return hashlib.sha1(blob.encode()).hexdigest()[:10]


def _last_json_line(text: str):
    """Last '{'-opening line of a stream, parsed — the bench line /
    info line extraction idiom shared by the orchestrator paths."""
    lines = [l for l in text.strip().splitlines() if l.startswith("{")]
    return json.loads(lines[-1]) if lines else None


def _probe_platform(timeout: float):
    """Backend platform via a bounded SUBPROCESS probe (an in-process
    probe would wedge this orchestrator's backend lock forever on a
    hung tunnel). None = unreachable."""
    import subprocess
    import sys

    try:
        res = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.devices()[0].platform)"],
            timeout=timeout, capture_output=True, text=True)
        if res.returncode == 0 and res.stdout.strip():
            return res.stdout.strip().splitlines()[-1]
    except subprocess.TimeoutExpired:
        pass
    except Exception:
        pass
    return None


def configs():
    """Default mode: run the full BASELINE config matrix, one bounded
    subprocess per config (fresh process = clean dispatch mode and an
    honest single-readback window per config — see
    docs/PERF_NOTES.md on readback poisoning), and emit ONE record
    whose ``configs`` array carries every row. The headline value/
    latency fields come from the historical 1M-mixed-Zipf workload so
    the metric stays comparable across rounds."""
    import subprocess
    import sys

    probe_timeout = float(os.environ.get("BENCH_INIT_TIMEOUT", "150"))
    cfg_timeout = float(os.environ.get("BENCH_CFG_TIMEOUT", "900"))
    forced = os.environ.get("BENCH_PLATFORM")
    plat = forced if forced else _probe_platform(probe_timeout)
    fallback = plat is None or plat == "cpu"
    if plat is None and os.environ.get("BENCH_NO_FALLBACK"):
        raise BenchInitError(
            f"backend probe failed (> {probe_timeout:.0f}s or error)")
    # global wall budget: skip (and label) remaining rows rather than
    # letting the driver's own timeout kill the process before the
    # final JSON line prints
    deadline = time.monotonic() + float(
        os.environ.get("BENCH_DEADLINE", "3000"))
    # BENCH_RESUME=1 (the recovery probe loop sets it): rows already
    # measured on a real accelerator are reused from the staged
    # artifact so a short tunnel-recovery window is spent ONLY on the
    # rows still missing — each window fills in more of the matrix
    # instead of re-measuring the headline until the tunnel re-wedges.
    # stamp each EXECUTED row with the tree revision: resume can
    # legitimately combine rows measured days apart, and a mixed-
    # revision aggregate must be distinguishable from a single-run one
    try:
        rev = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            capture_output=True, text=True, timeout=10,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        ).stdout.strip() or "unknown"
    except Exception:
        rev = "unknown"
    staged_rows = {}
    staged_ts = "unknown"
    if os.environ.get("BENCH_RESUME") and not fallback:
        last = _last_good_tpu(_MATRIX_METRIC) or {}
        staged_ts = last.get("ts", "unknown")
        staged_rows = {r.get("name"): r
                       for r in last.get("configs", []) if _good_row(r)}
    # BENCH_ONLY=a,b — targeted refresh: measure ONLY the named rows
    # (whitespace-tolerant); everything else is skip-labeled and
    # inherits its staged measurement through the merge. A named row
    # is measured even under BENCH_RESUME — an explicit selection IS
    # the request to re-measure, not to reuse.
    only = [s.strip() for s in
            os.environ.get("BENCH_ONLY", "").split(",") if s.strip()]
    rows = []
    ran_any = False
    for name, extra, mode, subs_tpu, subs_cpu in _CONFIG_MATRIX:
        spec = _row_spec(name, extra, mode, subs_tpu)
        # rows staged before spec-stamping existed were measured under
        # the then-current matrix; absence of "spec" is accepted once
        # — any row executed from here on carries its spec
        if name in staged_rows and not (only and name in only) \
                and staged_rows[name].get("spec", spec) == spec:
            # keep the ORIGINAL measurement time: re-staging stamps a
            # fresh top-level ts, and without measured_ts an all-
            # reused cycle would make old numbers look fresh
            row = dict(staged_rows[name], reused_staged=True)
            row.setdefault("measured_ts",
                           row.pop("carried_ts", staged_ts))
            # pre-spec rows: record the acceptance explicitly so the
            # once-only grace actually expires on re-staging
            row.setdefault("spec", spec)
            rows.append(row)
            continue
        if time.monotonic() > deadline:
            rows.append({"name": name,
                         "error": "skipped: BENCH_DEADLINE reached"})
            continue
        if only and name not in only:
            # targeted refresh: unselected rows are skip-labeled and
            # inherit their staged measurement through the merge,
            # exactly like a deadline skip
            rows.append({"name": name,
                         "error": "skipped: not in BENCH_ONLY"})
            continue
        env = dict(os.environ)
        for k_, v_ in extra.items():
            if k_ in ("BENCH_ITERS", "BENCH_WINDOWS") \
                    and k_ in os.environ:
                continue  # explicit operator effort override wins
            env[k_] = v_
        env["BENCH_NO_FALLBACK"] = "1"
        # an unset BENCH_MODE means `configs` since r4 — the child
        # must run the CONCRETE mode or it would recurse into this
        # orchestrator. Children never stage: only the parent's
        # aggregate may claim the headline metric's last-good slot.
        env["BENCH_MODE"] = mode or "mixed"
        env["BENCH_NO_STAGE"] = "1"
        subs = subs_cpu if fallback else subs_tpu
        if subs:
            env["BENCH_SUBS"] = str(subs)
        if fallback:
            env["BENCH_PLATFORM"] = "cpu"
        # per-row effort smaller than a solo run; explicit env wins
        env.setdefault("BENCH_ITERS", "12")
        env.setdefault("BENCH_WINDOWS", "3")
        t0 = time.time()
        ran_any = True
        row = {"name": name, "subs": subs or None, "rev": rev,
               "spec": spec}
        try:
            budget = min(cfg_timeout,
                         max(60.0, deadline - time.monotonic()))
            out = subprocess.run(
                [sys.executable, os.path.abspath(__file__)],
                capture_output=True, timeout=budget, env=env,
                text=True)
            rec = _last_json_line(out.stdout)
            if rec is None:
                row["error"] = "no JSON line from child"
            elif "error" in rec:
                row["error"] = rec["error"]
            else:
                for fld in ("metric", "value", "unit", "vs_baseline",
                            "p50_batch_ms", "p99_batch_ms",
                            "p99_deliver_ms", "platform"):
                    if fld in rec:
                        row[fld] = rec[fld]
                # the child's stderr info line carries the workload
                # context that makes a logical-rate row honest — a
                # Zipf batch can dedup 400x, and without the unique
                # count alongside, the row would overstate itself
                try:
                    inf = _last_json_line(out.stderr) or {}
                    for fld in ("avg_unique_topics", "batch",
                                "build_s", "build_cached", "native",
                                "unique_kmsgs_per_s",
                                "avg_deliveries_per_unique", "k",
                                "overflow_frac",
                                "cache", "cache_slots",
                                "cache_hit_rate",
                                "cache_warm_hit_rate",
                                "walk_levels_p50",
                                "gathers_per_topic",
                                "compress_off_p50_ms",
                                "compress_speedup",
                                "thr_logical_msgs_per_s", "chain"):
                        if fld in inf:
                            row[fld] = inf[fld]
                except Exception:
                    pass
                # measurement effort, recorded per row: an operator
                # override (BENCH_ITERS/WINDOWS) may change it, and a
                # headline measured at reduced effort must say so
                row["iters"] = int(env.get("BENCH_ITERS", "20"))
                row["windows"] = int(env.get("BENCH_WINDOWS", "5"))
        except subprocess.TimeoutExpired:
            row["error"] = f"config timed out > {budget:.0f}s"
        except Exception as e:
            row["error"] = repr(e)[:200]
        row["wall_s"] = round(time.time() - t0, 1)
        rows.append(row)
        print(json.dumps(row), file=sys.stderr, flush=True)

    head = next((r for r in rows
                 if r["name"] == _HEADLINE_ROW and "error" not in r),
                None)
    live_row = next((r for r in rows
                     if r["name"] == "live_paced" and "error" not in r),
                    None)
    rec = {
        "metric": _MATRIX_METRIC,
        "unit": "msgs/sec",
        "platform": plat or "unreachable",
        "configs": rows,
    }
    if head is not None:
        for fld in _HEADLINE_FIELDS:
            if fld in head:
                rec[fld] = head[fld]
    else:
        rec["value"] = rec["vs_baseline"] = None
    if live_row is not None and "p99_deliver_ms" in live_row:
        # keep the literal key (VERDICT r3 item 9's done-check), but
        # label its provenance explicitly: socket-to-deliver latency
        # is a HOST wire-path metric and the live row is CPU-pinned —
        # the platform field says so instead of an impersonating
        # unlabeled number or a renamed key nobody finds
        rec["p99_deliver_ms"] = live_row["p99_deliver_ms"]
        rec["p99_deliver_platform"] = live_row.get("platform",
                                                   "unknown")
    if fallback:
        # same labeling contract as _cpu_fallback_record: a CPU
        # number must never impersonate a TPU result
        for fld in _HEADLINE_FIELDS + ("p99_deliver_ms",):
            if rec.get(fld) is not None:
                rec[f"cpu_{fld}"] = rec.pop(fld)
        rec["value"] = rec["vs_baseline"] = None
        rec["platform_fallback"] = "cpu"
        if plat is None:
            rec["tpu_error"] = (
                f"backend probe failed (> {probe_timeout:.0f}s)")
        last = _last_good_tpu(rec["metric"])
        if last is not None:
            rec["last_good_tpu"] = last
        print(json.dumps(rec), flush=True)
        return
    # real accelerator: stage into the last-good artifact (the
    # in-process _emit would init a backend here; platform is already
    # known from the probe, so stage directly). Stage when anything
    # actually RAN and produced at least one good row — a run whose
    # HEADLINE failed still banks its other measurements (merge
    # inherits the staged headline, so the aggregate value survives);
    # an all-reused resume cycle must not re-stamp the artifact's ts
    # over measurements it didn't make.
    staged = None
    if ran_any and any(_good_row(r) and not r.get("reused_staged")
                       for r in rows):
        staged = _stage_tpu_record(rec)
        if staged is not None and rec.get("value") is None:
            # surface the merge-inherited headline on the emitted
            # line too, marked by headline_carried_ts
            for fld in _HEADLINE_FIELDS + ("headline_carried_ts",):
                if fld in staged:
                    rec[fld] = staged[fld]
    # a healthy-tunnel run that still lost rows (re-wedge, deadline
    # skips) attaches the staged record — the SAME full-record shape
    # the fallback path attaches — which merge-keeps every row ever
    # measured on a real accelerator
    if not all(_good_row(r) for r in rows):
        last = staged if staged is not None \
            else _last_good_tpu(_MATRIX_METRIC)
        if last is not None:
            rec["last_good_tpu"] = last
    print(json.dumps(rec), flush=True)


# mode -> (entry fn name, success-path metric name, unit); the
# fail-soft record must carry the SAME metric name the mode reports
# on success, or a failed run vanishes from per-metric time series
_MODES = {
    "bigfan": ("bigfan", "bigfan_bitmap_deliveries", "deliveries/sec"),
    "shared": ("shared", "shared_dispatch_throughput", "msgs/sec"),
    "live": ("live", "live_socket_throughput", "msgs/sec"),
    "latency": ("latency", "latency_8k_p99_ms", "ms"),
    "churn": ("churn", "churn_match_p99_ms", "ms"),
    "flapstorm": ("flapstorm", "flapstorm_match_p99_ms", "ms"),
    "overload": ("overload", "overload_delivered_msgs_per_s",
                 "msgs/sec"),
    "devloss": ("devloss", "devloss_host_fallback_msgs_per_s",
                "msgs/sec"),
    "drain": ("drain", "drain_time_to_empty_s", "s"),
    "fleet": ("fleet", "fleet_delivered_msgs_per_s", "msgs/sec"),
    "recovery": ("recovery", "recovery_replay_s", "s"),
    "partition": ("partition", "partition_heal_converge_s", "s"),
    "sharded": ("sharded", "sharded_publish_throughput", "msgs/sec"),
    "deep_smoke": ("deep_smoke", "deep_smoke_parity", "ok"),
    "retained": ("retained", "retained_subs_per_s", "subs/sec"),
    "mixed": ("main", "publish_match_fanout_throughput", "msgs/sec"),
    "configs": ("configs", "publish_match_fanout_throughput",
                "msgs/sec"),
    None: ("configs", "publish_match_fanout_throughput", "msgs/sec"),
}

#: mode -> required `workload` stamp on a staged record for it to
#: count as "already measured" (the mode analogue of the matrix
#: rows' _row_spec rule: a methodology change must invalidate staged
#: measurements, not silently satisfy the new definition with old
#: data). Modes absent here accept any staged record.
_MODE_WORKLOADS = {
    "sharded": "deduped_tick_v3_invexp",
    "shared": "walkv2",
    "churn": "delta_automaton_v1",
    "live": "probe_v1",
    "flapstorm": "flapstorm_v1",
    "overload": "overload_curve_v1",
    "devloss": "devloss_v2_deep",  # + the deep-bucket rewarm proof
    "drain": "drain_v1",
    "fleet": "fleet_v1",
    "recovery": "durability_v1",
    "partition": "cluster_heal_v1",
    "retained": "retained_v1",
}


def mode_staged_done(mode: str) -> bool:
    """True when `mode`'s metric is already staged from a real-
    accelerator run AND (where the mode declares one) the staged
    record carries the current workload stamp — the probe loop's
    staged-skip predicate."""
    _, metric, _ = _MODES[mode]
    rec = _last_good_tpu(metric)
    if rec is None or rec.get("value") is None:
        return False
    want = _MODE_WORKLOADS.get(mode)
    return want is None or rec.get("workload") == want


def _cpu_fallback_record(metric: str, tpu_error: str):
    """The chip is unreachable: re-run the same mode on CPU in a
    SUBPROCESS (a hung TPU init holds this process's backend lock
    forever) with a bounded workload, and emit its number explicitly
    flagged — a labeled CPU measurement proves the whole pipeline
    works, where a bare zero proves nothing."""
    import subprocess
    import sys

    env = dict(os.environ)
    env["BENCH_PLATFORM"] = "cpu"
    env["BENCH_NO_FALLBACK"] = "1"
    env["BENCH_SUBS"] = str(min(
        int(os.environ.get("BENCH_SUBS", "1000000")), 100000))
    env.setdefault("BENCH_ITERS", "20")
    try:
        out = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            capture_output=True, timeout=600, env=env, text=True)
        line = [l for l in out.stdout.strip().splitlines()
                if l.startswith("{")][-1]
        rec = json.loads(line)
        if rec.get("metric") != metric or "error" in rec:
            return None
        # the CPU figure must not impersonate a TPU result: `value`
        # nulls out, the measurement moves to cpu_* fields, and the
        # last driver-witnessed TPU record (if any) rides along
        rec["cpu_value"] = rec.pop("value", None)
        rec["cpu_vs_baseline"] = rec.pop("vs_baseline", None)
        if "p50_batch_ms" in rec:
            rec["cpu_p50_batch_ms"] = rec.pop("p50_batch_ms")
        if "p99_batch_ms" in rec:
            rec["cpu_p99_batch_ms"] = rec.pop("p99_batch_ms")
        rec["value"] = None
        rec["vs_baseline"] = None
        rec["platform_fallback"] = "cpu"
        rec["tpu_error"] = tpu_error[:300]
        return rec
    except Exception:
        return None


if __name__ == "__main__":
    _mode = os.environ.get("BENCH_MODE")
    _fn_name, _metric, _unit = _MODES.get(_mode, _MODES[None])
    try:
        globals()[_fn_name]()
    except Exception as _e:  # fail-soft: always emit the JSON line
        import sys
        import traceback
        traceback.print_exc()
        _rec = None
        if isinstance(_e, BenchInitError) \
                and not os.environ.get("BENCH_NO_FALLBACK") \
                and os.environ.get("BENCH_PLATFORM") != "cpu":
            _rec = _cpu_fallback_record(_metric, repr(_e))
        if _rec is None:
            _rec = {
                "metric": _metric,
                "value": None,
                "unit": _unit,
                "vs_baseline": None,
                "error": repr(_e)[:300],
            }
        _last = _last_good_tpu(_metric)
        if _last is not None:
            _rec["last_good_tpu"] = _last
        print(json.dumps(_rec), flush=True)
        sys.stdout.flush()
        sys.stderr.flush()
        # a wedged backend-init thread would keep a clean exit from
        # ever happening; the JSON line is out, so hard-exit
        os._exit(0)
