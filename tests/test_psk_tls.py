"""Native TLS-PSK termination (emqx_psk parity, src/emqx_psk.erl:31):
ctypes OpenSSL engine, memory-BIO pump, full MQTT connect over a
PSK-secured socket with identities resolved through the
'tls_handshake.psk_lookup' hook chain."""

import asyncio

import pytest

from emqx_tpu.hooks import Hooks
from emqx_tpu.psk import PskAuth
from emqx_tpu.psk_tls import (PskTlsEngine, PskTlsError, available,
                              open_psk_connection)

pytestmark = pytest.mark.skipif(
    not available(), reason="libssl not loadable")


def _pump_pair(client: PskTlsEngine, server: PskTlsEngine,
               rounds: int = 10) -> None:
    """Shuttle handshake bytes between two in-memory engines."""
    for _ in range(rounds):
        if client.handshake_done and server.handshake_done:
            return
        try:
            client.handshake()
        finally:
            out = client.outgoing()
        if out:
            server.feed(out)
        try:
            server.handshake()
        finally:
            back = server.outgoing()
        if back:
            client.feed(back)
    raise AssertionError("handshake did not converge")


def _mk_server(keys):
    hooks = Hooks()
    auth = PskAuth(hooks, keys=keys)
    return PskTlsEngine(server=True, lookup=auth.lookup)


def test_engine_handshake_and_data_both_ways():
    server = _mk_server({"dev1": b"sekret-key-123"})
    client = PskTlsEngine(server=False, identity="dev1",
                          key=b"sekret-key-123")
    _pump_pair(client, server)
    assert server.psk_identity == "dev1"
    # client -> server
    client.write(b"hello broker")
    server.feed(client.outgoing())
    assert server.read() == b"hello broker"
    # server -> client
    server.write(b"hello device")
    client.feed(server.outgoing())
    assert client.read() == b"hello device"
    client.close()
    server.close()


def test_engine_wrong_key_fails_handshake():
    server = _mk_server({"dev1": b"right-key"})
    client = PskTlsEngine(server=False, identity="dev1",
                          key=b"wrong-key")
    with pytest.raises((PskTlsError, AssertionError)):
        _pump_pair(client, server)


def test_engine_unknown_identity_rejected():
    server = _mk_server({"dev1": b"right-key"})
    client = PskTlsEngine(server=False, identity="nobody",
                          key=b"right-key")
    with pytest.raises((PskTlsError, AssertionError)):
        _pump_pair(client, server)


def test_engine_hook_chain_priority():
    """Lookup goes through run_fold: a higher-priority resolver wins
    (the reference's hook-chain PSK semantics)."""
    hooks = Hooks()
    PskAuth(hooks, keys={"d": b"low"}, priority=0)
    PskAuth(hooks, keys={"d": b"high"}, priority=10)
    server = PskTlsEngine(
        server=True,
        lookup=lambda i: hooks.run_fold(
            "tls_handshake.psk_lookup", (i,), None))
    client = PskTlsEngine(server=False, identity="d", key=b"high")
    _pump_pair(client, server)


async def test_mqtt_connect_over_native_psk_listener():
    """The full stack: Node PSK listener (no certfile, ssl module has
    no server PSK here) → native engine handshake → MQTT CONNECT /
    SUBSCRIBE / PUBLISH / deliver over the encrypted socket."""
    import sys

    sys.path.insert(0, "tests")
    from mqtt_client import TestClient

    from emqx_tpu.node import Node
    from emqx_tpu.tls import TlsOptions

    n = Node(boot_listeners=False)
    auth = PskAuth(n.hooks, keys={"sensor-7": b"super-secret"})
    lst = n.add_tls_listener(
        port=0, tls_options=TlsOptions(psk=auth), name="psk:test")
    await n.start()
    try:
        from emqx_tpu.psk_tls import PskTlsListener
        assert isinstance(lst, PskTlsListener)

        reader, writer = await open_psk_connection(
            "127.0.0.1", lst.port, "sensor-7", b"super-secret")
        c = TestClient("psk-client", version=4)
        await c.connect_over(reader, writer)
        await c.subscribe("s/+", qos=1)
        await c.publish("s/1", b"encrypted payload", qos=1)
        m = await asyncio.wait_for(c.recv(), 10)
        assert m.topic == "s/1" and m.payload == b"encrypted payload"
        writer.close()
    finally:
        await n.stop()


async def test_native_psk_listener_rejects_bad_key():
    from emqx_tpu.node import Node
    from emqx_tpu.tls import TlsOptions

    n = Node(boot_listeners=False)
    auth = PskAuth(n.hooks, keys={"sensor-7": b"super-secret"})
    lst = n.add_tls_listener(port=0,
                             tls_options=TlsOptions(psk=auth))
    await n.start()
    try:
        with pytest.raises((PskTlsError, ConnectionError,
                            asyncio.IncompleteReadError, OSError)):
            await open_psk_connection(
                "127.0.0.1", lst.port, "sensor-7", b"wrong")
    finally:
        await n.stop()


def test_engine_closed_guard():
    """Operations on a closed engine raise PskTlsError — never a
    NULL pointer into libssl."""
    server = _mk_server({"d": b"k"})
    server.close()
    with pytest.raises(PskTlsError):
        server.write(b"late")
    with pytest.raises(PskTlsError):
        server.feed(b"late")
    with pytest.raises(PskTlsError):
        server.read()
    assert server.psk_identity is None
    server.close()  # idempotent


def test_shared_context_multiple_engines():
    """The listener model: one SSL_CTX, many connections."""
    from emqx_tpu.psk_tls import PskTlsContext

    hooks = Hooks()
    auth = PskAuth(hooks, keys={"d": b"k"})
    ctx = PskTlsContext(server=True, lookup=auth.lookup)
    for _ in range(3):
        server = PskTlsEngine(context=ctx)
        client = PskTlsEngine(server=False, identity="d", key=b"k")
        _pump_pair(client, server)
        client.write(b"x")
        server.feed(client.outgoing())
        assert server.read() == b"x"
        client.close()
        server.close()
    ctx.close()


def test_bad_cipher_string_fails_at_listener_build():
    from emqx_tpu.broker import Broker
    from emqx_tpu.cm import ConnectionManager
    from emqx_tpu.psk_tls import PskTlsListener

    b = Broker()
    cm = ConnectionManager(broker=b)
    hooks = Hooks()
    auth = PskAuth(hooks, keys={})
    with pytest.raises(PskTlsError):
        PskTlsListener(b, cm, psk=auth,
                       psk_ciphers="NO-SUCH-CIPHER-FAMILY")


async def test_handshake_hard_deadline_drip_feed():
    """A drip-feeding client cannot hold a handshake slot past the
    timeout (slow-loris guard)."""
    from emqx_tpu.node import Node
    from emqx_tpu.tls import TlsOptions

    n = Node(boot_listeners=False)
    auth = PskAuth(n.hooks, keys={"d": b"k"})
    lst = n.add_tls_listener(port=0, tls_options=TlsOptions(psk=auth))
    lst.handshake_timeout = 0.5
    await n.start()
    try:
        r, w = await asyncio.open_connection("127.0.0.1", lst.port)
        t0 = asyncio.get_running_loop().time()
        # a legal record header declaring a 16KB body keeps OpenSSL
        # in WANT_READ; then drip filler forever
        w.write(b"\x16\x03\x03\x40\x00")

        async def drip():
            try:
                while True:
                    w.write(b"\x00")
                    await w.drain()
                    await asyncio.sleep(0.05)
            except (ConnectionError, OSError):
                pass

        task = asyncio.ensure_future(drip())
        # server must close at its 0.5s deadline, not hang
        await asyncio.wait_for(r.read(), 5)
        elapsed = asyncio.get_running_loop().time() - t0
        task.cancel()
        assert 0.3 <= elapsed < 4.0
        w.close()
    finally:
        await n.stop()


async def test_bad_key_gets_tls_alert_not_bare_close():
    """The failure alert reaches the wire so a client can distinguish
    a key mismatch from a network failure."""
    from emqx_tpu.node import Node
    from emqx_tpu.tls import TlsOptions

    n = Node(boot_listeners=False)
    auth = PskAuth(n.hooks, keys={"d": b"right"})
    lst = n.add_tls_listener(port=0, tls_options=TlsOptions(psk=auth))
    await n.start()
    try:
        with pytest.raises(PskTlsError) as ei:
            await open_psk_connection("127.0.0.1", lst.port,
                                      "d", b"wrong")
        # the client saw a TLS-level failure (alert), not a bare EOF
        assert "handshake" in str(ei.value).lower() or \
            "alert" in str(ei.value).lower() or \
            "failed" in str(ei.value).lower()
    finally:
        await n.stop()


async def test_concurrent_psk_handshakes_and_traffic():
    """Many simultaneous PSK handshakes against one shared SSL_CTX,
    then a fan-out delivery across all of them."""
    import sys

    sys.path.insert(0, "tests")
    from mqtt_client import TestClient

    from emqx_tpu.mqtt import constants as C
    from emqx_tpu.node import Node
    from emqx_tpu.tls import TlsOptions

    n = Node(boot_listeners=False)
    auth = PskAuth(n.hooks, keys={
        f"d{i}": f"k{i}".encode() for i in range(20)})
    lst = n.add_tls_listener(port=0, tls_options=TlsOptions(psk=auth))
    await n.start()
    try:
        async def one(i):
            r, w = await open_psk_connection(
                "127.0.0.1", lst.port, f"d{i}", f"k{i}".encode())
            c = TestClient(f"c{i}", version=C.MQTT_V4)
            await c.connect_over(r, w)
            await c.subscribe("st/all")
            return c, w

        clients = await asyncio.gather(*(one(i) for i in range(20)))
        await clients[0][0].publish("st/all", b"fanout", qos=1)
        for c, _ in clients:
            m = await asyncio.wait_for(c.recv(), 10)
            assert m.payload == b"fanout"
        for _, w in clients:
            w.close()
    finally:
        await n.stop()
