"""Write-ahead journal unit tier (docs/DURABILITY.md): CRC framing,
torn-tail truncation, batched fsync accounting, degrade-don't-wedge
on injected storage faults."""

import os

import pytest

from emqx_tpu import faults, wal
from emqx_tpu.types import Message, SubOpts

OPS = [
    ("route", "a/+", "n1", 1),
    ("route", "a/+", ("g", "n1"), 2),
    ("retain", "t/1", Message(topic="t/1", payload=b"\x00\xffv"), 1.5),
    ("retain", "t/1", None, 2.5),
    ("sess.sub", "c1", "$share/g/a/b", SubOpts(qos=1, nl=1)),
    ("sess.unsub", "c1", "a/b"),
    ("sess.close", "c1"),
]


def _write(path, ops, fsync=False):
    w = wal.Wal(path, fsync=fsync)
    for op in ops:
        w.append(op)
    assert w.flush()
    w.close()
    return w


def test_roundtrip_all_record_kinds(tmp_path):
    path = str(tmp_path / "j.wal")
    _write(path, OPS)
    records, torn = wal.replay(path)
    assert not torn
    assert len(records) == len(OPS)
    for got, want in zip(records, OPS):
        assert got[0] == want[0]
    # typed payloads survive: tuple dest, Message, SubOpts
    assert records[1][2] == ("g", "n1")
    assert records[2][2].payload == b"\x00\xffv"
    assert records[4][3].qos == 1 and records[4][3].nl == 1


def test_torn_tail_truncates_never_raises(tmp_path):
    path = str(tmp_path / "j.wal")
    _write(path, OPS[:3])
    size = os.path.getsize(path)
    with open(path, "ab") as f:  # a frame the crash cut in half
        f.write(wal.encode_record(OPS[3])[:7])
    records, torn = wal.replay(path)
    assert torn and len(records) == 3
    # every byte-level truncation of the file is a clean prefix
    data = open(path, "rb").read()
    for cut in range(0, size + 7):
        p2 = str(tmp_path / "cut.wal")
        with open(p2, "wb") as f:
            f.write(data[:cut])
        recs, _ = wal.replay(p2)
        assert len(recs) <= 3
        for got, want in zip(recs, OPS):
            assert got[0] == want[0]


def test_crc_corruption_stops_at_bad_record(tmp_path):
    path = str(tmp_path / "j.wal")
    _write(path, OPS[:4])
    data = bytearray(open(path, "rb").read())
    # flip one payload byte inside the SECOND record
    first = len(wal.encode_record(OPS[0]))
    data[first + wal._HDR.size + 2] ^= 0xFF
    with open(path, "wb") as f:
        f.write(data)
    records, torn = wal.replay(path)
    assert torn and len(records) == 1


def test_fsync_batched_per_flush_not_per_record(tmp_path):
    w = wal.Wal(str(tmp_path / "j.wal"), fsync=True)
    for op in OPS:
        w.append(op)
    assert w.flush()
    for op in OPS:
        w.append(op)
    assert w.flush()
    assert w.fsyncs == 2  # one sync per batch, 7 records each
    assert w.records == 2 * len(OPS)
    w.close()


def test_fsync_fault_degrades_alarms_and_recovers(tmp_path):
    events = []
    w = wal.Wal(str(tmp_path / "j.wal"), fsync=True,
                retry_backoff_s=0.0, on_error=events.append)
    w.append(OPS[0])
    with faults.injected("wal.fsync", times=1):
        assert not w.flush()
    assert w.degraded and w.fsync_errors == 1
    assert events and events[0] is not None  # alarm raise
    # the record stayed buffered; the retry (backoff 0) lands it
    assert w.pending() == 1
    assert w.flush()
    assert not w.degraded and events[-1] is None  # alarm clear
    records, torn = wal.replay(w.path)
    w.close()
    assert not torn and len(records) == 1


def test_append_fault_short_writes_torn_tail(tmp_path):
    """The injected short write models a crash mid-append: half a
    frame on disk, writer degraded — recovery from that file gets
    every record up to the torn one and nothing after."""
    path = str(tmp_path / "j.wal")
    w = wal.Wal(path, fsync=False, retry_backoff_s=0.0)
    for op in OPS[:2]:
        w.append(op)
    assert w.flush()
    w.append(OPS[2])
    with faults.injected("wal.append", times=1):
        assert not w.flush()
    assert w.degraded
    records, torn = wal.replay(path)
    assert torn and len(records) == 2
    w.close()


def test_real_write_failure_repairs_tail_before_resuming(tmp_path):
    """A REAL partial write (not the injected crash model) truncates
    back to the last clean frame so post-recovery appends stay
    reachable by replay."""
    path = str(tmp_path / "j.wal")
    w = wal.Wal(path, fsync=False, retry_backoff_s=0.0)
    w.append(OPS[0])
    assert w.flush()
    # simulate the kernel accepting half a frame then erroring:
    # inject garbage at the tail, then fail an fsync so the error
    # path runs its truncate-repair
    with open(path, "ab") as f:
        f.write(b"\x01\x02\x03")
    w.append(OPS[1])
    with faults.injected("wal.fsync", times=1):
        assert not w.flush()
    assert w.flush()  # repair truncated the garbage; clean resume
    records, torn = wal.replay(path)
    w.close()
    assert len(records) == 2
    assert not torn


def test_degraded_buffer_bounded_drop_oldest(tmp_path):
    w = wal.Wal(str(tmp_path / "j.wal"), fsync=False, max_buffer=3,
                retry_backoff_s=3600.0)
    with faults.injected("wal.fsync", times=1):
        w.append(OPS[0])
        assert not w.flush()
    for i in range(5):
        w.append(("sess.close", f"c{i}"))
    assert w.pending() == 3
    assert w.dropped == 3
    w.close()


def test_rotate_switches_segments(tmp_path):
    p1, p2 = str(tmp_path / "j1.wal"), str(tmp_path / "j2.wal")
    w = wal.Wal(p1, fsync=False)
    w.append(OPS[0])
    old = w.rotate(p2)  # rotate flushes the pending record first
    assert old == p1
    w.append(OPS[1])
    w.close()
    r1, _ = wal.replay(p1)
    r2, _ = wal.replay(p2)
    assert [r[0] for r in r1] == ["route"] and len(r1) == 1
    assert len(r2) == 1 and r2[0][2] == ("g", "n1")


def test_bad_magic_and_oversize_length_rejected(tmp_path):
    path = str(tmp_path / "j.wal")
    with open(path, "wb") as f:
        f.write(b"XX" + b"\x00" * 20)
    records, torn = wal.replay(path)
    assert torn and not records
    with open(path, "wb") as f:
        f.write(wal._HDR.pack(wal.MAGIC, wal.MAX_RECORD + 1, 0))
        f.write(b"z" * 64)
    records, torn = wal.replay(path)
    assert torn and not records


def test_new_fault_points_registered():
    for point in ("wal.append", "wal.fsync", "checkpoint.rename",
                  "repl.ship"):
        assert point in faults.POINTS
    with pytest.raises(ValueError):
        faults.arm("wal.nonsense")


# -- sharded WAL + group commit (docs/DURABILITY.md "Sharded WAL") --------


def _keyed_ops(n):
    """n (op, key) pairs across several distinct keys."""
    out = []
    for i in range(n):
        key = f"k{i % 5}"
        out.append((("route", f"f/{key}/{i}", "n1", i + 1), key))
    return out


def test_group_shards_roundtrip_and_key_affinity(tmp_path):
    g = wal.WalGroup(str(tmp_path), seq=3, shards=4, fsync=False)
    pairs = _keyed_ops(40)
    for op, key in pairs:
        g.append(op, key)
    assert g.flush()
    g.close()
    names = sorted(os.listdir(tmp_path))
    assert names == [f"journal-{i}-3.wal" for i in range(4)]
    # every record lands in exactly the shard its key hashes to, in
    # per-key order — the merge rule recovery leans on
    per_shard = {i: [r for r, _t in [wal.replay(
        str(tmp_path / f"journal-{i}-3.wal"))]][0] for i in range(4)}
    got = [r for recs in per_shard.values() for r in recs]
    assert sorted(got) == sorted(op for op, _k in pairs)
    for op, key in pairs:
        idx = wal.shard_of(key, 4)
        assert op in per_shard[idx]
    for i in range(4):
        seqs = [r[3] for r in per_shard[i]]
        assert seqs == sorted(seqs)  # per-key order == append order


def test_group_single_shard_is_legacy_layout_byte_for_byte(tmp_path):
    ops = [op for op, _k in _keyed_ops(9)]
    legacy = wal.Wal(str(tmp_path / "journal-7.wal"), fsync=False)
    for op in ops:
        legacy.append(op)
    legacy.flush()
    legacy.close()
    os.makedirs(str(tmp_path / "g"), exist_ok=True)
    g = wal.WalGroup(str(tmp_path / "g"), seq=7, shards=1,
                     fsync=False)
    for op, key in _keyed_ops(9):
        g.append(op, key)
    g.flush()
    g.close()
    want = open(str(tmp_path / "journal-7.wal"), "rb").read()
    got = open(str(tmp_path / "g" / "journal-7.wal"), "rb").read()
    assert got == want


def test_group_commit_coalesces_concurrent_flushes(tmp_path):
    import threading

    g = wal.WalGroup(str(tmp_path), seq=1, shards=2, fsync=False,
                     group_window_ms=20.0)
    n_threads = 6
    barrier = threading.Barrier(n_threads)

    def work(i):
        barrier.wait()
        for j in range(10):
            g.append(("sess.close", f"c{i}-{j}"), f"c{i}")
            g.flush()

    threads = [threading.Thread(target=work, args=(i,))
               for i in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert g.pending() == 0
    # every record durable…
    total = sum(len(wal.replay(str(tmp_path / f))[0])
                for f in os.listdir(tmp_path))
    assert total == n_threads * 10
    # …but far fewer leader commit passes than flush calls: the
    # window coalesced concurrent flushers onto shared fsync passes
    assert g.commits < n_threads * 10
    assert g.coalesced > 0


def test_group_shard_fault_degrades_only_that_shard(tmp_path):
    g = wal.WalGroup(str(tmp_path), seq=1, shards=2, fsync=True)
    # one record per shard (find keys that hash apart)
    keys = {}
    i = 0
    while len(keys) < 2:
        keys.setdefault(wal.shard_of(f"k{i}", 2), f"k{i}")
        i += 1
    for shard, key in keys.items():
        g.append(("sess.close", key), key)
    with faults.injected("wal.fsync", times=1):
        g.flush()
    assert g.degraded  # one shard degraded…
    degraded = [w for w in g.shards if w.degraded]
    healthy = [w for w in g.shards if not w.degraded]
    assert len(degraded) == 1 and len(healthy) == 1
    assert healthy[0].records == 1  # …its sibling committed
    g._retry_at = 0.0
    g.flush()
    assert not g.degraded
    g.close()
    total = sum(len(wal.replay(w.path)[0]) for w in g.shards)
    assert total == 2


def test_group_error_callback_clears_only_when_all_recover(tmp_path):
    events = []
    g = wal.WalGroup(str(tmp_path), seq=1, shards=2, fsync=True,
                     retry_backoff_s=0.0, on_error=events.append)
    for i in range(20):
        g.append(("sess.close", f"x{i}"), f"x{i}")
    with faults.injected("wal.fsync", times=2):
        g.flush()  # both shards degrade
    assert [e is not None for e in events] == [True, True]
    g.flush()  # both recover — ONE clear once no shard is degraded
    assert events[-1] is None
    assert not g.degraded
    g.close()


def test_group_rotate_switches_every_shard(tmp_path):
    g = wal.WalGroup(str(tmp_path), seq=1, shards=2, fsync=False)
    for op, key in _keyed_ops(8):
        g.append(op, key)
    old = g.rotate_to(2)
    assert sorted(os.path.basename(p) for p in old) == \
        ["journal-0-1.wal", "journal-1-1.wal"]
    g.append(("sess.close", "late"), "late")
    g.close()
    assert g.seq == 2
    old_records = sum(len(wal.replay(p)[0]) for p in old)
    assert old_records == 8  # rotate flushed the pending batch first
    new = [str(tmp_path / f"journal-{i}-2.wal") for i in range(2)]
    assert sum(len(wal.replay(p)[0]) for p in new) == 1
