"""Compiled-matcher parity vs the host oracle (the reference's own
trie SUITE is the oracle for the oracle; this closes the loop for the
device path). Runs on CPU via conftest; identical code path on TPU.
"""

import random

import numpy as np
import pytest

from emqx_tpu import topic as T
from emqx_tpu.oracle import TrieOracle
from emqx_tpu.ops.csr import (attach_walk_tables, build_automaton,
                              compress_automaton)
from emqx_tpu.ops.match import match_batch, walk_params
from emqx_tpu.ops.tokenize import WordTable, encode_batch


def _build(filters, mode=None):
    trie = TrieOracle()
    table = WordTable()
    fids = {}
    for f in filters:
        trie.insert(f)
        fids[f] = len(fids)
        for w in T.words(f):
            table.intern(w)
    if mode is None:
        auto = build_automaton(trie, fids, table)
    else:  # pin the kernel layout (both must hold exact parity)
        raw = build_automaton(trie, fids, table, skip_hash=True)
        auto, edges = compress_automaton(raw, force_mode=mode)
        auto = attach_walk_tables(auto, edges)
    inv = {v: k for k, v in fids.items()}
    return trie, table, auto, inv


def _match_device(auto, table, topics, L=16, k=64, m=128):
    ids, n, sysm = encode_batch(table, topics, L)
    res = match_batch(auto, ids, n, sysm, k=k, m=m,
                      **walk_params(auto, ids.shape[1]))
    return res


def _check_parity(filters, topics, L=16, k=64, m=128, mode=None):
    trie, table, auto, inv = _build(filters, mode=mode)
    res = _match_device(auto, table, topics, L=L, k=k, m=m)
    ids = np.asarray(res.ids)
    cnt = np.asarray(res.count)
    ovf = np.asarray(res.overflow)
    for i, t in enumerate(topics):
        expect = sorted(trie.match(t))
        if ovf[i]:
            # overflow is allowed but must be flagged; host fallback
            got = sorted(trie.match(t))
            assert got == expect
            continue
        got = sorted(inv[j] for j in ids[i] if j >= 0)
        assert len(got) == cnt[i], (t, got, cnt[i])
        assert got == expect, (t, got, expect)
    return ovf


def test_trie_suite_cases():
    filters = ["sensor/1/metric/2", "sensor/+/#", "sensor/#"]
    trie, table, auto, inv = _build(filters)
    res = _match_device(auto, table, ["sensor/1"])
    got = sorted(inv[j] for j in np.asarray(res.ids)[0] if j >= 0)
    assert got == sorted(["sensor/+/#", "sensor/#"])


def test_root_wildcards_and_sys():
    filters = ["#", "+/#", "+/+/#", "$SYS/#", "$SYS/broker/+"]
    _check_parity(filters, [
        "a/b/c", "$SYS/broker/zenmq", "$SYS/broker", "a", "$other/x",
        "$SYS", "x/y", "/", "//",
    ])


def test_hash_matches_parent_level():
    filters = ["sensor", "sensor/#", "a/b/#", "a/b"]
    _check_parity(filters, ["sensor", "sensor/1", "a/b", "a/b/c", "a"])


def test_empty_levels_and_unknown_words():
    filters = ["/+", "+//#", "a//b", "//"]
    _check_parity(filters, ["/x", "/", "a//b", "//", "never/seen/words"])


def test_deep_topics_too_long_flagged():
    filters = ["a/#"]
    trie, table, auto, inv = _build(filters)
    deep = "/".join(["a"] + ["x"] * 40)
    res = _match_device(auto, table, [deep], L=16)
    assert bool(np.asarray(res.overflow)[0])
    assert np.asarray(res.count)[0] == 0


def test_match_after_delete_rebuild():
    trie, table, auto, inv = _build(["a/+", "a/b", "b/#"])
    trie.delete("a/b")
    fids = {"a/+": 0, "b/#": 2}
    auto2 = build_automaton(trie, fids, table)
    res = match_batch(auto2, *encode_batch(table, ["a/b"], 16), k=16,
                      m=16, **walk_params(auto2, 16))
    got = [j for j in np.asarray(res.ids)[0] if j >= 0]
    assert got == [0]


def _random_word(rng):
    return rng.choice(["a", "b", "c", "d", "e", "x", "yy", "z0", "$s", ""])


def _random_filter(rng, maxlen=6):
    n = rng.randint(1, maxlen)
    ws = []
    for i in range(n):
        r = rng.random()
        if r < 0.2:
            ws.append("+")
        elif r < 0.3 and i == n - 1:
            ws.append("#")
        else:
            ws.append(_random_word(rng))
    return "/".join(ws)


@pytest.mark.parametrize("mode", [None, "narrow", "wide"])
def test_random_parity(mode):
    rng = random.Random(123)
    filters = list({_random_filter(rng) for _ in range(400)})
    topics = list({
        "/".join(_random_word(rng) for _ in range(rng.randint(1, 7)))
        for _ in range(300)
    })
    ovf = _check_parity(filters, topics, L=8, k=128, m=256, mode=mode)
    # with K=128 on a 400-filter trie nothing should overflow
    assert not ovf.any()


@pytest.mark.parametrize("mode", ["narrow", "wide"])
def test_deep_chain_parity(mode):
    """Long single-child literal chains — the hash_1m_deep shape the
    compression pass exists for (reference cost model:
    src/emqx_trie.erl:161-186). Both kernel layouts must agree with
    the oracle exactly, including topics that end mid-chain."""
    rng = random.Random(77)
    vocab = [f"v{i}" for i in range(9)]
    filters = set()
    while len(filters) < 300:
        depth = rng.randint(1, 16)
        ws = [rng.choice(vocab) for _ in range(depth)]
        filters.add("/".join(ws[: rng.randint(1, depth)] + ["#"]))
    filters = sorted(filters)
    topics = ["/".join(rng.choice(vocab)
                       for _ in range(rng.randint(1, 16)))
              for _ in range(500)]
    ovf = _check_parity(filters, topics, L=16, k=4, m=128, mode=mode)
    assert not ovf.any()  # no '+' edges: active set is 1 lane


def test_overflow_flagged_not_silent():
    """With a tiny K, dense '+' chains overflow — flag must be set."""
    rng = random.Random(5)
    filters = list({_random_filter(rng, maxlen=4) for _ in range(200)})
    topics = ["a/b/c", "a/a/a", "x/yy/z0"]
    # k=2 forces active-set overflow on wide NFA frontiers
    _check_parity(filters, topics, L=8, k=2, m=256)


def test_large_scale_smoke():
    rng = random.Random(9)
    filters = list({
        "/".join(rng.choice("abcdefgh") + str(rng.randint(0, 50))
                 for _ in range(rng.randint(2, 5)))
        for _ in range(5000)
    })
    # add some wildcards
    filters += ["a1/+/c2/#", "+/b3/#", "#"]
    topics = ["a1/b3/c2/d4", "a5/b3/x", "nope/nope"]
    _check_parity(filters, topics, L=8, k=64, m=256)
